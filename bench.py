"""Headline benchmark: batched 5-node Raft partition/crash fuzz throughput,
plus the service layers (kv, ctrler, shardkv) as secondary timed regions.

North star (BASELINE.json): >=100k 5-node cluster-steps/sec/chip with zero
safety violations. Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Methodology (round-3; see PERF.md for the batch-size sweep and phase budget):
- The tunnel platform's block_until_ready does NOT block, so every timed
  region ends with a device->host fetch of the violation bitmap — the only
  honest sync point.
- Default batch is 4096 clusters: the measured throughput KNEE. Larger
  batches are slower per step (HBM working-set pressure: 8k -> 16.1M,
  16k -> 13.2M, 64k -> 8.3M steps/s in the round-3 sweep), smaller ones
  under-fill the chip.
- The tick scan is chunked (host loop over compiled CHUNK-tick programs) so
  a single device execution stays well under the tunnel's per-call deadline;
  chunk inputs are donated so the state double-buffer is reused. The chunk
  runner is engine.make_chunked_fuzz_fn — ONE implementation shared with the
  CLI and the continuous pool (the hand-rolled duplicate with compile-time-
  baked knobs is deleted; runtime scalar knobs measured ~6% slower than
  baked constants, see PERF.md's knob-layout table — what is timed now is
  the path users actually run).
- Each timed region is whole runs repeated until >=1 s of wall time (at
  least 2 runs); the reported value is the best run, with the spread across
  runs so back-to-back agreement is visible.
- hbm_util_floor is a lower-bound utilization proxy: each tick must read and
  write the cluster state at least once, so (2 * state_bytes * ticks) / time
  relative to the chip's HBM peak bounds how far from memory-roofline the
  step function runs.
- compile_s per region: the service regions measure it directly via the
  FuzzProgram AOT split (the same mechanism behind the CLI fuzz telemetry);
  the raft region's host-looped chunk dispatch uses the cold-call-minus-best
  estimate — either way compile-time regressions are visible in BENCH
  artifacts, not only execution throughput.
- kv / shardkv rows time the full service stacks (clerks, apply machines,
  oracles, and for shardkv the groups axis + migration protocol) — a
  service-layer perf regression is visible in BENCH_r*.json, not just the
  raw raft tick (round-2 verdict item).
"""

import json
import os
import sys
import time

import jax
import numpy as np

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import make_chunked_fuzz_fn, report, run_pool

BASELINE_STEPS_PER_SEC = 100_000.0  # BASELINE.json north star
HBM_PEAK_BYTES_PER_S = 819e9        # TPU v5e; proxy denominator only

# Latency-tail regression gate (ISSUE 10; ROADMAP item 4's exit metric):
# p99 submit->ack latency of the storm profile, in ticks, measured via the
# on-device metrics plane. Pinned from round-10 measurements (CPU, seeds
# 12345): 63 ticks at the 128-tick smoke horizon, 127 at 600- and
# 1024-tick horizons (the tail is partition-bound, so it grows with the
# horizon until partitions resolve) — the bound sits one log-spaced bucket
# above the worst measured value, so only a real distribution shift (a
# replication/commit-path regression pushing ops past 255 ticks), not
# bucket-granularity noise, trips it. ci.sh asserts the analogous gate on
# the durability profile's clean pool leg (see its metrics smoke).
TAIL_P99_BOUND_TICKS = 255


def flagship_config() -> SimConfig:
    return SimConfig(
        n_nodes=5,
        p_client_cmd=0.2,
        loss_prob=0.1,
        p_crash=0.01,
        p_restart=0.2,
        max_dead=2,
        p_repartition=0.02,
        p_heal=0.05,
    )


def _timed(run, sync, min_s=1.0, min_runs=2):
    """Repeat run() until >= min_s total; return (best_s, runs, spread, out).
    The last run's output is returned so reports don't pay an extra run."""
    times = []
    out = None
    while sum(times) < min_s or len(times) < min_runs:
        t0 = time.perf_counter()
        out = run()
        sync(out)
        times.append(time.perf_counter() - t0)
    best = min(times)
    return best, len(times), (max(times) - min(times)) / best, out


def _warmed(run, sync):
    """Time the warm-up (compile + first execution) sync for a region and
    return (cold_wall_s, out). The caller subtracts its best timed run to
    estimate compile_s — making compile-time regressions visible in BENCH
    artifacts, not just steady-state throughput (ISSUE 2 satellite)."""
    t0 = time.perf_counter()
    out = run()
    sync(out)
    return time.perf_counter() - t0, out


def _compile_s(cold_s: float, best_s: float) -> float:
    """Compile-time estimate: first-call wall minus the best steady-state
    run (the execution share of the cold call); floored at 0 for noise.
    bench_raft's host-looped chunk dispatch has no AOT handle, so it is the
    one region that uses this estimate; the service regions measure compile
    directly (_compile_region)."""
    return round(max(0.0, cold_s - best_s), 3)


def _compile_region(fn, sync):
    """Measure a service region's compile time DIRECTLY via the
    FuzzProgram AOT split — the same mechanism the CLI fuzz telemetry uses,
    so compile_s means one thing across BENCH artifacts and fuzz reports.
    Returns finish(best_s) -> compile_s; when AOT lowering is unavailable
    it falls back to the cold-call estimate."""
    s = fn.compile_timed(12345)
    if s is not None:
        return lambda best: round(s, 3)
    cold_s, _ = _warmed(lambda: fn(12345), sync)
    return lambda best: _compile_s(cold_s, best)


def bench_raft(n_clusters: int, n_ticks: int, cfg: SimConfig) -> dict:
    # the engine's donated chunked dispatch (one implementation for
    # bench/CLI/pool — the hand-rolled duplicate with compile-time-baked
    # knobs is gone; this times the runtime-scalar-knob path users actually
    # run, measured ~6% below baked constants, see PERF.md knob-layout table)
    run_fn = make_chunked_fuzz_fn(cfg, n_clusters, n_ticks)
    ticks = n_ticks

    def run(seed=12345):
        return run_fn(seed)

    cold_s, final = _warmed(run, lambda s: np.asarray(s.violations))
    # the RESIDENT carry the chunk loop actually holds in HBM (packed when
    # the run fits the packed bounds — ISSUE 9), measured from live buffers
    state_bytes = run_fn.state_hbm_bytes
    best, runs, spread, final = _timed(run, lambda s: np.asarray(s.violations))
    rep = report(final)
    return {
        "steps_per_sec": n_clusters * ticks / best,
        "n_clusters": n_clusters,
        "n_ticks": ticks,
        "runs": runs,
        "best_wall_s": round(best, 3),
        "run_spread": round(spread, 3),
        "compile_s": _compile_s(cold_s, best),
        "state_layout": run_fn.state_layout,
        "bytes_per_lane": run_fn.bytes_per_lane,
        "hbm_util_floor": round(
            2 * state_bytes * ticks / best / HBM_PEAK_BYTES_PER_S, 4
        ),
        "violations": int((rep.violations != 0).sum()),
        "clusters_with_commits": int((rep.committed > 0).sum()),
    }


def bench_kv(n_clusters: int, n_ticks: int) -> dict:
    from madraft_tpu.tpusim.kv import KvConfig, make_kv_fuzz_fn

    cfg = flagship_config().replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16
    )
    fn = make_kv_fuzz_fn(cfg, KvConfig(p_get=0.3), n_clusters, n_ticks)
    sync = lambda s: np.asarray(s.raft.violations)  # noqa: E731
    finish = _compile_region(fn, sync)
    best, runs, spread, final = _timed(lambda: fn(12345), sync)
    return {
        "steps_per_sec": n_clusters * n_ticks / best,
        "n_clusters": n_clusters,
        "n_ticks": n_ticks,
        "runs": runs,
        "best_wall_s": round(best, 3),
        "run_spread": round(spread, 3),
        "compile_s": finish(best),
        "violations": int((np.asarray(final.raft.violations) != 0).sum()),
        "acked_ops": int(np.asarray(final.clerk_acked).sum()),
    }


def bench_ctrler(n_clusters: int, n_ticks: int) -> dict:
    from madraft_tpu.tpusim.ctrler import CtrlerConfig, make_ctrler_fuzz_fn

    cfg = flagship_config().replace(
        p_client_cmd=0.0, compact_at_commit=False, log_cap=32, compact_every=8
    )
    fn = make_ctrler_fuzz_fn(cfg, CtrlerConfig(), n_clusters, n_ticks)
    sync = lambda s: np.asarray(s.raft.violations)  # noqa: E731
    finish = _compile_region(fn, sync)
    best, runs, spread, final = _timed(lambda: fn(12345), sync)
    return {
        "steps_per_sec": n_clusters * n_ticks / best,
        "n_clusters": n_clusters,
        "n_ticks": n_ticks,
        "runs": runs,
        "best_wall_s": round(best, 3),
        "run_spread": round(spread, 3),
        "compile_s": finish(best),
        "violations": int((np.asarray(final.raft.violations) != 0).sum()),
        "configs_created": int(np.asarray(final.w_cfg_num).sum()),
    }


def bench_shardkv(n_deployments: int, n_ticks: int,
                  live_ctrler: bool = False,
                  computed_ctrler: bool = False) -> dict:
    from madraft_tpu.tpusim.shardkv import (
        ShardKvConfig,
        make_shardkv_fuzz_fn,
        shardkv_report,
    )

    cfg = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05,
    )
    kcfg = ShardKvConfig(live_ctrler=live_ctrler,
                         computed_ctrler=computed_ctrler)
    fn = make_shardkv_fuzz_fn(cfg, kcfg, n_deployments, n_ticks)
    sync = lambda s: np.asarray(s.violations)  # noqa: E731
    finish = _compile_region(fn, sync)
    best, runs, spread, final = _timed(lambda: fn(12345), sync)
    rep = shardkv_report(final)
    return {
        # one deployment-step advances n_groups full raft clusters + the
        # service layer; the group-cluster rate is the raft-comparable one
        "deployment_steps_per_sec": round(n_deployments * n_ticks / best, 1),
        "cluster_steps_per_sec": round(
            n_deployments * n_ticks * kcfg.n_groups / best, 1
        ),
        "n_deployments": n_deployments,
        "n_groups": kcfg.n_groups,
        "n_ticks": n_ticks,
        "runs": runs,
        "best_wall_s": round(best, 3),
        "run_spread": round(spread, 3),
        "compile_s": finish(best),
        "violations": rep.n_violating,
        "installs": int(rep.installs.sum()),
    }


def bench_pool(n_lanes: int, budget_ticks: int) -> dict:
    """Continuous-batching A/B on the planted-bug durability profile:
    violations per chip-second, fixed-batch fixed-horizon driver vs the
    retire-and-refill pool, SAME batch and SAME tick budget.

    The fixed driver's only way to spend the budget at a fixed batch is one
    run with horizon = budget — its population ages into low-hazard
    survivors (sticky violators burn ticks to the end, and a cluster that
    has stayed clean for thousands of ticks violates more rarely than a
    fresh one). The pool instead retires at the profile's demonstrated
    600-tick horizon (violated lanes at the next chunk boundary) and
    refills with fresh clusters under new global ids. Both legs are single
    timed runs (they are long); see PERF.md for the run-spread caveat."""
    from madraft_tpu.tpusim.config import storm_profiles

    from madraft_tpu.tpusim.engine import default_chunk_ticks

    prof, _, rec_ticks, _bugs = storm_profiles()["durability"]
    cfg = prof.replace(bug="ack_before_fsync")
    horizon = min(rec_ticks, budget_ticks)
    chunk = default_chunk_ticks(horizon)  # run_pool's own default rule
    sync = lambda s: np.asarray(s.violations)  # noqa: E731

    fuzz_fn = make_chunked_fuzz_fn(cfg, n_lanes, budget_ticks)
    # warm with ONE chunk, not a full budget run: the chunk program's tick
    # count is a runtime bound, so this compiles the identical executables —
    # PROVIDED the warm-up runs the same state layout as the timed leg (a
    # short warm run would auto-pack while the full-budget leg may exceed
    # max_lane_ticks and fall back wide, warming the wrong programs)
    _warmed(lambda: make_chunked_fuzz_fn(
        cfg, n_lanes, chunk,
        pack_states=(fuzz_fn.state_layout == "packed"))(12345), sync)
    t0 = time.perf_counter()
    final = fuzz_fn(12345)
    sync(final)
    fuzz_wall = time.perf_counter() - t0
    rep = report(final)
    fuzz_viol = int((rep.violations != 0).sum())

    # run_pool warms its own programs outside its timed window (harvest
    # included), so one call is the timed full-budget run
    summary = run_pool(cfg, 12345, n_lanes, horizon,
                       chunk_ticks=chunk, budget_ticks=budget_ticks)
    pool_wall = summary["wall_s"]
    pool_viol = summary["retired_violating"]
    fuzz_vps = fuzz_viol / fuzz_wall if fuzz_wall > 0 else 0.0
    pool_vps = pool_viol / pool_wall if pool_wall > 0 else 0.0
    return {
        "profile": "durability",
        "bug": "ack_before_fsync",
        "lanes": n_lanes,
        "budget_ticks": budget_ticks,
        "horizon": horizon,
        "chunk_ticks": chunk,
        "fuzz_violations": fuzz_viol,
        "fuzz_wall_s": round(fuzz_wall, 3),
        "fuzz_viol_per_chip_s": round(fuzz_vps, 4),
        "fuzz_steps_per_sec": round(n_lanes * budget_ticks / fuzz_wall, 1),
        # at full scale the fuzz leg's 12288-tick single lifetime exceeds
        # the default max_lane_ticks bound and reports the wide fallback
        # (the layout gate working as specified); at smoke budgets both
        # legs pack — the row says which happened
        "fuzz_state_layout": fuzz_fn.state_layout,
        "pool_state_layout": summary["state_layout"],
        "pool_bytes_per_lane": summary["bytes_per_lane"],
        "pool_violations": pool_viol,
        "pool_retired": summary["retired"],
        "pool_wall_s": pool_wall,
        "pool_viol_per_chip_s": round(pool_vps, 4),
        "pool_steps_per_sec": summary["steps_per_sec"],
        "pool_effective_steps_per_sec": summary["effective_steps_per_sec"],
        # the pipeline telemetry (ISSUE 7): how much host-side
        # harvest/emit wall hid under device execution, vs the host-caused
        # wall between device dispatches and the device-bound share
        "pool_dispatch_gap_s": summary["dispatch_gap_s"],
        "pool_device_wait_s": summary["device_wait_s"],
        "pool_host_overlap_s": summary["host_overlap_s"],
        "viol_per_chip_s_ratio": (
            round(pool_vps / fuzz_vps, 3) if fuzz_vps else None
        ),
    }


# Pinned bound for the telemetry-overhead A/B: heartbeat-on wall within
# 25% of heartbeat-off at equal shape. The emission runs on the harvest-
# consumer thread (hidden in host_overlap_s under the next chunk's device
# execution), so the true cost is ~0; the slack is single-run pool noise
# (PERF.md run-spread caveat), not an emission budget.
TELEMETRY_OVERHEAD_BOUND = 1.25


def bench_telemetry_overhead(n_lanes: int, budget_ticks: int) -> dict:
    """Heartbeat-emission overhead A/B (ISSUE 17): the SAME pool run with
    the live-telemetry plane off vs on (--heartbeat to a scratch file).
    Pins two claims: the deterministic counters are bit-identical (the
    plane only observes), and throughput stays within
    TELEMETRY_OVERHEAD_BOUND of heartbeat-off — i.e. per-generation row
    emission stays hidden in host_overlap_s instead of stretching the
    device loop."""
    import tempfile

    from madraft_tpu.tpusim.config import storm_profiles
    from madraft_tpu.tpusim.engine import default_chunk_ticks

    prof, _, rec_ticks, _bugs = storm_profiles()["durability"]
    cfg = prof.replace(bug="ack_before_fsync")
    horizon = min(rec_ticks, budget_ticks)
    chunk = default_chunk_ticks(horizon)

    off = run_pool(cfg, 12345, n_lanes, horizon,
                   chunk_ticks=chunk, budget_ticks=budget_ticks)
    with tempfile.TemporaryDirectory() as d:
        hb_path = os.path.join(d, "bench_hb.jsonl")
        on = run_pool(cfg, 12345, n_lanes, horizon,
                      chunk_ticks=chunk, budget_ticks=budget_ticks,
                      heartbeat=hb_path)
        with open(hb_path) as f:
            hb_rows = sum(1 for line in f if line.strip())
    det_identical = all(
        off[k] == on[k]
        for k in ("retired", "retired_violating", "effective_cluster_steps",
                  "lane_ticks")
    )
    wall_ratio = (on["wall_s"] / off["wall_s"]) if off["wall_s"] else None
    return {
        "profile": "durability",
        "bug": "ack_before_fsync",
        "lanes": n_lanes,
        "budget_ticks": budget_ticks,
        "heartbeat_rows": hb_rows,
        "off_steps_per_sec": off["steps_per_sec"],
        "on_steps_per_sec": on["steps_per_sec"],
        "off_wall_s": off["wall_s"],
        "on_wall_s": on["wall_s"],
        # where the emission wall actually went: consumer-thread overlap,
        # not the device loop (gap would grow if emission out-ran chunks)
        "off_host_overlap_s": off["host_overlap_s"],
        "on_host_overlap_s": on["host_overlap_s"],
        "on_dispatch_gap_s": on["dispatch_gap_s"],
        "det_columns_identical": det_identical,
        "wall_ratio": round(wall_ratio, 3) if wall_ratio else None,
        "bound": TELEMETRY_OVERHEAD_BOUND,
        "pass": bool(det_identical and wall_ratio is not None
                     and wall_ratio <= TELEMETRY_OVERHEAD_BOUND),
    }


def _pool_scaling_child(n_lanes: int, budget_ticks: int) -> dict:
    """The measured legs of bench_pool_scaling, BOTH run inside the one
    2-virtual-device process: the same (seed, lanes, horizon, budget) pool
    at devices=1 vs devices=2. Under the lane-partitioned id scheme both
    legs examine the identical cluster population (the device-count-
    invariance contract), so the row also double-checks report-multiset
    equality.

    Measurement framing (deliberate): with the device count forced, each
    virtual device owns an equal slice of host threads, so the devices=1
    leg runs on ONE device's worth of resources — per-device resources are
    held constant while the device count varies, which is what chip
    scaling means. An unforced 1-device process would hand the baseline
    the whole host (XLA's intra-op pool spans every core) and understate
    scaling; conversely the virtual devices share one memory system, which
    OVERSTATES nothing at small lanes but saturates at large ones — the
    setup string says so, and the real-chip row is queued behind the
    tunnel."""
    from madraft_tpu.tpusim.config import storm_profiles
    from madraft_tpu.tpusim.engine import run_pool

    prof, _, rec_ticks, _bugs = storm_profiles()["durability"]
    cfg = prof.replace(bug="ack_before_fsync")
    horizon = min(rec_ticks, budget_ticks)

    def leg(devs):
        rows = []
        s = run_pool(cfg, 12345, n_lanes, horizon, budget_ticks=budget_ticks,
                     devices=devs, on_retired=rows.append)
        key = sorted(
            (r["cluster_id"],
             tuple(sorted((k, str(v)) for k, v in r.items()
                          if k not in ("wall_s", "violations_per_s"))))
            for r in rows
        )
        return s, key

    s1, k1 = leg(1)
    s2, k2 = leg(2)
    v1, v2 = s1["retired_violating"], s2["retired_violating"]
    w1, w2 = s1["wall_s"], s2["wall_s"]
    speedup = round(w1 / w2, 3) if w2 > 0 else None
    return {
        "profile": "durability",
        "bug": "ack_before_fsync",
        "lanes": n_lanes,
        "budget_ticks": budget_ticks,
        "horizon": horizon,
        "setup": "both legs in one 2-virtual-device CPU process (equal "
                 "host threads per device — the per-chip-resources-"
                 "constant proxy; real-chip scaling is queued behind the "
                 "axon tunnel, TUNNEL_STATUS.jsonl)",
        "reports_identical": k1 == k2,
        "dev1_violations": v1,
        "dev2_violations": v2,
        "dev1_wall_s": w1,
        "dev2_wall_s": w2,
        "dev1_viol_per_chip_s": round(v1 / w1, 4) if w1 > 0 else None,
        # 2-device chip-seconds = wall * 2: per-chip parity at ~1.0 means
        # near-linear scaling (both legs retire the SAME violations)
        "dev2_viol_per_chip_s": (
            round(v2 / (w2 * 2), 4) if w2 > 0 else None
        ),
        "speedup": speedup,
        "scaling_efficiency": (
            round(speedup / 2, 3) if speedup is not None else None
        ),
        "dev1_dispatch_gap_s": s1["dispatch_gap_s"],
        "dev1_host_overlap_s": s1["host_overlap_s"],
        "dev2_dispatch_gap_s": s2["dispatch_gap_s"],
        "dev2_host_overlap_s": s2["host_overlap_s"],
    }


def bench_pool_scaling(n_lanes: int, budget_ticks: int) -> dict:
    """Sharded-pool scaling A/B (ROADMAP item 1): violations per
    chip-second at 1 vs 2 devices, same seed and budget, plus the
    report-multiset equality check the lane-partitioned id scheme
    guarantees. Runs in a SUBPROCESS pinned to 2 virtual CPU devices so
    the parent bench keeps its own device configuration (forcing extra
    host devices costs ~1.5x on every single-device region — the PR-3 CI
    finding); the on-chip 1->8 row is queued behind the axon tunnel."""
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
    # timeout scales with the two pool runs' work but stays small at smoke
    # scale, so a hung child inside ci.sh's 600 s bench envelope still
    # yields a labeled error row instead of the parent being SIGTERMed
    timeout_s = 240 + n_lanes * budget_ticks // 2000
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--pool-scaling-child", str(n_lanes), str(budget_ticks)],
            env=env, capture_output=True, text=True, timeout=timeout_s,
        )
        if out.returncode != 0:
            return {"error": f"child rc {out.returncode}",
                    "stderr": out.stderr[-800:]}
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # a lost row must be labeled, not a crash
        return {"error": str(e)}


def bench_latency(n_clusters: int, n_ticks: int) -> dict:
    """The latency-tail row (ISSUE 10): the storm profile with the
    on-device metrics plane enabled — p50/p99 submit->ack (injection ->
    commit) ticks decoded from the merged per-lane histograms, the
    `tail_gate` verdict against the pinned p99 bound, and the measured
    cost of carrying the plane: an A/B against the metrics-OFF program at
    the SAME batch shape (separate cached programs either way)."""
    from madraft_tpu.tpusim.metrics import event_summary, latency_summary

    cfg = flagship_config().replace(metrics=True)
    run_fn = make_chunked_fuzz_fn(cfg, n_clusters, n_ticks)
    sync = lambda s: np.asarray(s.violations)  # noqa: E731
    _warmed(lambda: run_fn(12345), sync)
    best, runs, spread, final = _timed(lambda: run_fn(12345), sync)
    off_fn = make_chunked_fuzz_fn(flagship_config(), n_clusters, n_ticks)
    _warmed(lambda: off_fn(12345), sync)
    off_best, _, _, _ = _timed(lambda: off_fn(12345), sync)
    rep = report(final)
    lat = latency_summary(rep.lat_hist.sum(axis=0))
    p99 = lat["p99_ticks"]
    steps = n_clusters * n_ticks / best
    return {
        "profile": "storm (flagship shape)",
        "n_clusters": n_clusters,
        "n_ticks": n_ticks,
        "runs": runs,
        "best_wall_s": round(best, 3),
        "run_spread": round(spread, 3),
        "metrics_steps_per_sec": round(steps, 1),
        "metrics_off_steps_per_sec": round(
            n_clusters * n_ticks / off_best, 1
        ),
        # the cost of the plane at equal shape (>= 1.0; stamp rings +
        # folds are elementwise, so this should stay near 1)
        "metrics_overhead_factor": round(best / off_best, 3),
        "latency_ops": lat["ops"],
        "latency_p50_ticks": lat["p50_ticks"],
        "latency_p99_ticks": p99,
        "latency_hist": lat["hist"],
        "events_per_kstep": {
            k: round(1000.0 * v / (n_clusters * n_ticks), 3)
            for k, v in event_summary(rep.ev_counts.sum(axis=0)).items()
        },
        "tail_gate": {
            "p99_ticks": p99,
            "bound_ticks": TAIL_P99_BOUND_TICKS,
            "pass": bool(p99 is not None and p99 <= TAIL_P99_BOUND_TICKS),
        },
    }


def bench_profile_gates(seed: int = 12345) -> dict:
    """Per-profile game-day gate table (ISSUE 19) — the generalization of
    the single storm `tail_gate`: every storm_profiles() name runs ONE
    clean-algorithm leg at its gate's `bench_scale` with the metrics plane
    on, and the verdict row checks three facts against
    config.profile_gates() (the one source of truth shared with ci.sh's
    gray smoke, `--list-profiles`, and the README table): zero safety
    violations, liveness (acked ops per lane = latency-histogram mass /
    lanes) >= the floor, and p99 submit->ack ticks <= the ceiling.
    Profiles carrying a `workload` entry run as kv-clerk legs so the
    open-loop arrival + Zipf hot-key knobs actually shape the traffic the
    gate measures. Legs are run once (SLO gate, not a throughput row); the
    raft legs share compiled programs across profiles of equal static
    shape, so the table costs a few compiles, not ten."""
    from madraft_tpu.tpusim.config import profile_gates, storm_profiles
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz
    from madraft_tpu.tpusim.metrics import latency_summary

    profiles = storm_profiles()
    rows = {}
    ok = True
    t0 = time.perf_counter()
    for name, g in profile_gates().items():
        cfg = profiles[name][0].replace(metrics=True)
        lanes, ticks = g["bench_scale"]
        if g["workload"]:
            rep = kv_fuzz(
                cfg.replace(p_client_cmd=0.0, compact_at_commit=False),
                KvConfig(p_get=0.3, p_put=0.2, **g["workload"]),
                seed, lanes, ticks,
            )
        else:
            rep = report(make_chunked_fuzz_fn(cfg, lanes, ticks)(seed))
        lat = latency_summary(rep.lat_hist.sum(axis=0))
        liveness = round(lat["ops"] / lanes, 2)
        p99 = lat["p99_ticks"]
        viol = rep.n_violating
        row_pass = bool(
            viol == 0
            and liveness >= g["liveness_floor"]
            and p99 is not None and p99 <= g["p99_ceiling"]
        )
        ok = ok and row_pass
        rows[name] = {
            "n_clusters": lanes,
            "n_ticks": ticks,
            "violating_lanes": viol,
            "liveness_ops_per_lane": liveness,
            "liveness_floor": g["liveness_floor"],
            "p99_ticks": p99,
            "p99_ceiling": g["p99_ceiling"],
            "bridge": g["bridge"],
            **({"workload": g["workload"]} if g["workload"] else {}),
            "pass": row_pass,
        }
    return {
        "wall_s": round(time.perf_counter() - t0, 3),
        "profiles": rows,
        "pass": ok,
    }


def bench_tail_attrib(n_clusters: int, n_ticks: int) -> dict:
    """Tail-latency attribution A/B (ISSUE 12): two kv-clerk legs whose
    fault axes stress DIFFERENT phases, with the dominant phase (largest
    exact tick share of the decomposition) pinned per leg — the per-phase
    readout ROADMAP item 1's optimization matrix will use to show which
    phase each knob moves:

    - ``storm``      an election storm — leaders keep dying (p_crash 0.2,
                     max_dead 2) and elections are slow (25-50-tick
                     timeouts) over a clean fast network, so ops spend
                     the tail WAITING FOR A LEADER. Pinned dominant:
                     leader_wait (election wait). Measured 81-88% of
                     latency ticks across seeds (round 12, CPU).
    - ``durability`` the lossy-persistence axis under a degraded network —
                     rare crashes (so few elections) but fsync_every 8 +
                     p_lose_unsynced 1.0 re-loses acked suffixes, and
                     loss 0.2 / ae_max 1 / delay_max 5 slow replication,
                     so ops spend the tail REPLICATING. Pinned dominant:
                     replicate (replication wait). Measured ~80%.

    The raw storm_profiles() pair does NOT separate this way (its
    durability profile crashes 2x harder than its storm, so BOTH tails are
    election-bound) — these legs are tuned so each axis isolates its
    phase, which is exactly the attribution the plane exists to show."""
    from madraft_tpu.tpusim.config import LATENCY_PHASES
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz
    from madraft_tpu.tpusim.metrics import merge_worst_registers

    legs = {
        "storm": (
            SimConfig(
                n_nodes=5, p_client_cmd=0.0, compact_at_commit=False,
                p_crash=0.2, p_restart=0.3, max_dead=2, loss_prob=0.01,
                election_timeout_min=25, election_timeout_max=50,
                metrics=True,
            ),
            "leader_wait",
        ),
        "durability": (
            SimConfig(
                n_nodes=5, p_client_cmd=0.0, compact_at_commit=False,
                p_crash=0.02, p_restart=0.5, max_dead=1,
                fsync_every=8, p_lose_unsynced=1.0,
                loss_prob=0.2, ae_max=1, delay_max=5, metrics=True,
            ),
            "replicate",
        ),
    }
    kcfg = KvConfig(p_get=0.3, p_put=0.2)
    out = {"n_clusters": n_clusters, "n_ticks": n_ticks}
    ok = True
    for name, (cfg, want) in legs.items():
        t0 = time.perf_counter()
        rep = kv_fuzz(cfg, kcfg, 12345, n_clusters, n_ticks)
        wall = time.perf_counter() - t0
        pt = rep.phase_ticks.sum(axis=0)
        total = max(int(pt.sum()), 1)
        dominant = LATENCY_PHASES[int(pt.argmax())]
        worst = merge_worst_registers(
            rep.worst_lat, rep.worst_phases, rep.worst_key,
            rep.worst_client, rep.worst_sub,
        )
        leg_pass = dominant == want
        ok = ok and leg_pass
        out[name] = {
            "acked_ops": int(rep.acked_ops.sum()),
            "phase_share": {
                n: round(int(pt[i]) / total, 4)
                for i, n in enumerate(LATENCY_PHASES)
            },
            "dominant_phase": dominant,
            "pinned_dominant": want,
            "pass": leg_pass,
            "worst_op": worst,
            "wall_s": round(wall, 3),
        }
    out["pass"] = ok
    return out


def bench_state_footprint() -> dict:
    """Per-lane resident-state footprint (ISSUE 9), wide vs packed, from
    LIVE device buffers (never a schema estimate): the lanes-per-HBM story.
    ``max_lanes_per_16g_shard_*`` divides a v5e-class 16 GiB HBM by the
    double-buffered (donation) per-lane footprint — the table is a proxy
    until the tunnel is back; the measurement method is chip-ready."""
    from madraft_tpu.tpusim import state as stmod
    from madraft_tpu.tpusim.config import packed_bounds

    cfg = flagship_config()
    s = stmod.init_cluster(cfg, jax.random.PRNGKey(0))
    wide = stmod.tree_bytes(s)
    packed = stmod.tree_bytes(stmod.pack_state(cfg, s))
    hbm = 16 * (1 << 30)
    return {
        "config": f"{cfg.n_nodes}-node/log_cap {cfg.log_cap} (storm shape)",
        "max_lane_ticks": cfg.max_lane_ticks,
        "bounds": packed_bounds(cfg)._asdict(),
        "wide_bytes_per_lane": wide,
        "packed_bytes_per_lane": packed,
        "reduction": round(wide / packed, 3),
        "max_lanes_per_16g_shard_wide": hbm // (2 * wide),
        "max_lanes_per_16g_shard_packed": hbm // (2 * packed),
    }


def bench_service_footprint(n_deployments: int, n_ticks: int) -> dict:
    """Service-layer resident-carry footprint, wide vs packed (ISSUE 11):
    bytes per deployment for the kv / ctrler / shardkv stacks at their
    bench shapes (live buffers, never a schema estimate), plus the
    PACK-TAX A/B on the heaviest stack — shardkv group-cluster-steps/s at
    equal shape on the wide vs packed carry. The packed leg shares its
    compiled program with bench_shardkv (same static shapes), so the row
    mostly pays one extra wide-leg compile. On CPU the packed path pays
    the pack/unpack casts with no HBM to win back, so the ratio is the
    regression bound PERF.md round 11 records (<= 10%, PR 9's measured
    tax); the bytes column is the on-chip story queued behind the tunnel.
    MADTPU_BENCH_FUSED=1 adds the cfg.fuse_packed_step leg (its own
    compiled program — the scan-level fusion audit's measurement surface,
    recorded in PERF.md rather than paid on every bench run)."""
    import os

    from madraft_tpu.tpusim import state as stmod
    from madraft_tpu.tpusim.ctrler import (
        CtrlerConfig,
        init_ctrler_cluster,
        pack_ctrler_state,
    )
    from madraft_tpu.tpusim.kv import KvConfig, init_kv_cluster, pack_kv_state
    from madraft_tpu.tpusim.shardkv import (
        ShardKvConfig,
        init_shardkv_cluster,
        make_shardkv_fuzz_fn,
        pack_shardkv_state,
    )

    kv_cfg = flagship_config().replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16
    )
    kv_kcfg = KvConfig(p_get=0.3)
    ctl_cfg = flagship_config().replace(
        p_client_cmd=0.0, compact_at_commit=False, log_cap=32, compact_every=8
    )
    ctl_kcfg = CtrlerConfig()
    skv_cfg = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05,
    )
    skv_kcfg = ShardKvConfig()

    key = jax.random.PRNGKey(0)
    rows = {}
    for name, wide_s, packed_s in (
        ("kv", init_kv_cluster(kv_cfg, kv_kcfg, key),
         lambda s: pack_kv_state(kv_cfg, kv_kcfg, s)),
        ("ctrler", init_ctrler_cluster(ctl_cfg, ctl_kcfg, key),
         lambda s: pack_ctrler_state(ctl_cfg, ctl_kcfg, s)),
        ("shardkv", init_shardkv_cluster(skv_cfg, skv_kcfg, key),
         lambda s: pack_shardkv_state(skv_cfg, skv_kcfg, s)),
    ):
        w = stmod.tree_bytes(wide_s)
        p = stmod.tree_bytes(packed_s(wide_s))
        rows[name] = {
            "wide_bytes_per_deployment": w,
            "packed_bytes_per_deployment": p,
            "reduction": round(w / p, 3),
        }

    sync = lambda s: np.asarray(s.violations)  # noqa: E731
    legs = {"wide": dict(pack_states=False), "packed": dict(pack_states=True)}
    if os.environ.get("MADTPU_BENCH_FUSED"):
        legs["fused"] = dict(pack_states=True, fused=True)
    for leg, opts in legs.items():
        cfg = skv_cfg.replace(fuse_packed_step=opts.pop("fused", False))
        fn = make_shardkv_fuzz_fn(cfg, skv_kcfg, n_deployments, n_ticks,
                                  **opts)
        finish = _compile_region(fn, sync)
        best, runs, spread, _ = _timed(lambda: fn(12345), sync)
        rows["shardkv"].update({
            f"{leg}_cluster_steps_per_sec": round(
                n_deployments * n_ticks * skv_kcfg.n_groups / best, 1
            ),
            f"{leg}_best_wall_s": round(best, 3),
            f"{leg}_run_spread": round(spread, 3),
            f"{leg}_compile_s": finish(best),
        })
    rows["shardkv"]["packed_steps_ratio"] = round(
        rows["shardkv"]["packed_cluster_steps_per_sec"]
        / rows["shardkv"]["wide_cluster_steps_per_sec"], 3
    )
    rows["shape"] = {
        "n_deployments": n_deployments, "n_ticks": n_ticks,
        "n_groups": skv_kcfg.n_groups,
    }
    return rows


def bench_coverage(n_lanes: int, budget_ticks: int) -> dict:
    """Coverage-guided vs uniform-random A/B (ROADMAP item 3), two legs:

    GROUND TRUTH — the 3-node small-alphabet config whose abstract-state
    space coverage.enumerate_abstract_codes enumerates offline (the
    LNT/mCRL2-style yardstick): fraction of enumerated states reached per
    chip-second, guided vs random, SAME lanes and SAME tick budget. The
    bitmap maps states 1:1 (identity mode), so the fractions are exact
    counts, not hash estimates.

    BUG HUNT — the planted-bug durability profile: new-fingerprints and
    violations per chip-second, guided vs random. Both legs run the SAME
    coverage programs (random = measurement-only refill), so the per-step
    cost is identical and the per-chip-second comparison is pure policy.
    """
    from madraft_tpu.tpusim.config import (
        CoverageConfig,
        coverage_ground_truth,
        storm_profiles,
    )
    from madraft_tpu.tpusim.coverage import enumerate_abstract_codes

    gt_cfg, gt_ccfg, gt_horizon = coverage_ground_truth()
    total = int(len(enumerate_abstract_codes(gt_cfg.n_nodes, gt_ccfg)))
    gt_budget = max(budget_ticks, 40 * gt_horizon)

    def leg(cfg, ccfg, horizon, budget, seed=12345):
        # run_pool warms its programs outside its timed window
        s = run_pool(cfg, seed, n_lanes, horizon, budget_ticks=budget,
                     coverage=ccfg)
        return s

    g = leg(gt_cfg, gt_ccfg, gt_horizon, gt_budget)
    r = leg(gt_cfg, gt_ccfg.replace(guided=False), gt_horizon, gt_budget)

    prof, _, rec_ticks, _bugs = storm_profiles()["durability"]
    bug_cfg = prof.replace(bug="ack_before_fsync")
    horizon = min(rec_ticks, budget_ticks)
    dcc = CoverageConfig()
    bg = leg(bug_cfg, dcc, horizon, budget_ticks, seed=1)
    br = leg(bug_cfg, dcc.replace(guided=False), horizon, budget_ticks,
             seed=1)
    # the coverage-MODE cliff (ROADMAP 3d), re-measured on the packed
    # layout: the same profile/budget through the plain pool (uniform
    # scalar knobs) vs the coverage pool (per-lane knob rows + per-tick
    # fingerprint) — the price of heterogeneous guided lanes
    plain = run_pool(bug_cfg, 1, n_lanes, horizon, budget_ticks=budget_ticks)
    cliff = {
        "plain_pool_steps_per_sec": plain["steps_per_sec"],
        "coverage_pool_steps_per_sec": br["steps_per_sec"],
        "cliff_factor": (
            round(plain["steps_per_sec"] / br["steps_per_sec"], 3)
            if br["steps_per_sec"] else None
        ),
        "state_layout": br["state_layout"],
    }

    def frac(s):
        return s["coverage"]["seen_fingerprints"] / total

    def frac_per_s(s):
        return frac(s) / s["wall_s"] if s["wall_s"] > 0 else None

    return {
        "knob_layout_cliff": cliff,
        "ground_truth": {
            "config": "3-node/64-tick/2-level alphabet",
            "enumerated_states": total,
            "lanes": n_lanes,
            "budget_ticks": gt_budget,
            "guided_states": g["coverage"]["seen_fingerprints"],
            "random_states": r["coverage"]["seen_fingerprints"],
            "guided_frac": round(frac(g), 4),
            "random_frac": round(frac(r), 4),
            "guided_wall_s": g["wall_s"],
            "random_wall_s": r["wall_s"],
            "guided_frac_per_chip_s": round(frac_per_s(g) or 0.0, 5),
            "random_frac_per_chip_s": round(frac_per_s(r) or 0.0, 5),
            "state_ratio": (
                round(frac(g) / frac(r), 3) if frac(r) else None
            ),
        },
        "durability_bug": {
            "profile": "durability",
            "bug": "ack_before_fsync",
            "lanes": n_lanes,
            "budget_ticks": budget_ticks,
            "horizon": horizon,
            # hashed-bitmap mode (5-node alphabet >> bitmap): the new_fps
            # counts below are collision-distorted popcounts, not exact
            # state counts like the identity-mapped ground-truth leg's
            "identity": bg["coverage"]["identity"],
            "guided_new_fps": bg["coverage"]["seen_fingerprints"],
            "random_new_fps": br["coverage"]["seen_fingerprints"],
            "guided_violations": bg["retired_violating"],
            "random_violations": br["retired_violating"],
            "guided_wall_s": bg["wall_s"],
            "random_wall_s": br["wall_s"],
            "guided_viol_per_chip_s": bg["violations_per_s"],
            "random_viol_per_chip_s": br["violations_per_s"],
            "guided_new_fps_per_chip_s": (
                bg["coverage"]["new_fingerprints_per_s"]
            ),
            "random_new_fps_per_chip_s": (
                br["coverage"]["new_fingerprints_per_s"]
            ),
        },
    }


def next_bench_path() -> str:
    """The artifact trail's next auto-number: BENCH_r<N+1>.json where N is
    the highest existing round file (the trail stopped at r05 while rounds
    6-9 lived only in PERF.md prose — ISSUE 10 satellite resumes it)."""
    import glob
    import os
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    ns = [0]
    for p in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(p))
        if m:
            ns.append(int(m.group(1)))
    return os.path.join(here, f"BENCH_r{max(ns) + 1:02d}.json")


def main() -> None:
    # MADTPU_BENCH_PLATFORM=cpu forces the CPU backend (ci.sh fallback when
    # no healthy accelerator is attached); must run before backend init.
    # Otherwise: probe the tunnel with bounded retry/backoff — round 3 lost
    # its bench artifact to one transient init failure (BENCH_r03.json rc:1,
    # third outage of the round); a degraded tunnel must yield a labeled
    # CPU-fallback artifact, not an empty record.
    import os

    # --out [PATH]: additionally write the JSON line to PATH, or — with no
    # PATH — to the next auto-numbered BENCH_r<N>.json, resuming the
    # per-round artifact trail. The value is the next argument unless it is
    # a flag or one of the integer positional scale args; stripped before
    # the positionals are read so `bench.py 1024 128 --out` keeps working.
    def _is_int(s):
        try:
            int(s)
            return True
        except ValueError:
            return False

    argv = sys.argv[1:]
    out_path = None
    if "--out" in argv:
        i = argv.index("--out")
        argv.pop(i)
        if i < len(argv) and not argv[i].startswith("-") \
                and not _is_int(argv[i]):
            out_path = argv.pop(i)
        else:
            out_path = next_bench_path()
    sys.argv = [sys.argv[0]] + argv

    if len(sys.argv) > 1 and sys.argv[1] == "--pool-scaling-child":
        # the 2-virtual-device scaling subprocess (bench_pool_scaling):
        # platform/devices come from the parent's env, set before the
        # module-level jax import of this fresh process
        print(json.dumps(
            _pool_scaling_child(int(sys.argv[2]), int(sys.argv[3]))
        ))
        return

    from madraft_tpu._platform import apply_platform, init_backend_with_retry

    # bench runs exist to leave artifacts — opt in to TUNNEL_STATUS.jsonl
    # probe recording (library/test imports stay silent by default)
    os.environ.setdefault("MADTPU_TUNNEL_LOG", "1")
    plat = apply_platform(os.environ.get("MADTPU_BENCH_PLATFORM"))
    degraded = None
    if plat != "cpu":
        ok, detail = init_backend_with_retry(plat)
        if not ok:
            degraded = f"accelerator unavailable after retries ({detail})"
            print(f"[bench] {degraded}; falling back to CPU", file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")
    n_clusters = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    n_ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    raft = bench_raft(n_clusters, n_ticks, flagship_config())
    # latency-tail row (ISSUE 10): p50/p99 + the p99 regression gate on the
    # storm profile, same //4 sizing as the other secondary rows
    latency = bench_latency(max(256, n_clusters // 4), n_ticks)
    # tail-attribution A/B (ISSUE 12): fixed scale on purpose — the pinned
    # dominant-phase assertions were measured at this shape across seeds
    tail_attrib = bench_tail_attrib(64, 600)
    # per-profile game-day gate table (ISSUE 19): every storm_profiles()
    # name, clean algorithm, liveness floor + p99 ceiling from
    # config.profile_gates() — the per-profile generalization of tail_gate;
    # fixed per-profile scale on purpose (the floors were measured there)
    pgates = bench_profile_gates()
    kv = bench_kv(max(256, n_clusters // 4), max(256, n_ticks // 2))
    # //4 like kv: 512 clusters under-fill the chip for this layer
    # (2.2M steps/s at 512 vs 3.4M at 1024, measured in the r03d soak)
    ctl = bench_ctrler(max(256, n_clusters // 4), max(256, n_ticks // 2))
    skv = bench_shardkv(max(64, n_clusters // 16), max(128, n_ticks // 4))
    # the live-ctrler 4B program (one extra raft cluster + the announce/
    # query protocol per deployment) as its own timed row
    skvl = bench_shardkv(max(64, n_clusters // 16), max(128, n_ticks // 4),
                         live_ctrler=True)
    # the computed-ctrler 4B program (the 4A-composed mode: per-replica
    # rebalance at the ctrl walker + map-adoption apply path) as its own row
    skvc = bench_shardkv(max(64, n_clusters // 16), max(128, n_ticks // 4),
                         computed_ctrler=True)
    # continuous-batching A/B: the fixed driver's waste (sticky violators
    # ticking to the horizon) grows with the budget — >= 20 durability
    # horizons makes it first-order (PERF.md round 6); smokes keep a small
    # budget so the row stays cheap on CPU
    pool = bench_pool(max(64, n_clusters // 16), max(2400, 12 * n_ticks))
    # live-telemetry overhead A/B (ISSUE 17): heartbeat-off vs -on at equal
    # shape; smaller budget than the pool row — it pays two full pool runs
    telem = bench_telemetry_overhead(max(64, n_clusters // 16),
                                     max(1200, 6 * n_ticks))
    # sharded-pool 1-vs-2-device scaling A/B (ROADMAP item 1), in its own
    # 2-virtual-device subprocess; smaller budget than the pool row — it
    # pays two full pool runs
    pscale = bench_pool_scaling(max(64, n_clusters // 16),
                                max(1200, 6 * n_ticks))
    # coverage-guided vs uniform-random A/B (ROADMAP item 3): the
    # ground-truth reached-fraction comparison plus the planted-bug leg;
    # a smaller budget than the pool row — two extra pool runs per leg
    covr = bench_coverage(max(64, n_clusters // 16), max(1200, 6 * n_ticks))
    # per-lane resident-state footprint, wide vs packed (ISSUE 9): tracks
    # the lanes-per-HBM trajectory from this round on
    footprint = bench_state_footprint()
    # service-layer footprint + shardkv pack-tax A/B (ISSUE 11): bytes per
    # deployment wide vs packed for kv/ctrler/shardkv, and group-cluster-
    # steps/s at equal shape on both carries (same shapes as the shardkv
    # row, so the packed leg shares its compiled program)
    svc_footprint = bench_service_footprint(max(64, n_clusters // 16),
                                            max(128, n_ticks // 4))
    steps_per_sec = raft.pop("steps_per_sec")
    doc = json.dumps(
            {
                "metric": "raft_fuzz_cluster_steps_per_sec",
                "value": round(steps_per_sec, 1),
                "unit": "cluster-steps/s/chip",
                # the north-star denominator is a TPU number; a degraded
                # (CPU-fallback) run must not quietly re-denominate it as a
                # 260x "regression" (round-4 verdict, weak #2)
                "vs_baseline": (
                    None if degraded
                    else round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3)
                ),
                "detail": {
                    **raft,
                    "kv_fuzz_steps_per_sec": round(kv.pop("steps_per_sec"), 1),
                    "kv": kv,
                    "ctrler_fuzz_steps_per_sec": round(
                        ctl.pop("steps_per_sec"), 1
                    ),
                    "ctrler": ctl,
                    "shardkv_fuzz_cluster_steps_per_sec": skv.pop(
                        "cluster_steps_per_sec"
                    ),
                    "shardkv": skv,
                    "shardkv_live_ctrler_cluster_steps_per_sec": skvl.pop(
                        "cluster_steps_per_sec"
                    ),
                    "shardkv_live_ctrler": skvl,
                    "shardkv_computed_ctrler_cluster_steps_per_sec": skvc.pop(
                        "cluster_steps_per_sec"
                    ),
                    "shardkv_computed_ctrler": skvc,
                    "pool_viol_per_chip_s_ratio": pool[
                        "viol_per_chip_s_ratio"
                    ],
                    "pool": pool,
                    # heartbeat-emission overhead gate (ISSUE 17)
                    "telemetry_overhead_pass": telem["pass"],
                    "telemetry_overhead": telem,
                    "pool_scaling_efficiency": pscale.get(
                        "scaling_efficiency"
                    ),
                    "pool_scaling": pscale,
                    "coverage_state_ratio": covr["ground_truth"][
                        "state_ratio"
                    ],
                    "coverage": covr,
                    "state_footprint_reduction": footprint["reduction"],
                    "state_footprint": footprint,
                    "service_footprint_shardkv_reduction": svc_footprint[
                        "shardkv"
                    ]["reduction"],
                    "service_footprint": svc_footprint,
                    # latency tail + the p99 regression gate (ISSUE 10)
                    "latency_p50_ticks": latency["latency_p50_ticks"],
                    "latency_p99_ticks": latency["latency_p99_ticks"],
                    "tail_gate_pass": latency["tail_gate"]["pass"],
                    "latency": latency,
                    # phase-attribution A/B + dominant-phase pin (ISSUE 12)
                    "tail_attrib_pass": tail_attrib["pass"],
                    "tail_attrib": tail_attrib,
                    # per-profile liveness/p99 gate table (ISSUE 19)
                    "profile_gates_pass": pgates["pass"],
                    "profile_gates": pgates,
                    "device": str(jax.devices()[0]),
                    **({"degraded": degraded} if degraded else {}),
                },
            }
    )
    print(doc)
    if out_path:
        with open(out_path, "w") as f:
            f.write(doc + "\n")
        print(f"[bench] artifact written to {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
