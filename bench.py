"""Headline benchmark: batched 5-node Raft partition/crash fuzz throughput.

North star (BASELINE.json): >=100k 5-node cluster-steps/sec/chip with zero safety
violations. Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import make_fuzz_fn, report

BASELINE_STEPS_PER_SEC = 100_000.0  # BASELINE.json north star


def main() -> None:
    n_clusters = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    n_ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    cfg = SimConfig(
        n_nodes=5,
        p_client_cmd=0.2,
        loss_prob=0.1,
        p_crash=0.01,
        p_restart=0.2,
        max_dead=2,
        p_repartition=0.02,
        p_heal=0.05,
    )
    fn = make_fuzz_fn(cfg, n_clusters, n_ticks)
    seed = jnp.asarray(12345, jnp.uint32)
    jax.block_until_ready(fn(seed))  # compile + warm-up
    t0 = time.perf_counter()
    final = jax.block_until_ready(fn(seed))
    dt = time.perf_counter() - t0
    rep = report(final)
    steps_per_sec = n_clusters * n_ticks / dt
    print(
        json.dumps(
            {
                "metric": "raft_fuzz_cluster_steps_per_sec",
                "value": round(steps_per_sec, 1),
                "unit": "cluster-steps/s/chip",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
                "detail": {
                    "n_clusters": n_clusters,
                    "n_ticks": n_ticks,
                    "wall_s": round(dt, 3),
                    "violations": int(rep.n_violating),
                    "clusters_with_commits": int((rep.committed > 0).sum()),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
