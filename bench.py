"""Headline benchmark: batched 5-node Raft partition/crash fuzz throughput.

North star (BASELINE.json): >=100k 5-node cluster-steps/sec/chip with zero safety
violations. Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Methodology (round-2, after the round-1 postmortem):
- The tunnel platform's block_until_ready does NOT block, so every timed
  region ends with a device->host fetch of the violation bitmap — the only
  honest sync point.
- The tick scan is chunked (host loop over compiled CHUNK-tick scans) so a
  single device execution stays well under the tunnel's per-call deadline —
  the round-1 "TPU device error" at 16k clusters was a >60 s single execution,
  not a kernel bug.
- The timed region is whole fuzz runs repeated until >=1 s of wall time (at
  least 2 runs); the reported value is the best run, and the spread across
  runs is reported so back-to-back agreement is visible.
- hbm_util_floor is a lower-bound utilization proxy: each tick must read and
  write the cluster state at least once, so (2 * state_bytes * ticks) / time
  relative to the chip's HBM peak bounds how far from memory-roofline the
  step function runs.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim import SimConfig, init_cluster, step_cluster
from madraft_tpu.tpusim.engine import report

BASELINE_STEPS_PER_SEC = 100_000.0  # BASELINE.json north star
HBM_PEAK_BYTES_PER_S = 819e9        # TPU v5e; proxy denominator only
CHUNK_TICKS = 64                    # one device execution = one chunk


def flagship_config() -> SimConfig:
    return SimConfig(
        n_nodes=5,
        p_client_cmd=0.2,
        loss_prob=0.1,
        p_crash=0.01,
        p_restart=0.2,
        max_dead=2,
        p_repartition=0.02,
        p_heal=0.05,
    )


def main() -> None:
    n_clusters = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    n_ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    cfg = flagship_config()
    import functools

    @jax.jit
    def init(seed):
        base = jax.random.PRNGKey(seed)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(n_clusters)
        )
        return jax.vmap(functools.partial(init_cluster, cfg))(keys), keys

    @jax.jit
    def chunk(states, keys):
        def body(c, _):
            return jax.vmap(functools.partial(step_cluster, cfg))(c, keys), None
        final, _ = jax.lax.scan(body, states, None, length=CHUNK_TICKS)
        return final

    n_chunks = max(1, n_ticks // CHUNK_TICKS)

    def run(seed: int):
        states, keys = init(jnp.asarray(seed, jnp.uint32))
        for _ in range(n_chunks):
            states = chunk(states, keys)
        return states

    # compile + warm-up; the fetch is the sync point (tunnel caveat above)
    final = run(12345)
    _ = np.asarray(final.violations)

    times = []
    while sum(times) < 1.0 or len(times) < 2:
        t0 = time.perf_counter()
        final = run(12345)
        viol = np.asarray(final.violations)
        times.append(time.perf_counter() - t0)
    rep = report(final)
    best = min(times)
    steps = n_chunks * CHUNK_TICKS * n_clusters
    steps_per_sec = steps / best
    spread = (max(times) - min(times)) / best
    state_bytes = sum(x.nbytes for x in jax.tree.leaves(final))
    hbm_floor = 2 * state_bytes * n_chunks * CHUNK_TICKS / best / HBM_PEAK_BYTES_PER_S

    print(
        json.dumps(
            {
                "metric": "raft_fuzz_cluster_steps_per_sec",
                "value": round(steps_per_sec, 1),
                "unit": "cluster-steps/s/chip",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
                "detail": {
                    "n_clusters": n_clusters,
                    "n_ticks": n_chunks * CHUNK_TICKS,
                    "runs": len(times),
                    "best_wall_s": round(best, 3),
                    "run_spread": round(spread, 3),
                    "hbm_util_floor": round(hbm_floor, 4),
                    "violations": int((viol != 0).sum()),
                    "clusters_with_commits": int((rep.committed > 0).sum()),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
