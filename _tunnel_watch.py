"""Patient TPU-tunnel watcher.

Probes the default (axon) backend in a subprocess on a fixed cadence until
it answers, recording every outcome to TUNNEL_STATUS.jsonl via
_platform._record_probe, then touches /tmp/madtpu_tunnel_up and exits.
Never kills an in-flight TPU init (the verify-skill gotcha: killing TPU
processes mid-init wedges the tunnel further) — each probe is its own
subprocess with a hard timeout, and the waiter itself just sleeps.

Usage: nohup python _tunnel_watch.py > /tmp/tunnel_watch.log 2>&1 &
"""

import os
import sys
import time

# recording probe outcomes IS this script's purpose — opt in to the
# TUNNEL_STATUS.jsonl artifact (library/test imports stay silent by default)
os.environ.setdefault("MADTPU_TUNNEL_LOG", "1")

from madraft_tpu import _platform

MARKER = "/tmp/madtpu_tunnel_up"
PERIOD_S = 600
PROBE_TIMEOUT_S = 120


def main() -> None:
    n = 0
    while True:
        n += 1
        ok, detail = _platform.probe_backend(None, timeout_s=PROBE_TIMEOUT_S)
        print(f"probe {n}: ok={ok} {detail}", flush=True)
        if ok:
            with open(MARKER, "w") as f:
                f.write(detail + "\n")
            print("tunnel is up — marker written; exiting", flush=True)
            return
        time.sleep(PERIOD_S)


if __name__ == "__main__":
    sys.exit(main())
