"""On-device metrics plane (ISSUE 10): metrics-off trajectories are
bit-identical (no PRNG draws added), host-recomputed latencies from the
flight-recorder stamp stream land in exactly the device histogram's
buckets, the delivery counters sum to msg_count, clerk-ack fold counts are
exact against the acked-op totals, the pool carries histograms through
rows/summary at any device count and layout, and the stats verb renders
any report stream."""

import contextlib
import io
import json

import jax
import numpy as np
import pytest

from madraft_tpu.__main__ import main
from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim import metrics as M
from madraft_tpu.tpusim.config import (
    HIST_BUCKETS,
    METRIC_EVENTS,
    storm_profiles,
)
from madraft_tpu.tpusim.engine import fuzz, replay_cluster, run_pool
from madraft_tpu.tpusim.trace import replay_cluster_traced

STORM = SimConfig(
    n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
    max_dead=2, p_repartition=0.02, p_heal=0.05,
)
MSTORM = STORM.replace(metrics=True)
DURABILITY = storm_profiles()["durability"][0]


def run_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, buf.getvalue()


def test_metrics_off_trajectories_bit_identical():
    # the plane adds NO PRNG draws: a metrics run IS the metrics-off run
    # plus observation — violations, commits, and deliveries must agree
    r_off = fuzz(STORM, 7, 8, 128)
    r_on = fuzz(MSTORM, 7, 8, 128)
    for f in ("violations", "first_violation_tick", "committed",
              "msg_count"):
        assert np.array_equal(getattr(r_off, f), getattr(r_on, f)), f
    assert r_off.lat_hist is None and r_on.lat_hist is not None
    assert r_on.lat_hist.shape == (8, HIST_BUCKETS)


def test_traced_replay_cross_check_histogram():
    # THE cross-check satellite: recompute every latency on the host from
    # the flight recorder's per-tick submit-stamp stream (t - stamp over
    # nonzero lanes) and bucket it with an INDEPENDENT implementation
    # (np.searchsorted) — the totals must land in exactly the buckets the
    # on-device fold reported for the same (seed, cluster id)
    final, rec = replay_cluster_traced(MSTORM, 7, 3, 300)
    untraced = replay_cluster(MSTORM, 7, 3, 300)
    assert np.array_equal(np.asarray(final.lat_hist),
                          np.asarray(untraced.lat_hist))
    assert np.array_equal(np.asarray(final.ev_counts),
                          np.asarray(untraced.ev_counts))
    host = np.zeros(HIST_BUCKETS, np.int64)
    T = rec.shadow_sub.shape[0]
    for ti in range(T):
        subs = rec.shadow_sub[ti]
        lats = (ti + 1) - subs[subs > 0]
        assert (lats >= 0).all()
        for b in M.host_bucket(lats):
            host[b] += 1
    assert host.sum() > 0, "storm committed no injected commands"
    np.testing.assert_array_equal(host, np.asarray(final.lat_hist))
    # the cumulative trace row agrees with the final state too
    np.testing.assert_array_equal(np.asarray(rec.lat_hist[-1]),
                                  np.asarray(final.lat_hist))


def test_delivery_counters_sum_to_msg_count():
    st = replay_cluster(MSTORM, 7, 3, 300)
    ev = np.asarray(st.ev_counts)
    names = list(METRIC_EVENTS)
    deliv = sum(ev[names.index(k)] for k in
                ("rv_req_delivered", "rv_rsp_delivered", "ae_req_delivered",
                 "ae_rsp_delivered", "snap_delivered"))
    assert deliv == int(st.msg_count)
    assert ev[names.index("elections_won")] >= 1
    assert ev[names.index("commit_advances")] >= 1
    # crashes/restarts come from the same Bernoulli stream the step always
    # drew; the storm profile crashes, so the counters must see it
    assert ev[names.index("crashes")] >= 1
    # every latency the histogram folded is a committed injected command —
    # bounded by the committed-entry total
    assert 0 < np.asarray(st.lat_hist).sum() <= int(st.shadow_len)


def test_kv_clerk_ack_fold_is_exact():
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz

    cfg = MSTORM.replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16,
        p_crash=0.0, max_dead=0,
    )
    rep = kv_fuzz(cfg, KvConfig(p_get=0.3, p_put=0.2), 5, 8, 200)
    # clerks serialize seqs, so acked_ops IS the number of ack events, and
    # every ack folded exactly one latency
    assert rep.lat_hist.sum() == rep.acked_ops.sum() > 0
    assert rep.ev_counts.shape == (8, len(METRIC_EVENTS))
    # service entries carry stamp 0: the raft-layer commit fold must not
    # double-count clerk ops (each op folds once, at its clerk ack)
    per_cluster = rep.lat_hist.sum(axis=1)
    np.testing.assert_array_equal(per_cluster, rep.acked_ops)


def test_shardkv_clerk_ack_fold_is_exact():
    from madraft_tpu.tpusim.shardkv import ShardKvConfig, shardkv_fuzz

    cfg = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05, metrics=True,
    )
    rep = shardkv_fuzz(cfg, ShardKvConfig(), 3, 2, 320)
    assert rep.lat_hist is not None
    np.testing.assert_array_equal(rep.lat_hist.sum(axis=1), rep.acked_ops)
    assert rep.acked_ops.sum() > 0
    assert rep.ev_counts.shape[-1] == len(METRIC_EVENTS)


def _pool_rows_and_summary(devices=None, pack_states=None, seed=3):
    cfg = DURABILITY.replace(bug="ack_before_fsync", metrics=True)
    rows = []
    s = run_pool(cfg, seed, 16, 100, chunk_ticks=50, budget_ticks=300,
                 devices=devices, on_retired=rows.append,
                 pack_states=pack_states)
    return rows, s


def test_pool_metrics_rows_and_summary():
    rows, s = _pool_rows_and_summary()
    lat = s["latency"]
    assert lat["ops"] > 0 and sum(lat["hist"]) == lat["ops"]
    assert s["events"]["commit_advances"] > 0
    assert all("latency_hist" in r and "events" in r for r in rows)
    # summary latency == retired rows + the last harvest's in-flight lanes;
    # at this budget every lane retires at the horizon, so the row rows
    # alone must not exceed the merged total
    row_sum = np.sum([r["latency_hist"] for r in rows], axis=0)
    assert (row_sum <= np.asarray(lat["hist"])).all()


def test_pool_metrics_bit_identical_across_layouts():
    rows_w, s_w = _pool_rows_and_summary(pack_states=False)
    rows_p, s_p = _pool_rows_and_summary(pack_states=True)
    assert s_w["state_layout"] == "wide" and s_p["state_layout"] == "packed"
    assert s_w["latency"] == s_p["latency"]
    assert s_w["events"] == s_p["events"]
    assert [r["latency_hist"] for r in rows_w] == \
        [r["latency_hist"] for r in rows_p]


def test_pool_metrics_device_count_invariant():
    # the ISSUE-10 extension of the PR-7 invariance contract: the SUMMED
    # histograms (and counters) of a fixed budget agree at any device count
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    rows1, s1 = _pool_rows_and_summary(devices=1)
    rows2, s2 = _pool_rows_and_summary(devices=2)
    assert s1["latency"] == s2["latency"]
    assert s1["events"] == s2["events"]
    key = lambda rows: sorted(  # noqa: E731
        (r["cluster_id"], tuple(r["latency_hist"])) for r in rows
    )
    assert key(rows1) == key(rows2)


def test_quantile_decode_and_bucket_layout():
    # layout: bucket 0 = [0,1], k >= 1 = [2^k, 2^(k+1)-1], last open-ended
    assert M.bucket_bounds(0) == (0, 1)
    assert M.bucket_bounds(3) == (8, 15)
    assert M.bucket_bounds(HIST_BUCKETS - 1)[1] is None
    assert list(M.host_bucket(np.asarray([0, 1, 2, 3, 4, 1 << 20]))) == \
        [0, 0, 1, 1, 2, HIST_BUCKETS - 1]
    # device fold == host buckets on a latency sweep
    lats = np.arange(0, 500, dtype=np.int32)
    dev = np.asarray(M.fold_latencies(
        np.zeros(HIST_BUCKETS, np.int32), lats, np.ones_like(lats, bool)
    ))
    host = np.bincount(M.host_bucket(lats), minlength=HIST_BUCKETS)
    np.testing.assert_array_equal(dev, host)
    # quantile = upper edge of the quantile's bucket
    h = np.zeros(HIST_BUCKETS, np.int64)
    h[2] = 99
    h[5] = 1
    assert M.quantile_from_hist(h, 0.5) == 7
    assert M.quantile_from_hist(h, 0.99) == 7
    assert M.quantile_from_hist(h, 0.999) == 63
    assert M.quantile_from_hist(np.zeros(HIST_BUCKETS), 0.5) is None
    # merging is plain addition of hist rows — the property every surface
    # (pool summary, stats verb, cross-file sums) relies on
    a = M.latency_summary(h)
    merged = M.latency_summary(np.asarray(a["hist"]) + np.asarray(a["hist"]))
    assert merged["ops"] == 2 * a["ops"]
    assert merged["p99_ticks"] == a["p99_ticks"]


def test_stats_summary_wins_rule_is_per_stream():
    # a full pool stream (rows + summary) next to a rows-only grep from a
    # DIFFERENT run: the summary suppresses only ITS OWN stream's rows —
    # the rows-only file must still merge in full
    from madraft_tpu.__main__ import _collect_stats

    hist_a = [0] * HIST_BUCKETS
    hist_a[2] = 5
    pool_stream = [
        json.dumps({"cluster_id": 0, "latency_hist": hist_a,
                    "events": {"crashes": 1}}),
        json.dumps({"lanes": 1, "latency": {"ops": 5, "hist": hist_a},
                    "events": {"crashes": 1}}),
    ]
    hist_b = [0] * HIST_BUCKETS
    hist_b[4] = 3
    rows_only = [
        json.dumps({"cluster_id": 9, "latency_hist": hist_b,
                    "events": {"crashes": 2}}),
    ]
    hist, events, seen = _collect_stats([pool_stream, rows_only])
    assert seen == 2  # the pool summary + the foreign row, not the pool row
    assert hist[2] == 5 and hist[4] == 3
    assert events[list(METRIC_EVENTS).index("crashes")] == 3
    # an events-ONLY report (the ctrler layer: counters without latency
    # stamps) must merge too, not read as "no metrics found"
    ev_only = [json.dumps({"violating": 0, "events": {"crashes": 4}})]
    hist, events, seen = _collect_stats([ev_only])
    assert seen == 1 and hist.sum() == 0
    assert events[list(METRIC_EVENTS).index("crashes")] == 4


def test_explain_chrome_gains_liveness_counters(tmp_path):
    from madraft_tpu.tpusim.trace import chrome_trace, decode_events

    # 300 ticks on purpose: shares the traced program (scan length is a
    # static cache key) with the cross-check test above
    final, rec = replay_cluster_traced(MSTORM, 7, 3, 300)
    events = decode_events(rec)
    advances = [e for e in events if e["event"] == "commit_advance"]
    assert advances and all("latencies" in e for e in advances)
    total = sum(len(e["latencies"]) for e in advances)
    assert total == int(np.asarray(final.lat_hist).sum())
    doc = chrome_trace(rec, MSTORM.ms_per_tick, events)
    counters = {e["name"] for e in doc["traceEvents"] if e["ph"] == "C"}
    assert {"liveness", "commit_latency_ticks", "deliveries"} <= counters
    live = [e for e in doc["traceEvents"]
            if e["ph"] == "C" and e["name"] == "liveness"]
    assert sum(e["args"]["commit_advances"] for e in live) == int(
        np.asarray(final.ev_counts)[list(METRIC_EVENTS).index(
            "commit_advances")]
    )


def test_service_cli_metrics_plumbing():
    # shardkv-fuzz builds its SimConfig from scratch — the --metrics flag
    # must be carried explicitly (a dropped flag silently reports nothing);
    # ctrler-fuzz now surfaces a REAL latency dict alongside the events
    # (the ISSUE 11 clerk_sub satellite closed PR 10's events-only gap)
    rc, out = run_cli(["shardkv-fuzz", "--clusters", "2", "--ticks", "160",
                       "--metrics", "--nodes", "3"])
    d = json.loads(out.strip().splitlines()[-1])
    assert "latency" in d and d["latency"]["ops"] > 0, d.keys()
    assert "events" in d
    rc, out = run_cli(["ctrler-fuzz", "--clusters", "8", "--ticks", "128",
                       "--metrics"])
    d = json.loads(out.strip().splitlines()[-1])
    assert "latency" in d and d["latency"]["ops"] > 0, d.keys()
    assert "events" in d
    assert d["events"]["elections_won"] > 0


def test_fuzz_cli_report_and_stats_verb(tmp_path):
    rc, out = run_cli([
        "fuzz", "--clusters", "8", "--ticks", "128", "--storm", "--metrics",
        "--seed", "7",
    ])
    rep = json.loads(out.strip().splitlines()[-1])
    assert "latency" in rep and rep["latency"]["ops"] > 0
    assert set(rep["events"]) == set(METRIC_EVENTS)
    p = tmp_path / "rep.json"
    p.write_text(out)
    rc, rendered = run_cli(["stats", str(p)])
    assert rc == 0
    assert f"ops={rep['latency']['ops']}" in rendered
    # a metrics-off report carries nothing to render: exit 2, say so
    rc, out_off = run_cli([
        "fuzz", "--clusters", "8", "--ticks", "64", "--seed", "7",
    ])
    assert "latency" not in json.loads(out_off.strip().splitlines()[-1])
    p2 = tmp_path / "off.json"
    p2.write_text(out_off)
    assert run_cli(["stats", str(p2)])[0] == 2
