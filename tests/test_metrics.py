"""On-device metrics plane (ISSUE 10): metrics-off trajectories are
bit-identical (no PRNG draws added), host-recomputed latencies from the
flight-recorder stamp stream land in exactly the device histogram's
buckets, the delivery counters sum to msg_count, clerk-ack fold counts are
exact against the acked-op totals, the pool carries histograms through
rows/summary at any device count and layout, and the stats verb renders
any report stream."""

import contextlib
import io
import json

import jax
import numpy as np
import pytest

from madraft_tpu.__main__ import main
from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim import metrics as M
from madraft_tpu.tpusim.config import (
    HIST_BUCKETS,
    METRIC_EVENTS,
    storm_profiles,
)
from madraft_tpu.tpusim.engine import fuzz, replay_cluster, run_pool
from madraft_tpu.tpusim.trace import replay_cluster_traced

STORM = SimConfig(
    n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
    max_dead=2, p_repartition=0.02, p_heal=0.05,
)
MSTORM = STORM.replace(metrics=True)
DURABILITY = storm_profiles()["durability"][0]


def run_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, buf.getvalue()


def test_metrics_off_trajectories_bit_identical():
    # the plane adds NO PRNG draws: a metrics run IS the metrics-off run
    # plus observation — violations, commits, and deliveries must agree
    r_off = fuzz(STORM, 7, 8, 128)
    r_on = fuzz(MSTORM, 7, 8, 128)
    for f in ("violations", "first_violation_tick", "committed",
              "msg_count"):
        assert np.array_equal(getattr(r_off, f), getattr(r_on, f)), f
    assert r_off.lat_hist is None and r_on.lat_hist is not None
    assert r_on.lat_hist.shape == (8, HIST_BUCKETS)


def test_traced_replay_cross_check_histogram():
    # THE cross-check satellite: recompute every latency on the host from
    # the flight recorder's per-tick submit-stamp stream (t - stamp over
    # nonzero lanes) and bucket it with an INDEPENDENT implementation
    # (np.searchsorted) — the totals must land in exactly the buckets the
    # on-device fold reported for the same (seed, cluster id)
    final, rec = replay_cluster_traced(MSTORM, 7, 3, 300)
    untraced = replay_cluster(MSTORM, 7, 3, 300)
    assert np.array_equal(np.asarray(final.lat_hist),
                          np.asarray(untraced.lat_hist))
    assert np.array_equal(np.asarray(final.ev_counts),
                          np.asarray(untraced.ev_counts))
    host = np.zeros(HIST_BUCKETS, np.int64)
    T = rec.shadow_sub.shape[0]
    for ti in range(T):
        subs = rec.shadow_sub[ti]
        lats = (ti + 1) - subs[subs > 0]
        assert (lats >= 0).all()
        for b in M.host_bucket(lats):
            host[b] += 1
    assert host.sum() > 0, "storm committed no injected commands"
    np.testing.assert_array_equal(host, np.asarray(final.lat_hist))
    # the cumulative trace row agrees with the final state too
    np.testing.assert_array_equal(np.asarray(rec.lat_hist[-1]),
                                  np.asarray(final.lat_hist))


def test_delivery_counters_sum_to_msg_count():
    st = replay_cluster(MSTORM, 7, 3, 300)
    ev = np.asarray(st.ev_counts)
    names = list(METRIC_EVENTS)
    deliv = sum(ev[names.index(k)] for k in
                ("rv_req_delivered", "rv_rsp_delivered", "ae_req_delivered",
                 "ae_rsp_delivered", "snap_delivered"))
    assert deliv == int(st.msg_count)
    assert ev[names.index("elections_won")] >= 1
    assert ev[names.index("commit_advances")] >= 1
    # crashes/restarts come from the same Bernoulli stream the step always
    # drew; the storm profile crashes, so the counters must see it
    assert ev[names.index("crashes")] >= 1
    # every latency the histogram folded is a committed injected command —
    # bounded by the committed-entry total
    assert 0 < np.asarray(st.lat_hist).sum() <= int(st.shadow_len)


def test_kv_clerk_ack_fold_is_exact():
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz

    cfg = MSTORM.replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16,
        p_crash=0.0, max_dead=0,
    )
    rep = kv_fuzz(cfg, KvConfig(p_get=0.3, p_put=0.2), 5, 8, 200)
    # clerks serialize seqs, so acked_ops IS the number of ack events, and
    # every ack folded exactly one latency
    assert rep.lat_hist.sum() == rep.acked_ops.sum() > 0
    assert rep.ev_counts.shape == (8, len(METRIC_EVENTS))
    # service entries carry stamp 0: the raft-layer commit fold must not
    # double-count clerk ops (each op folds once, at its clerk ack)
    per_cluster = rep.lat_hist.sum(axis=1)
    np.testing.assert_array_equal(per_cluster, rep.acked_ops)


def test_shardkv_clerk_ack_fold_is_exact():
    from madraft_tpu.tpusim.shardkv import ShardKvConfig, shardkv_fuzz

    cfg = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05, metrics=True,
    )
    rep = shardkv_fuzz(cfg, ShardKvConfig(), 3, 2, 320)
    assert rep.lat_hist is not None
    np.testing.assert_array_equal(rep.lat_hist.sum(axis=1), rep.acked_ops)
    assert rep.acked_ops.sum() > 0
    assert rep.ev_counts.shape[-1] == len(METRIC_EVENTS)


def _pool_rows_and_summary(devices=None, pack_states=None, seed=3):
    cfg = DURABILITY.replace(bug="ack_before_fsync", metrics=True)
    rows = []
    s = run_pool(cfg, seed, 16, 100, chunk_ticks=50, budget_ticks=300,
                 devices=devices, on_retired=rows.append,
                 pack_states=pack_states)
    return rows, s


def test_pool_metrics_rows_and_summary():
    rows, s = _pool_rows_and_summary()
    lat = s["latency"]
    assert lat["ops"] > 0 and sum(lat["hist"]) == lat["ops"]
    assert s["events"]["commit_advances"] > 0
    assert all("latency_hist" in r and "events" in r for r in rows)
    # summary latency == retired rows + the last harvest's in-flight lanes;
    # at this budget every lane retires at the horizon, so the row rows
    # alone must not exceed the merged total
    row_sum = np.sum([r["latency_hist"] for r in rows], axis=0)
    assert (row_sum <= np.asarray(lat["hist"])).all()


def test_pool_metrics_bit_identical_across_layouts():
    rows_w, s_w = _pool_rows_and_summary(pack_states=False)
    rows_p, s_p = _pool_rows_and_summary(pack_states=True)
    assert s_w["state_layout"] == "wide" and s_p["state_layout"] == "packed"
    assert s_w["latency"] == s_p["latency"]
    assert s_w["events"] == s_p["events"]
    assert [r["latency_hist"] for r in rows_w] == \
        [r["latency_hist"] for r in rows_p]


def test_pool_metrics_device_count_invariant():
    # the ISSUE-10 extension of the PR-7 invariance contract: the SUMMED
    # histograms (and counters) of a fixed budget agree at any device count
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    rows1, s1 = _pool_rows_and_summary(devices=1)
    rows2, s2 = _pool_rows_and_summary(devices=2)
    assert s1["latency"] == s2["latency"]
    assert s1["events"] == s2["events"]
    key = lambda rows: sorted(  # noqa: E731
        (r["cluster_id"], tuple(r["latency_hist"])) for r in rows
    )
    assert key(rows1) == key(rows2)


def test_quantile_decode_and_bucket_layout():
    # layout: bucket 0 = [0,1], k >= 1 = [2^k, 2^(k+1)-1], last open-ended
    assert M.bucket_bounds(0) == (0, 1)
    assert M.bucket_bounds(3) == (8, 15)
    assert M.bucket_bounds(HIST_BUCKETS - 1)[1] is None
    assert list(M.host_bucket(np.asarray([0, 1, 2, 3, 4, 1 << 20]))) == \
        [0, 0, 1, 1, 2, HIST_BUCKETS - 1]
    # device fold == host buckets on a latency sweep
    lats = np.arange(0, 500, dtype=np.int32)
    dev = np.asarray(M.fold_latencies(
        np.zeros(HIST_BUCKETS, np.int32), lats, np.ones_like(lats, bool)
    ))
    host = np.bincount(M.host_bucket(lats), minlength=HIST_BUCKETS)
    np.testing.assert_array_equal(dev, host)
    # quantile = upper edge of the quantile's bucket
    h = np.zeros(HIST_BUCKETS, np.int64)
    h[2] = 99
    h[5] = 1
    assert M.quantile_from_hist(h, 0.5) == 7
    assert M.quantile_from_hist(h, 0.99) == 7
    assert M.quantile_from_hist(h, 0.999) == 63
    assert M.quantile_from_hist(np.zeros(HIST_BUCKETS), 0.5) is None
    # merging is plain addition of hist rows — the property every surface
    # (pool summary, stats verb, cross-file sums) relies on
    a = M.latency_summary(h)
    merged = M.latency_summary(np.asarray(a["hist"]) + np.asarray(a["hist"]))
    assert merged["ops"] == 2 * a["ops"]
    assert merged["p99_ticks"] == a["p99_ticks"]


def test_stats_summary_wins_rule_is_per_stream():
    # a full pool stream (rows + summary) next to a rows-only grep from a
    # DIFFERENT run: the summary suppresses only ITS OWN stream's rows —
    # the rows-only file must still merge in full
    from madraft_tpu.__main__ import _collect_stats

    hist_a = [0] * HIST_BUCKETS
    hist_a[2] = 5
    pool_stream = [
        json.dumps({"cluster_id": 0, "latency_hist": hist_a,
                    "events": {"crashes": 1}}),
        json.dumps({"lanes": 1, "latency": {"ops": 5, "hist": hist_a},
                    "events": {"crashes": 1}}),
    ]
    hist_b = [0] * HIST_BUCKETS
    hist_b[4] = 3
    rows_only = [
        json.dumps({"cluster_id": 9, "latency_hist": hist_b,
                    "events": {"crashes": 2}}),
    ]
    m = _collect_stats([pool_stream, rows_only])
    assert m.seen == 2  # the pool summary + the foreign row, not the pool row
    assert m.seen_per_stream == [1, 1]
    assert m.hist[2] == 5 and m.hist[4] == 3
    assert m.events[list(METRIC_EVENTS).index("crashes")] == 3
    # an events-ONLY report (the ctrler layer: counters without latency
    # stamps) must merge too, not read as "no metrics found"
    ev_only = [json.dumps({"violating": 0, "events": {"crashes": 4}})]
    m = _collect_stats([ev_only])
    assert m.seen == 1 and m.hist.sum() == 0
    assert m.events[list(METRIC_EVENTS).index("crashes")] == 4


def test_explain_chrome_gains_liveness_counters(tmp_path):
    from madraft_tpu.tpusim.trace import chrome_trace, decode_events

    # 300 ticks on purpose: shares the traced program (scan length is a
    # static cache key) with the cross-check test above
    final, rec = replay_cluster_traced(MSTORM, 7, 3, 300)
    events = decode_events(rec)
    advances = [e for e in events if e["event"] == "commit_advance"]
    assert advances and all("latencies" in e for e in advances)
    total = sum(len(e["latencies"]) for e in advances)
    assert total == int(np.asarray(final.lat_hist).sum())
    doc = chrome_trace(rec, MSTORM.ms_per_tick, events)
    counters = {e["name"] for e in doc["traceEvents"] if e["ph"] == "C"}
    assert {"liveness", "commit_latency_ticks", "deliveries"} <= counters
    live = [e for e in doc["traceEvents"]
            if e["ph"] == "C" and e["name"] == "liveness"]
    assert sum(e["args"]["commit_advances"] for e in live) == int(
        np.asarray(final.ev_counts)[list(METRIC_EVENTS).index(
            "commit_advances")]
    )


def test_service_cli_metrics_plumbing():
    # shardkv-fuzz builds its SimConfig from scratch — the --metrics flag
    # must be carried explicitly (a dropped flag silently reports nothing);
    # ctrler-fuzz now surfaces a REAL latency dict alongside the events
    # (the ISSUE 11 clerk_sub satellite closed PR 10's events-only gap)
    rc, out = run_cli(["shardkv-fuzz", "--clusters", "2", "--ticks", "160",
                       "--metrics", "--nodes", "3"])
    d = json.loads(out.strip().splitlines()[-1])
    assert "latency" in d and d["latency"]["ops"] > 0, d.keys()
    assert "events" in d
    rc, out = run_cli(["ctrler-fuzz", "--clusters", "8", "--ticks", "128",
                       "--metrics"])
    d = json.loads(out.strip().splitlines()[-1])
    assert "latency" in d and d["latency"]["ops"] > 0, d.keys()
    assert "events" in d
    assert d["events"]["elections_won"] > 0


def test_fuzz_cli_report_and_stats_verb(tmp_path):
    rc, out = run_cli([
        "fuzz", "--clusters", "8", "--ticks", "128", "--storm", "--metrics",
        "--seed", "7",
    ])
    rep = json.loads(out.strip().splitlines()[-1])
    assert "latency" in rep and rep["latency"]["ops"] > 0
    assert set(rep["events"]) == set(METRIC_EVENTS)
    p = tmp_path / "rep.json"
    p.write_text(out)
    rc, rendered = run_cli(["stats", str(p)])
    assert rc == 0
    assert f"ops={rep['latency']['ops']}" in rendered
    # a metrics-off report carries nothing to render: exit 2, say so
    rc, out_off = run_cli([
        "fuzz", "--clusters", "8", "--ticks", "64", "--seed", "7",
    ])
    assert "latency" not in json.loads(out_off.strip().splitlines()[-1])
    p2 = tmp_path / "off.json"
    p2.write_text(out_off)
    assert run_cli(["stats", str(p2)])[0] == 2


# ---------------------------------------------------------------------------
# Tail-latency attribution plane (ISSUE 12)
# ---------------------------------------------------------------------------

def _phase_mass_invariants(lat_hist, phase_hist, phase_ticks, lat_ticks,
                           acked):
    """The pinned invariant family: every phase row folds one sample per
    acked op (zeros land in bucket 0), and the EXACT per-phase tick totals
    sum to the exact e2e latency total — per-op exactness aggregated."""
    assert lat_hist.sum() == acked
    for p in range(phase_hist.shape[-2]):
        assert phase_hist[..., p, :].sum() == acked, p
    assert phase_ticks.sum() == lat_ticks.sum()


def test_phase_sum_invariant_raft():
    # raft-injected commands: born at a leader, acked at commit — the whole
    # latency is the replicate leg, and every other row must be pure zeros
    from madraft_tpu.tpusim.config import LATENCY_PHASES

    st = replay_cluster(MSTORM, 7, 3, 300)
    acked = int(np.asarray(st.lat_hist).sum())
    assert acked > 0
    _phase_mass_invariants(
        np.asarray(st.lat_hist), np.asarray(st.phase_hist)[None],
        np.asarray(st.phase_ticks), np.asarray(st.lat_ticks), acked,
    )
    rep_i = LATENCY_PHASES.index("replicate")
    np.testing.assert_array_equal(np.asarray(st.phase_hist)[rep_i],
                                  np.asarray(st.lat_hist))
    for i, name in enumerate(LATENCY_PHASES):
        if i != rep_i:
            assert int(np.asarray(st.phase_ticks)[i]) == 0, name
    # the worst-op register: its phase vector sums to its latency exactly,
    # its latency is the histogram's max occupied bucket's range, and raft
    # ops carry no key/client
    assert int(np.asarray(st.worst_phases).sum()) == \
        int(np.asarray(st.worst_lat)[0]) > 0
    assert int(np.asarray(st.worst_key)[0]) == -1
    assert int(np.asarray(st.worst_client)[0]) == -1
    assert int(np.asarray(st.worst_sub)[0]) >= 1


def test_phase_sum_invariant_kv():
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz

    cfg = MSTORM.replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16,
    )
    rep = kv_fuzz(cfg, KvConfig(p_get=0.3, p_put=0.2), 5, 8, 200)
    acked = int(rep.acked_ops.sum())
    assert acked > 0
    _phase_mass_invariants(rep.lat_hist, rep.phase_hist, rep.phase_ticks,
                           rep.lat_ticks, acked)
    # per-cluster too (the aggregate could hide a compensating error)
    for c in range(8):
        assert rep.phase_ticks[c].sum() == rep.lat_ticks[c, 0], c
        assert rep.worst_phases[c].sum() == rep.worst_lat[c, 0], c
    # attribution axes carry the same total mass, sliced by key/client
    assert rep.key_hist.sum() == acked
    assert rep.client_hist.sum() == acked
    # per-client hist mass == that client's acked ops (clerks serialize
    # seqs, so clerk_acked IS the ack count per client)
    # and every worst op names a real key/client
    bad = rep.worst_sub[:, 0] > 0
    assert bad.any()
    assert (rep.worst_key[bad, 0] >= 0).all()
    assert (rep.worst_client[bad, 0] >= 0).all()


def test_phase_sum_invariant_shardkv():
    from madraft_tpu.tpusim.config import SHARDKV_PHASES
    from madraft_tpu.tpusim.shardkv import ShardKvConfig, shardkv_fuzz

    cfg = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05, metrics=True,
    )
    rep = shardkv_fuzz(cfg, ShardKvConfig(), 3, 2, 320)
    acked = int(rep.acked_ops.sum())
    assert acked > 0
    assert rep.phase_hist.shape[-2] == len(SHARDKV_PHASES)
    _phase_mass_invariants(rep.lat_hist, rep.phase_hist, rep.phase_ticks,
                           rep.lat_ticks, acked)
    for c in range(rep.phase_ticks.shape[0]):
        assert rep.phase_ticks[c].sum() == rep.lat_ticks[c, 0], c
        assert rep.worst_phases[c].sum() == rep.worst_lat[c, 0], c
    assert rep.key_hist.sum() == acked
    assert rep.client_hist.sum() == acked


def test_metrics_on_trajectories_still_bit_identical():
    # the attribution plane adds NO PRNG draws either: metrics-on stays
    # bit-identical to metrics-off on the service layers too
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz

    base = STORM.replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16,
    )
    r_off = kv_fuzz(base, KvConfig(p_get=0.3), 5, 4, 150)
    r_on = kv_fuzz(base.replace(metrics=True), KvConfig(p_get=0.3), 5, 4, 150)
    for f in ("violations", "first_violation_tick", "acked_ops",
              "committed", "msg_count"):
        assert np.array_equal(getattr(r_off, f), getattr(r_on, f)), f


def test_pool_summary_phases_and_worst_op():
    rows, s = _pool_rows_and_summary()
    lat = s["latency"]
    phases = lat["phases"]
    assert set(phases) == {"leader_wait", "replicate", "apply", "ack"}
    # mass + exact-tick-sum invariants survive the pool merge
    assert all(sum(d["hist"]) == lat["ops"] for d in phases.values())
    assert sum(d["ticks_total"] for d in phases.values()) == \
        lat["ticks_total"]
    w = s["worst_op"]
    assert w is not None and "cluster_id" in w
    assert sum(w["phases"].values()) == w["latency_ticks"]
    # rows carry the attribution columns; a row's worst op (when present)
    # sums exactly too
    assert all("latency_phases" in r and "worst_op" in r for r in rows)
    for r in rows:
        if r["worst_op"] is not None:
            assert sum(r["worst_op"]["phases"].values()) == \
                r["worst_op"]["latency_ticks"]
            assert sum(r["latency_phases"]["replicate"]) == \
                sum(r["latency_hist"])


def test_pool_attribution_device_count_invariant():
    # the ISSUE-12 extension of the invariance contract: the merged phase
    # rows AND the deterministic worst-op pick agree at any device count
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    rows1, s1 = _pool_rows_and_summary(devices=1)
    rows2, s2 = _pool_rows_and_summary(devices=2)
    assert s1["latency"]["phases"] == s2["latency"]["phases"]
    assert s1["latency"]["ticks_total"] == s2["latency"]["ticks_total"]
    assert s1["worst_op"] == s2["worst_op"]
    key = lambda rows: sorted(  # noqa: E731
        (r["cluster_id"], json.dumps(r["latency_phases"], sort_keys=True),
         json.dumps(r["worst_op"], sort_keys=True))
        for r in rows
    )
    assert key(rows1) == key(rows2)


def test_pool_attribution_bit_identical_across_layouts():
    rows_w, s_w = _pool_rows_and_summary(pack_states=False)
    rows_p, s_p = _pool_rows_and_summary(pack_states=True)
    assert s_w["latency"]["phases"] == s_p["latency"]["phases"]
    assert s_w["worst_op"] == s_p["worst_op"]
    assert [r["latency_phases"] for r in rows_w] == \
        [r["latency_phases"] for r in rows_p]
    assert [r["worst_op"] for r in rows_w] == \
        [r["worst_op"] for r in rows_p]


def test_hist_merge_associative_and_order_invariant():
    # THE property the pool sum-merge, the sharded harvest, and the stats
    # cross-file merge all rely on (previously untested): merging is plain
    # addition over fixed buckets, so it is associative and invariant
    # under any shard/file order — and the decoded quantiles depend only
    # on the merged histogram. Seeded random trials, no hypothesis dep.
    rng = np.random.default_rng(42)
    for trial in range(32):
        parts = [rng.integers(0, 1000, HIST_BUCKETS).astype(np.int64)
                 for _ in range(5)]
        left = parts[0].copy()
        for h in parts[1:]:
            left = left + h          # ((a+b)+c)+...
        right = parts[-1].copy()
        for h in parts[-2::-1]:
            right = h + right        # a+(b+(c+...))
        np.testing.assert_array_equal(left, right)
        perm = rng.permutation(5)
        shuffled = np.sum([parts[i] for i in perm], axis=0)
        np.testing.assert_array_equal(left, shuffled)
        a, b = M.latency_summary(left), M.latency_summary(shuffled)
        assert a == b, trial
        # merge commutes with the quantile decode at every split point:
        # decoding shards separately can disagree with the merged decode
        # (quantiles are not additive) but the merged hist is canonical
        assert M.quantile_from_hist(left, 0.99) == \
            M.quantile_from_hist(shuffled, 0.99)
    # the worst-op merge is associative + order-invariant too (max with a
    # deterministic tie-break)
    ops = [
        {"latency_ticks": t, "cluster_id": c,
         "submit_tick": 1, "key": -1, "client": -1, "phases": {}}
        for t, c in [(5, 3), (9, 1), (9, 2), (2, 0)]
    ]
    def fold(seq):
        w = None
        for o in seq:
            w = M.merge_worst(w, o)
        return w
    want = fold(ops)
    assert want["latency_ticks"] == 9 and want["cluster_id"] == 1
    for perm in ([3, 2, 1, 0], [1, 0, 3, 2], [2, 1, 0, 3]):
        assert fold([ops[i] for i in perm]) == want


def test_stats_phases_axes_and_exit2_naming(tmp_path):
    # end to end through the CLI: a kv --metrics report renders the phase
    # table and the --by-key/--by-client top-N; a metrics-free input is
    # NAMED at exit 2; a mixed input renders and warns with the names
    rc, out = run_cli([
        "kv-fuzz", "--clusters", "8", "--ticks", "128", "--storm",
        "--metrics", "--seed", "7",
    ])
    rep = json.loads(out.strip().splitlines()[-1])
    lat = rep["latency"]
    assert set(lat["phases"]) == {"leader_wait", "replicate", "apply",
                                  "ack"}
    assert sum(d["ticks_total"] for d in lat["phases"].values()) == \
        lat["ticks_total"]
    assert lat["by_key"] and lat["by_client"]
    assert sum(d["ops"] for d in lat["by_key"].values()) == lat["ops"]
    assert rep["worst_op"]["key"] >= 0
    p = tmp_path / "kv.json"
    p.write_text(out)
    rc, rendered = run_cli(["stats", str(p), "--by-key", "--by-client",
                            "--top", "2"])
    assert rc == 0
    assert "phases (sum of phase durations == e2e latency" in rendered
    assert "top keys by p99:" in rendered
    assert "top clients by p99:" in rendered
    assert "worst op:" in rendered
    # exit 2 must NAME the metrics-free input
    off = tmp_path / "off.json"
    off.write_text(json.dumps({"violating": 0}) + "\n")
    buf = io.StringIO()
    with contextlib.redirect_stderr(buf):
        rc2, _ = run_cli(["stats", str(off)])
    assert rc2 == 2 and str(off) in buf.getvalue()
    # mixed metrics / metrics-free input: exit 0, warn with the name
    buf = io.StringIO()
    with contextlib.redirect_stderr(buf):
        rc3, rendered3 = run_cli(["stats", str(p), str(off)])
    assert rc3 == 0
    assert str(off) in buf.getvalue() and "warning" in buf.getvalue()
    assert f"ops={lat['ops']}" in rendered3


def test_explain_chrome_phase_tracks_and_worst_span():
    from madraft_tpu.tpusim.trace import chrome_trace

    final, rec = replay_cluster_traced(MSTORM, 7, 3, 300)
    doc = chrome_trace(rec, MSTORM.ms_per_tick)
    tracks = [e for e in doc["traceEvents"]
              if e["ph"] == "C" and e["name"] == "latency_phases"]
    assert tracks, "per-phase counter track missing"
    # the per-tick deltas of each phase track sum to the exact totals
    pt = np.asarray(final.phase_ticks)
    for i, name in enumerate(("leader_wait", "replicate", "apply", "ack")):
        assert sum(e["args"][name] for e in tracks) == int(pt[i]), name
    spans = [e for e in doc["traceEvents"]
             if e["ph"] == "X" and e["name"].startswith("worst op")]
    assert len(spans) == 1
    w = spans[0]["args"]
    assert w["latency_ticks"] == int(np.asarray(final.worst_lat)[0])
    assert sum(w["phases"].values()) == w["latency_ticks"]
    assert w["submit_tick"] == int(np.asarray(final.worst_sub)[0])
