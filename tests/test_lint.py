"""Static analysis suite (ISSUE 15): the clean registry lints green, each
planted defect is caught by exactly its pass class, the report schema is
stable, and the `lint` CLI verb keeps the PR-6 exit-code convention
(0 = clean, 1 = findings, 2 = usage error). Everything here is trace-only:
no registry program is ever executed."""

import contextlib
import io
import json
import pathlib

import jax
import jax.numpy as jnp
import pytest

from madraft_tpu.__main__ import main
from madraft_tpu.tpusim.lint import (
    LANE_ISOLATION,
    PACKED_WIDTH,
    PRNG_DISCIPLINE,
    RULE_PASS,
    ZERO_WHEN_OFF,
    ProgramSpec,
    defect_registry,
    golden_guard_legs,
    registry,
    run_lint,
)

ROOT = pathlib.Path(__file__).resolve().parent


@pytest.fixture(scope="module")
def clean_report():
    # One full-registry lint for the whole module (each entry traces its
    # jaxpr once; ~15 s for all programs — cheap next to a compile, and
    # nothing executes).
    return run_lint(registry())


def run_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        try:
            rc = main(argv)
        except SystemExit as e:
            rc = e.code
    return rc, buf.getvalue()


# ------------------------------------------------------------ clean tree
def test_registry_lints_green(clean_report):
    assert clean_report["findings"] == [], (
        "the production registry must lint clean: "
        + "; ".join(f"{f['program']}: {f['rule']} {f['detail']}"
                    for f in clean_report["findings"])
    )
    s = clean_report["summary"]
    # conftest forces a 2-virtual-device CPU mesh, so even the sharded
    # entries trace — nothing may be skipped in CI
    assert s["skipped"] == 0 and s["traced"] == s["programs"]
    assert set(s["per_pass"]) == {LANE_ISOLATION, PRNG_DISCIPLINE,
                                  PACKED_WIDTH, ZERO_WHEN_OFF}


def test_registry_enumerates_every_program_family(clean_report):
    names = [p["name"] for p in clean_report["programs"]]
    assert len(names) == len(set(names)), "duplicate program names"
    families = {p["family"] for p in clean_report["programs"]}
    # the tentpole's coverage list: fuzz / sweep / pool chunk + harvest +
    # init / coverage / kv / ctrler / shardkv / trace / replay
    assert {"fuzz", "sweep", "pool", "coverage", "replay", "trace",
            "kv", "ctrler", "shardkv"} <= families
    # packed AND wide variants, single-device AND sharded
    assert any(n.endswith(".packed") for n in names)
    assert any(n.endswith(".wide") for n in names)
    assert any("sharded" in n for n in names)
    assert len(names) >= 30


def test_registry_count_pinned_exactly(clean_report):
    # the ISSUE-17 no-new-compiled-programs gate: the telemetry plane is
    # host-side only, so the cached-program count is pinned EXACTLY — any
    # drift (either direction) is a deliberate registry change that must
    # update lint.REGISTRY_PROGRAMS in the same commit
    from madraft_tpu.tpusim.lint import REGISTRY_PROGRAMS

    assert REGISTRY_PROGRAMS == 31
    assert len(clean_report["programs"]) == REGISTRY_PROGRAMS


def test_declared_exceptions_are_counted_not_flagged(clean_report):
    # harvest's cross-lane reductions and the coverage bitmap scatter are
    # DECLARED hits: they must show up in the allowed counts (proof the
    # lane pass actually sees them) and never as findings
    rows = {p["name"]: p["allowed"] for p in clean_report["programs"]}
    assert rows["pool.harvest.packed"].get("lane_reduce", 0) >= 1
    assert rows["pool.harvest.packed"].get("lane_cumsum", 0) >= 1
    assert rows["cov.chunk.packed"].get("lane_scatter", 0) >= 1


def test_metrics_and_coverage_add_zero_draws(clean_report):
    # the static form of the golden guard's "metrics/coverage change no
    # draw layout": same draw-site count across each program group
    draws = {p["name"]: p["draws"] for p in clean_report["programs"]}
    assert draws["fuzz.wide"] == draws["fuzz.metrics"]
    assert (draws["pool.chunk.packed"] == draws["pool.chunk.metrics"]
            == draws["cov.chunk.packed"])


# -------------------------------------------------------- planted defects
def test_each_pass_catches_its_planted_defect():
    report = run_lint(defect_registry())
    by_prog = {}
    for f in report["findings"]:
        by_prog.setdefault(f["program"], set()).add((f["pass"], f["rule"]))
    expect = {
        "defect.cross_lane_roll": LANE_ISOLATION,
        "defect.key_reuse": PRNG_DISCIPLINE,
        "defect.metrics_leak": ZERO_WHEN_OFF,
        "defect.wide_carry": PACKED_WIDTH,
    }
    for prog, want_pass in expect.items():
        passes = {p for p, _ in by_prog.get(prog, set())}
        assert want_pass in passes, (
            f"{prog}: pass {want_pass!r} missed its defect "
            f"(findings: {by_prog.get(prog)})"
        )
    assert ("prng_discipline", "key_reuse") in by_prog["defect.key_reuse"]
    assert ("zero_when_off", "metrics_leak") in by_prog["defect.metrics_leak"]
    assert ("packed_width", "wide_carry") in by_prog["defect.wide_carry"]


def test_draw_parity_flags_a_diverging_group():
    # a pair in the same draw group where one member draws twice: the
    # extra-draw member (and only it) must get a draw_parity finding
    def one_draw():
        def run(seed):
            return jax.random.uniform(jax.random.PRNGKey(seed), (4,))
        return jax.jit(run), (jax.ShapeDtypeStruct((), jnp.int32),)

    def two_draws():
        def run(seed):
            k = jax.random.PRNGKey(seed)
            k1, k2 = jax.random.split(k)
            return (jax.random.uniform(k1, (4,))
                    + jax.random.uniform(k2, (4,)))
        return jax.jit(run), (jax.ShapeDtypeStruct((), jnp.int32),)

    specs = [
        ProgramSpec("parity.base", "parity", one_draw, draw_group="g"),
        ProgramSpec("parity.extra", "parity", two_draws, draw_group="g"),
    ]
    report = run_lint(specs)
    flagged = {f["program"] for f in report["findings"]
               if f["rule"] == "draw_parity"}
    assert flagged == {"parity.extra"}


# ---------------------------------------------------------- report schema
def test_report_schema(clean_report):
    # the MIGRATION.md-documented shape: CI consumers key on these
    assert clean_report["schema"] == 1
    assert set(clean_report) == {"schema", "programs", "findings", "summary"}
    for row in clean_report["programs"]:
        assert {"name", "family", "lanes", "eqns", "draws", "skipped",
                "allowed"} <= set(row)
        assert row["eqns"] > 0
    s = clean_report["summary"]
    assert {"programs", "traced", "skipped", "findings", "per_pass"} == set(s)
    report = run_lint(defect_registry())
    for f in report["findings"]:
        assert set(f) == {"program", "pass", "rule", "detail"}
        assert RULE_PASS[f["rule"]] == f["pass"]


def test_golden_guard_legs_cover_the_golden_file():
    legs = golden_guard_legs()
    golden = json.loads((ROOT / "golden_fuzz.json").read_text())
    golden.pop("_comment", None)
    assert set(legs) == set(golden), (
        "registry golden legs must match golden_fuzz.json exactly"
    )
    names = {s.name for s in registry()}
    for leg, progs in legs.items():
        assert progs and set(progs) <= names, (leg, progs)


# ------------------------------------------------------------- CLI verb
def test_cli_clean_program_exits_zero():
    rc, out = run_cli(["lint", "--program", "fuzz.wide"])
    assert rc == 0
    assert "fuzz.wide" in out and "0 findings" in out


def test_cli_selftest_exits_one_and_reports_json(tmp_path):
    out_file = tmp_path / "lint_report.json"
    rc, out = run_cli(["lint", "--selftest", "--json", str(out_file)])
    assert rc == 1, "planted defects must produce finding-exit 1"
    assert "FINDING" in out
    report = json.loads(out_file.read_text())
    assert report["schema"] == 1 and report["findings"]


def test_cli_usage_errors_exit_two(tmp_path):
    # unknown --program: usage error, NOT a finding
    rc, _ = run_cli(["lint", "--program", "no_such_program"])
    assert rc == 2
    # unwritable --json report path: same convention
    rc, _ = run_cli(["lint", "--program", "fuzz.wide",
                     "--json", str(tmp_path / "missing_dir" / "r.json")])
    assert rc == 2


def test_cli_list_enumerates_without_tracing():
    rc, out = run_cli(["lint", "--list"])
    assert rc == 0
    lines = [ln for ln in out.strip().splitlines() if ln]
    assert len(lines) == len(registry())
    assert any("golden=pool" in ln for ln in lines)
