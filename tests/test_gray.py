"""The gray-failure game-day plane (ISSUE 19): limping nodes, per-node
election clock skew, fsync stalls, deterministic rolling restart waves,
and the open-loop / Zipf clerk workload.

Two invariants anchor the plane:

- **Zero cost when off.** Every gray knob is a runtime ``Knobs`` field
  whose draws ride FREE low bytes of words the step already consumes —
  the per-tick threefry budget (``step._block_total``) is pinned
  unchanged, and a run with gray magnitudes configured but probabilities
  at zero is bit-identical to the plain program, field for field.
- **Slow-but-alive, not broken.** Each gray axis degrades timing only:
  the correct algorithm must stay violation-free under every profile
  (the clean legs here and bench's per-profile gate table), while the
  widened windows make the PLANTED bugs easier to catch (the
  ``fsync_stall`` x ``ack_before_fsync`` catch row; PERF.md round 19
  records the limp x ``forget_voted_for`` A/B no fail-stop profile
  reaches).
"""

import contextlib
import io

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim import SimConfig, fuzz
from madraft_tpu.tpusim.config import (
    OPEN_QUEUE_SLOTS,
    profile_gates,
    storm_profiles,
    zipf_map,
)
from madraft_tpu.tpusim.engine import make_chunked_fuzz_fn
from madraft_tpu.tpusim.kv import KvConfig, kv_report, make_kv_fuzz_fn
from madraft_tpu.tpusim.state import init_cluster, packed_layout_reason
from madraft_tpu.tpusim.step import _block_total, step_cluster

_PROFILES = storm_profiles()
STORM = _PROFILES["storm"][0]

# the kv-layer substrate the open-loop tests run on (the fuzz-verb shape:
# raft client channel off, service clerks drive the log)
KV_RAFT = SimConfig(p_client_cmd=0.0, compact_at_commit=False)


def _trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(
        x.shape == y.shape and bool((np.asarray(x) == np.asarray(y)).all())
        for x, y in zip(la, lb)
    )


# ------------------------------------------------------------ zero cost off
def test_per_tick_draw_budget_unchanged():
    # The gray axes consume ZERO extra PRNG words: onset/multiplier/heal/
    # stall draws ride free low bytes of existing words, skew and rolling
    # waves are pure arithmetic. The budget formula is re-stated literally
    # so any new blk.bern/randint call shows up as a pin diff here AND a
    # draw-parity diff in test_lint.
    for n in (3, 5, 7):
        assert _block_total(n) == 11 * n + 3 + 3 * n * n


def test_inert_gray_magnitudes_bit_identical():
    # Magnitude knobs configured, probabilities/periods at zero: the
    # trajectory must be bit-identical to the plain storm, every field —
    # not merely report-equal. (eto_skew and rolling_period are the two
    # knobs that act without a probability, so THEY stay 0 here.)
    inert = STORM.replace(
        p_limp=0.0, limp_mult_max=9, p_limp_heal=0.7,
        p_fsync_stall=0.0, fsync_stall_ticks=31,
        rolling_period=0, rolling_down=0, eto_skew=0,
    )
    base = make_chunked_fuzz_fn(STORM, 32, 120)(7)
    gray = make_chunked_fuzz_fn(inert, 32, 120)(7)
    assert _trees_equal(base, gray), (
        "inert gray knobs perturbed the trajectory"
    )


def test_open_loop_cap_zero_is_closed_loop_bit_identical():
    # open_queue_cap=0 IS the closed loop: a nonzero offered rate must be
    # inert (the arrival gate is the cap, so the same words feed the same
    # p_op Bernoulli) — final service states compare bit-for-bit.
    kcfg = KvConfig(p_get=0.3, p_put=0.2)
    shut = kcfg.replace(open_rate=0.9, open_queue_cap=0, zipf_a=1.0)
    a = make_kv_fuzz_fn(KV_RAFT, kcfg, 16, 120)(3)
    b = make_kv_fuzz_fn(KV_RAFT, shut, 16, 120)(3)
    assert _trees_equal(a, b), "cap-0 open-loop knobs perturbed the clerks"


# ----------------------------------------------------------------- gray axes
def test_clock_skew_offsets_election_windows_at_init():
    # Same key, same base draw: node i's initial election timer under skew
    # differs from the unskewed init by EXACTLY i * eto_skew.
    key = jax.random.PRNGKey(11)
    skewed = STORM.replace(eto_skew=4)
    t0 = np.asarray(init_cluster(STORM, key, STORM.knobs()).timer)
    t1 = np.asarray(init_cluster(skewed, key, skewed.knobs()).timer)
    assert (t1 - t0 == 4 * np.arange(STORM.n_nodes)).all()


def test_limp_state_bounded_and_episodes_occur():
    cfg = _PROFILES["limp"][0]
    final = make_chunked_fuzz_fn(cfg, 32, 120)(0)
    limp = np.asarray(final.limp)
    assert limp.min() >= 1 and limp.max() <= cfg.limp_mult_max, (
        f"limp multiplier out of [1, {cfg.limp_mult_max}]: "
        f"[{limp.min()}, {limp.max()}]"
    )
    assert (limp > 1).any(), "no limp episode in 32x120 — axis inert?"
    assert np.asarray(final.violations).sum() == 0


def test_fsync_stall_clean_and_watermark_legal():
    # The widest ack_before_fsync window any profile offers must still be
    # provably safe for the CORRECT algorithm (handler persist-before-
    # reply is a blocking fsync, never stalled), with the stall counter
    # bounded and the watermark ordering intact at every tick.
    cfg = _PROFILES["fsync_stall"][0]
    key = jax.random.fold_in(jax.random.PRNGKey(2), 0)
    kn = cfg.knobs()

    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = step_cluster(cfg, carry, key, kn)
            return nxt, (nxt.fsync_stall, nxt.durable_len, nxt.log_len,
                         nxt.base)
        return jax.lax.scan(
            body, init_cluster(cfg, key, kn), None, length=400
        )[1]

    stall, dlen, llen, base = [np.asarray(x) for x in run(key)]
    assert stall.min() >= 0 and stall.max() <= cfg.fsync_stall_ticks
    assert stall.max() > 0, "no stall episode in 400 ticks — axis inert?"
    assert (dlen <= llen).all() and (base <= dlen).all()
    rep = fuzz(cfg, seed=0, n_clusters=64, n_ticks=300)
    assert rep.n_violating == 0, "correct algorithm unsafe under stalls"


def test_fsync_stall_widens_the_planted_bug_window():
    # The catch row: the stall profile must surface ack_before_fsync at a
    # budget where it demonstrably fires (bench A/B: it catches MORE lanes
    # than the plain durability storm and a log-matching fingerprint the
    # fail-stop profiles never reach — PERF.md round 19).
    cfg = _PROFILES["fsync_stall"][0].replace(bug="ack_before_fsync")
    rep = fuzz(cfg, seed=12345, n_clusters=64, n_ticks=300)
    assert rep.n_violating >= 1, "stall profile missed the planted bug"


def test_rolling_wave_schedule_is_deterministic():
    # No Bernoulli faults at all: wave w takes node (w mod n) down for
    # exactly the first rolling_down ticks of [w*P, (w+1)*P). The alive
    # trajectory must match the schedule EXACTLY, tick for tick, never
    # lose two nodes at once, and be identical across seeds (the schedule
    # consumes no randomness).
    P, D = 16, 5
    cfg = _PROFILES["rolling_wave"][0].replace(
        rolling_period=P, rolling_down=D, loss_prob=0.0,
    )
    n = cfg.n_nodes
    kn = cfg.knobs()

    def alive_track(seed):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), 0)

        @jax.jit
        def run(key):
            def body(carry, _):
                nxt = step_cluster(cfg, carry, key, kn)
                return nxt, (nxt.tick, nxt.alive, nxt.commit)
            return jax.lax.scan(
                body, init_cluster(cfg, key, kn), None, length=4 * P
            )[1]

        return [np.asarray(x) for x in run(key)]

    tick, alive, commit = alive_track(0)
    me = np.arange(n)
    for tt, row in zip(tick, alive):
        wave_i = tt // P - ((tt // P - me) % n)
        down = (wave_i >= 0) & (tt - wave_i * P < D)
        assert (row == ~down).all(), f"tick {tt}: alive {row} != {~down}"
        assert (~row).sum() <= 1
    _, alive2, _ = alive_track(1)
    assert (alive == alive2).all(), "rolling schedule drank randomness"
    assert commit[-1].max() > commit[0].max(), "no commit progress via waves"


# -------------------------------------------------------- open-loop workload
def test_open_loop_queue_accounting():
    # Saturating offered load: pending = arrivals - served stays within
    # the cap at all times (checked at the horizon), overflow arrivals are
    # counted as drops, and the clerks actually serve (the queue is a
    # queue, not a bit bucket).
    kcfg = KvConfig(p_get=0.3, p_put=0.2, open_rate=0.6, open_queue_cap=4)
    final = make_kv_fuzz_fn(KV_RAFT, kcfg, 16, 200)(1)
    arr = np.asarray(final.open_arr)
    srv = np.asarray(final.open_srv)
    drop = np.asarray(final.open_drop)
    assert (arr >= srv).all() and (arr - srv <= 4).all()
    assert srv.sum() > 0, "open-loop clerks never served an arrival"
    assert drop.sum() > 0, "rate 0.6 at cap 4 never overflowed in 200 ticks"
    assert kv_report(final).acked_ops.sum() > 0


def test_open_loop_arrivals_feed_latency_plane():
    # Arrival stamps (not dequeue ticks) are the submit times: the PR-10
    # histogram must accumulate mass under open-loop traffic, so queue
    # wait is measured, not hidden.
    kcfg = KvConfig(p_get=0.3, p_put=0.2, open_rate=0.4, open_queue_cap=8)
    final = make_kv_fuzz_fn(KV_RAFT.replace(metrics=True), kcfg, 16, 200)(1)
    rep = kv_report(final)
    assert rep.lat_hist is not None and rep.lat_hist.sum() > 0
    assert rep.violations.sum() == 0


def test_zipf_map_identity_and_skew():
    draws = jnp.arange(256, dtype=jnp.int32) % 64
    ident = zipf_map(draws, 64, jnp.float32(1.0))
    assert (np.asarray(ident) == np.asarray(draws)).all(), (
        "zipf_a=1.0 must be the exact identity"
    )
    hot = np.asarray(zipf_map(draws, 64, jnp.float32(3.0)))
    assert hot.min() >= 0 and hot.max() <= 63
    assert hot.mean() < np.asarray(draws).mean() / 2, "a=3 barely skewed"
    assert (hot == 0).mean() > (np.asarray(draws) == 0).mean(), (
        "no hot-key concentration at key 0"
    )


# ------------------------------------------------------- registry and gates
def test_every_profile_has_a_gate_and_packs_exact():
    profs = storm_profiles()
    gates = profile_gates()
    assert set(gates) == set(profs), "gate table and registry diverged"
    for name, (cfg, _, n_ticks, _) in profs.items():
        g = gates[name]
        assert g["liveness_floor"] > 0 and g["p99_ceiling"] > 0
        assert len(g["bench_scale"]) == 2
        assert g["bridge"] in ("mirrored", "unsupported")
        # every named profile stays on the packed carry (the gray bounds
        # gates in state.packed_layout_reason hold at its registry scale)
        assert packed_layout_reason(cfg, cfg.knobs(), n_ticks) is None, (
            f"profile {name!r} fell off the packed layout"
        )
    for name, g in gates.items():
        for knob in g["workload"]:
            assert knob in ("open_rate", "open_queue_cap", "zipf_a"), (
                f"gate {name!r} carries a non-workload override {knob!r}"
            )
    assert 0 < OPEN_QUEUE_SLOTS <= 255


def test_cli_list_profiles_and_unknown_profile():
    from madraft_tpu.__main__ import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["pool", "--list-profiles"])
    assert rc == 0
    out = buf.getvalue()
    for name in storm_profiles():
        assert name in out, f"--list-profiles omitted {name!r}"

    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = main(["fuzz", "--profile", "nosuch"])
    assert rc == 2, "unknown --profile must exit 2 (usage error)"
    assert "nosuch" in err.getvalue() and "limp" in err.getvalue()
