"""The CLI front door (python -m madraft_tpu): fuzz -> flag a violating
cluster -> replay it exactly -> bridge its schedule to the C++ runtime.
One JSON line per command; exit code 1 when violations were found."""

import contextlib
import io
import json

import pytest

from madraft_tpu.__main__ import main


def run(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, json.loads(buf.getvalue().strip().splitlines()[-1])


def sans_telemetry(out):
    """Report minus the run telemetry (wall times vary run to run; every
    other field is deterministic and comparable)."""
    return {k: v for k, v in out.items() if k != "telemetry"}


def test_cli_fuzz_replay_bridge_loop():
    rc, out = run(["fuzz", "--clusters", "48", "--ticks", "256", "--storm"])
    assert rc == 0 and out["violating"] == 0, out

    rc, out = run(["fuzz", "--clusters", "48", "--ticks", "256", "--storm",
                   "--majority-override", "2"])
    assert rc == 1 and out["violating"] > 0
    bad = out["violating_clusters"][0]

    rc, out = run(["replay", "--cluster", str(bad), "--ticks", "256",
                   "--storm", "--majority-override", "2"])
    assert rc == 1 and out["violations"] != 0, out

    from madraft_tpu import simcore

    if not simcore.available():
        pytest.skip("libmadtpu.so not buildable here")
    rc, out = run(["bridge", "--cluster", str(bad), "--ticks", "256",
                   "--storm", "--majority-override", "2"])
    assert out["classes_match"], out


def test_cli_check_deterministic():
    # The MADSIM_TEST_CHECK_DETERMINISTIC analogue on the batched backend:
    # the flag re-runs the identical program and demands a bit-identical
    # report (/root/reference/README.md:81-87; the C++ runner's env-var twin
    # is covered by the cpp suite wrapper).
    rc, out = run(["fuzz", "--clusters", "32", "--ticks", "128", "--storm",
                   "--check-deterministic"])
    assert rc == 0 and out["deterministic"] is True, out
    rc, out = run(["kv-fuzz", "--clusters", "16", "--ticks", "128",
                   "--check-deterministic"])
    assert rc == 0 and out["deterministic"] is True, out


def test_cli_mesh_flag():
    # --mesh shards the cluster batch over all attached devices (the virtual
    # CPU mesh from conftest) and must not change any report field; a batch
    # that does not divide over the devices is rejected eagerly.
    rc, out = run(["fuzz", "--clusters", "32", "--ticks", "128", "--storm"])
    rc_m, out_m = run(["fuzz", "--clusters", "32", "--ticks", "128", "--storm",
                       "--mesh"])
    assert rc == rc_m == 0, (out, out_m)
    assert sans_telemetry(out) == sans_telemetry(out_m), (out, out_m)
    assert out["telemetry"]["steps_per_sec"] > 0  # run telemetry present
    import jax

    if len(jax.devices()) > 1:  # on one device every batch divides evenly
        with pytest.raises(SystemExit):
            run(["fuzz", "--clusters", "33", "--ticks", "16", "--mesh"])


def test_cli_service_layers():
    rc, out = run(["kv-fuzz", "--clusters", "32", "--ticks", "256", "--storm"])
    assert rc == 0 and out["violating"] == 0 and out["acked_ops_mean"] > 0

    rc, out = run(["ctrler-fuzz", "--clusters", "16", "--ticks", "256",
                   "--storm"])
    assert rc == 0 and out["violating"] == 0, out
    assert out["configs_created_mean"] > 0 and out["queries_done_mean"] > 0

    rc, out = run(["shardkv-fuzz", "--clusters", "8", "--ticks", "440"])
    assert rc == 0 and out["violating"] == 0 and out["installs_mean"] > 0


def test_cli_sweep_grid():
    # the fault-grid verb: 12 cells x 4 clusters in one program, per-cell
    # safety + liveness; exit 1 iff any cell had a violation
    rc, out = run(["sweep", "--clusters", "48", "--ticks", "256",
                   "--check-deterministic"])
    assert rc == 0 and out["violating"] == 0, out
    assert out["deterministic"] is True
    assert len(out["cells"]) == 12
    lossless = [c for c in out["cells"] if c["loss"] == 0.0]
    assert all(c["live"] == c["clusters"] for c in lossless), (
        "lossless cells must all commit"
    )
    with pytest.raises(SystemExit):
        run(["sweep", "--clusters", "4", "--ticks", "16"])  # < cells

    import jax

    ndev = len(jax.devices())
    if ndev >= 2 and 96 % ndev == 0:
        # mesh-sharded sweep: identical cells over any mesh that divides
        # the 12-cell x 8-cluster batch
        rc_m, out_m = run(["sweep", "--clusters", "96", "--ticks", "128",
                           "--mesh"])
        rc_u, out_u = run(["sweep", "--clusters", "96", "--ticks", "128"])
        assert rc_m == rc_u == 0
        assert sans_telemetry(out_m) == sans_telemetry(out_u)
    if ndev >= 2 and 9 % ndev and 10 % ndev == 0:
        # the divisibility check runs on the TRUNCATED batch: a 3-cell grid
        # truncates --clusters 10 down to 9, and 9 doesn't divide over the
        # device count while the requested 10 does (the 10 % ndev guard) —
        # so this raises only if the check uses the truncated value. (The
        # default 12-cell grid truncates to even batches, hence the custom
        # --loss axis; cheap, too — SystemExit fires before anything
        # compiles.)
        with pytest.raises(SystemExit, match="divide evenly"):
            run(["sweep", "--clusters", "10", "--ticks", "16", "--mesh",
                 "--loss", "0.0,0.05,0.1", "--crash", "0.0",
                 "--repartition", "0.0"])


def test_cli_pool_streams_and_exit_codes():
    # the continuous-pool verb: one JSONL row per retired cluster (with the
    # running violations/s) + a summary line; exit 1 iff a violation retired
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["pool", "--clusters", "16", "--ticks", "64",
                   "--chunk-ticks", "32", "--budget-ticks", "128",
                   "--storm", "--majority-override", "2", "--seed", "7"])
    lines = [json.loads(x) for x in buf.getvalue().strip().splitlines()]
    rows, summary = lines[:-1], lines[-1]
    assert rc == 1 and summary["retired_violating"] > 0, summary
    assert summary["retired"] == len(rows)
    assert summary["violating_clusters"], summary
    viol = [r for r in rows if r["violations"]]
    assert viol and viol[0]["violation_names"] == ["DUAL_LEADER"]
    assert viol[-1]["violations_per_s"] is not None
    # a retired row's coordinates feed replay directly (the workflow the
    # README documents: pool -> explain -> replay)
    r = viol[0]
    rc2, out = run(["replay", "--cluster", str(r["cluster_id"]),
                    "--ticks", str(r["ticks_run"]), "--storm",
                    "--majority-override", "2", "--seed", "7"])
    assert rc2 == 1 and out["violations"] == r["violations"], (r, out)

    # clean profile: everything retires at the horizon, exit 0
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["pool", "--clusters", "16", "--ticks", "64",
                   "--budget-ticks", "64", "--storm", "--seed", "3"])
    summary = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert rc == 0 and summary["retired_violating"] == 0, summary
    assert summary["retired"] == 16


def test_cli_pool_devices_flag():
    # the pod-scale pool from the front door: --devices shards the lanes
    # under the lane-partitioned id scheme; --mesh is shorthand for all
    # attached devices; a non-dividing lane count is a usage error (exit 2,
    # distinct from the violation exit 1); a streamed hit replays exactly
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["pool", "--clusters", "16", "--ticks", "64",
                   "--chunk-ticks", "32", "--budget-ticks", "128",
                   "--storm", "--majority-override", "2", "--seed", "7",
                   "--devices", "2"])
    lines = [json.loads(x) for x in buf.getvalue().strip().splitlines()]
    rows, summary = lines[:-1], lines[-1]
    assert rc == 1 and summary["retired_violating"] > 0, summary
    assert summary["devices"] == 2 and summary["id_scheme"] == "lane"
    r = next(r for r in rows if r["violations"])
    rc2, out = run(["replay", "--cluster", str(r["cluster_id"]),
                    "--ticks", str(r["ticks_run"]), "--storm",
                    "--majority-override", "2", "--seed", "7"])
    assert rc2 == 1 and out["violations"] == r["violations"], (r, out)

    if 16 % len(jax.devices()) == 0:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(["pool", "--clusters", "16", "--ticks", "64",
                       "--budget-ticks", "64", "--storm", "--seed", "3",
                       "--mesh"])
        summary = json.loads(buf.getvalue().strip().splitlines()[-1])
        assert rc == 0 and summary["devices"] == len(jax.devices()), summary

    with pytest.raises(SystemExit) as ei:
        main(["pool", "--clusters", "15", "--ticks", "64",
              "--devices", "2"])
    assert ei.value.code == 2


def test_cli_sweep_small_grid_uniform_dispatch():
    # a small grid rides the fast uniform-knob layout (per-cell programs)
    # and says so; cell accounting is unchanged
    rc, out = run(["sweep", "--clusters", "16", "--ticks", "64",
                   "--loss", "0,0.1", "--crash", "0", "--repartition", "0"])
    assert rc == 0 and out["dispatch"] == "uniform", out
    assert len(out["cells"]) == 2 and out["clusters_run"] == 16


def test_cli_service_bug_flag():
    # the planted-bug library from the front door: each layer's bug fires
    # (exit 1 + violations) and unknown names / wrong verbs are rejected
    rc, out = run(["kv-fuzz", "--clusters", "32", "--ticks", "256", "--storm",
                   "--service-bug", "stale_read"])
    assert rc == 1 and out["violating"] > 0, out

    rc, out = run(["ctrler-fuzz", "--clusters", "32", "--ticks", "256",
                   "--storm", "--service-bug", "greedy_rebalance"])
    assert rc == 1 and out["violating"] > 0, out

    with pytest.raises(SystemExit):
        run(["kv-fuzz", "--clusters", "8", "--ticks", "16",
             "--service-bug", "not_a_bug"])
    with pytest.raises(SystemExit):
        run(["fuzz", "--clusters", "8", "--ticks", "16",
             "--service-bug", "stale_read"])
