"""Pytest wrapper over the C++ test binary (sim-core + raft-core suites).

Each C++ test runs in its own subprocess with a fixed seed (failures print the
seed for exact replay, reference README.md:42-55). The binary is (re)built on
demand with cmake+ninja; on containers without the cmake toolchain the whole
module SKIPS cleanly (one skipped parametrization + skipped watchdog tests)
instead of erroring at collection — ``--continue-on-collection-errors`` must
not be load-bearing for tier-1.
"""

import pathlib
import shutil
import subprocess

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
BUILD = ROOT / "build"
BINARY = BUILD / "madtpu_tests"
SEED = "12345"


def _unavailable_reason():
    """Non-None when the C++ suite cannot run here: the cmake/ninja
    toolchain is absent (this container ships only g++ — the in-process
    bridge tests still run via simcore's direct-g++ fallback, but this
    module's full test binary is a cmake build)."""
    missing = [t for t in ("cmake", "ninja") if shutil.which(t) is None]
    if missing:
        return f"C++ suite needs cmake+ninja; missing: {', '.join(missing)}"
    return None


def _build():
    subprocess.run(
        ["cmake", "-S", str(ROOT / "cpp"), "-B", str(BUILD), "-G", "Ninja"],
        check=True, capture_output=True,
    )
    subprocess.run(["ninja", "-C", str(BUILD)], check=True, capture_output=True)


def _ensure_built():
    srcs = list((ROOT / "cpp").rglob("*.cpp")) + list((ROOT / "cpp").rglob("*.h"))
    newest = max(p.stat().st_mtime for p in srcs)
    if not BINARY.exists() or BINARY.stat().st_mtime < newest:
        _build()


def _list_tests():
    _ensure_built()
    out = subprocess.run(
        [str(BINARY), "--list"], check=True, capture_output=True, text=True
    )
    # wdog_selftest_* deliberately wedge (they exist to prove the watchdog
    # fires); test_watchdog_names_the_wedged_test drives them explicitly
    return [t for t in out.stdout.split() if not t.startswith("wdog_selftest")]


def pytest_generate_tests(metafunc):
    if "cpp_test_name" in metafunc.fixturenames:
        reason = _unavailable_reason()
        if reason is None:
            try:
                names = _list_tests()
            except OSError as e:
                # missing/unrunnable binary only — a cmake build that RUNS
                # and fails (CalledProcessError) must FAIL the suite, not
                # skip it: skipping would silently green a broken C++ change
                # on boxes that do have the toolchain
                reason = f"C++ test binary unavailable: {e}"
        if reason is not None:
            # one visibly-skipped parametrization, not a collection error
            names = [pytest.param("toolchain-missing",
                                  marks=pytest.mark.skip(reason=reason))]
        metafunc.parametrize("cpp_test_name", names)


def _ensure_built_or_skip():
    reason = _unavailable_reason()
    if reason is not None:
        pytest.skip(reason)
    try:
        _ensure_built()
    except OSError as e:  # see pytest_generate_tests: build FAILURES fail
        pytest.skip(f"C++ test binary unavailable: {e}")


def test_cpp(cpp_test_name):
    _ensure_built_or_skip()
    proc = subprocess.run(
        [str(BINARY), cpp_test_name],
        env={"MADTPU_TEST_SEED": SEED, "PATH": "/usr/bin:/bin"},
        capture_output=True, text=True, timeout=300,
    )
    if proc.returncode != 0:
        pytest.fail(
            f"{cpp_test_name} failed (seed {SEED}):\n{proc.stdout[-3000:]}\n{proc.stderr[-3000:]}"
        )


def test_watchdog_names_the_wedged_test():
    """The per-run liveness watchdog (reference tester.rs:353-358's 120s
    panic + a virtual-time cap) must convert a wedged test into a crisp
    failure naming the test and both clocks — not an opaque runner timeout
    (the seed-7036 lesson, PERF.md round 5)."""
    _ensure_built_or_skip()
    proc = subprocess.run(
        [str(BINARY), "wdog_selftest_wedge"],
        env={
            "MADTPU_TEST_SEED": SEED,
            "MADTPU_TEST_VIRT_CAP": "2",
            "PATH": "/usr/bin:/bin",
        },
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode != 0
    assert "[WDOG ] test wdog_selftest_wedge exceeded 2s VIRTUAL" in proc.stderr


def test_sigalrm_backstop_names_cpu_bound_hang():
    """A CPU-bound hang never returns to the event loop, so only the runner's
    SIGALRM backstop can catch it — and it must still name the test."""
    _ensure_built_or_skip()
    proc = subprocess.run(
        [str(BINARY), "wdog_selftest_spin"],
        env={
            "MADTPU_TEST_SEED": SEED,
            "MADTPU_TEST_REAL_CAP": "1",  # alarm fires at ~3s
            "PATH": "/usr/bin:/bin",
        },
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode != 0
    assert "[WDOG ] test wdog_selftest_spin hit the SIGALRM" in proc.stderr
