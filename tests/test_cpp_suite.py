"""Pytest wrapper over the C++ test binary (sim-core + raft-core suites).

Each C++ test runs in its own subprocess with a fixed seed (failures print the
seed for exact replay, reference README.md:42-55). The binary is (re)built on
demand with cmake+ninja.
"""

import pathlib
import subprocess

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
BUILD = ROOT / "build"
BINARY = BUILD / "madtpu_tests"
SEED = "12345"


def _build():
    subprocess.run(
        ["cmake", "-S", str(ROOT / "cpp"), "-B", str(BUILD), "-G", "Ninja"],
        check=True, capture_output=True,
    )
    subprocess.run(["ninja", "-C", str(BUILD)], check=True, capture_output=True)


def _ensure_built():
    srcs = list((ROOT / "cpp").rglob("*.cpp")) + list((ROOT / "cpp").rglob("*.h"))
    newest = max(p.stat().st_mtime for p in srcs)
    if not BINARY.exists() or BINARY.stat().st_mtime < newest:
        _build()


def _list_tests():
    _ensure_built()
    out = subprocess.run(
        [str(BINARY), "--list"], check=True, capture_output=True, text=True
    )
    # wdog_selftest_* deliberately wedge (they exist to prove the watchdog
    # fires); test_watchdog_names_the_wedged_test drives them explicitly
    return [t for t in out.stdout.split() if not t.startswith("wdog_selftest")]


def pytest_generate_tests(metafunc):
    if "cpp_test_name" in metafunc.fixturenames:
        metafunc.parametrize("cpp_test_name", _list_tests())


def test_cpp(cpp_test_name):
    _ensure_built()
    proc = subprocess.run(
        [str(BINARY), cpp_test_name],
        env={"MADTPU_TEST_SEED": SEED, "PATH": "/usr/bin:/bin"},
        capture_output=True, text=True, timeout=300,
    )
    if proc.returncode != 0:
        pytest.fail(
            f"{cpp_test_name} failed (seed {SEED}):\n{proc.stdout[-3000:]}\n{proc.stderr[-3000:]}"
        )


def test_watchdog_names_the_wedged_test():
    """The per-run liveness watchdog (reference tester.rs:353-358's 120s
    panic + a virtual-time cap) must convert a wedged test into a crisp
    failure naming the test and both clocks — not an opaque runner timeout
    (the seed-7036 lesson, PERF.md round 5)."""
    _ensure_built()
    proc = subprocess.run(
        [str(BINARY), "wdog_selftest_wedge"],
        env={
            "MADTPU_TEST_SEED": SEED,
            "MADTPU_TEST_VIRT_CAP": "2",
            "PATH": "/usr/bin:/bin",
        },
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode != 0
    assert "[WDOG ] test wdog_selftest_wedge exceeded 2s VIRTUAL" in proc.stderr


def test_sigalrm_backstop_names_cpu_bound_hang():
    """A CPU-bound hang never returns to the event loop, so only the runner's
    SIGALRM backstop can catch it — and it must still name the test."""
    _ensure_built()
    proc = subprocess.run(
        [str(BINARY), "wdog_selftest_spin"],
        env={
            "MADTPU_TEST_SEED": SEED,
            "MADTPU_TEST_REAL_CAP": "1",  # alarm fires at ~3s
            "PATH": "/usr/bin:/bin",
        },
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode != 0
    assert "[WDOG ] test wdog_selftest_spin hit the SIGALRM" in proc.stderr
