"""Round-trip unit tests for the i32 log-value packings (kv.py, shardkv.py).

Every oracle and apply machine keys off these encodings — a collision or a
round-trip failure would silently corrupt dedup tables and truth counts, so
the bijectivity and the non-zero guarantee (0 is the empty-lane sentinel,
NOOP_CMD is the leader no-op) are pinned directly over the full domains the
fuzzers use."""

import numpy as np

from madraft_tpu.tpusim.config import NOOP_CMD
from madraft_tpu.tpusim import kv as kvm
from madraft_tpu.tpusim import shardkv as skvm


def test_kv_pack_roundtrip_and_uniqueness():
    cfg = kvm.KvConfig(n_clients=4, n_keys=4)
    seen = set()
    for client in range(cfg.n_clients):
        for seq in (0, 1, 2, kvm._SEQ_LIM - 1):
            for key in range(cfg.n_keys):
                for kind in (kvm._APPEND, kvm._GET, kvm._PUT):
                    v = int(kvm._pack(cfg, client, seq, key, kind))
                    assert v != 0 and v != NOOP_CMD
                    assert v not in seen
                    seen.add(v)
                    c, s, k, kd = kvm._unpack(cfg, np.int32(v))
                    assert (int(c), int(s), int(k), int(kd)) == (
                        client, seq, key, kind
                    )


def test_kv_pack_fits_i32_at_limits():
    cfg = kvm.KvConfig(n_clients=8, n_keys=8)
    v = kvm._pack(cfg, cfg.n_clients - 1, kvm._SEQ_LIM - 1, cfg.n_keys - 1,
                  kvm._PUT)  # the largest kind
    assert 0 < int(v) < 2**31


def test_shardkv_op_pack_roundtrip():
    cfg = skvm.ShardKvConfig()
    seen = set()
    for client in range(cfg.n_clients):
        for seq in (0, 1, skvm._SEQ_LIM - 1):
            for shard in range(cfg.n_shards):
                for kind in (skvm._APPEND, skvm._GET, skvm._PUT):
                    v = int(skvm._pack_op(cfg, client, seq, shard, kind))
                    assert v != 0 and v not in seen
                    seen.add(v)
                    kd, c, s, sh, _, _, _ = skvm._unpack(cfg, np.int32(v))
                    assert (int(kd), int(c), int(s), int(sh)) == (
                        kind, client, seq, shard
                    )


def test_shardkv_marker_packs_roundtrip_disjoint():
    # CONFIG / INSTALL / DELETE markers must round-trip their own payloads
    # and never collide with each other or with client ops.
    cfg = skvm.ShardKvConfig()
    seen = set()
    for c in range(cfg.n_configs):
        for var in (0, 1):  # the adopted-announce variant bit (live ctrler)
            v = int(skvm._pack_config(np.int32(c), var))
            kd, _, _, _, cfg_c, _, var_c = skvm._unpack(cfg, np.int32(v))
            assert int(kd) == skvm._CONFIG and int(cfg_c) == c
            assert int(var_c) == var
            assert v not in seen
            seen.add(v)
        for shard in range(cfg.n_shards):
            vi = int(skvm._pack_install(cfg, np.int32(c), np.int32(shard)))
            vd = int(skvm._pack_delete(cfg, np.int32(c), np.int32(shard)))
            for v2, want_kind in ((vi, skvm._INSTALL), (vd, skvm._DELETE)):
                kd, _, _, sh, _, cfg_i, _ = skvm._unpack(cfg, np.int32(v2))
                assert int(kd) == want_kind
                assert int(sh) == shard and int(cfg_i) == c
                assert v2 not in seen
                seen.add(v2)
    # kinds live in disjoint mod-8 classes, so ops can never alias markers:
    # every marker's class differs from BOTH op kinds
    op_kinds = {skvm._APPEND, skvm._GET}
    for kind in op_kinds:
        op = int(skvm._pack_op(cfg, 0, 0, 0, kind))
        assert (op - 1) % 8 == kind
    assert all((v - 1) % 8 not in op_kinds for v in seen)
