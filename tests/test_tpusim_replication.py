"""Batched counterparts of Lab 2B/2C agreement + persistence tests
(/root/reference/src/raft/tests.rs:115-856): log replication, commit safety under
message loss, partitions, and crash/restart storms.
"""

import numpy as np

from madraft_tpu.tpusim import SimConfig, fuzz
from madraft_tpu.tpusim.engine import make_fuzz_fn

import jax.numpy as jnp


def test_basic_agree():
    # basic_agree_2b: reliable net, commands commit on every node.
    cfg = SimConfig(n_nodes=5, p_client_cmd=0.3)
    fn = make_fuzz_fn(cfg, n_clusters=32, n_ticks=300)
    final = fn(jnp.asarray(11, jnp.uint32))
    assert int(final.violations.sum()) == 0
    commit = np.asarray(final.commit)
    shadow = np.asarray(final.shadow_len)
    assert (shadow >= 5).all(), f"too little committed: {shadow.min()}"
    # every live node eventually learns the commits (leader commit piggybacks)
    assert (commit.max(axis=1) >= shadow - 1).all()


def test_agreement_under_loss():
    # unreliable_agree_2c: 10% drop + jitter; safety holds, progress continues.
    cfg = SimConfig(n_nodes=5, p_client_cmd=0.2, loss_prob=0.1)
    rep = fuzz(cfg, seed=21, n_clusters=48, n_ticks=384)
    assert rep.n_violating == 0
    assert (rep.committed >= 3).all()


def test_figure8_crash_storm():
    # figure_8_2c (tests.rs:613): repeated leader crashes must never lose a
    # committed entry — the commit-shadow oracle checks exactly this.
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.2, p_crash=0.02, p_restart=0.2, max_dead=2,
        loss_prob=0.05,
    )
    rep = fuzz(cfg, seed=31, n_clusters=64, n_ticks=512)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()]} at "
        f"ticks {rep.first_violation_tick[rep.violating_clusters()]}"
    )
    # liveness: the vast majority of clusters still make progress
    assert (rep.committed >= 1).mean() > 0.9


def test_churn_partitions_crashes():
    # unreliable_churn_2c-style storm: partitions + crashes + loss together.
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.2, p_crash=0.01, p_restart=0.2, max_dead=2,
        p_repartition=0.02, p_heal=0.05, loss_prob=0.1,
    )
    rep = fuzz(cfg, seed=41, n_clusters=64, n_ticks=512)
    assert rep.n_violating == 0


def test_leader_targeted_and_asymmetric_cuts():
    # Leader-in-minority partitions (kvraft tester.rs:184-191) and one-sided
    # directed link cuts (the adj tensor is [dst, src]; connect/disconnect
    # asymmetry, raft tester.rs:264-276) as schedule draws: safety holds and
    # the cluster keeps re-electing and committing through targeted cuts.
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.2, loss_prob=0.05,
        p_leader_part=0.02, p_asym_cut=0.05, p_heal=0.05,
    )
    rep = fuzz(cfg, seed=51, n_clusters=64, n_ticks=512)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()]}"
    )
    assert (rep.first_leader_tick >= 0).all()
    assert (rep.committed >= 3).all(), "progress must survive targeted cuts"


def test_liveness_across_delay_spans():
    # Round-3 regression: deterministic (1..1) and min>=2 (2..3, 3..6) delay
    # spans starved the single-slot mailboxes under overwrite-newest + eager
    # resends — elections succeeded but NOTHING ever committed. Fixed by
    # responses-before-requests delivery order plus keep-oldest slots for
    # periodically-regenerated messages (step.py). Every span must commit.
    base = SimConfig(n_nodes=5, p_client_cmd=0.2)
    for dmin, dmax in ((1, 1), (2, 3), (3, 6), (1, 3)):
        rep = fuzz(base.replace(delay_min=dmin, delay_max=dmax), seed=321,
                   n_clusters=32, n_ticks=256)
        assert rep.n_violating == 0
        assert (rep.committed > 5).all(), (
            f"delay {dmin}..{dmax} starved: committed {rep.committed.min()}"
        )


def test_heterogeneous_fault_sweep():
    # make_sweep_fn: one compiled program fuzzes a GRID of fault intensities
    # across the cluster batch (the TPU-idiomatic inversion of the
    # reference's compile-time test matrix). The per-cluster knobs must
    # actually bind: lossless clusters commit far more than heavy-loss ones.
    from madraft_tpu.tpusim.engine import make_sweep_fn, report

    cfg = SimConfig(n_nodes=5, p_client_cmd=0.2)
    n = 64
    loss = jnp.where(jnp.arange(n) < n // 2, 0.0, 0.6).astype(jnp.float32)
    knobs = cfg.knobs()._replace(loss_prob=loss)
    fn = make_sweep_fn(cfg, knobs, n_clusters=n, n_ticks=384)
    rep = report(fn(3))
    assert rep.n_violating == 0
    clean = rep.committed[: n // 2].mean()
    lossy = rep.committed[n // 2:].mean()
    assert clean > 1.5 * lossy, (
        f"per-cluster loss knob did not bind: clean={clean} lossy={lossy}"
    )
    # the lossy half also pays for its elections (delivered-message account)
    assert rep.msg_count[: n // 2].mean() > 1.5 * rep.msg_count[n // 2:].mean()


def test_agreement_rpc_budget():
    # count_2b's agreement budget (tests.rs:461-476), batched: on a quiet
    # reliable net, total delivered messages stay within an elections +
    # heartbeats + per-commit budget. Eager replication batches entries, so
    # per committed entry the cost is bounded by one AE round to each peer
    # (2*(n-1) deliveries) plus slack for retries around elections.
    cfg = SimConfig(n_nodes=5, p_client_cmd=0.2)
    fn = make_fuzz_fn(cfg, n_clusters=32, n_ticks=300)
    final = fn(jnp.asarray(61, jnp.uint32))
    assert int(np.asarray(final.violations).sum()) == 0
    msgs = np.asarray(final.msg_count)
    committed = np.asarray(final.shadow_len)
    n = cfg.n_nodes
    heartbeats = (300 // cfg.heartbeat_ticks + 1) * 2 * (n - 1)
    budget = 30 + heartbeats + (committed + 4) * 2 * (n - 1)
    assert (msgs <= budget).all(), (
        f"RPC budget blown: worst {(msgs - budget).max()} over "
        f"(msgs max {msgs.max()}, committed max {committed.max()})"
    )
