"""Batched counterparts of Lab 2B/2C agreement + persistence tests
(/root/reference/src/raft/tests.rs:115-856): log replication, commit safety under
message loss, partitions, and crash/restart storms.
"""

import numpy as np

from madraft_tpu.tpusim import SimConfig, fuzz
from madraft_tpu.tpusim.engine import make_fuzz_fn

import jax.numpy as jnp


def test_basic_agree():
    # basic_agree_2b: reliable net, commands commit on every node.
    cfg = SimConfig(n_nodes=5, p_client_cmd=0.3)
    fn = make_fuzz_fn(cfg, n_clusters=32, n_ticks=300)
    final = fn(jnp.asarray(11, jnp.uint32))
    assert int(final.violations.sum()) == 0
    commit = np.asarray(final.commit)
    shadow = np.asarray(final.shadow_len)
    assert (shadow >= 5).all(), f"too little committed: {shadow.min()}"
    # every live node eventually learns the commits (leader commit piggybacks)
    assert (commit.max(axis=1) >= shadow - 1).all()


def test_agreement_under_loss():
    # unreliable_agree_2c: 10% drop + jitter; safety holds, progress continues.
    cfg = SimConfig(n_nodes=5, p_client_cmd=0.2, loss_prob=0.1)
    rep = fuzz(cfg, seed=21, n_clusters=48, n_ticks=384)
    assert rep.n_violating == 0
    assert (rep.committed >= 3).all()


def test_figure8_crash_storm():
    # figure_8_2c (tests.rs:613): repeated leader crashes must never lose a
    # committed entry — the commit-shadow oracle checks exactly this.
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.2, p_crash=0.02, p_restart=0.2, max_dead=2,
        loss_prob=0.05,
    )
    rep = fuzz(cfg, seed=31, n_clusters=64, n_ticks=512)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()]} at "
        f"ticks {rep.first_violation_tick[rep.violating_clusters()]}"
    )
    # liveness: the vast majority of clusters still make progress
    assert (rep.committed >= 1).mean() > 0.9


def test_churn_partitions_crashes():
    # unreliable_churn_2c-style storm: partitions + crashes + loss together.
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.2, p_crash=0.01, p_restart=0.2, max_dead=2,
        p_repartition=0.02, p_heal=0.05, loss_prob=0.1,
    )
    rep = fuzz(cfg, seed=41, n_clusters=64, n_ticks=512)
    assert rep.n_violating == 0


def test_leader_targeted_and_asymmetric_cuts():
    # Leader-in-minority partitions (kvraft tester.rs:184-191) and one-sided
    # directed link cuts (the adj tensor is [dst, src]; connect/disconnect
    # asymmetry, raft tester.rs:264-276) as schedule draws: safety holds and
    # the cluster keeps re-electing and committing through targeted cuts.
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.2, loss_prob=0.05,
        p_leader_part=0.02, p_asym_cut=0.05, p_heal=0.05,
    )
    rep = fuzz(cfg, seed=51, n_clusters=64, n_ticks=512)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()]}"
    )
    assert (rep.first_leader_tick >= 0).all()
    assert (rep.committed >= 3).all(), "progress must survive targeted cuts"


def test_liveness_across_delay_spans():
    # Round-3 regression: deterministic (1..1) and min>=2 (2..3, 3..6) delay
    # spans starved the single-slot mailboxes under overwrite-newest + eager
    # resends — elections succeeded but NOTHING ever committed. Fixed by
    # responses-before-requests delivery order plus keep-oldest slots for
    # periodically-regenerated messages (step.py). Every span must commit.
    base = SimConfig(n_nodes=5, p_client_cmd=0.2)
    for dmin, dmax in ((1, 1), (2, 3), (3, 6), (1, 3)):
        rep = fuzz(base.replace(delay_min=dmin, delay_max=dmax), seed=321,
                   n_clusters=32, n_ticks=256)
        assert rep.n_violating == 0
        assert (rep.committed > 5).all(), (
            f"delay {dmin}..{dmax} starved: committed {rep.committed.min()}"
        )


def test_backup_convergence_budget_across_delay_spans():
    """Batched backup_2b analogue (/root/reference/src/raft/tests.rs:316-388):
    cut {leader, one partner} away from the majority; the stale leader
    piles up ~30 uncommitted client entries (flow_cap deep) while the
    majority commits its own; heal; convergence must land within a tick
    budget AND a message budget, swept over delay spans {1..5} in ONE
    program (delay knobs are per-cluster). This pins the round-3
    keep-oldest/response-starvation fixes: a regression shows up as a
    starved span blowing the tick budget or a retry storm blowing the
    message budget (tests.rs:461-476's RPC-budget idea applied to
    recovery).

    Budgets from calibration: every span with dmax<5 converged well inside
    128 ticks (max msgs/cluster seen 613 at span 1..1); the fully
    deterministic 5..5 span has a long symmetric-election tail (one seed
    needed 256 ticks of repeated vote splits before randomized timeouts
    broke the tie) — its budget is 320 ticks.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from madraft_tpu.tpusim import init_cluster, step_cluster
    from madraft_tpu.tpusim.config import LEADER

    spans = ((1, 1), (1, 3), (2, 3), (3, 5), (5, 5))
    per = 8
    nc = per * len(spans)
    cfg = SimConfig(n_nodes=5, p_client_cmd=0.3)
    kn = cfg.knobs().broadcast(nc)
    kn = kn._replace(
        delay_min=jnp.repeat(
            jnp.asarray([s[0] for s in spans], jnp.int32), per
        ),
        delay_max=jnp.repeat(
            jnp.asarray([s[1] for s in spans], jnp.int32), per
        ),
    )
    keys = jax.vmap(
        lambda i: jax.random.fold_in(jax.random.PRNGKey(17), i)
    )(jnp.arange(nc))
    states = jax.vmap(functools.partial(init_cluster, cfg))(keys)

    def make_phase(ticks):
        @jax.jit
        def run(states):
            def body(c, _):
                return (
                    jax.vmap(functools.partial(step_cluster, cfg))(
                        c, keys, kn
                    ),
                    None,
                )

            out, _ = jax.lax.scan(body, states, None, length=ticks)
            return out

        return run

    s1 = make_phase(60)(states)
    lead = np.asarray(jnp.argmax((s1.role == LEADER) & s1.alive, axis=1))
    part = (lead + 1) % 5
    side = np.zeros((nc, 5), bool)
    side[np.arange(nc), lead] = True
    side[np.arange(nc), part] = True
    adj = jnp.asarray(side[:, :, None] == side[:, None, :])
    s2 = make_phase(100)(s1._replace(adj=adj))
    # the scenario has teeth: stale leaders accumulated divergent tails
    tail = np.asarray(s2.log_len)[np.arange(nc), lead] - np.asarray(
        s2.commit
    )[np.arange(nc), lead]
    assert tail.mean() > 5, f"no divergence built up: {tail.tolist()}"

    sh0 = np.asarray(s2.shadow_len)
    mc0 = np.asarray(s2.msg_count)
    healed = s2._replace(adj=jnp.ones_like(adj))
    s3 = make_phase(128)(healed)
    assert (np.asarray(s3.violations) == 0).all(), "safety broke on heal"
    new = np.asarray(s3.shadow_len) - sh0
    msgs = np.asarray(s3.msg_count) - mc0
    fast = np.arange(nc) < 4 * per  # every span except 5..5
    assert (new[fast] >= 3).all(), (
        f"a dmax<5 span failed the 128-tick convergence budget: "
        f"{new[:4 * per].tolist()}"
    )
    assert (msgs <= 1200).all(), (
        f"message budget blown (retry storm): {msgs.max()}"
    )
    # the deterministic 5..5 span gets its calibrated longer budget
    s4 = make_phase(192)(s3)
    assert (np.asarray(s4.violations) == 0).all()
    new4 = np.asarray(s4.shadow_len) - sh0
    msgs4 = np.asarray(s4.msg_count) - mc0
    assert (new4 >= 3).all(), (
        f"the 5..5 span failed the 320-tick convergence budget: "
        f"{new4[4 * per:].tolist()}"
    )
    assert (msgs4 <= 2400).all(), f"message budget blown: {msgs4.max()}"


def test_heterogeneous_fault_sweep():
    # make_sweep_fn: one compiled program fuzzes a GRID of fault intensities
    # across the cluster batch (the TPU-idiomatic inversion of the
    # reference's compile-time test matrix). The per-cluster knobs must
    # actually bind: lossless clusters commit far more than heavy-loss ones.
    from madraft_tpu.tpusim.engine import make_sweep_fn, report

    cfg = SimConfig(n_nodes=5, p_client_cmd=0.2)
    n = 64
    loss = jnp.where(jnp.arange(n) < n // 2, 0.0, 0.6).astype(jnp.float32)
    knobs = cfg.knobs()._replace(loss_prob=loss)
    fn = make_sweep_fn(cfg, knobs, n_clusters=n, n_ticks=384)
    rep = report(fn(3))
    assert rep.n_violating == 0
    clean = rep.committed[: n // 2].mean()
    lossy = rep.committed[n // 2:].mean()
    assert clean > 1.5 * lossy, (
        f"per-cluster loss knob did not bind: clean={clean} lossy={lossy}"
    )
    # the lossy half also pays for its elections (delivered-message account)
    assert rep.msg_count[: n // 2].mean() > 1.5 * rep.msg_count[n // 2:].mean()


def test_agreement_rpc_budget():
    # count_2b's agreement budget (tests.rs:461-476), batched: on a quiet
    # reliable net, total delivered messages stay within an elections +
    # heartbeats + per-commit budget. Eager replication batches entries, so
    # per committed entry the cost is bounded by one AE round to each peer
    # (2*(n-1) deliveries) plus slack for retries around elections.
    cfg = SimConfig(n_nodes=5, p_client_cmd=0.2)
    fn = make_fuzz_fn(cfg, n_clusters=32, n_ticks=300)
    final = fn(jnp.asarray(61, jnp.uint32))
    assert int(np.asarray(final.violations).sum()) == 0
    msgs = np.asarray(final.msg_count)
    committed = np.asarray(final.shadow_len)
    n = cfg.n_nodes
    heartbeats = (300 // cfg.heartbeat_ticks + 1) * 2 * (n - 1)
    budget = 30 + heartbeats + (committed + 4) * 2 * (n - 1)
    assert (msgs <= budget).all(), (
        f"RPC budget blown: worst {(msgs - budget).max()} over "
        f"(msgs max {msgs.max()}, committed max {committed.max()})"
    )
