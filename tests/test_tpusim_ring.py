"""Unit tests for the step function's foundations: the canonical-ring
algebra and the packed PRNG draw block (step.py). The rest of the suite is
integration tests on simulated clusters (the reference's strategy, SURVEY.md
§4); these pin down the two pure-function layers everything rests on —
the invariants the docstrings promise, checked directly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madraft_tpu.tpusim.step import (
    _block_total,
    _DrawBlock,
    _entry_mix,
    _lane_abs,
    _net_draws,
    _slot,
)
from madraft_tpu.tpusim import SimConfig


def test_ring_lane_is_canonical_and_stable():
    # The lane of an absolute index NEVER depends on the window: _slot is a
    # pure function of the index, so compaction (a base bump) moves no data.
    cap = 64
    idx = jnp.arange(1, 5 * cap + 1, dtype=jnp.int32)
    lanes = _slot(idx, cap)
    assert lanes.min() >= 0 and lanes.max() < cap
    # index a and a+cap share a lane; nothing nearer does
    np.testing.assert_array_equal(np.asarray(lanes[:cap]), np.asarray(lanes[cap:2 * cap]))
    assert len(set(np.asarray(lanes[:cap]).tolist())) == cap


def test_lane_abs_inverts_slot_over_the_window():
    # _lane_abs(base)[k] is THE unique a in (base, base+cap] with
    # _slot(a) == k — the round-trip that makes one one-hot serve both the
    # sender read and the receiver write in the AE delivery.
    cap = 32
    for base in (0, 1, 31, 32, 33, 1000):
        abs_arr = _lane_abs(jnp.asarray(base, jnp.int32), cap)
        assert abs_arr.shape == (cap,)
        a = np.asarray(abs_arr)
        assert a.min() == base + 1 and a.max() == base + cap
        np.testing.assert_array_equal(
            np.asarray(_slot(abs_arr, cap)), np.arange(cap)
        )


def test_entry_mix_fold_is_order_free_but_position_sensitive():
    # XOR-folding _entry_mix over a set of entries must not depend on fold
    # order (compaction folds batches in one vectorized pass), but MUST
    # depend on each entry's position, term, and value.
    t = jnp.asarray([3, 5, 7], jnp.int32)
    v = jnp.asarray([11, 13, 17], jnp.int32)
    a = jnp.asarray([1, 2, 3], jnp.int32)
    h = np.asarray(_entry_mix(t, v, a))
    fold_fwd = h[0] ^ h[1] ^ h[2]
    fold_rev = h[2] ^ h[0] ^ h[1]
    assert fold_fwd == fold_rev
    # swapping two entries' positions changes the fold
    a_sw = jnp.asarray([2, 1, 3], jnp.int32)
    h_sw = np.asarray(_entry_mix(t, v, a_sw))
    assert (h_sw[0] ^ h_sw[1] ^ h_sw[2]) != fold_fwd
    # and so does changing a term or a value
    assert int(_entry_mix(t[0] + 1, v[0], a[0])) != int(h[0])
    assert int(_entry_mix(t[0], v[0] + 1, a[0])) != int(h[0])


def _blk(seed, total):
    return _DrawBlock(jax.random.PRNGKey(seed), total)


def test_draw_block_budget_is_exact():
    # step_cluster slices the tick's whole randomness budget from one
    # threefry call; _block_total must cover exactly what a tick takes.
    # (Consuming more would read out of bounds silently via numpy clipping —
    # this pins the arithmetic.)
    from madraft_tpu.tpusim.state import init_cluster
    from madraft_tpu.tpusim.step import step_cluster

    counted = {}

    class CountingBlock(_DrawBlock):
        def _take(self, shape):
            out = super()._take(shape)
            counted["off"] = self.off
            return out

    import madraft_tpu.tpusim.step as step_mod

    orig = step_mod._DrawBlock
    step_mod._DrawBlock = CountingBlock
    try:
        for n in (3, 5, 7):
            cfg = SimConfig(n_nodes=n, p_client_cmd=0.2, loss_prob=0.1,
                            p_repartition=0.02, p_heal=0.05)
            counted.clear()
            key = jax.random.PRNGKey(0)
            st = init_cluster(cfg, key)
            _ = step_cluster(cfg, st, key)
            assert counted["off"] == _block_total(n), (
                f"n={n}: consumed {counted['off']} of {_block_total(n)}"
            )
    finally:
        step_mod._DrawBlock = orig


def test_randint_and_u01_bounds():
    blk = _blk(7, 4096 * 3)
    u = np.asarray(blk.uniform((4096,)))
    assert (u >= 0).all() and (u < 1.0).all()
    # p=1.0 fires ALWAYS (the round-2 advisory corner: no round-up-to-1.0)
    assert np.asarray(blk.bern(1.0, (2048,))).all()
    r = np.asarray(blk.randint(5, 12, (1024,)))
    assert r.min() >= 5 and r.max() <= 11
    assert len(set(r.tolist())) == 7  # every value drawable


def test_net_draws_delay_range_and_loss_extremes():
    cfg = SimConfig(n_nodes=3, delay_min=2, delay_max=5)
    kn = cfg.knobs()
    blk = _blk(9, 4096)
    delay, lost = _net_draws(kn, blk, (2048,))
    d = np.asarray(delay)
    assert d.min() >= 2 and d.max() <= 5
    assert len(set(d.tolist())) == 4  # every delay in the span drawable
    # loss_prob=0 loses nothing; =1 loses everything
    blk = _blk(9, 4096)
    _, l0 = _net_draws(cfg.replace(loss_prob=0.0).knobs(), blk, (1024,))
    _, l1 = _net_draws(cfg.replace(loss_prob=1.0).knobs(), blk, (1024,))
    assert not np.asarray(l0).any()
    assert np.asarray(l1).all()
