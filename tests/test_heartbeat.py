"""Live run-telemetry plane (ISSUE 17): the heartbeat stream's
deterministic columns are device-count invariant and state-layout blind,
the final row reconciles EXACTLY with the pool summary (windows sum to the
cumulative histogram bit-for-bit), the manifest is atomically replaced and
a SIGKILLed writer reads as "crashed", `stats --follow` on a finished
stream renders byte-identically to one-shot, and the Perfetto export from
a heartbeat file is a valid Chrome trace. Everything here is host-side —
the companion static pin is tests/test_lint.py's REGISTRY_PROGRAMS == 31
(the plane adds zero compiled programs)."""

import contextlib
import io
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from madraft_tpu.__main__ import main
from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.config import CoverageConfig, storm_profiles
from madraft_tpu.tpusim.engine import run_pool
from madraft_tpu.tpusim.telemetry import (
    HEARTBEAT_SCHEMA,
    HeartbeatWriter,
    digest_line,
    manifest_path,
    manifest_status,
    read_heartbeat,
    read_manifest,
)

STORM = SimConfig(
    n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
    max_dead=2, p_repartition=0.02, p_heal=0.05,
)
VIOL = STORM.replace(majority_override=2)
DURABILITY = storm_profiles()["durability"][0]


def _pool_rows(tmp_path, name, cfg, **kw):
    hb = str(tmp_path / f"{name}.jsonl")
    summary = run_pool(cfg, kw.pop("seed", 7), kw.pop("n", 16),
                       kw.pop("horizon", 64),
                       chunk_ticks=kw.pop("chunk_ticks", 32),
                       budget_ticks=kw.pop("budget_ticks", 320),
                       heartbeat=hb, **kw)
    with open(hb) as f:
        rows = read_heartbeat(f)
    return hb, rows, summary


def run_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        try:
            rc = main(argv)
        except SystemExit as e:
            rc = e.code
    return rc, buf.getvalue()


# --------------------------------------------------------- det invariance
def test_det_columns_device_count_invariant(tmp_path):
    # the ISSUE-17 pin: per-generation DETERMINISTIC columns are pure
    # functions of (seed, config, chunk cadence, budget) — the lane-
    # partitioned id scheme makes the same clusters retire in the same
    # generations on 1 and 2 devices
    if len(jax.devices()) < 2:
        pytest.skip("needs a >= 2-device mesh")
    _, r1, s1 = _pool_rows(tmp_path, "d1", VIOL, devices=1)
    _, r2, s2 = _pool_rows(tmp_path, "d2", VIOL, devices=2)
    assert len(r1) == len(r2) and len(r1) >= 2
    det1 = [(r["gen"], r.get("lane_ticks"), r["det"]) for r in r1]
    det2 = [(r["gen"], r.get("lane_ticks"), r["det"]) for r in r2]
    assert det1 == det2
    assert s1["retired"] == s2["retired"]


def test_det_columns_layout_blind(tmp_path):
    # packed vs wide state layout changes bytes moved, never observations —
    # with the metrics plane ON the latency window columns must also match
    cfg = DURABILITY.replace(bug="ack_before_fsync", metrics=True)
    _, rw, sw = _pool_rows(tmp_path, "wide", cfg, seed=3, horizon=100,
                           chunk_ticks=50, budget_ticks=300,
                           pack_states=False)
    _, rp, sp = _pool_rows(tmp_path, "packed", cfg, seed=3, horizon=100,
                           chunk_ticks=50, budget_ticks=300,
                           pack_states=True)
    assert sw["state_layout"] == "wide" and sp["state_layout"] == "packed"
    assert [(r["gen"], r.get("lane_ticks"), r["det"]) for r in rw] == \
        [(r["gen"], r.get("lane_ticks"), r["det"]) for r in rp]


# ------------------------------------------------- final-row reconciliation
def test_final_row_reconciles_with_summary_exactly(tmp_path):
    cfg = DURABILITY.replace(bug="ack_before_fsync", metrics=True)
    _, rows, s = _pool_rows(tmp_path, "fin", cfg, seed=3, horizon=100,
                            chunk_ticks=50, budget_ticks=300)
    fin = rows[-1]
    assert fin.get("final") is True
    assert fin["lane_ticks"] == s["lane_ticks"]
    det = fin["det"]
    assert det["retired"] == s["retired"]
    assert det["violating"] == s["retired_violating"]
    assert det["effective_steps"] == s["effective_cluster_steps"]
    lat = det["latency"]
    assert lat["ops"] == s["latency"]["ops"]
    assert lat["p50_ticks"] == s["latency"]["p50_ticks"]
    assert lat["p99_ticks"] == s["latency"]["p99_ticks"]
    assert lat["ticks_total"] == s["latency"]["ticks_total"]
    # window columns across ALL rows (final's window is the finish merge)
    # sum to the cumulative histogram bit-for-bit — the stats-merge
    # invariant that makes a stream fold equal the run total
    hist_sum = np.sum([r["det"]["latency"]["hist_w"] for r in rows], axis=0)
    np.testing.assert_array_equal(hist_sum, np.asarray(s["latency"]["hist"]))
    assert sum(r["det"]["retired_w"] for r in rows) == s["retired"]
    assert sum(r["det"]["violating_w"] for r in rows) == s["retired_violating"]


def test_coverage_pool_heartbeat_reconciles(tmp_path):
    # coverage runs add the discovery columns; final cumulative values must
    # equal the summary's coverage dict (deterministic per fixed devices)
    _, rows, s = _pool_rows(tmp_path, "cov", VIOL,
                            coverage=CoverageConfig())
    fin = rows[-1]["det"]
    cov = s["coverage"]
    assert fin["new_fps"] == cov["seen_fingerprints"]
    assert fin["refills_mutated"] == cov["refills_mutated"]
    assert fin["refills_fresh"] == cov["refills_fresh"]
    assert sum(r["det"]["new_fps_w"] for r in rows) == cov["seen_fingerprints"]


# ---------------------------------------------------------------- manifest
def test_manifest_tracks_rows_and_lands_terminal(tmp_path):
    hb, rows, s = _pool_rows(tmp_path, "man", VIOL)
    man = read_manifest(hb)
    assert man["schema"] == HEARTBEAT_SCHEMA
    assert manifest_status(man) == "done"
    assert man["last_gen"] == rows[-1]["gen"]
    assert man["heartbeat"] == os.path.basename(hb)
    ctx = man["context"]
    assert ctx["kind"] == "pool" and ctx["seed"] == 7
    assert ctx["budget_ticks"] == 320
    assert "static_key" in ctx and ctx["config"]["n_nodes"] == 5


def test_manifest_atomic_and_crash_detectable(tmp_path):
    # a writer SIGKILLed mid-stream must leave (a) a parseable manifest —
    # tmp + os.replace means no torn write is ever observable — and (b) a
    # pid trail that decays "running" -> "crashed" for the watcher. The
    # child drives HeartbeatWriter directly (file-path import, no JAX) so
    # the kill lands mid-row-loop deterministically and cheaply.
    hb = str(tmp_path / "killed.jsonl")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = (
        "import importlib.util, os, sys, time\n"
        "spec = importlib.util.spec_from_file_location('t', os.path.join("
        f"{root!r}, 'madraft_tpu', 'tpusim', 'telemetry.py'))\n"
        "t = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(t)\n"
        "hb = t.HeartbeatWriter(sys.argv[1])\n"
        "hb.open({'kind': 'kill_test'})\n"
        "for g in range(10 ** 6):\n"
        "    hb.row({'retired': g}, {'wall_s': g * 1e-3})\n"
        "    time.sleep(0.002)\n"
    )
    proc = subprocess.Popen([sys.executable, "-c", child, hb])
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            man = read_manifest(hb)
            if man and man.get("last_gen") is not None:
                break
            time.sleep(0.02)
        else:
            pytest.fail("child never emitted a heartbeat row")
        assert manifest_status(man) == "running"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    man = read_manifest(hb)
    assert man is not None and man["status"] == "running"
    assert manifest_status(man) == "crashed", man
    # every generation the manifest claims was flushed to the stream BEFORE
    # the manifest was replaced, so the pointer never over-promises
    with open(hb) as f:
        assert len(read_heartbeat(f)) >= man["last_gen"] + 1


# ------------------------------------------------------------ CLI surfaces
def test_stats_follow_final_render_equals_one_shot(tmp_path):
    # on a stream whose manifest is terminal, --follow degrades to exactly
    # one render pass through the SAME code path as one-shot — byte equality
    hb, _, _ = _pool_rows(tmp_path, "follow", VIOL)
    rc1, once = run_cli(["stats", hb])
    rc2, followed = run_cli(["stats", "--follow", "--interval", "0.1", hb])
    assert rc1 == 0 and rc2 == 0
    assert followed == once


def test_stats_renders_live_pool_block(tmp_path):
    hb, rows, s = _pool_rows(tmp_path, "live", VIOL)
    rc, out = run_cli(["stats", hb])
    assert rc == 0
    assert "[final]" in out
    assert f"gen {rows[-1]['gen']}" in out
    assert f"retired {s['retired']}" in out


def test_explain_heartbeat_chrome_trace(tmp_path):
    hb, rows, _ = _pool_rows(tmp_path, "chrome", VIOL)
    rc, out = run_cli(["explain", "--heartbeat", hb, "--format", "chrome"])
    assert rc == 0
    trace = json.loads(out)
    evs = trace["traceEvents"]
    phases = {e["ph"] for e in evs}
    assert {"M", "X", "C"} <= phases
    spans = [e for e in evs if e["ph"] == "X"]
    gens = {r["gen"] for r in rows if not r.get("final")}
    assert {e["name"] for e in spans} >= {f"chunk+harvest g{g}" for g in gens}
    counters = {e["name"] for e in evs if e["ph"] == "C"}
    assert "violations_per_s" in counters and "device_wait_s" in counters
    # --out writes the trace file and prints a pointer header instead
    out_file = tmp_path / "trace.json"
    rc, header = run_cli(["explain", "--heartbeat", hb, "--format", "chrome",
                          "--out", str(out_file)])
    assert rc == 0
    assert json.loads(header)["trace_events"] == len(evs)
    assert json.loads(out_file.read_text())["traceEvents"]


def test_explain_heartbeat_requires_chrome_format(tmp_path):
    hb, _, _ = _pool_rows(tmp_path, "fmt", VIOL)
    rc, _ = run_cli(["explain", "--heartbeat", hb])
    assert rc == 2  # usage error, not a finding


def test_pool_digest_every_stderr(tmp_path, capsys):
    hb = str(tmp_path / "digest.jsonl")
    rc, _ = run_cli(["pool", "--clusters", "16", "--ticks", "64",
                     "--chunk-ticks", "32", "--budget-ticks", "320",
                     "--seed", "7", "--majority-override", "2",
                     "--heartbeat", hb, "--digest-every", "2"])
    assert rc == 1  # violations retired -> finding exit
    err = capsys.readouterr().err
    digests = [ln for ln in err.splitlines() if ln.startswith("pool: gen ")]
    assert digests and all("% of budget" in ln for ln in digests)
    # the digest spelling is shared with the soaks via digest_line
    with open(hb) as f:
        rows = read_heartbeat(f)
    even = [r for r in rows if not r.get("final") and r["gen"] % 2 == 0]
    assert len(digests) == len(even)
    assert digests[0] == f"pool: {digest_line(even[0])}"


def test_pathless_writer_keeps_digest_pipeline():
    # --digest-every without --heartbeat: rows flow to on_row, no file I/O
    seen = []
    hb = HeartbeatWriter(on_row=seen.append)
    hb.open({"kind": "pool", "budget_ticks": 100})
    hb.row({"retired": 4, "retired_w": 4, "violating": 1, "violating_w": 1,
            "effective_steps": 64}, {"wall_s": 0.5})
    hb.close()
    assert len(seen) == 1 and seen[0]["gen"] == 0
    assert "gen 0" in digest_line(seen[0])
    assert hb.path is None and manifest_path("x") == "x.manifest.json"
