"""Multi-chip sharding of the cluster batch over a device mesh.

Runs on the 8-device virtual CPU mesh (conftest.py). The driver's
dryrun_multichip does the same through __graft_entry__.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import fuzz, make_fuzz_fn


def _mesh():
    devs = np.array(jax.devices())
    return Mesh(devs, ("clusters",))


def test_sharded_run_matches_unsharded():
    cfg = SimConfig(n_nodes=3, p_client_cmd=0.2, loss_prob=0.05)
    rep_local = fuzz(cfg, seed=9, n_clusters=16, n_ticks=200)
    rep_shard = fuzz(cfg, seed=9, n_clusters=16, n_ticks=200, mesh=_mesh())
    np.testing.assert_array_equal(rep_local.msg_count, rep_shard.msg_count)
    np.testing.assert_array_equal(rep_local.committed, rep_shard.committed)
    assert rep_shard.n_violating == 0


def test_sharded_sweep_matches_unsharded():
    # The per-cluster-knob layout (make_sweep_fn) has its own mesh branch in
    # _fuzz_program — the knob pytree itself is sharding-constrained along
    # the cluster axis. A heterogeneous loss sweep must produce identical
    # reports sharded and unsharded.
    from madraft_tpu.tpusim.engine import make_sweep_fn, report

    cfg = SimConfig(n_nodes=3, p_client_cmd=0.2)
    kn = cfg.knobs()._replace(
        loss_prob=jnp.repeat(jnp.asarray([0.0, 0.3], jnp.float32), 8)
    )
    rep_local = report(make_sweep_fn(cfg, kn, 16, 200)(9))
    rep_shard = report(make_sweep_fn(cfg, kn, 16, 200, mesh=_mesh())(9))
    np.testing.assert_array_equal(rep_local.msg_count, rep_shard.msg_count)
    np.testing.assert_array_equal(rep_local.committed, rep_shard.committed)
    assert rep_shard.n_violating == 0


def test_sharded_state_placement():
    mesh = _mesh()
    fn = make_fuzz_fn(SimConfig(n_nodes=3), n_clusters=16, n_ticks=20, mesh=mesh)
    final = fn(jnp.asarray(2, jnp.uint32))
    # cluster axis actually sharded over all devices
    assert len(final.term.sharding.device_set) == len(jax.devices())
