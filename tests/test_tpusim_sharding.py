"""Multi-chip sharding of the cluster batch over a device mesh.

Runs on the virtual CPU device mesh (conftest.py). The driver's
dryrun_multichip does the same through __graft_entry__.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import fuzz, make_fuzz_fn


def _mesh():
    devs = np.array(jax.devices())
    return Mesh(devs, ("clusters",))


def test_sharded_run_matches_unsharded():
    cfg = SimConfig(n_nodes=3, p_client_cmd=0.2, loss_prob=0.05)
    rep_local = fuzz(cfg, seed=9, n_clusters=16, n_ticks=200)
    rep_shard = fuzz(cfg, seed=9, n_clusters=16, n_ticks=200, mesh=_mesh())
    np.testing.assert_array_equal(rep_local.msg_count, rep_shard.msg_count)
    np.testing.assert_array_equal(rep_local.committed, rep_shard.committed)
    assert rep_shard.n_violating == 0


def test_sharded_sweep_matches_unsharded():
    # The per-cluster-knob layout (make_sweep_fn) has its own mesh branch in
    # _fuzz_program — the knob pytree itself is sharding-constrained along
    # the cluster axis. A heterogeneous loss sweep must produce identical
    # reports sharded and unsharded.
    from madraft_tpu.tpusim.engine import make_sweep_fn, report

    cfg = SimConfig(n_nodes=3, p_client_cmd=0.2)
    kn = cfg.knobs()._replace(
        loss_prob=jnp.repeat(jnp.asarray([0.0, 0.3], jnp.float32), 8)
    )
    rep_local = report(make_sweep_fn(cfg, kn, 16, 200)(9))
    rep_shard = report(make_sweep_fn(cfg, kn, 16, 200, mesh=_mesh())(9))
    np.testing.assert_array_equal(rep_local.msg_count, rep_shard.msg_count)
    np.testing.assert_array_equal(rep_local.committed, rep_shard.committed)
    assert rep_shard.n_violating == 0


def test_sharded_state_placement():
    mesh = _mesh()
    fn = make_fuzz_fn(SimConfig(n_nodes=3), n_clusters=16, n_ticks=20, mesh=mesh)
    final = fn(jnp.asarray(2, jnp.uint32))
    # cluster axis actually sharded over all devices
    assert len(final.term.sharding.device_set) == len(jax.devices())


def test_sharded_service_sweeps_match_unsharded():
    # The kv/ctrler sweep programs have their own per-cluster-knob mesh
    # branch (service knobs sharding-constrained along the cluster axis,
    # kv.py _kv_program / ctrler.py _ctrler_program); a heterogeneous
    # workload-and-bug sweep must be identical sharded and unsharded.
    from madraft_tpu.tpusim.ctrler import (
        CtrlerConfig,
        ctrler_report,
        make_ctrler_sweep_fn,
    )
    from madraft_tpu.tpusim.kv import KvConfig, kv_report, make_kv_sweep_fn

    cfg = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False,
        loss_prob=0.05, log_cap=32, compact_every=8,
    )
    half = jnp.arange(16) < 8
    kv = KvConfig()
    kkn = kv.knobs()._replace(
        p_get=jnp.where(half, 0.0, 0.5).astype(jnp.float32),
        bug_stale_read=~half,
    )
    a = kv_report(make_kv_sweep_fn(cfg, cfg.knobs(), kkn, kv, 16, 200)(9))
    b = kv_report(
        make_kv_sweep_fn(cfg, cfg.knobs(), kkn, kv, 16, 200, mesh=_mesh())(9)
    )
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)

    ct = CtrlerConfig()
    ckn = ct.knobs()._replace(bug_greedy_rebalance=~half)
    a = ctrler_report(
        make_ctrler_sweep_fn(cfg, cfg.knobs(), ckn, ct, 16, 200)(9)
    )
    b = ctrler_report(
        make_ctrler_sweep_fn(cfg, cfg.knobs(), ckn, ct, 16, 200,
                             mesh=_mesh())(9)
    )
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)

    from madraft_tpu.tpusim.shardkv import (
        ShardKvConfig,
        make_shardkv_sweep_fn,
        shardkv_report,
    )

    sk = ShardKvConfig(n_groups=2, n_configs=6)
    cfg2 = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False,
        log_cap=64, compact_every=16, loss_prob=0.05,
    )
    skn = sk.knobs()._replace(
        cfg_interval=jnp.where(half, 40, 80).astype(jnp.int32)
    )
    a = shardkv_report(
        make_shardkv_sweep_fn(cfg2, cfg2.knobs(), skn, sk, 16, 200)(9)
    )
    b = shardkv_report(
        make_shardkv_sweep_fn(cfg2, cfg2.knobs(), skn, sk, 16, 200,
                              mesh=_mesh())(9)
    )
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
