"""Multi-chip sharding of the cluster batch over a device mesh.

Runs on the 8-device virtual CPU mesh (conftest.py). The driver's
dryrun_multichip does the same through __graft_entry__.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import fuzz, make_fuzz_fn


def _mesh():
    devs = np.array(jax.devices())
    return Mesh(devs, ("clusters",))


def test_sharded_run_matches_unsharded():
    cfg = SimConfig(n_nodes=3, p_client_cmd=0.2, loss_prob=0.05)
    rep_local = fuzz(cfg, seed=9, n_clusters=16, n_ticks=200)
    rep_shard = fuzz(cfg, seed=9, n_clusters=16, n_ticks=200, mesh=_mesh())
    np.testing.assert_array_equal(rep_local.msg_count, rep_shard.msg_count)
    np.testing.assert_array_equal(rep_local.committed, rep_shard.committed)
    assert rep_shard.n_violating == 0


def test_sharded_state_placement():
    mesh = _mesh()
    fn = make_fuzz_fn(SimConfig(n_nodes=3), n_clusters=16, n_ticks=20, mesh=mesh)
    final = fn(jnp.asarray(2, jnp.uint32))
    # cluster axis actually sharded over all devices
    assert len(final.term.sharding.device_set) == len(jax.devices())
