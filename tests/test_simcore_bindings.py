"""In-process ctypes bindings to the C++ runtime (madraft_tpu.simcore /
libmadtpu.so): the same replay/lincheck semantics as the CLI binaries,
callable many times per process with interleaved knob settings — which
specifically exercises the per-call (uncached) env reads for the majority
override and the shardkv bug mode."""

import pytest

from madraft_tpu import simcore


def _skip_unless_available():
    if not simcore.available():
        pytest.skip("libmadtpu.so not buildable here")


# partition cycles ({0,1} vs {2,3,4}, then heal) force concurrent elections;
# with majority_override=2 both sides can win -> dual leaders
RAFT_SCHED = """
nodes 5
ms_per_tick 10
ticks 400
majority_override {q}
seed 7
ev 40 adj 3 3 1c 1c 1c
ev 100 adj 1f 1f 1f 1f 1f
ev 160 adj 3 3 1c 1c 1c
ev 220 adj 1f 1f 1f 1f 1f
ev 280 adj 3 3 1c 1c 1c
ev 340 adj 1f 1f 1f 1f 1f
"""


def _violated(rep):
    return rep["dual_leader"] or rep["commit_mismatch"] or rep["apply_disorder"]


def test_replay_in_process_and_override_not_cached():
    _skip_unless_available()
    # broken quorum first: a safety class must fire (partitioned elections
    # under quorum 2 commit divergent values)
    bad = simcore.replay_schedule(RAFT_SCHED.format(q=2))
    assert _violated(bad), bad
    # SAME process, correct quorum: must be clean — a cached env read would
    # keep the override and fail this
    good = simcore.replay_schedule(RAFT_SCHED.format(q=0))
    assert not _violated(good), good
    assert good["max_applied"] > 0
    # and broken again, to prove the restore works both ways
    bad2 = simcore.replay_schedule(RAFT_SCHED.format(q=2))
    assert _violated(bad2), bad2


def test_lincheck_in_process():
    _skip_unless_available()
    ok = ["op 1 2 append k a1;", "op 3 4 get k a1;"]
    assert simcore.check_linearizable("\n".join(ok) + "\n")
    stale = ["op 1 2 append k a1;", "op 3 4 get k "]  # read misses acked write
    assert not simcore.check_linearizable("\n".join(stale) + "\n")
    with pytest.raises(ValueError):
        simcore.check_linearizable("not a history\n")


SKV_SCHED = """
groups 3
nodes 3
ticks 700
ms_per_tick 10
seed 11
bug {bug}
cfg 0 0 1 2 0 1 2 0 1 2 0
cfg 60 1 1 2 2 1 2 1 1 2 2
cfg 130 0 0 0 2 1 2 1 1 2 2
cfg 200 2 0 0 2 1 2 2 1 2 2
cfg 270 2 0 0 2 1 1 2 1 1 2
cfg 340 1 0 1 2 1 1 2 0 1 2
cfg 410 1 0 1 1 1 1 2 0 1 0
cfg 480 2 0 1 1 2 1 2 0 1 0
"""


def test_shardkv_replay_in_process_and_bug_not_cached():
    _skip_unless_available()
    clean = simcore.replay_shardkv_schedule(SKV_SCHED.format(bug="none"))
    assert clean["dup_apply"] == 0 and clean["stale_read"] == 0, clean
    assert clean["ops"] > 0
    # same process, bug on: the env-gated injection must take effect (and
    # be restored, so a following clean run stays clean). The bug firing is
    # distributional; across a few seeds at least one must fire and every
    # clean interleave must stay silent.
    fired = 0
    for seed in (11, 12, 13, 14, 15):
        sched = SKV_SCHED.format(bug="drop_dup_table").replace(
            "seed 11", f"seed {seed}"
        )
        rep = simcore.replay_shardkv_schedule(sched)
        fired += rep["dup_apply"]
        ctl = simcore.replay_shardkv_schedule(
            SKV_SCHED.format(bug="none").replace("seed 11", f"seed {seed}")
        )
        assert ctl["dup_apply"] == 0 and ctl["stale_read"] == 0, ctl
    assert fired > 0, "bug never fired across 5 seeds — env injection broken?"
