"""Batched-fuzzer counterparts of the reference's Lab 2A election tests
(/root/reference/src/raft/tests.rs:21-113) plus oracle self-validation.

Where the reference checks one cluster per seed, these check every property over a
batch of independently-seeded clusters in one device program.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from madraft_tpu.tpusim import SimConfig, fuzz
from madraft_tpu.tpusim.config import (
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
)
from madraft_tpu.tpusim.engine import make_fuzz_fn, report

RELIABLE = SimConfig(n_nodes=3, p_client_cmd=0.0)


def test_initial_election_batched():
    # initial_election_2a (tests.rs:21): a leader emerges and no safety violation.
    rep = fuzz(RELIABLE, seed=1, n_clusters=64, n_ticks=200)
    assert rep.n_violating == 0, f"violations: {rep.violations[rep.violating_clusters()]}"
    assert (rep.first_leader_tick >= 0).all(), "some cluster never elected a leader"
    # Election takes a few timeout rounds at most on a reliable net.
    assert (rep.first_leader_tick <= 120).all()


def test_exactly_one_leader_settles():
    # After a reliable run, every cluster has exactly one live leader.
    fn = make_fuzz_fn(RELIABLE, n_clusters=32, n_ticks=200)
    final = fn(jnp.asarray(3, jnp.uint32))
    leaders = np.asarray((final.role == 2) & final.alive).sum(axis=1)
    assert (leaders == 1).all(), f"leader counts: {leaders}"


def test_reelection_under_partitions():
    # reelection_2a / many_election_2a (tests.rs:49,81): random partitions and
    # heals; safety must hold throughout and leaders keep re-emerging.
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.0, p_repartition=0.02, p_heal=0.05,
        loss_prob=0.05,
    )
    rep = fuzz(cfg, seed=7, n_clusters=64, n_ticks=500)
    assert rep.n_violating == 0
    assert (rep.first_leader_tick >= 0).all()


def test_deterministic_replay():
    # MADSIM_TEST_CHECK_DETERMINISTIC analogue (/root/reference/README.md:81-87):
    # identical seed => bit-identical outcome; different seed => different run.
    cfg = SimConfig(n_nodes=3, p_repartition=0.02, p_heal=0.05, loss_prob=0.1)
    r1 = fuzz(cfg, seed=42, n_clusters=16, n_ticks=300)
    r2 = fuzz(cfg, seed=42, n_clusters=16, n_ticks=300)
    np.testing.assert_array_equal(r1.first_leader_tick, r2.first_leader_tick)
    np.testing.assert_array_equal(r1.msg_count, r2.msg_count)
    r3 = fuzz(cfg, seed=43, n_clusters=16, n_ticks=300)
    assert (r1.msg_count != r3.msg_count).any()


def test_election_and_idle_rpc_budgets():
    """count_2b analogue (tests.rs:389-479): electing a leader must cost a
    bounded number of messages, and an idle cluster must stay on the
    heartbeat cadence — asserted per cluster over the whole batch. The
    reference budgets are <=30 RPCs to elect (60 message deliveries) and
    <=3x20 RPCs/s idle; the tick-quantized equivalent is 2(n-1) deliveries
    per heartbeat period once a leader exists."""
    cfg = RELIABLE
    n_ticks = 96
    rep = fuzz(cfg, seed=13, n_clusters=256, n_ticks=n_ticks)
    assert rep.n_violating == 0
    ftl = rep.first_leader_tick
    assert (ftl >= 0).all()
    # liveness budget: a couple of timeout rounds on a reliable net
    assert (ftl <= 3 * cfg.election_timeout_max).all(), (
        f"slowest election at tick {ftl.max()}"
    )
    # message budget: election (<=60 deliveries, the reference's 30-RPC cap)
    # + idle heartbeats (AE + response per peer per period)
    idle_periods = (n_ticks - ftl) // cfg.heartbeat_ticks + 1
    budget = 60 + idle_periods * 2 * (cfg.n_nodes - 1)
    assert (rep.msg_count <= budget).all(), (
        f"worst overshoot {(rep.msg_count - budget).max()} deliveries"
    )


def test_oracle_catches_broken_quorum():
    # Validate the election-safety oracle by breaking the algorithm: a 2-vote
    # "majority" on 5 nodes lets two leaders share a term under partitions.
    cfg = SimConfig(
        n_nodes=5, majority_override=2, p_client_cmd=0.0,
        p_repartition=0.05, p_heal=0.02,
    )
    rep = fuzz(cfg, seed=5, n_clusters=64, n_ticks=400)
    assert rep.n_violating > 0, "oracle failed to catch quorum-size bug"
    bits = rep.violations[rep.violating_clusters()]
    assert (bits & VIOLATION_DUAL_LEADER).any()
    # and the failure is pinpointed to a tick for replay
    assert (rep.first_violation_tick[rep.violating_clusters()] >= 0).all()


def test_oracle_catches_log_divergence():
    # Validate the LOG-MATCHING oracle (the pairwise same-(index,term) =>
    # identical-prefix reduction in step.py) by the same broken-quorum bug,
    # now with a client workload: two same-term leaders each accept different
    # commands at the same index, so some pair of logs shares (index, term)
    # with diverging values — exactly the Log Matching Property violation
    # the checker must flag (the batched analogue of push_and_check,
    # /root/reference/src/raft/tester.rs:379-397).
    cfg = SimConfig(
        n_nodes=5, majority_override=2, p_client_cmd=0.3,
        p_repartition=0.05, p_heal=0.02,
    )
    rep = fuzz(cfg, seed=5, n_clusters=64, n_ticks=400)
    bits = rep.violations[rep.violating_clusters()]
    assert (bits & VIOLATION_LOG_MATCHING).any(), (
        "log-matching oracle failed to catch same-term divergence"
    )


def test_raft_timing_requirement_faithful():
    """The simulator reproduces the paper's §5.6 timing requirement
    (broadcastTime << electionTimeout << MTBF) and the textbook case for
    RANDOMIZED timeouts — both as liveness, never safety, properties:
      * zero timeout randomness => perfectly symmetric split votes forever
        (in lockstep nothing ever breaks the tie: every node re-times-out
        on the same tick, votes for itself, repeats);
      * delays comparable to the election timeout => vote requests arrive
        around the voters' own timeouts and terms churn without progress;
      * restore the timing requirement => every cluster elects and commits.
    Safety (zero violations) holds in all three regimes."""
    degenerate = SimConfig(
        n_nodes=5, p_client_cmd=0.2, election_timeout_min=16,
        election_timeout_max=16, loss_prob=0.1,
    )
    rep = fuzz(degenerate, seed=4242, n_clusters=32, n_ticks=768)
    assert rep.n_violating == 0
    assert (rep.first_leader_tick < 0).all(), (
        "zero-randomness timeouts must livelock symmetric elections"
    )

    slow = SimConfig(
        n_nodes=5, p_client_cmd=0.2, delay_min=8, delay_max=20,
        election_timeout_min=15, election_timeout_max=30,
    )
    rep = fuzz(slow, seed=4242, n_clusters=32, n_ticks=1024)
    assert rep.n_violating == 0
    assert (rep.committed > 0).mean() < 0.5, (
        "broadcastTime ~ electionTimeout must (mostly) destroy liveness"
    )

    healthy = slow.replace(delay_min=1, delay_max=3)
    rep = fuzz(healthy, seed=4242, n_clusters=32, n_ticks=1024)
    assert rep.n_violating == 0
    assert (rep.committed > 0).all(), "timing requirement restored => live"
