"""Multi-group sharded-KV fuzzing on TPU (Lab 4B, the groups axis):
migration exactly-once, ownership exclusivity, shard GC (challenge 1),
serving through reconfiguration (challenge 2), oracle validation via bug
injection, and determinism. The reference scenarios these batch:
/root/reference/src/shardkv/tests.rs:70-362 (join/leave + concurrent +
crash storms), 438-493 (challenge 1), 499-605 (challenge 2).

Runs on the 8-device virtual CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.shardkv import (
    OWNED,
    ShardKvConfig,
    VIOLATION_SHARD_DIVERGE,
    make_shardkv_fuzz_fn,
    shardkv_fuzz,
    shardkv_report,
)

# 3 groups x 3 nodes; configs stop changing by ~tick 300, the tail quiesces.
RAFT = SimConfig(
    n_nodes=3,
    p_client_cmd=0.0,
    compact_at_commit=False,
    log_cap=64,
    compact_every=16,
    loss_prob=0.05,
)
SKV = ShardKvConfig()
TICKS = 440  # n_configs * ~cfg_interval + quiesce tail


def test_shardkv_migration_clean():
    """Reconfiguration churn with no faults: zero violations, ops flow, every
    migration completes and every surrendered copy is GC'd (challenge 1)."""
    rep = shardkv_fuzz(RAFT, SKV, seed=5, n_clusters=24, n_ticks=TICKS)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.acked_ops > 20).all()
    assert rep.installs.sum() > 24, "config churn must actually migrate shards"
    # challenge 1 at quiesce: every frozen copy was deleted, one owner/shard
    assert (rep.deletes == rep.installs).mean() > 0.85
    assert (rep.frozen_left == 0).mean() > 0.85
    assert (rep.owned_copies == 1).all()
    # the schedule was actually consumed
    assert (rep.final_cfg >= SKV.n_configs - 2).mean() > 0.8


def test_shardkv_serves_during_migration():
    """Challenge 2: ops on unaffected shards keep completing while other
    shards migrate — acks accrue across the whole run, not just between
    configs. (Weak-form liveness check: total acks far exceed what a
    stop-the-world implementation could commit in the gaps.)"""
    rep = shardkv_fuzz(RAFT, SKV.replace(p_op=0.8, p_retry=0.8), seed=9,
                       n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating == 0
    # every deployment keeps completing ops throughout ~5 reconfigurations; a
    # stop-the-world implementation would flatline during each migration.
    # (Per-deployment floor is loose — trajectories vary per seed — the
    # aggregate bound carries the real weight.)
    assert (rep.acked_ops > 30).all()
    assert rep.acked_ops.sum() > 16 * 60


def test_shardkv_fault_storm():
    """Crashes + message loss racing reconfiguration (concurrent1/2/3_4b,
    miss_change_4b): safety holds; migrations still complete."""
    storm = RAFT.replace(p_crash=0.01, p_restart=0.2, max_dead=1, loss_prob=0.1)
    rep = shardkv_fuzz(storm, SKV, seed=2, n_clusters=24, n_ticks=TICKS)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} raft "
        f"{rep.raft_violations[rep.violating_clusters()[:8]]}"
    )
    assert rep.installs.sum() > 24
    assert (rep.acked_ops > 0).all()


def test_shardkv_dup_migration_oracle_fires():
    """Dropping the dup table at install (exactly-once-across-migration bug):
    a clerk retry that lands after the shard moved double-applies, and the
    truth-walker divergence oracle must flag it."""
    rep = shardkv_fuzz(RAFT, SKV.replace(bug_drop_dup_table=True, p_retry=0.8),
                       seed=5, n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating > 0
    assert np.all(
        rep.violations[rep.violating_clusters()] & VIOLATION_SHARD_DIVERGE
    )


def test_shardkv_skip_freeze_oracle_fires():
    """Serving a surrendered shard (freeze bug): the nodes' state diverges
    from the canonical walker."""
    rep = shardkv_fuzz(RAFT, SKV.replace(bug_skip_freeze=True), seed=5,
                       n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating > 0
    assert np.all(
        rep.violations[rep.violating_clusters()] & VIOLATION_SHARD_DIVERGE
    )


def test_shardkv_deterministic():
    """Same seed => bit-identical outcome with the full groups stack."""
    r1 = shardkv_fuzz(RAFT, SKV, seed=33, n_clusters=8, n_ticks=256)
    r2 = shardkv_fuzz(RAFT, SKV, seed=33, n_clusters=8, n_ticks=256)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)


def test_shardkv_sharded_over_mesh():
    """The deployment axis shards over the 8-device mesh with identical
    results (the dryrun_multichip path for the groups axis)."""
    devs = np.array(jax.devices()[:8])
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    import jax.numpy as jnp

    mesh = jax.sharding.Mesh(devs, ("clusters",))
    fn = make_shardkv_fuzz_fn(RAFT, SKV, n_clusters=16, n_ticks=128, mesh=mesh)
    rep_sharded = shardkv_report(jax.block_until_ready(fn(jnp.asarray(4, jnp.uint32))))
    rep_local = shardkv_fuzz(RAFT, SKV, seed=4, n_clusters=16, n_ticks=128)
    np.testing.assert_array_equal(rep_sharded.violations, rep_local.violations)
    np.testing.assert_array_equal(rep_sharded.acked_ops, rep_local.acked_ops)
    np.testing.assert_array_equal(rep_sharded.installs, rep_local.installs)
