"""Multi-group sharded-KV fuzzing on TPU (Lab 4B, the groups axis):
migration exactly-once, ownership exclusivity, shard GC (challenge 1),
serving through reconfiguration (challenge 2), oracle validation via bug
injection, and determinism. The reference scenarios these batch:
/root/reference/src/shardkv/tests.rs:70-362 (join/leave + concurrent +
crash storms), 438-493 (challenge 1), 499-605 (challenge 2).

Runs on the virtual CPU device mesh from conftest.py.
"""

import functools

import jax
import numpy as np
import pytest

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.shardkv import (
    OWNED,
    ShardKvConfig,
    VIOLATION_SHARD_DIVERGE,
    VIOLATION_SHARD_STALE_READ,
    init_shardkv_cluster,
    make_shardkv_fuzz_fn,
    shardkv_fuzz,
    shardkv_report,
)

# XLA on this container SEGFAULTS compiling/serializing this module's big
# shardkv programs — but only deep into a long pytest process that has
# already compiled 100+ other programs (reproduced 6x in round 5, crash sites
# wandering between put_executable_and_time and backend_compile_and_load;
# standalone module runs always pass). Two mitigations, both module-scoped:
# skip persistent-cache WRITES (serialize is one crash site), and CLEAR the
# in-process executable caches once before the module. Since the round-6
# conftest reorder this module runs FIRST in full-suite runs (young process,
# outside the accumulation zone — the clear is then a no-op), but the
# defenses stay: standalone invocations like `pytest tests/` subsets or
# MADRAFT_TPU_TESTS=1 runs don't go through the reorder guarantee alone.
@pytest.fixture(autouse=True, scope="module")
def _fresh_xla_state_for_big_programs():
    import contextlib
    import os as _os

    import jax as _jax

    _jax.clear_caches()
    from conftest import no_persistent_cache

    # MADTPU_SHARDKV_CACHE_WRITE=1: allow persistent-cache writes anyway.
    # Safe whenever this module compiles on a YOUNG process (the crash
    # trigger needs 100+ prior programs): standalone runs
    #   MADTPU_SHARDKV_CACHE_WRITE=1 pytest tests/test_tpusim_shardkv.py
    # trivially qualify, and full-suite runs qualify via the conftest
    # reorder that puts this module first — ci.sh and the GitHub workflow
    # both set the var so .jax_cache gains these executables and later runs
    # DESERIALIZE them (cache reads are unaffected by this fixture),
    # skipping both crash sites (serialize and backend_compile) and the
    # several minutes of per-run shardkv compile. Default (var unset) still
    # suppresses writes: subset runs like `pytest tests/test_*.py k...`
    # don't get the reorder's young-process guarantee.
    guard = (contextlib.nullcontext()
             if _os.environ.get("MADTPU_SHARDKV_CACHE_WRITE") == "1"
             else no_persistent_cache())
    with guard:
        yield


# 3 groups x 3 nodes; configs stop changing by ~tick 300, the tail quiesces.
RAFT = SimConfig(
    n_nodes=3,
    p_client_cmd=0.0,
    compact_at_commit=False,
    log_cap=64,
    compact_every=16,
    loss_prob=0.05,
)
SKV = ShardKvConfig()
TICKS = 640  # n_configs * ~cfg_interval + quiesce tail (multi-shard configs)


def test_shardkv_schedule_is_join_leave():
    """The config schedule is Join/Leave churn: configs move SEVERAL shards
    at once between several group pairs, and every config is balanced over
    its member set (max - min <= 1) with minimal moves — the 4A semantics as
    data (shard_ctrler/tester.rs:134-150)."""
    st = jax.jit(functools.partial(init_shardkv_cluster, RAFT, SKV))(
        jax.random.PRNGKey(11)
    )
    own = np.asarray(st.cfg_owner)  # [NCFG, NS]
    moves = (own[1:] != own[:-1]).sum(axis=1)
    assert (moves >= 2).any(), f"multi-shard configs expected, moves={moves}"
    for i in range(own.shape[0]):
        counts = np.bincount(own[i], minlength=SKV.n_groups)
        members = counts > 0
        assert counts[members].max() - counts[members].min() <= 1, (
            f"config {i} unbalanced: {counts}"
        )
        if i > 0:
            # minimality, exactly: every move must reduce some group's
            # deficit, so #moves == sum of per-group gains. Swaps or
            # gratuitous reshuffles strictly exceed this.
            old_counts = np.bincount(own[i - 1], minlength=SKV.n_groups)
            min_moves = np.maximum(0, counts - old_counts).sum()
            assert moves[i - 1] == min_moves, (
                f"config {i}: {moves[i - 1]} moves but the distribution "
                f"change needs only {min_moves} — non-minimal rebalance"
            )


def test_shardkv_migration_clean():
    """Reconfiguration churn with no faults: zero violations, ops flow, every
    migration completes and every surrendered copy is GC'd (challenge 1)."""
    # 16 deployments: deterministic per (seed, shape); measured 178 installs
    # and min 28 acked ops at this size — margin intact at 2/3 the wall
    rep = shardkv_fuzz(RAFT, SKV, seed=5, n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.acked_ops > 20).all()
    assert (rep.acked_gets > 0).all(), "the read path must see traffic"
    assert rep.installs.sum() > 66, "multi-shard churn must migrate a lot"
    # challenge 1 at quiesce: every frozen copy was deleted, one owner/shard
    assert (rep.deletes == rep.installs).mean() > 0.85
    assert (rep.frozen_left == 0).mean() > 0.85
    assert (rep.owned_copies == 1).all()
    # the schedule was actually consumed
    assert (rep.final_cfg >= SKV.n_configs - 2).mean() > 0.8


def test_shardkv_serves_during_migration():
    """Challenge 2: ops on unaffected shards keep completing while other
    shards migrate — acks accrue across the whole run, not just between
    configs. (Weak-form liveness check: total acks far exceed what a
    stop-the-world implementation could commit in the gaps.)"""
    rep = shardkv_fuzz(RAFT, SKV.replace(p_op=0.8, p_retry=0.8), seed=9,
                       n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating == 0
    # every deployment keeps completing ops throughout ~5 multi-shard
    # reconfigurations; a stop-the-world implementation would flatline during
    # each migration. (Per-deployment floor is loose — multi-shard configs
    # make migration windows long and trajectories vary per seed — the
    # aggregate bound carries the real weight.)
    assert (rep.acked_ops > 15).all()
    assert rep.acked_ops.sum() > 16 * 45


def test_shardkv_fault_storm():
    """Crashes + message loss racing reconfiguration (concurrent1/2/3_4b):
    safety holds; migrations still complete."""
    storm = RAFT.replace(p_crash=0.01, p_restart=0.2, max_dead=1, loss_prob=0.1)
    rep = shardkv_fuzz(storm, SKV, seed=2, n_clusters=24, n_ticks=TICKS)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} raft "
        f"{rep.raft_violations[rep.violating_clusters()[:8]]}"
    )
    assert rep.installs.sum() > 24
    assert (rep.acked_ops > 0).all()


def test_shardkv_live_ctrler_clean():
    """Configs come from an ON-DEVICE replicated controller raft cluster
    (the reference's servers poll the ctrler via a ctrl-plane clerk,
    shardkv/server.rs:12-18): the ANNOUNCE stream rides the ctrler's raft
    under a fault storm, groups learn configs via racing Query reads over
    lossy mailboxes, and the truth-vs-phantom announce race resolves by
    commit order. All existing oracles plus CTRL_STALE must stay green,
    announces must resolve, and migrations must still chain through."""
    storm = RAFT.replace(
        p_crash=0.01, p_restart=0.2, max_dead=1, loss_prob=0.1,
        p_repartition=0.03, p_heal=0.08,
    )
    kcfg = SKV.replace(live_ctrler=True, p_phantom=0.4, cfg_interval=40)
    rep = shardkv_fuzz(storm, kcfg, seed=3, n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} raft "
        f"{rep.raft_violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.ann_resolved >= 2).mean() > 0.8, (
        f"the live controller barely committed announces: {rep.ann_resolved}"
    )
    assert rep.installs.sum() > 16, "migrations must flow from live configs"
    assert (rep.final_cfg >= 1).mean() > 0.8, (
        f"groups barely adopted live configs: {rep.final_cfg}"
    )


def test_shardkv_live_ctrler_stale_read_bug_caught():
    """bug_stale_ctrler_read: a queried ctrler node answers from its raw log
    tail, where a phantom announce (the losing order of racing proposals)
    may sit until raft rolls it back — a group can adopt a config the
    controller never committed. The CTRL_STALE oracle must flag it; the
    same storm without the bug is covered clean above."""
    from madraft_tpu.tpusim.shardkv import VIOLATION_SHARD_CTRL_STALE

    storm = RAFT.replace(
        p_crash=0.02, p_restart=0.2, max_dead=1, loss_prob=0.15,
        p_repartition=0.05, p_heal=0.08,
    )
    kcfg = SKV.replace(
        live_ctrler=True, bug_stale_ctrler_read=True, p_phantom=0.5,
        cfg_interval=40,
    )
    rep = shardkv_fuzz(storm, kcfg, seed=5, n_clusters=16, n_ticks=512)
    stale = (rep.violations & VIOLATION_SHARD_CTRL_STALE) != 0
    assert stale.any(), (
        "no deployment adopted a never-committed config — the planted "
        "stale-ctrler-read bug never manifested or the oracle is inert"
    )


def test_shardkv_missed_configs_catch_up():
    """miss_change_4b: nodes sleep through SEVERAL config activations (slow
    restarts, fast config churn) and catch up by log replay / snapshot
    install — safety holds and the lag metric proves the scenario ran."""
    storm = RAFT.replace(p_crash=0.02, p_restart=0.03, max_dead=1,
                         loss_prob=0.1)
    rep = shardkv_fuzz(storm, SKV.replace(cfg_interval=40), seed=2,
                       n_clusters=24, n_ticks=700)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} raft "
        f"{rep.raft_violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.max_cfg_lag >= 2).mean() > 0.5, (
        f"nodes must actually miss >= 2 configs; lags {rep.max_cfg_lag}"
    )
    assert rep.installs.sum() > 100
    assert (rep.acked_ops > 0).all()


def test_shardkv_gc_completes_under_storm():
    """Round-3 regression (soak-found): push-style install acks were retried
    only while the new owner stayed in its gain config, so a crash/loss storm
    could leak a frozen copy forever and deadlock every later config that
    re-gained the shard (the regain gate). GC is now pull-driven — the FROZEN
    holder polls the gain-config owner and deletes on confirmation — so a
    LONG schedule under a storm must complete: every deployment near the
    final config, installs == deletes, (almost) no frozen copies left."""
    storm = RAFT.replace(p_crash=0.01, p_restart=0.2, max_dead=1,
                         loss_prob=0.1)
    kcfg = SKV.replace(n_configs=16, cfg_interval=70)
    # 16 configs * 70-tick interval = the schedule ends by ~1120; the tail
    # gives in-flight migrations time to drain (the cutoff is otherwise
    # draw-sensitive: 1800 ticks left ~1 frozen copy per deployment pending)
    rep = shardkv_fuzz(storm, kcfg, seed=424, n_clusters=12, n_ticks=2400)
    assert rep.n_violating == 0
    assert (rep.final_cfg >= kcfg.n_configs - 2).all(), (
        f"schedule stalled: final configs {np.sort(rep.final_cfg)}"
    )
    # GC keeps up with installs: a handful of migrations may be mid-flight
    # at the cutoff (no quiesce tail), but a LEAK accumulates dozens
    lag = rep.installs - rep.deletes
    assert (lag >= 0).all() and (lag <= kcfg.n_shards).all(), (
        f"GC lag per deployment: {lag}"
    )
    assert rep.frozen_left.sum() <= kcfg.n_shards, (
        f"frozen copies leaked: {rep.frozen_left.sum()}"
    )


def test_shardkv_serve_frozen_oracle_fires():
    """A server that skips the ownership check for reads (serving Gets from a
    surrendered FROZEN copy / a GC'd shard) must trip the per-shard interval
    oracle — the sharded stale-read analogue of kv.py's bug_stale_read."""
    rep = shardkv_fuzz(
        RAFT, SKV.replace(bug_serve_frozen=True, p_get=0.5, p_cfg_learn=0.15),
        seed=5, n_clusters=16, n_ticks=560,
    )
    assert rep.n_violating > 0
    assert np.all(
        rep.violations[rep.violating_clusters()] & VIOLATION_SHARD_STALE_READ
    )


def test_shardkv_dup_migration_oracle_fires():
    """Dropping the dup table at install (exactly-once-across-migration bug):
    a clerk retry that lands after the shard moved double-applies, and the
    truth-walker divergence oracle must flag it."""
    rep = shardkv_fuzz(RAFT, SKV.replace(bug_drop_dup_table=True, p_retry=0.8),
                       seed=5, n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating > 0
    assert np.all(
        rep.violations[rep.violating_clusters()] & VIOLATION_SHARD_DIVERGE
    )


def test_shardkv_skip_freeze_oracle_fires():
    """Serving a surrendered shard (freeze bug): the nodes' state diverges
    from the canonical walker."""
    rep = shardkv_fuzz(RAFT, SKV.replace(bug_skip_freeze=True), seed=5,
                       n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating > 0
    assert np.all(
        rep.violations[rep.violating_clusters()] & VIOLATION_SHARD_DIVERGE
    )


def test_shardkv_deterministic():
    """Same seed => bit-identical outcome with the full groups stack."""
    r1 = shardkv_fuzz(RAFT, SKV, seed=33, n_clusters=8, n_ticks=256)
    r2 = shardkv_fuzz(RAFT, SKV, seed=33, n_clusters=8, n_ticks=256)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)


def test_shardkv_sharded_over_mesh():
    """The deployment axis shards over the virtual device mesh with identical
    results (the dryrun_multichip path for the groups axis)."""
    from conftest import cluster_mesh

    mesh = cluster_mesh(16)
    import jax.numpy as jnp

    fn = make_shardkv_fuzz_fn(RAFT, SKV, n_clusters=16, n_ticks=128, mesh=mesh)
    rep_sharded = shardkv_report(
        jax.block_until_ready(fn(jnp.asarray(4, jnp.uint32)))
    )
    rep_local = shardkv_fuzz(RAFT, SKV, seed=4, n_clusters=16, n_ticks=128)
    np.testing.assert_array_equal(rep_sharded.violations, rep_local.violations)
    np.testing.assert_array_equal(rep_sharded.acked_ops, rep_local.acked_ops)
    np.testing.assert_array_equal(rep_sharded.installs, rep_local.installs)

    # the computed-controller program must be sharding-invariant too (its
    # walker/maps state rides the same per-deployment axis)
    ckcfg = SKV.replace(computed_ctrler=True, cfg_interval=40)
    cfn = make_shardkv_fuzz_fn(RAFT, ckcfg, n_clusters=16, n_ticks=128,
                               mesh=mesh)
    crep_sharded = shardkv_report(
        jax.block_until_ready(cfn(jnp.asarray(4, jnp.uint32)))
    )
    crep_local = shardkv_fuzz(RAFT, ckcfg, seed=4, n_clusters=16, n_ticks=128)
    for a, b in zip(crep_sharded, crep_local):
        np.testing.assert_array_equal(a, b)


def test_shardkv_with_puts_clean():
    """The full reference op set Op::{Get,Put,Append} across migration: Puts
    mutate like Appends on the monotone version model, so every oracle —
    walker divergence, ownership, GC bound, reads-linearizability across the
    shard's migration chain — stays exact. Zero violations; all kinds flow."""
    rep = shardkv_fuzz(RAFT, SKV.replace(p_get=0.3, p_put=0.3), seed=31,
                       n_clusters=16, n_ticks=TICKS)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.acked_ops > 15).all()
    assert (rep.acked_gets > 0).all()
    assert rep.installs.sum() > 60


def test_shardkv_serve_frozen_oracle_fires_with_puts():
    """The serve-from-frozen bug stays visible with Puts in the mix."""
    rep = shardkv_fuzz(
        RAFT, SKV.replace(bug_serve_frozen=True, p_get=0.4, p_put=0.3,
                          p_cfg_learn=0.15),
        seed=5, n_clusters=16, n_ticks=560,
    )
    assert rep.n_violating > 0
    assert (
        rep.violations[rep.violating_clusters()] & VIOLATION_SHARD_STALE_READ
    ).any()


def test_shardkv_sweep_per_deployment_knobs_and_bugs():
    """The knob split landed on the sharded layer too: a uniform-valued
    sweep reproduces the uniform program bit-for-bit, and a per-deployment
    bug axis (drop_dup_table in the first half) lands every violation in
    that half — migration cadence, workload, and bugs as data."""
    import jax.numpy as jnp

    from madraft_tpu.tpusim.shardkv import (
        VIOLATION_SHARD_DIVERGE,
        make_shardkv_sweep_fn,
        shardkv_report,
    )

    n, ticks = 12, 900
    kcfg = SKV.replace(p_retry=0.8, n_configs=10, cfg_interval=70)
    fn = make_shardkv_sweep_fn(RAFT, RAFT.knobs(), kcfg.knobs(), kcfg, n,
                               ticks)
    rep_sweep = shardkv_report(
        jax.block_until_ready(fn(jnp.asarray(5, jnp.uint32)))
    )
    rep_uni = shardkv_fuzz(RAFT, kcfg, seed=5, n_clusters=n, n_ticks=ticks)
    for a, b in zip(rep_sweep, rep_uni):
        np.testing.assert_array_equal(a, b)

    half = jnp.arange(n) < n // 2
    skn = kcfg.knobs()._replace(bug_drop_dup_table=half)
    fn = make_shardkv_sweep_fn(RAFT, RAFT.knobs(), skn, kcfg, n, ticks)
    rep = shardkv_report(jax.block_until_ready(fn(jnp.asarray(5, jnp.uint32))))
    bugged = np.asarray(half)
    viol = (rep.violations | rep.raft_violations) != 0
    assert viol[bugged].any(), "bugged half produced no migration violation"
    assert (rep.violations[bugged & viol] & VIOLATION_SHARD_DIVERGE).any()
    assert not viol[~bugged].any(), (
        f"clean half flagged: {rep.violations[~bugged & viol]}"
    )


# ------------------------------------------------ computed controller (4A∘4B)
def test_shardkv_computed_ctrler_clean():
    """The controller cluster's apply machine IS the 4A state machine
    (/root/reference/src/shard_ctrler/server.rs:16-18 + shardkv/server.rs:
    12-18): membership FLIP ops ride the controller raft under the storm,
    config content is COMPUTED at walk time by the shared 4A closed-form
    rebalance (ctrler.py _rebalance), and groups adopt whatever committed.
    All oracles green; slots resolve; migrations chain through computed
    configs; every computed config is balanced over its owners."""
    storm = RAFT.replace(
        p_crash=0.01, p_restart=0.2, max_dead=1, loss_prob=0.1,
        p_repartition=0.03, p_heal=0.08,
    )
    kcfg = SKV.replace(computed_ctrler=True, p_phantom=0.4, cfg_interval=40)
    import jax.numpy as jnp

    fn = make_shardkv_fuzz_fn(storm, kcfg, n_clusters=24, n_ticks=TICKS)
    final = jax.block_until_ready(fn(jnp.asarray(3, jnp.uint32)))
    rep = shardkv_report(final)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} raft "
        f"{rep.raft_violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.ann_resolved >= 2).mean() > 0.8, (
        f"the computed controller barely committed flips: {rep.ann_resolved}"
    )
    assert rep.installs.sum() > 24, "migrations must flow from computed configs"
    assert (rep.final_cfg >= 1).mean() > 0.8, (
        f"groups barely adopted computed configs: {rep.final_cfg}"
    )
    # the committed flip of every resolved slot is one of the two racing
    # proposals, and ACROSS the batch the phantom sometimes won — committed
    # ORDER, not the pre-drawn schedule, decided config content
    win = np.asarray(final.win_var)    # [D, NCFG]
    fa = np.asarray(final.flip_a)
    fb = np.asarray(final.flip_b)
    resolved = win >= 0
    resolved[:, 0] = False  # slot 0 is the fixed initial config
    assert ((win == fa) | (win == fb))[resolved].all()
    assert (win == fb)[resolved].any(), (
        "the phantom proposal never won a slot — the announce race is inert"
    )
    # every computed config is balanced over the groups that own shards
    own = np.asarray(final.cfg_owner)  # [D, NCFG, NS]
    for d in range(own.shape[0]):
        for j in range(1, own.shape[1]):
            if not resolved[d, j]:
                continue
            counts = np.bincount(own[d, j], minlength=kcfg.n_groups)
            owners = counts > 0
            assert counts[owners].max() - counts[owners].min() <= 1, (
                f"deployment {d} config {j} unbalanced: {counts}"
            )


def test_shardkv_computed_rotate_bug_propagates_to_4b():
    """The composite 4A->4B bug: bug_rotate_tiebreak rotates each controller
    REPLICA's deficit-fill order (the HashMap-iteration-order classic the
    reference README bans), so replicas compute divergent owner maps from
    the same committed ops. A group adopts the map of whichever replica
    answered its query — the walker's adopted-vs-canonical check
    (VIOLATION_SHARD_CTRL_STALE) must fire, and the divergence must also
    manifest BEHAVIORALLY as two groups owning one shard
    (VIOLATION_SHARD_OWNERSHIP) somewhere in the batch."""
    from madraft_tpu.tpusim.shardkv import (
        VIOLATION_SHARD_CTRL_STALE,
        VIOLATION_SHARD_OWNERSHIP,
    )

    kcfg = SKV.replace(
        computed_ctrler=True, bug_rotate_tiebreak=True, cfg_interval=40,
    )
    rep = shardkv_fuzz(RAFT, kcfg, seed=7, n_clusters=24, n_ticks=512)
    stale = (rep.violations & VIOLATION_SHARD_CTRL_STALE) != 0
    owned2 = (rep.violations & VIOLATION_SHARD_OWNERSHIP) != 0
    assert stale.any(), (
        "no group adopted a rotated replica's map — the composite bug "
        "never manifested or the adopted-vs-canonical oracle is inert"
    )
    assert owned2.any(), (
        "the rebalance divergence never propagated into migration behavior "
        "(no dual ownership) — the composite propagation path is inert"
    )


def test_shardkv_computed_ctrler_deterministic():
    """Same seed => bit-identical outcome with the computed controller."""
    kcfg = SKV.replace(computed_ctrler=True, cfg_interval=40)
    r1 = shardkv_fuzz(RAFT, kcfg, seed=33, n_clusters=8, n_ticks=256)
    r2 = shardkv_fuzz(RAFT, kcfg, seed=33, n_clusters=8, n_ticks=256)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)


def test_shardkv_computed_ctrler_config_guards():
    """Misconfigurations fail eagerly, not silently: the rotate bug without
    the computed controller (would no-op and read as an oracle failure),
    both controller modes at once, and the live-mode stale-read bug under
    the computed controller."""
    with pytest.raises(ValueError, match="computed_ctrler"):
        ShardKvConfig(bug_rotate_tiebreak=True)
    with pytest.raises(ValueError, match="one"):
        ShardKvConfig(computed_ctrler=True, live_ctrler=True)
    with pytest.raises(ValueError, match="stale_ctrler_read"):
        ShardKvConfig(computed_ctrler=True, bug_stale_ctrler_read=True)
    # the flip_b "always a DIFFERENT gid" invariant degenerates with one
    # group (ADVICE round-5 finding #4)
    with pytest.raises(ValueError, match="n_groups"):
        ShardKvConfig(computed_ctrler=True, n_groups=1)
    from madraft_tpu.tpusim.shardkv import make_shardkv_sweep_fn

    kcfg = SKV.replace(cfg_interval=40)
    import jax.numpy as jnp

    with pytest.raises(ValueError, match="computed_ctrler"):
        make_shardkv_sweep_fn(
            RAFT, RAFT.knobs(),
            kcfg.knobs()._replace(bug_rotate_tiebreak=jnp.bool_(True)),
            kcfg, 4, 64,
        )


def test_shardkv_wrong_group_requery_helps_and_stays_safe():
    """WrongGroup re-query (client.rs:16-25) as an opt-in knob: a clerk whose
    submit reached an alive leader that does not serve the shard re-learns
    the config next tick. Measured (MIGRATION.md): the effect is real but
    marginal (+1-5% acked) because migration latency dominates the stall —
    this test pins that it (a) actually changes behavior, (b) never hurts
    beyond noise, and (c) leaves every safety oracle green.

    Liveness bar: 0.90, with documented headroom. The comparison is ONE
    deterministic 16-cluster sample, so the measured ratio is a draw from
    the seed distribution, not its mean — and it shifts with jax-version
    numeric drift (per-tick f32 draws reorder which clerk ops land where).
    Measured acked-sum ratios for this (seed=9, 16x640) point: 0.95+ on the
    jax the original 0.95 bar was tuned on, 0.942 (1489 vs 1580) on the
    current container (re-verified deterministic across runs at the seed
    commit — a pre-existing environment drift, not a code regression). The
    bar guards against the mark/re-learn path actively WASTING clerk
    budget (re-query loops would cost tens of percent), not against
    single-digit draw reshuffles; 0.90 keeps that failure mode caught
    while absorbing per-version noise. Re-measure before tightening: a
    sharper bar needs a bigger batch, and a fresh 512-cluster shardkv
    program costs minutes of compile this suite's budget cannot carry."""
    cfg = RAFT
    base = SKV.replace(p_cfg_learn=0.05, cfg_interval=50)
    r_off = shardkv_fuzz(cfg, base, seed=9, n_clusters=16, n_ticks=TICKS)
    r_on = shardkv_fuzz(cfg, base.replace(requery_wrong_group=True), seed=9,
                        n_clusters=16, n_ticks=TICKS)
    assert r_off.n_violating == 0 and r_on.n_violating == 0
    assert (r_on.acked_ops != r_off.acked_ops).any(), (
        "requery_wrong_group changed nothing — the WrongGroup mark/re-learn "
        "path is inert"
    )
    assert r_on.acked_ops.sum() >= 0.90 * r_off.acked_ops.sum(), (
        f"re-query must not cost liveness: {r_on.acked_ops.sum()} vs "
        f"{r_off.acked_ops.sum()}"
    )


def test_shardkv_computed_ctrler_long_chain_gc_completes():
    """The composed mode across a LONG computed chain: 16 configs computed
    from committed flips under a crash/loss storm, with the same
    GC-completion obligations as the schedule-tensor mode (the round-3
    soak-found-leak test, mirrored here for computed_ctrler) — every
    deployment near the end of the chain, installs ~= deletes, (almost) no
    frozen copies left, zero violations."""
    storm = RAFT.replace(p_crash=0.01, p_restart=0.2, max_dead=1,
                         loss_prob=0.1)
    kcfg = SKV.replace(computed_ctrler=True, n_configs=16, cfg_interval=70)
    rep = shardkv_fuzz(storm, kcfg, seed=424, n_clusters=12, n_ticks=2400)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} raft "
        f"{rep.raft_violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.ann_resolved >= kcfg.n_configs - 3).all(), (
        f"computed chain stalled: slots {np.sort(rep.ann_resolved)}"
    )
    assert (rep.final_cfg >= kcfg.n_configs - 3).all(), (
        f"adoption stalled: final configs {np.sort(rep.final_cfg)}"
    )
    lag = rep.installs - rep.deletes
    assert (lag >= 0).all() and (lag <= kcfg.n_shards).all(), (
        f"GC lag per deployment: {lag}"
    )
    assert rep.frozen_left.sum() <= kcfg.n_shards, (
        f"frozen copies leaked: {rep.frozen_left.sum()}"
    )
