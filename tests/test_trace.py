"""Flight recorder (ISSUE 2): traced replay is bit-identical to the untraced
replayer, the decoded event timeline names violations at the right tick, the
Perfetto export is well-formed, the shared violation-name table cannot drift
from the layer constants, the fixed-seed fuzz report matches the pre-PR
golden (hot-path guard), and the C++ per-tick trace export matches the TPU
trace's schedule-determined signals exactly."""

import contextlib
import io
import json
import pathlib

import numpy as np
import pytest

from madraft_tpu.__main__ import main
from madraft_tpu.tpusim.config import (
    VIOLATION_NAMES,
    storm_profiles,
    violation_names,
)
from madraft_tpu.tpusim.engine import replay_cluster
from madraft_tpu.tpusim.lint import golden_guard_legs
from madraft_tpu.tpusim.trace import (
    alive_masks,
    chrome_trace,
    decode_events,
    events_in_window,
    replay_cluster_traced,
)

ROOT = pathlib.Path(__file__).resolve().parent

_PROFILES = storm_profiles()
STORM = _PROFILES["storm"][0]
DURABILITY = _PROFILES["durability"][0]
# the violating (seed, cluster) comes FROM the golden file (durability storm
# + ack_before_fsync at 64 x 300 -> cluster 49 trips COMMIT_SHADOW at tick
# 157 today) so a deliberate golden regeneration cannot strand stale
# coordinates here
_GOLDEN = json.loads((ROOT / "golden_fuzz.json").read_text())
# The guard legs come from the ProgramRegistry (ISSUE 15), not a hand list:
# every registry entry tagged with a golden_leg is guarded here, so a new
# program family cannot silently dodge the golden guards. A fuzz leg pins a
# one-shot "report", a pool leg the pool run's "summary" (the golden file's
# own shape says which); the completeness check below fails collection-time
# if a registry leg has no golden entry or vice versa.
_GUARD_LEGS = golden_guard_legs()
assert set(_GUARD_LEGS) == {k for k in _GOLDEN if k != "_comment"}, (
    "registry golden legs and golden_fuzz.json drifted apart: "
    f"{sorted(_GUARD_LEGS)} vs {sorted(k for k in _GOLDEN if k != '_comment')}"
)
_FUZZ_LEGS = sorted(leg for leg in _GUARD_LEGS if "report" in _GOLDEN[leg])
_POOL_LEGS = sorted(leg for leg in _GUARD_LEGS if "summary" in _GOLDEN[leg])
assert sorted(_FUZZ_LEGS + _POOL_LEGS) == sorted(_GUARD_LEGS)
BUG_CFG = DURABILITY.replace(bug="ack_before_fsync")
_bug_argv = _GOLDEN["bug"]["argv"]
BUG_SEED = int(_bug_argv[_bug_argv.index("--seed") + 1])
BUG_TICKS = int(_bug_argv[_bug_argv.index("--ticks") + 1])
BUG_CLUSTER = _GOLDEN["bug"]["report"]["violating_clusters"][0]


def run_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    lines = [ln for ln in buf.getvalue().strip().splitlines() if ln]
    return rc, [json.loads(ln) for ln in lines]


def _assert_final_identical(cfg, seed, cluster, ticks):
    final, _ = replay_cluster_traced(cfg, seed, cluster, ticks)
    st = replay_cluster(cfg, seed, cluster, ticks)
    for f in st._fields:
        assert np.array_equal(
            np.asarray(getattr(st, f)), np.asarray(getattr(final, f))
        ), f"traced replay diverged from replay_cluster on {f!r}"


def test_traced_replay_bit_identical_storm():
    # tracing must be a pure observer: same step, same PRNG stream, same
    # final state — for the plain storm profile...
    _assert_final_identical(STORM, 7, 3, 300)


def test_traced_replay_bit_identical_durability_bug():
    # ...and for the durability storm with the planted bug (the suffix-loss
    # rollback path exercises every watermark interaction)
    _assert_final_identical(BUG_CFG, BUG_SEED, BUG_CLUSTER, BUG_TICKS)


def test_per_type_delivery_counts_are_exact():
    # the per-type delivered counts are derived, not instrumented — their
    # sum must equal the step function's own msg_count delta at EVERY tick
    _, rec = replay_cluster_traced(STORM, 7, 3, 300)
    per_type = (rec.rv_req_delivered + rec.rv_rsp_delivered
                + rec.ae_req_delivered + rec.ae_rsp_delivered
                + rec.snap_delivered)
    deltas = np.diff(np.concatenate([[0], rec.msg_count]))
    assert np.array_equal(per_type, deltas)
    assert int(rec.msg_count[-1]) > 0, "storm delivered nothing"


def test_decoded_timeline_names_the_violation():
    final, rec = replay_cluster_traced(BUG_CFG, BUG_SEED, BUG_CLUSTER,
                                       BUG_TICKS)
    st = replay_cluster(BUG_CFG, BUG_SEED, BUG_CLUSTER, BUG_TICKS)
    fvt = int(st.first_violation_tick)
    assert fvt >= 0
    events = decode_events(rec)
    viol = [e for e in events if e["event"] == "violation"]
    assert viol and viol[0]["first"] is True
    assert viol[0]["tick"] == fvt, (
        "decoded violation tick must equal the untraced replay's"
    )
    assert set(viol[0]["names"]) & {"COMMIT_SHADOW", "PREFIX_DIVERGE"}
    # the durability storm's signature event must be visible near the
    # violation: a crash that dropped an un-fsynced suffix
    near = events_in_window(events, fvt, 20)
    assert any(e["event"] == "crash" and e.get("lost_suffix", 0) > 0
               for e in near), "no suffix-loss crash decoded near the violation"
    # windowing keeps the violation itself even for a tiny window
    tiny = events_in_window(events, fvt, 1)
    assert any(e["event"] == "violation" for e in tiny)


def test_explain_cli_jsonl_matches_untraced_replay():
    argv = ["--profile", "durability", "--bug", "ack_before_fsync",
            "--seed", str(BUG_SEED), "--cluster", str(BUG_CLUSTER),
            "--ticks", str(BUG_TICKS)]
    rc, out = run_cli(["explain", *argv, "--window", "25"])
    header, events = out[0], out[1:]
    # explain is a debugging tool: exit 0 whenever the replay ran
    assert rc == 0
    assert header["violations"] != 0
    assert set(header["violation_names"]) & {"COMMIT_SHADOW",
                                             "PREFIX_DIVERGE"}
    assert events, "the timeline must be non-empty"
    rc_r, out_r = run_cli(["replay", *argv])
    assert rc_r == 1
    assert header["first_violation_tick"] == out_r[0]["first_violation_tick"]
    assert header["violations"] == out_r[0]["violations"]
    assert out_r[0]["violation_names"] == header["violation_names"]


def test_explain_cli_chrome_export(tmp_path):
    out_file = tmp_path / "trace.json"
    rc, out = run_cli([
        "explain", "--profile", "durability", "--bug", "ack_before_fsync",
        "--seed", str(BUG_SEED), "--cluster", str(BUG_CLUSTER),
        "--ticks", str(BUG_TICKS), "--format", "chrome",
        "--out", str(out_file),
    ])
    assert rc == 0 and out[0]["trace_events"] > 0
    doc = json.loads(out_file.read_text())
    evs = doc["traceEvents"]
    assert len(evs) == out[0]["trace_events"]
    # well-formed trace-event JSON: every event has a phase + pid; complete
    # ("X") events carry ts/dur/tid; one role-span track per node exists
    assert all("ph" in e and "pid" in e for e in evs)
    spans = [e for e in evs if e["ph"] == "X"]
    assert spans and all(
        {"name", "ts", "dur", "tid"} <= set(e) for e in spans
    )
    assert {e["tid"] for e in spans} == set(range(BUG_CFG.n_nodes))
    assert any(e["name"].startswith("leader") for e in spans)
    assert any(e["ph"] == "i" and e["name"] == "violation" for e in evs)


def test_violation_name_table_matches_layer_constants():
    # config.py duplicates the service-layer bit names by value (it cannot
    # import the layers back); this is the drift guard the table's comment
    # promises
    import madraft_tpu.tpusim.config as config_mod
    import madraft_tpu.tpusim.ctrler as ctrler_mod
    import madraft_tpu.tpusim.kv as kv_mod
    import madraft_tpu.tpusim.shardkv as shardkv_mod

    seen = {}
    for mod in (config_mod, kv_mod, shardkv_mod, ctrler_mod):
        for name in dir(mod):
            if name.startswith("VIOLATION_") and name != "VIOLATION_NAMES":
                bit = getattr(mod, name)
                assert bit in VIOLATION_NAMES, (
                    f"{name} ({bit}) missing from config.VIOLATION_NAMES"
                )
                assert VIOLATION_NAMES[bit] == name[len("VIOLATION_"):], (
                    f"table name for bit {bit} drifted from {name}"
                )
                seen[bit] = name
    assert len(seen) == len(VIOLATION_NAMES), (
        "table carries bits no layer defines"
    )
    # decoder basics: order, multi-bit masks, unknown-bit fallback
    assert violation_names(0) == []
    assert violation_names(4 | 512) == ["COMMIT_SHADOW", "PREFIX_DIVERGE"]
    assert violation_names(1 << 20) == ["BIT20"]


@pytest.mark.parametrize("leg", _FUZZ_LEGS)
def test_fuzz_report_matches_golden(leg):
    # The hot-path guard: the fixed-seed fuzz REPORT values recorded before
    # this PR must be reproduced bit-identically (tracing/telemetry added
    # zero hot-path cost and no draw-layout change). telemetry (wall times)
    # is the one legitimately nondeterministic key — golden has none.
    rc, out = run_cli(_GOLDEN[leg]["argv"])
    live = out[0]
    for key, want in _GOLDEN[leg]["report"].items():
        assert live[key] == want, (
            f"{leg}: fuzz report field {key!r} drifted: "
            f"{live[key]!r} != golden {want!r}"
        )


@pytest.mark.parametrize("leg", _POOL_LEGS)
def test_pool_summary_matches_golden(leg):
    # The pool-path extension of the golden guard (PR 6): the fixed-seed
    # pool run's deterministic summary fields must stay bit-identical —
    # proof that the coverage subsystem's separate programs left the
    # coverage-OFF chunk/harvest/refill path (HLO and output) unchanged.
    # Wall-clock keys are excluded by construction (the golden records only
    # deterministic fields).
    rc, out = run_cli(_GOLDEN[leg]["argv"])
    assert rc == 1, "the planted-bug pool leg must exit 1"
    summary = out[-1]
    for key, want in _GOLDEN[leg]["summary"].items():
        assert summary[key] == want, (
            f"pool summary field {key!r} drifted: "
            f"{summary[key]!r} != golden {want!r}"
        )


# ------------------------------------------------------- C++ bridge leg
def _simcore_or_skip():
    from madraft_tpu import simcore

    if not simcore.available():
        pytest.skip("libmadtpu.so not buildable here")
    return simcore


def test_cpp_trace_export_matches_tpu_alive_timeline():
    # The C++ flight-recorder leg: a traced in-process replay must export
    # one state row per tick, and its alive masks — the schedule-determined
    # signal — must equal the TPU trace's exactly.
    _simcore_or_skip()
    import dataclasses

    from madraft_tpu import bridge

    cfg = STORM
    n_ticks = 256
    sched = bridge.extract_schedule(cfg, seed=7, cluster_id=3,
                                    n_ticks=n_ticks)
    cpp = bridge.replay_on_simcore(dataclasses.replace(sched, trace=True))
    tr = cpp["trace"]
    assert len(tr["alive"]) == n_ticks
    assert len(tr["term"]) == n_ticks and len(tr["term"][0]) == cfg.n_nodes
    _, rec = replay_cluster_traced(cfg, 7, 3, n_ticks)
    assert [int(m) for m in alive_masks(rec)] == tr["alive"]
    # untraced replays must not pay for (or carry) the trace
    assert "trace" not in bridge.replay_on_simcore(sched)


def test_localize_divergence_reports_violation_onset():
    # The classes_match:false path: replay a TPU-found durability violation
    # against a C++ run with the bug STRIPPED (deterministically clean), and
    # the localizer must pin the divergence to the TPU's violation onset
    # with both sides' state snapshots attached.
    _simcore_or_skip()
    import dataclasses

    from madraft_tpu import bridge

    sched = bridge.extract_schedule(BUG_CFG, seed=BUG_SEED,
                                    cluster_id=BUG_CLUSTER, n_ticks=BUG_TICKS)
    assert sched.violations != 0
    stripped = dataclasses.replace(sched, bug="")
    div = bridge.localize_divergence(BUG_CFG, stripped, BUG_SEED,
                                     BUG_CLUSTER, BUG_TICKS)
    assert div["kind"] == "violation_onset"
    assert div["first_divergence_tick"] == sched.first_violation_tick
    assert div["tpu"]["tick"] == div["cpp"]["tick"]
    assert len(div["cpp"]["terms"]) == BUG_CFG.n_nodes


def test_localize_divergence_clean_run_has_no_divergence():
    _simcore_or_skip()
    from madraft_tpu import bridge

    cfg = STORM
    sched = bridge.extract_schedule(cfg, seed=7, cluster_id=3, n_ticks=256)
    assert sched.violations == 0
    div = bridge.localize_divergence(cfg, sched, 7, 3, 256)
    assert div["first_divergence_tick"] is None and div["kind"] is None
