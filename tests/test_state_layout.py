"""The packed cold-state schema (ISSUE 9): pack -> widen round-trip
bit-identity on randomized boundary-value ClusterStates across EVERY field,
derived widths pinned against config.packed_bounds (overflow at a configured
max is a test failure here, never silent wraparound at run time), layout-
blind trajectories (pool/replay/coverage bit-identical packed vs wide), the
packed fingerprint, the footprint telemetry, and the wide fallback gates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim import state as st
from madraft_tpu.tpusim.config import NOOP_CMD, metrics_dims, packed_bounds
from madraft_tpu.tpusim.engine import replay_cluster, run_pool

STORM = SimConfig(
    n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
    max_dead=2, p_repartition=0.02, p_heal=0.05,
)
VIOL = STORM.replace(majority_override=2)


def _rand_state(cfg: SimConfig, rng: np.random.Generator,
                boundary: bool = False) -> st.ClusterState:
    """A random wide ClusterState whose every field spans its CONFIGURED
    packed range — incl. the -1 sentinels, NOOP_CMD payloads, and (with
    ``boundary``) every bound's exact maximum, so the round-trip test fails
    loudly the day a width stops holding its declared bound."""
    n, cap = cfg.n_nodes, cfg.log_cap
    hb, evn, mcap, nph, reg = metrics_dims(cfg)
    b = packed_bounds(cfg)
    i32 = lambda x: jnp.asarray(x, jnp.int32)  # noqa: E731

    def ints(hi, shape=(), lo=0):
        if boundary:
            return i32(np.full(shape, hi, np.int64))
        return i32(rng.integers(lo, hi + 1, size=shape))

    def bools(shape):
        val = True if boundary else rng.integers(0, 2, size=shape).astype(bool)
        return jnp.asarray(np.broadcast_to(val, shape), jnp.bool_)

    tick = int(ints(b.tick))

    def stamp(shape):
        # a live mailbox slot is strictly in the future, within the u8 span
        rel = rng.integers(0, b.rel_stamp + 1, size=shape)
        if boundary:
            rel = np.full(shape, b.rel_stamp)
        return i32(np.where(rel > 0, tick + rel, 0))

    def cmds(shape):
        v = rng.integers(0, b.cmd + 1, size=shape)
        v = np.where(rng.random(shape) < 0.1, NOOP_CMD, v)
        if boundary:
            v = np.full(shape, b.cmd)
        return i32(v)

    def node_id(shape):
        return ints(n - 1, shape, lo=-1)

    def neg1_tick(shape):
        v = rng.integers(-1, b.tick + 1, size=shape)
        return i32(np.full(shape, b.tick) if boundary else v)

    return st.ClusterState(
        tick=i32(tick),
        term=ints(b.term, (n,)),
        voted_for=node_id((n,)),
        role=ints(2, (n,)),
        timer=ints(np.iinfo(np.uint16).max, (n,)),
        hb=ints(np.iinfo(np.uint16).max, (n,)),
        alive=bools((n,)),
        # gray-failure state (ISSUE 19): limp is 1..255 (u8, never 0 on a
        # live node), fsync_stall a u16 countdown
        limp=ints(np.iinfo(np.uint8).max, (n,), lo=1),
        fsync_stall=ints(np.iinfo(np.uint16).max, (n,)),
        log_term=ints(b.term, (n, cap)),
        log_val=cmds((n, cap)),
        log_len=ints(b.index, (n,)),
        base=ints(b.index, (n,)),
        snap_term=ints(b.term, (n,)),
        prefix_hash=i32(rng.integers(-(2**31), 2**31, size=(n,))),
        commit=ints(b.index, (n,)),
        durable_len=ints(b.index, (n,)),
        durable_term=ints(b.term, (n,)),
        durable_voted_for=node_id((n,)),
        compact_floor=ints(b.index, (n,)),
        votes=bools((n, n)),
        next_idx=ints(b.index, (n, n)),
        match_idx=ints(b.index, (n, n)),
        adj=bools((n, n)),
        rv_req_t=stamp((n, n)),
        rv_req_term=ints(b.term, (n, n)),
        rv_req_lli=ints(b.index, (n, n)),
        rv_req_llt=ints(b.term, (n, n)),
        rv_rsp_t=stamp((n, n)),
        rv_rsp_term=ints(b.term, (n, n)),
        rv_rsp_granted=bools((n, n)),
        ae_req_t=stamp((n, n)),
        ae_req_term=ints(b.term, (n, n)),
        ae_req_prev=ints(b.index, (n, n)),
        ae_req_prev_term=ints(b.term, (n, n)),
        ae_req_n=ints(cfg.ae_max, (n, n)),
        ae_req_commit=ints(b.index, (n, n)),
        ae_rsp_t=stamp((n, n)),
        ae_rsp_term=ints(b.term, (n, n)),
        ae_rsp_success=bools((n, n)),
        ae_rsp_match=ints(b.index, (n, n)),
        sn_req_t=stamp((n, n)),
        sn_req_term=ints(b.term, (n, n)),
        snap_installed_src=node_id((n,)),
        snap_installed_len=ints(b.index, (n,)),
        next_cmd=ints(b.tick),
        shadow_term=ints(b.term, (cap,)),
        shadow_val=cmds((cap,)),
        shadow_base=ints(b.index),
        shadow_len=ints(b.index),
        shadow_prefix_hash=i32(int(rng.integers(-(2**31), 2**31))),
        violations=i32(int(rng.integers(0, 1 << 16))),
        first_violation_tick=neg1_tick(()),
        first_leader_tick=neg1_tick(()),
        msg_count=i32(int(rng.integers(0, 2**31))),
        snap_install_count=i32(int(rng.integers(0, 2**31))),
        # metrics plane (ISSUE 10): zero-size with metrics off; stamps are
        # tick-bounded, hist counts index-bounded, ev counts event-bounded
        log_tick=ints(b.tick, (n, mcap)),
        shadow_sub=ints(b.tick, (mcap,)),
        lat_hist=ints(b.index, (hb,)),
        ev_counts=ints(b.event, (evn,)),
        # attribution plane (ISSUE 12): phase bucket counts index-bounded,
        # worst-op stamps/durations tick-bounded, the tick-total sums and
        # the key/client ids full-width i32 by design
        phase_hist=ints(b.index, (nph, hb)),
        phase_ticks=i32(rng.integers(0, 2**31, size=(nph,))),
        lat_ticks=i32(rng.integers(0, 2**31, size=(reg,))),
        worst_lat=ints(b.tick, (reg,)),
        worst_phases=ints(b.tick, (nph,)),
        worst_key=i32(rng.integers(-(2**31), 2**31, size=(reg,))),
        worst_client=i32(rng.integers(-(2**31), 2**31, size=(reg,))),
        worst_sub=ints(b.tick, (reg,)),
    )


def _assert_states_equal(a: st.ClusterState, b: st.ClusterState):
    for f in a._fields:
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert x.dtype == y.dtype, (f, x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y, err_msg=f"round-trip drift in {f}")


@pytest.mark.parametrize("cfg", [
    STORM,
    SimConfig(n_nodes=7, log_cap=32, compact_every=8),  # revote-ish shape
    SimConfig(n_nodes=3, log_cap=16, ae_max=2, compact_every=4),
    SimConfig(n_nodes=16, log_cap=16, compact_every=4),  # widest word
    SimConfig(max_lane_ticks=1 << 18),                # u32-index regime
    STORM.replace(metrics=True),          # ISSUE 10: metric rows populated
])
def test_pack_roundtrip_randomized_every_field(cfg):
    rng = np.random.default_rng(7)
    for i in range(4):
        s = _rand_state(cfg, rng)
        _assert_states_equal(s, st.unpack_state(cfg, st.pack_state(cfg, s)))
    # every bound's exact max must survive (the overflow-is-a-test-failure
    # satellite): term/log index/cmd/stamp/timer all at their ceilings
    s = _rand_state(cfg, rng, boundary=True)
    _assert_states_equal(s, st.unpack_state(cfg, st.pack_state(cfg, s)))


def test_widths_pin_to_config_bounds():
    # the derivation chain config.packed_bounds -> state.packed_spec is the
    # single source of widths: each dtype must hold its bound (incl. the
    # reserved NOOP sentinel strictly above the cmd bound), and bumping a
    # bound must WIDEN the dtype rather than silently wrap
    for cfg in (STORM, SimConfig(n_nodes=16), SimConfig(max_lane_ticks=1 << 18)):
        b = packed_bounds(cfg)
        sp = st.packed_spec(cfg)
        assert np.iinfo(sp.tick).max >= b.tick
        assert np.iinfo(sp.term).max >= b.term
        assert np.iinfo(sp.index).max >= b.index
        assert np.iinfo(sp.cmd).max >= b.cmd + 1
        assert sp.noop_code > b.cmd, "NOOP sentinel must sit above any cmd"
        assert np.iinfo(sp.tick_signed).max >= b.tick  # -1 sentinel fields
        assert b.rel_stamp <= np.iinfo(np.uint8).max - 1
        assert np.iinfo(sp.event).max >= b.event  # ISSUE 10 counter rows
    # defaults: 5 nodes / 4096 ticks fit u16 everywhere
    sp = st.packed_spec(STORM.static_key())
    assert sp.term == jnp.uint16 and sp.index == jnp.uint16
    assert sp.cmd == jnp.uint16
    # 16 nodes push the cmd bound past u16 -> the width derives up
    assert st.packed_spec(SimConfig(n_nodes=16).static_key()) .cmd == jnp.uint32
    # a longer declared horizon widens index/cmd, not a runtime surprise
    assert st.packed_spec(
        SimConfig(max_lane_ticks=1 << 18).static_key()
    ).index == jnp.uint32
    with pytest.raises(ValueError, match="max_lane_ticks"):
        SimConfig(max_lane_ticks=1 << 25)


def test_real_trajectory_roundtrip_batched():
    # not just synthetic states: a real 128-tick storm batch round-trips
    # bit-identically through the vmapped pack/unpack
    from madraft_tpu.tpusim.engine import make_fuzz_fn

    final = jax.block_until_ready(make_fuzz_fn(STORM, 16, 128)(3))
    packed = jax.vmap(lambda s: st.pack_state(STORM, s))(final)
    back = jax.vmap(lambda p: st.unpack_state(STORM, p))(packed)
    _assert_states_equal(final, back)


def _strip(rows):
    return [
        {k: v for k, v in r.items() if k not in ("wall_s", "violations_per_s")}
        for r in rows
    ]

_DET_SUMMARY = (
    "lanes", "horizon", "chunk_ticks", "lane_ticks", "ticks_dispatched",
    "retired", "retired_violating", "violating_clusters",
    "violating_clusters_total", "violation_names", "effective_cluster_steps",
)


def test_pool_reports_bit_identical_across_layouts():
    # THE golden-guard property of the refactor: the packed carry changes
    # where bytes live, never what the pool reports
    def leg(pack):
        rows = []
        s = run_pool(VIOL, 7, 16, 64, chunk_ticks=32, budget_ticks=320,
                     on_retired=rows.append, pack_states=pack)
        return rows, s

    rows_w, s_w = leg(False)
    rows_p, s_p = leg(True)
    assert s_w["state_layout"] == "wide" and s_p["state_layout"] == "packed"
    assert _strip(rows_p) == _strip(rows_w)
    for k in _DET_SUMMARY:
        assert s_p[k] == s_w[k], (k, s_p[k], s_w[k])
    # the point of the exercise: the resident carry shrank >= 2x, and the
    # reported footprint is the live-buffer measurement, not an estimate
    assert s_w["bytes_per_lane"] >= 2 * s_p["bytes_per_lane"]
    assert s_p["state_hbm_bytes"] == s_p["bytes_per_lane"] * 16


def test_coverage_pool_bit_identical_across_layouts():
    # guided coverage (mutated knob rows, per-lane layout, fingerprints)
    # is layout-blind too — including the knobs columns mutated refills
    # carry for replay
    from madraft_tpu.tpusim.config import CoverageConfig

    ccfg = CoverageConfig(bitmap_bits=1 << 12)

    def leg(pack):
        rows = []
        s = run_pool(VIOL, 7, 16, 64, chunk_ticks=32, budget_ticks=320,
                     coverage=ccfg, on_retired=rows.append, pack_states=pack)
        return rows, s

    rows_w, s_w = leg(False)
    rows_p, s_p = leg(True)
    assert _strip(rows_p) == _strip(rows_w)
    det_cov = lambda s: {k: v for k, v in s["coverage"].items()  # noqa: E731
                         if k != "new_fingerprints_per_s"}  # wall-derived
    assert det_cov(s_p) == det_cov(s_w)
    for k in _DET_SUMMARY:
        assert s_p[k] == s_w[k], (k, s_p[k], s_w[k])


def test_abstract_code_packed_matches_wide():
    from madraft_tpu.tpusim import coverage as cov
    from madraft_tpu.tpusim.config import CoverageConfig

    rng = np.random.default_rng(11)
    for ccfg in (CoverageConfig(), CoverageConfig(term_rank_levels=2,
                                                  commit_delta_levels=2)):
        for _ in range(8):
            s = _rand_state(STORM, rng)
            a = cov.abstract_code(ccfg, s)
            b = cov.abstract_code_packed(ccfg, st.pack_state(STORM, s))
            assert int(a) == int(b)


def test_replay_bit_identical_across_layouts():
    # the same (seed, cluster_id) through the packed replay carry vs a
    # config whose declared ceiling forces the wide fallback — trajectories
    # must agree field by field (the replay-contract half of the guard)
    assert st.packed_layout_reason(VIOL, VIOL.knobs(), 96) is None
    narrow = VIOL.replace(max_lane_ticks=8)  # 96 ticks > 8 -> wide layout
    assert st.packed_layout_reason(narrow, narrow.knobs(), 96) is not None
    a = replay_cluster(VIOL, 7, 3, 96)
    b = replay_cluster(narrow, 7, 3, 96)
    _assert_states_equal(a, b)


def test_traced_replay_matches_untraced_on_packed_layout():
    from madraft_tpu.tpusim.trace import replay_cluster_traced

    final, rec = replay_cluster_traced(VIOL, 7, 3, 96)
    untraced = replay_cluster(VIOL, 7, 3, 96)
    _assert_states_equal(final, untraced)
    assert rec.role.shape[0] == 96


def test_wide_fallback_reasons_and_forced_pack_rejection():
    kn = STORM.knobs()
    # each gate names its reason
    assert "max_lane_ticks" in st.packed_layout_reason(STORM, kn, 10**6)
    assert "n_nodes" in st.packed_layout_reason(
        SimConfig(n_nodes=17), SimConfig(n_nodes=17).knobs(), 10)
    wide_delay = STORM.replace(delay_max=300)
    assert "delay_max" in st.packed_layout_reason(
        wide_delay, wide_delay.knobs(), 10)
    # ae_req_n is a fixed u8: an ae_max past it must fall back, not wrap
    big_ae = SimConfig(ae_max=300, log_cap=1024)
    assert "ae_max" in st.packed_layout_reason(big_ae, big_ae.knobs(), 10)
    # a zero-delay send stamps the CURRENT tick — indistinguishable from an
    # empty slot under the relative encoding, so the gate must reject it
    # (the pool path never runs _validate_knobs)
    zero_delay = STORM.replace(delay_min=0)
    assert "delay_min" in st.packed_layout_reason(
        zero_delay, zero_delay.knobs(), 10)
    # ISSUE 19 gray-failure gates: the limp multiplier and the stretched
    # delay must fit the u8 fields, a stall spike its u16 field, and the
    # per-node skew offset the u16 timer — all exact-or-wide, never wrap
    limp_wide = STORM.replace(p_limp=0.1, limp_mult_max=300)
    assert "limp_mult_max" in st.packed_layout_reason(
        limp_wide, limp_wide.knobs(), 10)
    limp_stretch = STORM.replace(p_limp=0.1, limp_mult_max=100, delay_max=5)
    assert "stretched delay" in st.packed_layout_reason(
        limp_stretch, limp_stretch.knobs(), 10)
    stall_wide = STORM.replace(p_fsync_stall=0.1, fsync_stall_ticks=70000)
    assert "fsync_stall_ticks" in st.packed_layout_reason(
        stall_wide, stall_wide.knobs(), 10)
    skew_wide = STORM.replace(eto_skew=20000)
    assert "eto_skew" in st.packed_layout_reason(
        skew_wide, skew_wide.knobs(), 10)
    # neutral gray knobs never trip a gate (limp_mult_max=1 means the
    # stretch is the identity even with a wide delay budget)
    assert st.packed_layout_reason(STORM, STORM.knobs(), 10) is None
    # auto mode falls back (and says so); forcing the pack refuses loudly
    s = run_pool(wide_delay, 3, 8, 32, chunk_ticks=32, budget_ticks=32)
    assert s["state_layout"] == "wide"
    with pytest.raises(ValueError, match="packed layout is not exact"):
        run_pool(wide_delay, 3, 8, 32, chunk_ticks=32, budget_ticks=32,
                 pack_states=True)


def test_packed_chunk_carry_is_donated():
    # the packed pool keeps the PR-3 double-buffer discipline: the packed
    # chunk consumes its carry, so peak HBM is the packed footprint x2,
    # not packed + wide
    from madraft_tpu.tpusim.engine import _chunk_program, _pool_init_program

    static = STORM.static_key()
    kn = STORM.knobs()
    init = _pool_init_program(static, 16, None, True)
    chunk = _chunk_program(static, 16, True)
    states, keys, _ = init(jnp.asarray(3, jnp.uint32), kn,
                           jnp.asarray(0, jnp.int32))
    out = chunk(states, keys, kn, jnp.asarray(8, jnp.int32))
    assert int(np.asarray(out.tick)[0]) == 8
    with pytest.raises(Exception, match="[Dd]onat|[Dd]elet"):
        np.asarray(states.tick)


def test_footprint_reduction_at_least_2x_on_storm_shape():
    # the PERF.md round-9 headline, pinned as a regression bound from the
    # LIVE buffers (ci.sh additionally bounds the absolute bytes_per_lane
    # so a later PR cannot silently re-widen a field)
    key = jax.random.PRNGKey(0)
    s = st.init_cluster(STORM, key)
    wide = st.tree_bytes(s)
    packed = st.tree_bytes(st.pack_state(STORM, s))
    assert wide >= 2 * packed, (wide, packed)
