"""Config plumbing: every SimConfig/KvConfig field must reach the compiled
program — either as a shape-determining static_key field or as a dynamic knob
— so that a future field can't silently get baked to its default inside the
lru_cached shared program (the round-2 advisory trap)."""

import dataclasses

import pytest

from madraft_tpu.tpusim.config import Knobs, SimConfig
from madraft_tpu.tpusim.ctrler import CtrlerConfig, CtrlerKnobs
from madraft_tpu.tpusim.engine import _validate_knobs, make_sweep_fn
from madraft_tpu.tpusim.kv import KvConfig, KvKnobs

# Fields that are deliberately NOT part of the program: documentation-only,
# or folded into another knob (uncommitted_cap -> flow_cap; majority_override
# -> majority).
SIM_DOC_ONLY = {"ms_per_tick"}
SIM_FOLDED = {
    "uncommitted_cap": "flow_cap",
    "majority_override": "majority",
    "election_timeout_min": "eto_min",
    "election_timeout_max": "eto_max",
}


def test_simconfig_fields_all_reach_the_program():
    # static_key's fields (max_lane_ticks shapes the packed dtypes;
    # metrics shapes the ISSUE-10 metric arrays — zero-size when off;
    # fuse_packed_step selects the ISSUE-11 per-field-group composition,
    # its own cached program)
    static = {"n_nodes", "log_cap", "ae_max", "bug", "max_lane_ticks",
              "metrics", "fuse_packed_step"}
    knob_names = set(Knobs._fields)
    for f in dataclasses.fields(SimConfig):
        if f.name in SIM_DOC_ONLY or f.name in static:
            continue
        mapped = SIM_FOLDED.get(f.name, f.name)
        assert mapped in knob_names, (
            f"SimConfig.{f.name} is neither static nor a knob — it would be "
            f"silently baked to its default in the shared compiled program"
        )


def test_kvconfig_fields_all_reach_the_program():
    static = {"n_clients", "n_keys", "apply_max"}  # KvConfig.static_key fields
    knob_names = set(KvKnobs._fields)
    for f in dataclasses.fields(KvConfig):
        if f.name in static:
            continue
        assert f.name in knob_names, (
            f"KvConfig.{f.name} is neither static nor a knob"
        )


def test_ctrlerconfig_fields_all_reach_the_program():
    static = {"n_gids", "n_clients", "n_configs", "join_max", "apply_max",
              "walk_max"}
    knob_names = set(CtrlerKnobs._fields)
    for f in dataclasses.fields(CtrlerConfig):
        if f.name in static:
            continue
        assert f.name in knob_names, (
            f"CtrlerConfig.{f.name} is neither static nor a knob"
        )


def test_shardkvconfig_fields_all_reach_the_program():
    from madraft_tpu.tpusim.shardkv import ShardKvConfig, ShardKvKnobs

    static = {"n_groups", "n_shards", "n_clients", "n_configs",
              "apply_max", "walk_max", "live_ctrler", "computed_ctrler"}
    knob_names = set(ShardKvKnobs._fields)
    for f in dataclasses.fields(ShardKvConfig):
        if f.name in static:
            continue
        assert f.name in knob_names, (
            f"ShardKvConfig.{f.name} is neither static nor a knob"
        )


def test_sweep_knob_validation_rejects_bad_ranges():
    cfg = SimConfig()
    bad = cfg.replace(election_timeout_min=30, election_timeout_max=15).knobs()
    with pytest.raises(ValueError, match="election timeout"):
        _validate_knobs(bad)
    with pytest.raises(ValueError, match="outside"):
        _validate_knobs(cfg.replace(loss_prob=1.5).knobs())
    with pytest.raises(ValueError, match="election timeout"):
        make_sweep_fn(cfg, bad, n_clusters=4, n_ticks=4)
    # a valid sweep passes validation and builds
    make_sweep_fn(cfg, cfg.knobs(), n_clusters=4, n_ticks=4)
