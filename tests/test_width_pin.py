"""Static width pins (ISSUE 15): the packed-schema dtype derivations
asserted against ``packed_bounds`` WITHOUT executing anything.

These tests replace the bench-only re-widening gates that ci.sh used to
carry (``bytes_per_lane <= 2800/3600``, shardkv ``<= 14000``): those only
caught a widened field after a full pool/fuzz run, and only when the total
crossed the ceiling. Here every dtype is checked at import/trace time —
the minimality tests prove each spec field is the SMALLEST container for
its ``packed_bounds`` value, the snapshot tests pin the full field->dtype
tables so any width change (wider or narrower) needs a conscious update
here, and the byte pins reproduce the exact per-lane totals the old bench
gates measured, via ``jax.eval_shape`` (shape x itemsize, no device
allocation, no hot-path execution). The jaxpr-level widen-on-use audit
(no wide intermediate touching a packed field inside the step) is the
lint packed_width pass — tpusim/lint.py; this module pins the schema
side of the same invariant."""

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.config import packed_bounds, storm_profiles
from madraft_tpu.tpusim.ctrler import (
    CtrlerConfig,
    ctrler_packed_layout,
    init_ctrler_cluster,
    pack_ctrler_state,
)
from madraft_tpu.tpusim.kv import (
    _SEQ_LIM,
    KvConfig,
    _pack,
    init_kv_cluster,
    kv_packed_layout,
    pack_kv_state,
)
from madraft_tpu.tpusim.shardkv import (
    ShardKvConfig,
    init_shardkv_cluster,
    pack_shardkv_state,
    shardkv_packed_layout,
)
from madraft_tpu.tpusim.state import (
    abstract_bytes,
    init_cluster,
    pack_state,
    packed_spec_for,
)

# The exact CLI-shaped static configs the ci.sh smokes run (pool on the
# durability profile; kv/ctrler/shardkv at the fuzz-verb defaults) — the
# widths below are pinned at the same shapes the old bench gates measured.
DURABILITY = storm_profiles()["durability"][0]
KV_CFG = SimConfig().replace(
    p_client_cmd=0.0, compact_at_commit=False, compact_every=16
)
CTRLER_CFG = SimConfig().replace(
    p_client_cmd=0.0, compact_at_commit=False, log_cap=32, compact_every=8
)
SHARDKV_CFG = SimConfig(
    n_nodes=3, p_client_cmd=0.0, compact_at_commit=False,
    log_cap=64, compact_every=16, loss_prob=0.05,
    p_crash=0.0, p_restart=0.2, max_dead=0,
)

_KEY = jax.ShapeDtypeStruct((2,), jnp.uint32)


def _min_uint(bound):
    """Independent re-derivation of state._uint_for: smallest unsigned
    container for [0, bound]. Deliberately NOT imported from state.py —
    a re-widening slipped into the production derivation must disagree
    with this copy."""
    for dt in (np.uint8, np.uint16, np.uint32):
        if bound <= np.iinfo(dt).max:
            return np.dtype(dt)
    raise AssertionError(f"bound {bound} exceeds u32")


def _min_sint(bound):
    for dt in (np.int8, np.int16, np.int32):
        if bound <= np.iinfo(dt).max:
            return np.dtype(dt)
    raise AssertionError(f"bound {bound} exceeds i32")


def _spec_names(sp):
    return {f: np.dtype(getattr(sp, f)).name
            for f in sp._fields if f != "noop_code"}


def _dts_names(dts):
    return {f: np.dtype(dt).name for f, dt in dts.items()}


# --------------------------------------------------- minimality vs bounds
def test_raft_spec_is_minimal_for_every_profile():
    # packed_spec_for must pick the SMALLEST container for each
    # packed_bounds value — "bump it to be safe" is exactly the silent
    # re-widening this file exists to catch.
    cfgs = [SimConfig(), DURABILITY.replace(bug="ack_before_fsync"),
            KV_CFG, CTRLER_CFG, SHARDKV_CFG]
    cfgs += [c for legs in storm_profiles().values() for c in legs[:1]]
    for cfg in cfgs:
        b = packed_bounds(cfg)
        sp = packed_spec_for(cfg)
        assert np.dtype(sp.tick) == _min_uint(b.tick)
        assert np.dtype(sp.term) == _min_uint(b.term)
        assert np.dtype(sp.index) == _min_uint(b.index)
        # + 1: the cmd channel reserves a distinct NOOP sentinel
        assert np.dtype(sp.cmd) == _min_uint(b.cmd + 1)
        assert sp.noop_code == np.iinfo(np.dtype(sp.cmd)).max
        assert np.dtype(sp.tick_signed) == _min_sint(b.tick)
        assert np.dtype(sp.event) == _min_uint(b.event)


def test_service_spec_override_is_minimal_for_documented_bounds():
    # The kv layer's index/cmd overrides (kv_packed_layout docstring:
    # submits + leader no-op per node per tick; packed top op) re-derived
    # here from the same formulas — the spec must be minimal for THEM,
    # while the non-overridden fields must equal the raft derivation.
    kcfg = KvConfig()
    b = packed_bounds(KV_CFG)
    nc, nk = kcfg.n_clients, kcfg.n_keys
    idx_bound = (nc + 1) * b.tick + 1
    cmd_bound = _pack(kcfg, nc - 1, _SEQ_LIM - 1, nk - 1, 3)
    sp, _ = kv_packed_layout(KV_CFG, kcfg)
    assert sp == packed_spec_for(KV_CFG, index_bound=idx_bound,
                                 cmd_bound=cmd_bound)
    assert np.dtype(sp.index) == _min_uint(idx_bound)
    assert np.dtype(sp.cmd) == _min_uint(cmd_bound + 1)
    raft_sp = packed_spec_for(KV_CFG)
    for f in ("tick", "term", "tick_signed", "event"):
        assert getattr(sp, f) == getattr(raft_sp, f), (
            f"kv override changed non-overridden spec field {f!r}"
        )


# ----------------------------------------------------- snapshot dtype pins
# Full field -> dtype pins at the ci.sh shapes. Any change — widening OR
# narrowing — must update these literals, which is the point: the old
# bench ceilings let a field grow silently until the per-lane total
# crossed 2800/3600/14000; here the diff names the exact field.
RAFT_SPEC_PIN = {
    "tick": "uint16", "term": "uint16", "index": "uint16", "cmd": "uint16",
    "tick_signed": "int16", "event": "uint16",
}
KV_SPEC_PIN = dict(RAFT_SPEC_PIN, cmd="uint32")
KV_DTS_PIN = {
    "clerk_seq": "uint16", "clerk_out": "bool", "clerk_key": "uint8",
    "clerk_kind": "uint8", "clerk_acked": "uint16", "clerk_leader": "int8",
    "clerk_wait": "uint16", "open_arr": "uint16", "open_srv": "uint16",
    "open_drop": "uint16", "open_stamp": "uint16",
    "clerk_sub": "uint16", "clerk_app": "uint16",
    "clerk_cmt": "uint16", "clerk_apl": "uint16", "client_retries": "uint16",
    "key_lat_hist": "uint16", "client_lat_hist": "uint16",
    "truth_count": "uint16", "truth_max_seq": "uint16",
    "clerk_get_lo": "uint16", "clerk_get_obs": "int16",
    "clerk_last_obs": "int16", "gets_done": "uint16", "applied": "uint16",
    "last_seq": "uint16", "apply_count": "uint16", "key_hash": "int32",
    "key_count": "uint16", "snap_last_seq": "uint16",
    "snap_apply_count": "uint16", "snap_key_hash": "int32",
    "snap_key_count": "uint16",
}
CTRLER_SPEC_PIN = dict(RAFT_SPEC_PIN, cmd="uint32")
CTRLER_DTS_PIN = {
    "clerk_seq": "uint16", "clerk_out": "bool", "clerk_arg": "uint8",
    "clerk_kind": "uint8", "clerk_acked": "uint16", "clerk_q_obs": "int32",
    "queries_done": "uint16", "clerk_sub": "uint16", "clerk_app": "uint16",
    "clerk_cmt": "uint16", "clerk_apl": "uint16", "applied": "uint16",
    "last_seq": "uint16", "member": "bool", "owner": "int8",
    "cfg_num": "uint8", "hist": "int32", "snap_last_seq": "uint16",
    "snap_member": "bool", "snap_owner": "int8", "snap_cfg_num": "uint8",
    "snap_hist": "int32", "w_frontier": "uint16", "w_last_seq": "uint16",
    "w_member": "bool", "w_owner": "int8", "w_cfg_num": "uint8",
    "w_hist": "int32", "w_q_seq": "uint16", "w_q_obs": "int32",
    "w_stalled": "bool",
}
SHARDKV_GROUP_SPEC_PIN = dict(RAFT_SPEC_PIN, index="uint32", cmd="uint32")
SHARDKV_CTRL_SPEC_PIN = dict(RAFT_SPEC_PIN, cmd="uint8")
SHARDKV_DTS_PIN = {
    "cfg_owner": "int8", "ctrl_w_frontier": "uint16",
    "ctrl_w_stalled": "bool", "win_var": "int8", "flip_a": "int8",
    "flip_b": "int8", "slot_tick": "int16", "cmem": "bool",
    "ctrl_node_owner": "int8", "ctrl_maps": "int8", "node_src": "int8",
    "snap_src": "int8", "w_src": "int8", "cq_req_node": "int8",
    "cq_req_j": "uint8", "cq_rsp_j": "uint8", "cq_rsp_found": "bool",
    "cq_rsp_var": "uint8", "applied": "uint32", "node_cfg": "uint8",
    "phase": "uint8", "key_hash": "int32", "key_count": "uint16",
    "last_seq": "uint16", "snap_cfg": "uint8", "snap_phase": "uint8",
    "snap_hash": "int32", "snap_count": "uint16", "snap_last_seq": "uint16",
    "staged_cfg": "int8", "staged_hash": "int32", "staged_count": "uint16",
    "staged_last_seq": "uint16", "pull_req_cfg": "uint8",
    "pull_rsp_cfg": "uint8", "pull_rsp_hash": "int32",
    "pull_rsp_count": "uint16", "pull_rsp_last_seq": "uint16",
    "gcq_req_cfg": "uint8", "gcq_rsp_cfg": "uint8", "clerk_seq": "uint16",
    "clerk_out": "bool", "clerk_shard": "uint8", "clerk_kind": "uint8",
    "clerk_cfg": "uint8", "clerk_wrong": "bool", "clerk_acked": "uint16",
    "clerk_get_lo": "uint16", "clerk_get_obs": "int16",
    "gets_done": "uint16", "open_arr": "uint16", "open_srv": "uint16",
    "open_drop": "uint16", "open_stamp": "uint16",
    "clerk_sub": "uint16", "lat_hist": "uint16",
    "clerk_app": "uint16", "clerk_cmt": "uint16", "clerk_apl": "uint16",
    "clerk_mig": "uint16", "client_retries": "uint16",
    "phase_hist": "uint16", "phase_ticks": "int32", "lat_ticks": "int32",
    "worst_lat": "uint16", "worst_phases": "uint16", "worst_key": "int32",
    "worst_client": "int32", "worst_sub": "uint16",
    "key_lat_hist": "uint16", "client_lat_hist": "uint16",
    "w_frontier": "uint32", "w_cfg": "uint8", "w_phase": "uint8",
    "w_hash": "int32", "w_count": "uint16", "w_last_seq": "uint16",
    "frz_cfg": "int8", "frz_hash": "int32", "frz_count": "uint16",
    "frz_last_seq": "uint16", "truth_count": "uint16",
    "w_clerk_acked": "uint16", "installs_done": "int32",
    "deletes_done": "int32", "max_cfg_lag": "uint8", "violations": "int32",
    "first_violation_tick": "int16",
}


def test_raft_spec_pinned_at_pool_shape():
    assert _spec_names(packed_spec_for(DURABILITY)) == RAFT_SPEC_PIN


def test_kv_layout_pinned():
    sp, dts = kv_packed_layout(KV_CFG, KvConfig())
    assert _spec_names(sp) == KV_SPEC_PIN
    assert _dts_names(dts) == KV_DTS_PIN


def test_ctrler_layout_pinned():
    sp, dts = ctrler_packed_layout(CTRLER_CFG, CtrlerConfig())
    assert _spec_names(sp) == CTRLER_SPEC_PIN
    assert _dts_names(dts) == CTRLER_DTS_PIN


def test_shardkv_layout_pinned():
    sp, csp, dts = shardkv_packed_layout(SHARDKV_CFG, ShardKvConfig())
    assert _spec_names(sp) == SHARDKV_GROUP_SPEC_PIN
    assert _spec_names(csp) == SHARDKV_CTRL_SPEC_PIN
    assert _dts_names(dts) == SHARDKV_DTS_PIN


def test_no_packed_field_reaches_wide_width_unpinned():
    # The direct re-widening guard: a 4-byte field in any layout table
    # must already be pinned as int32/uint32 above (full-width-by-design
    # hashes / latency sums / sentinel ids). A new wide field fails here
    # with its name, not as an opaque byte-total regression.
    for pin, dts in (
        (KV_DTS_PIN, kv_packed_layout(KV_CFG, KvConfig())[1]),
        (CTRLER_DTS_PIN, ctrler_packed_layout(CTRLER_CFG, CtrlerConfig())[1]),
        (SHARDKV_DTS_PIN, shardkv_packed_layout(SHARDKV_CFG,
                                                ShardKvConfig())[2]),
    ):
        for f, dt in dts.items():
            if np.dtype(dt).itemsize >= 4:
                assert pin[f] in ("int32", "uint32"), (
                    f"field {f!r} widened to {np.dtype(dt).name} without a "
                    "pin update"
                )


# --------------------------------------------- packed <= wide, per field
def _packed_vs_wide(cfg):
    wide = jax.eval_shape(lambda k: init_cluster(cfg, k), _KEY)
    packed = jax.eval_shape(lambda k: pack_state(cfg, init_cluster(cfg, k)),
                            _KEY)
    return wide, packed


def test_packed_raft_state_never_wider_than_wide():
    # Field-for-field: the packed carry may never cost more bytes than the
    # wide carry it replaces (bitfield words may change SHAPE — role_bits
    # packs an [n] row into a scalar — so compare total bytes per field).
    for cfg in (DURABILITY, DURABILITY.replace(metrics=True)):
        wide, packed = _packed_vs_wide(cfg)
        for f in wide._fields:
            if not hasattr(packed, f):
                continue
            wb = int(np.prod(getattr(wide, f).shape)) * np.dtype(
                getattr(wide, f).dtype).itemsize
            pb = int(np.prod(getattr(packed, f).shape)) * np.dtype(
                getattr(packed, f).dtype).itemsize
            assert pb <= wb, (
                f"packed field {f!r} costs {pb} B > wide {wb} B"
            )


# ------------------------------------------------------ static byte pins
# Exact totals via eval_shape at the ci.sh smoke shapes — the numbers the
# old executed gates measured (PERF.md rounds 9/11/12), now proven without
# running a tick. The <= ceilings are kept as the documented regression
# budget; the == pins are what actually catch a one-field widening.
def test_static_bytes_per_lane_pool_shape():
    cfg = DURABILITY.replace(bug="ack_before_fsync")
    got = abstract_bytes(jax.eval_shape(
        lambda k: pack_state(cfg, init_cluster(cfg, k)), _KEY))
    # 2597 -> 2612 in round 19: +15 B for the gray per-node state
    # (limp u8 x5 + fsync_stall u16 x5)
    assert got == 2612, f"packed raft carry drifted: {got} B/lane != 2612"
    assert got <= 2800  # the retired ci.sh BYTES_PER_LANE_BOUND


def test_static_bytes_per_lane_metrics_shape():
    cfg = DURABILITY.replace(bug="ack_before_fsync", metrics=True)
    got = abstract_bytes(jax.eval_shape(
        lambda k: pack_state(cfg, init_cluster(cfg, k)), _KEY))
    # 3585 -> 3600 in round 19 (gray per-node state, as above) — exactly
    # AT the retired ceiling; the next widened field must argue its case
    assert got == 3600, f"metrics-on packed carry drifted: {got} != 3600"
    assert got <= 3600  # the retired METRICS_BYTES_PER_LANE_BOUND


def test_static_bytes_per_deployment_shardkv_shape():
    kcfg = ShardKvConfig()
    got = abstract_bytes(jax.eval_shape(
        lambda k: pack_shardkv_state(
            SHARDKV_CFG, kcfg,
            init_shardkv_cluster(SHARDKV_CFG, kcfg, k)), _KEY))
    # 12840 -> 12894 in round 19: gray raft state x2 carries (group +
    # ctrl) + the open-loop clerk queue cursors/stamp ring
    assert got == 12894, f"packed shardkv carry drifted: {got} != 12894"
    assert got <= 14000  # the retired SHARDKV_BYTES_PER_DEPLOYMENT_BOUND


def test_static_bytes_service_lanes():
    # kv/ctrler analogues (no old ceiling existed; pin the totals so the
    # service carries get the same one-field sensitivity)
    kcfg = KvConfig()
    got = abstract_bytes(jax.eval_shape(
        lambda k: pack_kv_state(KV_CFG, kcfg,
                                init_kv_cluster(KV_CFG, kcfg, k)), _KEY))
    # 3863 -> 3902 in round 19 (gray raft state + open-loop clerk queue)
    assert got == 3902, f"packed kv carry drifted: {got} != 3902"
    ccfg = CtrlerConfig()
    got = abstract_bytes(jax.eval_shape(
        lambda k: pack_ctrler_state(
            CTRLER_CFG, ccfg,
            init_ctrler_cluster(CTRLER_CFG, ccfg, k)), _KEY))
    # 3622 -> 3637 in round 19 (gray raft per-node state; the ctrler
    # clerk stays closed-loop, so no open-loop fields here)
    assert got == 3637, f"packed ctrler carry drifted: {got} != 3637"
