"""Log compaction / install-snapshot tests (the Lab 2D analogue on TPU):
histories far past the window capacity, snapshot catch-up of lagging nodes,
and the KV service surviving snapshot handoff of its dup tables.

Runs on the virtual CPU device mesh from conftest.py.
"""

import numpy as np

from madraft_tpu.tpusim import KvConfig, SimConfig, fuzz, kv_fuzz

# Tight window + harsh faults: compaction and snapshot installs are constant.
RAFT = SimConfig(
    n_nodes=5,
    log_cap=16,
    compact_every=6,
    p_client_cmd=0.3,
    loss_prob=0.1,
    p_crash=0.02,
    p_restart=0.1,   # long dead spells => nodes fall behind the snapshot
    max_dead=2,
    p_repartition=0.03,
    p_heal=0.08,
)


def test_long_history_past_window():
    """Commits must run far beyond log_cap (impossible without compaction)."""
    rep = fuzz(RAFT, seed=11, n_clusters=64, n_ticks=640)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} in "
        f"clusters {rep.violating_clusters()[:8]}"
    )
    # median history length must dwarf the 16-entry window
    assert np.median(rep.committed) > 4 * RAFT.log_cap
    # lagging nodes must have been caught up via install-snapshot
    assert rep.snap_installs.sum() > 0
    assert (rep.snap_installs > 0).mean() > 0.3


def test_kv_exactly_once_across_snapshots():
    """Dup tables must survive snapshot handoff: a node restored from a
    snapshot must still dedup retried ops it never applied from the log."""
    cfg = RAFT.replace(p_client_cmd=0.0, compact_at_commit=False)
    kcfg = KvConfig(p_retry=0.8, p_op=0.5)
    rep = kv_fuzz(cfg, kcfg, seed=11, n_clusters=64, n_ticks=640)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]} in "
        f"clusters {rep.violating_clusters()[:8]}"
    )
    assert np.median(rep.committed) > 2 * cfg.log_cap
    assert rep.snap_installs.sum() > 0
    assert rep.acked_ops.sum() > 64 * 8


def test_prefix_durability_oracle():
    """The commit shadow only covers the last log_cap committed entries; the
    prefix-hash oracle extends durability checking past the window (the
    round-1 advisory gap): equal snapshot boundaries must mean identical
    compacted prefixes. Clean storms must stay silent through compaction,
    restart, and install-snapshot; a broken quorum must trip it — divergent
    committed prefixes eventually get compacted on both sides."""
    from madraft_tpu.tpusim.config import VIOLATION_PREFIX_DIVERGE

    bug = RAFT.replace(majority_override=2, p_crash=0.0, max_dead=0)
    rep = fuzz(bug, seed=5, n_clusters=64, n_ticks=640)
    assert rep.n_violating > 0
    hits = (rep.violations & VIOLATION_PREFIX_DIVERGE) != 0
    assert hits.sum() > 10, f"prefix oracle fired in only {hits.sum()} clusters"
    # clean-run silence is covered by test_long_history_past_window (same
    # config, no override) — any false positive would fail it


def test_compaction_determinism():
    """Same seed => identical outcome with compaction in the loop."""
    r1 = fuzz(RAFT, seed=77, n_clusters=48, n_ticks=384)
    r2 = fuzz(RAFT, seed=77, n_clusters=48, n_ticks=384)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)
