"""Service-layer packed state (ISSUE 11): the kv/ctrler/shardkv carries
pack under the same exact-or-wide rule as the raft layer (PR 9). Pinned
here:

- round-trip exactness (pack -> unpack == identity, bit for bit) on
  randomized BOUNDARY-VALUE service fields — every field sampled across
  its derived range including the exact maximum — and on real batched
  trajectories;
- widths pin to the derived bounds (seq/index/cmd/count), including the
  derive-up cases where a larger tick ceiling widens a dtype;
- packed-vs-wide bit-identity of fuzz reports and replays on all three
  stacks, plus the fuse_packed_step composition (the per-field-group
  pack∘step∘unpack) — trajectories must be a property of the math, never
  of the carry layout;
- exact-or-wide fallback: out-of-bound knobs produce a named reason, the
  run falls back to wide, and a FORCED pack is rejected;
- the footprint bound: >= 1.5x fewer bytes per deployment on the shardkv
  bench shape (the ISSUE 11 headline).
"""

import contextlib
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(autouse=True, scope="module")
def _no_late_shardkv_cache_writes():
    """This module compiles shardkv-sized programs and runs LATE in a full
    suite (alphabetical order) — inside the round-5 serialize-segfault
    accumulation zone test_tpusim_shardkv.py documents. Same defense:
    suppress persistent-cache WRITES unless MADTPU_SHARDKV_CACHE_WRITE=1
    (ci.sh / the workflow set it; reads are unaffected, so a warm cache
    still skips the compiles)."""
    from conftest import no_persistent_cache

    guard = (contextlib.nullcontext()
             if os.environ.get("MADTPU_SHARDKV_CACHE_WRITE") == "1"
             else no_persistent_cache())
    with guard:
        yield

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim import state as st
from madraft_tpu.tpusim.config import packed_bounds
from madraft_tpu.tpusim import ctrler as ctl
from madraft_tpu.tpusim import kv
from madraft_tpu.tpusim import shardkv as skv

KV_CFG = SimConfig(
    n_nodes=5, p_client_cmd=0.0, compact_at_commit=False, compact_every=16,
    loss_prob=0.1, p_crash=0.01, p_restart=0.2, max_dead=2,
)
KV_KCFG = kv.KvConfig(p_get=0.3, p_put=0.1)

CTL_CFG = KV_CFG.replace(log_cap=32, compact_every=8)
CTL_KCFG = ctl.CtrlerConfig()

SKV_CFG = SimConfig(
    n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
    compact_every=16, loss_prob=0.05,
)
SKV_KCFG = skv.ShardKvConfig()


def _trees_equal(a, b, ctx=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert la.dtype == lb.dtype, (ctx, la.dtype, lb.dtype)
        assert np.array_equal(np.asarray(la), np.asarray(lb)), ctx


def _reports_equal(a, b, ctx=""):
    for f in a._fields:
        x, y = getattr(a, f), getattr(b, f)
        if x is None:
            assert y is None, (ctx, f)
            continue
        assert np.array_equal(x, y), (ctx, f)


def _bounded_service_fill(state, dts, bounds, rng):
    """Randomize every table-driven service field across its DERIVED range,
    forcing the exact maximum (and the -1 sentinel where legal) into slot
    0 — the exactness property is 'any in-bounds value round-trips', so the
    boundary is where it must be exercised."""
    new = {}
    for f, dt in dts.items():
        x = np.asarray(getattr(state, f))
        if x.size == 0:
            continue
        if dt == st.BOOL:
            new[f] = jnp.asarray(rng.integers(0, 2, x.shape).astype(bool))
            continue
        lo, hi = bounds.get(
            f, (0, int(np.iinfo(np.dtype(dt)).max))
        )
        v = rng.integers(lo, hi + 1, x.shape, dtype=np.int64)
        flat = v.reshape(-1)
        flat[0] = hi  # exact maximum must survive
        if lo < 0:
            flat[-1] = lo
        new[f] = jnp.asarray(
            flat.reshape(x.shape).astype(np.int32)
        )
    return state._replace(**new)


# --------------------------------------------------------------- round-trip
def test_kv_roundtrip_randomized_boundary_values():
    rng = np.random.default_rng(0)
    b = packed_bounds(KV_CFG)
    _, dts = kv.kv_packed_layout(KV_CFG, KV_KCFG)
    seq = min(b.tick, kv._SEQ_LIM - 1)
    idx = (KV_KCFG.n_clients + 1) * b.tick + 1
    bounds = {
        "clerk_seq": (0, seq), "clerk_acked": (0, seq),
        "clerk_key": (0, KV_KCFG.n_keys - 1), "clerk_kind": (0, 2),
        "clerk_leader": (-1, KV_CFG.n_nodes - 1),
        "clerk_wait": (0, b.tick), "clerk_sub": (0, b.tick),
        "truth_count": (0, idx), "truth_max_seq": (0, seq),
        "clerk_get_lo": (0, idx), "clerk_get_obs": (-1, idx),
        "clerk_last_obs": (-1, idx), "gets_done": (0, b.tick),
        "applied": (0, idx), "last_seq": (0, seq),
        "apply_count": (0, idx),
        "key_hash": (-(1 << 31), (1 << 31) - 1), "key_count": (0, idx),
        "snap_last_seq": (0, seq), "snap_apply_count": (0, idx),
        "snap_key_hash": (-(1 << 31), (1 << 31) - 1),
        "snap_key_count": (0, idx),
    }
    s0 = kv.init_kv_cluster(KV_CFG, KV_KCFG, jax.random.PRNGKey(1))
    for trial in range(4):
        s = _bounded_service_fill(s0, dts, bounds, rng)
        s2 = kv.unpack_kv_state(
            KV_CFG, KV_KCFG, kv.pack_kv_state(KV_CFG, KV_KCFG, s)
        )
        _trees_equal(s, s2, f"kv trial {trial}")


def test_ctrler_roundtrip_randomized_boundary_values():
    rng = np.random.default_rng(1)
    b = packed_bounds(CTL_CFG)
    _, dts = ctl.ctrler_packed_layout(CTL_CFG, CTL_KCFG)
    seq = min(b.tick, ctl._SEQ_LIM - 1)
    idx = (CTL_KCFG.n_clients + 1) * b.tick + 1
    h32 = (-(1 << 31), (1 << 31) - 1)
    bounds = {
        "clerk_seq": (0, seq), "clerk_acked": (0, seq),
        "clerk_arg": (0, CTL_KCFG._arg_lim - 1), "clerk_kind": (0, 3),
        "clerk_q_obs": (-1, (1 << 31) - 1),
        "queries_done": (0, b.tick), "clerk_sub": (0, b.tick),
        "applied": (0, idx), "last_seq": (0, seq),
        "owner": (-1, CTL_KCFG.n_gids - 1),
        "cfg_num": (0, CTL_KCFG.n_configs - 1), "hist": h32,
        "snap_last_seq": (0, seq),
        "snap_owner": (-1, CTL_KCFG.n_gids - 1),
        "snap_cfg_num": (0, CTL_KCFG.n_configs - 1), "snap_hist": h32,
        "w_frontier": (0, idx), "w_last_seq": (0, seq),
        "w_owner": (-1, CTL_KCFG.n_gids - 1),
        "w_cfg_num": (0, CTL_KCFG.n_configs - 1), "w_hist": h32,
        "w_q_seq": (0, seq), "w_q_obs": (-1, (1 << 31) - 1),
    }
    s0 = ctl.init_ctrler_cluster(
        CTL_CFG.replace(metrics=True), CTL_KCFG, jax.random.PRNGKey(2)
    )
    for trial in range(4):
        s = _bounded_service_fill(s0, dts, bounds, rng)
        cfg_m = CTL_CFG.replace(metrics=True)
        s2 = ctl.unpack_ctrler_state(
            cfg_m, CTL_KCFG, ctl.pack_ctrler_state(cfg_m, CTL_KCFG, s)
        )
        _trees_equal(s, s2, f"ctrler trial {trial}")


def test_shardkv_roundtrip_randomized_boundary_values():
    rng = np.random.default_rng(2)
    b = packed_bounds(SKV_CFG)
    _, _, dts = skv.shardkv_packed_layout(SKV_CFG, SKV_KCFG)
    k = SKV_KCFG
    seq = min(b.tick, skv._SEQ_LIM - 1)
    idx = (k.n_clients + 2 * k.n_shards + 2) * b.tick + 1
    cnt = k.n_clients * seq
    h32 = (-(1 << 31), (1 << 31) - 1)
    ncfg, g, n = k.n_configs, k.n_groups, SKV_CFG.n_nodes
    bounds = {
        "cfg_owner": (0, g - 1), "ctrl_w_frontier": (0, 3 * b.tick + 1),
        "win_var": (-1, max(g, 2) - 1), "flip_a": (0, g - 1),
        "flip_b": (0, g - 1), "slot_tick": (-1, b.tick),
        "ctrl_node_owner": (0, g - 1), "ctrl_maps": (0, g - 1),
        "node_src": (0, n - 1), "snap_src": (0, n - 1),
        "w_src": (0, n - 1), "cq_req_node": (0, n - 1),
        "cq_req_j": (0, ncfg - 1), "cq_rsp_j": (0, ncfg - 1),
        "cq_rsp_var": (0, max(n, 2) - 1),
        "applied": (0, idx), "node_cfg": (0, ncfg - 1),
        "phase": (0, 3), "key_hash": h32, "key_count": (0, cnt),
        "last_seq": (0, seq), "snap_cfg": (0, ncfg - 1),
        "snap_phase": (0, 3), "snap_hash": h32, "snap_count": (0, cnt),
        "snap_last_seq": (0, seq), "staged_cfg": (-1, ncfg - 1),
        "staged_hash": h32, "staged_count": (0, cnt),
        "staged_last_seq": (0, seq),
        "pull_req_cfg": (0, ncfg - 1), "pull_rsp_cfg": (0, ncfg - 1),
        "pull_rsp_hash": h32, "pull_rsp_count": (0, cnt),
        "pull_rsp_last_seq": (0, seq),
        "gcq_req_cfg": (0, ncfg - 1), "gcq_rsp_cfg": (0, ncfg - 1),
        "clerk_seq": (0, seq), "clerk_shard": (0, k.n_shards - 1),
        "clerk_kind": (0, 5), "clerk_cfg": (0, ncfg - 1),
        "clerk_acked": (0, seq), "clerk_get_lo": (0, cnt),
        "clerk_get_obs": (-1, cnt), "gets_done": (0, b.tick),
        "clerk_sub": (0, b.tick), "lat_hist": (0, cnt),
        "w_frontier": (0, idx), "w_cfg": (0, ncfg - 1),
        "w_phase": (0, 3), "w_hash": h32, "w_count": (0, cnt),
        "w_last_seq": (0, seq), "frz_cfg": (-1, ncfg - 1),
        "frz_hash": h32, "frz_count": (0, cnt), "frz_last_seq": (0, seq),
        "truth_count": (0, cnt), "w_clerk_acked": (0, seq),
        "installs_done": (0, (1 << 31) - 1),
        "deletes_done": (0, (1 << 31) - 1),
        "max_cfg_lag": (0, ncfg), "violations": (0, (1 << 31) - 1),
        "first_violation_tick": (-1, b.tick),
    }
    s0 = skv.init_shardkv_cluster(SKV_CFG, SKV_KCFG, jax.random.PRNGKey(3))
    for trial in range(3):
        s = _bounded_service_fill(s0, dts, bounds, rng)
        s2 = skv.unpack_shardkv_state(
            SKV_CFG, SKV_KCFG, skv.pack_shardkv_state(SKV_CFG, SKV_KCFG, s)
        )
        _trees_equal(s, s2, f"shardkv trial {trial}")


# ------------------------------------------------------------ width pinning
def test_widths_pin_to_bounds_and_derive_up():
    b = packed_bounds(KV_CFG)
    sp, dts = kv.kv_packed_layout(KV_CFG, KV_KCFG)
    seq_bound = min(b.tick, kv._SEQ_LIM - 1)
    idx_bound = (KV_KCFG.n_clients + 1) * b.tick + 1
    cmd_bound = kv._pack(
        KV_KCFG, KV_KCFG.n_clients - 1, kv._SEQ_LIM - 1,
        KV_KCFG.n_keys - 1, 3,
    )
    assert np.dtype(sp.index) == np.dtype(st.uint_for(idx_bound))
    assert np.iinfo(np.dtype(sp.cmd)).max >= cmd_bound + 1  # + NOOP code
    for f in ("clerk_seq", "clerk_acked", "truth_max_seq", "last_seq",
              "snap_last_seq"):
        assert np.dtype(dts[f]) == np.dtype(st.uint_for(seq_bound)), f
    for f in ("applied", "apply_count", "key_count", "truth_count"):
        assert np.iinfo(np.dtype(dts[f])).max >= idx_bound, f
    # at the default shapes the big tensors actually narrowed
    assert np.dtype(dts["last_seq"]) == np.uint16
    assert np.dtype(dts["clerk_kind"]) == np.uint8

    # derive-up: a tick ceiling that outgrows u16 widens the index fields
    big = KV_CFG.replace(max_lane_ticks=1 << 16)
    spb, dtsb = kv.kv_packed_layout(big, KV_KCFG)
    assert np.dtype(spb.index) == np.uint32
    assert np.dtype(dtsb["applied"]) == np.uint32

    # shardkv: counts pin to n_clients x seq; phases to u8; the raft index
    # bound includes the marker-entry append rate
    ssp, _, sdts = skv.shardkv_packed_layout(SKV_CFG, SKV_KCFG)
    sb = packed_bounds(SKV_CFG)
    sseq = min(sb.tick, skv._SEQ_LIM - 1)
    assert np.dtype(sdts["key_count"]) == np.dtype(
        st.uint_for(SKV_KCFG.n_clients * sseq)
    )
    assert np.dtype(sdts["phase"]) == np.uint8
    assert np.iinfo(np.dtype(ssp.index)).max >= (
        (SKV_KCFG.n_clients + 2 * SKV_KCFG.n_shards + 2) * sb.tick + 1
    )
    # ctrler: gid maps pin to i8, config nums to their history bound
    _, cdts = ctl.ctrler_packed_layout(CTL_CFG, CTL_KCFG)
    assert np.dtype(cdts["owner"]) == np.int8
    assert np.dtype(cdts["cfg_num"]) == np.dtype(
        st.uint_for(CTL_KCFG.n_configs - 1)
    )


# ----------------------------------------------- packed-vs-wide bit-identity
def test_kv_fuzz_and_replay_bit_identical_across_layouts():
    fw = kv.make_kv_fuzz_fn(KV_CFG, KV_KCFG, 16, 128, pack_states=False)
    fp = kv.make_kv_fuzz_fn(KV_CFG, KV_KCFG, 16, 128, pack_states=True)
    assert fw.state_layout == "wide" and fp.state_layout == "packed"
    rw = kv.kv_report(jax.block_until_ready(fw(7)))
    rp = kv.kv_report(jax.block_until_ready(fp(7)))
    _reports_equal(rw, rp, "kv fuzz")
    # the fused composition (pack∘step∘unpack per field group) is a layout
    # choice, never a semantics choice
    ff = kv.make_kv_fuzz_fn(
        KV_CFG.replace(fuse_packed_step=True), KV_KCFG, 16, 128,
        pack_states=True,
    )
    _reports_equal(rw, kv.kv_report(jax.block_until_ready(ff(7))), "kv fused")
    # replay: same compiled-contract across layouts, bit for bit
    sw = kv.kv_replay_cluster(KV_CFG, KV_KCFG, 7, 3, 128, pack_states=False)
    sp_ = kv.kv_replay_cluster(KV_CFG, KV_KCFG, 7, 3, 128, pack_states=True)
    _trees_equal(sw, sp_, "kv replay")


def test_ctrler_fuzz_and_replay_bit_identical_across_layouts():
    fw = ctl.make_ctrler_fuzz_fn(CTL_CFG, CTL_KCFG, 16, 128,
                                 pack_states=False)
    fp = ctl.make_ctrler_fuzz_fn(CTL_CFG, CTL_KCFG, 16, 128,
                                 pack_states=True)
    rw = ctl.ctrler_report(jax.block_until_ready(fw(7)))
    rp = ctl.ctrler_report(jax.block_until_ready(fp(7)))
    _reports_equal(rw, rp, "ctrler fuzz")
    ff = ctl.make_ctrler_fuzz_fn(
        CTL_CFG.replace(fuse_packed_step=True), CTL_KCFG, 16, 128,
        pack_states=True,
    )
    _reports_equal(rw, ctl.ctrler_report(jax.block_until_ready(ff(7))),
                   "ctrler fused")
    sw = ctl.ctrler_replay_cluster(CTL_CFG, CTL_KCFG, 7, 2, 128,
                                   pack_states=False)
    sp_ = ctl.ctrler_replay_cluster(CTL_CFG, CTL_KCFG, 7, 2, 128,
                                    pack_states=True)
    _trees_equal(sw, sp_, "ctrler replay")


def test_shardkv_fuzz_bit_identical_across_layouts():
    fw = skv.make_shardkv_fuzz_fn(SKV_CFG, SKV_KCFG, 4, 160,
                                  pack_states=False)
    fp = skv.make_shardkv_fuzz_fn(SKV_CFG, SKV_KCFG, 4, 160,
                                  pack_states=True)
    rw = skv.shardkv_report(jax.block_until_ready(fw(7)))
    rp = skv.shardkv_report(jax.block_until_ready(fp(7)))
    _reports_equal(rw, rp, "shardkv fuzz")
    assert fp.state_layout == "packed"


@pytest.mark.slow
def test_shardkv_fused_bit_identical():
    """The fused composition on the heaviest stack — its own (slow-marked)
    compile; the kv/ctrler fused legs pin the same property in tier-1."""
    fw = skv.make_shardkv_fuzz_fn(SKV_CFG, SKV_KCFG, 4, 160,
                                  pack_states=False)
    rw = skv.shardkv_report(jax.block_until_ready(fw(7)))
    ff = skv.make_shardkv_fuzz_fn(
        SKV_CFG.replace(fuse_packed_step=True), SKV_KCFG, 4, 160,
        pack_states=True,
    )
    _reports_equal(rw, skv.shardkv_report(jax.block_until_ready(ff(7))),
                   "shardkv fused")


# --------------------------------------------------------- exact-or-wide
def test_wide_fallback_reasons_and_forced_pack_rejection():
    kn, kkn = KV_CFG.knobs(), KV_KCFG.knobs()
    # raft-layer gate propagates through every service gate
    r = kv.kv_packed_layout_reason(KV_CFG, KV_KCFG, kn, kkn,
                                   KV_CFG.max_lane_ticks + 1)
    assert r is not None and "max_lane_ticks" in r
    # kv gate: an await countdown beyond the tick dtype
    big_wait = KV_KCFG.replace(retry_wait=packed_bounds(KV_CFG).tick + 1)
    r = kv.kv_packed_layout_reason(KV_CFG, big_wait, kn, big_wait.knobs(),
                                   128)
    assert r is not None and "retry_wait" in r
    fn = kv.make_kv_fuzz_fn(KV_CFG, big_wait, 4, 64)
    assert fn.state_layout == "wide" and "retry_wait" in fn.state_layout_reason
    with pytest.raises(ValueError, match="retry_wait"):
        kv.make_kv_fuzz_fn(KV_CFG, big_wait, 4, 64, pack_states=True)
    # shardkv gates: inter-group delays and the dup-table bug
    skn = SKV_KCFG.replace(pull_delay_max=300)
    r = skv.shardkv_packed_layout_reason(SKV_CFG, skn, SKV_CFG.knobs(),
                                         skn.knobs(), 128)
    assert r is not None and "pull_delay_max" in r
    skn = SKV_KCFG.replace(bug_drop_dup_table=True)
    r = skv.shardkv_packed_layout_reason(SKV_CFG, skn, SKV_CFG.knobs(),
                                         skn.knobs(), 128)
    assert r is not None and "bug_drop_dup_table" in r
    # ctrler carries no extra dynamic gates: the raft rule is the rule
    assert ctl.ctrler_packed_layout_reason(
        CTL_CFG, CTL_KCFG, CTL_CFG.knobs(), CTL_KCFG.knobs(), 128
    ) is None


# ----------------------------------------------------------------- footprint
def test_service_footprint_reduction():
    """The ISSUE 11 headline bound: >= 1.5x fewer bytes per deployment on
    the shardkv bench shape (and the kv/ctrler stacks shrink too)."""
    s = skv.init_shardkv_cluster(SKV_CFG, SKV_KCFG, jax.random.PRNGKey(0))
    wide = st.tree_bytes(s)
    packed = st.tree_bytes(skv.pack_shardkv_state(SKV_CFG, SKV_KCFG, s))
    assert wide / packed >= 1.5, (wide, packed)

    ks = kv.init_kv_cluster(KV_CFG, KV_KCFG, jax.random.PRNGKey(0))
    assert st.tree_bytes(ks) / st.tree_bytes(
        kv.pack_kv_state(KV_CFG, KV_KCFG, ks)
    ) >= 1.5
    cs = ctl.init_ctrler_cluster(CTL_CFG, CTL_KCFG, jax.random.PRNGKey(0))
    assert st.tree_bytes(cs) / st.tree_bytes(
        ctl.pack_ctrler_state(CTL_CFG, CTL_KCFG, cs)
    ) >= 1.4
