"""Mutation testing for the safety oracles: the planted-bug library.

Each classic Raft implementation bug (config.py RAFT_BUGS) is injected into
the batched step function and the matching oracle must catch it within a
modest fuzz budget. This is the proof of bug-finding power the reference
implies but cannot contain (its algorithm bodies are todo!() stubs): the
tests that would fail on a wrong implementation — Figure-8 commit loss
(/root/reference/src/raft/tests.rs:612-660), vote-restriction violations,
persistence bugs (tests.rs:482-610), conflict-truncation bugs
(tests.rs:278-314 rejoin) — here run as deliberate mutations the fuzzer
must flag. The same bug names replay on the C++ backend via MADTPU_BUG
(cpp/raftcore/raft.cpp) so every TPU-found class cross-validates.

Profile notes (tuned empirically; each bug has a characteristic window):
- commit_any_term needs a LONG old-term catch-up phase: ae_max=1 slows
  replication so a fresh leader's majority-match lands on old-term entries
  well before its no-op commits; crashes must be rare enough that commits
  keep happening but common enough to depose leaders mid-catch-up.
- forget_voted_for's double-vote window is one RequestVote flight: the
  voter must vote, crash, and restart while a rival's RV is in the air —
  delay_max widens the flight; 5 nodes give three voters' worth of chances.
"""

import numpy as np

from madraft_tpu.tpusim import SimConfig, fuzz
from madraft_tpu.tpusim.config import (
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
)

# Election/replication churn with client load, mirroring the figure_8_2c
# storm (/root/reference/src/raft/tests.rs:612-660): leaders crash often,
# the network repartitions, commits keep happening between faults.
STORM = SimConfig(
    n_nodes=5,
    p_client_cmd=0.3,
    p_crash=0.05,
    p_restart=0.3,
    max_dead=2,
    p_repartition=0.03,
    p_heal=0.05,
    loss_prob=0.1,
)

# Slow-catch-up storm for the Figure-8 commit bug (see module docstring).
FIG8 = STORM.replace(
    ae_max=1, delay_max=5, p_repartition=0.03, loss_prob=0.1, p_client_cmd=0.4,
)

# Crash-while-voting storm for the votedFor-persistence bug: 7 nodes give
# five voters' worth of double-vote chances, short timeouts give ~2x the
# elections, delay_max=6 widens each RequestVote's crash-restart window
# (the rate is thin — a few per thousand clusters — because the revote must
# land inside ONE RV flight while both same-term candidates stay live).
REVOTE = STORM.replace(
    n_nodes=7, max_dead=3, p_crash=0.15, p_restart=0.6, delay_max=6,
    election_timeout_min=10, election_timeout_max=20, p_client_cmd=0.1,
)


def _bits(rep):
    return rep.violations[rep.violating_clusters()]


def test_bug_commit_any_term_caught():
    # THE Figure-8 bug: commit by counting replicas of an old-term entry.
    # A later leader that never saw the entry overwrites it => the commit
    # shadow (committed entries are immutable) must fire.
    rep = fuzz(FIG8.replace(bug="commit_any_term"), seed=8,
               n_clusters=1024, n_ticks=1000)
    assert rep.n_violating > 0, "figure-8 commit bug escaped the oracles"
    assert (_bits(rep) & VIOLATION_COMMIT_SHADOW).any()


def test_bug_grant_any_vote_caught():
    # Without the §5.4.1 up-to-date check a stale-log candidate wins and
    # overwrites entries another leader committed.
    rep = fuzz(STORM.replace(bug="grant_any_vote"), seed=9,
               n_clusters=256, n_ticks=600)
    assert rep.n_violating > 0, "vote-restriction bug escaped the oracles"
    assert (_bits(rep) & (VIOLATION_COMMIT_SHADOW | VIOLATION_LOG_MATCHING)).any()


def test_bug_forget_voted_for_caught():
    # votedFor not persisted: a voter that crashes and restarts within one
    # term can vote twice, electing two leaders in that term.
    rep = fuzz(REVOTE.replace(bug="forget_voted_for"), seed=8,
               n_clusters=2048, n_ticks=1000)
    assert rep.n_violating > 0, "votedFor-persistence bug escaped the oracles"
    assert (_bits(rep) & VIOLATION_DUAL_LEADER).any()


def test_bug_no_truncate_caught():
    # A follower that never truncates a conflicting suffix keeps stale
    # entries past a rewritten prefix => pairwise log matching breaks.
    rep = fuzz(STORM.replace(bug="no_truncate"), seed=11,
               n_clusters=256, n_ticks=600)
    assert rep.n_violating > 0, "truncation bug escaped the oracles"
    assert (_bits(rep) & (VIOLATION_LOG_MATCHING | VIOLATION_COMMIT_SHADOW)).any()


def test_clean_storms_stay_clean():
    # The same storms with the correct algorithm: zero violations — the bug
    # tests above prove the oracles CAN fire; this proves they fire only on
    # real bugs (same seeds, same schedule intensities).
    for cfg, seed in ((STORM, 9), (FIG8, 8), (REVOTE, 10)):
        rep = fuzz(cfg, seed=seed, n_clusters=256, n_ticks=600)
        assert rep.n_violating == 0, (
            f"false positive {np.unique(_bits(rep))} on {cfg}"
        )
