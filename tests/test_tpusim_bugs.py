"""Mutation testing for the safety oracles: the planted-bug library.

Each classic Raft implementation bug (config.py RAFT_BUGS) is injected into
the batched step function and the matching oracle must catch it within a
modest fuzz budget. This is the proof of bug-finding power the reference
implies but cannot contain (its algorithm bodies are todo!() stubs): the
tests that would fail on a wrong implementation — Figure-8 commit loss
(/root/reference/src/raft/tests.rs:612-660), vote-restriction violations,
persistence bugs (tests.rs:482-610), conflict-truncation bugs
(tests.rs:278-314 rejoin) — here run as deliberate mutations the fuzzer
must flag. The same bug names replay on the C++ backend via MADTPU_BUG
(cpp/raftcore/raft.cpp) so every TPU-found class cross-validates.

Profile notes (tuned empirically; each bug has a characteristic window):
- commit_any_term needs a LONG old-term catch-up phase: ae_max=1 slows
  replication so a fresh leader's majority-match lands on old-term entries
  well before its no-op commits; crashes must be rare enough that commits
  keep happening but common enough to depose leaders mid-catch-up.
- forget_voted_for's double-vote window is one RequestVote flight: the
  voter must vote, crash, and restart while a rival's RV is in the air —
  delay_max widens the flight; 5 nodes give three voters' worth of chances.
"""

import numpy as np

from madraft_tpu.tpusim import SimConfig, fuzz
from madraft_tpu.tpusim.config import (
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
    VIOLATION_PREFIX_DIVERGE,
)

# The tuned storms live in config.storm_profiles() — ONE source shared with
# the CLI --profile presets, so the demonstrated (profile, bug, scale)
# triples can never drift from what these tests validate. Profile shapes:
# STORM mirrors the figure_8_2c churn (/root/reference/src/raft/
# tests.rs:612-660); FIG8 is the slow-catch-up variant; REVOTE the
# crash-while-voting 7-node storm (see module docstring).
from madraft_tpu.tpusim.config import storm_profiles

_PROFILES = storm_profiles()
STORM = _PROFILES["storm"][0]
FIG8 = _PROFILES["fig8"][0]
REVOTE = _PROFILES["revote"][0]
DURABILITY = _PROFILES["durability"][0]


def test_profiles_scale_matches_demonstrations():
    """The CLI presets advertise exactly the (clusters, ticks) these tests
    demonstrate each bug at — keep them honest."""
    assert _PROFILES["fig8"][1:3] == (1024, 1000)
    assert _PROFILES["revote"][1:3] == (2048, 1000)
    assert _PROFILES["storm"][1:3] == (256, 600)
    assert _PROFILES["durability"][1:3] == (256, 600)
    assert "commit_any_term" in _PROFILES["fig8"][3]
    assert "forget_voted_for" in _PROFILES["revote"][3]
    assert set(_PROFILES["storm"][3]) == {"grant_any_vote", "no_truncate"}
    assert set(_PROFILES["durability"][3]) == {"ack_before_fsync"}


def _bits(rep):
    return rep.violations[rep.violating_clusters()]


def test_bug_commit_any_term_caught():
    # THE Figure-8 bug: commit by counting replicas of an old-term entry.
    # A later leader that never saw the entry overwrites it => the commit
    # shadow (committed entries are immutable) must fire.
    # 512 clusters: measured 3 violating / 3 commit-shadow at this size —
    # deterministic margin for the asserts at half the batch (CI wall).
    rep = fuzz(FIG8.replace(bug="commit_any_term"), seed=8,
               n_clusters=512, n_ticks=1000)
    assert rep.n_violating > 0, "figure-8 commit bug escaped the oracles"
    assert (_bits(rep) & VIOLATION_COMMIT_SHADOW).any()


def test_bug_grant_any_vote_caught():
    # Without the §5.4.1 up-to-date check a stale-log candidate wins and
    # overwrites entries another leader committed.
    rep = fuzz(STORM.replace(bug="grant_any_vote"), seed=9,
               n_clusters=256, n_ticks=600)
    assert rep.n_violating > 0, "vote-restriction bug escaped the oracles"
    assert (_bits(rep) & (VIOLATION_COMMIT_SHADOW | VIOLATION_LOG_MATCHING)).any()


def test_bug_forget_voted_for_caught():
    # votedFor not persisted: a voter that crashes and restarts within one
    # term can vote twice, electing two leaders in that term.
    # 512 clusters: deterministic for a fixed (seed, shape), and measured 4
    # violating / 3 dual-leader at this size — enough margin for the > 0
    # asserts without the 2048-cluster batch (168s of 2-core CI wall).
    rep = fuzz(REVOTE.replace(bug="forget_voted_for"), seed=8,
               n_clusters=512, n_ticks=1000)
    assert rep.n_violating > 0, "votedFor-persistence bug escaped the oracles"
    assert (_bits(rep) & VIOLATION_DUAL_LEADER).any()


def test_bug_no_truncate_caught():
    # A follower that never truncates a conflicting suffix keeps stale
    # entries past a rewritten prefix => pairwise log matching breaks.
    rep = fuzz(STORM.replace(bug="no_truncate"), seed=11,
               n_clusters=256, n_ticks=600)
    assert rep.n_violating > 0, "truncation bug escaped the oracles"
    assert (_bits(rep) & (VIOLATION_LOG_MATCHING | VIOLATION_COMMIT_SHADOW)).any()


def test_bug_ack_before_fsync_caught():
    # The classic "reply before fsync" production bug: RV/AE handlers ack
    # from volatile state. Under the durability storm (every crash drops
    # the un-fsynced suffix, background fsync every 8 ticks) a follower's
    # acked-but-volatile entries get commit-counted, crash away, and a
    # later leader re-mints their indices — the commit-shadow / prefix-hash
    # durability oracles must fire. The same storm with the correct
    # algorithm is pinned clean by tests/test_tpusim_durability.py.
    rep = fuzz(DURABILITY.replace(bug="ack_before_fsync"), seed=8,
               n_clusters=256, n_ticks=600)
    assert rep.n_violating > 0, "ack-before-fsync bug escaped the oracles"
    assert (_bits(rep) & (VIOLATION_COMMIT_SHADOW | VIOLATION_PREFIX_DIVERGE)).any()


def test_clean_storms_stay_clean():
    # The same storms with the correct algorithm: zero violations — the bug
    # tests above prove the oracles CAN fire; this proves they fire only on
    # real bugs (same seeds, same schedule intensities).
    for cfg, seed in ((STORM, 9), (FIG8, 8), (REVOTE, 10)):
        rep = fuzz(cfg, seed=seed, n_clusters=256, n_ticks=600)
        assert rep.n_violating == 0, (
            f"false positive {np.unique(_bits(rep))} on {cfg}"
        )
