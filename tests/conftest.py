"""Test configuration: force a 2-device virtual CPU mesh for sharding tests.

Must run before the first `import jax` in the process (pytest imports conftest
first). Bench (`bench.py`) and the graft entry are unaffected — they run outside
pytest and see the real TPU.

Why 2 virtual devices and not 8: the CI box has 2 physical cores, and forcing
8 host devices costs ~1.5x wall on every single-device program in the suite
(measured: the 256x600 storm fuzz executes in 7.0s under 2 devices vs 10.7s
under 8 — the extra fake devices fragment the XLA CPU client's thread pool).
Every sharding property the suite checks (sharded == unsharded, device_set
coverage, mesh divisibility errors) is exercised by ANY >= 2-device mesh;
the mesh tests build their mesh from jax.devices() and skip below 2.

Escape hatch: set MADRAFT_TPU_TESTS=1 to skip the CPU override and run the
suite against whatever platform the environment provides (e.g. a real TPU).
The container's interpreter-startup hook (sitecustomize) force-registers the
TPU tunnel as "axon,cpu" regardless of JAX_PLATFORMS — that is why the
override re-asserts the jax config after import instead of relying on the
env var alone.
"""

import contextlib
import os

_ON_TPU = os.environ.get("MADRAFT_TPU_TESTS") == "1"

if not _ON_TPU:
    # Hard assignment, not setdefault: the driver environment presets
    # JAX_PLATFORMS (e.g. the TPU tunnel), and tests must still run on the
    # virtual CPU mesh — single-core TPU can't exercise the sharding path.
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Replace (not just append around) any preset device count: 2 is a perf
    # invariant now, and a leaked preset — e.g. the dryrun_multichip(8) env
    # from the verify recipe — would silently re-impose the 1.5x slowdown.
    _flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()

import jax  # noqa: E402

if not _ON_TPU:
    # Backends are not initialized yet at conftest time, so this sticks.
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is compile-dominated (many distinct
# (config, shape) step programs); with the cache warm a full run saves minutes
# of compile. One shared configuration (madraft_tpu._platform) — the CLI
# entry point enables the same cache, so suite and CLI runs feed each other.
from madraft_tpu._platform import enable_compilation_cache  # noqa: E402

enable_compilation_cache(os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
# CAUTION: XLA's executable.serialize() SEGFAULTS on this container for the
# largest mesh-sharded shardkv executable (jax compilation_cache
# put_executable_and_time, reproduced 4x in round 5 — localized by the
# faulthandler trace, NOT a madtpu bug). Tests that compile that program
# wrap themselves in no_persistent_cache() below; everything else caches.


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy compile/runtime tests excluded from tier-1 "
        "(-m 'not slow'); run explicitly or via -m slow",
    )


def pytest_collection_modifyitems(session, config, items):
    """Run the shardkv module FIRST (file order is otherwise alphabetical).

    Its programs are the largest in the suite, and the XLA CPU client
    degrades as executables accumulate: the same shardkv tests measured
    ~2.6x slower after ~600 prior programs (module total 630s late in the
    suite vs 240s when only the kv module preceded it, warm persistent
    cache both times). Running it on a young process restores the fast
    measurements AND keeps the module out of the round-5 segfault zone
    (crashes reproduced only after 100+ prior programs — see the module's
    own fixture). The small programs that now run after it don't care:
    their per-program footprint is tiny.
    """
    front = [it for it in items if "test_tpusim_shardkv" in str(it.fspath)]
    if front:
        rest = [it for it in items if "test_tpusim_shardkv" not in str(it.fspath)]
        items[:] = front + rest


def cluster_mesh(batch):
    """A Mesh over the cluster axis built from the largest prefix of
    jax.devices() whose count divides `batch`; skips the calling test when
    no >= 2-device mesh exists. Mesh tests share this so they run on any
    device count (2 in CI, 8+ on a pod) instead of skipping when the full
    count doesn't divide the batch."""
    import numpy as np
    import pytest

    ndev = len(jax.devices())
    while ndev > 1 and batch % ndev:
        ndev -= 1
    if ndev < 2:
        pytest.skip("needs a >= 2-device mesh")
    return jax.sharding.Mesh(np.array(jax.devices()[:ndev]), ("clusters",))


@contextlib.contextmanager
def no_persistent_cache():
    """Temporarily disable persistent-cache WRITES (see CAUTION).

    Setting jax_compilation_cache_dir to None here does NOT work: the cache
    object initializes at most once per process (compilation_cache._get_cache)
    and later config changes are ignored. The min-compile-time threshold IS
    read live by compiler._cache_write, so an absurd floor skips every write.
    """
    old = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1e9)
    try:
        yield
    finally:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", old
        )
