"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Must run before the first `import jax` in the process (pytest imports conftest
first). Bench (`bench.py`) and the graft entry are unaffected — they run outside
pytest and see the real TPU.

Escape hatch: set MADRAFT_TPU_TESTS=1 to skip the CPU override and run the
suite against whatever platform the environment provides (e.g. a real TPU).
The container's interpreter-startup hook (sitecustomize) force-registers the
TPU tunnel as "axon,cpu" regardless of JAX_PLATFORMS — that is why the
override re-asserts the jax config after import instead of relying on the
env var alone.
"""

import contextlib
import os

_ON_TPU = os.environ.get("MADRAFT_TPU_TESTS") == "1"

if not _ON_TPU:
    # Hard assignment, not setdefault: the driver environment presets
    # JAX_PLATFORMS (e.g. the TPU tunnel), and tests must still run on the
    # virtual CPU mesh — single-core TPU can't exercise the 8-way sharding path.
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _ON_TPU:
    # Backends are not initialized yet at conftest time, so this sticks.
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is compile-dominated (many distinct
# (config, shape) step programs); with the cache warm a full run saves minutes
# of compile. One shared configuration (madraft_tpu._platform) — the CLI
# entry point enables the same cache, so suite and CLI runs feed each other.
from madraft_tpu._platform import enable_compilation_cache  # noqa: E402

enable_compilation_cache(os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
# CAUTION: XLA's executable.serialize() SEGFAULTS on this container for the
# largest mesh-sharded shardkv executable (jax compilation_cache
# put_executable_and_time, reproduced 4x in round 5 — localized by the
# faulthandler trace, NOT a madtpu bug). Tests that compile that program
# wrap themselves in no_persistent_cache() below; everything else caches.


@contextlib.contextmanager
def no_persistent_cache():
    """Temporarily disable persistent-cache WRITES (see CAUTION).

    Setting jax_compilation_cache_dir to None here does NOT work: the cache
    object initializes at most once per process (compilation_cache._get_cache)
    and later config changes are ignored. The min-compile-time threshold IS
    read live by compiler._cache_write, so an absurd floor skips every write.
    """
    old = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1e9)
    try:
        yield
    finally:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", old
        )
