"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Must run before the first `import jax` in the process (pytest imports conftest
first). Bench (`bench.py`) and the graft entry are unaffected — they run outside
pytest and see the real TPU.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
