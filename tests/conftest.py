"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Must run before the first `import jax` in the process (pytest imports conftest
first). Bench (`bench.py`) and the graft entry are unaffected — they run outside
pytest and see the real TPU.
"""

import os

# Hard assignment, not setdefault: the driver environment presets
# JAX_PLATFORMS (e.g. the TPU tunnel), and tests must still run on the
# virtual CPU mesh — single-core TPU can't exercise the 8-way sharding path.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's interpreter-startup hook (sitecustomize) registers the
# TPU-tunnel plugin and force-updates jax's platform config to "axon,cpu",
# defeating the env var above. Re-assert CPU after import — backends are not
# initialized yet at conftest time, so this sticks.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
