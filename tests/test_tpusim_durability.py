"""The storage-durability fault axis (madsim's `fs` layer: crash/restore
with PARTIALLY durable files — SURVEY §0/§2.6; state.py durability notes).

Every node carries an fsync watermark (durable_len + durable term/voted_for
shadows); writes become durable when an fsync boundary passes (the
``fsync_every`` cadence or one of the explicit persist sites), and a crash
with ``p_lose_unsynced`` rolls the log/term/vote back to the watermark. The
correct algorithm fsyncs before every state-exposing emission
(persist-before-reply, raft.rs:224-233), so it must stay clean under a
full-loss crash storm; the planted ``ack_before_fsync`` bug (the classic
"reply before fsync" production consensus bug) strips exactly the handler
syncs and must be caught — see test_tpusim_bugs.py for the catch row.
"""

import jax
import numpy as np

from madraft_tpu.tpusim import SimConfig, fuzz
from madraft_tpu.tpusim.config import storm_profiles
from madraft_tpu.tpusim.engine import replay_cluster
from madraft_tpu.tpusim.state import init_cluster
from madraft_tpu.tpusim.step import step_cluster

_PROFILES = storm_profiles()
DURABILITY = _PROFILES["durability"][0]


def test_clean_under_suffix_loss_storm():
    # The correct algorithm under TOTAL suffix loss (every crash drops the
    # un-fsynced tail) and a slow background fsync: persist-before-reply
    # must keep every committed entry on a durable majority — zero
    # violations, and the storm must still commit (the axis is not clean
    # merely because nothing happened).
    assert DURABILITY.p_lose_unsynced == 1.0
    assert DURABILITY.fsync_every > DURABILITY.delay_max  # real volatility
    rep = fuzz(DURABILITY, seed=1, n_clusters=256, n_ticks=600)
    assert rep.n_violating == 0, (
        f"false positive under suffix-loss storm: "
        f"{np.unique(rep.violations[rep.violating_clusters()])}"
    )
    assert (rep.committed > 0).mean() > 0.9, "storm starved commit progress"


def _scan_cluster(cfg, seed, n_ticks, cluster_id=0):
    """Single-cluster trajectory of (durable_len, log_len, base,
    durable_term, term) per tick."""
    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)
    kn = cfg.knobs()

    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = step_cluster(cfg, carry, key, kn)
            return nxt, (nxt.durable_len, nxt.log_len, nxt.base,
                         nxt.durable_term, nxt.term)

        return jax.lax.scan(
            body, init_cluster(cfg, key, kn), None, length=n_ticks
        )[1]

    return [np.asarray(x) for x in jax.block_until_ready(run(ckey))]


def test_watermark_invariants_under_storm():
    # base <= durable_len <= log_len at every tick (the rolled-back window
    # stays legal and disk never claims more than memory), and the durable
    # term shadow is monotone (a crash rolls the LIVE term back to it, never
    # it below itself).
    dlen, llen, base, dterm, term = _scan_cluster(DURABILITY, 5, 500)
    assert (dlen <= llen).all(), "watermark claims more than the live log"
    assert (base <= dlen).all(), (
        "snapshot boundary passed the watermark — a crash could roll the "
        "log below its own base"
    )
    assert (dterm <= term).all()
    assert (np.diff(dterm, axis=0) >= 0).all(), "durable term went backward"


def test_fsync_every_tick_is_perfect_persistence():
    # fsync_every=1 (the default): durable == live at every tick end — the
    # historic model, under which p_lose_unsynced can never bite.
    cfg = DURABILITY.replace(fsync_every=1)
    dlen, llen, base, dterm, term = _scan_cluster(cfg, 3, 300)
    assert (dlen == llen).all()
    assert (dterm == term).all()


def test_inert_axis_leaves_reports_unchanged():
    # p_lose_unsynced=0 gates the whole axis: a lazy fsync cadence alone
    # must not change a single report field (the rollback is the only
    # consumer of the watermark) — and the knobs being dynamic, both runs
    # share one compiled program.
    storm = _PROFILES["storm"][0]
    a = fuzz(storm, seed=7, n_clusters=64, n_ticks=300)
    b = fuzz(storm.replace(fsync_every=8), seed=7, n_clusters=64, n_ticks=300)
    for f in a._fields:
        assert np.array_equal(getattr(a, f), getattr(b, f)), f


def test_suffix_loss_draws_are_deterministic():
    # The new fault draw (the low byte of the color words) is a pure
    # function of (seed, cluster, tick): double-running the bug storm must
    # be bit-identical — the MADSIM_TEST_CHECK_DETERMINISTIC contract holds
    # on the new axis.
    cfg = DURABILITY.replace(bug="ack_before_fsync")
    a = fuzz(cfg, seed=1, n_clusters=64, n_ticks=300)
    b = fuzz(cfg, seed=1, n_clusters=64, n_ticks=300)
    for f in a._fields:
        assert np.array_equal(getattr(a, f), getattr(b, f)), f
    assert a.n_violating > 0  # the small storm still manifests the bug


def test_replay_reproduces_durability_violation():
    # The (seed, cluster_id) replay contract extends to the new axis: a
    # violating cluster found by the batched bug sweep reproduces exactly
    # in the single-cluster replayer.
    cfg = DURABILITY.replace(bug="ack_before_fsync")
    rep = fuzz(cfg, seed=1, n_clusters=64, n_ticks=300)
    assert rep.n_violating > 0
    cid = int(rep.violating_clusters()[0])
    st = replay_cluster(cfg, seed=1, cluster_id=cid, n_ticks=300)
    assert int(st.violations) == int(rep.violations[cid])
    assert int(st.first_violation_tick) == int(rep.first_violation_tick[cid])


def test_durability_knob_validation():
    import pytest

    from madraft_tpu.tpusim.engine import _validate_knobs

    with pytest.raises(ValueError, match="fsync_every"):
        SimConfig(fsync_every=0)
    with pytest.raises(ValueError, match="p_lose_unsynced"):
        SimConfig(p_lose_unsynced=1.5)
    with pytest.raises(ValueError, match="fsync_every"):
        _validate_knobs(
            SimConfig().knobs()._replace(fsync_every=np.int32(0))
        )
    with pytest.raises(ValueError, match="p_lose_unsynced"):
        _validate_knobs(
            SimConfig().knobs()._replace(p_lose_unsynced=np.float32(-0.1))
        )
