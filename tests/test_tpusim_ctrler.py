"""Shard-controller fuzzing layer tests (Lab 4A on TPU): the canonical
rebalance (balance + minimality + determinism, cross-validated against an
independent numpy model), fuzzing under fault storms, oracle validation via
the three planted 4A bugs, determinism, replay, and sharded execution.

The reference 4A suite (/root/reference/src/shard_ctrler/tests.rs) asserts
balance (tester.rs:113-150), minimal transfers (tests.rs:122-163,239-278),
historical query_at (tests.rs:64-75), and config equality across leader
failover (tests.rs:280-296); these tests are the batched analogue.

Runs on the virtual CPU device mesh from conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.ctrler import (
    N_SHARDS,
    VIOLATION_CTRL_BALANCE,
    VIOLATION_CTRL_DIVERGE,
    VIOLATION_CTRL_MINIMAL,
    VIOLATION_CTRL_QUERY,
    CtrlerConfig,
    _min_moves,
    _rebalance,
    ctrler_fuzz,
    ctrler_replay_cluster,
    ctrler_report,
    make_ctrler_fuzz_fn,
)
from madraft_tpu.tpusim.state import I32

BASE = SimConfig(
    n_nodes=5,
    p_client_cmd=0.0,         # the ctrler layer owns command injection
    compact_at_commit=False,  # the layer drives the compaction boundary
    loss_prob=0.1,
    p_crash=0.01,
    p_restart=0.2,
    max_dead=2,
    p_repartition=0.02,
    p_heal=0.05,
    log_cap=32,
    compact_every=8,
)
CT = CtrlerConfig()
NG = CT.n_gids


# ------------------------------------------------------ numpy reference model
def ref_rebalance(member, owner):
    """Independent model of the canonical closed-form rebalance (ctrler.py
    _rebalance docstring): ceil targets to the biggest retainers (ties by
    lowest gid); each member keeps its first target-many shards by index;
    moving shards fill deficits in shard-index order, members by gid."""
    ng = len(member)
    ns = len(owner)
    own = [g if (0 <= g < ng and member[g]) else -1 for g in owner]
    memb = [g for g in range(ng) if member[g]]
    if not memb:
        return [-1] * ns
    k = len(memb)
    q, r = divmod(ns, k)
    retained = {g: sum(1 for x in own if x == g) for g in memb}
    by_load = sorted(memb, key=lambda g: (-retained[g], g))
    target = {g: q + (1 if i < r else 0) for i, g in enumerate(by_load)}
    kept = {g: 0 for g in memb}
    out = []
    moving = []
    for s, g in enumerate(own):
        if g >= 0 and kept[g] < target[g]:
            kept[g] += 1
            out.append(g)
        else:
            moving.append(s)
            out.append(None)
    slots = []
    for g in sorted(memb):  # assignment order: gid ascending (rot = 0)
        slots += [g] * (target[g] - kept[g])
    for s, g in zip(moving, slots):
        out[s] = g
    return out


def ref_min_moves(member, owner):
    ng = len(member)
    ns = len(owner)
    k = sum(member)
    valid = [0 <= g < ng and member[g] for g in owner]
    orphans = valid.count(False)
    retained = [
        sum(1 for s in range(ns) if valid[s] and owner[s] == g)
        for g in range(ng)
    ]
    q, r = divmod(ns, k)
    ret = sorted((retained[g] for g in range(ng) if member[g]), reverse=True)
    shed = sum(max(0, c - (q + 1 if i < r else q)) for i, c in enumerate(ret))
    return orphans + shed


def _random_states(rng, n_cases):
    for _ in range(n_cases):
        member = rng.random(NG) < 0.6
        if not member.any():
            member[rng.integers(NG)] = True
        # owners drawn from {-1} + all gids (including non-members: models the
        # post-Leave orphaning the rebalance must fix)
        owner = rng.integers(-1, NG, size=N_SHARDS)
        yield member.tolist(), owner.tolist()


def test_rebalance_matches_numpy_model():
    """The jnp rebalance equals the independent numpy model exactly, and the
    result is balanced, orphan-free, and minimal (moved == closed-form
    minimum) over hundreds of random membership/owner states."""
    rng = np.random.default_rng(42)
    off = jnp.bool_(False)
    for member, owner in _random_states(rng, 300):
        got = np.asarray(
            _rebalance(NG, jnp.asarray(member), jnp.asarray(owner, I32),
                       jnp.asarray(0, I32), off, off)
        )
        want = np.asarray(ref_rebalance(member, owner))
        np.testing.assert_array_equal(got, want, err_msg=f"{member} {owner}")
        # balance + no orphans
        counts = [int((got == g).sum()) for g in range(NG) if member[g]]
        assert all(member[g] for g in got), f"orphan/non-member in {got}"
        assert max(counts) - min(counts) <= 1, f"unbalanced {counts}"
        # minimality vs the pre-state (only shards with still-member owners
        # can be retained; the rest necessarily move)
        moved = int(
            (got != np.asarray(owner)).sum()
        )
        assert moved == ref_min_moves(member, owner), (
            f"{moved} moves, min {ref_min_moves(member, owner)} "
            f"for {member} {owner}"
        )
        assert ref_min_moves(member, owner) == int(
            _min_moves(NG, jnp.asarray(member), jnp.asarray(owner, I32))
        )


def test_rebalance_multigid_transition_chain_minimal():
    """Chained multi-gid Join/Leave transitions (1..3 gids per op — the
    reference's Join takes a MAP of groups and its suite fuzzes concurrent
    multijoins, msg.rs:20-37, tests.rs:216-237): every step must stay
    balanced, orphan-free, minimal-move, and equal to the numpy model."""
    rng = np.random.default_rng(9)
    off = jnp.bool_(False)
    member = np.zeros(NG, bool)
    owner = np.full(N_SHARDS, -1, np.int64)
    multi = 0
    for _ in range(200):
        mask = np.zeros(NG, bool)
        picks = rng.choice(NG, size=int(rng.integers(1, 4)), replace=False)
        mask[picks] = True
        new_member = (member | mask) if rng.random() < 0.55 else (member & ~mask)
        if not new_member.any() or (new_member == member).all():
            continue
        multi += int(np.sum(new_member != member) >= 2)
        got = np.asarray(
            _rebalance(NG, jnp.asarray(new_member), jnp.asarray(owner, I32),
                       jnp.asarray(0, I32), off, off)
        )
        want = np.asarray(ref_rebalance(new_member.tolist(), owner.tolist()))
        np.testing.assert_array_equal(got, want)
        counts = [int((got == g).sum()) for g in range(NG) if new_member[g]]
        assert all(new_member[g] for g in got)
        assert max(counts) - min(counts) <= 1
        assert int((got != owner).sum()) == ref_min_moves(
            new_member.tolist(), owner.tolist()
        )
        member, owner = new_member, got.astype(np.int64)
    assert multi > 40, "the chain barely exercised multi-gid transitions"


def test_rebalance_tie_rotation_permutes_but_stays_balanced():
    """Rotated tie-breaking (the planted divergence bug) must still produce a
    balanced minimal assignment — only a DIFFERENT one, so the divergence
    oracle (not balance/minimality) is what catches it."""
    rng = np.random.default_rng(7)
    off = jnp.bool_(False)
    differs = 0
    for member, owner in _random_states(rng, 60):
        a = np.asarray(_rebalance(NG, jnp.asarray(member),
                                  jnp.asarray(owner, I32),
                                  jnp.asarray(0, I32), off, off))
        b = np.asarray(_rebalance(NG, jnp.asarray(member),
                                  jnp.asarray(owner, I32),
                                  jnp.asarray(1, I32), off, off))
        counts = [int((b == g).sum()) for g in range(NG) if member[g]]
        assert max(counts) - min(counts) <= 1
        assert int((b != np.asarray(owner)).sum()) == ref_min_moves(member, owner)
        differs += int(not np.array_equal(a, b))
    assert differs > 10, "rotation never changed an assignment — bug is inert"


def test_ctrler_fuzz_clean():
    """Fault storm over many clusters: no violations; Join/Leave/Move/Query
    all flow (configs are created and historical queries complete)."""
    rep = ctrler_fuzz(BASE, CT, seed=11, n_clusters=96, n_ticks=320)
    assert rep.n_violating == 0, (
        f"violations in clusters {rep.violating_clusters()[:8]}: "
        f"{rep.violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.acked_ops > 0).mean() > 0.9
    assert rep.configs_created.sum() > 96 * 3, "reconfigurations must flow"
    assert rep.queries_done.sum() > 96, "historical queries must complete"
    assert not rep.walker_stalled.any(), (
        "truth walker fell behind the shadow window: 4A oracle coverage lost"
    )


def test_ctrler_walker_stall_is_sticky_and_reported():
    """A walker whose next entry has been overwritten by shadow-ring
    wraparound must raise the sticky stalled flag instead of silently
    standing the oracles down — a clean report with a frozen frontier is
    indistinguishable from real coverage (round-3 advisor finding).

    A live fuzz cannot reach this state (measured commit throughput is
    ~0.5 entries/tick, below any walk budget), so the window-slid state is
    constructed directly: shadow_base past the frontier, exactly the
    configuration a commit burst > log_cap would leave behind."""
    from madraft_tpu.tpusim.ctrler import ctrler_step, init_ctrler_cluster

    cfg = BASE.replace(loss_prob=0.0, p_crash=0.0, p_repartition=0.0)
    cap = cfg.log_cap
    key = jax.random.PRNGKey(3)
    ks = init_ctrler_cluster(cfg, CT, key)
    behind = ks._replace(
        raft=ks.raft._replace(
            shadow_len=jnp.asarray(cap + 5, I32),
            shadow_base=jnp.asarray(5, I32),
        )
    )
    out = jax.jit(
        lambda s, k: ctrler_step(cfg, CT, s, k)
    )(behind, key)
    assert bool(out.w_stalled), "slid-window walker must report the stall"
    # and it is sticky: a later tick with the same frontier keeps it set
    out2 = jax.jit(lambda s, k: ctrler_step(cfg, CT, s, k))(out, key)
    assert bool(out2.w_stalled)
    # a healthy run never sets it (covered in test_ctrler_fuzz_clean)


def test_ctrler_rotate_tiebreak_diverges():
    """Node-id-rotated tie-breaking — the batched analogue of iterating a
    HashMap in the rebalance (README.md:79's determinism warning) — must trip
    the replica-divergence oracle, NOT balance/minimality (each replica's
    answer is individually balanced and minimal, they just disagree)."""
    rep = ctrler_fuzz(BASE, CT.replace(bug_rotate_tiebreak=True), seed=11,
                      n_clusters=96, n_ticks=320)
    assert rep.n_violating > 0, "replica-divergent rebalance escaped"
    bits = rep.violations[rep.violating_clusters()]
    assert (bits & VIOLATION_CTRL_DIVERGE).any()
    assert not (bits & (VIOLATION_CTRL_BALANCE | VIOLATION_CTRL_MINIMAL)).any()
    # diverged replicas also serve diverging historical-query answers, so the
    # query_at oracle must catch some of them — this validates CTRL_QUERY
    assert (bits & VIOLATION_CTRL_QUERY).any(), (
        "no diverged query observation was caught by the query_at oracle"
    )


def test_ctrler_greedy_rebalance_unbalances():
    """Dumping every orphan on one group with no balancing pass must trip the
    balance oracle (tester.rs:134-150's max-min<=1 check)."""
    rep = ctrler_fuzz(BASE, CT.replace(bug_greedy_rebalance=True), seed=11,
                      n_clusters=96, n_ticks=320)
    assert rep.n_violating > 0, "unbalanced rebalance escaped"
    bits = rep.violations[rep.violating_clusters()]
    assert (bits & VIOLATION_CTRL_BALANCE).any()


def test_ctrler_full_reshuffle_moves_too_much():
    """A balanced from-scratch reassignment that ignores retention must trip
    the minimality oracle (tests.rs:122-163's minimal-transfer checks) while
    staying balanced."""
    rep = ctrler_fuzz(BASE, CT.replace(bug_full_reshuffle=True), seed=11,
                      n_clusters=96, n_ticks=384)
    assert rep.n_violating > 0, "retention-blind rebalance escaped"
    bits = rep.violations[rep.violating_clusters()]
    assert (bits & VIOLATION_CTRL_MINIMAL).any()
    assert not (bits & VIOLATION_CTRL_BALANCE).any(), (
        "round-robin reassignment is balanced; only minimality should fire"
    )


def test_ctrler_leader_targeted_cuts():
    """The 4A service under leader-in-minority partitions and asymmetric
    one-sided cuts (kvraft tester.rs:184-191's scenario on the config
    service): a deposed-but-unaware leader keeps accepting Join/Leave/Query
    ops that must be superseded without breaking any 4A oracle — the
    failover path behind the reference's config-equality-across-leader-kill
    assertions (shard_ctrler/tests.rs:280-296)."""
    cfg = BASE.replace(
        p_repartition=0.0, p_leader_part=0.03, p_asym_cut=0.05, p_heal=0.06,
    )
    rep = ctrler_fuzz(cfg, CT, seed=29, n_clusters=96, n_ticks=384)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.acked_ops > 0).mean() > 0.9
    assert rep.configs_created.sum() > 96 * 3
    assert rep.queries_done.sum() > 96


def test_ctrler_deterministic_and_replay():
    """Same seed => bit-identical report; single-cluster replay reproduces —
    the (seed, cluster_id) replay contract (README.md:42-55)."""
    r1 = ctrler_fuzz(BASE, CT, seed=123, n_clusters=48, n_ticks=256)
    r2 = ctrler_fuzz(BASE, CT, seed=123, n_clusters=48, n_ticks=256)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)
    final = ctrler_replay_cluster(BASE, CT, seed=123, cluster_id=3,
                                  n_ticks=256)
    assert int(final.raft.violations) == int(r1.violations[3])
    assert int(final.clerk_acked.sum()) == int(r1.acked_ops[3])
    assert int(final.w_cfg_num) == int(r1.configs_created[3])
    assert int(final.raft.msg_count) == int(r1.msg_count[3])


def test_ctrler_sharded_over_mesh():
    """The cluster axis shards over the device mesh, results identical."""
    from conftest import cluster_mesh

    mesh = cluster_mesh(64)
    fn = make_ctrler_fuzz_fn(BASE, CT, n_clusters=64, n_ticks=128, mesh=mesh)
    rep_sharded = ctrler_report(
        jax.block_until_ready(fn(jnp.asarray(5, jnp.uint32)))
    )
    rep_local = ctrler_fuzz(BASE, CT, seed=5, n_clusters=64, n_ticks=128)
    np.testing.assert_array_equal(rep_sharded.violations, rep_local.violations)
    np.testing.assert_array_equal(rep_sharded.acked_ops, rep_local.acked_ops)
    np.testing.assert_array_equal(
        rep_sharded.configs_created, rep_local.configs_created
    )
    assert rep_sharded.n_violating == 0


def test_ctrler_sweep_per_cluster_knobs_and_bugs():
    """4A sweeps (make_ctrler_sweep_fn): uniform-valued sweep reproduces
    the uniform program exactly, and a per-cluster bug axis (greedy
    rebalance in the first half) lands every violation in that half."""
    from madraft_tpu.tpusim.ctrler import (
        ctrler_report,
        make_ctrler_sweep_fn,
    )

    n, ticks = 48, 320
    fn = make_ctrler_sweep_fn(BASE, BASE.knobs(), CT.knobs(), CT, n, ticks)
    rep_sweep = ctrler_report(
        jax.block_until_ready(fn(jnp.asarray(11, jnp.uint32)))
    )
    rep_uni = ctrler_fuzz(BASE, CT, seed=11, n_clusters=n, n_ticks=ticks)
    for a, b in zip(rep_sweep, rep_uni):
        np.testing.assert_array_equal(a, b)

    half = jnp.arange(n) < n // 2
    ckn = CT.knobs()._replace(bug_greedy_rebalance=half)
    fn = make_ctrler_sweep_fn(BASE, BASE.knobs(), ckn, CT, n, ticks)
    rep = ctrler_report(jax.block_until_ready(fn(jnp.asarray(11, jnp.uint32))))
    bugged = np.asarray(half)
    viol = rep.violations != 0
    assert viol[bugged].any(), "bugged half produced no balance violation"
    assert (rep.violations[bugged & viol] & VIOLATION_CTRL_BALANCE).all()
    assert not viol[~bugged].any()
