"""Coverage-guided schedule search (ROADMAP item 3): fingerprint determinism
across runs and devices, structural validity of reached codes against the
offline enumerator, seen-set saturation, first-generation bit-identity with
the coverage-off pool (the golden-guard property on the coverage path),
mutation-refill replay bit-exactness across refill generations, and the
guided-beats-random reached-state A/B on the ground-truth config."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from madraft_tpu.tpusim import coverage as cov
from madraft_tpu.tpusim.config import (
    CoverageConfig,
    coverage_ground_truth,
)
from madraft_tpu.tpusim.engine import (
    make_fuzz_fn,
    replay_cluster,
    run_pool,
)

GT_CFG, GT_CCFG, GT_HORIZON = coverage_ground_truth()

_CACHE = {}


def _pooled(key, **kw):
    """One pool run per distinct argument set (results are pure functions of
    the arguments — determinism is itself pinned by the replay test)."""
    if key not in _CACHE:
        rows = []
        summary = run_pool(on_retired=rows.append, **kw)
        _CACHE[key] = (rows, summary)
    return _CACHE[key]


def _guided(budget_mult=8, seed=7, ccfg=GT_CCFG):
    return _pooled(
        ("guided", seed, budget_mult, ccfg), cfg=GT_CFG, seed=seed,
        n_clusters=16, horizon=GT_HORIZON,
        budget_ticks=GT_HORIZON * budget_mult, coverage=ccfg,
    )


def test_fingerprint_deterministic_across_runs_and_devices():
    # the fingerprint is a pure function of the state: two jit invocations
    # and both virtual devices must produce identical codes
    fn = make_fuzz_fn(GT_CFG, 8, 48)
    final = jax.block_until_ready(fn(3))
    code_fn = jax.jit(jax.vmap(lambda s: cov.abstract_code(GT_CCFG, s)))
    a = np.asarray(code_fn(final))
    b = np.asarray(code_fn(jax.tree.map(jnp.asarray, final)))
    np.testing.assert_array_equal(a, b)
    devs = jax.devices()
    if len(devs) >= 2:
        on_dev1 = jax.device_put(final, devs[1])
        c = np.asarray(code_fn(on_dev1))
        np.testing.assert_array_equal(a, c)


def test_reached_codes_are_enumerated_and_identity_mapped():
    # every code a real run produces must be a member of the enumerated
    # structural state space (the enumerator is a sound superset), and in
    # identity mode the bitmap index IS the code
    assert cov.identity_mapped(GT_CFG.n_nodes, GT_CCFG)
    enumerated = set(
        cov.enumerate_abstract_codes(GT_CFG.n_nodes, GT_CCFG).tolist()
    )
    fn = make_fuzz_fn(GT_CFG, 8, 48)
    final = jax.block_until_ready(fn(3))
    codes = np.asarray(
        jax.vmap(lambda s: cov.abstract_code(GT_CCFG, s))(final)
    )
    assert set(codes.tolist()) <= enumerated
    idx = np.asarray(cov.bitmap_index(GT_CCFG, GT_CFG.n_nodes,
                                      jnp.asarray(codes)))
    np.testing.assert_array_equal(idx, codes.astype(np.int32))
    # the hashed (non-identity) path stays inside the bitmap
    small = GT_CCFG.replace(bitmap_bits=64)
    hidx = np.asarray(cov.bitmap_index(small, GT_CFG.n_nodes,
                                       jnp.asarray(codes)))
    assert ((hidx >= 0) & (hidx < 64)).all()


def test_seen_set_saturation():
    # a deliberately tiny bitmap must saturate: the popcount never exceeds
    # the bitmap, per-generation discoveries account for it exactly, and
    # once every bit is set later generations discover nothing
    ccfg = GT_CCFG.replace(bitmap_bits=64)
    _, summary = _guided(budget_mult=10, ccfg=ccfg)
    c = summary["coverage"]
    assert not c["identity"]
    assert 0 < c["seen_fingerprints"] <= 64
    gens = c["new_fp_per_gen"]
    assert sum(gens) == c["seen_fingerprints"]
    running = np.cumsum(gens)
    after_full = np.asarray(gens)[1:][running[:-1] >= 64]
    assert (after_full == 0).all(), (
        f"seen-set kept 'discovering' after saturation: {gens}"
    )
    assert running[-1] >= 56, f"64-bit map should nearly fill, got {gens}"


def test_first_generation_bit_identical_to_coverage_off():
    # horizon == chunk == budget: one generation, no refill ever applied —
    # the coverage pool's retired-cluster reports must match the plain
    # pool's bit-identically (the per-cluster knob layout changes the HLO,
    # not the numbers), and every gen-1 lane runs the base knob row
    rows_off = []
    run_pool(GT_CFG, 5, 16, GT_HORIZON, chunk_ticks=GT_HORIZON,
             budget_ticks=GT_HORIZON, on_retired=rows_off.append)
    rows_cov = []
    run_pool(GT_CFG, 5, 16, GT_HORIZON, chunk_ticks=GT_HORIZON,
             budget_ticks=GT_HORIZON, coverage=GT_CCFG,
             on_retired=rows_cov.append)
    assert len(rows_off) == len(rows_cov) == 16
    skip = {"wall_s", "violations_per_s"}
    base_kn = GT_CFG.knobs()
    for off, con in zip(rows_off, rows_cov):
        for k, want in off.items():
            if k in skip:
                continue
            assert con[k] == want, f"coverage drift in gen-1 field {k!r}"
        assert con["refill"] == "seed"
        assert con["new_fingerprints"] > 0
        for name, v in con["knobs"].items():
            assert v == float(np.asarray(getattr(base_kn, name)))


def test_mutation_refill_replay_bit_exact_across_generations():
    # the replay contract for mutated lanes: every retired cluster —
    # including knob-mutated and fresh-drawn descendants, >= 2 refill
    # generations deep — reproduces bit-exactly through
    # replay_cluster(seed, global_id, knobs=row["knobs"])
    rows, summary = _guided()
    gens = {r["cluster_id"] // 16 for r in rows if r["refill"] != "seed"}
    assert len(gens) >= 2, f"need >= 2 refill generations, got {gens}"
    kinds = {r["refill"] for r in rows}
    assert "mutate" in kinds and "fresh" in kinds, kinds
    assert summary["coverage"]["refills_mutated"] > 0
    assert summary["coverage"]["refills_fresh"] > 0
    picked = [r for r in rows if r["refill"] == "mutate"][:4]
    picked += [r for r in rows if r["refill"] == "fresh"][:2]
    picked += [r for r in rows if r["violations"]][:2]
    for r in picked:
        st = replay_cluster(GT_CFG, 7, r["cluster_id"], r["ticks_run"],
                            knobs=r["knobs"])
        assert int(st.violations) == r["violations"]
        assert int(st.first_violation_tick) == r["first_violation_tick"]
        assert int(st.shadow_len) == r["committed"]
        assert int(st.msg_count) == r["msg_count"]
    # the explain surface applies the same knob row: the traced replay of a
    # MUTATED lane must reproduce the untraced one bit-identically (base
    # knobs would decode a different execution)
    from madraft_tpu.tpusim.trace import replay_cluster_traced

    r = picked[0]
    final, _ = replay_cluster_traced(GT_CFG, 7, r["cluster_id"],
                                     r["ticks_run"], knobs=r["knobs"])
    assert int(final.violations) == r["violations"]
    assert int(final.msg_count) == r["msg_count"]
    assert int(final.shadow_len) == r["committed"]


def test_mutated_knob_rows_respect_the_prior():
    # mutation and fresh draws stay probabilities, and a knob the base
    # profile disabled is never turned on by the search
    rows, _ = _guided()
    base_kn = GT_CFG.knobs()
    for r in rows:
        for name, v in r["knobs"].items():
            assert 0.0 <= v <= 1.0, (name, v)
            if float(np.asarray(getattr(base_kn, name))) == 0.0:
                assert v == 0.0, f"{name} enabled by mutation"


def test_guided_reaches_more_states_than_random():
    # the ground-truth A/B (bench.py's exit criterion, pinned small): equal
    # lanes and tick budget, guided must reach strictly more enumerated
    # abstract states than the uniform-random baseline — and both must be
    # sane fractions of the enumerated space
    total = len(cov.enumerate_abstract_codes(GT_CFG.n_nodes, GT_CCFG))
    _, guided = _guided(budget_mult=20)
    _, random_ = _pooled(
        ("random", 7, 20), cfg=GT_CFG, seed=7, n_clusters=16,
        horizon=GT_HORIZON, budget_ticks=GT_HORIZON * 20,
        coverage=GT_CCFG.replace(guided=False),
    )
    gs = guided["coverage"]["seen_fingerprints"]
    rs = random_["coverage"]["seen_fingerprints"]
    assert 0 < rs < gs <= total, (gs, rs, total)
    assert random_["coverage"]["refills_mutated"] == 0
    assert random_["coverage"]["guided"] is False


def test_coverage_config_and_devices_validation():
    with pytest.raises(ValueError, match="power of two"):
        CoverageConfig(bitmap_bits=100)
    with pytest.raises(ValueError, match=">= 2"):
        CoverageConfig(term_rank_levels=1)
    with pytest.raises(ValueError, match="mut_span"):
        CoverageConfig(mut_span=1.0)
    with pytest.raises(ValueError, match="enumerate"):
        cov.enumerate_abstract_codes(5, CoverageConfig())
    # the coverage+mesh gate is LIFTED (ROADMAP 3a): coverage composes with
    # devices= (per-shard seen-set); only the usual devices validation holds
    with pytest.raises(ValueError, match="divide evenly"):
        run_pool(GT_CFG, 1, 15, GT_HORIZON, coverage=GT_CCFG, devices=2)
    with pytest.raises(ValueError, match="divide evenly"):
        cov.lane_shards(15, 2)
    with pytest.raises(ValueError, match="unknown knob"):
        replay_cluster(GT_CFG, 1, 0, 8, knobs={"not_a_knob": 1.0})
    with pytest.raises(ValueError, match="loss_prob"):
        # out-of-range overrides are rejected eagerly (_validate_knobs),
        # not silently run as a bogus "bit-exact" replay
        replay_cluster(GT_CFG, 1, 0, 8, knobs={"loss_prob": 1.5})


def test_coverage_sharded_union_count_and_mutated_replay():
    # the sharded coverage pool (ROADMAP 3a): each shard owns a seen-set
    # row updated locally every tick; the summary's seen_fingerprints is
    # the popcount of the OR over the rows (exact union in identity mode),
    # and the per-generation discovery curve accounts for it exactly. A
    # knob-MUTATED lane harvested on shard 1 must replay bit-exactly on a
    # single device from its recorded knob row — the replay contract is
    # device-count- and shard-blind.
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    from madraft_tpu.tpusim.config import pool_shard

    rows = []
    summary = run_pool(GT_CFG, 7, 16, GT_HORIZON,
                       budget_ticks=GT_HORIZON * 8, coverage=GT_CCFG,
                       devices=2, on_retired=rows.append)
    c = summary["coverage"]
    total = len(cov.enumerate_abstract_codes(GT_CFG.n_nodes, GT_CCFG))
    assert c["shards"] == 2 and c["guided"]
    assert 0 < c["seen_fingerprints"] <= total
    assert sum(c["new_fp_per_gen"]) == c["seen_fingerprints"]
    assert summary["id_scheme"] == "lane" and summary["devices"] == 2
    assert c["refills_mutated"] > 0
    mut = [r for r in rows if r["refill"] == "mutate"
           and pool_shard(r["cluster_id"], 16, 2) == 1]
    assert mut, "need a mutated lane harvested on shard 1"
    for r in mut[:3]:
        st = replay_cluster(GT_CFG, 7, r["cluster_id"], r["ticks_run"],
                            knobs=r["knobs"])
        assert int(st.violations) == r["violations"]
        assert int(st.first_violation_tick) == r["first_violation_tick"]
        assert int(st.shadow_len) == r["committed"]
        assert int(st.msg_count) == r["msg_count"]
