"""TPU <-> simcore differential bridge: a TPU-found violation must replay on
the C++ backend and trip the same violation class there.

This is SURVEY.md §7 architecture item 4 ("determinism across backends") and
the reference's seed-replay contract (/root/reference/README.md:42-55),
expressed across backends: the interchange is the FAULT SCHEDULE (alive/adj
timelines), not PRNG streams, because the two backends draw from different
generators. Equivalence is therefore class-level, and validated with a
deliberately broken quorum (majority_override=2) that both backends support.
"""

import pathlib
import shutil
import subprocess

import numpy as np
import pytest

from madraft_tpu import bridge
from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import fuzz

ROOT = pathlib.Path(__file__).resolve().parent.parent
BUILD = ROOT / "build"


def _ensure_binary(target: str) -> pathlib.Path:
    binary = BUILD / target
    srcs = list((ROOT / "cpp").rglob("*.cpp")) + list((ROOT / "cpp").rglob("*.h"))
    newest = max(p.stat().st_mtime for p in srcs)
    if not binary.exists() or binary.stat().st_mtime < newest:
        # Missing TOOLCHAIN -> clean skip (the test_cpp_suite.py treatment:
        # cmake-less containers run the rest of the suite green instead of
        # carrying 9 documented failures). A toolchain that is present but
        # FAILS still fails loudly below — skipping would silently green a
        # broken C++ change. The in-process simcore bridge tests are
        # unaffected either way (they use bridge.py's direct-g++ fallback).
        missing = [t for t in ("cmake", "ninja") if shutil.which(t) is None]
        if missing:
            pytest.skip(
                f"cmake-built C++ replay binaries need cmake+ninja; "
                f"missing: {', '.join(missing)}"
            )
        for cmd in (
            ["cmake", "-S", str(ROOT / "cpp"), "-B", str(BUILD), "-G", "Ninja"],
            ["ninja", "-C", str(BUILD), target],
        ):
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:  # surface the compiler diagnostics
                pytest.fail(
                    f"{' '.join(cmd)} failed:\n{proc.stdout[-2000:]}\n"
                    f"{proc.stderr[-4000:]}"
                )
    return binary


def _ensure_replay_binary() -> pathlib.Path:
    return _ensure_binary("madtpu_replay")


BUGGY = SimConfig(
    n_nodes=5,
    majority_override=2,     # deliberate bug: quorum of 2 on 5 nodes
    loss_prob=0.1,
    p_repartition=0.05,
    p_heal=0.05,
    p_client_cmd=0.3,
)


def test_bridge_replays_violation_class():
    """Find a violating cluster on the batched backend, export its fault
    schedule, replay on simcore, and require the same violation class."""
    binary = _ensure_replay_binary()
    n_ticks = 384
    rep = fuzz(BUGGY, seed=7, n_clusters=64, n_ticks=n_ticks)
    bad = rep.violating_clusters()
    assert bad.size > 0, "quorum=2 must produce violations on the TPU backend"

    # Pick the violating cluster whose classes include a commit/log class if
    # one exists (richest cross-backend signal); else the first.
    viol = rep.violations[bad]
    prefer = bad[(viol & 6) != 0]  # LOG_MATCHING | COMMIT_SHADOW
    cluster = int(prefer[0] if prefer.size else bad[0])

    sched = bridge.extract_schedule(BUGGY, seed=7, cluster_id=cluster,
                                    n_ticks=n_ticks)
    assert sched.violations == rep.violations[cluster], (
        "single-cluster replay must reproduce the batched run exactly "
        "(same PRNG stream)"
    )
    cpp = bridge.replay_on_simcore(sched, binary=binary)
    assert bridge.classes_match(sched.violations, cpp), (
        f"C++ replay saw no matching violation class: tpu={sched.violations:#x} "
        f"cpp={cpp}"
    )


def test_kv_stale_read_cross_validated_by_wing_gong():
    """VERDICT item: a stale read caught by the on-device interval oracle
    must also fail the C++ Wing-Gong checker when its history is exported,
    and a clean history must pass. The committed order is streamed from the
    per-tick shadow trace, so the clean leg runs a LONG compacting history —
    many times the shadow window — and still exports the full order (the
    round-2 export was limited to one window). (The interval oracle is
    slightly stricter — it counts committed-but-unacked appends — so the bug
    run is asserted over several clusters.)"""
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz

    _ensure_lincheck_binary()
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
        max_dead=2,
    )
    kcfg = KvConfig(p_get=0.5, p_retry=0.6)

    # clean: a 2000-tick compacting run exports far more committed appends
    # than one shadow window holds, and the history is linearizable
    long_ticks = 2000
    rep = kv_fuzz(cfg, kcfg, seed=17, n_clusters=8, n_ticks=long_ticks)
    assert rep.n_violating == 0
    assert (rep.committed > 2 * cfg.log_cap).any(), (
        "the long run must outgrow the shadow window for this test to bite"
    )
    for cid in (0, 3):
        lines, viol = bridge.extract_kv_history(cfg, kcfg, 17, cid, long_ticks)
        assert viol == 0
        assert len(lines) > 10
        assert bridge.check_history_on_simcore(lines)
    n_ticks = 200

    # bug: stale reads flagged on device must fail the Wing-Gong check too
    bcfg = kcfg.replace(bug_stale_read=True)
    rep = kv_fuzz(cfg, bcfg, seed=17, n_clusters=16, n_ticks=n_ticks)
    bad = rep.violating_clusters()
    assert bad.size > 0
    flagged = 0
    for cid in bad[:4]:
        lines, viol = bridge.extract_kv_history(cfg, bcfg, 17, int(cid), n_ticks)
        assert viol != 0
        if not bridge.check_history_on_simcore(lines):
            flagged += 1
    assert flagged > 0, "no exported bug history failed the C++ checker"


def _ensure_lincheck_binary() -> pathlib.Path:
    return _ensure_binary("madtpu_lincheck")


def test_shardkv_bridge_replays_violation_class():
    """VERDICT item: a TPU-found SHARDKV violation must replay on the full
    C++ shardkv stack (ctrler + groups + migration/GC) and trip the same
    violation class there. Validated with bug_drop_dup_table: the TPU
    walker-divergence oracle fires on device; the C++ replay (same protocol
    bug via shardkv.h bug_mode()) must observe a client-side duplicate apply.
    The same schedule replayed WITHOUT the bug stays clean."""
    from madraft_tpu.tpusim.shardkv import ShardKvConfig, shardkv_fuzz

    binary = _ensure_binary("madtpu_shardkv_replay")
    raft = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05,
    )
    # long enough that the C++ replay sees many migrations racing many
    # client retries — reproduction is distributional, and short schedules
    # reproduce too rarely
    kcfg = ShardKvConfig(bug_drop_dup_table=True, p_retry=0.8,
                         n_configs=12, cfg_interval=70)
    n_ticks = 1200
    rep = shardkv_fuzz(raft, kcfg, seed=5, n_clusters=8, n_ticks=n_ticks)
    bad = rep.violating_clusters()
    assert bad.size > 0, "bug_drop_dup_table must fire on the TPU backend"

    matched = False
    for cid in bad[:3]:
        sched = bridge.extract_shardkv_schedule(raft, kcfg, 5, int(cid), n_ticks)
        assert sched.violations == (
            rep.violations[cid] | rep.raft_violations[cid]
        ), "single-deployment replay must reproduce the batched run exactly"
        assert sched.bug == "drop_dup_table"
        assert len(sched.cfg_events) >= 8, "config churn must be exported"
        # cross-backend equivalence is class-level and distributional
        # (different PRNG streams — SURVEY.md §7), so each schedule may be
        # replayed under a few simcore seeds
        for seed_bump in (0, 1000, 2000):
            trial = bridge.ShardKvSchedule(**{
                **sched.__dict__, "seed": sched.seed + seed_bump,
            })
            cpp = bridge.replay_shardkv_on_simcore(trial, binary=binary)
            if bridge.shardkv_classes_match(sched.violations, cpp):
                matched = True
                # control: the same schedule without the bug stays clean
                clean = bridge.ShardKvSchedule(**{
                    **trial.__dict__, "bug": "none",
                })
                cpp_clean = bridge.replay_shardkv_on_simcore(
                    clean, binary=binary
                )
                assert (
                    not cpp_clean["dup_apply"] and not cpp_clean["stale_read"]
                ), f"clean replay flagged: {cpp_clean}"
                break
        if matched:
            break
    assert matched, "no C++ shardkv replay reproduced the violation class"


def test_bridge_clean_on_correct_quorum():
    """Sanity: with the correct quorum the same schedule shape yields zero
    violations on both backends."""
    binary = _ensure_replay_binary()
    cfg = BUGGY.replace(majority_override=None)
    n_ticks = 256
    rep = fuzz(cfg, seed=11, n_clusters=32, n_ticks=n_ticks)
    assert rep.n_violating == 0
    sched = bridge.extract_schedule(cfg, seed=11, cluster_id=3, n_ticks=n_ticks)
    cpp = bridge.replay_on_simcore(sched, binary=binary)
    assert not cpp["dual_leader"] and not cpp["commit_mismatch"], cpp
    assert cpp["max_applied"] > 0, "replay must make progress"


def test_bridge_replays_planted_bug_classes():
    """The planted-bug library crosses the bridge: a violation the batched
    fuzzer finds under SimConfig.bug replays on the C++ backend with the
    SAME bug injected (the schedule carries a `bug` line -> MADTPU_BUG,
    cpp/raftcore/raft.cpp) and must reproduce the violation class; the same
    schedule with the bug stripped must replay clean — the bug, not the
    fault schedule, is what breaks safety. (Measured odds: ~16/16 schedules
    class-match for these two bugs; commit_any_term / forget_voted_for have
    much thinner per-schedule odds on the C++ side's independent election
    timing — as does ack_before_fsync, whose C++ manifestation additionally
    needs a kill to land between a handler reply and the next unrelated
    persist() — so the cross-backend leg pins the two robust ones and
    tests/test_tpusim_bugs.py covers the full library on the batched
    side.)"""
    import dataclasses

    from tests.test_tpusim_bugs import STORM as storm  # single tuned profile

    binary = _ensure_replay_binary()
    n_ticks = 600
    for bug, seed in (("grant_any_vote", 9), ("no_truncate", 11)):
        cfg = storm.replace(bug=bug)
        rep = fuzz(cfg, seed=seed, n_clusters=64, n_ticks=n_ticks)
        bad = rep.violating_clusters()
        assert bad.size > 0, f"{bug}: no TPU violations to bridge"
        matched = 0
        for cid in bad[:3]:
            sched = bridge.extract_schedule(cfg, seed=seed, cluster_id=int(cid),
                                            n_ticks=n_ticks)
            assert sched.bug == bug  # rides the schedule into MADTPU_BUG
            cpp = bridge.replay_on_simcore(sched, binary=binary)
            if bridge.classes_match(sched.violations, cpp):
                matched += 1
            clean = bridge.replay_on_simcore(
                dataclasses.replace(sched, bug=""), binary=binary
            )
            assert not (clean["dual_leader"] or clean["commit_mismatch"]
                        or clean["apply_disorder"]), (
                f"{bug}: clean replay of the same schedule violated: {clean}"
            )
        assert matched > 0, f"{bug}: no C++ replay reproduced the class"


def test_kv_put_histories_cross_validated_by_wing_gong():
    """Put joins the exported op set: a clean Get/Put/Append history must
    pass the C++ Wing-Gong checker with values translated through the
    mutation-version model (a version maps to last-Put-token + Appends
    after it — cpp/kvraft/kv.h apply semantics)."""
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz

    _ensure_lincheck_binary()
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
        max_dead=2,
    )
    kcfg = KvConfig(p_get=0.35, p_put=0.3, p_retry=0.6)
    n_ticks = 600
    rep = kv_fuzz(cfg, kcfg, seed=23, n_clusters=8, n_ticks=n_ticks)
    assert rep.n_violating == 0
    checked = puts = 0
    for cid in (0, 5):
        lines, viol = bridge.extract_kv_history(cfg, kcfg, 23, cid, n_ticks)
        assert viol == 0
        puts += sum(" put " in ln for ln in lines)
        assert bridge.check_history_on_simcore(lines)
        checked += 1
    assert checked == 2 and puts > 0, "put ops must appear in the export"


# ------------------------------------------------------------- 4A ctrler leg
CTRL_SIM = SimConfig(
    n_nodes=5, p_client_cmd=0.0, compact_at_commit=False, loss_prob=0.1,
    p_crash=0.01, p_restart=0.2, max_dead=2, p_repartition=0.02, p_heal=0.05,
    log_cap=32, compact_every=8,
)


def test_ctrler_bridge_exact_map_on_clean_run():
    """The 4A leg's strongest form: the config service is a deterministic
    state machine, so a bug-free committed-op stream must reproduce the TPU
    walker's EXACT config history on the C++ ShardInfo — same final owner
    map (gid g <-> Gid g+1), same config count. This proves both backends
    implement the same canonical rebalance spec, not merely the same
    balance/minimality properties."""
    from madraft_tpu.tpusim.ctrler import CtrlerConfig, ctrler_fuzz

    binary = _ensure_binary("madtpu_ctrler_replay")
    kcfg = CtrlerConfig()
    n_ticks = 320
    rep = ctrler_fuzz(CTRL_SIM, kcfg, seed=11, n_clusters=8, n_ticks=n_ticks)
    assert rep.n_violating == 0
    checked = 0
    multi_gid_ops = 0
    for cid in range(8):
        if rep.configs_created[cid] < 5:
            continue
        sched = bridge.extract_ctrler_schedule(
            CTRL_SIM, kcfg, 11, cid, n_ticks
        )
        assert sched.bug == "none" and sched.expect_cfgs >= 5
        multi_gid_ops += sum(
            1 for op in sched.ops
            if op[0] in ("join", "leave") and len(op) > 2
        )
        cpp = bridge.replay_ctrler_on_simcore(sched, binary=binary)
        assert cpp["map_match"] == 1, (sched.dumps(), cpp)
        assert cpp["balance_bad"] == 0 and cpp["minimal_bad"] == 0, cpp
        assert cpp["configs"] == sched.expect_cfgs
        checked += 1
        if checked >= 3:
            break
    assert checked >= 2, "not enough config churn exported to prove parity"
    assert multi_gid_ops > 0, (
        "no multi-gid Join/Leave crossed the bridge — the C++ ShardInfo "
        "never saw the map-of-groups op shape (msg.rs:20-37)"
    )


def test_ctrler_bridge_replays_bug_classes():
    """Each planted 4A rebalance bug found by the TPU oracles must reproduce
    its violation class on the C++ side with the SAME bug enabled
    (ctrler.h ctrl_bug_mode), and the bug-stripped replay must be clean —
    the same contract as the raft and shardkv legs."""
    from madraft_tpu.tpusim.ctrler import CtrlerConfig, ctrler_fuzz

    binary = _ensure_binary("madtpu_ctrler_replay")
    n_ticks = 320
    for bug_kw in ("bug_rotate_tiebreak", "bug_greedy_rebalance",
                   "bug_full_reshuffle"):
        kcfg = CtrlerConfig(**{bug_kw: True})
        rep = ctrler_fuzz(CTRL_SIM, kcfg, seed=11, n_clusters=32,
                          n_ticks=n_ticks)
        bad = rep.violating_clusters()
        assert bad.size > 0, f"{bug_kw} must fire on the TPU backend"
        matched = False
        for cid in bad[:6]:
            sched = bridge.extract_ctrler_schedule(
                CTRL_SIM, kcfg, 11, int(cid), n_ticks
            )
            assert sched.violations == rep.violations[cid]
            cpp = bridge.replay_ctrler_on_simcore(sched, binary=binary)
            if bridge.ctrler_classes_match(sched.violations, cpp):
                matched = True
                clean = bridge.CtrlerSchedule(**{
                    **sched.__dict__, "bug": "none",
                })
                cpp_clean = bridge.replay_ctrler_on_simcore(
                    clean, binary=binary
                )
                assert (
                    cpp_clean["balance_bad"] == 0
                    and cpp_clean["minimal_bad"] == 0
                    and cpp_clean["diverged"] == 0
                ), f"bug-stripped replay flagged: {cpp_clean}"
                break
        assert matched, f"no C++ replay reproduced {bug_kw}'s class"


def test_shardkv_bridge_replays_composite_computed_schedule():
    """VERDICT round-5 item: the composite 4A∘4B schedule replays on C++.
    A TPU run with the COMPUTED controller and the planted rotate-tiebreak
    bug finds groups adopting rotated replica maps (VIOLATION_SHARD_CTRL_
    STALE). The exported schedule carries the committed membership-flip
    stream; the C++ side (a) drives REAL Join/Leave through its 4A service
    so the ctrler computes every config via its own rebalance, and (b)
    replays the same op stream into two rotated ShardInfo replicas — whose
    config histories must diverge (the same class the TPU oracle flagged).
    The same schedule with ctrl_bug=none must not diverge."""
    from madraft_tpu.tpusim.shardkv import (
        ShardKvConfig,
        VIOLATION_SHARD_CTRL_STALE,
        shardkv_fuzz,
    )

    binary = _ensure_binary("madtpu_shardkv_replay")
    raft = SimConfig(
        n_nodes=3, p_client_cmd=0.0, compact_at_commit=False, log_cap=64,
        compact_every=16, loss_prob=0.05,
    )
    kcfg = ShardKvConfig(computed_ctrler=True, bug_rotate_tiebreak=True,
                         cfg_interval=40)
    n_ticks = 512
    rep = shardkv_fuzz(raft, kcfg, seed=7, n_clusters=8, n_ticks=n_ticks)
    bad = rep.violating_clusters()
    bad = bad[(rep.violations[bad] & VIOLATION_SHARD_CTRL_STALE) != 0]
    assert bad.size > 0, "the composite rotate bug must fire on the TPU"

    matched = False
    for cid in bad[:3]:
        sched = bridge.extract_shardkv_schedule(raft, kcfg, 7, int(cid),
                                                n_ticks)
        assert sched.violations == (
            rep.violations[cid] | rep.raft_violations[cid]
        ), "single-deployment replay must reproduce the batched run exactly"
        assert sched.mode == "computed"
        assert sched.ctrl_bug == "rotate_tiebreak"
        assert len(sched.flip_events) >= 2, "committed flips must be exported"
        cpp = bridge.replay_shardkv_on_simcore(sched, binary=binary)
        if cpp["diverged"] and bridge.shardkv_classes_match(
            sched.violations, cpp
        ):
            assert cpp["ops"] > 0, (
                "the computed-config C++ service must still serve ops"
            )
            # control: same flip stream, no 4A bug -> no divergence
            clean = bridge.ShardKvSchedule(**{
                **sched.__dict__, "ctrl_bug": "none",
            })
            cpp_clean = bridge.replay_shardkv_on_simcore(clean, binary=binary)
            assert not cpp_clean["diverged"], f"clean replay diverged: {cpp_clean}"
            matched = True
            break
    assert matched, "no C++ composite replay reproduced the divergence class"
