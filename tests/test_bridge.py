"""TPU <-> simcore differential bridge: a TPU-found violation must replay on
the C++ backend and trip the same violation class there.

This is SURVEY.md §7 architecture item 4 ("determinism across backends") and
the reference's seed-replay contract (/root/reference/README.md:42-55),
expressed across backends: the interchange is the FAULT SCHEDULE (alive/adj
timelines), not PRNG streams, because the two backends draw from different
generators. Equivalence is therefore class-level, and validated with a
deliberately broken quorum (majority_override=2) that both backends support.
"""

import pathlib
import subprocess

import numpy as np
import pytest

from madraft_tpu import bridge
from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import fuzz

ROOT = pathlib.Path(__file__).resolve().parent.parent
BUILD = ROOT / "build"


def _ensure_replay_binary() -> pathlib.Path:
    binary = BUILD / "madtpu_replay"
    srcs = list((ROOT / "cpp").rglob("*.cpp")) + list((ROOT / "cpp").rglob("*.h"))
    newest = max(p.stat().st_mtime for p in srcs)
    if not binary.exists() or binary.stat().st_mtime < newest:
        for cmd in (
            ["cmake", "-S", str(ROOT / "cpp"), "-B", str(BUILD), "-G", "Ninja"],
            ["ninja", "-C", str(BUILD), "madtpu_replay"],
        ):
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:  # surface the compiler diagnostics
                pytest.fail(
                    f"{' '.join(cmd)} failed:\n{proc.stdout[-2000:]}\n"
                    f"{proc.stderr[-4000:]}"
                )
    return binary


BUGGY = SimConfig(
    n_nodes=5,
    majority_override=2,     # deliberate bug: quorum of 2 on 5 nodes
    loss_prob=0.1,
    p_repartition=0.05,
    p_heal=0.05,
    p_client_cmd=0.3,
)


def test_bridge_replays_violation_class():
    """Find a violating cluster on the batched backend, export its fault
    schedule, replay on simcore, and require the same violation class."""
    binary = _ensure_replay_binary()
    n_ticks = 384
    rep = fuzz(BUGGY, seed=7, n_clusters=64, n_ticks=n_ticks)
    bad = rep.violating_clusters()
    assert bad.size > 0, "quorum=2 must produce violations on the TPU backend"

    # Pick the violating cluster whose classes include a commit/log class if
    # one exists (richest cross-backend signal); else the first.
    viol = rep.violations[bad]
    prefer = bad[(viol & 6) != 0]  # LOG_MATCHING | COMMIT_SHADOW
    cluster = int(prefer[0] if prefer.size else bad[0])

    sched = bridge.extract_schedule(BUGGY, seed=7, cluster_id=cluster,
                                    n_ticks=n_ticks)
    assert sched.violations == rep.violations[cluster], (
        "single-cluster replay must reproduce the batched run exactly "
        "(same PRNG stream)"
    )
    cpp = bridge.replay_on_simcore(sched, binary=binary)
    assert bridge.classes_match(sched.violations, cpp), (
        f"C++ replay saw no matching violation class: tpu={sched.violations:#x} "
        f"cpp={cpp}"
    )


def test_bridge_clean_on_correct_quorum():
    """Sanity: with the correct quorum the same schedule shape yields zero
    violations on both backends."""
    binary = _ensure_replay_binary()
    cfg = BUGGY.replace(majority_override=None)
    n_ticks = 256
    rep = fuzz(cfg, seed=11, n_clusters=32, n_ticks=n_ticks)
    assert rep.n_violating == 0
    sched = bridge.extract_schedule(cfg, seed=11, cluster_id=3, n_ticks=n_ticks)
    cpp = bridge.replay_on_simcore(sched, binary=binary)
    assert not cpp["dual_leader"] and not cpp["commit_mismatch"], cpp
    assert cpp["max_applied"] > 0, "replay must make progress"
