"""TPU <-> simcore differential bridge: a TPU-found violation must replay on
the C++ backend and trip the same violation class there.

This is SURVEY.md §7 architecture item 4 ("determinism across backends") and
the reference's seed-replay contract (/root/reference/README.md:42-55),
expressed across backends: the interchange is the FAULT SCHEDULE (alive/adj
timelines), not PRNG streams, because the two backends draw from different
generators. Equivalence is therefore class-level, and validated with a
deliberately broken quorum (majority_override=2) that both backends support.
"""

import pathlib
import subprocess

import numpy as np
import pytest

from madraft_tpu import bridge
from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import fuzz

ROOT = pathlib.Path(__file__).resolve().parent.parent
BUILD = ROOT / "build"


def _ensure_binary(target: str) -> pathlib.Path:
    binary = BUILD / target
    srcs = list((ROOT / "cpp").rglob("*.cpp")) + list((ROOT / "cpp").rglob("*.h"))
    newest = max(p.stat().st_mtime for p in srcs)
    if not binary.exists() or binary.stat().st_mtime < newest:
        for cmd in (
            ["cmake", "-S", str(ROOT / "cpp"), "-B", str(BUILD), "-G", "Ninja"],
            ["ninja", "-C", str(BUILD), target],
        ):
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:  # surface the compiler diagnostics
                pytest.fail(
                    f"{' '.join(cmd)} failed:\n{proc.stdout[-2000:]}\n"
                    f"{proc.stderr[-4000:]}"
                )
    return binary


def _ensure_replay_binary() -> pathlib.Path:
    return _ensure_binary("madtpu_replay")


BUGGY = SimConfig(
    n_nodes=5,
    majority_override=2,     # deliberate bug: quorum of 2 on 5 nodes
    loss_prob=0.1,
    p_repartition=0.05,
    p_heal=0.05,
    p_client_cmd=0.3,
)


def test_bridge_replays_violation_class():
    """Find a violating cluster on the batched backend, export its fault
    schedule, replay on simcore, and require the same violation class."""
    binary = _ensure_replay_binary()
    n_ticks = 384
    rep = fuzz(BUGGY, seed=7, n_clusters=64, n_ticks=n_ticks)
    bad = rep.violating_clusters()
    assert bad.size > 0, "quorum=2 must produce violations on the TPU backend"

    # Pick the violating cluster whose classes include a commit/log class if
    # one exists (richest cross-backend signal); else the first.
    viol = rep.violations[bad]
    prefer = bad[(viol & 6) != 0]  # LOG_MATCHING | COMMIT_SHADOW
    cluster = int(prefer[0] if prefer.size else bad[0])

    sched = bridge.extract_schedule(BUGGY, seed=7, cluster_id=cluster,
                                    n_ticks=n_ticks)
    assert sched.violations == rep.violations[cluster], (
        "single-cluster replay must reproduce the batched run exactly "
        "(same PRNG stream)"
    )
    cpp = bridge.replay_on_simcore(sched, binary=binary)
    assert bridge.classes_match(sched.violations, cpp), (
        f"C++ replay saw no matching violation class: tpu={sched.violations:#x} "
        f"cpp={cpp}"
    )


def test_kv_stale_read_cross_validated_by_wing_gong():
    """VERDICT item: a stale read caught by the on-device interval oracle
    must also fail the C++ Wing-Gong checker when its history is exported,
    and a clean history must pass. (The interval oracle is slightly stricter
    — it counts committed-but-unacked appends — so the bug run is asserted
    over several clusters.)"""
    from madraft_tpu.tpusim.kv import KvConfig, kv_fuzz

    _ensure_lincheck_binary()
    cfg = SimConfig(
        n_nodes=5, p_client_cmd=0.0, compact_at_commit=False, log_cap=128,
        compact_every=1 << 20,  # single shadow window for full-order export
        loss_prob=0.1, p_crash=0.01, p_restart=0.2, max_dead=2,
    )
    kcfg = KvConfig(p_get=0.5, p_retry=0.6)
    n_ticks = 200

    # clean: every exported history is linearizable
    rep = kv_fuzz(cfg, kcfg, seed=17, n_clusters=16, n_ticks=n_ticks)
    assert rep.n_violating == 0
    for cid in (0, 3):
        lines, viol = bridge.extract_kv_history(cfg, kcfg, 17, cid, n_ticks)
        assert viol == 0
        assert len(lines) > 10
        assert bridge.check_history_on_simcore(lines)

    # bug: stale reads flagged on device must fail the Wing-Gong check too
    bcfg = kcfg.replace(bug_stale_read=True)
    rep = kv_fuzz(cfg, bcfg, seed=17, n_clusters=16, n_ticks=n_ticks)
    bad = rep.violating_clusters()
    assert bad.size > 0
    flagged = 0
    for cid in bad[:4]:
        lines, viol = bridge.extract_kv_history(cfg, bcfg, 17, int(cid), n_ticks)
        assert viol != 0
        if not bridge.check_history_on_simcore(lines):
            flagged += 1
    assert flagged > 0, "no exported bug history failed the C++ checker"


def _ensure_lincheck_binary() -> pathlib.Path:
    return _ensure_binary("madtpu_lincheck")


def test_bridge_clean_on_correct_quorum():
    """Sanity: with the correct quorum the same schedule shape yields zero
    violations on both backends."""
    binary = _ensure_replay_binary()
    cfg = BUGGY.replace(majority_override=None)
    n_ticks = 256
    rep = fuzz(cfg, seed=11, n_clusters=32, n_ticks=n_ticks)
    assert rep.n_violating == 0
    sched = bridge.extract_schedule(cfg, seed=11, cluster_id=3, n_ticks=n_ticks)
    cpp = bridge.replay_on_simcore(sched, binary=binary)
    assert not cpp["dual_leader"] and not cpp["commit_mismatch"], cpp
    assert cpp["max_applied"] > 0, "replay must make progress"
