"""KV-service fuzzing layer tests (Lab 3 on TPU): exactly-once, agreement,
oracle validation via bug injection, determinism, and sharded execution.

Runs on the virtual CPU device mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.kv import (
    KvConfig,
    VIOLATION_EXACTLY_ONCE,
    VIOLATION_STALE_READ,
    kv_fuzz,
    kv_replay_cluster,
    make_kv_fuzz_fn,
    kv_report,
)

BASE = SimConfig(
    n_nodes=5,
    p_client_cmd=0.0,  # the KV layer owns injection
    compact_at_commit=False,  # the KV layer drives the compaction boundary
    loss_prob=0.1,
    p_crash=0.01,
    p_restart=0.2,
    max_dead=2,
    p_repartition=0.02,
    p_heal=0.05,
    log_cap=32,
    compact_every=8,  # flow_cap (16) + compact_every must stay below log_cap
)
KV = KvConfig()


def test_kv_fuzz_clean():
    """Fault storm over many clusters: no violations, real client progress."""
    rep = kv_fuzz(BASE, KV, seed=7, n_clusters=96, n_ticks=320)
    assert rep.n_violating == 0, (
        f"violations in clusters {rep.violating_clusters()[:8]}: "
        f"{rep.violations[rep.violating_clusters()[:8]]}"
    )
    # the workload must actually exercise the service — including reads
    assert (rep.acked_ops > 0).mean() > 0.9
    assert rep.acked_ops.sum() > 96 * 5
    assert rep.acked_gets.sum() > 96, "Get ops must flow and complete"


def test_kv_leader_targeted_cuts():
    """The service stack under LEADER-TARGETED minority partitions and
    asymmetric one-sided link cuts (the kvraft tester's leader-in-minority
    scenario, tester.rs:184-191): a deposed-but-unaware leader keeps
    accepting clerk ops that must be superseded without breaking
    exactly-once or reads linearizability."""
    cfg = BASE.replace(
        p_repartition=0.0, p_leader_part=0.03, p_asym_cut=0.05, p_heal=0.06,
    )
    rep = kv_fuzz(cfg, KV.replace(p_get=0.4), seed=13, n_clusters=96,
                  n_ticks=384)
    assert rep.n_violating == 0, (
        f"violations {rep.violations[rep.violating_clusters()[:8]]}"
    )
    assert (rep.acked_ops > 0).mean() > 0.9
    assert rep.acked_gets.sum() > 96


def test_kv_dedup_oracle_fires():
    """Applying duplicates blindly must trip the exactly-once oracle: clerk
    retries create duplicate log entries, and the dup table is the only thing
    standing between them and a double Append."""
    rep = kv_fuzz(BASE, KV.replace(bug_skip_dedup=True), seed=7,
                  n_clusters=96, n_ticks=320)
    assert rep.n_violating > 0
    assert np.all(
        (rep.violations[rep.violating_clusters()] & VIOLATION_EXACTLY_ONCE) != 0
    )


def test_kv_uncommitted_apply_oracle_fires():
    """Applying past the commit index must trip an oracle (divergence between
    apply machines, or commit-shadow once overwritten entries commit)."""
    rep = kv_fuzz(BASE, KV.replace(bug_apply_uncommitted=True), seed=7,
                  n_clusters=96, n_ticks=320)
    assert rep.n_violating > 0


def test_kv_stale_read_oracle_fires():
    """Serving Gets from the contacted node's local state without committing
    them (the read-from-follower bug) must trip the reads-linearizability
    oracle: a lagging node's state is below the invoke-time committed truth.
    The reference leaves its linearizability tests commented out
    (kvraft/tests.rs:386-390); this is their on-device analogue."""
    rep = kv_fuzz(BASE, KV.replace(bug_stale_read=True, p_get=0.5), seed=7,
                  n_clusters=96, n_ticks=320)
    assert rep.n_violating > 0
    assert np.any(
        (rep.violations[rep.violating_clusters()] & VIOLATION_STALE_READ) != 0
    )


def test_kv_deterministic_and_replay():
    """Same seed => bit-identical report; single-cluster replay reproduces."""
    r1 = kv_fuzz(BASE, KV, seed=123, n_clusters=48, n_ticks=256)
    r2 = kv_fuzz(BASE, KV, seed=123, n_clusters=48, n_ticks=256)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)
    # replay cluster 3 alone and match the batched run's observables
    final = kv_replay_cluster(BASE, KV, seed=123, cluster_id=3, n_ticks=256)
    assert int(final.raft.violations) == int(r1.violations[3])
    assert int(final.clerk_acked.sum()) == int(r1.acked_ops[3])
    assert int(final.raft.msg_count) == int(r1.msg_count[3])


def test_kv_sharded_over_mesh():
    """The cluster axis shards over the device mesh with identical results."""
    from conftest import cluster_mesh

    mesh = cluster_mesh(64)
    fn = make_kv_fuzz_fn(BASE, KV, n_clusters=64, n_ticks=128, mesh=mesh)
    rep_sharded = kv_report(jax.block_until_ready(fn(jnp_seed(5))))
    rep_local = kv_fuzz(BASE, KV, seed=5, n_clusters=64, n_ticks=128)
    np.testing.assert_array_equal(rep_sharded.violations, rep_local.violations)
    np.testing.assert_array_equal(rep_sharded.acked_ops, rep_local.acked_ops)
    assert rep_sharded.n_violating == 0


def jnp_seed(s):
    import jax.numpy as jnp

    return jnp.asarray(s, jnp.uint32)


def test_kv_with_puts_clean():
    """The full reference op set Op::{Get,Put,Append} (msg.rs:3-8) under the
    fault storm: Puts reset values but the mutation-version model keeps every
    oracle exact — zero violations, and all three kinds flow."""
    rep = kv_fuzz(BASE, KV.replace(p_get=0.3, p_put=0.3), seed=21,
                  n_clusters=96, n_ticks=320)
    assert rep.n_violating == 0, (
        f"violations: {rep.violations[rep.violating_clusters()[:8]]}"
    )
    assert rep.acked_ops.sum() > 96 * 5
    assert rep.acked_gets.sum() > 96


def test_kv_stale_read_oracle_fires_with_puts():
    """The read-from-follower bug must stay visible when Puts are in the
    mix (a stale version below the invoke-time truth)."""
    rep = kv_fuzz(BASE, KV.replace(bug_stale_read=True, p_get=0.4, p_put=0.3),
                  seed=7, n_clusters=64, n_ticks=320)
    assert rep.n_violating > 0, "stale-read bug with puts escaped the oracle"
    bits = rep.violations[rep.violating_clusters()]
    assert (bits & VIOLATION_STALE_READ).any()


def test_kv_sweep_per_cluster_knobs_and_bugs():
    """Service-layer sweeps (make_kv_sweep_fn): per-cluster raft AND kv
    knobs in ONE program. Two properties: (a) a sweep whose knobs are all
    equal reproduces the uniform-layout program bit-for-bit (same cluster
    keys, same draws — only the knob layout differs); (b) the BUG axis is
    per-cluster data — planting bug_stale_read in exactly half the batch
    puts every violation in that half."""
    import jax.numpy as jnp

    from madraft_tpu.tpusim.kv import kv_report, make_kv_sweep_fn

    n, ticks = 48, 320
    # (a) uniform-valued sweep == uniform program
    fn = make_kv_sweep_fn(BASE, BASE.knobs(), KV.knobs(), KV, n, ticks)
    rep_sweep = kv_report(jax.block_until_ready(fn(jnp.asarray(7, jnp.uint32))))
    rep_uni = kv_fuzz(BASE, KV, seed=7, n_clusters=n, n_ticks=ticks)
    for a, b in zip(rep_sweep, rep_uni):
        np.testing.assert_array_equal(a, b)

    # (b) the bug axis as data: stale-read serving in the first half only
    half = jnp.arange(n) < n // 2
    kkn = KV.replace(p_get=0.5).knobs()._replace(bug_stale_read=half)
    fn = make_kv_sweep_fn(BASE, BASE.knobs(), kkn, KV, n, ticks)
    rep = kv_report(jax.block_until_ready(fn(jnp.asarray(7, jnp.uint32))))
    bugged = np.asarray(half)
    viol = rep.violations != 0
    assert viol[bugged].any(), "bugged half produced no stale read"
    assert (rep.violations[bugged & viol] & VIOLATION_STALE_READ).all()
    assert not viol[~bugged].any(), (
        f"clean half flagged: {rep.violations[~bugged & viol]}"
    )

    # knob validation is eager
    bad = KV.knobs()._replace(p_get=jnp.float32(0.8), p_put=jnp.float32(0.5))
    with pytest.raises(ValueError, match="p_get"):
        make_kv_sweep_fn(BASE, BASE.knobs(), bad, KV, n, ticks)


# ------------------------------------------- NotLeader{hint} clerk routing
def test_kv_clerk_hint_following_beats_random_routing():
    """The reference clerk follows NotLeader{hint} replies and paces itself
    by awaiting each call (/root/reference/src/kvraft/msg.rs:10-18,
    client.rs:32-63). Modeled: clerk_leader belief + p_follow_hint routing +
    retry_wait await-reply pacing. Under a storm, hint-following must beat
    random routing on acked throughput (the hint exists to skip the 1/n
    leader lottery), with safety untouched. Without the await pacing this
    inverts — concentrated retries enqueue duplicate appends faster than
    commit drains them (queueing feedback; PERF.md round 5) — which is why
    retry_wait exists."""
    storm = BASE.replace(p_client_cmd=0.0, compact_at_commit=False,
                         loss_prob=0.1, p_crash=0.01, p_restart=0.2,
                         max_dead=1)
    base = KvConfig(p_retry=0.8, retry_wait=12)
    r_rand = kv_fuzz(storm, base, seed=11, n_clusters=16, n_ticks=600)
    r_hint = kv_fuzz(storm, base.replace(p_follow_hint=0.9), seed=11,
                     n_clusters=16, n_ticks=600)
    assert (r_rand.violations == 0).all()
    assert (r_hint.violations == 0).all()
    assert r_hint.acked_ops.sum() > 1.2 * r_rand.acked_ops.sum(), (
        f"hint-following must beat random routing: "
        f"{r_hint.acked_ops.sum()} vs {r_rand.acked_ops.sum()}"
    )


def test_kv_stale_hint_loop_caught_as_liveness_loss():
    """bug_stale_hint: nodes hint the next FOLLOWER in the ring, skipping
    the real leader — the deposed-leaders-hint-each-other loop. Hints only
    steer routing, so no safety oracle can fire; the catch is the measured
    liveness collapse: bugged hint-following loses a large share of the
    hint advantage (acked-ops floor comparison, the VERDICT round-5 item)."""
    storm = BASE.replace(p_client_cmd=0.0, compact_at_commit=False,
                         loss_prob=0.1, p_crash=0.01, p_restart=0.2,
                         max_dead=1)
    base = KvConfig(p_retry=0.8, retry_wait=12, p_follow_hint=0.9)
    r_hint = kv_fuzz(storm, base, seed=11, n_clusters=16, n_ticks=600)
    r_bug = kv_fuzz(storm, base.replace(bug_stale_hint=True), seed=11,
                    n_clusters=16, n_ticks=600)
    assert (r_bug.violations == 0).all(), "hints must not corrupt safety"
    assert r_bug.acked_ops.sum() < 0.85 * r_hint.acked_ops.sum(), (
        f"the hint loop must cost measurable liveness: "
        f"bugged {r_bug.acked_ops.sum()} vs honest {r_hint.acked_ops.sum()}"
    )
