"""The continuous fuzzing pool (retire-and-refill) and the uniform sweep
dispatch: the first pool generation is bit-identical to straight fuzz, every
pool hit replays bit-exactly via (seed, global_cluster_id) across refill
generations, the chunk carry is donated, pool hits explain like fuzz hits,
the sharded (lane-partitioned) pool's report multiset is device-count
invariant with shard-blind replay, the harvest-pipeline telemetry rides in
every summary, and a small-grid sweep's uniform dispatch matches the
per-cluster layout."""

import jax.numpy as jnp
import numpy as np
import pytest

from madraft_tpu.tpusim import SimConfig
from madraft_tpu.tpusim.engine import (
    fuzz,
    make_sweep_fn,
    replay_cluster,
    report,
    run_pool,
)

STORM = SimConfig(
    n_nodes=5, p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
    max_dead=2, p_repartition=0.02, p_heal=0.05,
)
# dual-leader demo config: violations land early and often, so a tiny pool
# run retires violating clusters across several refill generations
VIOL = STORM.replace(majority_override=2)

_POOL_CACHE = {}


def _pooled(cfg, seed, n, horizon, chunk, budget):
    """Run the pool once per distinct argument tuple (results are pure
    functions of the arguments — determinism is itself under test via the
    replay assertions)."""
    key = (cfg, seed, n, horizon, chunk, budget)
    if key not in _POOL_CACHE:
        rows = []
        summary = run_pool(cfg, seed, n, horizon, chunk_ticks=chunk,
                           budget_ticks=budget, on_retired=rows.append)
        _POOL_CACHE[key] = (rows, summary)
    return _POOL_CACHE[key]


def test_pool_first_generation_bit_identical_to_fuzz():
    # horizon == chunk == budget: exactly one chunk + one harvest, so every
    # lane retires with the state straight fuzz would have produced — all
    # report fields must match bit-exactly (the golden-guard property on
    # the pool path)
    rep = fuzz(STORM, 12345, 16, 96)
    rows, summary = _pooled(STORM, 12345, 16, 96, 96, 96)
    assert summary["retired"] == 16
    assert sorted(r["cluster_id"] for r in rows) == list(range(16))
    for r in rows:
        c = r["cluster_id"]
        assert r["ticks_run"] == 96
        assert r["violations"] == int(rep.violations[c])
        assert r["first_violation_tick"] == int(rep.first_violation_tick[c])
        assert r["first_leader_tick"] == int(rep.first_leader_tick[c])
        assert r["committed"] == int(rep.committed[c])
        assert r["msg_count"] == int(rep.msg_count[c])
        assert r["snap_installs"] == int(rep.snap_installs[c])


def test_pool_refill_ids_are_monotone_and_unique():
    rows, summary = _pooled(VIOL, 7, 16, 64, 32, 320)
    ids = [r["cluster_id"] for r in rows]
    assert len(ids) == len(set(ids)), "a global cluster id was reused"
    assert summary["retired"] == len(rows)
    assert summary["retired_violating"] == sum(
        1 for r in rows if r["violations"]
    )
    # refill actually happened: ids beyond the first generation retired,
    # and the monotone counter accounts for every lane ever started
    assert max(ids) >= 16
    assert summary["next_cluster_id"] == 16 + len(rows), (
        "next_id must advance by exactly the number of retirements"
    )
    # a lane's age is always a whole number of chunks
    assert all(r["ticks_run"] % 32 == 0 for r in rows)


def test_pool_hits_replay_bit_exact_across_generations():
    # the (seed, global_cluster_id) replay contract across >= 2 refill
    # generations: every violating retired cluster must reproduce through
    # replay_cluster with its reported ticks_run
    rows, _ = _pooled(VIOL, 7, 16, 64, 32, 320)
    viol = [r for r in rows if r["violations"]]
    assert viol, "the dual-leader demo config must violate"
    gens = {r["cluster_id"] // 16 for r in viol}
    assert len(gens) >= 2 and max(gens) >= 1, (
        f"need violating hits across >= 2 refill generations, got ids "
        f"{[r['cluster_id'] for r in viol]}"
    )
    for r in viol[:8]:
        st = replay_cluster(VIOL, 7, r["cluster_id"], r["ticks_run"])
        assert int(st.violations) == r["violations"]
        assert int(st.first_violation_tick) == r["first_violation_tick"]
        assert int(st.shadow_len) == r["committed"]
        assert int(st.msg_count) == r["msg_count"]


def test_pool_hit_explains_like_a_fuzz_hit():
    # the flight recorder works on a pool hit's (seed, global id) exactly
    # as on a fuzz hit: traced replay reproduces the violation and decodes
    # a violation event at the reported tick
    from madraft_tpu.tpusim.trace import decode_events, replay_cluster_traced

    rows, _ = _pooled(VIOL, 7, 16, 64, 32, 320)
    r = next(r for r in rows if r["violations"])
    final, rec = replay_cluster_traced(VIOL, 7, r["cluster_id"],
                                       r["ticks_run"])
    assert int(final.violations) == r["violations"]
    events = decode_events(rec)
    viol_events = [e for e in events if e.get("event") == "violation"]
    assert viol_events, "no violation event decoded for a pool hit"
    assert viol_events[0]["tick"] == r["first_violation_tick"]


def test_pool_chunk_carry_is_donated():
    # no double peak-HBM vs the fixed-horizon program: the chunk program
    # consumes its state carry (donate_argnums), so the input buffer is
    # dead after the call
    from madraft_tpu.tpusim.engine import _chunk_program, _pool_init_program

    static = STORM.static_key()
    kn = STORM.knobs()
    init = _pool_init_program(static, 16, None)
    chunk = _chunk_program(static, 16)
    states, keys, _ = init(jnp.asarray(3, jnp.uint32), kn,
                           jnp.asarray(0, jnp.int32))
    out = chunk(states, keys, kn, jnp.asarray(8, jnp.int32))
    assert int(np.asarray(out.tick)[0]) == 8
    with pytest.raises(Exception, match="[Dd]onat|[Dd]elet"):
        np.asarray(states.tick)


def _strip(rows):
    return [
        {k: v for k, v in r.items()
         if k not in ("wall_s", "violations_per_s")}
        for r in rows
    ]


def test_pool_sharded_multiset_matches_single_device():
    # the pod-scale replay contract (ISSUE 7): under the lane-partitioned
    # global-id scheme (lane l's generation-g cluster owns id g*lanes + l),
    # a cluster's lifetime is a pure function of (seed, global_id, chunk
    # cadence, horizon) and the id set a tick budget draws is device-count
    # independent — so the 2-device pool must produce the SAME MULTISET of
    # retired-cluster reports as the 1-device run (emission order differs:
    # harvests interleave lanes, not id order)
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    rows_1, rows_2 = [], []
    s1 = run_pool(VIOL, 7, 16, 64, chunk_ticks=32, budget_ticks=320,
                  devices=1, on_retired=rows_1.append)
    s2 = run_pool(VIOL, 7, 16, 64, chunk_ticks=32, budget_ticks=320,
                  devices=2, on_retired=rows_2.append)
    key = lambda r: r["cluster_id"]  # noqa: E731
    assert sorted(_strip(rows_2), key=key) == sorted(_strip(rows_1), key=key)
    assert s2["devices"] == 2 and s2["id_scheme"] == "lane"
    assert s1["retired"] == s2["retired"]
    assert s1["retired_violating"] == s2["retired_violating"]
    assert sorted(s1["violating_clusters"]) == sorted(s2["violating_clusters"])
    # lane-partitioned ids: unique, refilled beyond generation 0, and every
    # id decodes to its lane (id mod lanes) under the documented scheme
    ids = [r["cluster_id"] for r in rows_2]
    assert len(ids) == len(set(ids)), "a global cluster id was reused"
    assert max(ids) >= 16, "no refill generation retired"
    assert all(i < s2["id_watermark"] for i in ids)
    from madraft_tpu.tpusim.config import pool_generation, pool_lane

    for r in rows_2:
        i = r["cluster_id"]
        assert pool_generation(i, 16) * 16 + pool_lane(i, 16) == i


def test_pool_sharded_hit_replays_on_single_device():
    # a violating hit harvested on shard 1 (lanes 8..15 of the 2-device
    # run) replays bit-exactly through the ordinary single-device
    # replay_cluster — the (seed, global_id) contract is shard-blind
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    from madraft_tpu.tpusim.config import pool_shard

    rows = []
    run_pool(VIOL, 7, 16, 64, chunk_ticks=32, budget_ticks=320,
             devices=2, on_retired=rows.append)
    viol = [r for r in rows if r["violations"]
            and pool_shard(r["cluster_id"], 16, 2) == 1]
    assert viol, "need a violating hit harvested on shard 1"
    for r in viol[:4]:
        st = replay_cluster(VIOL, 7, r["cluster_id"], r["ticks_run"])
        assert int(st.violations) == r["violations"]
        assert int(st.first_violation_tick) == r["first_violation_tick"]
        assert int(st.shadow_len) == r["committed"]
        assert int(st.msg_count) == r["msg_count"]


def test_pool_summary_pipeline_telemetry():
    # the pipeline telemetry (ISSUE 7) rides in every pool summary:
    # warm-up compile wall, the inter-dispatch gap, the device-bound wall,
    # and the host harvest/emit wall that overlapped device execution
    _, summary = _pooled(VIOL, 7, 16, 64, 32, 320)
    for k in ("compile_s", "dispatch_gap_s", "device_wait_s",
              "host_overlap_s"):
        assert k in summary and summary[k] >= 0, (k, summary)
    # the device loop is device-bound here: the gap (host-caused wall
    # between dispatches) must be a small fraction of the device wall
    assert summary["dispatch_gap_s"] < summary["wall_s"]


def test_pool_on_retired_exception_propagates():
    # the consumer thread must not swallow an emitter crash: the exception
    # surfaces on the calling thread and the pool shuts down cleanly
    def boom(row):
        raise RuntimeError("emitter died")

    with pytest.raises(RuntimeError, match="emitter died"):
        run_pool(VIOL, 3, 16, 64, chunk_ticks=32, budget_ticks=64,
                 on_retired=boom)


def test_pool_devices_validation():
    import jax

    with pytest.raises(ValueError, match="divide evenly"):
        run_pool(VIOL, 7, 15, 64, devices=2)
    with pytest.raises(ValueError, match="exceeds"):
        run_pool(VIOL, 7, 16, 64, devices=len(jax.devices()) + 1)
    with pytest.raises(ValueError, match=">= 1"):
        run_pool(VIOL, 7, 16, 64, devices=0)


def test_pool_budget_seconds_terminates():
    # wall-clock budget: stops at the first harvest past the budget, and
    # still reports whatever retired on the way
    rows = []
    summary = run_pool(VIOL, 11, 16, 64, chunk_ticks=32,
                       budget_seconds=0.001, on_retired=rows.append)
    assert summary["lane_ticks"] >= 32  # at least one chunk always runs
    assert summary["retired"] == len(rows)


def test_sweep_uniform_dispatch_matches_per_cluster():
    # the knob-layout cliff fix for small grids: <= K contiguous knob cells
    # dispatch as per-cell uniform-knob programs (the fast layout); the
    # report must be bit-identical to the per-cluster-knob program, field
    # by field — same knob values reaching the same (seed, cluster_id)
    # streams
    n, per = 12, 6
    loss = jnp.repeat(jnp.asarray([0.0, 0.3], jnp.float32), per)
    kn = STORM.knobs()._replace(loss_prob=loss)
    fast = make_sweep_fn(STORM, kn, n, 160)
    slow = make_sweep_fn(STORM, kn, n, 160, uniform_max_cells=0)
    assert fast.dispatch == "uniform"
    assert slow.dispatch == "per_cluster"
    ra, rb = report(fast(5)), report(slow(5))
    for f in ra._fields:
        np.testing.assert_array_equal(getattr(ra, f), getattr(rb, f),
                                      err_msg=f"sweep layout drift in {f}")
    # AOT split works on the uniform dispatch too (run_telemetry path)
    assert fast.compile_timed(5) is not None
    rc = report(fast(5))
    for f in rc._fields:
        np.testing.assert_array_equal(getattr(ra, f), getattr(rc, f))


def test_sweep_uniform_falls_back_above_cell_cap():
    # 16 distinct cells > the K=8 cap: the heterogeneous program must be
    # chosen (per-cell batches would under-fill the chip)
    n = 16
    loss = jnp.arange(n, dtype=jnp.float32) / (2 * n)
    kn = STORM.knobs()._replace(loss_prob=loss)
    fn = make_sweep_fn(STORM, kn, n, 8)
    assert fn.dispatch == "per_cluster"
