"""madtpu CLI — the front door over the batched fuzzers and the bridge
(SURVEY.md §7 architecture item 4's "CLI" deliverable).

    python -m madraft_tpu fuzz        --clusters 4096 --ticks 1024 [--storm]
    python -m madraft_tpu pool        --clusters 4096 --ticks 600 --budget-ticks 4800
    python -m madraft_tpu kv-fuzz     --clusters 512  --ticks 512
    python -m madraft_tpu ctrler-fuzz --clusters 512  --ticks 512
    python -m madraft_tpu shardkv-fuzz --clusters 64  --ticks 640
    python -m madraft_tpu sweep       --loss 0,0.1,0.3 --crash 0,0.02
    python -m madraft_tpu replay      --seed S --cluster C --ticks T [--storm]
    python -m madraft_tpu explain     --seed S --cluster C --ticks T [--window W]
    python -m madraft_tpu bridge      --seed S --cluster C --ticks T [--storm]

Every command prints one JSON line (machine-readable; violations are data;
fuzz/sweep reports carry run telemetry — compile vs execute wall, steps/s,
device, backend). A violating cluster reported by `fuzz` is reproduced
exactly by `replay` with the same (seed, cluster) — the MADSIM_TEST_SEED
replay contract; `explain` re-runs it with the flight recorder on
(tpusim/trace.py) and prints the decoded event timeline around the first
violation (or a Perfetto export via --format chrome) — and `bridge` closes
the loop by re-running its fault schedule on the C++ runtime via the
in-process bindings (madraft_tpu.simcore), localizing the first divergence
tick when the violation class fails to reproduce. The fuzz commands accept
`--check-deterministic` (or the env var MADTPU_TEST_CHECK_DETERMINISTIC,
the C++ runner's spelling) to double-run and demand a bit-identical report
— the MADSIM_TEST_CHECK_DETERMINISTIC analogue.
"""

from __future__ import annotations

import argparse
import json
import sys


def _storm(cfg):
    return cfg.replace(
        p_client_cmd=0.2, loss_prob=0.1, p_crash=0.01, p_restart=0.2,
        max_dead=2, p_repartition=0.02, p_heal=0.05,
    )


def _sim_config(args):
    import sys

    from madraft_tpu.tpusim import SimConfig
    from madraft_tpu.tpusim.config import storm_profiles

    profiles = storm_profiles()
    prof = getattr(args, "profile", "")
    # the budget/manifestation warnings are fuzz advice — meaningless for
    # the single-cluster verbs (replay/explain/bridge, which carry
    # --cluster and re-run one already-known cluster)
    single_cluster = getattr(args, "cluster", None) is not None
    if prof:
        cfg, rec_clusters, rec_ticks, _bugs = profiles[prof]
        # the profile owns topology and fault knobs (--nodes/--storm do not
        # apply); scale stays overridable, with a warning when it is below
        # the validated demonstration scale
        if args.storm:
            print(
                f"madtpu: warning: --storm is ignored — profile {prof!r} "
                "defines the full fault storm", file=sys.stderr,
            )
        if args.bug and not single_cluster and (
                args.clusters * args.ticks < rec_clusters * rec_ticks):
            print(
                f"madtpu: warning: profile {prof!r} demonstrated "
                f"{args.bug!r} at --clusters {rec_clusters} --ticks "
                f"{rec_ticks}; the current budget may be too small for the "
                "bug to manifest", file=sys.stderr,
            )
    else:
        cfg = SimConfig(n_nodes=args.nodes)
        if args.storm:
            cfg = _storm(cfg)
        if args.bug and not single_cluster:
            # each bug needs its tuned storm; at generic settings the buggy
            # branch often never executes and the report is bit-identical to
            # the correct program's (round-3 verdict)
            want = [
                name for name, (_, _, _, bugs) in profiles.items()
                if args.bug in bugs
            ]
            hint = f" (try --profile {want[0]})" if want else ""
            print(
                f"madtpu: warning: --bug {args.bug!r} without --profile — "
                f"the bug may never manifest at these settings{hint}",
                file=sys.stderr,
            )
    if args.majority_override:
        cfg = cfg.replace(majority_override=args.majority_override)
    if args.bug:
        cfg = cfg.replace(bug=args.bug)
    # durability-axis overrides compose with any profile (a storm profile
    # plus --lose-unsynced turns its crashes into power-loss crashes)
    if args.fsync_every:
        cfg = cfg.replace(fsync_every=args.fsync_every)
    if args.lose_unsynced >= 0:
        cfg = cfg.replace(p_lose_unsynced=args.lose_unsynced)
    if getattr(args, "metrics", False):
        # the on-device metrics plane (README "Metrics"): a STATIC program
        # flag like coverage — metric runs select their own cached
        # programs, the metrics-off hot path is untouched
        cfg = cfg.replace(metrics=True)
    return cfg


def cmd_list_profiles(args=None) -> int:
    """``--list-profiles`` (ISSUE 19): print the scenario registry — one
    row per named profile with its knob deltas from the profile's own
    n_nodes default, the demonstrated scale, the clean-algorithm liveness
    floor, the p99 ceiling, and the C++-bridge support — and exit 0.
    Host-side only (runs before backend init, like stats)."""
    import dataclasses

    from madraft_tpu.tpusim import SimConfig
    from madraft_tpu.tpusim.config import profile_gates, storm_profiles

    gates = profile_gates()
    print(f"{'profile':18s} {'floor>=':>8s} {'p99<=':>6s} {'scale':>10s} "
          f"{'bridge':11s} knobs")
    for name, (cfg, rec_clusters, rec_ticks, bugs) in storm_profiles().items():
        base = dataclasses.asdict(SimConfig(n_nodes=cfg.n_nodes))
        cur = dataclasses.asdict(cfg)
        delta = " ".join(
            f"{k}={v}" for k, v in cur.items() if v != base[k]
        ) or "(defaults)"
        if cfg.n_nodes != 5:
            delta = f"n_nodes={cfg.n_nodes} " + delta
        g = gates[name]
        wl = g.get("workload") or {}
        if wl:
            delta += " | workload: " + " ".join(
                f"{k}={v}" for k, v in wl.items()
            )
        if bugs:
            delta += " | demonstrates: " + ",".join(bugs)
        print(f"{name:18s} {g['liveness_floor']:>8g} {g['p99_ceiling']:>6d} "
              f"{rec_clusters:>5d}x{rec_ticks:<4d} {g['bridge']:11s} "
              f"{delta}")
    return 0


def _knobs_json(verb: str, raw: str):
    """``--knobs-json`` value -> dict (or None when absent), with clean CLI
    errors at exit code 2 (the argparse usage-error convention) so a bad
    row stays distinguishable from replay's violation-found exit 1."""
    if not raw:
        return None
    try:
        return json.loads(raw)
    except json.JSONDecodeError as e:
        print(f"{verb}: --knobs-json is not valid JSON: {e}",
              file=sys.stderr)
        raise SystemExit(2)


def _replay_or_usage_error(verb: str, fn, *a, **kw):
    """Run a replay-family call, converting the eager knob-validation
    ValueErrors (unknown field, out-of-range, non-object row) into clean
    usage errors instead of raw tracebacks."""
    try:
        return fn(*a, **kw)
    except ValueError as e:
        print(f"{verb}: {e}", file=sys.stderr)
        raise SystemExit(2)


def _mesh(args):
    """--mesh: shard the cluster batch over every attached device (the
    workload's scaling axis — pure data parallelism, no cross-chip
    collectives on the hot path; SURVEY.md §5). Multi-host deployments
    initialize jax.distributed before invoking the CLI and get the global
    device set the same way."""
    if not getattr(args, "mesh", False):
        return None
    import jax
    import numpy as np

    devs = np.array(jax.devices())
    if args.clusters % len(devs):
        raise SystemExit(
            f"--clusters {args.clusters} must divide evenly over "
            f"{len(devs)} devices"
        )
    return jax.sharding.Mesh(devs, ("clusters",))


def _reports_equal(a, b) -> bool:
    import numpy as np

    return all(
        np.array_equal(getattr(a, f), getattr(b, f)) for f in a._fields
    )


def _det_check(args, rep, rerun):
    """The MADSIM_TEST_CHECK_DETERMINISTIC contract on the batched backend
    (/root/reference/README.md:81-87): re-run the identical program and
    demand a bit-identical report. Enabled by --check-deterministic or the
    env var MADTPU_TEST_CHECK_DETERMINISTIC — which shares the C++ runner's
    semantics: unset, empty, or "0" disables. Returns (extra_json_fields,
    failed)."""
    import os

    env = os.environ.get("MADTPU_TEST_CHECK_DETERMINISTIC", "0")
    if not (args.check_deterministic or env not in ("", "0")):
        return {}, False
    same = _reports_equal(rep, rerun())
    return {"deterministic": bool(same)}, not same


def _finish_fuzz(args, fn, rep_fn):
    """AOT-compile the fuzz program (timed), run it (timed), optionally
    double-run for the determinism check, and print the JSON report with
    per-invocation run telemetry (compile vs execute wall, steps/s, device,
    backend — throughput is observable per run, not only via bench.py)."""
    import jax

    from madraft_tpu.tpusim.engine import run_telemetry

    rep, tele = run_telemetry(
        fn, rep_fn, args.seed, args.clusters * args.ticks,
        n_lanes=args.clusters,
    )

    def run():
        return rep_fn(jax.block_until_ready(fn(args.seed)))

    extra, det_failed = _det_check(args, rep, run)
    _report_json(rep, {"seed": args.seed, "telemetry": tele, **extra})
    return 1 if (rep.n_violating or det_failed) else 0


def _violation_union(rep) -> int:
    """OR of every violation bitmask in the report (incl. the shardkv
    report's separate per-group raft masks)."""
    import numpy as np

    union = 0
    for field in ("violations", "raft_violations"):
        v = np.asarray(getattr(rep, field, np.zeros(0, np.int64))).ravel()
        if v.size:
            union |= int(np.bitwise_or.reduce(v))
    return union


def _report_json(rep, extra=None):
    from madraft_tpu.tpusim.config import violation_names

    bad = rep.violating_clusters()
    out = {
        "violating": int(rep.n_violating),
        "violating_clusters": [int(c) for c in bad[:16]],
        # the list above truncates at 16 — carry the full count so coverage
        # accounting never under-reads
        "violating_clusters_total": int(bad.size),
        "violation_names": violation_names(_violation_union(rep)),
    }
    for f in rep._fields:
        v = getattr(rep, f)
        # the metrics rows (lat_hist/ev_counts, 2-d or None) get their own
        # decoded blocks below, not a meaningless *_mean scalar
        if v is None or getattr(v, "ndim", 0) > 1:
            continue
        if hasattr(v, "mean"):
            out[f"{f}_mean"] = round(float(v.mean()), 2)
    # histograms/counters merge across clusters by plain addition —
    # latency_p50/p99 decode from the merged buckets (ISSUE 10). The two
    # blocks are independent; since ISSUE 11 every clerk-bearing layer
    # (kv, ctrler, shardkv) stamps submit->ack latency, so a --metrics
    # report carries both.
    if getattr(rep, "lat_hist", None) is not None:
        from madraft_tpu.tpusim.metrics import latency_summary

        out["latency"] = latency_summary(rep.lat_hist.sum(axis=0))
    if getattr(rep, "phase_hist", None) is not None:
        # attribution plane (ISSUE 12): phase breakdown keyed by name, the
        # per-key/per-client axes (kv/shardkv reports), and the report's
        # global worst op (the max over the per-cluster registers)
        from madraft_tpu.tpusim.metrics import (
            latency_summary,
            merge_worst_registers,
            phases_summary,
        )

        out["latency"]["phases"] = phases_summary(
            rep.phase_hist.sum(axis=0), rep.phase_ticks.sum(axis=0)
        )
        out["latency"]["ticks_total"] = int(rep.lat_ticks.sum())
        retries = getattr(rep, "client_retries", None)
        r = retries.sum(axis=0) if retries is not None else None
        for field, key in (("key_hist", "by_key"),
                           ("client_hist", "by_client")):
            axes = getattr(rep, field, None)
            if axes is not None:
                merged = axes.sum(axis=0)  # [rows, HB]
                # a client with zero acked ops but nonzero retries is the
                # MOST interesting per-client row (a permanent NotLeader
                # hunt: retries >> ops) — it must not vanish from the axis
                want_c = key == "by_client" and r is not None
                out["latency"][key] = {
                    str(k): latency_summary(merged[k])
                    for k in range(merged.shape[0])
                    if merged[k].sum() or (want_c and r[k])
                }
                if want_c:
                    for k, d in out["latency"][key].items():
                        d["retries"] = int(r[int(k)])
        out["worst_op"] = merge_worst_registers(
            rep.worst_lat, rep.worst_phases, rep.worst_key,
            rep.worst_client, rep.worst_sub,
        )
    if getattr(rep, "ev_counts", None) is not None:
        from madraft_tpu.tpusim.metrics import event_summary

        out["events"] = event_summary(rep.ev_counts.sum(axis=0))
    if extra:
        out.update(extra)
    print(json.dumps(out))


def cmd_fuzz(args):
    from madraft_tpu.tpusim.engine import make_fuzz_fn, report

    fn = make_fuzz_fn(_sim_config(args), args.clusters, args.ticks,
                      mesh=_mesh(args))
    return _finish_fuzz(args, fn, report)


def cmd_pool(args):
    """Continuous fuzzing pool (retire-and-refill): --clusters lanes stay
    resident on device; a lane retires when its cluster violated or reached
    the --ticks horizon and is refilled with a fresh cluster under a new
    global id — the (seed, cluster_id) replay contract survives arbitrarily
    many refills, so any streamed hit replays/explains exactly like a fuzz
    hit. Streams one JSONL line per retired cluster (with the running
    violations/s), then a summary line; exit 1 iff a violation was found.

    --devices N is the pod-scale path: lanes shard over the first N
    attached devices under the lane-partitioned global-id scheme (lane l's
    generation-g cluster owns id g*lanes + l; config.pool_lane/pool_shard
    decode it), which keeps refill bookkeeping per-shard and makes the
    retired-report multiset independent of the device count. --mesh is
    shorthand for --devices <all attached>."""
    import jax

    from madraft_tpu.tpusim.engine import run_pool

    cfg = _sim_config(args)
    budget_ticks = args.budget_ticks if args.budget_ticks > 0 else None
    budget_seconds = args.budget_seconds if args.budget_seconds > 0 else None
    emit_all = args.emit == "all"
    def usage_error(msg):
        # exit 2 (argparse convention), NOT 1: for pool, exit 1 is the
        # documented "violation found" signal automation keys on
        print(f"pool: {msg}", file=sys.stderr)
        raise SystemExit(2)

    if args.devices < 0:
        # a negative count (e.g. a typo for a positive one) must not
        # silently fall back to the single-device monotone pool
        usage_error(f"--devices {args.devices} must be >= 1 (0 = unset)")
    devices = args.devices if args.devices > 0 else None
    if args.mesh and devices is None:
        devices = len(jax.devices())
    if devices is not None:
        from madraft_tpu.tpusim.engine import _pool_mesh

        try:
            # the engine's validation (device count, the one shard-layout
            # rule), surfaced as a clean usage error instead of a traceback
            _pool_mesh(args.clusters, devices)
        except ValueError as e:
            usage_error(str(e))
    ccfg = None
    if not args.coverage and (args.coverage_random
                              or args.coverage_bits is not None):
        # a silently-ignored modifier would run the WRONG program: a user
        # asking for the A/B baseline must not get the plain pool (no
        # coverage dict, different compiled program) without noticing.
        # --coverage-bits defaults to None (not the real default) so an
        # EXPLICIT default-valued pass still trips this gate.
        usage_error(
            "--coverage-random/--coverage-bits modify --coverage — add "
            "--coverage (or drop them)"
        )
    if args.coverage:
        from madraft_tpu.tpusim.config import CoverageConfig

        bits = {} if args.coverage_bits is None else \
            {"bitmap_bits": args.coverage_bits}
        try:
            ccfg = CoverageConfig(guided=not args.coverage_random, **bits)
        except ValueError as e:  # e.g. --coverage-bits 100
            usage_error(str(e))

    def on_retired(row):
        if emit_all or row["violations"]:
            print(json.dumps(row), flush=True)

    # live-telemetry plane (ISSUE 17): --heartbeat streams one JSONL row
    # per harvest generation (+ the attachable manifest) to PATH;
    # --digest-every N prints the one-line human digest of every Nth
    # generation on stderr — stdout stays a clean JSONL stream either way
    hb = None
    if args.digest_every < 0:
        usage_error(f"--digest-every {args.digest_every} must be >= 1 "
                    f"(0 = off)")
    if args.heartbeat or args.digest_every:
        from madraft_tpu.tpusim.telemetry import HeartbeatWriter, digest_line

        def on_row(row, _every=args.digest_every):
            if _every and not row.get("final") and row["gen"] % _every == 0:
                print(f"pool: {digest_line(row)}", file=sys.stderr,
                      flush=True)

        hb = HeartbeatWriter(args.heartbeat or None, on_row=on_row)

    summary = run_pool(
        cfg, args.seed, args.clusters, args.ticks,
        chunk_ticks=args.chunk_ticks, budget_ticks=budget_ticks,
        budget_seconds=budget_seconds, devices=devices,
        on_retired=on_retired, coverage=ccfg, heartbeat=hb,
        profile=getattr(args, "profile", ""),
    )
    dev = jax.devices()[0]
    summary.update(
        {"seed": args.seed, "device": str(dev), "backend": dev.platform}
    )
    if "latency" in summary:
        # one-line human digest of the client experience, next to the
        # summary's violations/s — on stderr, so both --emit modes keep
        # stdout as a clean JSONL stream
        lat = summary["latency"]
        print(
            f"pool: latency p50={lat['p50_ticks']} p99={lat['p99_ticks']} "
            f"ticks over {lat['ops']} ops; "
            f"{summary['violations_per_s']} violations/s",
            file=sys.stderr,
        )
    print(json.dumps(summary))
    return 1 if summary["retired_violating"] else 0


def _service_bugs(cfg_cls) -> set:
    """The layer's planted-bug names, derived from its config dataclass's
    bug_* fields — one source of truth, so a new bug knob is automatically
    reachable from the CLI."""
    import dataclasses

    return {
        f.name[len("bug_"):]
        for f in dataclasses.fields(cfg_cls)
        if f.name.startswith("bug_")
    }


def _with_service_bug(kcfg, name):
    """Set the layer's planted-bug knob named by --service-bug ('' = none).
    Unknown names are rejected eagerly — a typo'd bug silently fuzzing the
    correct service would read as 'bug not caught'."""
    if not name:
        return kcfg
    known = _service_bugs(type(kcfg))
    if name not in known:
        raise SystemExit(
            f"unknown service bug {name!r}; this layer knows: {sorted(known)}"
        )
    return kcfg.replace(**{f"bug_{name}": True})


def cmd_kv_fuzz(args):
    from madraft_tpu.tpusim.kv import KvConfig, kv_report, make_kv_fuzz_fn

    cfg = _sim_config(args).replace(
        p_client_cmd=0.0, compact_at_commit=False, compact_every=16
    )
    kcfg = _with_service_bug(
        KvConfig(p_get=args.p_get, p_put=args.p_put,
                 p_follow_hint=args.p_follow_hint,
                 retry_wait=args.retry_wait),
        args.service_bug,
    )

    fn = make_kv_fuzz_fn(cfg, kcfg, args.clusters, args.ticks,
                         mesh=_mesh(args))
    return _finish_fuzz(args, fn, kv_report)


def cmd_ctrler_fuzz(args):
    from madraft_tpu.tpusim.ctrler import (
        CtrlerConfig,
        ctrler_report,
        make_ctrler_fuzz_fn,
    )

    cfg = _sim_config(args).replace(
        p_client_cmd=0.0, compact_at_commit=False, log_cap=32, compact_every=8
    )
    kcfg = _with_service_bug(
        CtrlerConfig(p_query=args.p_query, p_move=args.p_move),
        args.service_bug,
    )

    fn = make_ctrler_fuzz_fn(cfg, kcfg, args.clusters, args.ticks,
                             mesh=_mesh(args))
    return _finish_fuzz(args, fn, ctrler_report)


def cmd_shardkv_fuzz(args):
    from madraft_tpu.tpusim import SimConfig
    from madraft_tpu.tpusim.shardkv import (
        ShardKvConfig,
        make_shardkv_fuzz_fn,
        shardkv_report,
    )

    cfg = SimConfig(
        n_nodes=args.nodes, p_client_cmd=0.0, compact_at_commit=False,
        log_cap=64, compact_every=16,
        loss_prob=0.1 if args.storm else 0.05,
        p_crash=0.01 if args.storm else 0.0,
        p_restart=0.2, max_dead=1 if args.storm else 0,
        bug=args.bug,
        # this verb builds its SimConfig from scratch (it owns the fault
        # shape), so the metrics flag must be carried explicitly or the
        # shardkv clerk instrumentation is unreachable from the CLI
        metrics=getattr(args, "metrics", False),
    )

    # mode prerequisites BEFORE config construction — ShardKvConfig's own
    # __post_init__ validation would otherwise surface as a raw traceback
    if args.service_bug == "stale_ctrler_read" and not args.live_ctrler:
        raise SystemExit(
            "--service-bug stale_ctrler_read needs --live-ctrler: the bug "
            "lives in the query path to the on-device replicated controller"
        )
    if args.service_bug == "rotate_tiebreak" and not args.computed_ctrler:
        raise SystemExit(
            "--service-bug rotate_tiebreak needs --computed-ctrler: the bug "
            "rotates each controller replica's rebalance order, which only "
            "exists when config content is computed on-device"
        )
    kcfg = _with_service_bug(
        ShardKvConfig(p_get=args.p_get, p_put=args.p_put,
                      live_ctrler=args.live_ctrler,
                      computed_ctrler=args.computed_ctrler),
        args.service_bug,
    )

    fn = make_shardkv_fuzz_fn(cfg, kcfg, args.clusters, args.ticks,
                              mesh=_mesh(args))
    return _finish_fuzz(args, fn, shardkv_report)


def cmd_sweep(args):
    """Fault-parameter grid in ONE compiled program (engine.make_sweep_fn):
    the cartesian product of --loss x --crash x --repartition tiles across
    the cluster batch; per-cell safety AND liveness are reported. The
    reference's analogue is a compile-time test matrix, one process per
    cell."""
    import itertools

    import jax
    import jax.numpy as jnp

    from madraft_tpu.tpusim.engine import make_sweep_fn, report

    cfg = _sim_config(args)
    axes = {
        "loss_prob": [float(x) for x in args.loss.split(",")],
        "p_crash": [float(x) for x in args.crash.split(",")],
        "p_repartition": [float(x) for x in args.repartition.split(",")],
    }
    combos = list(itertools.product(*axes.values()))
    per = args.clusters // len(combos)
    if per == 0:
        raise SystemExit(
            f"--clusters {args.clusters} < {len(combos)} grid cells"
        )
    n = per * len(combos)
    mesh = None
    if args.mesh:
        import numpy as np

        devs = np.array(jax.devices())
        # validate on the TRUNCATED batch n (args.clusters rounds down to a
        # multiple of the cell count), not on the requested cluster count
        if n % len(devs):
            raise SystemExit(
                f"sweep batch {n} ({len(combos)} cells x {per}) must divide "
                f"evenly over {len(devs)} devices — pick --clusters as a "
                f"multiple of {len(combos) * len(devs)}"
            )
        mesh = jax.sharding.Mesh(devs, ("clusters",))
    if any(c[1] > 0 for c in combos) and cfg.max_dead == 0:
        # crash cells are inert without a dead-node budget + restarts
        cfg = cfg.replace(max_dead=2, p_restart=max(cfg.p_restart, 0.2))
    if any(c[2] > 0 for c in combos) and cfg.p_heal == 0.0:
        cfg = cfg.replace(p_heal=0.05)

    def tile(i):
        return jnp.repeat(
            jnp.asarray([c[i] for c in combos], jnp.float32), per,
            total_repeat_length=n,
        )

    kn = cfg.knobs()._replace(
        **{name: tile(i) for i, name in enumerate(axes)}
    )
    fn = make_sweep_fn(cfg, kn, n, args.ticks, mesh=mesh)

    def run():
        return report(jax.block_until_ready(fn(args.seed)))

    from madraft_tpu.tpusim.engine import run_telemetry

    rep, tele = run_telemetry(fn, report, args.seed, n * args.ticks,
                              n_lanes=n)
    extra, det_failed = _det_check(args, rep, run)
    extra["telemetry"] = tele
    cells = []
    for i, c in enumerate(combos):
        sl = slice(i * per, (i + 1) * per)
        cells.append({
            "loss": c[0], "crash": c[1], "repartition": c[2],
            "clusters": per,
            "violating": int((rep.violations[sl] != 0).sum()),
            "live": int((rep.committed[sl] > 0).sum()),
            "committed_mean": round(float(rep.committed[sl].mean()), 1),
        })
    print(json.dumps({
        "violating": int(rep.n_violating),
        # n rounds --clusters DOWN to a multiple of the cell count — surface
        # it so coverage accounting never silently over-reads
        "clusters_run": n,
        # which knob layout ran: "uniform" (small grid -> per-cell fast
        # programs) or "per_cluster" (one heterogeneous-knob program)
        "dispatch": getattr(fn, "dispatch", "per_cluster"),
        "cells": cells,
        "seed": args.seed,
        **extra,
    }))
    return 1 if (rep.n_violating or det_failed) else 0


def _state_schema(cfg, knobs, ticks: int) -> dict:
    """The trace/replay artifact schema stamp (MIGRATION.md "State layout"):
    which packed-state schema version this build writes, and which layout
    the run actually carried (the engine's one layout rule). Called only
    after the replay succeeded, so the knobs are known-valid."""
    from madraft_tpu.tpusim.engine import resolve_knobs
    from madraft_tpu.tpusim.state import (
        STATE_SCHEMA_VERSION,
        packed_layout_reason,
    )

    packed = packed_layout_reason(cfg, resolve_knobs(cfg, knobs), ticks) is None
    return {
        "state_schema_version": STATE_SCHEMA_VERSION,
        "state_layout": "packed" if packed else "wide",
    }


def cmd_replay(args):
    import numpy as np

    from madraft_tpu.tpusim.config import violation_names
    from madraft_tpu.tpusim.engine import replay_cluster

    cfg = _sim_config(args)
    knobs = _knobs_json("replay", args.knobs_json)
    st = _replay_or_usage_error(
        "replay", replay_cluster, cfg, args.seed, args.cluster,
        args.ticks, knobs=knobs)
    print(json.dumps({
        "seed": args.seed,
        "cluster": args.cluster,
        **_state_schema(cfg, knobs, args.ticks),
        "violations": int(st.violations),
        "violation_names": violation_names(int(st.violations)),
        "first_violation_tick": int(st.first_violation_tick),
        "committed": int(st.shadow_len),
        "terms": np.asarray(st.term).tolist(),
    }))
    return 1 if int(st.violations) else 0


def _explain_heartbeat(args) -> int:
    """`explain --heartbeat` (ISSUE 17): render a pool heartbeat stream as
    a Perfetto host timeline — per-generation chunk/harvest/emit spans plus
    counter tracks — instead of replaying a cluster. Pure host-side (no
    backend, no compiled programs), same --out/--format conventions as the
    cluster mode."""
    from madraft_tpu.tpusim.telemetry import read_heartbeat, read_manifest
    from madraft_tpu.tpusim.trace import chrome_pool_timeline

    def usage_error(msg):
        print(f"explain: {msg}", file=sys.stderr)
        raise SystemExit(2)

    if args.format != "chrome":
        usage_error("--heartbeat renders a host timeline: add "
                    "--format chrome")
    try:
        with open(args.heartbeat) as f:
            rows = read_heartbeat(f)
    except OSError as e:
        usage_error(str(e))
    if not rows:
        usage_error(f"no heartbeat rows in {args.heartbeat}")
    manifest = read_manifest(args.heartbeat)
    doc = chrome_pool_timeline(
        rows, label=f"madtpu pool heartbeat {args.heartbeat}",
        manifest=manifest,
    )
    text = json.dumps(doc)
    header = {
        "heartbeat": args.heartbeat,
        "generations": len(rows),
        "trace_events": len(doc["traceEvents"]),
    }
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        header["trace_file"] = args.out
        print(json.dumps(header))
    else:
        print(text)
    return 0


def cmd_explain(args):
    """Flight-recorder replay of ONE cluster: decode the per-tick trace into
    a structured event timeline (JSONL around the first violation) or a
    Perfetto-loadable chrome trace; or, with --heartbeat, a host-timeline
    render of a pool's telemetry stream. A debugging tool, not a checker:
    exit 0 whenever the replay ran, violations or not."""
    if args.heartbeat:
        return _explain_heartbeat(args)
    if args.cluster is None:
        print("explain: --cluster is required (or --heartbeat PATH for "
              "the pool host timeline)", file=sys.stderr)
        raise SystemExit(2)
    from madraft_tpu.tpusim.config import violation_names
    from madraft_tpu.tpusim.trace import (
        chrome_trace,
        decode_events,
        events_in_window,
        replay_cluster_traced,
    )

    cfg = _sim_config(args)
    knobs = _knobs_json("explain", args.knobs_json)
    final, rec = _replay_or_usage_error(
        "explain", replay_cluster_traced, cfg, args.seed, args.cluster,
        args.ticks, knobs=knobs)
    events = decode_events(rec)
    viol = int(final.violations)
    fvt = int(final.first_violation_tick)
    header = {
        "seed": args.seed,
        "cluster": args.cluster,
        "ticks": args.ticks,
        **_state_schema(cfg, knobs, args.ticks),
        "violations": viol,
        "violation_names": violation_names(viol),
        "first_violation_tick": fvt,
        "committed": int(final.shadow_len),
        "events_total": len(events),
    }
    if args.format == "chrome":
        doc = chrome_trace(
            rec, cfg.ms_per_tick, events,
            label=f"madtpu cluster {args.cluster} seed {args.seed}",
        )
        text = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
            header["trace_file"] = args.out
            header["trace_events"] = len(doc["traceEvents"])
            print(json.dumps(header))
        else:
            print(text)
        return 0
    shown = events_in_window(events, fvt if fvt >= 0 else None, args.window)
    header["window"] = args.window
    header["events_shown"] = len(shown)
    print(json.dumps(header))
    for e in shown:
        print(json.dumps(e))
    return 0


class _StatsMerge:
    """Everything `stats` pulls out of the input streams (ISSUE 10 + 12):
    merged e2e histogram, event counters, per-source-file counts (the
    exit-2 UX: name WHICH inputs carried nothing), phase histograms merged
    BY NAME, the per-key/per-client axes, and the global worst op."""

    def __init__(self, hist_buckets: int, n_events: int):
        import numpy as np

        self.hist = np.zeros(hist_buckets, np.int64)
        self.events = np.zeros(n_events, np.int64)
        self.seen = 0
        self.seen_per_stream: list = []
        self.phases: dict = {}    # name -> (hist row, ticks_total)
        self.by_key: dict = {}    # key -> hist row
        self.by_client: dict = {}  # client -> hist row
        self.worst = None
        # last heartbeat row per stream (ISSUE 17; None for non-heartbeat
        # streams) — the live-pool progress block of the render
        self.live_per_stream: list = []
        self.paths: list = []


def _merge_axis(table: dict, key, hist_row) -> None:
    import numpy as np

    row = np.asarray(hist_row, np.int64)
    if key in table:
        table[key] = table[key] + row
    else:
        table[key] = row


def _collect_stats(streams) -> _StatsMerge:
    """Pull every histogram/counter the metrics plane ever writes out of
    report JSON streams (one list of lines per input file): fuzz/sweep
    reports ({"latency": {...}, "events": {...}}), pool summaries (same
    keys), and pool JSONL rows ({"latency_hist": [...], "events": {...}}).
    Everything merges by plain addition over the fixed bucket layout;
    phase rows and the by_key/by_client axes merge BY NAME/id, so layers
    with different phase sets (shardkv's migration row) and different key
    alphabets coexist; worst ops merge by the deterministic max rule.

    A pool stream carries BOTH per-row histograms and a summary that
    already merged them (plus the in-flight lanes' rows) — counting both
    would double every op. The summary-wins rule is PER STREAM: within one
    file, a summary-level "latency" dict suppresses that file's bare
    per-row columns; a rows-only file (e.g. a grep of violating rows from
    another run) still merges in full next to it."""
    import numpy as np

    from madraft_tpu.tpusim.config import HIST_BUCKETS, METRIC_EVENTS
    from madraft_tpu.tpusim.metrics import merge_worst

    m = _StatsMerge(HIST_BUCKETS, len(METRIC_EVENTS))
    for lines in streams:
        docs = []
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                doc = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if isinstance(doc, dict):
                docs.append(doc)
        use_rows = not any(
            isinstance(d.get("latency"), dict) and d["latency"].get("hist")
            for d in docs
        )
        stream_seen = 0
        last_hb = None
        for doc in docs:
            if doc.get("hb") == 1:
                # heartbeat row (ISSUE 17): window histograms sum across a
                # stream's rows to exactly the run-cumulative histogram
                # (fixed buckets, pure addition), so merging every hist_w
                # here equals merging the finished summary. Window phase
                # ticks merge by name; windows carry no per-phase
                # histograms, so those columns stay zero-hist like
                # rows-only pool inputs carry ticks_total 0.
                last_hb = doc
                det = doc.get("det") or {}
                hlat = det.get("latency")
                m.seen += 1
                stream_seen += 1
                if isinstance(hlat, dict) and hlat.get("hist_w") and \
                        len(hlat["hist_w"]) == HIST_BUCKETS:
                    m.hist += np.asarray(hlat["hist_w"], np.int64)
                    for name, t in (hlat.get("phase_ticks_w") or {}).items():
                        old_h, old_t = m.phases.get(
                            name, (np.zeros(HIST_BUCKETS, np.int64), 0)
                        )
                        m.phases[name] = (old_h, old_t + int(t))
                continue
            lat = doc.get("latency")
            row_hist = None
            row_phases = None
            from_summary = False
            if isinstance(lat, dict) and lat.get("hist"):
                row_hist = lat["hist"]
                from_summary = True
                if isinstance(lat.get("phases"), dict):
                    row_phases = {
                        name: (d.get("hist"), d.get("ticks_total", 0))
                        for name, d in lat["phases"].items()
                        if isinstance(d, dict)
                    }
            elif use_rows and doc.get("latency_hist"):
                row_hist = doc["latency_hist"]
                if isinstance(doc.get("latency_phases"), dict):
                    # pool rows carry the raw phase rows only (no exact
                    # tick totals); the merged table shows ticks_total 0
                    # for rows-only inputs rather than estimating it
                    row_phases = {
                        name: (h, 0)
                        for name, h in doc["latency_phases"].items()
                    }
            # an events-ONLY report (the ctrler layer counts events but
            # carries no latency stamps) still merges — but a pool row
            # suppressed by its own stream's summary contributes neither
            ev_only = (
                "latency_hist" not in doc
                and not isinstance(lat, dict)
                and isinstance(doc.get("events"), dict)
            )
            if row_hist is None and not ev_only:
                continue
            m.seen += 1
            stream_seen += 1
            if row_hist is not None and len(row_hist) == HIST_BUCKETS:
                m.hist += np.asarray(row_hist, np.int64)
            if row_phases:
                for name, (h, ticks) in row_phases.items():
                    if h is None or len(h) != HIST_BUCKETS:
                        continue
                    old_h, old_t = m.phases.get(
                        name, (np.zeros(HIST_BUCKETS, np.int64), 0)
                    )
                    m.phases[name] = (
                        old_h + np.asarray(h, np.int64), old_t + int(ticks)
                    )
            if from_summary or use_rows:
                for src, table in (("by_key", m.by_key),
                                   ("by_client", m.by_client)):
                    ax = lat.get(src) if isinstance(lat, dict) else None
                    if isinstance(ax, dict):
                        for k, d in ax.items():
                            if isinstance(d, dict) and d.get("hist"):
                                _merge_axis(table, str(k), d["hist"])
                w = doc.get("worst_op")
                if isinstance(w, dict):
                    # a pool row's id rides the row, not the worst dict —
                    # pass it so the deterministic tie-break sees real ids
                    m.worst = merge_worst(m.worst, w,
                                          b_id=doc.get("cluster_id"))
            row_ev = doc.get("events")
            if isinstance(row_ev, dict):
                for i, name in enumerate(METRIC_EVENTS):
                    m.events[i] += int(row_ev.get(name, 0))
        m.seen_per_stream.append(stream_seen)
        m.live_per_stream.append(last_hb)
    return m


def _stats_once(args, paths) -> int:
    """One read-merge-render pass over ``paths`` (the whole historic
    `stats` body; `--follow` re-runs it per poll, which is what makes the
    final followed render EQUAL to the one-shot render by construction)."""
    from madraft_tpu.tpusim.config import METRIC_EVENTS
    from madraft_tpu.tpusim.metrics import (
        latency_summary,
        render_histogram,
    )

    streams = []
    for path in paths:
        if path == "-":
            streams.append(sys.stdin.read().splitlines())
        else:
            try:
                with open(path) as f:
                    streams.append(f.read().splitlines())
            except OSError as e:
                print(f"stats: {e}", file=sys.stderr)
                raise SystemExit(2)
    m = _collect_stats(streams)
    m.paths = list(paths)
    empty = [p for p, n in zip(paths, m.seen_per_stream) if n == 0]
    if not m.seen:
        # name the specific inputs so a glob with one stale metrics-off
        # file reads differently from an entirely metrics-free run
        which = ", ".join("stdin" if p == "-" else p for p in empty)
        print(f"stats: no metrics found in: {which} — was the run made "
              "with --metrics?", file=sys.stderr)
        return 2
    if empty:
        # mixed input: render what was found, but say which files carried
        # no metrics blocks (a silently-skipped file reads as merged)
        which = ", ".join("stdin" if p == "-" else p for p in empty)
        print(f"stats: warning: no metrics blocks in: {which} (merged the "
              f"other {m.seen} source(s))", file=sys.stderr)
    lat = latency_summary(m.hist)
    try:
        _print_stats(args, m, lat, METRIC_EVENTS, render_histogram)
    except BrokenPipeError:  # e.g. `stats ... | head` — not an error
        pass
    return 0


def _follow_stats(args, paths):
    """`stats --follow` (ISSUE 17): poll the run manifests next to the
    inputs and re-render in place until every run is terminal. Returns the
    final render's exit code, or None to degrade to one-shot — inputs with
    no live manifest (finished artifacts, plain report files, stdin) get
    exactly the historic render, which is also what makes the followed
    final render of a finished run provably equal to one-shot `stats`."""
    import time as time_mod

    from madraft_tpu.tpusim.telemetry import (
        is_terminal,
        manifest_status,
        read_manifest,
    )

    real = [p for p in paths if p != "-"]
    mans = {p: read_manifest(p) for p in real}
    live = [p for p, d in mans.items()
            if d is not None and not is_terminal(manifest_status(d))]
    if not live:
        if not any(d is not None for d in mans.values()):
            print("stats: no run manifest next to the inputs — one-shot "
                  "render", file=sys.stderr)
        return None
    while True:
        statuses = {p: manifest_status(read_manifest(p)) for p in real}
        still_live = [p for p in live
                      if not is_terminal(statuses.get(p, "unknown"))]
        if sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home: in-place
        rc = _stats_once(args, paths)
        for p in live:
            print(f"stats: {p}: {statuses.get(p, 'unknown')}",
                  file=sys.stderr)
        if not still_live:
            return rc
        time_mod.sleep(args.interval)


def cmd_stats(args):
    """Render the metrics plane of any report artifact (ISSUE 10): feed it
    a fuzz/sweep report, a pool summary + JSONL stream, a live heartbeat
    stream (ISSUE 17), or any mix of files; it merges every
    histogram/counter row it finds (fixed buckets sum across sources) and
    prints the latency distribution, p50/p99, and the liveness-counter
    table. A read-only renderer: exit 0 when metrics were found, exit 2
    when the input carries none (e.g. a metrics-off report — say so rather
    than print an empty table). --follow tails live heartbeat inputs via
    their manifests and re-renders until the runs finish."""
    paths = args.inputs or ["-"]
    if getattr(args, "follow", False):
        rc = _follow_stats(args, paths)
        if rc is not None:
            return rc
    return _stats_once(args, paths)


def cmd_lint(args):
    """Static analysis over every cached compiled program (ISSUE 15): trace
    each registry entry to its closed jaxpr (never executing anything) and
    run the lane-isolation / PRNG-discipline / packed-width /
    zero-when-off passes. Exit 0 when every traced program is clean, 1 on
    findings, 2 on usage errors (unknown --program, unwritable --json) —
    the PR-6 CLI convention."""
    from madraft_tpu.tpusim import lint as lint_mod

    specs = (lint_mod.defect_registry() if args.selftest
             else lint_mod.registry())
    if args.list:
        for s in specs:
            legs = f" golden={s.golden_leg}" if s.golden_leg else ""
            print(f"{s.name}  [{s.family}] lanes={s.n_lanes}{legs}")
        return 0
    if args.program:
        if not any(args.program in s.name for s in specs):
            print(f"lint: no program matches {args.program!r} "
                  f"(see lint --list)", file=sys.stderr)
            raise SystemExit(2)
    report = lint_mod.run_lint(specs, program=args.program or None)
    for row in report["programs"]:
        status = (f"SKIP ({row['skipped']})" if row["skipped"]
                  else "ok")
        allowed = (" allowed=" + ",".join(
            f"{k}x{v}" for k, v in sorted(row["allowed"].items()))
            if row["allowed"] else "")
        print(f"{row['name']:<28} eqns={row['eqns']:>6} "
              f"draws={row['draws']:>3}{allowed}  {status}")
    for f in report["findings"]:
        print(f"FINDING {f['program']}: [{f['pass']}/{f['rule']}] "
              f"{f['detail']}")
    s = report["summary"]
    print(f"lint: {s['traced']}/{s['programs']} programs traced "
          f"({s['skipped']} skipped), {s['findings']} findings")
    if args.json:
        try:
            with open(args.json, "w") as fh:
                json.dump(report, fh, indent=1)
        except OSError as e:
            print(f"lint: {e}", file=sys.stderr)
            raise SystemExit(2)
    return 1 if report["findings"] else 0


def _top_axis(table: dict, top: int) -> list:
    """The top-N rows of a per-key/per-client axis, worst tail first
    (p99 desc, then ops desc) — the hot-key-skew readout."""
    from madraft_tpu.tpusim.metrics import latency_summary

    rows = [(k, latency_summary(h)) for k, h in table.items()]
    rows.sort(key=lambda kv: (-(kv[1]["p99_ticks"] or 0), -kv[1]["ops"]))
    return rows[:top]


def _print_stats(args, m, lat, METRIC_EVENTS, render_histogram):
    from madraft_tpu.tpusim.metrics import latency_summary

    print(f"sources merged: {m.seen}")
    for p, hbr in zip(m.paths, m.live_per_stream):
        if hbr is None:
            continue
        # heartbeat progress block (ISSUE 17): the stream's newest row —
        # deterministic counters first, then the explicitly wall-clock part
        det, t = hbr.get("det", {}), hbr.get("t", {})
        bits = [f"gen {hbr.get('gen')}"]
        if hbr.get("lane_ticks") is not None:
            bits.append(f"lane_ticks {hbr['lane_ticks']}")
        if det.get("retired") is not None:
            bits.append(f"retired {det['retired']} "
                        f"({det.get('violating', 0)} violating)")
        if det.get("new_fps") is not None:
            bits.append(f"fingerprints {det['new_fps']}")
        if t.get("budget_frac") is not None:
            bits.append(f"{100.0 * t['budget_frac']:.0f}% of budget")
        if t.get("wall_s") is not None:
            bits.append(f"wall {t['wall_s']}s")
        state = "final" if hbr.get("final") else "live"
        name = "stdin" if p == "-" else p
        print(f"pool {name} [{state}]: " + " · ".join(bits))
    print(f"latency: ops={lat['ops']} p50={lat['p50_ticks']} "
          f"p99={lat['p99_ticks']} (ticks; log-spaced buckets, quantile = "
          f"bucket upper edge)")
    for line in render_histogram(m.hist):
        print(line)
    if m.phases:
        # the attribution table (ISSUE 12): where the tail actually lives.
        # share = this phase's exact tick total over all phases' (0 when
        # the inputs carried only raw rows, which lack tick totals).
        total_ticks = sum(t for _, t in m.phases.values())
        print("phases (sum of phase durations == e2e latency, per op):")
        width = max(len(n) for n in m.phases)
        for name, (h, ticks) in m.phases.items():
            d = latency_summary(h)
            share = (f"  {100.0 * ticks / total_ticks:5.1f}% of ticks"
                     if total_ticks else "")
            print(f"  {name:<{width}}  ops={d['ops']:>8}  "
                  f"p50={str(d['p50_ticks']):>6} p99={str(d['p99_ticks']):>6}"
                  f"{share}")
    for flag, label, table in (("by_key", "key", m.by_key),
                               ("by_client", "client", m.by_client)):
        if getattr(args, flag, False) and table:
            print(f"top {label}s by p99:")
            for k, d in _top_axis(table, args.top):
                print(f"  {label} {k:>4}  ops={d['ops']:>8}  "
                      f"p50={str(d['p50_ticks']):>6} "
                      f"p99={str(d['p99_ticks']):>6}")
    if m.worst is not None:
        ph = ", ".join(f"{k}={v}" for k, v in m.worst["phases"].items()
                       if v)
        print(f"worst op: {m.worst['latency_ticks']} ticks "
              f"(submit tick {m.worst['submit_tick']}, "
              f"key {m.worst['key']}, client {m.worst['client']}"
              + (f", cluster {m.worst['cluster_id']}"
                 if "cluster_id" in m.worst else "")
              + f") — {ph or 'all phases 0'}")
    if m.events.any():
        print("events:")
        width = max(len(n) for n in METRIC_EVENTS)
        for i, name in enumerate(METRIC_EVENTS):
            print(f"  {name:<{width}}  {int(m.events[i])}")
    if args.json:
        doc = {
            "sources": m.seen,
            "latency": lat,
            "events": {n: int(m.events[i])
                       for i, n in enumerate(METRIC_EVENTS)},
        }
        if m.phases:
            doc["latency"]["phases"] = {
                name: {**latency_summary(h), "ticks_total": int(t)}
                for name, (h, t) in m.phases.items()
            }
        if m.by_key:
            doc["latency"]["by_key"] = {
                k: latency_summary(h) for k, h in m.by_key.items()
            }
        if m.by_client:
            doc["latency"]["by_client"] = {
                k: latency_summary(h) for k, h in m.by_client.items()
            }
        if m.worst is not None:
            doc["worst_op"] = m.worst
        print(json.dumps(doc))


def cmd_bridge(args):
    from madraft_tpu import bridge
    from madraft_tpu.tpusim.config import violation_names

    cfg = _sim_config(args)
    sched = bridge.extract_schedule(cfg, seed=args.seed,
                                    cluster_id=args.cluster, n_ticks=args.ticks)
    cpp = bridge.replay_on_simcore(sched)
    match = bridge.classes_match(sched.violations, cpp)
    out = {
        "tpu_violations": sched.violations,
        "tpu_violation_names": violation_names(sched.violations),
        "cpp_report": cpp,
        "classes_match": match,
    }
    if sched.violations and not match:
        # boolean mismatch -> localized lead: replay both sides with the
        # flight recorder on and report the first divergence tick
        out["divergence"] = bridge.localize_divergence(
            cfg, sched, args.seed, args.cluster, args.ticks
        )
    print(json.dumps(out))
    # failure = a TPU-found violation the C++ replay could NOT reproduce
    return 1 if (sched.violations and not match) else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m madraft_tpu",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, clusters):
        sp.add_argument("--platform", default=None,
                        help="force a JAX backend (e.g. cpu) — by default "
                             "the attached accelerator is used")
        sp.add_argument("--seed", type=int, default=12345)
        sp.add_argument("--nodes", type=int, default=5)
        sp.add_argument("--clusters", type=int, default=clusters)
        sp.add_argument("--ticks", type=int, default=512)
        sp.add_argument("--storm", action="store_true",
                        help="full fault storm (loss+crash+partitions)")
        sp.add_argument("--majority-override", type=int, default=0,
                        help="deliberately broken quorum (oracle demo)")
        sp.add_argument("--fsync-every", type=int, default=0,
                        help="background fsync cadence in ticks (the lossy-"
                             "persistence axis; 0 = keep the profile/"
                             "default, 1 = fsync every tick = the perfect-"
                             "persistence model)")
        sp.add_argument("--lose-unsynced", type=float, default=-1.0,
                        help="probability a crash drops the un-fsynced "
                             "suffix (rolls log/term/vote back to the fsync "
                             "watermark; negative = keep profile/default)")
        sp.add_argument("--bug", default="",
                        help="raft-layer planted bug (config.py RAFT_BUGS: "
                             "commit_any_term | grant_any_vote | "
                             "forget_voted_for | no_truncate | "
                             "ack_before_fsync)")
        sp.add_argument("--metrics", action="store_true",
                        help="on-device metrics plane (README 'Metrics'): "
                             "per-lane submit->ack latency histograms + "
                             "liveness-event counters folded inside the "
                             "compiled step; reports gain latency p50/p99 "
                             "and event columns (separate cached programs "
                             "— the metrics-off hot path is untouched)")
        sp.add_argument("--profile", default="",
                        help="named scenario from config.storm_profiles() — "
                             "the planted-bug storms (storm | fig8 | revote "
                             "| durability) plus the ISSUE-19 gray-failure "
                             "game days (limp | skew_storm | fsync_stall | "
                             "rolling_wave | hot_key_openloop | gray_storm). "
                             "A profile owns topology and fault knobs "
                             "(overrides --nodes and --storm); see "
                             "--list-profiles for the full table with each "
                             "profile's liveness floor and p99 ceiling "
                             "(an unknown name exits 2 listing the "
                             "available ones)")
        sp.add_argument("--list-profiles", action="store_true",
                        help="print the scenario registry — every profile's "
                             "knob deltas, demonstrated scale, liveness "
                             "floor, p99 ceiling, and C++-bridge support — "
                             "and exit 0 (host-side: no backend init)")

    def fuzz_common(sp, clusters):
        common(sp, clusters)
        sp.add_argument("--mesh", action="store_true",
                        help="shard the cluster batch over ALL attached "
                             "devices (jax.sharding.Mesh data parallelism)")
        sp.add_argument("--check-deterministic", action="store_true",
                        help="run twice, demand a bit-identical report "
                             "(MADSIM_TEST_CHECK_DETERMINISTIC analogue; "
                             "also enabled by the env var "
                             "MADTPU_TEST_CHECK_DETERMINISTIC)")

    def service_common(sp, clusters):
        fuzz_common(sp, clusters)
        # help stays static so --help never pays the jax import the cmd_*
        # handlers defer; the valid names are derived from the layer's
        # config dataclass at use time (_service_bugs) and an unknown name
        # errors with the full list
        sp.add_argument(
            "--service-bug", default="",
            help="plant one of this layer's SERVICE bugs (README "
                 "planted-bug library; an unknown name lists the valid set)",
        )

    sp = sub.add_parser("fuzz", help="raw-raft batched fuzz")
    fuzz_common(sp, 4096)
    sp.set_defaults(fn=cmd_fuzz)

    sp = sub.add_parser(
        "pool",
        help="continuous fuzzing pool: retire violated/horizon-reached "
             "clusters on device and refill their lanes with fresh ones "
             "under new global ids (--ticks is the per-cluster horizon); "
             "streams retired-cluster reports as JSONL + a summary line",
    )
    common(sp, 4096)
    sp.add_argument("--mesh", action="store_true",
                    help="shard the lane batch over ALL attached devices "
                         "(shorthand for --devices <device count>)")
    sp.add_argument("--devices", type=int, default=0,
                    help="pod-scale sharded pool: shard the lanes over the "
                         "FIRST N attached devices under the lane-"
                         "partitioned global-id scheme (lane l's "
                         "generation-g cluster owns id g*lanes + l), so "
                         "refill bookkeeping stays per-shard and the "
                         "retired-report multiset is identical at any "
                         "device count; N must divide --clusters "
                         "(0 = the single-device monotone-id pool)")
    sp.add_argument("--chunk-ticks", type=int, default=0,
                    help="ticks per compiled chunk between harvests (0 = "
                         "the horizon split into equal chunks of at most "
                         "256 ticks, so lanes retire exactly at the "
                         "horizon); retirement is detected at chunk "
                         "boundaries, so a retired cluster's ticks_run is "
                         "a multiple of this")
    sp.add_argument("--budget-ticks", type=int, default=0,
                    help="stop once every lane has dispatched this many "
                         "ticks, rounded up to whole chunks (0 = unset; "
                         "with --budget-seconds also unset, one horizon)")
    sp.add_argument("--budget-seconds", type=float, default=0.0,
                    help="stop at the first harvest past this wall-clock "
                         "budget (0 = unset)")
    sp.add_argument("--emit", default="all", choices=["all", "violations"],
                    help="stream every retired-cluster report, or only "
                         "violating ones")
    sp.add_argument("--coverage", action="store_true",
                    help="coverage-guided corpus scheduling (README "
                         "'Coverage-guided fuzzing'): every tick each "
                         "lane's abstract-state fingerprint updates an "
                         "on-device seen-set, and retiring lanes that "
                         "discovered new fingerprints respawn with mutated "
                         "storm knobs (the JSONL rows gain "
                         "new_fingerprints/refill/knobs columns; the "
                         "summary a coverage dict)")
    sp.add_argument("--coverage-bits", type=int, default=None,
                    help="seen-set bitmap size in bits (power of two, "
                         "default 65536); small enough to saturate = "
                         "coverage plateaus read as saturation, not "
                         "exhaustion")
    sp.add_argument("--coverage-random", action="store_true",
                    help="with --coverage: count coverage but refill "
                         "uniformly at the base knobs (measurement-only "
                         "mode — the random baseline of the A/B)")
    sp.add_argument("--heartbeat", default="",
                    help="live-telemetry stream (README 'Live telemetry'): "
                         "write one JSONL row per harvest generation to "
                         "PATH (deterministic counters + timing columns) "
                         "and keep PATH.manifest.json atomically updated "
                         "so a watcher can attach (`stats --follow PATH`) "
                         "and tell crashed from running from done")
    sp.add_argument("--digest-every", type=int, default=0,
                    help="print a one-line progress digest of every Nth "
                         "harvest generation on stderr (gen/budget%%/"
                         "viol-per-s/p99); stdout stays clean JSONL "
                         "(0 = off)")
    sp.set_defaults(fn=cmd_pool)

    sp = sub.add_parser("kv-fuzz", help="KV service fuzz (Lab 3)")
    service_common(sp, 512)
    sp.add_argument("--p-get", type=float, default=0.3)
    sp.add_argument("--p-put", type=float, default=0.2)
    sp.add_argument("--p-follow-hint", type=float, default=0.0,
                    help="prob a clerk targets its believed leader (the "
                         "NotLeader{hint} ClerkCore model) instead of a "
                         "random node; 0 = historic random routing")
    sp.add_argument("--retry-wait", type=int, default=0,
                    help="ticks a clerk pauses after a submit landed at a "
                         "leader (the 500ms call-timeout pacing); needed "
                         "for meaningful hint-following runs")
    sp.set_defaults(fn=cmd_kv_fuzz)

    sp = sub.add_parser(
        "ctrler-fuzz", help="shard-controller config service (Lab 4A)"
    )
    service_common(sp, 512)
    sp.add_argument("--p-query", type=float, default=0.3)
    sp.add_argument("--p-move", type=float, default=0.1)
    sp.set_defaults(fn=cmd_ctrler_fuzz)

    sp = sub.add_parser("shardkv-fuzz", help="multi-group sharded KV (Lab 4B)")
    service_common(sp, 64)
    sp.add_argument("--p-get", type=float, default=0.3)
    sp.add_argument("--p-put", type=float, default=0.2)
    sp.add_argument("--live-ctrler", action="store_true",
                    help="configs ride an on-device replicated controller "
                         "raft cluster (announce/query protocol) instead of "
                         "the schedule tensor")
    sp.add_argument("--computed-ctrler", action="store_true",
                    help="the controller cluster's apply machine IS the 4A "
                         "state machine: membership flips ride its raft and "
                         "config content is computed by the shared 4A "
                         "rebalance (supersedes --live-ctrler)")
    sp.set_defaults(fn=cmd_shardkv_fuzz)

    sp = sub.add_parser(
        "sweep", help="fault-parameter grid in one program (per-cell report)"
    )
    fuzz_common(sp, 4096)
    sp.add_argument("--loss", default="0,0.1,0.3",
                    help="comma list of loss_prob values")
    sp.add_argument("--crash", default="0,0.02",
                    help="comma list of p_crash values")
    sp.add_argument("--repartition", default="0,0.05",
                    help="comma list of p_repartition values")
    sp.set_defaults(fn=cmd_sweep)

    sp = sub.add_parser("replay", help="re-run ONE cluster exactly")
    common(sp, 1)
    sp.add_argument("--cluster", type=int, required=True)
    sp.add_argument("--knobs-json", default="",
                    help="JSON object of dynamic-knob overrides (field -> "
                         "value) — paste a coverage-pool row's \"knobs\" "
                         "to replay a mutated lane bit-exactly")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser(
        "explain",
        help="flight-recorder replay of ONE cluster: decoded event timeline "
             "(JSONL) around the first violation, or a Perfetto export; "
             "with --heartbeat, a Perfetto host timeline of a pool run",
    )
    common(sp, 1)
    sp.add_argument("--cluster", type=int, default=None,
                    help="cluster id to replay (required unless "
                         "--heartbeat)")
    sp.add_argument("--heartbeat", default="",
                    help="render a pool heartbeat stream (pool --heartbeat "
                         "PATH) as a Perfetto host timeline instead of "
                         "replaying a cluster: chunk/harvest/emit spans "
                         "per generation + counter tracks (violations/s, "
                         "coverage, p99, device_wait); needs --format "
                         "chrome; runs with no accelerator")
    sp.add_argument("--window", type=int, default=60,
                    help="±ticks around first_violation_tick to print "
                         "(<= 0 = the full timeline; violation events are "
                         "always shown)")
    sp.add_argument("--format", default="jsonl",
                    choices=["jsonl", "chrome"],
                    help="jsonl = header line + one event per line; chrome "
                         "= Perfetto/chrome://tracing trace JSON (one track "
                         "per node: role spans + instant events)")
    sp.add_argument("--out", default="",
                    help="with --format chrome: write the trace JSON to "
                         "this file (a summary line goes to stdout) "
                         "instead of dumping it to stdout")
    sp.add_argument("--knobs-json", default="",
                    help="JSON object of dynamic-knob overrides — paste a "
                         "coverage-pool row's \"knobs\" so the timeline "
                         "decodes the mutated lane's actual execution")
    sp.set_defaults(fn=cmd_explain)

    sp = sub.add_parser(
        "bridge", help="export a cluster's fault schedule and replay on C++"
    )
    common(sp, 1)
    sp.add_argument("--cluster", type=int, required=True)
    sp.set_defaults(fn=cmd_bridge)

    sp = sub.add_parser(
        "stats",
        help="render the metrics plane of any report artifact: merges the "
             "latency histograms and event counters found in fuzz/sweep "
             "reports, pool summaries, and pool JSONL rows (files or "
             "stdin), prints the distribution + p50/p99 + counter table",
    )
    sp.add_argument("inputs", nargs="*", metavar="FILE",
                    help="report/JSONL files to merge ('-' or none = stdin)")
    sp.add_argument("--json", action="store_true",
                    help="additionally print the merged digest as one "
                         "machine-readable JSON line")
    sp.add_argument("--by-key", action="store_true", dest="by_key",
                    help="render the top-N per-key latency rows (worst "
                         "p99 first) from reports carrying the per-key "
                         "attribution axis (kv/shardkv --metrics)")
    sp.add_argument("--by-client", action="store_true", dest="by_client",
                    help="render the top-N per-client latency rows")
    sp.add_argument("--top", type=int, default=5,
                    help="N for --by-key/--by-client (default 5)")
    sp.add_argument("--follow", action="store_true",
                    help="tail live heartbeat inputs (pool --heartbeat / "
                         "the soak harness): poll each input's run "
                         "manifest and re-render in place until every run "
                         "is terminal; inputs with no live manifest "
                         "degrade to the one-shot render")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="poll period in seconds for --follow (default 2)")
    sp.set_defaults(fn=cmd_stats)

    sp = sub.add_parser(
        "lint",
        help="static analysis over every cached compiled program: trace "
             "each ProgramRegistry entry to its jaxpr (no execution) and "
             "run the lane-isolation / PRNG-discipline / packed-width / "
             "zero-when-off passes; exit 1 on findings",
    )
    sp.add_argument("--platform", default=None,
                    help="force a JAX backend (e.g. cpu)")
    sp.add_argument("--program", default="",
                    help="only lint programs whose name contains this "
                         "substring (an unknown name exits 2)")
    sp.add_argument("--json", default="",
                    help="additionally write the full machine-readable "
                         "report (schema in MIGRATION.md) to this file")
    sp.add_argument("--list", action="store_true",
                    help="list the registry's program names and exit")
    sp.add_argument("--selftest", action="store_true",
                    help="lint the planted-defect registry instead: each "
                         "pass must catch its deliberately-broken program "
                         "(expected exit 1 — the CI smoke that the "
                         "analyzer still bites)")
    sp.set_defaults(fn=cmd_lint)

    args = p.parse_args(argv)
    if getattr(args, "list_profiles", False):
        # the scenario registry is pure config (ISSUE 19) — print it and
        # exit 0 without touching any backend
        return cmd_list_profiles(args)
    prof = getattr(args, "profile", "")
    if prof:
        # dynamic validation (the registry is the source of truth, not an
        # argparse choices list): unknown names exit 2 per the PR-6
        # usage-error convention, listing what IS available
        from madraft_tpu.tpusim.config import storm_profiles

        names = list(storm_profiles())
        if prof not in names:
            print(
                f"madtpu: unknown --profile {prof!r}; available: "
                + " ".join(names), file=sys.stderr,
            )
            return 2
    if args.cmd == "stats" or (args.cmd == "explain"
                               and getattr(args, "heartbeat", "")):
        # pure host-side renderers (stats; explain over a heartbeat
        # stream): no compiled programs, no accelerator — skip backend
        # init entirely (a degraded tunnel must not block reading a
        # report file)
        return args.fn(args)
    # Must run before any backend init. Honors --platform > MADTPU_PLATFORM
    # > JAX_PLATFORMS (re-asserted via jax.config because the container's
    # startup hook force-registers the tunnel regardless of the env var),
    # and fails fast with an actionable message — instead of hanging
    # indefinitely inside PJRT init — when the tunnel is degraded.
    from madraft_tpu._platform import (
        enable_compilation_cache,
        require_backend_or_die,
    )

    # Persistent XLA compilation cache (same knobs as tests/conftest.py):
    # a cold CLI run reuses every program the test suite — or any earlier
    # run — already compiled, instead of recompiling it. MADTPU_CACHE_DIR
    # overrides the location ("0" disables).
    enable_compilation_cache()
    require_backend_or_die(args.platform)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
