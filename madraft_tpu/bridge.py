"""TPU <-> simcore differential bridge: schedule export + C++ replay.

The batched fuzzer reports a violating cluster as ``(seed, cluster_id)``
(kv.py / engine.py). This module closes the loop that the reference closes
with seed replay (/root/reference/README.md:42-55): re-run that ONE cluster
on host, record its fault schedule — the per-tick ``alive`` bitmask and
``adj`` adjacency matrix, i.e. exactly the crash/restart/partition decisions
the per-cluster PRNG made — and hand the schedule to the C++ raft-core
running on simcore (``cpp/tools/replay_main.cpp``). Schedules, not PRNG
streams, are the interchange format (SURVEY.md §7 "determinism across
backends"): the two backends draw from different generators, so equivalence
is class-level — the C++ online checkers must observe the same violation
CLASS the TPU oracles flagged.

Violation-class mapping (TPU bitmask -> C++ report fields):
  VIOLATION_DUAL_LEADER   -> dual_leader
  VIOLATION_LOG_MATCHING  -> commit_mismatch | apply_disorder
  VIOLATION_COMMIT_SHADOW -> commit_mismatch | apply_disorder
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import pathlib
import subprocess
import tempfile
from typing import Optional

import jax
import numpy as np

from madraft_tpu.tpusim.config import (
    SimConfig,
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
)
from madraft_tpu.tpusim.state import init_cluster
from madraft_tpu.tpusim.step import step_cluster

_REPO = pathlib.Path(__file__).resolve().parent.parent
DEFAULT_BINARY = _REPO / "build" / "madtpu_replay"


@dataclasses.dataclass
class Schedule:
    """One cluster's fault schedule plus the meta the C++ replayer needs."""

    n_nodes: int
    ms_per_tick: int
    n_ticks: int
    majority_override: int            # 0 = correct quorum
    seed: int                         # simcore PRNG seed for the replay
    # (tick, alive_bitmask) and (tick, adj row bitmasks) change events
    alive_events: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    adj_events: list[tuple[int, list[int]]] = dataclasses.field(default_factory=list)
    violations: int = 0               # TPU violation bitmask for this cluster
    first_violation_tick: int = -1

    def dumps(self) -> str:
        lines = [
            "# madtpu differential-replay schedule (bridge.py)",
            f"nodes {self.n_nodes}",
            f"ms_per_tick {self.ms_per_tick}",
            f"ticks {self.n_ticks}",
            f"majority_override {self.majority_override}",
            f"seed {self.seed}",
        ]
        events = [(t, "alive", f"{m:x}") for t, m in self.alive_events] + [
            (t, "adj", " ".join(f"{r:x}" for r in rows))
            for t, rows in self.adj_events
        ]
        for t, kind, payload in sorted(events, key=lambda e: e[0]):
            lines.append(f"ev {t} {kind} {payload}")
        return "\n".join(lines) + "\n"


def _bitmask(bits: np.ndarray) -> int:
    return int(sum(1 << i for i, b in enumerate(bits) if b))


def extract_schedule(
    cfg: SimConfig,
    seed: int,
    cluster_id: int,
    n_ticks: int,
    step_fn=None,
    init_fn=None,
) -> Schedule:
    """Re-run ONE cluster tick by tick and record its fault schedule.

    ``step_fn``/``init_fn`` default to the raw raft step; service-layer
    fuzzers (kv.py) can pass their own wrappers as long as the returned state
    exposes ``.alive``/``.adj``/``.violations`` under a ``raft`` attribute or
    directly. Exact per-cluster replay is cheap: one un-batched jit + n_ticks
    dispatches.
    """
    step_fn = step_fn or functools.partial(step_cluster, cfg)
    init_fn = init_fn or functools.partial(init_cluster, cfg)
    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)

    def raft_of(state):
        return state.raft if hasattr(state, "raft") else state

    # One compiled scan records the whole (alive, adj) timeline on device —
    # [T, n] + [T, n, n] bools are tiny; per-tick host dispatch is not.
    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = step_fn(carry, key)
            r = raft_of(nxt)
            return nxt, (r.alive, r.adj)

        final, (alives, adjs) = jax.lax.scan(
            body, init_fn(key), None, length=n_ticks
        )
        return final, alives, adjs

    final, alives, adjs = jax.block_until_ready(run(ckey))
    alives, adjs = np.asarray(alives), np.asarray(adjs)

    sched = Schedule(
        n_nodes=cfg.n_nodes,
        ms_per_tick=cfg.ms_per_tick,
        n_ticks=n_ticks,
        majority_override=cfg.majority_override or 0,
        seed=seed,
    )
    prev_alive = _bitmask(np.ones(cfg.n_nodes, bool))
    prev_adj = [_bitmask(np.ones(cfg.n_nodes, bool))] * cfg.n_nodes
    for t in range(1, n_ticks + 1):
        alive = _bitmask(alives[t - 1])
        adj = [_bitmask(row) for row in adjs[t - 1]]
        if alive != prev_alive:
            sched.alive_events.append((t, alive))
            prev_alive = alive
        if adj != prev_adj:
            sched.adj_events.append((t, adj))
            prev_adj = adj
    r = raft_of(final)
    sched.violations = int(r.violations)
    sched.first_violation_tick = int(r.first_violation_tick)
    return sched


def replay_on_simcore(
    schedule: Schedule,
    binary: Optional[pathlib.Path] = None,
    workdir: Optional[pathlib.Path] = None,
) -> dict:
    """Run the C++ replayer on a schedule; returns its JSON report."""
    binary = pathlib.Path(binary or DEFAULT_BINARY)
    # unique file per replay: concurrent replays must not clobber each other
    with tempfile.NamedTemporaryFile(
        "w", suffix=".txt", prefix="madtpu_replay_",
        dir=str(workdir) if workdir else None, delete=False,
    ) as f:
        f.write(schedule.dumps())
        path = f.name
    try:
        proc = subprocess.run(
            [str(binary), path], capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"replay failed rc={proc.returncode}: {proc.stderr[-2000:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    finally:
        os.unlink(path)


def classes_match(tpu_violations: int, cpp_report: dict) -> bool:
    """Did the C++ replay observe (at least) one of the TPU's violation classes?"""
    if tpu_violations & VIOLATION_DUAL_LEADER and cpp_report["dual_leader"]:
        return True
    if tpu_violations & (VIOLATION_LOG_MATCHING | VIOLATION_COMMIT_SHADOW) and (
        cpp_report["commit_mismatch"] or cpp_report["apply_disorder"]
    ):
        return True
    return False
