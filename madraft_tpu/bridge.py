"""TPU <-> simcore differential bridge: schedule export + C++ replay.

The batched fuzzer reports a violating cluster as ``(seed, cluster_id)``
(kv.py / engine.py). This module closes the loop that the reference closes
with seed replay (/root/reference/README.md:42-55): re-run that ONE cluster
on host, record its fault schedule — the per-tick ``alive`` bitmask and
``adj`` adjacency matrix, i.e. exactly the crash/restart/partition decisions
the per-cluster PRNG made — and hand the schedule to the C++ raft-core
running on simcore (``cpp/tools/replay_main.cpp``). Schedules, not PRNG
streams, are the interchange format (SURVEY.md §7 "determinism across
backends"): the two backends draw from different generators, so equivalence
is class-level — the C++ online checkers must observe the same violation
CLASS the TPU oracles flagged.

Violation-class mapping (TPU bitmask -> C++ report fields):
  VIOLATION_DUAL_LEADER   -> dual_leader
  VIOLATION_LOG_MATCHING  -> commit_mismatch | apply_disorder
  VIOLATION_COMMIT_SHADOW -> commit_mismatch | apply_disorder
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import pathlib
import subprocess
import tempfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim.config import (
    SimConfig,
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
)
from madraft_tpu.tpusim.state import init_cluster
from madraft_tpu.tpusim.step import _slot, step_cluster

_REPO = pathlib.Path(__file__).resolve().parent.parent
DEFAULT_BINARY = _REPO / "build" / "madtpu_replay"


@dataclasses.dataclass
class Schedule:
    """One cluster's fault schedule plus the meta the C++ replayer needs."""

    n_nodes: int
    ms_per_tick: int
    n_ticks: int
    majority_override: int            # 0 = correct quorum
    seed: int                         # simcore PRNG seed for the replay
    bug: str = ""                     # planted bug name ("" = correct;
    #                                   config.py RAFT_BUGS <-> MADTPU_BUG)
    trace: bool = False               # per-tick C++ state export (the
    #                                   flight-recorder leg; replay_core.h)
    # (tick, alive_bitmask) and (tick, adj row bitmasks) change events
    alive_events: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    adj_events: list[tuple[int, list[int]]] = dataclasses.field(default_factory=list)
    violations: int = 0               # TPU violation bitmask for this cluster
    first_violation_tick: int = -1

    def dumps(self) -> str:
        lines = [
            "# madtpu differential-replay schedule (bridge.py)",
            f"nodes {self.n_nodes}",
            f"ms_per_tick {self.ms_per_tick}",
            f"ticks {self.n_ticks}",
            f"majority_override {self.majority_override}",
            f"seed {self.seed}",
        ]
        if self.trace:
            lines.append("trace 1")
        if self.bug:
            lines.insert(-1, f"bug {self.bug}")
        events = [(t, "alive", f"{m:x}") for t, m in self.alive_events] + [
            (t, "adj", " ".join(f"{r:x}" for r in rows))
            for t, rows in self.adj_events
        ]
        for t, kind, payload in sorted(events, key=lambda e: e[0]):
            lines.append(f"ev {t} {kind} {payload}")
        return "\n".join(lines) + "\n"


def _bitmask(bits: np.ndarray) -> int:
    return int(sum(1 << i for i, b in enumerate(bits) if b))


def extract_schedule(
    cfg: SimConfig,
    seed: int,
    cluster_id: int,
    n_ticks: int,
    step_fn=None,
    init_fn=None,
) -> Schedule:
    """Re-run ONE cluster tick by tick and record its fault schedule.

    ``step_fn``/``init_fn`` default to the raw raft step; service-layer
    fuzzers (kv.py) can pass their own wrappers as long as the returned state
    exposes ``.alive``/``.adj``/``.violations`` under a ``raft`` attribute or
    directly. Exact per-cluster replay is cheap: one un-batched jit + n_ticks
    dispatches.
    """
    step_fn = step_fn or functools.partial(step_cluster, cfg)
    init_fn = init_fn or functools.partial(init_cluster, cfg)
    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)

    def raft_of(state):
        return state.raft if hasattr(state, "raft") else state

    # One compiled scan records the whole (alive, adj) timeline on device —
    # [T, n] + [T, n, n] bools are tiny; per-tick host dispatch is not.
    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = step_fn(carry, key)
            r = raft_of(nxt)
            return nxt, (r.alive, r.adj)

        final, (alives, adjs) = jax.lax.scan(
            body, init_fn(key), None, length=n_ticks
        )
        return final, alives, adjs

    final, alives, adjs = jax.block_until_ready(run(ckey))
    alives, adjs = np.asarray(alives), np.asarray(adjs)

    sched = Schedule(
        n_nodes=cfg.n_nodes,
        ms_per_tick=cfg.ms_per_tick,
        n_ticks=n_ticks,
        majority_override=cfg.majority_override or 0,
        bug=cfg.bug,
        seed=seed,
    )
    prev_alive = _bitmask(np.ones(cfg.n_nodes, bool))
    prev_adj = [_bitmask(np.ones(cfg.n_nodes, bool))] * cfg.n_nodes
    for t in range(1, n_ticks + 1):
        alive = _bitmask(alives[t - 1])
        adj = [_bitmask(row) for row in adjs[t - 1]]
        if alive != prev_alive:
            sched.alive_events.append((t, alive))
            prev_alive = alive
        if adj != prev_adj:
            sched.adj_events.append((t, adj))
            prev_adj = adj
    r = raft_of(final)
    sched.violations = int(r.violations)
    sched.first_violation_tick = int(r.first_violation_tick)
    return sched


def replay_on_simcore(
    schedule: Schedule,
    binary: Optional[pathlib.Path] = None,
    workdir: Optional[pathlib.Path] = None,
) -> dict:
    """Run the C++ replayer on a schedule; returns its JSON report.

    In-process by default (madraft_tpu.simcore ctypes bindings — no
    fork/exec per replay); pass ``binary`` to force the CLI subprocess."""
    if binary is None:
        from madraft_tpu import simcore

        if simcore.available():
            return simcore.replay_schedule(schedule.dumps())
    binary = pathlib.Path(binary or DEFAULT_BINARY)
    # unique file per replay: concurrent replays must not clobber each other
    with tempfile.NamedTemporaryFile(
        "w", suffix=".txt", prefix="madtpu_replay_",
        dir=str(workdir) if workdir else None, delete=False,
    ) as f:
        f.write(schedule.dumps())
        path = f.name
    try:
        proc = subprocess.run(
            [str(binary), path], capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"replay failed rc={proc.returncode}: {proc.stderr[-2000:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    finally:
        os.unlink(path)


def extract_kv_history(cfg, kcfg, seed: int, cluster_id: int, n_ticks: int):
    """Re-run ONE KV-fuzz cluster and export its op history as HistOp lines
    for the C++ Wing-Gong checker (cpp/tools/lincheck_main.cpp).

    Value translation: the TPU oracle observes per-key MUTATION VERSIONS;
    the checker works on value strings. Since every node applies the same
    committed order, observing version v is exactly observing the value after
    the first v committed mutations to that key (in shadow order): the last
    Put's token concatenated with the Appends after it. Each Get's output
    becomes that string and each mutation's input its unique token
    ("a{c}.{s};" / "p{c}.{s};"). The committed order is STREAMED from the per-tick
    shadow trace (each tick's newly-committed lanes are read while still in
    window), so the export works for runs of arbitrary length — far past one
    shadow window of ``log_cap`` entries (the round-2 limitation).

    Returns (lines, violations): the history file body and the cluster's
    violation bitmask.
    """
    # local import: keep the raft-only bridge importable without the kv layer
    from madraft_tpu.tpusim.config import NOOP_CMD
    from madraft_tpu.tpusim.kv import (
        _GET,
        _PUT,
        _unpack,
        init_kv_cluster,
        kv_step,
    )

    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)

    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = kv_step(cfg, kcfg, carry, key)
            return nxt, (nxt.clerk_seq, nxt.clerk_out, nxt.clerk_kind,
                         nxt.clerk_key, nxt.clerk_acked, nxt.clerk_last_obs,
                         nxt.raft.shadow_len, nxt.raft.shadow_val)

        final, trace = jax.lax.scan(
            body, init_kv_cluster(cfg, kcfg, key), None, length=n_ticks
        )
        return final, trace

    final, (seq_t, out_t, kind_t, key_t, acked_t, obs_t, sh_len_t, sh_val_t) = (
        jax.block_until_ready(run(ckey))
    )
    seq_t, out_t, kind_t = np.asarray(seq_t), np.asarray(out_t), np.asarray(kind_t)
    key_t, acked_t, obs_t = np.asarray(key_t), np.asarray(acked_t), np.asarray(obs_t)
    sh_len_t, sh_val_t = np.asarray(sh_len_t), np.asarray(sh_val_t)

    # committed append order per key, deduped, streamed from the shadow trace:
    # entries committed at tick ti occupy absolute indices
    # (len[ti-1], len[ti]] and their canonical lanes ((a-1) mod cap, step.py)
    # are still live in that tick's window, so reading them tick by tick
    # reconstructs the full order no matter how far the window slid since.
    cap = sh_val_t.shape[1]
    # committed MUTATION order per key (appends and puts), deduped; a key's
    # version v maps to the value string after its first v mutations — the
    # last put's token plus the appends after it (Put replaces, Append
    # concatenates; cpp/kvraft/kv.h apply semantics)
    muts_by_key: dict[int, list[tuple[int, str]]] = {}
    seen = set()
    seen_len = 0
    for ti in range(sh_len_t.shape[0]):
        ln = int(sh_len_t[ti])
        for a in range(seen_len + 1, ln + 1):
            # one source of truth for the ring-lane math (step.py)
            val = int(sh_val_t[ti][int(_slot(a, cap))])
            c, s, k, kind = _unpack(kcfg, val)
            # leader no-ops are not client ops (they unpack to kind 3, which
            # the old two-kind filter excluded implicitly — skip explicitly)
            if val == NOOP_CMD or kind == _GET or val in seen:
                continue
            seen.add(val)
            tag = "p" if kind == _PUT else "a"
            muts_by_key.setdefault(int(k), []).append(
                (int(kind), f"{tag}{int(c)}.{int(s)};")
            )
        seen_len = max(seen_len, ln)

    def _state_at(k: int, v: int) -> str:
        muts = muts_by_key.get(k, [])[:v]
        lo = 0
        for i, (kind, _) in enumerate(muts):
            if kind == _PUT:
                lo = i  # put replaces: value restarts at its own token
        return "".join(tok for _, tok in muts[lo:])

    nc = kcfg.n_clients
    lines = []
    T = seq_t.shape[0]
    for c in range(nc):
        for s in range(1, int(seq_t[:, c].max()) + 1):
            # first tick whose post-state shows seq s = the start tick (works
            # even when the op completes within that same tick, when out_t is
            # already False again — the bug_stale_read serve path)
            started = np.nonzero(seq_t[:, c] == s)[0]
            if started.size == 0:
                continue
            invoke = int(started[0]) + 1
            done = np.nonzero(acked_t[:, c] >= s)[0]
            ret_idx = int(done[0]) if done.size else None
            kind = int(kind_t[started[0], c])
            key = int(key_t[started[0], c])
            if kind == _GET:
                if ret_idx is None:
                    continue  # no reply: unconstrained, drop
                obs = int(obs_t[ret_idx, c])
                if obs < 0:
                    continue  # defensive: completed Get must carry its obs
                lines.append(
                    f"op {invoke} {ret_idx + 1} get k{key} {_state_at(key, obs)}"
                )
            else:
                # a pending mutation may still have taken effect: close it at
                # the horizon so the checker may linearize it anywhere after
                # invoke (sound; dropping it could fault a correct history)
                ret = (ret_idx + 1) if ret_idx is not None else (T + 1)
                verb, tag = ("put", "p") if kind == _PUT else ("append", "a")
                lines.append(
                    f"op {invoke} {ret} {verb} k{key} {tag}{c}.{s};"
                )
    return lines, int(final.raft.violations)


def check_history_on_simcore(
    lines: list[str], binary: Optional[pathlib.Path] = None
) -> bool:
    """Run the C++ Wing-Gong checker on an exported history; True =
    linearizable. In-process by default; ``binary`` forces the CLI."""
    if binary is None:
        from madraft_tpu import simcore

        if simcore.available():
            return simcore.check_linearizable("\n".join(lines) + "\n")
    binary = pathlib.Path(binary or _REPO / "build" / "madtpu_lincheck")
    with tempfile.NamedTemporaryFile(
        "w", suffix=".txt", prefix="madtpu_hist_", delete=False
    ) as f:
        f.write("\n".join(lines) + "\n")
        path = f.name
    try:
        proc = subprocess.run(
            [str(binary), path], capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"lincheck failed rc={proc.returncode}: {proc.stderr[-2000:]}"
            )
        return "NOT-linearizable" not in proc.stdout
    finally:
        os.unlink(path)


def classes_match(tpu_violations: int, cpp_report: dict) -> bool:
    """Did the C++ replay observe (at least) one of the TPU's violation classes?"""
    if tpu_violations & VIOLATION_DUAL_LEADER and cpp_report["dual_leader"]:
        return True
    if tpu_violations & (VIOLATION_LOG_MATCHING | VIOLATION_COMMIT_SHADOW) and (
        cpp_report["commit_mismatch"] or cpp_report["apply_disorder"]
    ):
        return True
    return False


def _tick_summary(rec, tick: int) -> dict:
    """Small human-readable snapshot of the TPU trace at a 1-based tick."""
    ti = max(0, min(tick - 1, rec.role.shape[0] - 1))
    from madraft_tpu.tpusim.config import LEADER

    return {
        "tick": ti + 1,
        "alive": [bool(a) for a in rec.alive[ti]],
        "leaders": [int(i) for i in np.nonzero(
            rec.role[ti] == LEADER)[0]],
        "terms": [int(x) for x in rec.term[ti]],
        "commits": [int(x) for x in rec.commit[ti]],
        "log_lens": [int(x) for x in rec.log_len[ti]],
    }


def _cpp_tick_summary(tr: dict, tick: int, n: int) -> dict:
    ti = max(0, min(tick - 1, len(tr["alive"]) - 1))
    return {
        "tick": ti + 1,
        "alive": [bool((tr["alive"][ti] >> i) & 1) for i in range(n)],
        "leaders": [i for i in range(n) if (tr["leader"][ti] >> i) & 1],
        "terms": tr["term"][ti],
        "commits": tr["commit"][ti],
        "log_lens": tr["len"][ti],
    }


def localize_divergence(
    cfg: SimConfig,
    sched: Schedule,
    seed: int,
    cluster_id: int,
    n_ticks: int,
    binary: Optional[pathlib.Path] = None,
) -> dict:
    """Turn a ``classes_match: false`` boolean into a localized lead: replay
    BOTH sides with the flight recorder on and report the first tick where
    the per-tick states diverge.

    Two signals, strongest first:

    - ``fault_schedule``: the per-tick ALIVE mask is schedule-determined and
      must match EXACTLY across backends — a mismatch means the schedule
      transport itself broke, at that tick. (Compared with a one-tick
      persistence filter: a restart that lands within the same virtual
      instant as the sample may lag one sample on the C++ side.)
    - ``violation_onset``: the two backends draw from different PRNGs, so
      per-tick raft state legitimately differs; what must still agree at
      class level is WHETHER/WHEN a violation fires. The divergence tick is
      the first tick where exactly one side is in violation; both sides'
      state snapshots around it are attached as the debugging lead.
    """
    from madraft_tpu.tpusim.trace import alive_masks, replay_cluster_traced

    _, rec = replay_cluster_traced(cfg, seed, cluster_id, n_ticks)
    traced = dataclasses.replace(sched, trace=True)
    cpp = replay_on_simcore(traced, binary=binary)
    tr = cpp.get("trace")
    if not tr or not tr["alive"]:
        return {"error": "c++ replay returned no trace"}
    n = cfg.n_nodes
    tpu_alive = [int(m) for m in alive_masks(rec)]
    T = min(len(tpu_alive), len(tr["alive"]))
    for k in range(T - 1):
        if (tpu_alive[k] != tr["alive"][k]
                and tpu_alive[k + 1] != tr["alive"][k + 1]):
            return {
                "first_divergence_tick": k + 1,
                "kind": "fault_schedule",
                "tpu": _tick_summary(rec, k + 1),
                "cpp": _cpp_tick_summary(tr, k + 1, n),
            }
    tpu_first = int(sched.first_violation_tick)
    cpp_first = -1
    if cpp["dual_leader"] or cpp["commit_mismatch"] or cpp["apply_disorder"]:
        # ceil: a detection at ms in ((t-1)*mpt, t*mpt] happened DURING tick
        # t (floor would report t-1 for any mid-tick detection — the C++
        # checkers fire at apply/poll time, not on tick boundaries)
        mpt = max(1, int(sched.ms_per_tick))
        cpp_first = (int(cpp["first_violation_ms"]) + mpt - 1) // mpt
    onsets = [t for t in (tpu_first, cpp_first) if t >= 0]
    # ±1 tick tolerance: the C++ poll cadences quantize detection, so
    # adjacent-tick onsets agree — but only if the violation CLASSES also
    # correspond; same-time different-class is still a divergence (it is
    # what made classes_match false in the first place)
    near = (tpu_first >= 0 and cpp_first >= 0
            and abs(tpu_first - cpp_first) <= 1)
    if not onsets or (near and classes_match(sched.violations, cpp)):
        return {
            "first_divergence_tick": None,
            "kind": None,
            "note": "alive timelines match and violation onsets agree",
        }
    div = min(onsets)
    return {
        "first_divergence_tick": div,
        "kind": "violation_class" if near else "violation_onset",
        "tpu_first_violation_tick": tpu_first,
        "cpp_first_violation_tick": cpp_first,
        "tpu": _tick_summary(rec, div),
        "cpp": _cpp_tick_summary(tr, div, n),
    }


# --------------------------------------------------------------- shardkv leg
@dataclasses.dataclass
class ShardKvSchedule:
    """One shardkv deployment's config + fault schedule for the C++ replayer
    (cpp/tools/shardkv_replay_main.cpp). The TPU controller is a pre-drawn
    owner-map schedule; the C++ side reproduces each map through the real
    ctrler service (Move ops) so every group chains through the same
    reconfiguration pressure with the full pull/install/ack protocol."""

    n_groups: int
    n_nodes: int
    ms_per_tick: int
    n_ticks: int
    seed: int
    bug: str = "none"  # none | drop_dup_table | serve_frozen (service layer)
    raft_bug: str = ""  # raft-layer planted bug (config.py RAFT_BUGS ->
    #                     MADTPU_BUG), same contract as the raw-raft leg
    # mode "schedule": reproduce the pre-drawn owner maps via Move ops.
    # mode "computed": composite replay — the committed membership-flip
    # stream drives REAL Join/Leave through the C++ 4A service, which then
    # COMPUTES every config via its own rebalance (the computed_ctrler
    # composition, shard_ctrler/server.rs:16-18 + shardkv/server.rs:12-18).
    mode: str = "schedule"
    ctrl_bug: str = "none"  # 4A planted bug (MADTPU_CTRLER_BUG name table)
    cfg_events: list[tuple[int, list[int]]] = dataclasses.field(
        default_factory=list
    )  # (activation tick, owner group per shard)
    alive_events: list[tuple[int, int, int]] = dataclasses.field(
        default_factory=list
    )  # (tick, group, bitmask)
    flip_events: list[tuple[int, int]] = dataclasses.field(
        default_factory=list
    )  # (commit tick, flipped group) — mode "computed"
    violations: int = 0
    first_violation_tick: int = -1

    def dumps(self) -> str:
        lines = [
            "# madtpu shardkv differential-replay schedule (bridge.py)",
            f"groups {self.n_groups}",
            f"nodes {self.n_nodes}",
            f"ticks {self.n_ticks}",
            f"ms_per_tick {self.ms_per_tick}",
            f"seed {self.seed}",
            f"bug {self.bug}",
        ]
        if self.raft_bug:
            lines.append(f"raft_bug {self.raft_bug}")
        if self.mode != "schedule":
            lines.append(f"mode {self.mode}")
        if self.ctrl_bug != "none":
            lines.append(f"ctrl_bug {self.ctrl_bug}")
        for t, owners in self.cfg_events:
            lines.append(f"cfg {t} " + " ".join(str(o) for o in owners))
        for t, g in self.flip_events:
            lines.append(f"flip {t} {g}")
        for t, g, m in self.alive_events:
            lines.append(f"ev {t} alive {g} {m:x}")
        return "\n".join(lines) + "\n"


def extract_shardkv_schedule(cfg, kcfg, seed: int, cluster_id: int,
                             n_ticks: int) -> ShardKvSchedule:
    """Re-run ONE shardkv deployment and record its config schedule + the
    per-group-node fault schedule (the counterpart of extract_schedule for
    the sharded stack)."""
    from madraft_tpu.tpusim.shardkv import init_shardkv_cluster, shardkv_step

    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)

    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = shardkv_step(cfg, kcfg, carry, key)
            return nxt, nxt.rafts.alive

        final, alives = jax.lax.scan(
            body, init_shardkv_cluster(cfg, kcfg, key), None, length=n_ticks
        )
        return final, alives

    final, alives = jax.block_until_ready(run(ckey))
    alives = np.asarray(alives)  # [T, G, N]
    sched = ShardKvSchedule(
        n_groups=kcfg.n_groups,
        n_nodes=cfg.n_nodes,
        ms_per_tick=cfg.ms_per_tick,
        n_ticks=n_ticks,
        seed=seed,
        bug=(
            "drop_dup_table" if kcfg.bug_drop_dup_table
            else "serve_frozen" if kcfg.bug_serve_frozen
            else "none"
        ),
        raft_bug=cfg.bug,
        mode="computed" if kcfg.computed_ctrler else "schedule",
        ctrl_bug=(
            "rotate_tiebreak" if kcfg.bug_rotate_tiebreak else "none"
        ),
    )
    if kcfg.computed_ctrler:
        # the composite interchange: the COMMITTED flip stream (slot order,
        # commit ticks) — the C++ side derives real Join/Leave from it and
        # computes the configs through its own 4A rebalance
        win = np.asarray(final.win_var)      # [NCFG] committed flip gids
        stick = np.asarray(final.slot_tick)  # [NCFG] commit ticks
        for j in range(1, win.shape[0]):
            if win[j] < 0 or stick[j] < 0 or stick[j] >= n_ticks:
                continue
            sched.flip_events.append((int(stick[j]), int(win[j])))
    else:
        cfg_tick = np.asarray(final.cfg_tick)
        cfg_owner = np.asarray(final.cfg_owner)
        for i in range(cfg_tick.shape[0]):
            t = int(cfg_tick[i])
            if t >= n_ticks:
                continue
            sched.cfg_events.append((t, [int(o) for o in cfg_owner[i]]))
    prev = [(1 << cfg.n_nodes) - 1] * kcfg.n_groups
    for t in range(1, n_ticks + 1):
        for g in range(kcfg.n_groups):
            m = _bitmask(alives[t - 1, g])
            if m != prev[g]:
                sched.alive_events.append((t, g, m))
                prev[g] = m
    viol = int(final.violations)
    for v in np.asarray(final.rafts.violations).ravel():
        viol |= int(v)
    sched.violations = viol
    sched.first_violation_tick = int(final.first_violation_tick)
    return sched


def replay_shardkv_on_simcore(
    schedule: ShardKvSchedule,
    binary: Optional[pathlib.Path] = None,
    workdir: Optional[pathlib.Path] = None,
) -> dict:
    """Run the C++ shardkv replayer on a schedule; returns its JSON report.
    The bug mode rides in the schedule text; the C++ side sets (and
    restores) the env-gated injection (shardkv.h bug_mode()) itself.
    In-process by default; ``binary`` forces the CLI subprocess."""
    if binary is None:
        from madraft_tpu import simcore

        if simcore.available():
            return simcore.replay_shardkv_schedule(schedule.dumps())
    binary = pathlib.Path(binary or _REPO / "build" / "madtpu_shardkv_replay")
    with tempfile.NamedTemporaryFile(
        "w", suffix=".txt", prefix="madtpu_skv_replay_",
        dir=str(workdir) if workdir else None, delete=False,
    ) as f:
        f.write(schedule.dumps())
        path = f.name
    try:
        proc = subprocess.run(
            [str(binary), path], capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"shardkv replay failed rc={proc.returncode}: "
                f"{proc.stderr[-2000:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    finally:
        os.unlink(path)


def shardkv_classes_match(tpu_violations: int, cpp_report: dict) -> bool:
    """Class map for the sharded stack: the TPU walker-divergence bit (the
    exactly-once-across-migration oracle) corresponds to the C++ client-side
    dup_apply flag; the TPU interval-oracle bit to stale_read; the composite
    adopted-vs-canonical bit (computed_ctrler + rotate_tiebreak) to the C++
    dual-replica config-history divergence over the same committed ops."""
    from madraft_tpu.tpusim.shardkv import (
        VIOLATION_SHARD_CTRL_STALE,
        VIOLATION_SHARD_DIVERGE,
        VIOLATION_SHARD_STALE_READ,
    )

    if tpu_violations & VIOLATION_SHARD_DIVERGE and cpp_report["dup_apply"]:
        return True
    if tpu_violations & VIOLATION_SHARD_STALE_READ and cpp_report["stale_read"]:
        return True
    if tpu_violations & VIOLATION_SHARD_CTRL_STALE and cpp_report.get(
        "diverged"
    ):
        return True
    return False


# ---------------------------------------------------------------- ctrler leg
@dataclasses.dataclass
class CtrlerSchedule:
    """One 4A cluster's COMMITTED OP STREAM for the C++ replayer
    (cpp/tools/ctrler_replay_core.h). Unlike the raft/shardkv legs, the
    interchange is not a fault schedule but the deduplicated, effective op
    sequence the replicated config service applied — the service state
    machine is deterministic, so the C++ ShardInfo must reproduce the TPU
    walker's exact config history from it (gid g <-> C++ Gid g+1). Planted
    rebalance bugs ride by name, exactly as the other legs."""

    n_gids: int
    bug: str = "none"  # none | rotate_tiebreak | greedy_rebalance | full_reshuffle
    ops: list = dataclasses.field(default_factory=list)
    # ("join", g, ...) | ("leave", g, ...) | ("move", shard, g) |
    # ("query", num) — join/leave carry 1..join_max gids (the TPU layer's
    # multi-gid ops; the reference's Join takes a map, msg.rs:20-37)
    expect_cfgs: int = -1
    expect_owner: list = dataclasses.field(default_factory=list)
    violations: int = 0
    first_violation_tick: int = -1

    def dumps(self) -> str:
        lines = [
            "# madtpu 4A differential-replay schedule (bridge.py)",
            f"gids {self.n_gids}",
            f"bug {self.bug}",
        ]
        for op in self.ops:
            lines.append("op " + " ".join(str(x) for x in op))
        if self.expect_cfgs >= 0:
            lines.append(f"expect_cfgs {self.expect_cfgs}")
        if self.expect_owner:
            lines.append(
                "expect_owner " + " ".join(str(o) for o in self.expect_owner)
            )
        return "\n".join(lines) + "\n"


def extract_ctrler_schedule(cfg, kcfg, seed: int, cluster_id: int,
                            n_ticks: int) -> CtrlerSchedule:
    """Re-run ONE 4A cluster, stream its committed shadow log, and reduce it
    to the effective op sequence (dedup clerk retries; drop the ops both
    backends reject — Join of a member, Leave of a non-member, Move to a
    non-member, any mutation past the history capacity). For bug-free runs
    the canonical model (the REAL ``_rebalance``) also yields the expected
    final owner map, cross-checked against the TPU walker before export."""
    from madraft_tpu.tpusim.config import NOOP_CMD
    from madraft_tpu.tpusim.ctrler import (
        N_SHARDS,
        _rebalance,
        _unpack,
        ctrler_step,
        init_ctrler_cluster,
    )
    from madraft_tpu.tpusim.state import I32

    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)

    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = ctrler_step(cfg, kcfg, carry, key)
            return nxt, (nxt.raft.shadow_len, nxt.raft.shadow_base,
                         nxt.raft.shadow_val)

        return jax.lax.scan(
            body, init_ctrler_cluster(cfg, kcfg, key), None, length=n_ticks
        )

    final, (lens, bases, vals) = jax.block_until_ready(run(ckey))
    lens, bases, vals = np.asarray(lens), np.asarray(bases), np.asarray(vals)
    cap = cfg.log_cap

    stream = []
    prev = 0
    for t in range(n_ticks):
        ln = int(lens[t])
        for ab in range(prev + 1, ln + 1):
            assert ab > int(bases[t]), (
                "shadow window outran the export walk — commit burst > log_cap"
            )
            stream.append(int(vals[t, int(_slot(ab, cap))]))
        prev = ln

    ng = kcfg.n_gids
    off, rot0 = jnp.bool_(False), jnp.asarray(0, I32)

    def rebal(member, owner):
        # np.array (copy): the Move branch writes into the result, and a
        # zero-copy view of a jax array is read-only
        return np.array(_rebalance(
            ng, jnp.asarray(member), jnp.asarray(owner, I32), rot0, off, off
        ))

    member = np.zeros(ng, bool)
    owner = np.full(N_SHARDS, -1, np.int64)
    cfgs = 0
    last_seq: dict = {}
    sched = CtrlerSchedule(
        n_gids=ng,
        bug=(
            "rotate_tiebreak" if kcfg.bug_rotate_tiebreak
            else "greedy_rebalance" if kcfg.bug_greedy_rebalance
            else "full_reshuffle" if kcfg.bug_full_reshuffle
            else "none"
        ),
        violations=int(final.raft.violations),
        first_violation_tick=int(final.raft.first_violation_tick),
    )
    for v in stream:
        if v == 0 or v == NOOP_CMD:
            continue
        client, seq, arg, kind = _unpack(kcfg, v)
        if seq <= last_seq.get(client, 0):
            continue
        last_seq[client] = seq
        room = cfgs < kcfg.n_configs - 1
        if kind == 0:  # Join: arg is a gid-set bitmask; effective iff it
            # adds at least one new member (ctrler.py _apply_entry) — export
            # only the genuinely-new gids so the C++ replay is independent
            # of its join-of-existing-gid no-op behavior
            gset = [g for g in range(ng)
                    if (arg >> g) & 1 and not member[g]]
            if room and gset:
                for g in gset:
                    member[g] = True
                owner = rebal(member, owner)
                cfgs += 1
                sched.ops.append(("join", *gset))
        elif kind == 1:  # Leave: effective iff it removes a present member
            gset = [g for g in range(ng) if (arg >> g) & 1 and member[g]]
            if room and gset:
                for g in gset:
                    member[g] = False
                owner = rebal(member, owner)
                cfgs += 1
                sched.ops.append(("leave", *gset))
        elif kind == 2:  # Move
            shard, gid = arg // ng, arg % ng
            if room and member[gid]:
                owner[shard] = gid
                cfgs += 1
                sched.ops.append(("move", shard, gid))
        else:  # Query: num beyond the history means "latest" on both sides
            sched.ops.append(("query", arg))
    if sched.bug == "none":
        # internal consistency gate: the canonical model must agree with the
        # TPU walker before we assert anything about the C++ side
        w_owner = np.asarray(final.w_owner)
        w_cfgs = int(final.w_cfg_num)
        assert cfgs == w_cfgs and (owner == w_owner).all(), (
            f"exporter model diverged from the TPU walker: "
            f"{cfgs}/{owner.tolist()} vs {w_cfgs}/{w_owner.tolist()}"
        )
        sched.expect_cfgs = cfgs
        sched.expect_owner = [int(o) for o in owner]
    return sched


def replay_ctrler_on_simcore(
    schedule: CtrlerSchedule,
    binary: Optional[pathlib.Path] = None,
    workdir: Optional[pathlib.Path] = None,
) -> dict:
    """Apply a 4A op schedule to the real C++ ShardInfo; returns its JSON
    report. In-process by default; ``binary`` forces the CLI subprocess."""
    if binary is None:
        from madraft_tpu import simcore

        if simcore.available():
            return simcore.replay_ctrler_schedule(schedule.dumps())
    binary = pathlib.Path(binary or _REPO / "build" / "madtpu_ctrler_replay")
    with tempfile.NamedTemporaryFile(
        "w", suffix=".txt", prefix="madtpu_ctl_replay_",
        dir=str(workdir) if workdir else None, delete=False,
    ) as f:
        f.write(schedule.dumps())
        path = f.name
    try:
        proc = subprocess.run(
            [str(binary), path], capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"ctrler replay failed rc={proc.returncode}: "
                f"{proc.stderr[-2000:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    finally:
        os.unlink(path)


def ctrler_classes_match(tpu_violations: int, cpp_report: dict) -> bool:
    """Class map for the 4A service: balance/minimality transfer directly;
    the TPU divergence AND historical-query bits both stem from
    replica-divergent rebalance, which the C++ side reproduces as two
    rotated ShardInfo replicas disagreeing on the config history."""
    from madraft_tpu.tpusim.ctrler import (
        VIOLATION_CTRL_BALANCE,
        VIOLATION_CTRL_DIVERGE,
        VIOLATION_CTRL_MINIMAL,
        VIOLATION_CTRL_QUERY,
    )

    if tpu_violations & VIOLATION_CTRL_BALANCE and cpp_report["balance_bad"]:
        return True
    if tpu_violations & VIOLATION_CTRL_MINIMAL and cpp_report["minimal_bad"]:
        return True
    if tpu_violations & (VIOLATION_CTRL_DIVERGE | VIOLATION_CTRL_QUERY) and (
        cpp_report["diverged"]
    ):
        return True
    return False
