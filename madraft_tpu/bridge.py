"""TPU <-> simcore differential bridge: schedule export + C++ replay.

The batched fuzzer reports a violating cluster as ``(seed, cluster_id)``
(kv.py / engine.py). This module closes the loop that the reference closes
with seed replay (/root/reference/README.md:42-55): re-run that ONE cluster
on host, record its fault schedule — the per-tick ``alive`` bitmask and
``adj`` adjacency matrix, i.e. exactly the crash/restart/partition decisions
the per-cluster PRNG made — and hand the schedule to the C++ raft-core
running on simcore (``cpp/tools/replay_main.cpp``). Schedules, not PRNG
streams, are the interchange format (SURVEY.md §7 "determinism across
backends"): the two backends draw from different generators, so equivalence
is class-level — the C++ online checkers must observe the same violation
CLASS the TPU oracles flagged.

Violation-class mapping (TPU bitmask -> C++ report fields):
  VIOLATION_DUAL_LEADER   -> dual_leader
  VIOLATION_LOG_MATCHING  -> commit_mismatch | apply_disorder
  VIOLATION_COMMIT_SHADOW -> commit_mismatch | apply_disorder
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import pathlib
import subprocess
import tempfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim.config import (
    SimConfig,
    VIOLATION_COMMIT_SHADOW,
    VIOLATION_DUAL_LEADER,
    VIOLATION_LOG_MATCHING,
)
from madraft_tpu.tpusim.state import init_cluster
from madraft_tpu.tpusim.step import _lane_abs, step_cluster

_REPO = pathlib.Path(__file__).resolve().parent.parent
DEFAULT_BINARY = _REPO / "build" / "madtpu_replay"


@dataclasses.dataclass
class Schedule:
    """One cluster's fault schedule plus the meta the C++ replayer needs."""

    n_nodes: int
    ms_per_tick: int
    n_ticks: int
    majority_override: int            # 0 = correct quorum
    seed: int                         # simcore PRNG seed for the replay
    # (tick, alive_bitmask) and (tick, adj row bitmasks) change events
    alive_events: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    adj_events: list[tuple[int, list[int]]] = dataclasses.field(default_factory=list)
    violations: int = 0               # TPU violation bitmask for this cluster
    first_violation_tick: int = -1

    def dumps(self) -> str:
        lines = [
            "# madtpu differential-replay schedule (bridge.py)",
            f"nodes {self.n_nodes}",
            f"ms_per_tick {self.ms_per_tick}",
            f"ticks {self.n_ticks}",
            f"majority_override {self.majority_override}",
            f"seed {self.seed}",
        ]
        events = [(t, "alive", f"{m:x}") for t, m in self.alive_events] + [
            (t, "adj", " ".join(f"{r:x}" for r in rows))
            for t, rows in self.adj_events
        ]
        for t, kind, payload in sorted(events, key=lambda e: e[0]):
            lines.append(f"ev {t} {kind} {payload}")
        return "\n".join(lines) + "\n"


def _bitmask(bits: np.ndarray) -> int:
    return int(sum(1 << i for i, b in enumerate(bits) if b))


def extract_schedule(
    cfg: SimConfig,
    seed: int,
    cluster_id: int,
    n_ticks: int,
    step_fn=None,
    init_fn=None,
) -> Schedule:
    """Re-run ONE cluster tick by tick and record its fault schedule.

    ``step_fn``/``init_fn`` default to the raw raft step; service-layer
    fuzzers (kv.py) can pass their own wrappers as long as the returned state
    exposes ``.alive``/``.adj``/``.violations`` under a ``raft`` attribute or
    directly. Exact per-cluster replay is cheap: one un-batched jit + n_ticks
    dispatches.
    """
    step_fn = step_fn or functools.partial(step_cluster, cfg)
    init_fn = init_fn or functools.partial(init_cluster, cfg)
    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)

    def raft_of(state):
        return state.raft if hasattr(state, "raft") else state

    # One compiled scan records the whole (alive, adj) timeline on device —
    # [T, n] + [T, n, n] bools are tiny; per-tick host dispatch is not.
    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = step_fn(carry, key)
            r = raft_of(nxt)
            return nxt, (r.alive, r.adj)

        final, (alives, adjs) = jax.lax.scan(
            body, init_fn(key), None, length=n_ticks
        )
        return final, alives, adjs

    final, alives, adjs = jax.block_until_ready(run(ckey))
    alives, adjs = np.asarray(alives), np.asarray(adjs)

    sched = Schedule(
        n_nodes=cfg.n_nodes,
        ms_per_tick=cfg.ms_per_tick,
        n_ticks=n_ticks,
        majority_override=cfg.majority_override or 0,
        seed=seed,
    )
    prev_alive = _bitmask(np.ones(cfg.n_nodes, bool))
    prev_adj = [_bitmask(np.ones(cfg.n_nodes, bool))] * cfg.n_nodes
    for t in range(1, n_ticks + 1):
        alive = _bitmask(alives[t - 1])
        adj = [_bitmask(row) for row in adjs[t - 1]]
        if alive != prev_alive:
            sched.alive_events.append((t, alive))
            prev_alive = alive
        if adj != prev_adj:
            sched.adj_events.append((t, adj))
            prev_adj = adj
    r = raft_of(final)
    sched.violations = int(r.violations)
    sched.first_violation_tick = int(r.first_violation_tick)
    return sched


def replay_on_simcore(
    schedule: Schedule,
    binary: Optional[pathlib.Path] = None,
    workdir: Optional[pathlib.Path] = None,
) -> dict:
    """Run the C++ replayer on a schedule; returns its JSON report."""
    binary = pathlib.Path(binary or DEFAULT_BINARY)
    # unique file per replay: concurrent replays must not clobber each other
    with tempfile.NamedTemporaryFile(
        "w", suffix=".txt", prefix="madtpu_replay_",
        dir=str(workdir) if workdir else None, delete=False,
    ) as f:
        f.write(schedule.dumps())
        path = f.name
    try:
        proc = subprocess.run(
            [str(binary), path], capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"replay failed rc={proc.returncode}: {proc.stderr[-2000:]}"
            )
        return json.loads(proc.stdout.strip().splitlines()[-1])
    finally:
        os.unlink(path)


def extract_kv_history(cfg, kcfg, seed: int, cluster_id: int, n_ticks: int):
    """Re-run ONE KV-fuzz cluster and export its op history as HistOp lines
    for the C++ Wing-Gong checker (cpp/tools/lincheck_main.cpp).

    Value translation: the TPU oracle observes per-key applied-APPEND COUNTS;
    the checker works on append-string states. Since every node applies the
    same committed order, observing count k is exactly observing the
    concatenation of the first k committed appends to that key (in shadow
    order), so each Get's output becomes that prefix string and each Append's
    input its unique token. Requires the run to stay within one shadow window
    (committed entries <= log_cap) so the full order is recoverable.

    Returns (lines, violations): the history file body and the cluster's
    violation bitmask.
    """
    # local import: keep the raft-only bridge importable without the kv layer
    from madraft_tpu.tpusim.kv import _APPEND, _GET, _unpack, init_kv_cluster, kv_step

    ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)

    @jax.jit
    def run(key):
        def body(carry, _):
            nxt = kv_step(cfg, kcfg, carry, key)
            return nxt, (nxt.clerk_seq, nxt.clerk_out, nxt.clerk_kind,
                         nxt.clerk_key, nxt.clerk_acked, nxt.clerk_last_obs)

        final, trace = jax.lax.scan(
            body, init_kv_cluster(cfg, kcfg, key), None, length=n_ticks
        )
        return final, trace

    final, (seq_t, out_t, kind_t, key_t, acked_t, obs_t) = jax.block_until_ready(
        run(ckey)
    )
    seq_t, out_t, kind_t = np.asarray(seq_t), np.asarray(out_t), np.asarray(kind_t)
    key_t, acked_t, obs_t = np.asarray(key_t), np.asarray(acked_t), np.asarray(obs_t)

    # committed append order per key, deduped, from the final shadow window
    sh_val = np.asarray(final.raft.shadow_val)
    sh_base = int(final.raft.shadow_base)
    sh_len = int(final.raft.shadow_len)
    assert sh_len - 0 <= sh_val.shape[0], "history outgrew the shadow window"
    cap = sh_val.shape[0]
    # one source of truth for the ring math (step.py)
    lane_abs = np.asarray(_lane_abs(jnp.asarray(sh_base, jnp.int32), cap))
    order = np.argsort(lane_abs)
    appends_by_key: dict[int, list[str]] = {}
    seen = set()
    for lane in order:
        if not (0 < lane_abs[lane] <= sh_len):
            continue
        val = int(sh_val[lane])
        c, s, k, kind = _unpack(kcfg, val)
        if kind != _APPEND or val in seen:
            continue
        seen.add(val)
        appends_by_key.setdefault(int(k), []).append(f"a{int(c)}.{int(s)};")

    nc = kcfg.n_clients
    lines = []
    T = seq_t.shape[0]
    for c in range(nc):
        for s in range(1, int(seq_t[:, c].max()) + 1):
            # first tick whose post-state shows seq s = the start tick (works
            # even when the op completes within that same tick, when out_t is
            # already False again — the bug_stale_read serve path)
            started = np.nonzero(seq_t[:, c] == s)[0]
            if started.size == 0:
                continue
            invoke = int(started[0]) + 1
            done = np.nonzero(acked_t[:, c] >= s)[0]
            ret_idx = int(done[0]) if done.size else None
            kind = int(kind_t[started[0], c])
            key = int(key_t[started[0], c])
            if kind == _GET:
                if ret_idx is None:
                    continue  # no reply: unconstrained, drop
                obs = int(obs_t[ret_idx, c])
                if obs < 0:
                    continue  # defensive: completed Get must carry its obs
                prefix = "".join(appends_by_key.get(key, [])[:obs])
                lines.append(
                    f"op {invoke} {ret_idx + 1} get k{key} {prefix}"
                )
            else:
                # a pending append may still have taken effect: close it at
                # the horizon so the checker may linearize it anywhere after
                # invoke (sound; dropping it could fault a correct history)
                ret = (ret_idx + 1) if ret_idx is not None else (T + 1)
                lines.append(
                    f"op {invoke} {ret} append k{key} a{c}.{s};"
                )
    return lines, int(final.raft.violations)


def check_history_on_simcore(
    lines: list[str], binary: Optional[pathlib.Path] = None
) -> bool:
    """Run the C++ Wing-Gong checker on an exported history; True = linearizable."""
    binary = pathlib.Path(binary or _REPO / "build" / "madtpu_lincheck")
    with tempfile.NamedTemporaryFile(
        "w", suffix=".txt", prefix="madtpu_hist_", delete=False
    ) as f:
        f.write("\n".join(lines) + "\n")
        path = f.name
    try:
        proc = subprocess.run(
            [str(binary), path], capture_output=True, text=True, timeout=300
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"lincheck failed rc={proc.returncode}: {proc.stderr[-2000:]}"
            )
        return "NOT-linearizable" not in proc.stdout
    finally:
        os.unlink(path)


def classes_match(tpu_violations: int, cpp_report: dict) -> bool:
    """Did the C++ replay observe (at least) one of the TPU's violation classes?"""
    if tpu_violations & VIOLATION_DUAL_LEADER and cpp_report["dual_leader"]:
        return True
    if tpu_violations & (VIOLATION_LOG_MATCHING | VIOLATION_COMMIT_SHADOW) and (
        cpp_report["commit_mismatch"] or cpp_report["apply_disorder"]
    ):
        return True
    return False
