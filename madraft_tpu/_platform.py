"""Platform selection + tunnel-resilient backend init, shared by every
entry point (CLI, bench.py, _soak.py, _campaign.py).

Two container facts drive this module (both observed across rounds 2-3,
documented in PERF.md "degraded phases"):

1. The interpreter-startup hook (sitecustomize) force-registers the TPU
   tunnel regardless of ``JAX_PLATFORMS``, so honoring a platform choice
   requires re-asserting ``jax.config.update("jax_platforms", ...)`` after
   import — the env var alone is not enough (tests/conftest.py:8-13 does
   exactly this for the pytest suite; this module does it for everything
   else).
2. The tunnel fails by HANGING inside PJRT backend init (not by raising),
   and occasionally by raising ``UNAVAILABLE``. An in-process hang is
   uninterruptible (the block is inside C++), so health is probed in a
   SUBPROCESS with a hard timeout, with bounded retry/backoff. Round 3
   lost its driver bench artifact (BENCH_r03.json rc:1) and two full
   soaks (~2e10 clean steps) to exactly this; see VERDICT round 3 item 1.

Mirrors the reference's env-driven runtime selection idiom
(/root/reference/README.md:42-87: MADSIM_TEST_* env vars configure the
runtime before any test body runs).
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

_PROBE_SNIPPET = (
    "import jax\n"
    "p = {plat!r}\n"
    "if p: jax.config.update('jax_platforms', p)\n"
    "d = jax.devices()\n"
    "print('MADTPU_PROBE_OK', d[0])\n"
)

# Probe outcomes are appended here when MADTPU_TUNNEL_LOG is set (round-4
# verdict, weak #6: outage claims must be checkable from an artifact, not
# narrative). One JSON line per probe: {ts, plat, ok, latency_s, detail}.
# OPT-IN (ADVICE round-5 finding #1): a library import, test run, or an
# installed copy must not silently append next to the package — set
# MADTPU_TUNNEL_LOG=1 to log to the repo-root default, or to a path to log
# there. Driver scripts that exist to produce artifacts (_soak.py etc.)
# export it themselves.
_STATUS_LOG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "TUNNEL_STATUS.jsonl",
)


def _record_probe(plat, ok: bool, latency_s: float, detail: str) -> None:
    dest = os.environ.get("MADTPU_TUNNEL_LOG", "")
    if not dest or dest == "0":
        return
    row = {
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        ),
        "plat": plat or "default(axon)",
        "ok": ok,
        "latency_s": round(latency_s, 1),
        "detail": detail,
    }
    try:
        with open(_STATUS_LOG if dest == "1" else dest, "a") as f:
            f.write(json.dumps(row) + "\n")
    except OSError:
        pass  # a read-only checkout must not break the probe itself


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Persistent XLA compilation cache — ONE configuration shared by the
    test suite (tests/conftest.py) and the CLI entry point, so a cold CLI
    run reuses every program the suite (or a previous run) already
    compiled instead of recompiling it. Explicit config is required — the
    cache directory merely existing is not enough (round-1 mistake).

    ``cache_dir=None`` resolves MADTPU_CACHE_DIR (a path, or "0" to
    disable) and falls back to ``<repo root>/.jax_cache`` — the same
    directory conftest.py points at. Returns the directory used, or None
    when disabled."""
    if cache_dir is None:
        cache_dir = os.environ.get("MADTPU_CACHE_DIR", "")
        if cache_dir == "0":
            return None
        if not cache_dir:
            cache_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            )
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


def resolve_platform(explicit: str | None = None) -> str | None:
    """The platform the user asked for, or None for 'whatever the
    environment provides' (on this container: the axon tunnel).

    Precedence: explicit flag > MADTPU_PLATFORM > JAX_PLATFORMS. The last
    matters because the sitecustomize hook ignores JAX_PLATFORMS — a user
    running ``JAX_PLATFORMS=cpu python -m madraft_tpu ...`` on a dead
    tunnel reasonably expects CPU, not a silent indefinite hang (round-3
    verdict, weak item 2).
    """
    plat = explicit or os.environ.get("MADTPU_PLATFORM")
    if plat:
        return plat
    env = os.environ.get("JAX_PLATFORMS", "")
    # "axon" (or empty) means the container default — not a user override.
    if env and all(p.strip() in ("cpu", "tpu") for p in env.split(",")):
        return env
    return None


def apply_platform(explicit: str | None = None) -> str | None:
    """Resolve and re-assert the platform choice. Must run before the
    first backend touch (jax.devices / first jit). Returns the choice."""
    plat = resolve_platform(explicit)
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    return plat


def probe_backend(plat: str | None, timeout_s: float = 90.0):
    """Initialize the backend in a subprocess with a hard timeout.

    Returns (ok: bool, detail: str). ``detail`` is the device string on
    success, the failure mode ("hang >Ns" / stderr tail) otherwise.
    """
    code = _PROBE_SNIPPET.format(plat=plat)
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        detail = f"backend init hang (> {timeout_s:.0f}s)"
        _record_probe(plat, False, time.time() - t0, detail)
        return False, detail
    for line in r.stdout.splitlines():
        if line.startswith("MADTPU_PROBE_OK"):
            detail = line.split(" ", 1)[1]
            _record_probe(plat, True, time.time() - t0, detail)
            return True, detail
    tail = (r.stderr or r.stdout).strip().splitlines()
    detail = tail[-1] if tail else f"probe exit {r.returncode}"
    _record_probe(plat, False, time.time() - t0, detail)
    return False, detail


def init_backend_with_retry(
    plat: str | None = None,
    attempts: int = 4,
    timeout_s: float = 90.0,
    backoff_s: float = 15.0,
    log=lambda msg: print(msg, file=sys.stderr, flush=True),
):
    """Bounded retry/backoff around backend init.

    Returns (ok, detail) after at most ``attempts`` subprocess probes with
    linearly growing backoff (15s, 30s, 45s ... by default — the round-3
    outages that resolved at all resolved within minutes). CPU never needs
    probing (it cannot hang), so it short-circuits.
    """
    if plat == "cpu":
        return True, "cpu (unprobed: cannot hang)"
    last = ""
    for i in range(attempts):
        ok, detail = probe_backend(plat, timeout_s=timeout_s)
        if ok:
            return True, detail
        last = detail
        if i + 1 < attempts:
            wait = backoff_s * (i + 1)
            log(
                f"[madtpu] backend probe {i + 1}/{attempts} failed "
                f"({detail}); retrying in {wait:.0f}s"
            )
            time.sleep(wait)
    return False, last


def require_backend_or_die(explicit: str | None = None, timeout_s: float = 90.0):
    """CLI front door: apply the platform choice, then fail FAST with an
    actionable message if the chosen backend cannot initialize — never
    hang indefinitely (round-3 verdict: a fuzz run on a degraded tunnel
    blocked >10 minutes with no diagnostic)."""
    plat = apply_platform(explicit)
    if plat == "cpu":
        return plat
    ok, detail = init_backend_with_retry(
        plat, attempts=1, timeout_s=timeout_s
    )
    if not ok:
        sys.exit(
            f"madtpu: backend init failed: {detail}.\n"
            "The TPU tunnel looks degraded. Re-run on CPU with "
            "--platform cpu (or MADTPU_PLATFORM=cpu / JAX_PLATFORMS=cpu), "
            "or retry later."
        )
    return plat
