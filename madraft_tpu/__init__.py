"""madtpu — a TPU-native deterministic-simulation framework for fuzzing Raft at scale.

Built from scratch with the capabilities of adaqus/MadRaft (MIT 6.824 Raft labs on the
MadSim deterministic simulator). Two backends, one spec:

- ``madraft_tpu.tpusim``: the batched lockstep fuzzer — the per-node Raft tick as a
  pure JAX step function ``vmap``'d over thousands of independent
  (seed x fault-schedule) clusters, with partitions as boolean adjacency masks and
  safety invariants as on-device reductions.
- ``madraft_tpu.simcore``: ctypes bindings to the C++ deterministic event-loop runtime
  (the oracle and exact replayer; madsim-equivalent, see SURVEY.md §2.6).
"""

__version__ = "0.1.0"
