"""Batched multi-group sharded-KV fuzzing on TPU (Lab 4B; the groups axis).

The reference's shardkv (SURVEY.md §2 C9, /root/reference/src/shardkv/) runs
G raft groups, a shard controller assigning N_SHARDS shards to groups, and a
migration protocol that pulls shards between groups on reconfiguration, with
two "challenges": delete surrendered shards (bounded storage,
tests.rs:438-493) and keep serving unaffected shards mid-migration
(tests.rs:499-605). This module is its TPU-native re-imagination:

- Each simulated *deployment* holds G complete raft clusters — the existing
  ``step_cluster`` vmapped over a groups axis — plus the service layer as
  dense tensors. ``vmap`` over deployments gives the fuzz batch.
- The shard controller is not simulated as a fourth raft cluster; it is a
  pre-drawn **config schedule tensor** (activation tick + shard->group map per
  config), the batched analogue of the reference's ctrler service whose
  content the tests fully script anyway (join/leave calls). Correctness of
  the *controller itself* is fuzzed separately: on-device by ``ctrler.py``
  (the 4A service as a replicated state machine with balance / minimality /
  determinism / query_at oracles) and on the C++ backend by its 4A suite.
- Config adoption, shard install, and shard deletion all ride each group's
  raft log as marker entries (CONFIG/INSTALL/DELETE), so crash-restart
  recovery and duplicate suppression work exactly like client ops — the
  reference commits config changes and migrations through raft the same way.
  The pull payload itself (per-shard state + dup table) is modeled as riding
  the INSTALL entry via a per-group staging buffer filled by the inter-group
  pull response (the tensor analogue of the RPC payload).
- Inter-group traffic (pull request / pull response / GC-confirm poll) uses
  per-(dst_group, src_group, shard) mailbox tensors with the same
  delivery-tick + loss semantics as the in-group network. GC is PULL-driven:
  the FROZEN holder polls the gain-config owner "did the install land?" and
  deletes on confirmation — self-contained per frozen copy (derived from the
  static schedule + persisted configs), so no push-ack window can be missed.

Oracles (all on-device reductions, sticky violation bits):
- A **truth walker** per group: a canonical service state machine advanced
  along the group's committed shadow log (bounded entries/tick). It maintains
  the per-shard phases, per-shard state and the MIGRATED dup tables exactly
  as a correct server would. Any alive node whose apply cursor equals the
  walker frontier must match it bit-for-bit (VIOLATION_SHARD_DIVERGE) — this
  is what catches exactly-once-across-migration bugs: an un-migrated dup
  table or a serve-after-freeze both diverge from the walker.
- **Ownership exclusivity** (VIOLATION_SHARD_OWNERSHIP): no shard may be
  walker-OWNED by two groups at once; the freeze-before-pull protocol makes
  dual ownership impossible in a correct implementation.
- **Storage bound** (VIOLATION_SHARD_STORAGE): at most one extra (frozen)
  copy of a shard may exist during migration; frozen copies must disappear
  after GC confirm + delete — challenge 1's bound as an invariant.
- Bug modes validate the oracles: ``bug_skip_freeze`` (a lost shard keeps
  serving at the nodes) and ``bug_drop_dup_table`` (INSTALL resets the dup
  table, so migrated-away retries double-apply).

Entry packing (i32 log values, low 3 bits = kind):
  APPEND/GET/PUT ((client*SEQ_LIM + seq)*NS + shard)*8 + {0,4,5} + 1
  CONFIG     (cfg_idx)*8 + 1 + 1
  INSTALL    (cfg_idx*NS + shard)*8 + 2 + 1
  DELETE     (cfg_idx*NS + shard)*8 + 3 + 1

Gets ride the log like the reference's committed-read path (msg.rs:10-15,
client.rs:16-25 WrongGroup routing): accepted only where the shard is OWNED,
deduped like appends, and checked by a per-shard interval oracle
(VIOLATION_SHARD_STALE_READ) — a serve-from-frozen-copy bug
(``bug_serve_frozen``) is the read-side analogue kv.py's stale-read oracle
catches on the unsharded stack.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from madraft_tpu.tpusim.config import (
    LEADER,
    NOOP_CMD,
    OPEN_QUEUE_SLOTS,
    SimConfig,
    metrics_dims,
    SHARDKV_PHASES,
    packed_bounds,
    zipf_map,
)
from madraft_tpu.tpusim.ctrler import _rebalance as _ctrl_rebalance
from madraft_tpu.tpusim.engine import (
    FuzzProgram,
    attach_layout_telemetry,
    choose_layout_from_reason,
)
from madraft_tpu.tpusim.metrics import (
    fold_latencies,
    fold_latencies_by,
    fold_phases,
    update_worst,
)
from madraft_tpu.tpusim.state import (
    BOOL,
    ClusterState,
    I32,
    PackedClusterState,
    U8,
    durable_after_append,
    init_cluster,
    pack_fields,
    pack_state,
    packed_layout_reason,
    packed_spec_for,
    sint_for,
    uint_for,
    unpack_fields,
    unpack_state,
)
from madraft_tpu.tpusim.step import _lane_abs, _slot, step_cluster

# Violation bits (extending config.VIOLATION_* and kv.VIOLATION_*).
VIOLATION_SHARD_DIVERGE = 64     # node state != truth walker at equal cursor
VIOLATION_SHARD_OWNERSHIP = 128  # a shard walker-OWNED by two groups at once
VIOLATION_SHARD_STORAGE = 256    # state retained for an ABSENT shard (GC leak)
VIOLATION_SHARD_STALE_READ = 1024  # a Get observed a count outside its
#                                    invoke..return truth window (the sharded
#                                    reads-linearizability oracle; kv.py's
#                                    VIOLATION_STALE_READ across migration)
VIOLATION_SHARD_CTRL_STALE = 32768  # live-ctrler mode: a group committed a
#                                     CONFIG entry whose variant bit differs
#                                     from the controller's first-committed
#                                     announce — it adopted a config the
#                                     controller never committed (the
#                                     stale-read-of-the-ctrler bug; the
#                                     reference's groups must only act on
#                                     configs the ctrler's raft committed,
#                                     server.rs:12-18)

_SEQ_LIM = 1 << 13
_BIG = 1 << 30

# Entry kinds (3 bits; GET rides the log like the reference's committed-read
# path, /root/reference/src/shardkv/msg.rs:10-15 Reply::Get; PUT completes
# the reference op set — it mutates like an Append, and a key's observable
# state is its monotone MUTATION VERSION, kv.py's model).
_APPEND, _CONFIG, _INSTALL, _DELETE, _GET, _PUT = 0, 1, 2, 3, 4, 5
# Shard phases.
ABSENT, OWNED, PULLING, FROZEN = 0, 1, 2, 3

# PRNG site ids (disjoint from step.py _S_STEP_BLOCK=0 and kv.py 8..14).
_S_GROUP = 100       # + g: per-group raft stream
_S_POLL = 16
_S_PULL = 17
_S_CLERK = 18
_S_CFGGEN = 19
_S_NET_PULL = 20
_S_CTRL = 21         # live-ctrler raft cluster stream
_S_ANN = 22          # announcer / phantom-announcer / query draws
_S_FLIP = 23         # computed-ctrler flip-op workload schedule


@dataclasses.dataclass(frozen=True)
class ShardKvConfig:
    """Static knobs of the sharded-KV fuzzing layer."""

    n_groups: int = 3
    n_shards: int = 10          # the reference's N_SHARDS (shard_ctrler/mod.rs:9)
    n_clients: int = 4
    n_configs: int = 6          # length of the pre-drawn config schedule
    cfg_interval: int = 60      # mean ticks between config activations
    p_op: float = 0.4           # idle clerk starts a fresh op
    p_get: float = 0.3          # a fresh op is a Get with this probability,
    p_put: float = 0.0          # a Put with this one (one uniform draw, as
    #                             kv.py), an Append otherwise — the full
    #                             reference op set (shardkv Op::{Get,Put,
    #                             Append}, msg.rs)
    p_retry: float = 0.5        # pending clerk re-submits this tick
    p_cfg_learn: float = 0.3    # clerk/leader learns a newer config this tick
    p_pull: float = 0.4         # leader (re)sends a pull for a PULLING shard
    p_ack: float = 0.4          # a FROZEN holder polls the gain-config owner
    #                             for GC confirmation (low values stretch the
    #                             window where the old copy survives)
    pull_delay_min: int = 1
    pull_delay_max: int = 3
    pull_loss: float = 0.1      # inter-group message loss (pulls AND
    #                             GC-confirm polls)
    apply_max: int = 4          # apply-machine entries per node per tick
    walk_max: int = 6           # truth-walker entries per group per tick
    # --- live replicated controller (STATIC: adds a raft cluster) ---
    # When set, config ANNOUNCE entries ride a real on-device raft cluster
    # (the controller the reference's servers poll, server.rs:12-18) and
    # groups learn configs via Query request/response mailboxes to random
    # controller nodes over the lossy inter-group network — config
    # visibility races (two groups seeing different "latest" configs
    # because their reads race a ctrler leader change) arise from the
    # protocol, not from a shared truth tensor. Config CONTENT stays the
    # pre-drawn schedule (the reference's tests script the Join/Leave
    # sequence too; 4A content correctness is ctrler.py's province); what
    # races is the committed ORDER of two competing announce variants —
    # the truth announcer vs a "phantom" (the losing operation order of
    # concurrent Join/Leave proposals). The first-committed variant IS the
    # controller's decision; a group must only ever adopt that one.
    live_ctrler: bool = False
    p_announce: float = 0.5     # truth announcer submits this tick
    p_phantom: float = 0.3      # phantom announcer submits this tick
    # --- computed replicated controller (STATIC: supersedes live_ctrler's
    # pre-drawn config CONTENT). The controller cluster's apply machine IS
    # the 4A state machine: membership FLIP ops (single-gid Join-or-Leave,
    # the reference's Join/Leave pair as one self-normalizing op) ride the
    # controller raft; config j's owner map is COMPUTED at walk time by the
    # 4A closed-form rebalance (ctrler.py _rebalance — the same function the
    # 4A layer fuzzes and the C++ backend mirrors) from whatever op COMMITTED
    # at slot j. Two announcers race competing flips per slot, so the
    # committed ORDER decides config content (concurrent Join/Leave
    # proposals, /root/reference/src/shard_ctrler/server.rs:16-18), and the
    # 4B groups consume the computed service exactly as the reference's
    # servers consume the ctrler (/root/reference/src/shardkv/server.rs:
    # 12-18). Under bug_rotate_tiebreak each controller REPLICA computes its
    # own rotated deficit-fill order (the HashMap-iteration-order classic),
    # a group adopts the map of whichever replica answered its query, and
    # the 4A bug propagates into 4B migration divergence — caught by the
    # walker's adopted-vs-canonical map check (VIOLATION_SHARD_CTRL_STALE)
    # and behaviorally by the ownership-exclusivity oracle.
    computed_ctrler: bool = False
    bug_rotate_tiebreak: bool = False  # computed-ctrler composite bug (4A
    #                                    rotate propagating into 4B)
    # WrongGroup re-query (the reference clerk re-queries the ctrler the
    # moment a group answers WrongGroup, /root/reference/src/shardkv/
    # client.rs:16-25). Modeled: a submit that reaches an ALIVE LEADER of
    # the targeted group for a shard it is not serving marks the clerk, and
    # a marked clerk re-learns the latest config NEXT tick instead of
    # waiting for its p_cfg_learn draw. Off by default (historic visibility-
    # draw model — MIGRATION.md "known model differences"); the liveness
    # delta is pinned by tests either way.
    requery_wrong_group: bool = False
    # Oracle-validation bug modes (False = correct service).
    bug_skip_freeze: bool = False    # lost shards keep serving at the nodes
    bug_drop_dup_table: bool = False  # INSTALL resets the migrated dup table
    bug_serve_frozen: bool = False   # nodes skip the ownership check for
    #                                  reads: a Get on a non-OWNED shard is
    #                                  served from whatever local copy exists
    #                                  (a FROZEN surrendered copy, or nothing
    #                                  after GC) — the sharded stale-read bug
    #                                  the interval oracle must catch
    bug_stale_ctrler_read: bool = False  # live-ctrler mode: a queried ctrler
    #                                  node answers from its LOG TAIL
    #                                  (uncommitted entries included) instead
    #                                  of its committed prefix — a group can
    #                                  adopt a phantom announce that raft
    #                                  later rolls back; CTRL_STALE must fire
    # --- open-loop traffic shape (ISSUE 19; dynamic — kv.py semantics:
    # Bernoulli-per-tick arrivals into a bounded per-clerk queue, submit
    # stamp = arrival tick, zipf_a skews the fresh-op SHARD draw) ---
    open_rate: float = 0.0
    open_queue_cap: int = 0     # 0 = the historic closed-loop clerk
    zipf_a: float = 1.0         # 1.0 = the historic uniform shard draw

    def __post_init__(self):
        if self.p_get + self.p_put > 1.0:
            raise ValueError(
                f"p_get ({self.p_get}) + p_put ({self.p_put}) must stay <= 1"
            )
        if not 0.0 <= self.open_rate <= 1.0:
            raise ValueError(f"open_rate {self.open_rate} not in [0, 1]")
        if not 0 <= self.open_queue_cap <= OPEN_QUEUE_SLOTS:
            raise ValueError(
                f"open_queue_cap {self.open_queue_cap} not in "
                f"[0, {OPEN_QUEUE_SLOTS}] (the arrival-stamp ring size)"
            )
        if self.zipf_a < 1.0:
            raise ValueError(f"zipf_a {self.zipf_a} must be >= 1.0 "
                             "(1.0 = uniform)")
        if self.computed_ctrler and self.live_ctrler:
            raise ValueError(
                "computed_ctrler supersedes live_ctrler — enable one"
            )
        if self.computed_ctrler:
            from madraft_tpu.tpusim.ctrler import N_SHARDS as CTRL_NS

            if self.n_groups < 2:
                raise ValueError(
                    f"computed_ctrler needs n_groups >= 2 (got "
                    f"{self.n_groups}): the phantom's competing flip is "
                    "'always a DIFFERENT gid' (init flip_b), which "
                    "degenerates with one group — the announce race would "
                    "be meaningless"
                )

            if self.n_shards != CTRL_NS:
                raise ValueError(
                    f"computed_ctrler reuses the 4A rebalance (ctrler.py), "
                    f"which is fixed at N_SHARDS={CTRL_NS}; got n_shards="
                    f"{self.n_shards}"
                )
            if self.bug_stale_ctrler_read:
                raise ValueError(
                    "bug_stale_ctrler_read is a live_ctrler-mode oracle "
                    "validation; computed_ctrler's planted bug is "
                    "bug_rotate_tiebreak"
                )
        elif self.bug_rotate_tiebreak:
            raise ValueError(
                "bug_rotate_tiebreak plants a per-replica rebalance "
                "divergence in the COMPUTED controller — it needs "
                "computed_ctrler=True (otherwise the knob would silently "
                "do nothing and read as an oracle failure)"
            )
        # packed ops must stay below NOOP_CMD (which decodes as the unused
        # kind 7) so no client op ever aliases the no-op or overflows i32
        top = _pack_op(self, self.n_clients - 1, _SEQ_LIM - 1,
                       self.n_shards - 1, 7)
        if top >= NOOP_CMD:
            raise ValueError(
                f"n_clients ({self.n_clients}) x n_shards ({self.n_shards}) "
                f"overflow the op packing (max {top} >= NOOP_CMD {NOOP_CMD})"
            )

    def replace(self, **kw) -> "ShardKvConfig":
        return dataclasses.replace(self, **kw)

    def knobs(self) -> "ShardKvKnobs":
        return ShardKvKnobs(
            cfg_interval=jnp.int32(self.cfg_interval),
            p_op=jnp.float32(self.p_op),
            p_get=jnp.float32(self.p_get),
            p_put=jnp.float32(self.p_put),
            p_retry=jnp.float32(self.p_retry),
            p_cfg_learn=jnp.float32(self.p_cfg_learn),
            p_pull=jnp.float32(self.p_pull),
            p_ack=jnp.float32(self.p_ack),
            pull_delay_min=jnp.int32(self.pull_delay_min),
            pull_delay_max=jnp.int32(self.pull_delay_max),
            pull_loss=jnp.float32(self.pull_loss),
            p_announce=jnp.float32(self.p_announce),
            p_phantom=jnp.float32(self.p_phantom),
            bug_skip_freeze=jnp.bool_(self.bug_skip_freeze),
            bug_drop_dup_table=jnp.bool_(self.bug_drop_dup_table),
            bug_serve_frozen=jnp.bool_(self.bug_serve_frozen),
            bug_stale_ctrler_read=jnp.bool_(self.bug_stale_ctrler_read),
            bug_rotate_tiebreak=jnp.bool_(self.bug_rotate_tiebreak),
            requery_wrong_group=jnp.bool_(self.requery_wrong_group),
            open_rate=jnp.float32(self.open_rate),
            open_queue_cap=jnp.int32(self.open_queue_cap),
            zipf_a=jnp.float32(self.zipf_a),
        )

    def static_key(self) -> "ShardKvConfig":
        """Only the shape-determining fields; everything else rides in
        ShardKvKnobs, so configs differing in probabilities, intervals, or
        bug modes share ONE compiled program (the config.py design, landed
        on this layer last — it previously recompiled per config).
        ``live_ctrler`` is static: it adds a whole raft cluster plus the
        announce/query machinery to the program."""
        return ShardKvConfig(
            n_groups=self.n_groups, n_shards=self.n_shards,
            n_clients=self.n_clients, n_configs=self.n_configs,
            apply_max=self.apply_max, walk_max=self.walk_max,
            live_ctrler=self.live_ctrler,
            computed_ctrler=self.computed_ctrler,
        )


class ShardKvKnobs(NamedTuple):
    """Dynamic shardkv-layer knobs (see ShardKvConfig). Uniform scalars
    normally; ``make_shardkv_sweep_fn`` broadcasts them per deployment."""

    cfg_interval: jax.Array
    p_op: jax.Array
    p_get: jax.Array
    p_put: jax.Array
    p_retry: jax.Array
    p_cfg_learn: jax.Array
    p_pull: jax.Array
    p_ack: jax.Array
    pull_delay_min: jax.Array
    pull_delay_max: jax.Array
    pull_loss: jax.Array
    p_announce: jax.Array
    p_phantom: jax.Array
    bug_skip_freeze: jax.Array
    bug_drop_dup_table: jax.Array
    bug_serve_frozen: jax.Array
    bug_stale_ctrler_read: jax.Array
    bug_rotate_tiebreak: jax.Array
    requery_wrong_group: jax.Array
    open_rate: jax.Array
    open_queue_cap: jax.Array
    zipf_a: jax.Array

    def broadcast(self, n_clusters: int) -> "ShardKvKnobs":
        return ShardKvKnobs(
            *(jnp.broadcast_to(x, (n_clusters,)) for x in self)
        )


def _pack_op(cfg: ShardKvConfig, client, seq, shard, kind):
    """APPEND or GET client op."""
    return (((client * _SEQ_LIM + seq) * cfg.n_shards + shard) * 8 + kind) + 1


def _pack_config(cfg_idx, var=0, src_lim=2):
    """CONFIG payload = cfg_idx*src_lim + src. ``src`` records WHICH
    committed announce variant (live-ctrler mode, src_lim=2) or WHICH
    controller replica's computed map (computed-ctrler mode, src_lim=
    n_nodes) the group adopted; 0 when the controller is the schedule
    tensor — the walker checks it against the controller's canonical
    decision (VIOLATION_SHARD_CTRL_STALE)."""
    return ((cfg_idx * src_lim + var) * 8 + _CONFIG) + 1


def _pack_install(cfg: ShardKvConfig, cfg_idx, shard):
    return ((cfg_idx * cfg.n_shards + shard) * 8 + _INSTALL) + 1


def _pack_delete(cfg: ShardKvConfig, cfg_idx, shard):
    return ((cfg_idx * cfg.n_shards + shard) * 8 + _DELETE) + 1


def _unpack(cfg: ShardKvConfig, val, src_lim=2):
    """-> (kind, client, seq, shard, cfg_idx_c, cfg_idx_i, var_c); fields
    valid per kind (var_c: the CONFIG entry's adopted src — announce
    variant or controller replica, see _pack_config)."""
    v = val - 1
    kind = v % 8
    payload = v // 8
    shard = payload % cfg.n_shards
    cs = payload // cfg.n_shards
    client = cs // _SEQ_LIM
    seq = cs % _SEQ_LIM
    cfg_idx_c = payload // src_lim  # CONFIG payload
    var_c = payload % src_lim
    cfg_idx_i = payload // cfg.n_shards  # INSTALL/DELETE payload
    return kind, client, seq, shard, cfg_idx_c, cfg_idx_i, var_c


class ShardKvState(NamedTuple):
    """One deployment: G raft groups + service layer (vmap adds deployments)."""

    rafts: ClusterState          # every leaf has leading axis [G]
    # --- controller schedule (drawn at init, constant thereafter) ---
    cfg_tick: jax.Array          # i32 [NCFG] activation tick of config j
    cfg_owner: jax.Array         # i32 [NCFG, NS] owning group per shard
    # --- live replicated controller (kcfg.live_ctrler; zeros when off) ---
    ctrl: ClusterState           # the controller's own raft cluster [N]
    ctrl_w_frontier: jax.Array   # i32: walker cursor on the ctrl shadow log
    ctrl_w_stalled: jax.Array    # bool, sticky: the walker needed a shadow
    #                              entry the ring had overwritten — win_var
    #                              stops resolving and the CTRL_STALE oracle
    #                              silently stands down without this flag
    #                              (the ctrler.py w_stalled pattern)
    win_var: jax.Array           # i32 [NCFG]: first-committed announce's
    #                              variant per config; -1 = not yet committed.
    #                              THIS is the controller's decision — the
    #                              committed winner of the truth-vs-phantom
    #                              announce race. (computed_ctrler: the
    #                              committed FLIP GID of slot j instead.)
    # --- computed replicated controller (kcfg.computed_ctrler; zeros off) ---
    flip_a: jax.Array            # i32 [NCFG] truth announcer's flip gid per slot
    flip_b: jax.Array            # i32 [NCFG] phantom's competing flip gid
    slot_tick: jax.Array         # i32 [NCFG] tick slot j resolved (-1 pending)
    cmem: jax.Array              # bool [G] canonical member mask (walker)
    ctrl_node_owner: jax.Array   # i32 [N, NS] per-replica owner chain (walker-
    #                              computed; replicas diverge under the
    #                              planted rotate bug, else all canonical)
    ctrl_maps: jax.Array         # i32 [N, NCFG, NS] write-once map history:
    #                              replica n's computed owner map for config j
    #                              (stable: a pure function of the committed
    #                              prefix and n, so crash/replay re-derives it)
    node_src: jax.Array          # i32 [G, N] replica whose map this node's
    #                              latest CONFIG adopted (volatile, replayed)
    snap_src: jax.Array          # i32 [G, N] persisted counterpart
    w_src: jax.Array             # i32 [G] walker's adopted-src register
    cq_req_t: jax.Array          # i32 [G] query delivery tick (0 = none)
    cq_req_node: jax.Array       # i32 [G] targeted ctrler node
    cq_req_j: jax.Array          # i32 [G] asked config index
    cq_rsp_t: jax.Array          # i32 [G] response delivery tick (0 = none)
    cq_rsp_j: jax.Array          # i32 [G]
    cq_rsp_found: jax.Array      # bool [G]
    cq_rsp_var: jax.Array        # i32 [G]
    # --- per-node service state (volatile; rebuilt by log replay) ---
    applied: jax.Array           # i32 [G, N] apply cursor (absolute)
    node_cfg: jax.Array          # i32 [G, N] highest config applied
    phase: jax.Array             # i32 [G, N, NS] ABSENT/OWNED/PULLING/FROZEN
    key_hash: jax.Array          # i32 [G, N, NS]
    key_count: jax.Array         # i32 [G, N, NS]
    last_seq: jax.Array          # i32 [G, N, NS, NC] per-shard dup table
    # --- persisted service snapshot at each node's log base ---
    snap_cfg: jax.Array          # i32 [G, N]
    snap_phase: jax.Array        # i32 [G, N, NS]
    snap_hash: jax.Array         # i32 [G, N, NS]
    snap_count: jax.Array        # i32 [G, N, NS]
    snap_last_seq: jax.Array     # i32 [G, N, NS, NC]
    # --- group-level pull staging (payload "riding" the INSTALL entry) ---
    staged_cfg: jax.Array        # i32 [G, NS] config of staged payload (-1 none)
    staged_hash: jax.Array       # i32 [G, NS]
    staged_count: jax.Array      # i32 [G, NS]
    staged_last_seq: jax.Array   # i32 [G, NS, NC]
    # --- inter-group mailboxes [dst_g, src_g, NS] (delivery tick; 0 empty) ---
    pull_req_t: jax.Array
    pull_req_cfg: jax.Array
    pull_rsp_t: jax.Array
    pull_rsp_cfg: jax.Array
    pull_rsp_hash: jax.Array
    pull_rsp_count: jax.Array
    pull_rsp_last_seq: jax.Array  # [dst, src, NS, NC]
    # GC confirm protocol (challenge 1): the FROZEN HOLDER drives its own
    # deletion — it derives the config it froze at from the static schedule
    # plus its persisted config, and polls that config's owner "installed?";
    # the answer derives from the owner's persisted state alone. Nothing to
    # book-keep at the new owner, so no ack window can be missed (the
    # soak-found leak: push-style acks retried only while the new owner
    # stayed in its gain config — all lost => the frozen copy leaked forever
    # and a later re-gain deadlocked on the regain gate).
    gcq_req_t: jax.Array          # [dst(gain-cfg owner), src(holder), NS]
    gcq_req_cfg: jax.Array
    gcq_rsp_t: jax.Array          # [dst(holder), src(gain-cfg owner), NS]
    gcq_rsp_cfg: jax.Array
    # --- clerks [NC] ---
    clerk_seq: jax.Array
    clerk_out: jax.Array          # bool
    clerk_shard: jax.Array
    clerk_kind: jax.Array         # i32: _APPEND, _GET, or _PUT
    clerk_cfg: jax.Array          # clerk's believed config index
    clerk_wrong: jax.Array        # bool: last submit got WrongGroup (an
    #                               alive leader of the targeted group does
    #                               not serve the shard) — drives the
    #                               requery_wrong_group re-learn
    clerk_acked: jax.Array
    # --- reads-linearizability oracle state (kv.py's design per shard:
    # a shard's state IS its accepted-mutation VERSION (appends + puts;
    # monotone, kv.py's model), so a Get is linearizable
    # iff its observed count lies in [truth at invoke, truth at return]) ---
    clerk_get_lo: jax.Array       # i32 [NC] truth_count[shard] at invoke
    clerk_get_obs: jax.Array      # i32 [NC] observed count; -1 = no reply yet
    gets_done: jax.Array          # i32 [NC] completed Gets
    # --- open-loop arrival queue (ISSUE 19; kv.py semantics: pending =
    # arr - srv, stamp ring mod OPEN_QUEUE_SLOTS, frozen at zero in the
    # neutral closed-loop mode) ---
    open_arr: jax.Array           # i32 [NC] arrivals accepted
    open_srv: jax.Array           # i32 [NC] arrivals started
    open_drop: jax.Array          # i32 [NC] arrivals dropped at a full queue
    open_stamp: jax.Array         # i32 [NC, OPEN_QUEUE_SLOTS] arrival ticks
    #                               (metrics only)
    # --- metrics plane (ISSUE 10; zero-size with cfg.metrics off) ---
    clerk_sub: jax.Array          # i32 [NC] submit stamp: tick the
    #                               outstanding op started (kv.py clerk_sub)
    lat_hist: jax.Array           # i32 [HIST_BUCKETS] DEPLOYMENT-level clerk
    #                               submit->ack histogram — acks happen at
    #                               the service layer (walker accept), so
    #                               the fold lives here, not in any single
    #                               group's raft row; migration stalls and
    #                               WrongGroup re-query hunts are inside the
    #                               measured window
    # --- attribution plane (ISSUE 12; zero-size with metrics off).
    # Boundary stamps follow kv.py (app = first landed append ANYWHERE —
    # a wrong-group append counts, its rejection wait lands in replicate;
    # cmt = walker accept; apl = Get observation), plus the shardkv-only
    # migration counter: clerk_mig counts pre-append ticks the clerk spent
    # marked WrongGroup, and is carved OUT of leader_wait so the 5-phase
    # sum (config.SHARDKV_PHASES) still telescopes to t - sub exactly. ---
    clerk_app: jax.Array          # i32 [NC]
    clerk_cmt: jax.Array          # i32 [NC]
    clerk_apl: jax.Array          # i32 [NC]
    clerk_mig: jax.Array          # i32 [NC] WrongGroup wait ticks
    client_retries: jax.Array     # i32 [NC] submit attempts
    phase_hist: jax.Array         # i32 [5, HIST_BUCKETS] (SHARDKV_PHASES)
    phase_ticks: jax.Array        # i32 [5]
    lat_ticks: jax.Array          # i32 [1]
    worst_lat: jax.Array          # i32 [1]
    worst_phases: jax.Array       # i32 [5]
    worst_key: jax.Array          # i32 [1] — the op's SHARD
    worst_client: jax.Array       # i32 [1]
    worst_sub: jax.Array          # i32 [1]
    key_lat_hist: jax.Array       # i32 [NS, HIST_BUCKETS] per-shard axis
    client_lat_hist: jax.Array    # i32 [NC, HIST_BUCKETS]
    # --- truth walker (oracle ground truth at each group's shadow frontier) ---
    w_frontier: jax.Array        # i32 [G] entries walked (absolute shadow index)
    w_cfg: jax.Array             # i32 [G]
    w_phase: jax.Array           # i32 [G, NS]
    w_hash: jax.Array            # i32 [G, NS]
    w_count: jax.Array           # i32 [G, NS]
    w_last_seq: jax.Array        # i32 [G, NS, NC]
    frz_cfg: jax.Array           # i32 [NS] walker freeze-snapshot config (-1)
    frz_hash: jax.Array          # i32 [NS]
    frz_count: jax.Array         # i32 [NS]
    frz_last_seq: jax.Array      # i32 [NS, NC]
    truth_count: jax.Array       # i32 [NS] accepted appends per shard
    w_clerk_acked: jax.Array     # i32 [NC] walker-accepted seq per client
    installs_done: jax.Array     # i32 scalar: INSTALL entries walked
    deletes_done: jax.Array      # i32 scalar: DELETE entries walked
    max_cfg_lag: jax.Array       # i32 scalar: max configs a restarting node
    #                              had missed (miss_change_4b coverage signal)
    # --- deployment-level violations (group raft violations live in rafts) ---
    violations: jax.Array        # i32 scalar sticky bitmask
    first_violation_tick: jax.Array


def _gen_schedule(cfg: SimConfig, kcfg: ShardKvConfig, key: jax.Array, skn):
    """Config schedule: activation ticks + owner maps, as Join/Leave churn.

    Config 0 assigns shards round-robin over all groups. Each later config is
    a Join (a departed group re-enters) or a Leave (a member departs, always
    keeping >= 1), followed by a deterministic balanced minimal-move
    rebalance: orphaned shards go to the least-loaded member one at a time,
    then single shards move most->least loaded until max - min <= 1. This is
    the reference's Join/Leave semantics as data — several shards migrating
    concurrently between several group pairs per config
    (/root/reference/src/shard_ctrler/tester.rs:134-150 balance check,
    /root/reference/src/shardkv/tests.rs:193-362 concurrent churn). Groups
    that leave keep running (their raft cluster stays up, serving migration
    pulls); membership is purely an ownership-map property, as in the
    reference where a left group's servers still host surrendered shards
    until GC.
    """
    ncfg, ns, g = kcfg.n_configs, kcfg.n_shards, kcfg.n_groups
    kt, km = jax.random.split(jax.random.fold_in(key, _S_CFGGEN))
    gaps = jax.random.randint(
        kt, (ncfg,), skn.cfg_interval // 2, skn.cfg_interval * 3 // 2 + 1,
        dtype=I32,
    )
    cfg_tick = jnp.cumsum(gaps) - gaps[0]  # config 0 active from tick 0
    owner0 = jnp.arange(ns, dtype=I32) % g
    gids = jnp.arange(g, dtype=I32)

    def counts_of(owner, members):
        c = jnp.sum(owner[None, :] == gids[:, None], axis=1).astype(I32)
        return jnp.where(members, c, 0)

    def rebalance(owner, members):
        # orphans (owner no longer a member) -> least-loaded member, in shard
        # order (deterministic, minimal: orphans must move anyway)
        def orphan_body(sh, owner):
            c = counts_of(owner, members)
            tgt = jnp.argmin(jnp.where(members, c, _BIG)).astype(I32)
            is_orph = ~members[owner[sh]]
            return owner.at[sh].set(jnp.where(is_orph, tgt, owner[sh]))

        owner = jax.lax.fori_loop(0, ns, orphan_body, owner)

        # level: move ONE shard most->least loaded while max - min > 1
        # (ns iterations always suffice; each no-op draw is masked out)
        def level_body(_, owner):
            c = counts_of(owner, members)
            mx = jnp.argmax(jnp.where(members, c, -1)).astype(I32)
            mn = jnp.argmin(jnp.where(members, c, _BIG)).astype(I32)
            need = (c[mx] - c[mn]) > 1
            ssel = jnp.argmax(owner == mx).astype(I32)
            return owner.at[ssel].set(
                jnp.where(need, mn, owner[ssel]).astype(I32)
            )

        return jax.lax.fori_loop(0, ns, level_body, owner)

    def body(carry, k):
        owner, members = carry
        ke, kp = jax.random.split(k)
        n_mem = jnp.sum(members.astype(I32))
        can_join = n_mem < g
        can_leave = n_mem > 1
        do_join = can_join & (jax.random.bernoulli(ke, 0.5) | ~can_leave)
        # the r-th element of the candidate pool (members for Leave,
        # non-members for Join), picked by cumsum rank
        pool = jnp.where(do_join, ~members, members)
        r = jax.random.randint(
            kp, (), 0, jnp.maximum(jnp.sum(pool.astype(I32)), 1), dtype=I32
        )
        pick = (jnp.cumsum(pool.astype(I32)) == r + 1) & pool
        gsel = jnp.argmax(pick).astype(I32)
        members = members.at[gsel].set(do_join)
        owner = rebalance(owner, members)
        return (owner, members), owner

    (_, _), owners = jax.lax.scan(
        body, (owner0, jnp.ones((g,), jnp.bool_)),
        jax.random.split(km, ncfg - 1),
    )
    cfg_owner = jnp.concatenate([owner0[None], owners], axis=0)
    return cfg_tick, cfg_owner


def _shardkv_phase_matrix(t, sub, app, cmt, apl, mig, is_get):
    """Exact 5-phase decomposition [len(SHARDKV_PHASES), NC] of t - sub
    (kv.clerk_phase_matrix plus the migration row): boundaries are clamped
    monotone and the migration wait is clipped into the pre-append window,
    so the rows always telescope to exactly t - sub."""
    app_e = jnp.maximum(app, sub)
    mig_e = jnp.minimum(mig, app_e - sub)
    cmt_e = jnp.maximum(cmt, app_e)
    b3 = jnp.where(is_get, jnp.maximum(apl, cmt_e), cmt_e)
    return jnp.stack([
        app_e - sub - mig_e,   # leader_wait
        cmt_e - app_e,         # replicate (incl. wrong-group rejections)
        b3 - cmt_e,            # apply
        t - b3,                # ack
        mig_e,                 # migration
    ])


def _check_shardkv_cfg(cfg: SimConfig) -> None:
    assert cfg.p_client_cmd == 0.0, "shardkv layer owns command injection"
    assert not cfg.compact_at_commit, (
        "shardkv needs compact_at_commit=False (boundary = apply cursor)"
    )


def init_shardkv_cluster(
    cfg: SimConfig, kcfg: ShardKvConfig, key: jax.Array, kn=None, skn=None
) -> ShardKvState:
    if kn is None:
        kn = cfg.knobs()
    if skn is None:
        skn = kcfg.knobs()
    g, n, ns, nc = kcfg.n_groups, cfg.n_nodes, kcfg.n_shards, kcfg.n_clients
    gkeys = jax.vmap(lambda i: jax.random.fold_in(key, _S_GROUP + i))(
        jnp.arange(g)
    )
    rafts = jax.vmap(
        functools.partial(init_cluster, cfg), in_axes=(0, None)
    )(gkeys, kn)
    cfg_tick, cfg_owner = _gen_schedule(cfg, kcfg, key, skn)
    phase0 = jnp.where(
        cfg_owner[0][None, None, :] == jnp.arange(g, dtype=I32)[:, None, None],
        OWNED, ABSENT,
    ) * jnp.ones((g, n, ns), I32)
    zgns = jnp.zeros((g, n, ns), I32)
    zggs = jnp.zeros((g, g, ns), I32)
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        ctrl = init_cluster(cfg, jax.random.fold_in(key, _S_CTRL), kn)
    else:
        # the mode is off (a STATIC choice — its own compiled program):
        # carry the smallest legal ClusterState instead of a full dead
        # cluster; shardkv throughput sits at the HBM working-set knee
        # (bench.py), so an unused n-node cluster per deployment is real
        # bandwidth (_ctrl_sim_cfg is the one copy of that choice)
        ctrl = init_cluster(
            _ctrl_sim_cfg(cfg, kcfg), jax.random.fold_in(key, _S_CTRL)
        )
    ncfg = kcfg.n_configs
    owner0 = cfg_owner[0]
    if kcfg.computed_ctrler:
        # config CONTENT comes from the controller's apply machine, not the
        # pre-drawn schedule: rows 1+ are placeholders the ctrl walker
        # overwrites as slots commit (readers only touch rows <= the
        # committed frontier). cfg_tick stays as the announcers' pacing.
        cfg_owner = jnp.broadcast_to(owner0, (ncfg, ns)) + jnp.zeros(
            (ncfg, ns), I32
        )
        kf = jax.random.split(jax.random.fold_in(key, _S_FLIP))
        flip_a = jax.random.randint(kf[0], (ncfg,), 0, g, dtype=I32)
        # the phantom's competing flip is always a DIFFERENT gid, so the
        # committed order genuinely decides config content
        flip_b = (
            flip_a
            + 1
            + jax.random.randint(kf[1], (ncfg,), 0, max(g - 1, 1), dtype=I32)
        ) % g
    else:
        flip_a = jnp.zeros((ncfg,), I32)
        flip_b = jnp.zeros((ncfg,), I32)
    return ShardKvState(
        rafts=rafts,
        cfg_tick=cfg_tick,
        cfg_owner=cfg_owner,
        ctrl=ctrl,
        ctrl_w_frontier=jnp.asarray(0, I32),
        ctrl_w_stalled=jnp.asarray(False, jnp.bool_),
        win_var=jnp.full((kcfg.n_configs,), -1, I32).at[0].set(0),
        flip_a=flip_a,
        flip_b=flip_b,
        slot_tick=jnp.full((ncfg,), -1, I32).at[0].set(0),
        cmem=jnp.ones((g,), jnp.bool_),
        ctrl_node_owner=jnp.broadcast_to(owner0, (n, ns)) + jnp.zeros(
            (n, ns), I32
        ),
        ctrl_maps=jnp.zeros((n, ncfg, ns), I32).at[:, 0, :].set(owner0),
        node_src=jnp.zeros((g, n), I32),
        snap_src=jnp.zeros((g, n), I32),
        w_src=jnp.zeros((g,), I32),
        cq_req_t=jnp.zeros((g,), I32),
        cq_req_node=jnp.zeros((g,), I32),
        cq_req_j=jnp.zeros((g,), I32),
        cq_rsp_t=jnp.zeros((g,), I32),
        cq_rsp_j=jnp.zeros((g,), I32),
        cq_rsp_found=jnp.zeros((g,), jnp.bool_),
        cq_rsp_var=jnp.zeros((g,), I32),
        applied=jnp.zeros((g, n), I32),
        node_cfg=jnp.zeros((g, n), I32),
        phase=phase0,
        key_hash=zgns, key_count=zgns,
        last_seq=jnp.zeros((g, n, ns, nc), I32),
        snap_cfg=jnp.zeros((g, n), I32),
        snap_phase=phase0,
        snap_hash=zgns, snap_count=zgns,
        snap_last_seq=jnp.zeros((g, n, ns, nc), I32),
        staged_cfg=jnp.full((g, ns), -1, I32),
        staged_hash=jnp.zeros((g, ns), I32),
        staged_count=jnp.zeros((g, ns), I32),
        staged_last_seq=jnp.zeros((g, ns, nc), I32),
        pull_req_t=zggs, pull_req_cfg=zggs,
        pull_rsp_t=zggs, pull_rsp_cfg=zggs,
        pull_rsp_hash=zggs, pull_rsp_count=zggs,
        pull_rsp_last_seq=jnp.zeros((g, g, ns, nc), I32),
        gcq_req_t=zggs, gcq_req_cfg=zggs,
        gcq_rsp_t=zggs, gcq_rsp_cfg=zggs,
        clerk_seq=jnp.zeros((nc,), I32),
        clerk_out=jnp.zeros((nc,), jnp.bool_),
        clerk_shard=jnp.zeros((nc,), I32),
        clerk_kind=jnp.zeros((nc,), I32),
        clerk_cfg=jnp.zeros((nc,), I32),
        clerk_wrong=jnp.zeros((nc,), jnp.bool_),
        clerk_acked=jnp.zeros((nc,), I32),
        clerk_get_lo=jnp.zeros((nc,), I32),
        clerk_get_obs=jnp.full((nc,), -1, I32),
        gets_done=jnp.zeros((nc,), I32),
        open_arr=jnp.zeros((nc,), I32),
        open_srv=jnp.zeros((nc,), I32),
        open_drop=jnp.zeros((nc,), I32),
        open_stamp=jnp.zeros((nc if cfg.metrics else 0, OPEN_QUEUE_SLOTS),
                             I32),
        clerk_sub=jnp.zeros((nc if cfg.metrics else 0,), I32),
        lat_hist=jnp.zeros(metrics_dims(cfg)[:1], I32),
        clerk_app=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_cmt=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_apl=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_mig=jnp.zeros((nc if cfg.metrics else 0,), I32),
        client_retries=jnp.zeros((nc if cfg.metrics else 0,), I32),
        phase_hist=jnp.zeros(
            (len(SHARDKV_PHASES) if cfg.metrics else 0,
             metrics_dims(cfg)[0]), I32,
        ),
        phase_ticks=jnp.zeros(
            (len(SHARDKV_PHASES) if cfg.metrics else 0,), I32
        ),
        lat_ticks=jnp.zeros(metrics_dims(cfg)[4:], I32),
        worst_lat=jnp.zeros(metrics_dims(cfg)[4:], I32),
        worst_phases=jnp.zeros(
            (len(SHARDKV_PHASES) if cfg.metrics else 0,), I32
        ),
        worst_key=jnp.full(metrics_dims(cfg)[4:], -1, I32),
        worst_client=jnp.full(metrics_dims(cfg)[4:], -1, I32),
        worst_sub=jnp.zeros(metrics_dims(cfg)[4:], I32),
        key_lat_hist=jnp.zeros(
            (ns if cfg.metrics else 0, metrics_dims(cfg)[0]), I32
        ),
        client_lat_hist=jnp.zeros(
            (nc if cfg.metrics else 0, metrics_dims(cfg)[0]), I32
        ),
        w_frontier=jnp.zeros((g,), I32),
        w_cfg=jnp.zeros((g,), I32),
        w_phase=phase0[:, 0, :],
        w_hash=jnp.zeros((g, ns), I32),
        w_count=jnp.zeros((g, ns), I32),
        w_last_seq=jnp.zeros((g, ns, nc), I32),
        frz_cfg=jnp.full((ns,), -1, I32),
        frz_hash=jnp.zeros((ns,), I32),
        frz_count=jnp.zeros((ns,), I32),
        frz_last_seq=jnp.zeros((ns, nc), I32),
        truth_count=jnp.zeros((ns,), I32),
        w_clerk_acked=jnp.zeros((nc,), I32),
        installs_done=jnp.asarray(0, I32),
        deletes_done=jnp.asarray(0, I32),
        max_cfg_lag=jnp.asarray(0, I32),
        violations=jnp.asarray(0, I32),
        first_violation_tick=jnp.asarray(-1, I32),
    )


def _ctrl_sim_cfg(cfg: SimConfig, kcfg: ShardKvConfig) -> SimConfig:
    """The SimConfig of the deployment's controller cluster: the real one
    when a controller mode is on, else the smallest legal placeholder (see
    the init_shardkv_cluster note on the HBM working-set knee). The ONE
    copy of that choice — init and the packed schema both read it."""
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        return cfg
    return cfg.replace(n_nodes=1, log_cap=4, uncommitted_cap=1,
                       compact_every=1)


def shardkv_step(
    cfg: SimConfig, kcfg: ShardKvConfig, st: ShardKvState,
    cluster_key: jax.Array, kn=None, skn=None,
) -> ShardKvState:
    """One lockstep tick of a whole deployment."""
    if kn is None:
        # direct (non-program) callers derive knobs from cfg, so cfg must be
        # the REAL config here — check it; program callers pass kn/skn and a
        # static_key() cfg whose pinned dynamic fields are never read
        _check_shardkv_cfg(cfg)
        kn = cfg.knobs()
    if skn is None:
        skn = kcfg.knobs()
    pre = st.rafts
    gkeys = jax.vmap(lambda i: jax.random.fold_in(cluster_key, _S_GROUP + i))(
        jnp.arange(kcfg.n_groups)
    )
    s = jax.vmap(
        functools.partial(step_cluster, cfg), in_axes=(0, 0, None)
    )(pre, gkeys, kn)
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        ctrl = step_cluster(
            cfg, st.ctrl, jax.random.fold_in(cluster_key, _S_CTRL), kn
        )
    else:
        ctrl = st.ctrl
    return _shardkv_service_tick(
        cfg, kcfg, st, pre.alive, pre.base, s, ctrl, cluster_key, kn, skn
    )


def _shardkv_service_tick(
    cfg: SimConfig, kcfg: ShardKvConfig, st: ShardKvState,
    pre_alive: jax.Array, pre_base: jax.Array, s: ClusterState,
    ctrl: ClusterState, cluster_key: jax.Array, kn, skn,
) -> ShardKvState:
    """The service share of one deployment tick given the STEPPED group
    rafts ``s``, the stepped (or passthrough) controller cluster ``ctrl``,
    and the pre-tick raft views (alive/base) — ONE copy of the math for
    the wide step and the fused packed step (the kv.py contract)."""
    g, n, cap = kcfg.n_groups, cfg.n_nodes, cfg.log_cap
    ns, nc = kcfg.n_shards, kcfg.n_clients
    t = s.tick[0]  # all groups tick in lockstep
    key = jax.random.fold_in(cluster_key, t)
    viol = jnp.asarray(0, I32)

    active_cfg = jnp.sum((st.cfg_tick <= t).astype(I32)) - 1  # controller's view

    # ------------------------------------------- live replicated controller
    # (kcfg.live_ctrler) The ANNOUNCE(j, variant) stream rides a real raft
    # cluster under the same fault storm as the groups. Two announcers race:
    # truth (variant 0) and phantom (variant 1 — the losing operation order
    # of concurrent Join/Leave proposals); whichever commits FIRST for a
    # given j is the controller's decision. The walker below resolves the
    # winner from the committed shadow log; groups may only ever adopt that
    # winner (VIOLATION_SHARD_CTRL_STALE otherwise). The reference's servers
    # poll this service via a ctrl-plane clerk (shardkv/server.rs:12-18).
    # (``ctrl`` arrives already stepped when a controller mode is on —
    # shardkv_step's raft sub-phase — and is the untouched carry otherwise.)
    win_var = st.win_var
    ctrl_w_frontier = st.ctrl_w_frontier
    ctrl_w_stalled = st.ctrl_w_stalled
    ncfgs = kcfg.n_configs
    cfg_owner = st.cfg_owner
    cmem, slot_tick = st.cmem, st.slot_tick
    ctrl_node_owner, ctrl_maps = st.ctrl_node_owner, st.ctrl_maps
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        lane1 = jnp.arange(cap, dtype=I32)
        csh_abs = _lane_abs(ctrl.shadow_base, cap)  # [cap]
    if kcfg.live_ctrler:
        for _ in range(kcfg.walk_max):
            canw = ctrl_w_frontier < ctrl.shadow_len
            posw = _slot(ctrl_w_frontier + 1, cap)
            in_win = jnp.any(
                (lane1 == posw) & (csh_abs == ctrl_w_frontier + 1)
            )
            ctrl_w_stalled = ctrl_w_stalled | (canw & ~in_win)
            canw = canw & in_win
            val = jnp.sum(jnp.where(lane1 == posw, ctrl.shadow_val, 0))
            is_ann = canw & (val > 0) & (val != NOOP_CMD)
            aj = jnp.clip((val - 1) // 2, 0, ncfgs - 1)
            av = (val - 1) % 2
            j_oh = jnp.arange(ncfgs, dtype=I32) == aj
            win_var = jnp.where(
                j_oh & is_ann & (win_var < 0), av, win_var
            )
            ctrl_w_frontier = jnp.where(canw, ctrl_w_frontier + 1, ctrl_w_frontier)
        # announces resolve in j order (announcers wait for j-1), so the
        # committed frontier is the resolved prefix length - 1
        frontier = jnp.sum(jnp.cumprod((win_var >= 0).astype(I32))) - 1
        # the committed frontier replaces the schedule tensor as "the
        # controller's view" for clerk visibility and the lag metric
        active_cfg = frontier
    elif kcfg.computed_ctrler:
        # (kcfg.computed_ctrler) The controller's apply machine IS the 4A
        # state machine. The committed stream carries FLIP(slot, gid) ops;
        # the walker applies them IN SLOT ORDER: flip the canonical member
        # mask (floor: never empty), then run the 4A closed-form rebalance
        # ONCE PER REPLICA with that replica's tie rotation (tie_rot =
        # node_id under the planted rotate bug, else 0 everywhere — the
        # ctrler.py node-apply contract). Replica maps land write-once in
        # ctrl_maps; replica 0 (rot 0) is canonical and fills cfg_owner[j],
        # which the rest of the layer (freeze epochs, pull/GC routing,
        # clerks) keeps reading unchanged. Entries for already-resolved
        # slots are late duplicates (the losing announcer) — ignored.
        resolved = jnp.sum(jnp.cumprod((win_var >= 0).astype(I32))) - 1
        rot_n = jnp.arange(n, dtype=I32) * skn.bug_rotate_tiebreak.astype(I32)
        g_lane = jnp.arange(g, dtype=I32)
        slot_lane = jnp.arange(ncfgs, dtype=I32)
        # Pass 1 — walk the committed window (cheap scalar scan): advance
        # the cursor, spot THE resolving entry if one is present. At most
        # ONE slot can resolve per tick: a slot-(j+1) proposal is only
        # submitted after an announcer OBSERVED the walker-resolved
        # frontier >= j (can_ann below), so its commit is strictly later
        # than j's resolution tick — which is what lets the expensive
        # per-replica rebalance run ONCE per tick (pass 2) instead of
        # walk_max times (the round-3 sequential-depth-cliff discipline).
        found = jnp.asarray(False)
        found_flip = jnp.asarray(0, I32)
        for _ in range(kcfg.walk_max):
            canw = ctrl_w_frontier < ctrl.shadow_len
            posw = _slot(ctrl_w_frontier + 1, cap)
            in_win = jnp.any(
                (lane1 == posw) & (csh_abs == ctrl_w_frontier + 1)
            )
            ctrl_w_stalled = ctrl_w_stalled | (canw & ~in_win)
            canw = canw & in_win
            val = jnp.sum(jnp.where(lane1 == posw, ctrl.shadow_val, 0))
            is_op = canw & (val > 0) & (val != NOOP_CMD)
            slot = (val - 1) // g
            flip = jnp.clip((val - 1) % g, 0, g - 1)
            applies = (
                is_op & ~found
                & (slot == resolved + 1) & (resolved + 1 < ncfgs)
            )
            found_flip = jnp.where(applies, flip, found_flip)
            found = found | applies
            ctrl_w_frontier = jnp.where(
                canw, ctrl_w_frontier + 1, ctrl_w_frontier
            )
        # Pass 2 — apply the single resolution: flip the canonical member
        # mask (>=1 floor), run the 4A rebalance once per replica with its
        # tie rotation, and write the maps (write-once per slot).
        nm = jnp.where(g_lane == found_flip, ~cmem, cmem)
        nm = jnp.where(jnp.any(nm), nm, cmem)  # >=1 member floor
        new_mem = jnp.where(found, nm, cmem)
        reb = jax.vmap(
            lambda own, rot: _ctrl_rebalance(
                g, new_mem, own, rot,
                jnp.asarray(False), jnp.asarray(False),
            )
        )(ctrl_node_owner, rot_n)  # [N, NS]
        ctrl_node_owner = jnp.where(found, reb, ctrl_node_owner)
        slot_oh = slot_lane == jnp.clip(resolved + 1, 0, ncfgs - 1)
        ctrl_maps = jnp.where(
            slot_oh[None, :, None] & found, reb[:, None, :], ctrl_maps
        )
        cfg_owner = jnp.where(
            slot_oh[:, None] & found, reb[0][None, :], cfg_owner
        )
        win_var = jnp.where(slot_oh & found, found_flip, win_var)
        slot_tick = jnp.where(slot_oh & found, t, slot_tick)
        cmem = new_mem
        resolved = jnp.where(found, resolved + 1, resolved)
        frontier = resolved
        active_cfg = frontier
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        # announcers: submit the slot-(frontier+1) proposal to a random node
        # that believes it is the leader, once the schedule says the config
        # is due. A stale minority leader accepts the entry into its log
        # (the phantom's home until raft rolls it back); only the majority
        # leader's copy commits. live mode: ANNOUNCE(j, variant); computed
        # mode: FLIP(j, gid) — truth and phantom carry COMPETING flips, so
        # the committed order decides config content.
        ka = jax.random.split(jax.random.fold_in(key, _S_ANN), 6)
        jnext = jnp.clip(frontier + 1, 0, ncfgs - 1)
        due = jnp.sum(
            jnp.where(jnp.arange(ncfgs, dtype=I32) == jnext, st.cfg_tick, 0)
        ) <= t
        can_ann = (frontier + 1 < ncfgs) & due
        c_term, c_val, c_len = ctrl.log_term, ctrl.log_val, ctrl.log_len
        me_cn = jnp.arange(n, dtype=I32)
        jnext_oh = jnp.arange(ncfgs, dtype=I32) == jnext
        for var_bit, p_sub, kd, kt_ in (
            (0, skn.p_announce, ka[0], ka[1]),
            (1, skn.p_phantom, ka[2], ka[3]),
        ):
            sub = can_ann & jax.random.bernoulli(kd, p_sub)
            tgt = jax.random.randint(kt_, (), 0, n, dtype=I32)
            ok = (
                (me_cn == tgt) & sub & ctrl.alive & (ctrl.role == LEADER)
                & (c_len - ctrl.base < cap)
                & (c_len - ctrl.commit < kn.flow_cap)
            )
            if kcfg.computed_ctrler:
                flips = st.flip_a if var_bit == 0 else st.flip_b
                av_ = jnext * g + jnp.sum(jnp.where(jnext_oh, flips, 0)) + 1
            else:
                av_ = jnext * 2 + var_bit + 1
            hit = ok[:, None] & (
                lane1[None, :] == _slot(c_len + 1, cap)[:, None]
            )
            c_term = jnp.where(hit, ctrl.term[:, None], c_term)
            c_val = jnp.where(hit, av_, c_val)
            c_len = jnp.where(ok, c_len + 1, c_len)
        ctrl = ctrl._replace(
            log_term=c_term, log_val=c_val, log_len=c_len,
            durable_len=durable_after_append(ctrl, c_len),
        )

    applied, node_cfg, phase = st.applied, st.node_cfg, st.phase
    key_hash, key_count, last_seq = st.key_hash, st.key_count, st.last_seq
    snap_cfg, snap_phase = st.snap_cfg, st.snap_phase
    snap_hash, snap_count = st.snap_hash, st.snap_count
    snap_last_seq = st.snap_last_seq
    node_src, snap_src, w_src = st.node_src, st.snap_src, st.w_src

    # computed_ctrler: replica `src`'s owner map for config `cj` — a
    # one-hot contraction over the write-once map history (tiny [N*NCFG, NS]
    # matmul; no dynamic gather). Well-defined for any (src, cj) a CONFIG
    # entry can carry: groups adopt only walker-resolved slots.
    if kcfg.computed_ctrler:
        maps_flat = ctrl_maps.reshape(n * ncfgs, ns)
        idx_lane = jnp.arange(n * ncfgs, dtype=I32)

        def map_at(src, cj):
            idx = (
                jnp.clip(src, 0, n - 1) * ncfgs
                + jnp.clip(cj, 0, ncfgs - 1)
            )
            oh = idx_lane == idx[..., None]
            return jnp.sum(jnp.where(oh[..., None], maps_flat, 0), axis=-2)

    # 1. Crash/restart: live service state resets to the node's own persisted
    #    snapshot; replay from base rebuilds (kv.py pattern).
    fresh = (~pre_alive & s.alive) | ~s.alive  # [G, N]
    applied = jnp.where(fresh, s.base, applied)
    node_cfg = jnp.where(fresh, snap_cfg, node_cfg)
    node_src = jnp.where(fresh, snap_src, node_src)
    phase = jnp.where(fresh[..., None], snap_phase, phase)
    key_hash = jnp.where(fresh[..., None], snap_hash, key_hash)
    key_count = jnp.where(fresh[..., None], snap_count, key_count)
    last_seq = jnp.where(fresh[..., None, None], snap_last_seq, last_seq)
    # miss_change_4b coverage signal: how many config activations did a
    # restarting node sleep through? (It recovers by replaying CONFIG entries
    # / installing a snapshot — the max lag metric proves the scenario ran.)
    restarted = (~pre_alive) & s.alive
    max_cfg_lag = jnp.maximum(
        st.max_cfg_lag,
        jnp.max(jnp.where(restarted, active_cfg - node_cfg, 0)),
    )

    # 2. Compaction (base advanced without install): capture live tables as
    #    the persisted snapshot (they equal the state at the new base, because
    #    the boundary is the pre-tick apply cursor).
    inst = s.snap_installed_src >= 0  # [G, N]
    comp = (s.base != pre_base) & ~inst & s.alive
    snap_cfg = jnp.where(comp, node_cfg, snap_cfg)
    snap_src = jnp.where(comp, node_src, snap_src)
    snap_phase = jnp.where(comp[..., None], phase, snap_phase)
    snap_hash = jnp.where(comp[..., None], key_hash, snap_hash)
    snap_count = jnp.where(comp[..., None], key_count, snap_count)
    snap_last_seq = jnp.where(comp[..., None, None], last_seq, snap_last_seq)

    # 3. Raft install-snapshot: adopt the in-group sender's persisted service
    #    snapshot (one-hot over the node axis, per group).
    me_n = jnp.arange(n, dtype=I32)
    src_oh = me_n[None, None, :] == s.snap_installed_src[:, :, None]  # [G,N,Nsrc]

    def adopt(snap):  # snap [G, N, ...] -> gathered over the src-node axis
        extra = snap.ndim - 2  # trailing dims beyond [G, N]
        w = src_oh.reshape(src_oh.shape + (1,) * extra)
        return jnp.sum(jnp.where(w, snap[:, None], 0), axis=2)

    applied = jnp.where(inst, s.base, applied)
    node_cfg = jnp.where(inst, adopt(snap_cfg[..., None])[..., 0], node_cfg)
    node_src = jnp.where(inst, adopt(snap_src[..., None])[..., 0], node_src)
    phase = jnp.where(inst[..., None], adopt(snap_phase), phase)
    key_hash = jnp.where(inst[..., None], adopt(snap_hash), key_hash)
    key_count = jnp.where(inst[..., None], adopt(snap_count), key_count)
    last_seq = jnp.where(inst[..., None, None], adopt(snap_last_seq), last_seq)
    snap_cfg = jnp.where(inst, node_cfg, snap_cfg)
    snap_src = jnp.where(inst, node_src, snap_src)
    snap_phase = jnp.where(inst[..., None], phase, snap_phase)
    snap_hash = jnp.where(inst[..., None], key_hash, snap_hash)
    snap_count = jnp.where(inst[..., None], key_count, snap_count)
    snap_last_seq = jnp.where(inst[..., None, None], last_seq, snap_last_seq)

    # ---------------------------------------------------------- apply machines
    lane = jnp.arange(cap, dtype=I32)[None, None, :]
    sh_lane = jnp.arange(ns, dtype=I32)
    cl_lane = jnp.arange(nc, dtype=I32)
    cl_ids = jnp.arange(nc, dtype=I32)
    clerk_get_obs = st.clerk_get_obs
    gids_v = jnp.arange(g, dtype=I32)

    # away[g, c-1, s]: the schedule moved s away from g when adopting config
    # c. freeze_epoch(view) = the latest such c <= the applier's config view:
    # THE live freeze epoch per (applier, shard) (the regain gate guarantees
    # at most one). DELETE entries are applied ONLY at their own epoch, so a
    # stale-epoch DELETE — e.g. appended by a replay-lagged leader whose
    # applied view still showed an older freeze — is a no-op instead of
    # destroying a newer frozen copy.
    # (computed_ctrler: cfg_owner is the CANONICAL computed chain — rows fill
    # as slots commit, and every consumer below only reads rows <= a config
    # view that is itself <= the committed frontier)
    away_gs = (
        (cfg_owner[None, :-1] == gids_v[:, None, None])
        & (cfg_owner[None, 1:] != gids_v[:, None, None])
    )  # [G, NCFG-1, NS]
    cnum_v = jnp.arange(1, kcfg.n_configs, dtype=I32)[None, :, None]

    def freeze_epoch(cfg_view):
        """[G] -> [G, NS] or [G, N] -> [G, N, NS]: latest away-config <= view."""
        if cfg_view.ndim == 1:
            elig = away_gs & (cnum_v <= cfg_view[:, None, None])
            return jnp.max(jnp.where(elig, cnum_v, 0), axis=1)
        elig = away_gs[:, None] & (cnum_v[:, None] <= cfg_view[..., None, None])
        return jnp.max(jnp.where(elig, cnum_v[:, None], 0), axis=2)
    for _ in range(kcfg.apply_max):
        can = s.alive & (applied < s.commit)  # [G, N]
        pos = _slot(applied + 1, cap)
        val = jnp.sum(jnp.where(lane == pos[..., None], s.log_val, 0), axis=-1)
        kind, client, seq, shard, cfg_c, cfg_i, _var = _unpack(
            kcfg, val, src_lim=n if kcfg.computed_ctrler else 2
        )
        client = jnp.clip(client, 0, nc - 1)
        sh_oh = sh_lane[None, None, :] == shard[..., None]          # [G,N,NS]
        cl_oh = cl_lane[None, None, :] == client[..., None]          # [G,N,NC]

        # APPEND/PUT/GET: accept iff the shard is OWNED here and the seq is
        # fresh; mutations (Append/Put) bump the version, all update the
        # dup table.
        cur_phase = jnp.sum(jnp.where(sh_oh, phase, 0), axis=-1)
        owned = cur_phase == OWNED
        prev_seq = jnp.sum(
            jnp.where(sh_oh[..., None] & cl_oh[..., None, :], last_seq, 0),
            axis=(-2, -1),
        )
        is_rw = can & ((kind == _APPEND) | (kind == _PUT) | (kind == _GET))
        acc_rw = is_rw & owned & (seq > prev_seq)
        acc = acc_rw & (kind != _GET)  # Appends AND Puts mutate
        upd = sh_oh & acc[..., None]
        key_hash = jnp.where(upd, key_hash * 1000003 + val[..., None], key_hash)
        key_count = jnp.where(upd, key_count + 1, key_count)
        last_seq = jnp.where(
            sh_oh[..., None] & acc_rw[..., None, None] & cl_oh[..., None, :],
            jnp.maximum(last_seq, seq[..., None, None]), last_seq,
        )
        # Get observation: the value a Get returns is the shard's
        # mutation version at its log position (a pure function of the committed
        # prefix; the first node to apply it yields the canonical reply, and
        # inter-node agreement is covered by the walker-divergence oracle).
        cur_count = jnp.sum(jnp.where(sh_oh, key_count, 0), axis=-1)  # [G,N]
        get_acc = acc_rw & (kind == _GET)
        m = (
            get_acc[None, :, :]
            & (client[None, :, :] == cl_ids[:, None, None])
            & (seq[None, :, :] == st.clerk_seq[:, None, None])
        )  # [NC, G, N]
        cand = jnp.max(jnp.where(m, cur_count[None, :, :], -1), axis=(1, 2))
        clerk_get_obs = jnp.where(
            (clerk_get_obs < 0) & (cand >= 0), cand, clerk_get_obs
        )

        # CONFIG c+1: adopt iff it is exactly node_cfg+1 (in-order). Lost
        # shards freeze (unless bug), gained shards start pulling; a shard
        # gained in config 0..  that nobody previously owned starts OWNED.
        is_cfg = can & (kind == _CONFIG) & (cfg_c == node_cfg + 1)
        if kcfg.computed_ctrler:
            # the entry records WHICH controller replica's computed map the
            # group adopted; the previous map is the node's own last
            # adoption (node_src) — both stable pure functions of the
            # committed controller prefix, so replay reconstructs them
            new_owner = map_at(_var, cfg_c)          # [G, N, NS]
            prev_owner = map_at(node_src, node_cfg)  # [G, N, NS]
        else:
            # cfg_c is [G,N]; cfg_owner is [NCFG, NS] -> result [G,N,NS]
            new_owner = cfg_owner[jnp.clip(cfg_c, 0, kcfg.n_configs - 1)]
            prev_owner = cfg_owner[jnp.clip(cfg_c - 1, 0, kcfg.n_configs - 1)]
        my_g = jnp.arange(g, dtype=I32)[:, None, None]
        # gains only from ABSENT: a leader may not adopt a config that
        # re-gains a shard it still holds FROZEN (the older migration still
        # needs that copy) — the can_advance gate below delays the CONFIG
        # append until the DELETE landed, so at apply time the phase is
        # ABSENT. Turning FROZEN into PULLING here instead would destroy the
        # frozen copy and deadlock the older migration against the newer one.
        gains = (new_owner == my_g) & (phase == ABSENT)
        loses = (new_owner != my_g) & (phase == OWNED)
        from_nobody = prev_owner == new_owner  # unchanged owner: no migration
        phase = jnp.where(
            is_cfg[..., None] & gains,
            jnp.where(from_nobody, OWNED, PULLING), phase,
        )
        phase = jnp.where(
            is_cfg[..., None] & loses & ~skn.bug_skip_freeze, FROZEN, phase
        )
        node_cfg = jnp.where(is_cfg, cfg_c, node_cfg)
        if kcfg.computed_ctrler:
            node_src = jnp.where(is_cfg, jnp.clip(_var, 0, n - 1), node_src)

        # INSTALL(s, c): adopt the staged payload (group-level staging models
        # the payload riding the entry); only meaningful while PULLING, and
        # only when the staging still holds THIS config's payload — a node
        # replaying an old INSTALL after the group re-pulled the shard at a
        # later config must skip it (it converges at the later INSTALL; the
        # walker's frz_cfg gate is the oracle-side mirror of this guard).
        stg_cfg_at = jnp.sum(
            jnp.where(sh_oh, st.staged_cfg[:, None, :], 0), axis=-1
        )  # [G, N]
        is_inst = can & (kind == _INSTALL) & (stg_cfg_at == cfg_i)
        inst_upd = sh_oh & is_inst[..., None] & (phase == PULLING)
        stg_hash = st.staged_hash[:, None, :] * jnp.ones((1, n, 1), I32)
        stg_count = st.staged_count[:, None, :] * jnp.ones((1, n, 1), I32)
        key_hash = jnp.where(inst_upd, stg_hash, key_hash)
        key_count = jnp.where(inst_upd, stg_count, key_count)
        adopted = st.staged_last_seq[:, None, :, :] * jnp.ones((1, n, 1, 1), I32)
        last_seq = jnp.where(
            inst_upd[..., None],
            jnp.where(skn.bug_drop_dup_table, 0, adopted),
            last_seq,
        )
        phase = jnp.where(inst_upd, OWNED, phase)

        # DELETE(s, c): drop the frozen copy (challenge-1 GC) — only at its
        # own freeze epoch (see the freeze_epoch comment above).
        fe_at = jnp.sum(jnp.where(sh_oh, freeze_epoch(node_cfg), 0), axis=-1)
        is_del = can & (kind == _DELETE) & (cfg_i == fe_at)
        del_upd = sh_oh & is_del[..., None] & (phase == FROZEN)
        phase = jnp.where(del_upd, ABSENT, phase)
        key_hash = jnp.where(del_upd, 0, key_hash)
        key_count = jnp.where(del_upd, 0, key_count)
        last_seq = jnp.where(del_upd[..., None], 0, last_seq)

        applied = jnp.where(can, applied + 1, applied)

    # ------------------------------------------------------------ truth walker
    # Advance each group's canonical state machine along its committed shadow
    # log (bounded entries/tick; the walker chases the frontier and the
    # divergence oracle gates on exact frontier match).
    w_frontier, w_cfg = st.w_frontier, st.w_cfg
    w_phase, w_hash, w_count = st.w_phase, st.w_hash, st.w_count
    w_last_seq = st.w_last_seq
    frz_cfg, frz_hash = st.frz_cfg, st.frz_hash
    frz_count, frz_last_seq = st.frz_count, st.frz_last_seq
    truth_count, w_clerk_acked = st.truth_count, st.w_clerk_acked
    installs_done, deletes_done = st.installs_done, st.deletes_done
    sh_abs = jax.vmap(lambda b: _lane_abs(b, cap))(s.shadow_base)  # [G, cap]
    lane_g = jnp.arange(cap, dtype=I32)[None, :]
    my_gv = jnp.arange(g, dtype=I32)  # [G]
    for _ in range(kcfg.walk_max):
        canw = w_frontier < s.shadow_len  # [G]
        # value at shadow index w_frontier+1 (one-hot over lanes; a lane
        # outside the window means the walker fell > cap behind — treated as
        # a zero value that matches nothing; tests keep walk_max high enough)
        posw = _slot(w_frontier + 1, cap)
        in_win = jnp.any(
            (lane_g == posw[:, None]) & (sh_abs == (w_frontier + 1)[:, None]),
            axis=1,
        )
        val = jnp.sum(
            jnp.where(lane_g == posw[:, None], s.shadow_val, 0), axis=1
        )
        canw = canw & in_win
        kind, client, seq, shard, cfg_c, cfg_i, var_c = _unpack(
            kcfg, val, src_lim=n if kcfg.computed_ctrler else 2
        )
        client = jnp.clip(client, 0, nc - 1)
        sh_oh = sh_lane[None, :] == shard[:, None]   # [G, NS]
        cl_oh = cl_lane[None, :] == client[:, None]  # [G, NC]

        cur_phase = jnp.sum(jnp.where(sh_oh, w_phase, 0), axis=-1)
        # Cross-group walk ordering: a dst group's INSTALL may reach the
        # walker before the src group's freeze was walked (walkers advance
        # independently). The freeze-snapshot copy would then be stale, so the
        # walker STALLS this group's walk until the snapshot for exactly this
        # (shard, config) exists. No circular wait: the shard's migration
        # chain follows config order, and each group's own log orders its
        # install before its subsequent freeze.
        frz_at = jnp.sum(jnp.where(sh_oh, frz_cfg[None, :], 0), axis=-1)
        stall = (
            canw & (kind == _INSTALL) & (cur_phase == PULLING)
            & (frz_at != cfg_i)
        )
        canw = canw & ~stall
        # Live-ctrler oracle: the committed CONFIG entry's adopted-announce
        # variant must equal the controller's first-committed one. A group
        # that adopted a phantom (or an uncommitted announce, win_var still
        # -1) acted on a config the controller never committed.
        if kcfg.live_ctrler:
            wv_at = jnp.sum(
                jnp.where(
                    jnp.arange(ncfgs, dtype=I32)[None, :]
                    == jnp.clip(cfg_c, 0, ncfgs - 1)[:, None],
                    win_var[None, :], 0,
                ),
                axis=1,
            )
            stale_adopt = (
                canw & (kind == _CONFIG) & (cfg_c == w_cfg + 1)
                & (var_c != wv_at)
            )
            viol |= jnp.where(
                jnp.any(stale_adopt), VIOLATION_SHARD_CTRL_STALE, 0
            )
        prev_seq = jnp.sum(
            jnp.where(sh_oh[..., None] & cl_oh[:, None, :], w_last_seq, 0),
            axis=(-2, -1),
        )
        is_rw = canw & ((kind == _APPEND) | (kind == _PUT) | (kind == _GET))
        acc_rw = is_rw & (cur_phase == OWNED) & (seq > prev_seq)
        acc = acc_rw & (kind != _GET)  # Appends AND Puts mutate
        upd = sh_oh & acc[:, None]
        w_hash = jnp.where(upd, w_hash * 1000003 + val[:, None], w_hash)
        w_count = jnp.where(upd, w_count + 1, w_count)
        w_last_seq = jnp.where(
            sh_oh[..., None] & acc_rw[:, None, None] & cl_oh[:, None, :],
            jnp.maximum(w_last_seq, seq[:, None, None]), w_last_seq,
        )
        truth_count = truth_count + jnp.sum(
            (sh_lane[None, :] == shard[:, None]) & acc[:, None], axis=0,
            dtype=I32,
        )
        # the walker's accept IS the service's reply: ack the clerk (both
        # kinds; a Get additionally needs its observation, checked below)
        w_clerk_acked = jnp.maximum(
            w_clerk_acked,
            jnp.max(jnp.where(cl_oh & acc_rw[:, None], seq[:, None], 0), axis=0),
        )

        is_cfg = canw & (kind == _CONFIG) & (cfg_c == w_cfg + 1)
        if kcfg.computed_ctrler:
            new_owner = map_at(var_c, cfg_c)     # [G, NS]
            prev_owner = map_at(w_src, w_cfg)    # [G, NS]
            # Composite 4A->4B oracle: the adopted map must BE the canonical
            # controller decision (replica 0's rot-0 chain). Under the
            # planted rotate bug a group that adopted a rotated replica's
            # map acted on a config the canonical controller never produced
            # — the HashMap-iteration divergence propagating into 4B.
            canon = cfg_owner[jnp.clip(cfg_c, 0, kcfg.n_configs - 1)]
            stale_map = is_cfg & jnp.any(new_owner != canon, axis=-1)
            viol |= jnp.where(
                jnp.any(stale_map), VIOLATION_SHARD_CTRL_STALE, 0
            )
            w_src = jnp.where(is_cfg, jnp.clip(var_c, 0, n - 1), w_src)
        else:
            new_owner = cfg_owner[jnp.clip(cfg_c, 0, kcfg.n_configs - 1)]  # [G,NS]
            prev_owner = cfg_owner[jnp.clip(cfg_c - 1, 0, kcfg.n_configs - 1)]
        gains = (new_owner == my_gv[:, None]) & (w_phase == ABSENT)
        loses = (new_owner != my_gv[:, None]) & (w_phase == OWNED)
        from_nobody = prev_owner == new_owner
        freeze = is_cfg[:, None] & loses
        # snapshot the frozen state for the INSTALL-side dup-table copy
        any_freeze = jnp.any(freeze, axis=0)  # [NS]
        frz_cfg = jnp.where(any_freeze, jnp.max(jnp.where(freeze, cfg_c[:, None], -1), axis=0), frz_cfg)
        frz_hash = jnp.where(any_freeze, jnp.sum(jnp.where(freeze, w_hash, 0), axis=0), frz_hash)
        frz_count = jnp.where(any_freeze, jnp.sum(jnp.where(freeze, w_count, 0), axis=0), frz_count)
        frz_last_seq = jnp.where(
            any_freeze[:, None],
            jnp.sum(jnp.where(freeze[..., None], w_last_seq, 0), axis=0),
            frz_last_seq,
        )
        w_phase = jnp.where(
            is_cfg[:, None] & gains,
            jnp.where(from_nobody, OWNED, PULLING), w_phase,
        )
        w_phase = jnp.where(freeze, FROZEN, w_phase)
        w_cfg = jnp.where(is_cfg, cfg_c, w_cfg)

        is_inst = canw & (kind == _INSTALL)
        inst_upd = sh_oh & is_inst[:, None] & (w_phase == PULLING)
        w_hash = jnp.where(inst_upd, frz_hash[None, :], w_hash)
        w_count = jnp.where(inst_upd, frz_count[None, :], w_count)
        w_last_seq = jnp.where(
            inst_upd[..., None], frz_last_seq[None, :, :], w_last_seq
        )
        w_phase = jnp.where(inst_upd, OWNED, w_phase)
        installs_done += jnp.sum(inst_upd, dtype=I32)

        # epoch-guarded like the node apply machines (freeze_epoch comment)
        fe_w_at = jnp.sum(jnp.where(sh_oh, freeze_epoch(w_cfg), 0), axis=-1)
        is_del = canw & (kind == _DELETE) & (cfg_i == fe_w_at)
        del_upd = sh_oh & is_del[:, None] & (w_phase == FROZEN)
        w_phase = jnp.where(del_upd, ABSENT, w_phase)
        w_hash = jnp.where(del_upd, 0, w_hash)
        w_count = jnp.where(del_upd, 0, w_count)
        w_last_seq = jnp.where(del_upd[..., None], 0, w_last_seq)
        deletes_done += jnp.sum(del_upd, dtype=I32)

        w_frontier = jnp.where(canw, w_frontier + 1, w_frontier)

    # ----------------------------------------------------------------- oracles
    # Divergence: an alive node at exactly the walker frontier must equal it.
    at_frontier = s.alive & (applied == w_frontier[:, None])  # [G, N]
    m_state = (
        (phase == w_phase[:, None, :])
        & (key_hash == w_hash[:, None, :])
        & (key_count == w_count[:, None, :])
    )
    m_dup = jnp.all(last_seq == w_last_seq[:, None, :, :], axis=-1)
    m_cfg = node_cfg == w_cfg[:, None]
    diverge = at_frontier & ~(jnp.all(m_state & m_dup, axis=-1) & m_cfg)
    viol |= jnp.where(jnp.any(diverge), VIOLATION_SHARD_DIVERGE, 0)
    # Ownership exclusivity (walker-level; freeze-before-pull forbids dual own).
    owned_ct = jnp.sum((w_phase == OWNED).astype(I32), axis=0)  # [NS]
    viol |= jnp.where(jnp.any(owned_ct > 1), VIOLATION_SHARD_OWNERSHIP, 0)
    # Storage (challenge 1): deleted means DELETED — a node holding state for
    # a shard whose phase is ABSENT is a GC leak (the bytes challenge 1
    # bounds). Chained migrations make any per-tick bound on frozen-copy
    # counts unsound (confirm polls lag arbitrarily), so eventual GC
    # completion is asserted at quiesce by the tests via the report's
    # frozen_left/deletes fields — the analogue of the reference's
    # end-of-test total-storage assertion (shardkv/tests.rs:477-488).
    leak = s.alive[..., None] & (phase == ABSENT) & (
        (key_hash != 0) | (key_count != 0)
    )
    viol |= jnp.where(jnp.any(leak), VIOLATION_SHARD_STORAGE, 0)

    # ------------------------------------------------- inter-group mailboxes
    # Leaders of each group (there may transiently be several; raft dedups the
    # resulting marker entries via apply-side guards).
    is_lead = s.alive & (s.role == LEADER)  # [G, N]
    lead_any = jnp.any(is_lead, axis=1)     # [G]
    # leader-held service view: take the max-applied leader node per group
    lead_score = jnp.where(is_lead, applied, -1)
    lead_node = jnp.argmax(lead_score, axis=1)  # [G]
    ln_oh = me_n[None, :] == lead_node[:, None]  # [G, N]

    def lead_view(x):  # x [G, N, ...] -> [G, ...] at the leader node
        extra = x.ndim - 2
        w = ln_oh.reshape(ln_oh.shape + (1,) * extra)
        return jnp.sum(jnp.where(w, x, 0), axis=1)

    l_phase = lead_view(phase)        # [G, NS]
    l_cfg = lead_view(node_cfg[..., None])[..., 0]  # [G]
    l_hash, l_count = lead_view(key_hash), lead_view(key_count)
    l_last_seq = lead_view(last_seq)  # [G, NS, NC]

    kp = jax.random.split(jax.random.fold_in(key, _S_PULL), 4)
    knet = jax.random.split(jax.random.fold_in(key, _S_NET_PULL), 6)

    def _net_pair(k, shape):
        """(delay, lost) for a batch of inter-group sends from ONE u32 word
        each (the step.py _net_draws packing: loss decided by the top 24
        bits, delay by the low byte)."""
        w = jax.random.bits(k, shape)
        lost = (
            (w >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)
        ) < skn.pull_loss
        span = jnp.maximum(1, skn.pull_delay_max + 1 - skn.pull_delay_min)
        delay = skn.pull_delay_min + ((w & 0xFF) % span).astype(I32)
        return delay, lost

    # Deliver pull requests: src leader answers for FROZEN shards at the
    # requested config with its own (frozen) state.
    req_arr = st.pull_req_t == t  # [dst, src, NS] arrives at src
    src_frozen = (l_phase == FROZEN)[None, :, :]  # src's leader view
    src_cfg_ok = (l_cfg[None, :, None] >= st.pull_req_cfg) & lead_any[None, :, None]
    answer = req_arr & src_frozen & src_cfg_ok
    delay, lost = _net_pair(knet[0], (g, g, ns))
    send_rsp = answer & ~lost
    pull_rsp_t = jnp.where(send_rsp, t + delay, st.pull_rsp_t)
    pull_rsp_cfg = jnp.where(send_rsp, st.pull_req_cfg, st.pull_rsp_cfg)
    pull_rsp_hash = jnp.where(send_rsp, l_hash[None, :, :], st.pull_rsp_hash)
    pull_rsp_count = jnp.where(send_rsp, l_count[None, :, :], st.pull_rsp_count)
    pull_rsp_last_seq = jnp.where(
        send_rsp[..., None], l_last_seq[None, :, :, :], st.pull_rsp_last_seq
    )
    pull_req_t = jnp.where(req_arr, 0, st.pull_req_t)

    # Deliver pull responses at dst: stage the payload (overwrite is fine —
    # frozen state is immutable per config transition).
    rsp_arr = pull_rsp_t == t  # [dst, src, NS]
    got = jnp.any(rsp_arr, axis=1)  # [dst, NS]
    pick = jnp.where(rsp_arr, 1, 0)
    staged_cfg = jnp.where(
        got, jnp.max(jnp.where(rsp_arr, pull_rsp_cfg, -1), axis=1), st.staged_cfg
    )
    staged_hash = jnp.where(
        got, jnp.sum(pull_rsp_hash * pick, axis=1), st.staged_hash
    )
    staged_count = jnp.where(
        got, jnp.sum(pull_rsp_count * pick, axis=1), st.staged_count
    )
    staged_last_seq = jnp.where(
        got[..., None],
        jnp.sum(pull_rsp_last_seq * pick[..., None], axis=1),
        st.staged_last_seq,
    )
    pull_rsp_t = jnp.where(rsp_arr, 0, pull_rsp_t)

    # The config each group's CURRENT frozen copy of shard s dates from
    # (freeze_epoch comment above; leader's applied view). 0 = never froze.
    freeze_cfg = freeze_epoch(l_cfg)  # [G, NS]

    # Deliver GC confirms at the holder FIRST (responses before requests —
    # the step.py ordering principle): the leader appends DELETE, but only
    # when the confirmed epoch matches the CURRENT freeze epoch, so an
    # in-flight confirm from an older epoch can never delete a newer frozen
    # copy whose own migration is still in progress.
    grsp_arr = st.gcq_rsp_t == t  # [dst(holder), src, NS]
    ack_del = jnp.any(
        grsp_arr & (st.gcq_rsp_cfg == freeze_cfg[:, None, :]), axis=1
    ) & (l_phase == FROZEN)  # [G, NS]
    ack_del_cfg = freeze_cfg
    gcq_rsp_t = jnp.where(grsp_arr, 0, st.gcq_rsp_t)

    # Deliver GC-confirm requests at the gain-config owner: its leader
    # answers "installed" iff its PERSISTED state proves the (s, c) install
    # applied — l_cfg > c (config advance gates on pulls complete, and the
    # CONFIG c+1 entry follows the INSTALL in its log), or l_cfg == c with
    # the shard OWNED. Keep-oldest on the response slot (an in-flight
    # confirm is never clobbered by a fresh one).
    gq_arr = st.gcq_req_t == t  # [dst(gain owner), src(holder), NS]
    installed = (
        (l_cfg[:, None, None] > st.gcq_req_cfg)
        | (
            (l_cfg[:, None, None] == st.gcq_req_cfg)
            & ((l_phase == OWNED)[:, None, :])
        )
    ) & lead_any[:, None, None]
    gdelay, glost = _net_pair(knet[1], (g, g, ns))
    send_grsp = (
        (gq_arr & installed & ~glost).transpose(1, 0, 2) & (gcq_rsp_t == 0)
    )
    gcq_rsp_t = jnp.where(send_grsp, t + gdelay, gcq_rsp_t)
    gcq_rsp_cfg = jnp.where(
        send_grsp, st.gcq_req_cfg.transpose(1, 0, 2), st.gcq_rsp_cfg
    )
    gcq_req_t = jnp.where(gq_arr, 0, st.gcq_req_t)

    # ------------------------------------------- leader protocol transitions
    # (a) poll the controller: append CONFIG(node_cfg+1) once migrations for
    #     the current config are complete (no PULLING shard at the leader).
    poll = jax.random.bernoulli(kp[0], skn.p_cfg_learn, (g,))
    # Advance gate: all pulls for the current config done, AND no FROZEN
    # shard that the next config would hand back to us — its frozen copy
    # still serves the older migration; the DELETE (driven by our own
    # GC-confirm poll of the gain-config owner) must land first. No circular
    # wait: the dest's install only needs the frozen copy to exist, not our
    # config progress.
    next_owner_l = cfg_owner[
        jnp.clip(l_cfg + 1, 0, kcfg.n_configs - 1)
    ]  # [G, NS]
    regain_blocked = jnp.any(
        (l_phase == FROZEN) & (next_owner_l == my_gv[:, None]), axis=1
    )
    gate_advance = (
        lead_any
        & ~jnp.any(l_phase == PULLING, axis=1)
        & ~regain_blocked
    )
    adopt_var = jnp.zeros((g,), I32)
    cq_req_t, cq_req_node = st.cq_req_t, st.cq_req_node
    cq_req_j = st.cq_req_j
    cq_rsp_t, cq_rsp_j = st.cq_rsp_t, st.cq_rsp_j
    cq_rsp_found, cq_rsp_var = st.cq_rsp_found, st.cq_rsp_var
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        # Query protocol to the live controller: one outstanding Query per
        # group, request and response each paying a lossy delayed hop. The
        # group adopts config j = l_cfg+1 when a response says the announce
        # exists — a race: another group's response, a ctrler leader change,
        # or a restart may have shifted the ground under it.
        rsp_arr = cq_rsp_t == t
        adopt = rsp_arr & cq_rsp_found & (cq_rsp_j == l_cfg + 1)
        can_advance = gate_advance & adopt
        adopt_var = jnp.where(adopt, cq_rsp_var, 0)
        cq_rsp_t = jnp.where(rsp_arr, 0, cq_rsp_t)
        # deliver requests at ctrler nodes; an ALIVE node answers from its
        # committed prefix (follower answers model the reference's stale
        # reads of a lagging replica — safe: committed data is monotone),
        # or, under bug_stale_ctrler_read, from its raw LOG TAIL where a
        # phantom announce may sit until raft rolls it back.
        req_arr = cq_req_t == t
        node_oh = me_n[None, :] == cq_req_node[:, None]  # [G, N]
        csh_abs2 = _lane_abs(ctrl.shadow_base, cap)      # [cap]
        ann_in_win = (
            (ctrl.shadow_val > 0) & (ctrl.shadow_val != NOOP_CMD)
            & (csh_abs2 <= ctrl.shadow_len)
        )
        above = jnp.sum(
            ann_in_win[None, :] & (csh_abs2[None, :] > ctrl.commit[:, None]),
            axis=1,
        )  # [N]: committed announces this node has not yet covered
        cnt_node = jnp.clip(frontier - above, 0, frontier)  # [N]
        cnt_at = jnp.sum(jnp.where(node_oh, cnt_node[None, :], 0), axis=1)
        jreq = cq_req_j
        j_ohg = (
            jnp.arange(ncfgs, dtype=I32)[None, :]
            == jnp.clip(jreq, 0, ncfgs - 1)[:, None]
        )
        wv_req = jnp.sum(jnp.where(j_ohg, win_var[None, :], 0), axis=1)
        found_ok = (jreq <= cnt_at) & (wv_req >= 0)
        if kcfg.computed_ctrler:
            # the answer IS the replica: the group adopts the map the
            # queried replica computed (canonical when the rotate bug is
            # off; that replica's rotated chain when it is on — the 4A
            # divergence reaching a 4B group through a legal read)
            found_rep = found_ok
            var_rep = jnp.clip(cq_req_node, 0, n - 1)
        else:
            labs = _lane_abs(ctrl.base, cap)                 # [N, cap]
            lval = ctrl.log_val
            is_ann_l = (
                (lval > 0) & (lval != NOOP_CMD)
                & (labs <= ctrl.log_len[:, None])
            )
            lj = (lval - 1) // 2
            lv = (lval - 1) % 2
            m = (
                node_oh[:, :, None] & is_ann_l[None, :, :]
                & (lj[None, :, :] == jreq[:, None, None])
            )  # [G, N, cap]
            has_tail = jnp.any(m, axis=(1, 2))
            amin = jnp.min(
                jnp.where(m, labs[None, :, :], _BIG), axis=(1, 2)
            )  # the node's FIRST log occurrence of announce j
            var_tail = jnp.sum(
                jnp.where(
                    m & (labs[None, :, :] == amin[:, None, None]),
                    lv[None, :, :], 0,
                ),
                axis=(1, 2),
            )
            found_rep = jnp.where(
                skn.bug_stale_ctrler_read, has_tail | found_ok, found_ok
            )
            var_rep = jnp.where(
                skn.bug_stale_ctrler_read & has_tail,
                var_tail, jnp.maximum(wv_req, 0),
            )
        alive_at = jnp.any(node_oh & ctrl.alive[None, :], axis=1)
        rdelay, rlost = _net_pair(knet[4], (g,))
        send_rsp2 = req_arr & alive_at & ~rlost
        cq_rsp_t = jnp.where(send_rsp2, t + rdelay, cq_rsp_t)
        cq_rsp_j = jnp.where(send_rsp2, jreq, cq_rsp_j)
        cq_rsp_found = jnp.where(send_rsp2, found_rep, cq_rsp_found)
        cq_rsp_var = jnp.where(send_rsp2, var_rep, cq_rsp_var)
        cq_req_t = jnp.where(req_arr, 0, cq_req_t)
        # fresh queries from idle groups (a lost request or a dead node
        # leaves the group idle again — it simply re-polls)
        idle = (cq_req_t == 0) & (cq_rsp_t == 0)
        ask = lead_any & poll & idle & (l_cfg + 1 < ncfgs)
        tgtq = jax.random.randint(ka[4], (g,), 0, n, dtype=I32)
        qdelay, qlost = _net_pair(knet[5], (g,))
        sendq = ask & ~qlost
        cq_req_t = jnp.where(sendq, t + qdelay, cq_req_t)
        cq_req_node = jnp.where(sendq, tgtq, cq_req_node)
        cq_req_j = jnp.where(sendq, l_cfg + 1, cq_req_j)
    else:
        can_advance = gate_advance & poll & (l_cfg < active_cfg)
    # (b) pull requests for PULLING shards -> previous owner.
    want_pull = (l_phase == PULLING) & lead_any[:, None]  # [G(dst), NS]
    pull_draw = jax.random.bernoulli(kp[1], skn.p_pull, (g, ns))
    prev_owner_l = cfg_owner[jnp.clip(l_cfg - 1, 0, kcfg.n_configs - 1)]  # [G, NS]
    do_pull = want_pull & pull_draw
    tgt_oh = prev_owner_l[:, None, :] == my_gv[None, :, None]  # [dst, src, NS]
    delay2, lost2 = _net_pair(knet[2], (g, g, ns))
    send_req = do_pull[:, None, :] & tgt_oh & ~lost2
    pull_req_t = jnp.where(send_req, t + delay2, pull_req_t)
    pull_req_cfg = jnp.where(
        send_req, l_cfg[:, None, None], st.pull_req_cfg
    )
    # (c) GC-confirm polling: every FROZEN holder asks the gain-config owner
    #     whether the install landed (see the delivery comment above);
    #     retried forever at p_ack over the same lossy/delayed network, and
    #     self-contained — no per-migration bookkeeping at the new owner, so
    #     no ack window can be missed (the soak-found leak).
    gain_owner = jnp.sum(
        jnp.where(
            jnp.arange(kcfg.n_configs, dtype=I32)[None, :, None]
            == freeze_cfg[:, None, :],
            cfg_owner[None, :, :], 0,
        ),
        axis=1,
    )  # [G, NS]: owner at the holder's freeze config
    gc_draw = jax.random.bernoulli(kp[3], skn.p_ack, (g, ns))
    do_gcq = (
        (l_phase == FROZEN) & (freeze_cfg > 0) & gc_draw & lead_any[:, None]
    )
    gtgt_oh = gain_owner[:, None, :] == my_gv[None, :, None]  # [holder, dst?, NS]
    gdelay2, glost2 = _net_pair(knet[3], (g, g, ns))
    # keep-oldest: a poll in flight is not re-stamped by the next draw
    # (otherwise p_ack ~ 1/delay re-sends could starve delivery forever)
    send_gcq = (
        (do_gcq[:, None, :] & gtgt_oh).transpose(1, 0, 2) & ~glost2
        & (gcq_req_t == 0)
    )
    gcq_req_t = jnp.where(send_gcq, t + gdelay2, gcq_req_t)
    # [dst(gain owner), src(holder), NS]: the cfg is the HOLDER's epoch
    gcq_req_cfg = jnp.where(send_gcq, freeze_cfg[None, :, :], st.gcq_req_cfg)

    # --------------------------------------------------------------- clerks
    kc = jax.random.split(jax.random.fold_in(key, _S_CLERK), 6)
    sh_oh_c = sh_lane[None, :] == st.clerk_shard[:, None]  # [NC, NS]
    truth_at = jnp.sum(jnp.where(sh_oh_c, truth_count[None, :], 0), axis=1)
    is_get_c = st.clerk_kind == _GET
    # phase boundary stamps (ISSUE 12): cmt = first tick the walker
    # accepted the op, apl = first tick its Get observation landed
    clerk_cmt, clerk_apl = st.clerk_cmt, st.clerk_apl
    if cfg.metrics:
        clerk_cmt = jnp.where(
            st.clerk_out & (w_clerk_acked >= st.clerk_seq)
            & (clerk_cmt == 0),
            t, clerk_cmt,
        )
        clerk_apl = jnp.where(
            st.clerk_out & (clerk_get_obs >= 0) & (clerk_apl == 0), t,
            clerk_apl,
        )
    newly = (
        st.clerk_out & (w_clerk_acked >= st.clerk_seq)
        & (~is_get_c | (clerk_get_obs >= 0))
    )
    # Reads linearizability across migration: the observed mutation
    # version must lie in the op's [invoke, return] truth window (exact for
    # count registers — kv.py KvState docstring; the freeze/install protocol
    # makes the count well-defined across the shard's migration chain).
    done_get = newly & is_get_c
    viol |= jnp.where(
        jnp.any(
            done_get
            & ((clerk_get_obs < st.clerk_get_lo) | (clerk_get_obs > truth_at))
        ),
        VIOLATION_SHARD_STALE_READ, 0,
    )
    clerk_acked = jnp.where(newly, st.clerk_seq, st.clerk_acked)
    clerk_out = st.clerk_out & ~newly
    gets_done = st.gets_done + done_get.astype(I32)
    # metrics (ISSUE 10): fold the acked op's whole submit->ack latency —
    # stamped at op start, so config hunts, WrongGroup retries, and
    # migration stalls are all inside the measured window (kv.py fold)
    lat_hist = st.lat_hist
    phase_hist, phase_ticks, lat_ticks = (
        st.phase_hist, st.phase_ticks, st.lat_ticks
    )
    worst = (st.worst_lat, st.worst_phases, st.worst_key, st.worst_client,
             st.worst_sub)
    key_lat_hist, client_lat_hist = st.key_lat_hist, st.client_lat_hist
    cl_ids_v = jnp.arange(nc, dtype=I32)
    if cfg.metrics:
        e2e = t - st.clerk_sub
        lat_hist = fold_latencies(lat_hist, e2e, newly)
        ph = _shardkv_phase_matrix(
            t, st.clerk_sub, st.clerk_app, clerk_cmt, clerk_apl,
            st.clerk_mig, is_get_c,
        )
        phase_hist, phase_ticks, lat_ticks = fold_phases(
            phase_hist, phase_ticks, lat_ticks, ph, e2e, newly
        )
        worst = update_worst(
            worst, e2e, newly, ph, st.clerk_shard, cl_ids_v, st.clerk_sub
        )
        key_lat_hist = fold_latencies_by(key_lat_hist, e2e, newly,
                                         st.clerk_shard)
        client_lat_hist = fold_latencies_by(client_lat_hist, e2e, newly,
                                            cl_ids_v)
    # WrongGroup re-query (client.rs:16-25): a marked clerk re-learns NOW
    learn = jax.random.bernoulli(kc[0], skn.p_cfg_learn, (nc,)) | (
        skn.requery_wrong_group & st.clerk_wrong
    )
    clerk_cfg = jnp.where(
        learn, active_cfg, st.clerk_cfg
    )
    # The p_op start word is drawn at BIT level (kv.py's clerk): the
    # uniform reconstruction below matches jax.random.bernoulli's mantissa
    # path bit-identically, and the free low 9 bits are the open-loop
    # arrival draw (ISSUE 19) — zero extra PRNG draws either way.
    w_start = jax.random.bits(kc[1], (nc,))
    u_start = jax.lax.bitcast_convert_type(
        (w_start >> np.uint32(9)) | np.uint32(0x3F800000), jnp.float32
    ) - 1.0
    openloop = skn.open_queue_cap > 0
    arrive = openloop & (
        (w_start & np.uint32(0x1FF)).astype(jnp.float32)
        * jnp.float32(2.0 ** -9)
        < skn.open_rate
    )
    drop = arrive & (st.open_arr - st.open_srv >= skn.open_queue_cap)
    enq = arrive & ~drop
    open_arr = st.open_arr + enq.astype(I32)
    open_drop = st.open_drop + drop.astype(I32)
    open_stamp = st.open_stamp
    if cfg.metrics:
        slot_e = (
            jnp.arange(OPEN_QUEUE_SLOTS, dtype=I32)[None, :]
            == (st.open_arr % OPEN_QUEUE_SLOTS)[:, None]
        )
        open_stamp = jnp.where(enq[:, None] & slot_e, t, st.open_stamp)
    start = (
        ~clerk_out
        & jnp.where(openloop, open_arr > st.open_srv, u_start < skn.p_op)
        & (st.clerk_seq < _SEQ_LIM - 1)
    )
    open_srv = st.open_srv + (openloop & start).astype(I32)
    clerk_seq = jnp.where(start, st.clerk_seq + 1, st.clerk_seq)
    # hot-shard skew: zipf_map is the identity at zipf_a=1.0 (the randint
    # draw itself is unchanged either way)
    clerk_shard = jnp.where(
        start,
        zipf_map(jax.random.randint(kc[2], (nc,), 0, ns, dtype=I32),
                 ns, skn.zipf_a),
        st.clerk_shard,
    )
    u_kind = jax.random.uniform(kc[5], (nc,))
    clerk_kind = jnp.where(
        start,
        jnp.where(
            u_kind < skn.p_get,
            _GET,
            jnp.where(u_kind < skn.p_get + skn.p_put, _PUT, _APPEND),
        ),
        st.clerk_kind,
    )
    # a fresh Get captures its invoke-time truth; its observation resets
    sh_oh_new = sh_lane[None, :] == clerk_shard[:, None]
    truth_at_new = jnp.sum(jnp.where(sh_oh_new, truth_count[None, :], 0), axis=1)
    clerk_get_lo = jnp.where(start, truth_at_new, st.clerk_get_lo)
    clerk_get_obs = jnp.where(start, -1, clerk_get_obs)
    clerk_sub = st.clerk_sub
    clerk_app, clerk_mig = st.clerk_app, st.clerk_mig
    if cfg.metrics:
        # submit stamp: open-loop dequeues read the op's ARRIVAL tick from
        # the stamp ring (same-tick arrive->start reads the stamp just
        # written, i.e. t) so queue wait is measured; closed loop stamps NOW
        slot_d = (
            jnp.arange(OPEN_QUEUE_SLOTS, dtype=I32)[None, :]
            == (st.open_srv % OPEN_QUEUE_SLOTS)[:, None]
        )
        arr_t = jnp.sum(jnp.where(slot_d, open_stamp, 0), axis=1)
        clerk_sub = jnp.where(start, jnp.where(openloop, arr_t, t),
                              clerk_sub)
        clerk_app = jnp.where(start, 0, clerk_app)
        clerk_cmt = jnp.where(start, 0, clerk_cmt)
        clerk_apl = jnp.where(start, 0, clerk_apl)
        clerk_mig = jnp.where(start, 0, clerk_mig)
    clerk_out = clerk_out | start
    retry = clerk_out & (start | jax.random.bernoulli(kc[3], skn.p_retry, (nc,)))
    client_retries = st.client_retries
    if cfg.metrics:
        client_retries = client_retries + retry.astype(I32)
    tgt_node = jax.random.randint(kc[4], (nc,), 0, n, dtype=I32)

    # ---------------------------- service-layer log appends (post-raft-tick)
    log_term, log_val, log_len = s.log_term, s.log_val, s.log_len

    def append_at(mask_gn, value_gn, log_term, log_val, log_len):
        """Append value at nodes where mask (leader-gated by caller). Room is
        re-derived from the running log_len — several appends can land at one
        node in one tick. The flow-control gate (config.py uncommitted_cap)
        bounds the uncommitted backlog so retry spam can never wedge the ring
        against the current-term commit rule."""
        ok = (
            mask_gn & s.alive
            & (log_len - s.base < cap)
            & (log_len - s.commit < kn.flow_cap)
        )
        hit = ok[..., None] & (
            jnp.arange(cap, dtype=I32)[None, None, :]
            == _slot(log_len + 1, cap)[..., None]
        )
        log_term = jnp.where(hit, s.term[..., None], log_term)
        log_val = jnp.where(hit, value_gn[..., None], log_val)
        log_len = jnp.where(ok, log_len + 1, log_len)
        # ok is returned so metrics stamp sites read the REAL acceptance
        # mask (a re-derived copy could silently drift from this gate)
        return log_term, log_val, log_len, ok

    # CONFIG advance at the (single chosen) leader node; the entry records
    # which announce variant (live-ctrler) or controller replica
    # (computed-ctrler) the group adopted.
    cfg_val = _pack_config(
        node_cfg + 1, adopt_var[:, None],
        src_lim=n if kcfg.computed_ctrler else 2,
    )  # [G, N]
    log_term, log_val, log_len, _ = append_at(
        ln_oh & can_advance[:, None] & is_lead, cfg_val,
        log_term, log_val, log_len,
    )
    # INSTALL entries: leader appends for PULLING shards whose staging holds a
    # payload for its current config.
    have_stage = staged_cfg == l_cfg[:, None]
    inst_ready = want_pull & have_stage  # [G, NS]
    for sh in range(ns):
        v = _pack_install(kcfg, node_cfg, jnp.asarray(sh, I32))
        log_term, log_val, log_len, _ = append_at(
            ln_oh & inst_ready[:, sh:sh + 1] & is_lead, v,
            log_term, log_val, log_len,
        )
    # DELETE entries at the old owner on ack.
    for sh in range(ns):
        v = _pack_delete(kcfg, ack_del_cfg[:, sh][:, None], jnp.asarray(sh, I32))
        log_term, log_val, log_len, _ = append_at(
            ln_oh & ack_del[:, sh:sh + 1] & is_lead, v,
            log_term, log_val, log_len,
        )
    # Bug mode: the contacted node skips the ownership check for reads and
    # serves a Get on a non-OWNED shard immediately from whatever local copy
    # it has — a FROZEN surrendered copy (missing every append the new owner
    # accepted since the freeze) or nothing at all after GC. The interval
    # oracle must flag any observation below the invoke-time truth.
    owner_of = cfg_owner[jnp.clip(clerk_cfg, 0, kcfg.n_configs - 1)]  # [NC, NS]
    grp_c = jnp.sum(jnp.where(sh_oh_new, owner_of, 0), axis=1)  # [NC]
    sel4 = (
        (gids_v[None, :, None, None] == grp_c[:, None, None, None])
        & (me_n[None, None, :, None] == tgt_node[:, None, None, None])
        & (sh_lane[None, None, None, :] == clerk_shard[:, None, None, None])
    )  # [NC, G, N, NS]
    ph_at = jnp.sum(jnp.where(sel4, phase[None], 0), axis=(1, 2, 3))
    cnt_at = jnp.sum(jnp.where(sel4, key_count[None], 0), axis=(1, 2, 3))
    alive_at = jnp.any(jnp.any(sel4, axis=-1) & s.alive[None], axis=(1, 2))
    served = (
        skn.bug_serve_frozen
        & retry & ~start & (clerk_kind == _GET) & alive_at & (ph_at != OWNED)
    )
    viol |= jnp.where(
        jnp.any(
            served & ((cnt_at < clerk_get_lo) | (cnt_at > truth_at_new))
        ),
        VIOLATION_SHARD_STALE_READ, 0,
    )
    clerk_acked = jnp.where(served, clerk_seq, clerk_acked)
    clerk_out = clerk_out & ~served
    gets_done = gets_done + served.astype(I32)
    retry = retry & ~served
    if cfg.metrics:
        # the bug-mode local serve is an ack too (served requires ~start,
        # so the op's stamp predates this tick's start update); a local
        # serve skips the log, so its whole latency is the apply phase
        e2e_s = t - clerk_sub
        lat_hist = fold_latencies(lat_hist, e2e_s, served)
        zeros = jnp.zeros_like(e2e_s)
        ph_s = jnp.stack([zeros, zeros, e2e_s, zeros, zeros])
        phase_hist, phase_ticks, lat_ticks = fold_phases(
            phase_hist, phase_ticks, lat_ticks, ph_s, e2e_s, served
        )
        worst = update_worst(
            worst, e2e_s, served, ph_s, clerk_shard, cl_ids_v, clerk_sub
        )
        key_lat_hist = fold_latencies_by(key_lat_hist, e2e_s, served,
                                         clerk_shard)
        client_lat_hist = fold_latencies_by(client_lat_hist, e2e_s, served,
                                            cl_ids_v)
    # WrongGroup detection (client.rs:16-25): this submit reached an alive
    # LEADER of the believed owner group and the shard is not serving there
    # — the clerk is marked and (under requery_wrong_group) re-learns the
    # config next tick instead of waiting for its p_cfg_learn draw.
    tgt_gn = jnp.any(sel4, axis=-1)  # [NC, G, N]
    lead_at_c = jnp.any(tgt_gn & is_lead[None], axis=(1, 2))
    clerk_wrong = jnp.where(
        retry, lead_at_c & (ph_at != OWNED), st.clerk_wrong & ~learn
    )
    if cfg.metrics:
        # migration/WrongGroup wait (ISSUE 12): a pre-append tick spent
        # marked WrongGroup is attributed to the migration phase (carved
        # out of leader_wait; bounded by the pre-append window, so the
        # phase sum stays exact)
        clerk_mig = jnp.where(
            clerk_out & (clerk_app == 0) & clerk_wrong, clerk_mig + 1,
            clerk_mig,
        )

    # Client ops at the believed owner's targeted node (leader-gated; a wrong
    # or stale guess commits nothing or a rejected entry — the clerk retries).
    landed = []
    for c in range(nc):
        sel = (
            (gids_v[:, None] == grp_c[c])
            & (me_n[None, :] == tgt_node[c])
            & is_lead
        )
        v = _pack_op(kcfg, jnp.asarray(c, I32), clerk_seq[c], clerk_shard[c],
                     clerk_kind[c])
        log_term, log_val, log_len, ok_c = append_at(
            sel & retry[c], jnp.broadcast_to(v, (g, n)),
            log_term, log_val, log_len,
        )
        landed.append(jnp.any(ok_c))
    if cfg.metrics:
        # leader_wait boundary (kv.py submit-loop treatment): the first
        # tick ANY group's leader accepted the op — wrong-group appends
        # count (the hunt ended; their rejection wait lands in replicate)
        clerk_app = jnp.where(
            jnp.stack(landed) & clerk_out & (clerk_app == 0), t, clerk_app
        )

    violations = st.violations | viol
    first_violation_tick = jnp.where(
        (st.first_violation_tick < 0) & (viol != 0), t, st.first_violation_tick
    )

    rafts = s._replace(
        log_term=log_term, log_val=log_val, log_len=log_len,
        durable_len=durable_after_append(s, log_len),
        compact_floor=applied,
    )
    return ShardKvState(
        rafts=rafts,
        cfg_tick=st.cfg_tick, cfg_owner=cfg_owner,
        ctrl=ctrl, ctrl_w_frontier=ctrl_w_frontier,
        ctrl_w_stalled=ctrl_w_stalled, win_var=win_var,
        flip_a=st.flip_a, flip_b=st.flip_b, slot_tick=slot_tick,
        cmem=cmem, ctrl_node_owner=ctrl_node_owner, ctrl_maps=ctrl_maps,
        node_src=node_src, snap_src=snap_src, w_src=w_src,
        cq_req_t=cq_req_t, cq_req_node=cq_req_node, cq_req_j=cq_req_j,
        cq_rsp_t=cq_rsp_t, cq_rsp_j=cq_rsp_j,
        cq_rsp_found=cq_rsp_found, cq_rsp_var=cq_rsp_var,
        applied=applied, node_cfg=node_cfg, phase=phase,
        key_hash=key_hash, key_count=key_count, last_seq=last_seq,
        snap_cfg=snap_cfg, snap_phase=snap_phase,
        snap_hash=snap_hash, snap_count=snap_count,
        snap_last_seq=snap_last_seq,
        staged_cfg=staged_cfg, staged_hash=staged_hash,
        staged_count=staged_count, staged_last_seq=staged_last_seq,
        pull_req_t=pull_req_t, pull_req_cfg=pull_req_cfg,
        pull_rsp_t=pull_rsp_t, pull_rsp_cfg=pull_rsp_cfg,
        pull_rsp_hash=pull_rsp_hash, pull_rsp_count=pull_rsp_count,
        pull_rsp_last_seq=pull_rsp_last_seq,
        gcq_req_t=gcq_req_t, gcq_req_cfg=gcq_req_cfg,
        gcq_rsp_t=gcq_rsp_t, gcq_rsp_cfg=gcq_rsp_cfg,
        clerk_seq=clerk_seq, clerk_out=clerk_out,
        clerk_shard=clerk_shard, clerk_kind=clerk_kind, clerk_cfg=clerk_cfg,
        clerk_wrong=clerk_wrong, clerk_acked=clerk_acked,
        clerk_get_lo=clerk_get_lo, clerk_get_obs=clerk_get_obs,
        gets_done=gets_done,
        open_arr=open_arr, open_srv=open_srv, open_drop=open_drop,
        open_stamp=open_stamp,
        clerk_sub=clerk_sub, lat_hist=lat_hist,
        clerk_app=clerk_app, clerk_cmt=clerk_cmt, clerk_apl=clerk_apl,
        clerk_mig=clerk_mig, client_retries=client_retries,
        phase_hist=phase_hist, phase_ticks=phase_ticks, lat_ticks=lat_ticks,
        worst_lat=worst[0], worst_phases=worst[1], worst_key=worst[2],
        worst_client=worst[3], worst_sub=worst[4],
        key_lat_hist=key_lat_hist, client_lat_hist=client_lat_hist,
        w_frontier=w_frontier, w_cfg=w_cfg, w_phase=w_phase,
        w_hash=w_hash, w_count=w_count, w_last_seq=w_last_seq,
        frz_cfg=frz_cfg, frz_hash=frz_hash,
        frz_count=frz_count, frz_last_seq=frz_last_seq,
        truth_count=truth_count, w_clerk_acked=w_clerk_acked,
        installs_done=installs_done, deletes_done=deletes_done,
        max_cfg_lag=max_cfg_lag,
        violations=violations, first_violation_tick=first_violation_tick,
    )


# ---------------------------------------------------------------------------
# Packed deployment carry (ISSUE 11) — the real shardkv footprint
# multiplier: per-deployment tensors up to [G, N, NS, NC] wide i32, plus G
# embedded raft clusters. Same exact-or-wide contract as kv.py; the
# deployment-level additions:
#
#   - the G group rafts pack with a service-rate index bound (a tick can
#     append 1 no-op + 1 CONFIG + NS INSTALL + NS DELETE + NC client ops
#     per node) and the shardkv op packing's cmd bound;
#   - the controller cluster packs with its own (tiny) bounds via
#     _ctrl_sim_cfg — announce values are the only commands it carries;
#   - inter-group mailbox stamps (pull/GC/query) store tick-RELATIVE u8
#     exactly like the in-group mailboxes (0 = empty; gated on the pull
#     delay knobs);
#   - per-shard counts bound by n_clients x seq (each accepted mutation is
#     a distinct (client, seq)); bug_drop_dup_table breaks that bound by
#     re-applying migrated ops, so it gates the run to the wide layout.
# ---------------------------------------------------------------------------

# Raft fields the service tick writes back into the group rafts / the
# controller cluster (deployment-level violations live outside the rafts).
_SKV_RAFT_WRITES = (
    "log_term", "log_val", "log_len", "durable_len", "compact_floor",
)
_SKV_CTRL_WRITES = ("log_term", "log_val", "log_len", "durable_len")

# Inter-group mailbox delivery stamps, stored tick-relative u8 when packed.
_SKV_REL_FIELDS = (
    "pull_req_t", "pull_rsp_t", "gcq_req_t", "gcq_rsp_t",
    "cq_req_t", "cq_rsp_t",
)


@functools.lru_cache(maxsize=None)
def shardkv_packed_layout(cfg: SimConfig, kcfg: ShardKvConfig) -> tuple:
    """(group-raft PackedSpec, controller PackedSpec, service field ->
    dtype table) for one static (SimConfig, ShardKvConfig) pair — the one
    place the deployment widths derive (kv_packed_layout contract)."""
    b = packed_bounds(cfg)
    g, ns = kcfg.n_groups, kcfg.n_shards
    nc, ncfg = kcfg.n_clients, kcfg.n_configs
    seq_bound = min(b.tick, _SEQ_LIM - 1)
    # appends per node per tick: leader no-op + CONFIG + NS installs + NS
    # deletes + NC client ops (append_at re-derives room per append)
    idx_bound = (nc + 2 * ns + 2) * b.tick + 1
    cmd_bound = _pack_op(kcfg, nc - 1, _SEQ_LIM - 1, ns - 1, 7)
    sp = packed_spec_for(cfg, index_bound=idx_bound, cmd_bound=cmd_bound)
    # controller: at most 2 announce appends + a leader no-op per tick, and
    # announce values are (slot, variant|gid) pairs
    csp = packed_spec_for(
        _ctrl_sim_cfg(cfg, kcfg), index_bound=3 * b.tick + 1,
        cmd_bound=ncfg * max(g, 2),
    )
    seq = uint_for(seq_bound)
    cnt = uint_for(nc * seq_bound)   # distinct (client, seq) per shard
    obs = sint_for(nc * seq_bound)   # -1 sentinel + count range
    num = uint_for(ncfg)             # config indices (>= 0 forms)
    num_s = sint_for(ncfg)           # config indices with a -1 sentinel
    gid = jnp.int8                   # group/replica ids (-1 capacity)
    dts = {
        "cfg_owner": gid,
        "ctrl_w_frontier": csp.index,
        "ctrl_w_stalled": BOOL,
        "win_var": sint_for(max(g, 2)),
        "flip_a": gid,
        "flip_b": gid,
        "slot_tick": sp.tick_signed,
        "cmem": BOOL,
        "ctrl_node_owner": gid,
        "ctrl_maps": gid,
        "node_src": gid,
        "snap_src": gid,
        "w_src": gid,
        "cq_req_node": gid,
        "cq_req_j": num,
        "cq_rsp_j": num,
        "cq_rsp_found": BOOL,
        "cq_rsp_var": U8,
        "applied": sp.index,
        "node_cfg": num,
        "phase": U8,
        "key_hash": I32,
        "key_count": cnt,
        "last_seq": seq,
        "snap_cfg": num,
        "snap_phase": U8,
        "snap_hash": I32,
        "snap_count": cnt,
        "snap_last_seq": seq,
        "staged_cfg": num_s,
        "staged_hash": I32,
        "staged_count": cnt,
        "staged_last_seq": seq,
        "pull_req_cfg": num,
        "pull_rsp_cfg": num,
        "pull_rsp_hash": I32,
        "pull_rsp_count": cnt,
        "pull_rsp_last_seq": seq,
        "gcq_req_cfg": num,
        "gcq_rsp_cfg": num,
        "clerk_seq": seq,
        "clerk_out": BOOL,
        "clerk_shard": uint_for(ns - 1),
        "clerk_kind": U8,
        "clerk_cfg": num,
        "clerk_wrong": BOOL,
        "clerk_acked": seq,
        "clerk_get_lo": cnt,
        "clerk_get_obs": obs,
        "gets_done": sp.tick,
        "open_arr": sp.tick,         # <= 1 arrival per clerk per tick
        "open_srv": sp.tick,
        "open_drop": sp.tick,
        "open_stamp": sp.tick,       # absolute arrival ticks
        "clerk_sub": sp.tick,
        "lat_hist": cnt,             # acked ops are distinct (client, seq)
        # attribution plane (ISSUE 12)
        "clerk_app": sp.tick,
        "clerk_cmt": sp.tick,
        "clerk_apl": sp.tick,
        "clerk_mig": sp.tick,        # bounded by elapsed ticks
        "client_retries": sp.tick,   # at most one attempt per tick
        "phase_hist": cnt,           # bucket counts <= acked ops
        "phase_ticks": I32,          # sums of latencies: full width
        "lat_ticks": I32,
        "worst_lat": sp.tick,
        "worst_phases": sp.tick,
        "worst_key": I32,            # -1 sentinel; full width by design
        "worst_client": I32,
        "worst_sub": sp.tick,
        "key_lat_hist": cnt,
        "client_lat_hist": cnt,
        "w_frontier": sp.index,
        "w_cfg": num,
        "w_phase": U8,
        "w_hash": I32,
        "w_count": cnt,
        "w_last_seq": seq,
        "frz_cfg": num_s,
        "frz_hash": I32,
        "frz_count": cnt,
        "frz_last_seq": seq,
        "truth_count": cnt,
        "w_clerk_acked": seq,
        "installs_done": I32,        # walked-marker totals: unbounded by
        "deletes_done": I32,         # any per-op rule — full width
        "max_cfg_lag": num,
        "violations": I32,
        "first_violation_tick": sp.tick_signed,
    }
    return sp, csp, dts


class PackedShardKvState(NamedTuple):
    """ShardKvState in the packed schema: G packed raft clusters, a packed
    controller cluster, rel-u8 inter-group mailbox stamps, and every other
    field narrowed per shardkv_packed_layout. cfg_tick stays i32 — its
    bound rides the cfg_interval knob and the array is [NCFG] tiny."""

    rafts: PackedClusterState        # every leaf has leading axis [G]
    cfg_tick: jax.Array              # i32, kept wide
    cfg_owner: jax.Array
    ctrl: PackedClusterState
    ctrl_w_frontier: jax.Array
    ctrl_w_stalled: jax.Array
    win_var: jax.Array
    flip_a: jax.Array
    flip_b: jax.Array
    slot_tick: jax.Array
    cmem: jax.Array
    ctrl_node_owner: jax.Array
    ctrl_maps: jax.Array
    node_src: jax.Array
    snap_src: jax.Array
    w_src: jax.Array
    cq_req_t: jax.Array              # rel u8 stamps (0 = empty)
    cq_req_node: jax.Array
    cq_req_j: jax.Array
    cq_rsp_t: jax.Array
    cq_rsp_j: jax.Array
    cq_rsp_found: jax.Array
    cq_rsp_var: jax.Array
    applied: jax.Array
    node_cfg: jax.Array
    phase: jax.Array
    key_hash: jax.Array
    key_count: jax.Array
    last_seq: jax.Array
    snap_cfg: jax.Array
    snap_phase: jax.Array
    snap_hash: jax.Array
    snap_count: jax.Array
    snap_last_seq: jax.Array
    staged_cfg: jax.Array
    staged_hash: jax.Array
    staged_count: jax.Array
    staged_last_seq: jax.Array
    pull_req_t: jax.Array
    pull_req_cfg: jax.Array
    pull_rsp_t: jax.Array
    pull_rsp_cfg: jax.Array
    pull_rsp_hash: jax.Array
    pull_rsp_count: jax.Array
    pull_rsp_last_seq: jax.Array
    gcq_req_t: jax.Array
    gcq_req_cfg: jax.Array
    gcq_rsp_t: jax.Array
    gcq_rsp_cfg: jax.Array
    clerk_seq: jax.Array
    clerk_out: jax.Array
    clerk_shard: jax.Array
    clerk_kind: jax.Array
    clerk_cfg: jax.Array
    clerk_wrong: jax.Array
    clerk_acked: jax.Array
    clerk_get_lo: jax.Array
    clerk_get_obs: jax.Array
    gets_done: jax.Array
    open_arr: jax.Array
    open_srv: jax.Array
    open_drop: jax.Array
    open_stamp: jax.Array
    clerk_sub: jax.Array
    lat_hist: jax.Array
    clerk_app: jax.Array
    clerk_cmt: jax.Array
    clerk_apl: jax.Array
    clerk_mig: jax.Array
    client_retries: jax.Array
    phase_hist: jax.Array
    phase_ticks: jax.Array
    lat_ticks: jax.Array
    worst_lat: jax.Array
    worst_phases: jax.Array
    worst_key: jax.Array
    worst_client: jax.Array
    worst_sub: jax.Array
    key_lat_hist: jax.Array
    client_lat_hist: jax.Array
    w_frontier: jax.Array
    w_cfg: jax.Array
    w_phase: jax.Array
    w_hash: jax.Array
    w_count: jax.Array
    w_last_seq: jax.Array
    frz_cfg: jax.Array
    frz_hash: jax.Array
    frz_count: jax.Array
    frz_last_seq: jax.Array
    truth_count: jax.Array
    w_clerk_acked: jax.Array
    installs_done: jax.Array
    deletes_done: jax.Array
    max_cfg_lag: jax.Array
    violations: jax.Array
    first_violation_tick: jax.Array


def _rel_pack(st, t):
    """Inter-group mailbox stamps -> tick-relative u8 (0 = empty). Every
    live stamp is strictly in the future at the carry boundary (arrivals
    are consumed and zeroed at stamp == t) and the pull-delay gate bounds
    rel in [1, 254]."""
    return {
        f: jnp.where(getattr(st, f) > 0, getattr(st, f) - t, 0).astype(U8)
        for f in _SKV_REL_FIELDS
    }


def _rel_unpack(p, t):
    out = {}
    for f in _SKV_REL_FIELDS:
        r32 = getattr(p, f).astype(I32)
        out[f] = jnp.where(r32 > 0, t + r32, 0)
    return out


def pack_shardkv_state(cfg: SimConfig, kcfg: ShardKvConfig,
                       st: ShardKvState) -> PackedShardKvState:
    sp, csp, dts = shardkv_packed_layout(cfg, kcfg)
    t = st.rafts.tick[0]  # groups tick in lockstep
    return PackedShardKvState(
        rafts=jax.vmap(lambda r: pack_state(cfg, r, sp))(st.rafts),
        ctrl=pack_state(_ctrl_sim_cfg(cfg, kcfg), st.ctrl, csp),
        cfg_tick=st.cfg_tick,
        **_rel_pack(st, t),
        **pack_fields(st, dts),
    )


def unpack_shardkv_state(cfg: SimConfig, kcfg: ShardKvConfig,
                         p: PackedShardKvState) -> ShardKvState:
    sp, csp, dts = shardkv_packed_layout(cfg, kcfg)
    rafts = jax.vmap(lambda r: unpack_state(cfg, r, sp))(p.rafts)
    t = rafts.tick[0]
    return ShardKvState(
        rafts=rafts,
        ctrl=unpack_state(_ctrl_sim_cfg(cfg, kcfg), p.ctrl, csp),
        cfg_tick=p.cfg_tick,
        **_rel_unpack(p, t),
        **unpack_fields(p, dts),
    )


def shardkv_packed_layout_reason(cfg: SimConfig, kcfg: ShardKvConfig,
                                 kn, skn,
                                 ticks_needed: int) -> Optional[str]:
    """None when the packed deployment schema is exact for this run — else
    the wide-fallback reason (state.packed_layout_reason plus the
    shardkv-layer gates on the inter-group network and the dup-table bug)."""
    r = packed_layout_reason(cfg, kn, ticks_needed)
    if r is not None:
        return r
    k = jax.tree.map(np.asarray, skn)
    b = packed_bounds(cfg)
    if (k.pull_delay_max > b.rel_stamp - 1).any():
        return (
            f"pull_delay_max {k.pull_delay_max} > {b.rel_stamp - 1}: "
            "inter-group mailbox stamps are stored tick-relative in one u8"
        )
    if (k.pull_delay_min < 1).any():
        return (
            f"pull_delay_min {k.pull_delay_min} < 1: a same-tick stamp "
            "would pack as an empty mailbox slot"
        )
    if k.bug_drop_dup_table.any():
        return (
            "bug_drop_dup_table re-applies migrated ops, so the per-shard "
            "count bound (n_clients x seq) no longer holds"
        )
    return None


def shardkv_step_packed(
    cfg: SimConfig, kcfg: ShardKvConfig, pst: PackedShardKvState,
    cluster_key: jax.Array, kn=None, skn=None,
) -> PackedShardKvState:
    """One deployment tick over the PACKED carry; with cfg.fuse_packed_step
    the composition is per field group (the kv_step_packed contract): the G
    group rafts and the controller cluster stay packed across their step
    boundaries — only the fields the service writes (_SKV_RAFT_WRITES /
    _SKV_CTRL_WRITES) re-pack, and a mode-off controller passes through
    without ever widening."""
    if kn is None:
        _check_shardkv_cfg(cfg)
        kn = cfg.knobs()
    if skn is None:
        skn = kcfg.knobs()
    if not cfg.fuse_packed_step:
        return pack_shardkv_state(cfg, kcfg, shardkv_step(
            cfg, kcfg, unpack_shardkv_state(cfg, kcfg, pst), cluster_key,
            kn, skn,
        ))
    sp, csp, dts = shardkv_packed_layout(cfg, kcfg)
    ctrl_cfg = _ctrl_sim_cfg(cfg, kcfg)
    pre = jax.vmap(lambda r: unpack_state(cfg, r, sp))(pst.rafts)
    gkeys = jax.vmap(lambda i: jax.random.fold_in(cluster_key, _S_GROUP + i))(
        jnp.arange(kcfg.n_groups)
    )
    ps = jax.vmap(
        lambda r, k: pack_state(cfg, step_cluster(cfg, r, k, kn), sp)
    )(pre, gkeys)
    s = jax.vmap(lambda r: unpack_state(cfg, r, sp))(ps)
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        pctrl = pack_state(ctrl_cfg, step_cluster(
            cfg, unpack_state(ctrl_cfg, pst.ctrl, csp),
            jax.random.fold_in(cluster_key, _S_CTRL), kn,
        ), csp)
    else:
        pctrl = pst.ctrl
    ctrl = unpack_state(ctrl_cfg, pctrl, csp)  # mode off: a pure DCE view
    st = ShardKvState(
        rafts=s, ctrl=ctrl, cfg_tick=pst.cfg_tick,
        **_rel_unpack(pst, pre.tick[0]),
        **unpack_fields(pst, dts),
    )
    nst = _shardkv_service_tick(cfg, kcfg, st, pre.alive, pre.base, s, ctrl,
                                cluster_key, kn, skn)
    pw = jax.vmap(lambda r: pack_state(cfg, r, sp))(nst.rafts)
    rafts = ps._replace(**{f: getattr(pw, f) for f in _SKV_RAFT_WRITES})
    if kcfg.live_ctrler or kcfg.computed_ctrler:
        pwc = pack_state(ctrl_cfg, nst.ctrl, csp)
        pctrl = pctrl._replace(
            **{f: getattr(pwc, f) for f in _SKV_CTRL_WRITES}
        )
    return PackedShardKvState(
        rafts=rafts, ctrl=pctrl, cfg_tick=nst.cfg_tick,
        **_rel_pack(nst, nst.rafts.tick[0]),
        **pack_fields(nst, dts),
    )


# ------------------------------------------------------------------- drivers
class ShardKvFuzzReport(NamedTuple):
    violations: np.ndarray            # deployment-level bitmask
    raft_violations: np.ndarray       # OR over the deployment's groups
    first_violation_tick: np.ndarray
    acked_ops: np.ndarray
    acked_gets: np.ndarray            # completed Gets (read-path workload)
    installs: np.ndarray              # completed shard migrations
    deletes: np.ndarray               # completed shard GCs
    final_cfg: np.ndarray             # min walker config across groups
    owned_copies: np.ndarray          # per-deployment max owners of any shard
    frozen_left: np.ndarray           # frozen copies remaining at the end
    max_cfg_lag: np.ndarray           # max configs a restarting node missed
    ann_resolved: np.ndarray          # live-ctrler: committed announce
    #                                   frontier (0 when the mode is off)
    ctrl_walker_stalled: np.ndarray   # live-ctrler: oracle coverage lost
    #                                   (sticky; False when the mode is off)
    # metrics plane (ISSUE 10): per-deployment clerk submit->ack histograms
    # and liveness counters summed over the deployment's group rafts (plus
    # the live controller cluster); None with cfg.metrics off
    lat_hist: Optional[np.ndarray] = None
    ev_counts: Optional[np.ndarray] = None
    # attribution plane (ISSUE 12): 5-phase decomposition
    # (config.SHARDKV_PHASES — migration is the extra row), the
    # per-shard/per-client axes, and the worst-op registers (key = shard)
    phase_hist: Optional[np.ndarray] = None
    phase_ticks: Optional[np.ndarray] = None
    lat_ticks: Optional[np.ndarray] = None
    key_hist: Optional[np.ndarray] = None
    client_hist: Optional[np.ndarray] = None
    client_retries: Optional[np.ndarray] = None
    worst_lat: Optional[np.ndarray] = None
    worst_phases: Optional[np.ndarray] = None
    worst_key: Optional[np.ndarray] = None
    worst_client: Optional[np.ndarray] = None
    worst_sub: Optional[np.ndarray] = None

    @property
    def n_violating(self) -> int:
        return int(((self.violations | self.raft_violations) != 0).sum())

    def violating_clusters(self) -> np.ndarray:
        return np.nonzero((self.violations | self.raft_violations) != 0)[0]


@functools.lru_cache(maxsize=None)
def _shardkv_program(
    static_cfg: SimConfig, static_kcfg: ShardKvConfig, n_clusters: int,
    mesh: Optional[Mesh], per_cluster_knobs: bool = False,
    packed: bool = False,
):
    """One compiled program per static shape; every probability, interval,
    and bug mode is a runtime knob (uniform scalars — the fast layout; the
    per-cluster layout serves make_shardkv_sweep_fn). Before the knob split
    this layer rebuilt an uncached jit closure per make_shardkv_fuzz_fn
    call, recompiling for every (config, call site) pair. With ``packed``
    the fori carry is the PackedShardKvState (ISSUE 11; separate cached
    program, wide final returned)."""
    constraint = None
    if mesh is not None:
        constraint = NamedSharding(mesh, P(mesh.axis_names[0]))
    kn_ax = 0 if per_cluster_knobs else None
    step_fn = shardkv_step_packed if packed else shardkv_step

    def run(seed, kn, skn, n_ticks) -> ShardKvState:
        base = jax.random.PRNGKey(seed)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(n_clusters)
        )
        states = jax.vmap(
            functools.partial(init_shardkv_cluster, static_cfg, static_kcfg),
            in_axes=(0, kn_ax, kn_ax),
        )(keys, kn, skn)
        if packed:
            states = jax.vmap(
                functools.partial(pack_shardkv_state, static_cfg,
                                  static_kcfg)
            )(states)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys = jax.lax.with_sharding_constraint(keys, constraint)
            if per_cluster_knobs:
                kn, skn = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, constraint),
                    (kn, skn),
                )

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_fn, static_cfg, static_kcfg),
                in_axes=(0, 0, kn_ax, kn_ax),
            )(carry, keys, kn, skn)

        final = jax.lax.fori_loop(0, n_ticks, body, states)
        if packed:
            final = jax.vmap(
                functools.partial(unpack_shardkv_state, static_cfg,
                                  static_kcfg)
            )(final)
        return final

    return jax.jit(run)


def _shardkv_layout_telemetry(fn, cfg, kcfg, n_clusters, packed, layout,
                              reason):
    # here ``bytes_per_lane`` is bytes per DEPLOYMENT — this layer's lane
    return attach_layout_telemetry(
        fn, n_clusters, packed, layout, reason,
        lambda: pack_shardkv_state(
            cfg, kcfg, init_shardkv_cluster(cfg, kcfg, jax.random.PRNGKey(0))
        ),
    )


def make_shardkv_fuzz_fn(
    cfg: SimConfig,
    kcfg: ShardKvConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    pack_states: Optional[bool] = None,
):
    """Build a jitted fn(seed) -> final batched ShardKvState
    (``pack_states`` follows the make_kv_fuzz_fn exact-or-wide contract)."""
    _check_shardkv_cfg(cfg)
    kn = cfg.knobs()
    skn = kcfg.knobs()
    reason = shardkv_packed_layout_reason(cfg, kcfg, kn, skn, n_ticks)
    packed, layout = choose_layout_from_reason(reason, pack_states)
    prog = _shardkv_program(cfg.static_key(), kcfg.static_key(), n_clusters,
                            mesh, False, packed)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    # uint32 coercion: keep the (seed, cluster_id) replay contract under x64
    fn = FuzzProgram(
        prog,
        lambda seed: (jnp.asarray(seed, jnp.uint32), kn, skn, ticks),
    )
    return _shardkv_layout_telemetry(fn, cfg, kcfg, n_clusters, packed,
                                     layout, reason)


def _validate_shardkv_knobs(skn) -> None:
    """Eager rejection of knob values that would silently misbehave inside
    the compiled program (the engine._validate_knobs analogue)."""
    from madraft_tpu.tpusim.engine import validate_bool_bugs, validate_probs

    k = jax.tree.map(np.asarray, skn)
    validate_probs(
        k, ("p_op", "p_get", "p_put", "p_retry", "p_cfg_learn", "p_pull",
            "p_ack", "pull_loss", "p_announce", "p_phantom", "open_rate"),
        "shardkv",
    )
    if (k.p_get + k.p_put > 1.0).any():
        raise ValueError("p_get + p_put must stay <= 1 per deployment")
    if ((k.open_queue_cap < 0) | (k.open_queue_cap > OPEN_QUEUE_SLOTS)).any():
        raise ValueError(
            f"open_queue_cap must stay in [0, {OPEN_QUEUE_SLOTS}] (the "
            "arrival-stamp ring size; 0 = closed loop)"
        )
    if (k.zipf_a < 1.0).any():
        raise ValueError("zipf_a must be >= 1.0 (1.0 = the uniform draw)")
    if (k.pull_delay_max < k.pull_delay_min).any() or (
        k.pull_delay_min < 1
    ).any():
        raise ValueError(
            f"pull delay span empty: [{k.pull_delay_min}, {k.pull_delay_max}]"
        )
    if (k.cfg_interval < 2).any():
        raise ValueError(f"cfg_interval must be >= 2: {k.cfg_interval}")
    validate_bool_bugs(
        k, ("bug_skip_freeze", "bug_drop_dup_table", "bug_serve_frozen",
            "bug_stale_ctrler_read", "bug_rotate_tiebreak",
            "requery_wrong_group"),
        "shardkv",
    )


def make_shardkv_sweep_fn(
    cfg: SimConfig,
    knobs,   # config.Knobs, uniform or with leading [n_clusters] axes
    sknobs,  # ShardKvKnobs, uniform or with leading [n_clusters] axes
    kcfg: ShardKvConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    pack_states: Optional[bool] = None,
):
    """Like make_shardkv_fuzz_fn, but every deployment runs its own raft AND
    service knobs — reconfiguration cadence, workload mix, inter-group
    network, and the planted migration bugs become per-deployment data.
    The layout gate sees the whole knob matrix (e.g. any deployment running
    bug_drop_dup_table sends the sweep to the wide carry)."""
    from madraft_tpu.tpusim.engine import (
        _validate_knobs,
        validate_service_raft_knobs,
    )

    _check_shardkv_cfg(cfg)
    _validate_knobs(knobs)
    validate_service_raft_knobs(knobs)
    _validate_shardkv_knobs(sknobs)
    if not kcfg.computed_ctrler and bool(
        np.asarray(sknobs.bug_rotate_tiebreak).any()
    ):
        raise ValueError(
            "bug_rotate_tiebreak (sweep knob) needs kcfg.computed_ctrler "
            "— without the computed controller it would silently do nothing"
        )
    reason = shardkv_packed_layout_reason(cfg, kcfg, knobs, sknobs, n_ticks)
    packed, layout = choose_layout_from_reason(reason, pack_states)
    prog = _shardkv_program(cfg.static_key(), kcfg.static_key(), n_clusters,
                            mesh, True, packed)
    kn = knobs.broadcast(n_clusters)
    skn = sknobs.broadcast(n_clusters)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    fn = FuzzProgram(
        prog,
        lambda seed: (jnp.asarray(seed, jnp.uint32), kn, skn, ticks),
    )
    return _shardkv_layout_telemetry(fn, cfg, kcfg, n_clusters, packed,
                                     layout, reason)


def shardkv_report(final: ShardKvState) -> ShardKvFuzzReport:
    w_phase = np.asarray(final.w_phase)  # [D, G, NS]
    owned = (w_phase == OWNED).sum(axis=1)    # [D, NS]
    frozen = (w_phase == FROZEN).sum(axis=1)  # [D, NS]
    return ShardKvFuzzReport(
        violations=np.asarray(final.violations),
        raft_violations=np.bitwise_or.reduce(
            np.asarray(final.rafts.violations).reshape(
                np.asarray(final.violations).shape[0], -1
            ),
            axis=1,
        ),
        first_violation_tick=np.asarray(final.first_violation_tick),
        acked_ops=np.asarray(final.clerk_acked.sum(axis=-1)),
        acked_gets=np.asarray(final.gets_done.sum(axis=-1)),
        installs=np.asarray(final.installs_done),
        deletes=np.asarray(final.deletes_done),
        final_cfg=np.asarray(final.w_cfg.min(axis=-1)),
        owned_copies=owned.max(axis=-1),
        frozen_left=frozen.sum(axis=-1),
        max_cfg_lag=np.asarray(final.max_cfg_lag),
        ann_resolved=np.asarray(
            np.cumprod(np.asarray(final.win_var) >= 0, axis=-1).sum(axis=-1)
            - 1
        ),
        ctrl_walker_stalled=np.asarray(final.ctrl_w_stalled),
        lat_hist=(
            np.asarray(final.lat_hist) if final.lat_hist.size else None
        ),
        ev_counts=(
            np.asarray(final.rafts.ev_counts).sum(axis=1)
            + np.asarray(final.ctrl.ev_counts)
            if final.rafts.ev_counts.size else None
        ),
        **(
            {
                "phase_hist": np.asarray(final.phase_hist),
                "phase_ticks": np.asarray(final.phase_ticks),
                "lat_ticks": np.asarray(final.lat_ticks),
                "key_hist": np.asarray(final.key_lat_hist),
                "client_hist": np.asarray(final.client_lat_hist),
                "client_retries": np.asarray(final.client_retries),
                "worst_lat": np.asarray(final.worst_lat),
                "worst_phases": np.asarray(final.worst_phases),
                "worst_key": np.asarray(final.worst_key),
                "worst_client": np.asarray(final.worst_client),
                "worst_sub": np.asarray(final.worst_sub),
            }
            if final.lat_hist.size else {}
        ),
    )


def shardkv_fuzz(
    cfg: SimConfig,
    kcfg: ShardKvConfig,
    seed: int,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
) -> ShardKvFuzzReport:
    fn = make_shardkv_fuzz_fn(cfg, kcfg, n_clusters, n_ticks, mesh=mesh)
    final = jax.block_until_ready(fn(jnp.asarray(seed, jnp.uint32)))
    return shardkv_report(final)
