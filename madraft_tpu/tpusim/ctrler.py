"""Batched shard-controller fuzzing on top of the Raft tick (Lab 4A on TPU).

The reference's shard_ctrler (SURVEY.md §2 C8, /root/reference/src/shard_ctrler/)
is a replicated config service: ``Config{num, shards: [Gid; 10], groups}`` with
ops ``Join/Leave/Move/Query`` (msg.rs:20-37), where Join/Leave must rebalance
shards over the member groups *balanced* (max-min <= 1, tester.rs:134-150),
*minimally* (tests.rs:122-163,239-278), and — the part the README warns about
(README.md:79, "never iterate a HashMap") — *deterministically across
replicas*. ``shardkv.py`` deliberately models the controller as a pre-drawn
schedule tensor (its §4B focus is the migration protocol); THIS module is the
4A service itself as a replicated state machine fuzzed on-device:

- One raft cluster per universe (``step_cluster`` under vmap); the state
  machine is the config service: a member mask over ``n_gids`` possible
  groups, the shard->group owner map, and the full config HISTORY (one hash
  per config num, the tensor analogue of the reference's ``Vec<Config>``).
- Clerks are tensors exactly as in kv.py: one outstanding (client, seq, op)
  each, retried to random nodes until committed — the ClerkCore contract
  (shard_ctrler/client.rs reuses kvraft's ClerkCore, client.rs:2).
- Join(gid)/Leave(gid) apply the canonical rebalance below; Move(shard, gid)
  applies verbatim (server applies Move without rebalancing — the reference's
  Move semantics; a Move to a non-member gid is REJECTED with no new config,
  the error-surfacing behavior the C++ backend adopted in round 3); Query(num)
  is a committed read returning the config at ``min(num, latest)`` — num
  beyond the history means "latest", the u64::MAX convention (client.rs:17).

Canonical rebalance (the deterministic spec; the reference leaves
ShardInfo::apply as a todo!() stub, server.rs:17, so the spec is ours — and
it is deliberately CLOSED FORM rather than a greedy fixpoint loop, because
the batched backend pays sequential depth, not op count; see _rebalance):
  1. invalidate owners that left the member set;
  2. targets: floor(NS/k) shards each, +1 for the NS mod k members with the
     largest retained loads (ties: lowest gid) — ceil targets to the biggest
     retainers is what makes the result minimal-move;
  3. each member keeps its first min(retained, target) shards by shard
     index; every other shard (orphans + overflow) moves;
  4. moving shards fill member deficits in shard-index order, members
     ordered by gid.
Balanced AND minimal by construction (unit-tested against an independent
numpy model in tests/test_tpusim_ctrler.py).

Oracles (on-device reductions, sticky violation bits):
- CTRL_DIVERGE: an alive node whose apply cursor equals the truth walker's
  frontier must match it bit-for-bit (member mask, owner map, config num,
  whole config history, dup table). This is the oracle that catches the
  classic 4A bug: replica-divergent rebalance from iteration-order-dependent
  tie-breaking (``bug_rotate_tiebreak`` rotates the tie-break order by node
  id — the batched analogue of iterating a HashMap).
- CTRL_BALANCE: every Join/Leave-created config must assign each shard to a
  member and balance loads max-min <= 1 (tester.rs:113-150's check());
  stands down while no group is joined. ``bug_greedy_rebalance`` (dump all
  orphans on one group) must trip it.
- CTRL_MINIMAL: a Join/Leave transition must move exactly the minimal number
  of shards (computed in closed form from the retained loads — see
  ``_min_moves``); ``bug_full_reshuffle`` (recompute assignment from scratch,
  balanced but ignoring retention) must trip it. Move-created configs are
  exempt (the reference applies Move verbatim and only asserts minimality
  around Join/Leave, tests.rs:122-163).
- CTRL_QUERY: a completed Query's observation must equal the truth walker's
  answer for the same (client, seq) — historical query_at correctness across
  leader changes and restarts (tests.rs:64-75, 280-296: "config identical
  across leader failover").

Entry packing (i32 log values): ((client*SEQ_LIM + seq)*ARG_LIM + arg)*4
+ kind + 1, kind in {JOIN, LEAVE, MOVE, QUERY}; arg = gid-set bitmask (the
reference's Join takes a MAP of groups and Leave a vec, msg.rs:20-37 —
multi-gid ops carry up to ``join_max`` gids), gid-set bitmask, shard*NG+gid,
or config num (ARG_LIM-1 = "latest").
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from madraft_tpu.tpusim.config import (
    LEADER,
    NOOP_CMD,
    SimConfig,
    packed_bounds,
)
from madraft_tpu.tpusim.engine import (
    FuzzProgram,
    attach_layout_telemetry,
    choose_layout_from_reason,
)
from madraft_tpu.tpusim.metrics import (
    clerk_phase_matrix,
    fold_latencies,
    fold_phases,
    update_worst,
)
from madraft_tpu.tpusim.state import (
    BOOL,
    ClusterState,
    I32,
    PackedClusterState,
    U8,
    durable_after_append,
    init_cluster,
    pack_fields,
    pack_state,
    packed_layout_reason,
    packed_spec_for,
    uint_for,
    unpack_fields,
    unpack_state,
)
from madraft_tpu.tpusim.step import _lane_abs, _slot, step_cluster

# Violation bits (extending config/kv/shardkv's 1..1024).
VIOLATION_CTRL_DIVERGE = 2048   # replicas disagree at equal apply cursors
VIOLATION_CTRL_BALANCE = 4096   # a Join/Leave config is unbalanced or orphans a shard
VIOLATION_CTRL_MINIMAL = 8192   # a Join/Leave moved more shards than necessary
VIOLATION_CTRL_QUERY = 16384    # a Query observed a config != the history's truth

N_SHARDS = 10     # the reference's N_SHARDS (shard_ctrler/mod.rs:9)
_SEQ_LIM = 1 << 10
_BIG = 1 << 30

# Op kinds (msg.rs:20-37).
_JOIN, _LEAVE, _MOVE, _QUERY = 0, 1, 2, 3

# PRNG site ids (disjoint from step.py 0, kv.py 8..14, shardkv.py 16..20/100+).
_S_CLERK_START, _S_CLERK_KIND = 24, 27


@dataclasses.dataclass(frozen=True)
class CtrlerConfig:
    """Knobs of the 4A fuzzing layer. ``n_gids``/``n_clients``/``n_configs``/
    ``apply_max``/``walk_max`` shape the program; probabilities and bug modes
    are dynamic traced scalars (one compiled program serves all)."""

    n_gids: int = 5          # universe of possible group ids
    n_clients: int = 4
    n_configs: int = 24      # config-history capacity; mutations are rejected
    #                          once full (deterministically, on every replica)
    join_max: int = 3        # gids per Join/Leave op (the reference's Join
    #                          takes a MAP of groups, msg.rs:20-37; multijoin
    #                          is fuzzed concurrently, tests.rs:216-237)
    p_op: float = 0.3        # idle clerk starts a fresh op
    p_query: float = 0.3     # fresh op is a Query with this probability,
    p_move: float = 0.1      # a Move with this one; else Join/Leave (one draw)
    p_retry: float = 0.5     # pending clerk re-submits this tick
    apply_max: int = 4       # apply-machine entries per node per tick
    walk_max: int = 6        # truth-walker entries per tick
    # Oracle-validation bug modes (dynamic; False = correct service).
    bug_rotate_tiebreak: bool = False   # node-id-rotated tie-breaks: replicas
    #                                     diverge (the HashMap-iteration bug)
    bug_greedy_rebalance: bool = False  # all orphans to one group, no
    #                                     balancing pass (balance must fire)
    bug_full_reshuffle: bool = False    # balanced from-scratch reassignment
    #                                     ignoring retention (minimality fires)

    def __post_init__(self):
        if self.p_query + self.p_move > 1.0:
            raise ValueError(
                f"p_query ({self.p_query}) + p_move ({self.p_move}) must stay "
                "<= 1 (one uniform draw splits Query/Move/Join-Leave)"
            )
        if self.n_gids < 2 or self.n_gids > N_SHARDS:
            raise ValueError(f"n_gids must be in [2, {N_SHARDS}], got {self.n_gids}")
        if self.join_max < 1 or self.join_max > self.n_gids:
            raise ValueError(
                f"join_max must be in [1, n_gids={self.n_gids}], "
                f"got {self.join_max}"
            )
        top = _pack(self, self.n_clients - 1, _SEQ_LIM - 1, self._arg_lim - 1,
                    _QUERY)
        if top >= NOOP_CMD:
            raise ValueError(
                f"n_clients ({self.n_clients}) x arg space ({self._arg_lim}) "
                f"overflow the op packing (max {top} >= NOOP_CMD {NOOP_CMD})"
            )

    @property
    def _arg_lim(self) -> int:
        # gid-set bitmask | shard*NG+gid | config num (+1 for "latest")
        return max(1 << self.n_gids, N_SHARDS * self.n_gids,
                   self.n_configs + 1)

    def replace(self, **kw) -> "CtrlerConfig":
        return dataclasses.replace(self, **kw)

    def knobs(self) -> "CtrlerKnobs":
        return CtrlerKnobs(
            p_op=jnp.float32(self.p_op),
            p_query=jnp.float32(self.p_query),
            p_move=jnp.float32(self.p_move),
            p_retry=jnp.float32(self.p_retry),
            bug_rotate_tiebreak=jnp.bool_(self.bug_rotate_tiebreak),
            bug_greedy_rebalance=jnp.bool_(self.bug_greedy_rebalance),
            bug_full_reshuffle=jnp.bool_(self.bug_full_reshuffle),
        )

    def static_key(self) -> "CtrlerConfig":
        return CtrlerConfig(
            n_gids=self.n_gids, n_clients=self.n_clients,
            n_configs=self.n_configs, join_max=self.join_max,
            apply_max=self.apply_max, walk_max=self.walk_max,
        )


class CtrlerKnobs(NamedTuple):
    """Dynamic 4A-layer knobs (see CtrlerConfig). Uniform scalars normally;
    ``make_ctrler_sweep_fn`` broadcasts them per cluster (heterogeneous
    workload/bug sweeps in one program, engine.make_sweep_fn's design)."""

    p_op: jax.Array
    p_query: jax.Array
    p_move: jax.Array
    p_retry: jax.Array
    bug_rotate_tiebreak: jax.Array
    bug_greedy_rebalance: jax.Array
    bug_full_reshuffle: jax.Array

    def broadcast(self, n_clusters: int) -> "CtrlerKnobs":
        return CtrlerKnobs(
            *(jnp.broadcast_to(x, (n_clusters,)) for x in self)
        )


def _pack(cfg: CtrlerConfig, client, seq, arg, kind):
    return (((client * _SEQ_LIM + seq) * cfg._arg_lim + arg) * 4 + kind) + 1


def _unpack(cfg: CtrlerConfig, val):
    v = val - 1
    kind = v % 4
    v = v // 4
    arg = v % cfg._arg_lim
    cs = v // cfg._arg_lim
    return cs // _SEQ_LIM, cs % _SEQ_LIM, arg, kind  # client, seq, arg, kind


def _counts(owner, ng: int):
    """Per-group shard loads: [.., NG] from owner [.., NS]."""
    gid = jnp.arange(ng, dtype=I32)
    return jnp.sum(
        owner[..., None, :] == gid[..., :, None], axis=-1
    ).astype(I32)


def _retained_targets(ng: int, member, owner_oh, valid):
    """Retained loads and per-group balanced targets (ceil targets to the r
    biggest retainers, ties by lowest gid) — the ONE ranking both _rebalance
    and _min_moves use, so the CTRL_MINIMAL oracle and the canonical
    rebalance can never drift apart. Rank is computed by counting smaller
    keys, NOT argsort (sort kernels and dynamic gathers serialize on the
    tiny per-instance axes; one-hot compare-reduce fuses)."""
    gid = jnp.arange(ng, dtype=I32)
    k = jnp.sum(member.astype(I32))
    ksafe = jnp.maximum(k, 1)
    retained = jnp.sum(owner_oh & valid[:, None], axis=0).astype(I32)  # [NG]
    q, r = N_SHARDS // ksafe, N_SHARDS % ksafe
    sort_key = jnp.where(member, (N_SHARDS - retained) * ng + gid, _BIG)
    rank = jnp.sum(
        (sort_key[None, :] < sort_key[:, None]).astype(I32), axis=1
    )  # keys are distinct (gid term), so this IS the sort position
    target = jnp.where(member, q + (rank < r).astype(I32), 0)
    return retained, target


def _rebalance(ng: int, member, owner, tie_rot, greedy, reshuffle):
    """The canonical deterministic rebalance, CLOSED FORM (no sequential
    fixpoint loop — a 10-pass argmin/argmax loop measured ~45x slower than
    the kv layer on-chip, pure sequential-depth latency):

      1. invalidate owners that left the member set;
      2. targets: q = NS//k each, +1 for the first NS%k members ranked by
         retained load (descending; ties by lowest gid) — giving the ceil
         targets to the biggest retainers maximizes retention, which is what
         makes the result minimal-move;
      3. each member keeps its first ``min(retained, target)`` shards by
         shard index; every other shard (orphans + overflow) moves;
      4. moving shards fill member deficits in shard-index order, members
         ordered by gid — the rotate bug permutes THIS order per replica
         (the HashMap-iteration analogue): assignments diverge while balance
         and move count stay invariant.

    Everything is sorts/cumsums over the tiny [NG]/[NS] axes — fixed shallow
    depth, vmap-friendly. The two planted-bug variants ride traced flags.
    Single instance: member [NG] bool, owner [NS] i32 (-1 = unowned)."""
    gid = jnp.arange(ng, dtype=I32)
    sid = jnp.arange(N_SHARDS, dtype=I32)
    k = jnp.sum(member.astype(I32))
    ksafe = jnp.maximum(k, 1)
    owner_oh = owner[:, None] == gid[None, :]  # [NS, NG]; -1 matches nothing
    valid = jnp.any(owner_oh & member[None, :], axis=1)
    own0 = jnp.where(valid, owner, -1)
    own0_oh = owner_oh & valid[:, None]
    retained, target = _retained_targets(ng, member, owner_oh, valid)
    keep_g = jnp.minimum(retained, target)
    need_g = target - keep_g  # [NG] >= 0, sums to the moving count

    # keep set (step 3): shard s stays iff its ordinal among its group's
    # shards (by index) is below keep_g[owner(s)]
    own_eq = (own0[None, :] == own0[:, None]) & (own0[:, None] >= 0)  # [s, t]
    ord_s = jnp.sum(own_eq & (sid[None, :] < sid[:, None]), axis=1).astype(I32)
    keep_lim = jnp.sum(jnp.where(own0_oh, keep_g[None, :], 0), axis=1)
    keep_s = (own0 >= 0) & (ord_s < keep_lim)

    # assignment (step 4): the m-th moving shard (by index) goes to the
    # member at the m-th deficit slot, members ordered by (gid + rot) % ng;
    # slot starts by counting need over smaller rotated keys
    moving = ~keep_s
    m_ord = jnp.cumsum(moving.astype(I32)) - moving.astype(I32)  # exclusive
    akey = jnp.where(member, (gid + tie_rot) % ng, _BIG)
    start = jnp.sum(
        jnp.where(akey[None, :] < akey[:, None], need_g[None, :], 0), axis=1
    )
    in_slot = (
        member[None, :]
        & (m_ord[:, None] >= start[None, :])
        & (m_ord[:, None] < (start + need_g)[None, :])
    )
    dst_s = jnp.sum(jnp.where(in_slot, gid[None, :], 0), axis=1)
    own = jnp.where(k >= 1, jnp.where(keep_s, own0, dst_s), -1)

    # --- bug_greedy_rebalance: all orphans to the single least-loaded member
    # at entry; no balancing pass
    gkey = jnp.where(member, retained * ng + akey % ng, _BIG)
    dst0 = jnp.sum(jnp.where(gkey == jnp.min(gkey), gid, 0))  # keys distinct
    own_greedy = jnp.where((own0 < 0) & (k >= 1), dst0, own0)

    # --- bug_full_reshuffle: shard s -> s-th member round-robin (balanced,
    # retention-blind); member rank by counting smaller rotated keys
    mrank = jnp.sum(
        (member[None, :] & (akey[None, :] < akey[:, None])).astype(I32), axis=1
    )
    rs_oh = member[None, :] & (mrank[None, :] == (sid % ksafe)[:, None])
    own_rs = jnp.where(
        k >= 1, jnp.sum(jnp.where(rs_oh, gid[None, :], 0), axis=1), -1
    )

    return jnp.where(reshuffle, own_rs, jnp.where(greedy, own_greedy, own))


def _min_moves(ng: int, member, owner):
    """Closed-form minimal move count for a membership change: orphans (owner
    not in the new member set) must move, and overloaded members must shed
    down to the best-case targets (the r := NS mod k largest retained loads
    get ceil targets — the same rank-by-counting as _rebalance, so this is
    exactly the canonical spec's move count). Sort- and gather-free. Used by
    the CTRL_MINIMAL oracle; stands down at k=0."""
    gid = jnp.arange(ng, dtype=I32)
    owner_oh = owner[:, None] == gid[None, :]
    valid = jnp.any(owner_oh & member[None, :], axis=1)
    orphans = jnp.sum((~valid).astype(I32))
    retained, target = _retained_targets(ng, member, owner_oh, valid)
    shed = jnp.sum(
        jnp.where(member, jnp.maximum(retained - target, 0), 0)
    )
    return orphans + shed


_HASH_W = 1000003
# W^(NS-s) mod 2^32 as wrapping i32 constants: the polynomial hash below is
# the vectorized form of the Horner fold h = ((bits+1)*W + o_0)*W + o_1 ...
_HASH_POW = np.array(
    [pow(_HASH_W, N_SHARDS - s, 1 << 32) for s in range(N_SHARDS + 1)],
    dtype=np.uint64,
).astype(np.uint32).view(np.int32)


def _hash_config(member, owner, num):
    """i32 hash of one config (member mask + owner map + its num); one
    multiply-sum instead of a 10-deep sequential fold."""
    bits = member.astype(I32) << jnp.arange(member.shape[0], dtype=I32)
    h = (jnp.sum(bits) + 1) * _HASH_POW[0] + jnp.sum(
        (owner + 2) * jnp.asarray(_HASH_POW[1:]), axis=-1
    )
    return h * 31 + num


def _apply_entry(kcfg: CtrlerConfig, kkn: CtrlerKnobs, tie_rot,
                 member, owner, hist, cfg_num, last_seq, val, live):
    """Apply ONE log entry to one controller state machine instance.

    ``live`` gates the whole apply (cursor < commit and node alive). Returns
    the new state plus (accepted, q_obs, viol) — q_obs >= 0 only for an
    accepted Query. Shared verbatim between node apply machines and the truth
    walker so a planted transition bug affects both (letting the balance /
    minimality oracles fire without a divergence); only ``tie_rot`` differs
    (nodes pass node-id * bug_rotate_tiebreak, the walker passes 0).
    """
    ng, ncfg = kcfg.n_gids, kcfg.n_configs
    client, seq, arg, kind = _unpack(kcfg, val)
    client = jnp.clip(client, 0, kcfg.n_clients - 1)
    is_op = live & (val != NOOP_CMD)
    cl_oh = jnp.arange(kcfg.n_clients, dtype=I32) == client
    prev = jnp.sum(jnp.where(cl_oh, last_seq, 0))  # one-hot, not a gather
    fresh = is_op & (seq > prev)
    last_seq = jnp.where(cl_oh & is_op, jnp.maximum(prev, seq), last_seq)

    room = cfg_num < ncfg - 1
    # Join/Leave arg is a gid-SET bitmask (the reference's Join takes a map
    # of several groups and Leave a vec of gids, msg.rs:20-37); Move arg is
    # shard*NG+gid as before. A Join is effective iff it adds at least one
    # new member, a Leave iff it removes at least one — matching the C++
    # backend's set semantics (ctrler.h CtrlOp::join/leave).
    mask = ((arg >> jnp.arange(ng, dtype=I32)) & 1) > 0  # [NG]
    mv_gid = jnp.clip(arg % ng, 0, ng - 1)
    mv_shard = jnp.clip(arg // ng, 0, N_SHARDS - 1)
    mv_oh = jnp.arange(ng, dtype=I32) == mv_gid
    mem_at_mv = jnp.any(mv_oh & member)

    do_join = fresh & (kind == _JOIN) & room & jnp.any(mask & ~member)
    do_leave = fresh & (kind == _LEAVE) & room & jnp.any(mask & member)
    new_member = jnp.where(
        do_join, member | mask, jnp.where(do_leave, member & ~mask, member)
    )
    do_move = fresh & (kind == _MOVE) & room & mem_at_mv
    do_rebal = do_join | do_leave

    reb = _rebalance(ng, new_member, owner, tie_rot,
                     kkn.bug_greedy_rebalance, kkn.bug_full_reshuffle)
    moved_owner = jnp.where(
        jnp.arange(N_SHARDS, dtype=I32) == mv_shard, mv_gid, owner
    )
    new_owner = jnp.where(do_rebal, reb, jnp.where(do_move, moved_owner, owner))
    new_cfg = do_rebal | do_move
    cfg_num2 = jnp.where(new_cfg, cfg_num + 1, cfg_num)

    # --- balance + minimality oracles on Join/Leave transitions (k >= 1)
    k2 = jnp.sum(new_member.astype(I32))
    cnt2 = _counts(new_owner, ng)
    no_oh = new_owner[:, None] == jnp.arange(ng, dtype=I32)[None, :]
    owners_ok = jnp.all(jnp.any(no_oh & new_member[None, :], axis=1))
    cmax = jnp.max(jnp.where(new_member, cnt2, -1))
    cmin = jnp.min(jnp.where(new_member, cnt2, _BIG))
    bal_bad = do_rebal & (k2 >= 1) & (~owners_ok | (cmax - cmin > 1))
    moved = jnp.sum((new_owner != owner).astype(I32))
    min_bad = do_rebal & (k2 >= 1) & (moved != _min_moves(ng, new_member, owner))
    viol = jnp.where(bal_bad, VIOLATION_CTRL_BALANCE, 0) | jnp.where(
        min_bad, VIOLATION_CTRL_MINIMAL, 0
    )

    hist = jnp.where(
        (jnp.arange(ncfg, dtype=I32) == cfg_num2) & new_cfg,
        _hash_config(new_member, new_owner, cfg_num2), hist,
    )
    member = jnp.where(new_cfg, new_member, member)
    owner = jnp.where(new_cfg, new_owner, owner)

    # Query: committed read of the config at min(num, latest); arg beyond the
    # history (incl. the ARG_LIM-1 sentinel) means "latest" (client.rs:17).
    # Masked to 31 bits so a legitimate observation is never negative (-1 is
    # the "no reply yet" sentinel in clerk_q_obs / w_q_obs).
    is_q = fresh & (kind == _QUERY)
    eff = jnp.minimum(arg, cfg_num2)
    hist_at = jnp.sum(
        jnp.where(jnp.arange(ncfg, dtype=I32) == eff, hist, 0)
    )  # one-hot read, not a gather
    q_obs = jnp.where(is_q, hist_at & 0x7FFFFFFF, -1)

    return member, owner, hist, cfg_num2, last_seq, fresh, client, seq, q_obs, viol


class CtrlerState(NamedTuple):
    """Raft cluster + the 4A service layer (vmap adds the cluster axis)."""

    raft: ClusterState
    # --- clerks [NC] ---
    clerk_seq: jax.Array    # i32 last started seq (0 = none yet)
    clerk_out: jax.Array    # bool: outstanding
    clerk_arg: jax.Array    # i32 packed arg of the outstanding op
    clerk_kind: jax.Array   # i32 op kind
    clerk_acked: jax.Array  # i32 highest committed seq
    clerk_q_obs: jax.Array  # i32 node-served Query observation (-1 = none)
    queries_done: jax.Array  # i32 completed Queries (workload metric)
    clerk_sub: jax.Array    # i32 [NC] submit stamp: tick the outstanding op
    #                         STARTED (ISSUE 11 satellite; zero-size with
    #                         cfg.metrics off — the kv.py clerk_sub
    #                         treatment, closing PR 10's documented
    #                         events-only gap). At ack, t - clerk_sub folds
    #                         into the raft lat_hist: the client-experienced
    #                         submit->ack latency, retries included
    # --- phase boundary stamps (ISSUE 12; zero-size with metrics off —
    # the kv.py clerk_app/clerk_cmt/clerk_apl treatment: app closes
    # leader_wait, cmt closes replicate, apl (the walker catching up with
    # a Query's observation) closes apply; the ctrler layer carries no
    # per-key axis — its ops have no key — and worst-op keys report -1 ---
    clerk_app: jax.Array
    clerk_cmt: jax.Array
    clerk_apl: jax.Array
    # --- per-node apply machines (live + persisted snapshot) ---
    applied: jax.Array      # i32 [N] apply cursor, absolute
    last_seq: jax.Array     # i32 [N, NC] dup table
    member: jax.Array       # bool [N, NG]
    owner: jax.Array        # i32 [N, NS]; -1 = unowned
    cfg_num: jax.Array      # i32 [N]
    hist: jax.Array         # i32 [N, NCFG] config hash per num
    snap_last_seq: jax.Array
    snap_member: jax.Array
    snap_owner: jax.Array
    snap_cfg_num: jax.Array
    snap_hist: jax.Array
    # --- truth walker (canonical state machine on the committed shadow) ---
    w_frontier: jax.Array   # i32 walker cursor, absolute
    w_last_seq: jax.Array   # i32 [NC]
    w_member: jax.Array     # bool [NG]
    w_owner: jax.Array      # i32 [NS]
    w_cfg_num: jax.Array    # i32
    w_hist: jax.Array       # i32 [NCFG]
    w_q_seq: jax.Array      # i32 [NC] seq of the walker's last Query per client
    w_q_obs: jax.Array      # i32 [NC] the walker's answer for it
    # Sticky diagnostic: the walker needed an entry the shadow ring had
    # already overwritten (commit burst > log_cap inside one walk budget).
    # From that point the frontier freezes and the 4A oracles stand down;
    # without this bit a stalled-oracle run is indistinguishable from a
    # clean one (round-3 advisor finding).
    w_stalled: jax.Array    # bool


def _check_ctrler_cfg(cfg: SimConfig) -> None:
    assert cfg.p_client_cmd == 0.0, "ctrler layer owns command injection"
    assert not cfg.compact_at_commit, (
        "ctrler fuzzing needs cfg.compact_at_commit=False: the compaction "
        "boundary must follow the apply cursor, not the commit index"
    )


def init_ctrler_cluster(
    cfg: SimConfig, kcfg: CtrlerConfig, key: jax.Array, kn=None
) -> CtrlerState:
    n, nc = cfg.n_nodes, kcfg.n_clients
    ng, ncfg = kcfg.n_gids, kcfg.n_configs
    # config 0: no groups, every shard unowned (the reference's initial
    # Config{num: 0, shards: [0; 10]}, shard_ctrler/msg.rs:10-18)
    h0 = _hash_config(jnp.zeros((ng,), jnp.bool_),
                      jnp.full((N_SHARDS,), -1, I32), jnp.asarray(0, I32))
    hist0 = jnp.zeros((ncfg,), I32).at[0].set(h0)
    return CtrlerState(
        raft=init_cluster(cfg, key, kn),
        clerk_seq=jnp.zeros((nc,), I32),
        clerk_out=jnp.zeros((nc,), jnp.bool_),
        clerk_arg=jnp.zeros((nc,), I32),
        clerk_kind=jnp.zeros((nc,), I32),
        clerk_acked=jnp.zeros((nc,), I32),
        clerk_q_obs=jnp.full((nc,), -1, I32),
        queries_done=jnp.zeros((nc,), I32),
        clerk_sub=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_app=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_cmt=jnp.zeros((nc if cfg.metrics else 0,), I32),
        clerk_apl=jnp.zeros((nc if cfg.metrics else 0,), I32),
        applied=jnp.zeros((n,), I32),
        last_seq=jnp.zeros((n, nc), I32),
        member=jnp.zeros((n, ng), jnp.bool_),
        owner=jnp.full((n, N_SHARDS), -1, I32),
        cfg_num=jnp.zeros((n,), I32),
        hist=jnp.broadcast_to(hist0, (n, ncfg)),
        snap_last_seq=jnp.zeros((n, nc), I32),
        snap_member=jnp.zeros((n, ng), jnp.bool_),
        snap_owner=jnp.full((n, N_SHARDS), -1, I32),
        snap_cfg_num=jnp.zeros((n,), I32),
        snap_hist=jnp.broadcast_to(hist0, (n, ncfg)),
        w_frontier=jnp.asarray(0, I32),
        w_last_seq=jnp.zeros((nc,), I32),
        w_member=jnp.zeros((ng,), jnp.bool_),
        w_owner=jnp.full((N_SHARDS,), -1, I32),
        w_cfg_num=jnp.asarray(0, I32),
        w_hist=hist0,
        w_q_seq=jnp.zeros((nc,), I32),
        w_q_obs=jnp.full((nc,), -1, I32),
        w_stalled=jnp.asarray(False, jnp.bool_),
    )


def ctrler_step(
    cfg: SimConfig, kcfg: CtrlerConfig, ks: CtrlerState, cluster_key: jax.Array,
    kn=None, ckn=None,
) -> CtrlerState:
    """One lockstep tick: raft tick, apply machines, walker, oracles, clerks."""
    if kn is None:
        _check_ctrler_cfg(cfg)
        kn = cfg.knobs()
    if ckn is None:
        ckn = kcfg.knobs()
    pre = ks.raft
    s = step_cluster(cfg, pre, cluster_key, kn)
    return _ctrler_service_tick(
        cfg, kcfg, ks, pre.alive, pre.base, s, cluster_key, kn, ckn
    )


def _ctrler_service_tick(
    cfg: SimConfig, kcfg: CtrlerConfig, ks: CtrlerState,
    pre_alive: jax.Array, pre_base: jax.Array, s: ClusterState,
    cluster_key: jax.Array, kn, ckn,
) -> CtrlerState:
    """The service share of one tick given the STEPPED raft ``s`` and the
    two pre-tick raft views it needs (alive/base) — ONE copy of the math
    for the wide step and the fused packed step (the kv.py contract)."""
    n, cap, nc = cfg.n_nodes, cfg.log_cap, kcfg.n_clients
    me = jnp.arange(n, dtype=I32)
    t = s.tick
    key = jax.random.fold_in(cluster_key, t)

    applied, last_seq = ks.applied, ks.last_seq
    member, owner = ks.member, ks.owner
    cfg_num, hist = ks.cfg_num, ks.hist
    snap_last_seq, snap_member = ks.snap_last_seq, ks.snap_member
    snap_owner, snap_cfg_num = ks.snap_owner, ks.snap_cfg_num
    snap_hist = ks.snap_hist

    # 1. Crash/restart: live machine resets to the persisted snapshot; replay
    #    from base rebuilds the rest (restore-then-replay, raft.rs:194-211).
    fresh_node = (~pre_alive & s.alive) | ~s.alive
    fz = fresh_node[:, None]
    applied = jnp.where(fresh_node, s.base, applied)
    last_seq = jnp.where(fz, snap_last_seq, last_seq)
    member = jnp.where(fz, snap_member, member)
    owner = jnp.where(fz, snap_owner, owner)
    cfg_num = jnp.where(fresh_node, snap_cfg_num, cfg_num)
    hist = jnp.where(fz, snap_hist, hist)

    # 2. Compaction: capture the live tables as the persisted snapshot at the
    #    new base (the boundary is the pre-tick apply cursor; kv.py pattern).
    inst = s.snap_installed_src >= 0
    comp = (s.base != pre_base) & ~inst & s.alive
    cz = comp[:, None]
    snap_last_seq = jnp.where(cz, last_seq, snap_last_seq)
    snap_member = jnp.where(cz, member, snap_member)
    snap_owner = jnp.where(cz, owner, snap_owner)
    snap_cfg_num = jnp.where(comp, cfg_num, snap_cfg_num)
    snap_hist = jnp.where(cz, hist, snap_hist)

    # 3. Install-snapshot: adopt the SENDER's persisted snapshot (one-hot over
    #    the tiny node axis) as live + persisted state; jump the cursor.
    src_oh = (me[None, :] == s.snap_installed_src[:, None])[:, :, None]

    def _adopt(snap):
        return jnp.sum(jnp.where(src_oh, snap[None, :, :], 0), axis=1)

    ad_last_seq = _adopt(ks.snap_last_seq)
    ad_member = _adopt(ks.snap_member.astype(I32)) > 0
    ad_owner = jnp.sum(
        jnp.where(src_oh, ks.snap_owner[None, :, :] + 1, 0), axis=1
    ) - 1  # +1/-1: the -1 sentinel must survive the masked sum
    ad_cfg_num = jnp.sum(
        jnp.where(src_oh[:, :, 0], ks.snap_cfg_num[None, :], 0), axis=1
    )
    ad_hist = _adopt(ks.snap_hist)
    iz = inst[:, None]
    applied = jnp.where(inst, s.base, applied)
    last_seq = jnp.where(iz, ad_last_seq, last_seq)
    member = jnp.where(iz, ad_member, member)
    owner = jnp.where(iz, ad_owner, owner)
    cfg_num = jnp.where(inst, ad_cfg_num, cfg_num)
    hist = jnp.where(iz, ad_hist, hist)
    snap_last_seq = jnp.where(iz, ad_last_seq, snap_last_seq)
    snap_member = jnp.where(iz, ad_member, snap_member)
    snap_owner = jnp.where(iz, ad_owner, snap_owner)
    snap_cfg_num = jnp.where(inst, ad_cfg_num, snap_cfg_num)
    snap_hist = jnp.where(iz, ad_hist, snap_hist)

    # ---------------------------------------------------------- apply machines
    viol = jnp.asarray(0, I32)
    lane = jnp.arange(cap, dtype=I32)[None, :]
    clerk_q_obs = ks.clerk_q_obs
    cl_ids = jnp.arange(nc, dtype=I32)
    # the rotate bug's per-node tie-break rotation (0 when off / for walker)
    node_rot = jnp.where(ckn.bug_rotate_tiebreak, me, 0)
    apply_one = jax.vmap(
        functools.partial(_apply_entry, kcfg, ckn),
        in_axes=(0, 0, 0, 0, 0, 0, 0, 0),
    )
    for _ in range(kcfg.apply_max):
        can = s.alive & (applied < s.commit)
        pos = _slot(applied + 1, cap)
        val = jnp.sum(jnp.where(lane == pos[:, None], s.log_val, 0), axis=-1)
        (member, owner, hist, cfg_num, last_seq,
         fresh, client, seq, q_obs, v) = apply_one(
            node_rot, member, owner, hist, cfg_num, last_seq, val, can)
        for bit in (VIOLATION_CTRL_BALANCE, VIOLATION_CTRL_MINIMAL):
            viol |= jnp.where(jnp.any(v & bit != 0), bit, 0)
        # Query observation: the reply is a pure function of the log prefix,
        # so the first node to apply it yields the canonical answer (replica
        # agreement is checked by CTRL_DIVERGE)
        m = (
            (fresh & (q_obs >= 0))[None, :]
            & (client[None, :] == cl_ids[:, None])
            & (seq[None, :] == ks.clerk_seq[:, None])
        )  # [nc, n]
        cand = jnp.max(jnp.where(m, q_obs[None, :], -1), axis=1)
        clerk_q_obs = jnp.where(
            (clerk_q_obs < 0) & (cand >= 0), cand, clerk_q_obs
        )
        applied = jnp.where(can, applied + 1, applied)

    # ------------------------------------------------------------ truth walker
    w_frontier, w_last_seq = ks.w_frontier, ks.w_last_seq
    w_member, w_owner = ks.w_member, ks.w_owner
    w_cfg_num, w_hist = ks.w_cfg_num, ks.w_hist
    w_q_seq, w_q_obs = ks.w_q_seq, ks.w_q_obs
    sh_abs = _lane_abs(s.shadow_base, cap)  # [cap]
    lane1 = jnp.arange(cap, dtype=I32)
    w_stalled = ks.w_stalled
    for _ in range(kcfg.walk_max):
        canw = w_frontier < s.shadow_len
        posw = _slot(w_frontier + 1, cap)
        in_win = jnp.any((lane1 == posw) & (sh_abs == w_frontier + 1))
        # Entry needed but already overwritten by ring wraparound: permanent
        # (the ring never un-overwrites), so the flag is sticky.
        w_stalled = w_stalled | (canw & ~in_win)
        canw = canw & in_win
        val = jnp.sum(jnp.where(lane1 == posw, s.shadow_val, 0))
        (w_member, w_owner, w_hist, w_cfg_num, w_last_seq,
         fresh, client, seq, q_obs, v) = _apply_entry(
            kcfg, ckn, jnp.asarray(0, I32), w_member, w_owner, w_hist,
            w_cfg_num, w_last_seq, val, canw)
        viol |= v
        cl_oh = cl_ids == client
        hit_q = cl_oh & fresh & (q_obs >= 0)
        w_q_seq = jnp.where(hit_q, seq, w_q_seq)
        w_q_obs = jnp.where(hit_q, q_obs, w_q_obs)
        w_frontier = jnp.where(canw, w_frontier + 1, w_frontier)

    # ----------------------------------------------------------------- oracles
    # Divergence: an alive node at exactly the walker frontier must equal the
    # canonical state machine bit-for-bit (README.md:79's determinism rule —
    # replica-divergent rebalance is THE classic 4A bug).
    at_frontier = s.alive & (applied == w_frontier)  # [N]
    m_all = (
        jnp.all(member == w_member[None, :], axis=1)
        & jnp.all(owner == w_owner[None, :], axis=1)
        & (cfg_num == w_cfg_num)
        & jnp.all(hist == w_hist[None, :], axis=1)
        & jnp.all(last_seq == w_last_seq[None, :], axis=1)
    )
    viol |= jnp.where(jnp.any(at_frontier & ~m_all), VIOLATION_CTRL_DIVERGE, 0)

    # ------------------------------------------------------------------ clerks
    want = _pack(kcfg, cl_ids, ks.clerk_seq, ks.clerk_arg, ks.clerk_kind)
    sh_live = _lane_abs(s.shadow_base, cap) <= s.shadow_len
    in_shadow = jnp.any(
        (s.shadow_val[None, :] == want[:, None]) & sh_live[None, :], axis=1
    )
    is_q = ks.clerk_kind == _QUERY
    # phase boundary stamps (ISSUE 12; the kv.py treatment): cmt = first
    # tick in the shadow, apl = first tick the Query's answer was ready
    # (node observation recorded AND the walker caught up to it)
    clerk_cmt, clerk_apl = ks.clerk_cmt, ks.clerk_apl
    if cfg.metrics:
        clerk_cmt = jnp.where(
            ks.clerk_out & in_shadow & (clerk_cmt == 0), t, clerk_cmt
        )
        clerk_apl = jnp.where(
            ks.clerk_out & (clerk_q_obs >= 0) & (w_q_seq == ks.clerk_seq)
            & (clerk_apl == 0),
            t, clerk_apl,
        )
    newly_acked = ks.clerk_out & in_shadow & (
        ~is_q | ((clerk_q_obs >= 0) & (w_q_seq == ks.clerk_seq))
    )
    done_q = newly_acked & is_q
    # Historical-query correctness: the served config must equal the walker's
    # answer for the same (client, seq) — query_at across restarts/failovers.
    viol |= jnp.where(
        jnp.any(done_q & (clerk_q_obs != w_q_obs)), VIOLATION_CTRL_QUERY, 0
    )
    clerk_acked = jnp.where(newly_acked, ks.clerk_seq, ks.clerk_acked)
    clerk_out = ks.clerk_out & ~newly_acked
    queries_done = ks.queries_done + done_q.astype(I32)
    # metrics (ISSUE 11 satellite): the ack is the clerk's Ok reply — fold
    # the op's whole submit->ack latency into the cluster histogram (the
    # kv.py clerk fold; ctrler ops carry log_tick 0, so the raft layer's
    # own commit fold never double-counts them). ISSUE 12 adds the phase
    # decomposition + worst-op register (key -1: ctrler ops have no key).
    lat_hist = s.lat_hist
    phase_hist, phase_ticks, lat_ticks = (
        s.phase_hist, s.phase_ticks, s.lat_ticks
    )
    worst = (s.worst_lat, s.worst_phases, s.worst_key, s.worst_client,
             s.worst_sub)
    if cfg.metrics:
        e2e = t - ks.clerk_sub
        lat_hist = fold_latencies(lat_hist, e2e, newly_acked)
        ph = clerk_phase_matrix(
            t, ks.clerk_sub, ks.clerk_app, clerk_cmt, clerk_apl, is_q
        )
        phase_hist, phase_ticks, lat_ticks = fold_phases(
            phase_hist, phase_ticks, lat_ticks, ph, e2e, newly_acked
        )
        worst = update_worst(
            worst, e2e, newly_acked, ph,
            jnp.full((nc,), -1, I32), cl_ids, ks.clerk_sub,
        )

    # start fresh ops / retry pending ones
    kk = jax.random.split(jax.random.fold_in(key, _S_CLERK_START), 7)
    start = (
        ~clerk_out
        & jax.random.bernoulli(kk[0], ckn.p_op, (nc,))
        & (ks.clerk_seq < _SEQ_LIM - 1)
    )
    clerk_seq = jnp.where(start, ks.clerk_seq + 1, ks.clerk_seq)
    u_kind = jax.random.uniform(jax.random.fold_in(key, _S_CLERK_KIND), (nc,))
    new_kind = jnp.where(
        u_kind < ckn.p_query, _QUERY,
        jnp.where(
            u_kind < ckn.p_query + ckn.p_move, _MOVE,
            # Join/Leave split evenly on the residual probability mass
            jnp.where(
                u_kind < ckn.p_query + ckn.p_move
                + (1.0 - ckn.p_query - ckn.p_move) * 0.5,
                _JOIN, _LEAVE,
            ),
        ),
    )
    # arg draws: a gid-SET bitmask for Join/Leave (1..join_max gids — the
    # reference fuzzes concurrent multijoins, tests.rs:216-237; duplicate
    # draws collapse, so set sizes vary), (shard, gid) for Move from one
    # randint; the Query num from its OWN randint over the full history
    # range — deriving it from the Move-sized draw would truncate
    # historical-query coverage whenever N_SHARDS*n_gids < n_configs+1
    raw = jax.random.randint(
        kk[1], (nc,), 0, N_SHARDS * kcfg.n_gids, dtype=I32
    )
    qnum = jax.random.randint(kk[4], (nc,), 0, kcfg.n_configs + 1, dtype=I32)
    gsel = jax.random.randint(
        kk[5], (nc, kcfg.join_max), 0, kcfg.n_gids, dtype=I32
    )
    gcnt = jax.random.randint(kk[6], (nc,), 1, kcfg.join_max + 1, dtype=I32)
    gmask = jnp.any(
        (jnp.arange(kcfg.n_gids, dtype=I32)[None, None, :]
         == gsel[:, :, None])
        & (jnp.arange(kcfg.join_max, dtype=I32)[None, :, None]
           < gcnt[:, None, None]),
        axis=1,
    )  # [nc, NG]
    mask_arg = jnp.sum(
        gmask.astype(I32)
        << jnp.arange(kcfg.n_gids, dtype=I32)[None, :],
        axis=1,
    )
    new_arg = jnp.where(
        new_kind == _QUERY,
        jnp.where(
            raw % 4 == 0, kcfg._arg_lim - 1,  # "latest" 25% of the time
            qnum,
        ),
        jnp.where(new_kind == _MOVE, raw, mask_arg),
    )
    clerk_kind = jnp.where(start, new_kind, ks.clerk_kind)
    clerk_arg = jnp.where(start, new_arg, ks.clerk_arg)
    clerk_q_obs = jnp.where(start, -1, clerk_q_obs)
    clerk_sub = ks.clerk_sub
    clerk_app = ks.clerk_app
    if cfg.metrics:
        # submit stamp: the latency window opens at op start (an op never
        # acks in its start tick — the shadow ack needs a commit, which
        # takes at least one tick)
        clerk_sub = jnp.where(start, t, clerk_sub)
        clerk_app = jnp.where(start, 0, clerk_app)
        clerk_cmt = jnp.where(start, 0, clerk_cmt)
        clerk_apl = jnp.where(start, 0, clerk_apl)
    clerk_out = clerk_out | start
    retry = clerk_out & (
        start | jax.random.bernoulli(kk[2], ckn.p_retry, (nc,))
    )
    target = jax.random.randint(kk[3], (nc,), 0, n, dtype=I32)

    violations = s.violations | viol
    first_violation_tick = jnp.where(
        (s.first_violation_tick < 0) & (viol != 0), t, s.first_violation_tick
    )

    # submit: append at the targeted node iff it believes it is the leader
    # (kv.py submit loop; stale-leader acceptance is the rejoin_2b hazard)
    log_term, log_val, log_len = s.log_term, s.log_val, s.log_len
    landed = []
    for c in range(nc):
        sel = me == target[c]
        ok = (
            sel
            & retry[c]
            & s.alive
            & (s.role == LEADER)
            & (log_len - s.base < cap)
            & (log_len - s.commit < kn.flow_cap)
        )
        v = _pack(kcfg, jnp.asarray(c, I32), clerk_seq[c], clerk_arg[c],
                  clerk_kind[c])
        hit = ok[:, None] & (lane == _slot(log_len + 1, cap)[:, None])
        log_term = jnp.where(hit, s.term[:, None], log_term)
        log_val = jnp.where(hit, v, log_val)
        log_len = jnp.where(ok, log_len + 1, log_len)
        landed.append(jnp.any(ok))
    if cfg.metrics:
        # leader_wait boundary (kv.py submit-loop treatment)
        clerk_app = jnp.where(
            jnp.stack(landed) & clerk_out & (clerk_app == 0), t, clerk_app
        )

    raft = s._replace(
        log_term=log_term,
        log_val=log_val,
        log_len=log_len,
        durable_len=durable_after_append(s, log_len),
        violations=violations,
        first_violation_tick=first_violation_tick,
        compact_floor=applied,
        lat_hist=lat_hist,
        phase_hist=phase_hist,
        phase_ticks=phase_ticks,
        lat_ticks=lat_ticks,
        worst_lat=worst[0],
        worst_phases=worst[1],
        worst_key=worst[2],
        worst_client=worst[3],
        worst_sub=worst[4],
    )
    return CtrlerState(
        raft=raft,
        clerk_seq=clerk_seq,
        clerk_out=clerk_out,
        clerk_arg=clerk_arg,
        clerk_kind=clerk_kind,
        clerk_acked=clerk_acked,
        clerk_q_obs=clerk_q_obs,
        queries_done=queries_done,
        clerk_sub=clerk_sub,
        clerk_app=clerk_app,
        clerk_cmt=clerk_cmt,
        clerk_apl=clerk_apl,
        applied=applied,
        last_seq=last_seq,
        member=member,
        owner=owner,
        cfg_num=cfg_num,
        hist=hist,
        snap_last_seq=snap_last_seq,
        snap_member=snap_member,
        snap_owner=snap_owner,
        snap_cfg_num=snap_cfg_num,
        snap_hist=snap_hist,
        w_frontier=w_frontier,
        w_last_seq=w_last_seq,
        w_member=w_member,
        w_owner=w_owner,
        w_cfg_num=w_cfg_num,
        w_hist=w_hist,
        w_q_seq=w_q_seq,
        w_q_obs=w_q_obs,
        w_stalled=w_stalled,
    )


# ---------------------------------------------------------------------------
# Packed controller carry (ISSUE 11; the derivation contract is kv.py's:
# every width below comes from config.packed_bounds plus the static
# CtrlerConfig under the exact-or-wide rule, and the embedded raft group
# re-derives its index/cmd dtypes for the service append rate).
# ---------------------------------------------------------------------------

_CTRL_RAFT_WRITES = (
    "log_term", "log_val", "log_len", "durable_len", "violations",
    "first_violation_tick", "compact_floor", "lat_hist",
    # attribution plane (ISSUE 12; zero-size with metrics off)
    "phase_hist", "phase_ticks", "lat_ticks", "worst_lat", "worst_phases",
    "worst_key", "worst_client", "worst_sub",
)


@functools.lru_cache(maxsize=None)
def ctrler_packed_layout(cfg: SimConfig, kcfg: CtrlerConfig) -> tuple:
    """(raft PackedSpec, service field -> dtype table). Bounds: seq <=
    min(T, _SEQ_LIM - 1) (one clerk start per tick), raft index <=
    (n_clients + 1) * T + 1 (submits + leader no-op per node per tick),
    cmd <= the top packed op; gids fit i8 (n_gids <= N_SHARDS = 10),
    config nums fit their n_configs bound; the 31-bit config hashes
    (hist / w_hist / q_obs) stay full-width i32 by design."""
    b = packed_bounds(cfg)
    nc, ncfg = kcfg.n_clients, kcfg.n_configs
    idx_bound = (nc + 1) * b.tick + 1
    cmd_bound = _pack(kcfg, nc - 1, _SEQ_LIM - 1, kcfg._arg_lim - 1, _QUERY)
    sp = packed_spec_for(cfg, index_bound=idx_bound, cmd_bound=cmd_bound)
    seq = uint_for(min(b.tick, _SEQ_LIM - 1))
    num = uint_for(ncfg - 1)
    dts = {
        "clerk_seq": seq,
        "clerk_out": BOOL,
        "clerk_arg": uint_for(kcfg._arg_lim - 1),
        "clerk_kind": U8,
        "clerk_acked": seq,
        "clerk_q_obs": I32,            # 31-bit config hash (-1 sentinel)
        "queries_done": sp.tick,
        "clerk_sub": sp.tick,
        "clerk_app": sp.tick,          # phase boundary stamps (ISSUE 12)
        "clerk_cmt": sp.tick,
        "clerk_apl": sp.tick,
        "applied": sp.index,
        "last_seq": seq,
        "member": BOOL,
        "owner": jnp.int8,             # gid, -1 sentinel (n_gids <= 10)
        "cfg_num": num,
        "hist": I32,                   # full-width hash by design
        "snap_last_seq": seq,
        "snap_member": BOOL,
        "snap_owner": jnp.int8,
        "snap_cfg_num": num,
        "snap_hist": I32,
        "w_frontier": sp.index,
        "w_last_seq": seq,
        "w_member": BOOL,
        "w_owner": jnp.int8,
        "w_cfg_num": num,
        "w_hist": I32,
        "w_q_seq": seq,
        "w_q_obs": I32,
        "w_stalled": BOOL,
    }
    return sp, dts


class PackedCtrlerState(NamedTuple):
    """CtrlerState in the packed schema (field names mirror CtrlerState;
    widths per ctrler_packed_layout)."""

    raft: PackedClusterState
    clerk_seq: jax.Array
    clerk_out: jax.Array
    clerk_arg: jax.Array
    clerk_kind: jax.Array
    clerk_acked: jax.Array
    clerk_q_obs: jax.Array
    queries_done: jax.Array
    clerk_sub: jax.Array
    clerk_app: jax.Array
    clerk_cmt: jax.Array
    clerk_apl: jax.Array
    applied: jax.Array
    last_seq: jax.Array
    member: jax.Array
    owner: jax.Array
    cfg_num: jax.Array
    hist: jax.Array
    snap_last_seq: jax.Array
    snap_member: jax.Array
    snap_owner: jax.Array
    snap_cfg_num: jax.Array
    snap_hist: jax.Array
    w_frontier: jax.Array
    w_last_seq: jax.Array
    w_member: jax.Array
    w_owner: jax.Array
    w_cfg_num: jax.Array
    w_hist: jax.Array
    w_q_seq: jax.Array
    w_q_obs: jax.Array
    w_stalled: jax.Array


def pack_ctrler_state(cfg: SimConfig, kcfg: CtrlerConfig,
                      ks: CtrlerState) -> PackedCtrlerState:
    sp, dts = ctrler_packed_layout(cfg, kcfg)
    return PackedCtrlerState(raft=pack_state(cfg, ks.raft, sp),
                             **pack_fields(ks, dts))


def unpack_ctrler_state(cfg: SimConfig, kcfg: CtrlerConfig,
                        p: PackedCtrlerState) -> CtrlerState:
    sp, dts = ctrler_packed_layout(cfg, kcfg)
    return CtrlerState(raft=unpack_state(cfg, p.raft, sp),
                       **unpack_fields(p, dts))


def ctrler_packed_layout_reason(cfg: SimConfig, kcfg: CtrlerConfig, kn, ckn,
                                ticks_needed: int) -> Optional[str]:
    """None when the packed controller schema is exact for this run — the
    ctrler layer adds no dynamic-knob gates beyond the raft ones (every
    service width derives from static config fields alone)."""
    return packed_layout_reason(cfg, kn, ticks_needed)


def ctrler_step_packed(
    cfg: SimConfig, kcfg: CtrlerConfig, pks: PackedCtrlerState,
    cluster_key: jax.Array, kn=None, ckn=None,
) -> PackedCtrlerState:
    """One tick over the PACKED controller carry; with cfg.fuse_packed_step
    the composition is per field group (the kv_step_packed contract — raft
    passthrough fields never widen, only _CTRL_RAFT_WRITES re-pack)."""
    if kn is None:
        _check_ctrler_cfg(cfg)
        kn = cfg.knobs()
    if ckn is None:
        ckn = kcfg.knobs()
    if not cfg.fuse_packed_step:
        return pack_ctrler_state(cfg, kcfg, ctrler_step(
            cfg, kcfg, unpack_ctrler_state(cfg, kcfg, pks), cluster_key,
            kn, ckn,
        ))
    sp, dts = ctrler_packed_layout(cfg, kcfg)
    pre = unpack_state(cfg, pks.raft, sp)
    ps = pack_state(cfg, step_cluster(cfg, pre, cluster_key, kn), sp)
    s = unpack_state(cfg, ps, sp)
    ks = CtrlerState(raft=s, **unpack_fields(pks, dts))
    nks = _ctrler_service_tick(cfg, kcfg, ks, pre.alive, pre.base, s,
                               cluster_key, kn, ckn)
    pw = pack_state(cfg, nks.raft, sp)
    raft = ps._replace(**{f: getattr(pw, f) for f in _CTRL_RAFT_WRITES})
    return PackedCtrlerState(raft=raft, **pack_fields(nks, dts))


# ------------------------------------------------------------------- drivers
class CtrlerFuzzReport(NamedTuple):
    violations: np.ndarray            # i32 bitmask per cluster
    first_violation_tick: np.ndarray  # -1 = none
    acked_ops: np.ndarray             # committed clerk ops per cluster
    queries_done: np.ndarray          # completed Queries per cluster
    configs_created: np.ndarray       # walker config num per cluster
    committed: np.ndarray             # committed log entries per cluster
    msg_count: np.ndarray
    snap_installs: np.ndarray
    walker_stalled: np.ndarray        # bool: oracle coverage lost (see state)
    # metrics plane (ISSUE 10 + the ISSUE 11 clerk-latency satellite): the
    # ctrler clerk now stamps clerk_sub at op start and folds t - sub at
    # ack, exactly like kv/shardkv — a --metrics run reports a real
    # latency block alongside the events (the PR-10 events-only gap is
    # closed); both None with cfg.metrics off
    lat_hist: Optional[np.ndarray] = None
    ev_counts: Optional[np.ndarray] = None
    # attribution plane (ISSUE 12): phase decomposition + worst-op register
    # (ctrler carries no per-key axis — its ops have no key)
    phase_hist: Optional[np.ndarray] = None
    phase_ticks: Optional[np.ndarray] = None
    lat_ticks: Optional[np.ndarray] = None
    worst_lat: Optional[np.ndarray] = None
    worst_phases: Optional[np.ndarray] = None
    worst_key: Optional[np.ndarray] = None
    worst_client: Optional[np.ndarray] = None
    worst_sub: Optional[np.ndarray] = None

    @property
    def n_violating(self) -> int:
        return int((self.violations != 0).sum())

    def violating_clusters(self) -> np.ndarray:
        return np.nonzero(self.violations != 0)[0]


@functools.lru_cache(maxsize=None)
def _ctrler_program(
    static_cfg: SimConfig, static_kcfg: CtrlerConfig, n_clusters: int,
    mesh: Optional[Mesh], per_cluster_knobs: bool = False,
    packed: bool = False,
):
    """One compiled program per static shape; probabilities, bug modes, and
    tick count are runtime args (uniform scalars — the fast knob layout;
    the per-cluster layout serves make_ctrler_sweep_fn only). ``packed``
    carries the fori loop in the PackedCtrlerState (a separate cached
    program; the final state is widened before returning)."""
    constraint = None
    if mesh is not None:
        constraint = NamedSharding(mesh, P(mesh.axis_names[0]))
    kn_ax = 0 if per_cluster_knobs else None
    step_fn = ctrler_step_packed if packed else ctrler_step

    def run(seed, kn, ckn, n_ticks) -> CtrlerState:
        base = jax.random.PRNGKey(seed)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(n_clusters)
        )
        states = jax.vmap(
            functools.partial(init_ctrler_cluster, static_cfg, static_kcfg),
            in_axes=(0, kn_ax),
        )(keys, kn)
        if packed:
            states = jax.vmap(
                functools.partial(pack_ctrler_state, static_cfg, static_kcfg)
            )(states)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys = jax.lax.with_sharding_constraint(keys, constraint)
            if per_cluster_knobs:
                kn, ckn = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, constraint),
                    (kn, ckn),
                )

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_fn, static_cfg, static_kcfg),
                in_axes=(0, 0, kn_ax, kn_ax),
            )(carry, keys, kn, ckn)

        final = jax.lax.fori_loop(0, n_ticks, body, states)
        if packed:
            final = jax.vmap(
                functools.partial(unpack_ctrler_state, static_cfg,
                                  static_kcfg)
            )(final)
        return final

    return jax.jit(run)


def _ctrler_layout_telemetry(fn, cfg, kcfg, n_clusters, packed, layout,
                             reason):
    return attach_layout_telemetry(
        fn, n_clusters, packed, layout, reason,
        lambda: pack_ctrler_state(
            cfg, kcfg, init_ctrler_cluster(cfg, kcfg, jax.random.PRNGKey(0))
        ),
    )


def make_ctrler_fuzz_fn(
    cfg: SimConfig,
    kcfg: CtrlerConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    pack_states: Optional[bool] = None,
):
    """Build fn(seed) -> final batched CtrlerState (see engine.make_fuzz_fn;
    ``pack_states`` follows the make_kv_fuzz_fn exact-or-wide contract)."""
    _check_ctrler_cfg(cfg)
    kn = cfg.knobs()
    ckn = kcfg.knobs()
    reason = ctrler_packed_layout_reason(cfg, kcfg, kn, ckn, n_ticks)
    packed, layout = choose_layout_from_reason(reason, pack_states)
    prog = _ctrler_program(cfg.static_key(), kcfg.static_key(), n_clusters,
                           mesh, False, packed)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    fn = FuzzProgram(
        prog,
        lambda seed: (jnp.asarray(seed, jnp.uint32), kn, ckn, ticks),
    )
    return _ctrler_layout_telemetry(fn, cfg, kcfg, n_clusters, packed,
                                    layout, reason)


def _validate_ctrler_knobs(ckn) -> None:
    """Eager rejection of service-knob values that would silently misbehave
    inside the compiled program (the engine._validate_knobs analogue)."""
    from madraft_tpu.tpusim.engine import validate_bool_bugs, validate_probs

    k = jax.tree.map(np.asarray, ckn)
    validate_probs(k, ("p_op", "p_query", "p_move", "p_retry"), "ctrler")
    if (k.p_query + k.p_move > 1.0).any():
        raise ValueError(
            "p_query + p_move must stay <= 1 per cluster (one uniform draw "
            "splits Query/Move/Join-Leave)"
        )
    validate_bool_bugs(
        k, ("bug_rotate_tiebreak", "bug_greedy_rebalance",
            "bug_full_reshuffle"), "ctrler",
    )


def make_ctrler_sweep_fn(
    cfg: SimConfig,
    knobs,   # config.Knobs, uniform or with leading [n_clusters] axes
    cknobs,  # CtrlerKnobs, uniform or with leading [n_clusters] axes
    kcfg: CtrlerConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    pack_states: Optional[bool] = None,
):
    """Like make_ctrler_fuzz_fn, but every cluster runs its own raft AND
    service knobs — fault intensity, op mix, and the planted rebalance bugs
    become per-cluster data (one program for a whole mutation matrix)."""
    from madraft_tpu.tpusim.engine import (
        _validate_knobs,
        validate_service_raft_knobs,
    )

    _check_ctrler_cfg(cfg)
    _validate_knobs(knobs)
    validate_service_raft_knobs(knobs)
    _validate_ctrler_knobs(cknobs)
    reason = ctrler_packed_layout_reason(cfg, kcfg, knobs, cknobs, n_ticks)
    packed, layout = choose_layout_from_reason(reason, pack_states)
    prog = _ctrler_program(cfg.static_key(), kcfg.static_key(), n_clusters,
                           mesh, True, packed)
    kn = knobs.broadcast(n_clusters)
    ckn = cknobs.broadcast(n_clusters)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    fn = FuzzProgram(
        prog,
        lambda seed: (jnp.asarray(seed, jnp.uint32), kn, ckn, ticks),
    )
    return _ctrler_layout_telemetry(fn, cfg, kcfg, n_clusters, packed,
                                    layout, reason)


def ctrler_report(final: CtrlerState) -> CtrlerFuzzReport:
    return CtrlerFuzzReport(
        violations=np.asarray(final.raft.violations),
        first_violation_tick=np.asarray(final.raft.first_violation_tick),
        acked_ops=np.asarray(final.clerk_acked.sum(axis=-1)),
        queries_done=np.asarray(final.queries_done.sum(axis=-1)),
        configs_created=np.asarray(final.w_cfg_num),
        committed=np.asarray(final.raft.shadow_len),
        msg_count=np.asarray(final.raft.msg_count),
        snap_installs=np.asarray(final.raft.snap_install_count),
        walker_stalled=np.asarray(final.w_stalled),
        lat_hist=(
            np.asarray(final.raft.lat_hist)
            if final.raft.lat_hist.size else None
        ),
        ev_counts=(
            np.asarray(final.raft.ev_counts)
            if final.raft.ev_counts.size else None
        ),
        **(
            {
                f: np.asarray(getattr(final.raft, f))
                for f in ("phase_hist", "phase_ticks", "lat_ticks",
                          "worst_lat", "worst_phases", "worst_key",
                          "worst_client", "worst_sub")
            }
            if final.raft.lat_hist.size else {}
        ),
    )


def ctrler_fuzz(
    cfg: SimConfig,
    kcfg: CtrlerConfig,
    seed: int,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
) -> CtrlerFuzzReport:
    """Fuzz the 4A config service over n_clusters independent clusters."""
    fn = make_ctrler_fuzz_fn(cfg, kcfg, n_clusters, n_ticks, mesh=mesh)
    final = jax.block_until_ready(fn(jnp.asarray(seed, jnp.uint32)))
    return ctrler_report(final)


@functools.lru_cache(maxsize=None)
def _ctrler_replay_program(static_cfg: SimConfig, static_kcfg: CtrlerConfig,
                           packed: bool = False):
    step_fn = ctrler_step_packed if packed else ctrler_step

    def run(cluster_id, kn, ckn, n_ticks, seed):
        ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)
        state = init_ctrler_cluster(static_cfg, static_kcfg, ckey, kn)
        if packed:
            state = pack_ctrler_state(static_cfg, static_kcfg, state)

        def body(_, carry):
            return step_fn(static_cfg, static_kcfg, carry, ckey, kn, ckn)

        final = jax.lax.fori_loop(0, n_ticks, body, state)
        if packed:
            final = unpack_ctrler_state(static_cfg, static_kcfg, final)
        return final

    return jax.jit(run)


def ctrler_replay_cluster(
    cfg: SimConfig, kcfg: CtrlerConfig, seed: int, cluster_id: int,
    n_ticks: int, pack_states: Optional[bool] = None,
) -> CtrlerState:
    """Re-run one cluster exactly (the (seed, cluster_id) replay contract;
    layout-blind — the packed carry replays bit-identically, test-pinned)."""
    _check_ctrler_cfg(cfg)
    kn, ckn = cfg.knobs(), kcfg.knobs()
    packed, _ = choose_layout_from_reason(
        ctrler_packed_layout_reason(cfg, kcfg, kn, ckn, n_ticks), pack_states
    )
    prog = _ctrler_replay_program(cfg.static_key(), kcfg.static_key(), packed)
    return jax.block_until_ready(
        prog(jnp.asarray(cluster_id, jnp.int32), kn, ckn,
             jnp.asarray(n_ticks, jnp.int32), jnp.asarray(seed, jnp.uint32))
    )
