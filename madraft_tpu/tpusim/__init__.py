"""Batched lockstep Raft simulator on TPU.

Re-imagines the reference's discrete-event simulator (madsim 0.1.1, the L0 runtime of
/root/reference — see SURVEY.md §2.6) as a lockstep pure step function: virtual time is
quantized into ticks; every per-node behavior (election timers, RequestVote /
AppendEntries, commit advance) is a masked dense update; the network is a set of
single-slot per-(dst, src) mailbox tensors with sampled delivery ticks; faults
(crashes, partitions, message loss) are boolean masks drawn from a counter-based
per-cluster PRNG. ``jax.vmap`` over the cluster axis fuzzes tens of thousands of
independent (seed x fault-schedule) clusters per step; safety invariants
(election safety, log matching, commit durability) run as on-device reductions.
"""

from madraft_tpu.tpusim.config import (
    HIST_BUCKETS,
    METRIC_EVENTS,
    CoverageConfig,
    SimConfig,
)
from madraft_tpu.tpusim.metrics import (
    event_summary,
    latency_summary,
    quantile_from_hist,
)
from madraft_tpu.tpusim.state import ClusterState, init_cluster
from madraft_tpu.tpusim.step import step_cluster
from madraft_tpu.tpusim.engine import FuzzReport, fuzz, make_fuzz_fn
from madraft_tpu.tpusim.kv import (
    VIOLATION_EXACTLY_ONCE,
    VIOLATION_KV_DIVERGE,
    KvConfig,
    KvFuzzReport,
    KvState,
    PackedKvState,
    init_kv_cluster,
    kv_fuzz,
    kv_replay_cluster,
    kv_report,
    kv_step,
    make_kv_fuzz_fn,
    pack_kv_state,
    unpack_kv_state,
)

from madraft_tpu.tpusim.ctrler import (
    VIOLATION_CTRL_BALANCE,
    VIOLATION_CTRL_DIVERGE,
    VIOLATION_CTRL_MINIMAL,
    VIOLATION_CTRL_QUERY,
    CtrlerConfig,
    CtrlerFuzzReport,
    CtrlerState,
    PackedCtrlerState,
    ctrler_fuzz,
    ctrler_replay_cluster,
    ctrler_report,
    ctrler_step,
    init_ctrler_cluster,
    make_ctrler_fuzz_fn,
    pack_ctrler_state,
    unpack_ctrler_state,
)
from madraft_tpu.tpusim.shardkv import (
    VIOLATION_SHARD_DIVERGE,
    VIOLATION_SHARD_OWNERSHIP,
    VIOLATION_SHARD_STORAGE,
    PackedShardKvState,
    ShardKvConfig,
    ShardKvFuzzReport,
    ShardKvState,
    init_shardkv_cluster,
    make_shardkv_fuzz_fn,
    pack_shardkv_state,
    shardkv_fuzz,
    shardkv_report,
    shardkv_step,
    unpack_shardkv_state,
)

__all__ = [
    "SimConfig",
    "CoverageConfig",
    "HIST_BUCKETS",
    "METRIC_EVENTS",
    "event_summary",
    "latency_summary",
    "quantile_from_hist",
    "CtrlerConfig",
    "CtrlerFuzzReport",
    "CtrlerState",
    "ctrler_fuzz",
    "ctrler_replay_cluster",
    "ctrler_report",
    "ctrler_step",
    "init_ctrler_cluster",
    "make_ctrler_fuzz_fn",
    "VIOLATION_CTRL_BALANCE",
    "VIOLATION_CTRL_DIVERGE",
    "VIOLATION_CTRL_MINIMAL",
    "VIOLATION_CTRL_QUERY",
    "ShardKvConfig",
    "ShardKvFuzzReport",
    "ShardKvState",
    "init_shardkv_cluster",
    "make_shardkv_fuzz_fn",
    "shardkv_fuzz",
    "shardkv_report",
    "shardkv_step",
    "VIOLATION_SHARD_DIVERGE",
    "VIOLATION_SHARD_OWNERSHIP",
    "VIOLATION_SHARD_STORAGE",
    "ClusterState",
    "init_cluster",
    "step_cluster",
    "FuzzReport",
    "fuzz",
    "make_fuzz_fn",
    "KvConfig",
    "KvFuzzReport",
    "KvState",
    "init_kv_cluster",
    "kv_fuzz",
    "kv_replay_cluster",
    "kv_report",
    "kv_step",
    "make_kv_fuzz_fn",
    "VIOLATION_EXACTLY_ONCE",
    "VIOLATION_KV_DIVERGE",
    "PackedKvState",
    "PackedCtrlerState",
    "PackedShardKvState",
    "pack_kv_state",
    "unpack_kv_state",
    "pack_ctrler_state",
    "unpack_ctrler_state",
    "pack_shardkv_state",
    "unpack_shardkv_state",
]
