"""Coverage-guided schedule search: on-device abstract-state fingerprints.

ROADMAP item 3. Storm schedules were uniform-random, so rare interleavings
(the fig-8 class) were found only by raw volume. This module defines the
*abstract state* of a cluster — the per-node (role, alive, term-rank,
commit-delta) tuple from ``state.abstract_node_tuple``, folded into one u32
code per lane per tick — plus the pieces the engine's coverage pool
(``engine.run_pool(coverage=...)``) composes:

- ``abstract_code``     the per-tick fingerprint (pure function of
                        ``ClusterState``), computed inside the coverage
                        chunk program for every lane at every tick
- ``bitmap_index``      code -> seen-set bit. When the whole code space fits
                        the bitmap the mapping is the IDENTITY (one bit ==
                        one abstract state — the exact-count mode the
                        ground-truth A/B needs); otherwise a murmur3-style
                        avalanche mixes the code before masking
- ``refill_knobs``      the biased refill policy: a retiring lane that
                        discovered new fingerprints gets its float storm
                        knobs jittered (its schedule neighborhood is worth
                        exploring); an unproductive lane redraws fresh
                        knobs from the prior. Draws are a pure function of
                        (seed, new global id), so a lane's knob row — which
                        every coverage JSONL report carries — replays
                        bit-exactly through ``replay_cluster(...,
                        knobs=row)``
- ``enumerate_abstract_codes``  the offline ground-truth harness: for a tiny
                        config (``config.coverage_ground_truth``) it
                        enumerates every structurally-valid abstract code,
                        the denominator of the reached-state fraction that
                        validates guided-beats-random per chip-second (the
                        exhaustive-model-checking yardstick of the LNT/mCRL2
                        Raft models, arXiv:2004.13284 / 2403.18916)

The coverage programs are SEPARATE cached programs (engine.py): with
coverage off, no existing fuzz/pool program's HLO changes — the golden
guard (tests/golden_fuzz.json) pins this.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim.config import (
    CoverageConfig,
    Knobs,
    pool_lanes_per_shard,
)
from madraft_tpu.tpusim.state import (
    ClusterState,
    PackedClusterState,
    abstract_node_tuple,
)

U32 = jnp.uint32

# The storm knobs the biased refill may mutate: every per-tick Bernoulli
# probability. All live in [0, 1] (clipped after mutation), so a mutated row
# always passes engine._validate_knobs; the int knobs (timeout spans, delay
# spans, cadences, quorum) keep their base values — mutating those would
# change the compiled program's semantics class, not just the schedule
# density, and several carry cross-field validity constraints.
MUTABLE_KNOBS = (
    "loss_prob", "p_crash", "p_restart", "p_repartition", "p_heal",
    "p_leader_part", "p_asym_cut", "p_client_cmd", "p_lose_unsynced",
)

# PRNG domain separation for the refill-mutation draws: the stream must be
# disjoint from the cluster streams (fold_in(PRNGKey(seed), global_id)), so
# the mutation key hangs off seed ^ _COV_SALT instead.
_COV_SALT = 0x434F5647  # "COVG"

# How the knobs a lane is running were produced — the ``refill`` column of
# the coverage JSONL (engine reports the RETIRING lane's own kind).
REFILL_SEED, REFILL_FRESH, REFILL_MUTATE = 0, 1, 2
REFILL_NAMES = {REFILL_SEED: "seed", REFILL_FRESH: "fresh",
                REFILL_MUTATE: "mutate"}


def node_alphabet(ccfg: CoverageConfig) -> int:
    """Distinct per-node abstract values: role(3) x alive(2) x rank x delta."""
    return 3 * 2 * ccfg.term_rank_levels * ccfg.commit_delta_levels


def code_space(n_nodes: int, ccfg: CoverageConfig) -> int:
    """Size of the full abstract-code space (before reachability filters)."""
    return node_alphabet(ccfg) ** n_nodes


def identity_mapped(n_nodes: int, ccfg: CoverageConfig) -> bool:
    """True when every abstract code owns its own seen-set bit (no hashing):
    the exact-count mode the ground-truth fraction measurement requires."""
    return code_space(n_nodes, ccfg) <= ccfg.bitmap_bits


def _combine_node_code(ccfg: CoverageConfig, role, alive, rank, delta):
    """The per-node quantized tuple -> one code in [0, node_alphabet) —
    like _fold_code, ONE spelling shared by the wide and packed
    fingerprints (and mirrored by enumerate_abstract_codes' host loop)."""
    return (
        ((role * 2 + alive) * ccfg.term_rank_levels + rank)
        * ccfg.commit_delta_levels + delta
    )


def _fold_code(ccfg: CoverageConfig, node_code: jax.Array) -> jax.Array:
    """Big-endian fold of per-node u32 codes by node id — injective whenever
    the code space fits u32, and u32-wraparound (harmless: the non-identity
    path mixes anyway) beyond that. ONE fold for the wide and packed
    fingerprints, so the two spellings cannot diverge."""
    n = node_code.shape[0]  # static
    a = node_alphabet(ccfg)
    weights = jnp.asarray(
        [pow(a, n - 1 - i, 1 << 32) for i in range(n)], U32
    )
    return jnp.sum(node_code * weights, dtype=U32)


def abstract_code(ccfg: CoverageConfig, s: ClusterState) -> jax.Array:
    """u32 abstract-state code of ONE cluster at its current tick (vmap adds
    the lane axis)."""
    role, alive, rank, delta = abstract_node_tuple(
        s, ccfg.term_rank_levels, ccfg.commit_delta_levels
    )
    return _fold_code(
        ccfg, _combine_node_code(ccfg, role, alive, rank, delta).astype(U32)
    )


def abstract_code_packed(
    ccfg: CoverageConfig, p: PackedClusterState
) -> jax.Array:
    """``abstract_code`` folded DIRECTLY from the packed schema (ISSUE 9):
    role and alive are read straight out of their bitfield words — the
    packed layout already stores exactly the 2-bit/1-bit alphabet the
    fingerprint quantizes to — and term-rank/commit-delta come from the
    narrow term/commit arrays (comparisons and the bounded delta are exact
    in the narrow dtype: commit - min(commit) is non-negative and clipped
    below the dtype's range). Produces the IDENTICAL code for the
    round-tripped state (tests/test_state_layout.py pins it), so guided
    search is layout-blind."""
    n = p.term.shape[0]
    idx = jnp.arange(n, dtype=U32)
    role = ((p.role_bits >> (2 * idx)) & 3).astype(U32)
    alive = ((p.alive_bits >> idx) & 1).astype(U32)
    rank = jnp.clip(
        jnp.sum(p.term[None, :] < p.term[:, None], axis=1),
        0, ccfg.term_rank_levels - 1,
    ).astype(U32)
    delta = jnp.clip(
        p.commit - jnp.min(p.commit), 0, ccfg.commit_delta_levels - 1
    ).astype(U32)
    return _fold_code(ccfg, _combine_node_code(ccfg, role, alive, rank, delta))


def _mix32(x: jax.Array) -> jax.Array:
    """murmur3 finalizer: full-avalanche u32 -> u32."""
    x = (x ^ (x >> 16)) * U32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * U32(0xC2B2AE35)
    return x ^ (x >> 16)


# The bitmap update this index feeds is the ONE declared cross-lane
# scatter on the coverage hot path: every lane writes the SHARED seen-set
# through lane-tagged indices. The lint lane_isolation pass (ISSUE 15)
# flags exactly that pattern, so the coverage chunk registry entries carry
# an explicit lane_scatter allowance — counted per trace (x1 expected),
# never silently widened.
def bitmap_index(ccfg: CoverageConfig, n_nodes: int,
                 code: jax.Array) -> jax.Array:
    """Seen-set bit of an abstract code: the code itself in identity mode,
    else its avalanche hash masked to the (power-of-two) bitmap."""
    if identity_mapped(n_nodes, ccfg):
        return code.astype(jnp.int32)
    return (_mix32(code) & U32(ccfg.bitmap_bits - 1)).astype(jnp.int32)


def lane_shards(n_lanes: int, n_shards: int) -> jax.Array:
    """i32 [n_lanes] lane -> shard map for the pod-scale pool's PER-SHARD
    seen-set (ROADMAP 3a): the vectorized twin of ``config.pool_shard``
    (both route through ``config.pool_lanes_per_shard`` — ONE copy of the
    contiguous-slice layout rule). Each lane updates only row
    ``lane_shards[l]`` of the ``[n_shards, bitmap_bits]`` bitmap — the
    per-tick update never crosses a shard boundary; the engine's sharded
    harvest OR-reduces the rows so summary coverage counts the exact union
    (in identity mode)."""
    lps = pool_lanes_per_shard(n_lanes, n_shards)
    return jnp.arange(n_lanes, dtype=jnp.int32) // lps


def refill_knobs(
    ccfg: CoverageConfig,
    kn_lanes: Knobs,      # per-lane knob rows (leading [n] axis on every leaf)
    base_kn: Knobs,       # the base profile's scalar knobs (the prior center)
    retired: jax.Array,   # bool [n]
    productive: jax.Array,  # bool [n]: retiring lane discovered new fps
    new_ids: jax.Array,   # i32 [n]: global id after refill (fresh on retired)
    seed: jax.Array,      # u32 scalar (the pool's seed)
) -> tuple:
    """Per-lane knob rows and refill kinds after a harvest.

    Kept lanes keep their rows. A retired PRODUCTIVE lane's child jitters
    each mutable knob multiplicatively within [1/mut_span, mut_span] of the
    parent (explore the discovering schedule's neighborhood); an
    UNPRODUCTIVE lane's child redraws each knob uniformly in
    [fresh_lo, fresh_hi] x base (a fresh point of the prior). Everything is
    clipped to [0, 1], and a knob the base profile disabled (base == 0)
    stays 0 under both rules — coverage search never turns on a fault axis
    the profile turned off.

    Determinism/replay: all draws come from fold_in(PRNGKey(seed ^
    _COV_SALT), new_global_id) — disjoint from the cluster streams and a
    pure function of the pool's arguments, so the run is exactly
    reproducible and the resulting row (carried in the JSONL report)
    replays through ``engine.replay_cluster(..., knobs=row)`` bit-exactly.
    """
    n_mut = len(MUTABLE_KNOBS)
    base = jax.random.PRNGKey(seed ^ _COV_SALT)
    u = jax.vmap(
        lambda i: jax.random.uniform(jax.random.fold_in(base, i), (n_mut,))
    )(new_ids)  # [n, n_mut] in [0, 1)
    span = float(np.log2(ccfg.mut_span))
    updates = {}
    for j, name in enumerate(MUTABLE_KNOBS):
        parent = getattr(kn_lanes, name)
        b = getattr(base_kn, name)
        fresh = b * (ccfg.fresh_lo + u[:, j] * (ccfg.fresh_hi - ccfg.fresh_lo))
        mut = parent * jnp.exp2((u[:, j] * 2.0 - 1.0) * span)
        child = jnp.clip(jnp.where(productive, mut, fresh), 0.0, 1.0)
        updates[name] = jnp.where(retired, child, parent).astype(parent.dtype)
    kinds = jnp.where(productive, REFILL_MUTATE, REFILL_FRESH)
    return kn_lanes._replace(**updates), kinds


def enumerate_abstract_codes(n_nodes: int, ccfg: CoverageConfig) -> np.ndarray:
    """Offline ground truth: every structurally-valid abstract code, sorted.

    Filters (all provable invariants of the abstraction, see
    state.abstract_node_tuple):
      - some node has term-rank 0 (the minimum-term node is behind no one);
      - every *interior* (un-clipped) rank r must count exactly r nodes
        strictly below it — rank vectors like (0, 2, 2) with nothing at 1
        cannot arise from any term assignment;
      - some node has commit-delta 0 (delta is relative to min(commit)).

    For 2-level quantization (the ``config.coverage_ground_truth`` alphabet)
    the rank filter is EXACT — the enumerated set is precisely the codes any
    term assignment can produce. At deeper quantizations, and in general
    (the abstraction drops log/commit coupling), the result is a superset of
    the truly reachable set, which makes it a sound denominator for the
    reached-fraction metric: fractions are comparable between runs and
    conservative in absolute terms.

    Intended for tiny configs only (the ground-truth validation); guarded
    against accidental use on the 5-node default alphabet, whose space
    (54^5) is enumerable by machine but meaningless to iterate in a test.
    """
    space = code_space(n_nodes, ccfg)
    if space > 1 << 20:
        raise ValueError(
            f"abstract code space {space} too large to enumerate — this is "
            "the offline ground-truth harness for tiny configs "
            "(config.coverage_ground_truth), not a general counter"
        )
    levels_r, levels_c = ccfg.term_rank_levels, ccfg.commit_delta_levels
    per_node = list(itertools.product(
        range(3), range(2), range(levels_r), range(levels_c)
    ))
    codes = []
    for combo in itertools.product(per_node, repeat=n_nodes):
        ranks = [c[2] for c in combo]
        deltas = [c[3] for c in combo]
        if min(ranks) != 0 or min(deltas) != 0:
            continue
        if any(
            sum(r2 < r for r2 in ranks) != r
            for r in ranks if 0 < r < levels_r - 1
        ):
            continue
        code = 0
        for role, alive, rank, delta in combo:
            code = code * node_alphabet(ccfg) + _combine_node_code(
                ccfg, role, alive, rank, delta
            )
        codes.append(code)
    return np.asarray(sorted(codes), np.uint32)
