"""Simulation configuration: static shape knobs + dynamic runtime knobs.

Mirrors the knobs of the reference runtime (madsim 0.1.1) and its testers, quantized
onto a tick grid: the reference draws election timeouts of 150..300ms
(/root/reference/src/raft/raft.rs:260-263), clerk/RPC latencies of 1-27ms and 10%
loss in unreliable mode (/root/reference/src/raft/tester.rs:127-137). With the default
``ms_per_tick=10`` those become 15..30 tick timeouts and 1..3 tick delivery delays.

Two kinds of knobs, split deliberately:

- **Static** (shapes and loop bounds: ``n_nodes``, ``log_cap``, ``ae_max``) are
  Python ints baked into the trace — they determine array shapes, so they must
  be.
- **Dynamic** (every probability, timeout span, cadence, quorum override) are
  carried as traced scalars (``Knobs``) through the jit boundary. One compiled
  XLA program therefore serves *any* fault intensity and *any* bug injection;
  ``engine.make_sweep_fn`` additionally broadcasts the knobs per cluster so a
  whole *sweep* of fault parameters runs across the cluster batch in a single
  program (sweeps pay a measured 2.4x for that heterogeneity — see
  engine._fuzz_program; plain fuzzing uses uniform scalars at full speed).
  This is the TPU-idiomatic inversion of the reference's compile-time test
  matrix: the program is compiled once; the matrix is data.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp


# The raft-layer planted-bug library (see SimConfig.bug).
RAFT_BUGS = (
    "", "commit_any_term", "grant_any_vote", "forget_voted_for", "no_truncate",
    "ack_before_fsync",
)


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static parameters of one batched simulation. All times are in ticks."""

    n_nodes: int = 5
    log_cap: int = 64        # ring capacity: entries retained past the snapshot
    #                          (power of two: canonical lane = (index-1) & (cap-1))
    ae_max: int = 4          # max entries carried per AppendEntries message

    # On-device metrics plane (ISSUE 10): latency-tail histograms and
    # per-lane liveness-event counters folded INSIDE the compiled step.
    # STATIC on purpose, exactly like `bug` and the coverage knobs: the
    # metric arrays' shapes derive from it (metrics_dims — zero-size with
    # metrics off, so the metrics-off ClusterState carries zero extra
    # bytes), it joins static_key, and a metrics run therefore selects its
    # own cached programs — the metrics-off hot path, its golden guards,
    # and its packed bytes_per_lane are untouched. Metrics add NO PRNG
    # draws, so a metrics-on run's trajectory (violations, commits, every
    # draw) is bit-identical to the same run with metrics off.
    metrics: bool = False

    # Packed-step fusion (ISSUE 11): compose pack∘step∘unpack PER FIELD
    # GROUP instead of at the whole-state boundary. On the packed service
    # carries (kv/ctrler/shardkv), the raft sub-tick then consumes and
    # produces the PACKED raft group directly — the service tick reads a
    # widened VIEW of only the raft fields it needs (XLA dead-code-
    # eliminates the rest) and packs only the fields it writes, so the
    # full wide raft pytree never materializes between the raft layer and
    # the service apply machines (the HBM round-trip ROADMAP item 3
    # names). STATIC on purpose, like `bug` and `metrics`: a fused run
    # selects its own cached programs, so every existing program's HLO —
    # and all golden guards — stay bit-identical with the flag off.
    # Trajectories are bit-identical either way (pure layout change; the
    # arithmetic is the same wide ops — test-pinned), so the flag is a
    # perf knob, not a semantics knob.
    fuse_packed_step: bool = False

    # Packed-state tick ceiling (ISSUE 9): the per-lane tick count the
    # PACKED ClusterState layout (state.PackedClusterState) is sized for.
    # Every tick-derived quantity is bounded by it — term bumps at most
    # once per tick cluster-wide, the log grows at most 2 entries per tick
    # (leader no-op + injection on a win tick), and command values are
    # next_cmd * n_nodes + node + 1 with next_cmd <= ticks — so the packed
    # dtypes are DERIVED from this one bound (config.packed_bounds is the
    # single source of truth; state.packed_spec turns bounds into dtypes,
    # and tests/test_state_layout.py pins the derivation). A run whose
    # per-lane horizon exceeds it simply uses the wide i32 layout
    # (engine/trace report which via `state_layout`); exceeding the bound
    # on the packed path is impossible by construction, not UB. Static
    # (shapes the compiled programs' dtypes), so it joins static_key.
    max_lane_ticks: int = 4096

    def __post_init__(self):
        if self.log_cap <= 0 or self.log_cap & (self.log_cap - 1):
            raise ValueError(f"log_cap must be a power of two, got {self.log_cap}")
        if self.compact_every < 1:
            raise ValueError(f"compact_every must be >= 1, got {self.compact_every}")
        # the leader-no-op liveness argument (step.py win block) needs the
        # ring to always have room for one current-term entry:
        # len - base <= flow_cap + compact_every must stay < log_cap
        if self.flow_cap < 1:
            raise ValueError(f"flow_cap must be >= 1, got {self.flow_cap}")
        if self.flow_cap + self.compact_every >= self.log_cap:
            raise ValueError(
                f"flow_cap ({self.flow_cap}) + compact_every "
                f"({self.compact_every}) must stay below log_cap "
                f"({self.log_cap}) or a full ring can deadlock commit"
            )
        if self.bug not in RAFT_BUGS:
            raise ValueError(f"unknown bug {self.bug!r}; known: {RAFT_BUGS}")
        if self.fsync_every < 1:
            raise ValueError(
                f"fsync_every must be >= 1 (ticks), got {self.fsync_every}"
            )
        if not 0.0 <= self.p_lose_unsynced <= 1.0:
            raise ValueError(
                f"p_lose_unsynced outside [0, 1]: {self.p_lose_unsynced}"
            )
        # cmd bound n * (T + 1) must stay < 2^31 for the widest derived
        # dtype (and leave the wide-i32 layout itself sound)
        if not 1 <= self.max_lane_ticks <= (1 << 24):
            raise ValueError(
                f"max_lane_ticks outside [1, 2^24]: {self.max_lane_ticks}"
            )
        for name in ("p_limp", "p_limp_heal", "p_fsync_stall"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} outside [0, 1]: {v}")
        if self.limp_mult_max < 1:
            raise ValueError(
                f"limp_mult_max must be >= 1 (1 = off), got {self.limp_mult_max}"
            )
        if self.eto_skew < 0:
            raise ValueError(f"eto_skew must be >= 0, got {self.eto_skew}")
        if self.fsync_stall_ticks < 0:
            raise ValueError(
                f"fsync_stall_ticks must be >= 0, got {self.fsync_stall_ticks}"
            )
        if self.rolling_period < 0 or self.rolling_down < 0:
            raise ValueError(
                f"rolling_period/rolling_down must be >= 0, got "
                f"{self.rolling_period}/{self.rolling_down}"
            )
        if self.rolling_period > 0 and self.rolling_down >= self.rolling_period:
            raise ValueError(
                f"rolling_down ({self.rolling_down}) must stay below "
                f"rolling_period ({self.rolling_period}) or a scheduled node "
                "never comes back up"
            )

    # Log compaction (the Lab 2D snapshot path, raft.rs:149-168): a node
    # discards its window prefix up to the compaction boundary every
    # `compact_every` committed-and-covered entries; a leader whose peer lags
    # behind its snapshot sends an install-snapshot instead of entries.
    # With compact_at_commit=True the boundary is the commit index (pure-raft
    # fuzzing); service layers (kv.py) set False and drive the boundary via
    # the per-node compact_floor state (their apply cursor), so a snapshot
    # never outruns the state machine.
    compact_every: int = 16
    compact_at_commit: bool = True

    # Flow control: a leader refuses new proposals (client commands, service
    # entries) while its uncommitted backlog log_len - commit reaches this
    # many entries (0 = log_cap // 2). Without it, retry-heavy service layers
    # can fill the bounded ring with uncommitted old-term entries; after an
    # election the new leader then has no room to append a current-term
    # entry, the current-term commit rule (step.py commit advance) can never
    # fire, and commit/apply/base deadlock permanently. The bound keeps
    # len - base strictly below cap (given compact_every <= cap/4), so a
    # fresh leader can always propose and drain the backlog. The reference's
    # analogue is Server::apply backpressuring on the raft handle — an
    # unbounded log hides the hazard; a ring must make it explicit.
    uncommitted_cap: int = 0

    @property
    def flow_cap(self) -> int:
        return self.uncommitted_cap or self.log_cap // 2

    # Virtual-time quantization: 1 tick ~ 10 simulated ms.
    ms_per_tick: int = 10
    election_timeout_min: int = 15   # 150 ms, raft.rs:262
    election_timeout_max: int = 30   # 300 ms
    heartbeat_ticks: int = 5         # 50 ms leader heartbeat cadence

    # Network model (tester.rs:127-137: unreliable = 10% loss, 1-27ms latency).
    delay_min: int = 1
    delay_max: int = 3
    loss_prob: float = 0.0

    # Fault schedule (per-tick Bernoulli draws from the per-cluster PRNG).
    p_crash: float = 0.0        # alive node crashes (kill: volatile state lost)
    p_restart: float = 0.2      # dead node restarts (recovers persisted state)
    p_repartition: float = 0.0  # network re-partitions into a random 2-coloring
    p_heal: float = 0.0         # network heals to full connectivity
    p_leader_part: float = 0.0  # leader-in-minority partition (leader + its
    #                             successor vs the rest; kvraft tester.rs:184-191)
    p_asym_cut: float = 0.0     # one DIRECTED link goes down (one-sided failure;
    #                             accumulates until the next repartition/heal)
    max_dead: int = 0           # cap on simultaneously-dead nodes (0 = no crashes)

    # Client workload: probability a leader gets a fresh command injected per tick
    # (models RaftHandle::start, /root/reference/src/raft/raft.rs:131).
    p_client_cmd: float = 0.2

    # Storage durability model (the madsim `fs` fault axis: crash/restore
    # with PARTIALLY durable files — see state.py durability notes and the
    # README fault-model table). Writes become durable when an fsync
    # boundary passes:
    #   fsync_every   — background fsync cadence in ticks (per-node
    #                   staggered). 1 = sync every tick, i.e. perfect
    #                   persistence — the historic model, and the default.
    #   p_lose_unsynced — probability a CRASH also loses the un-fsynced
    #                   suffix: log_len/term/voted_for roll back to the
    #                   durable watermark (power loss drops the page cache).
    # The correct algorithm additionally fsyncs before every state-exposing
    # emission (persist-before-reply, raft.rs:224-233), so it stays safe at
    # any (fsync_every, p_lose_unsynced); the planted "ack_before_fsync"
    # bug removes exactly those reply-point syncs.
    fsync_every: int = 1
    p_lose_unsynced: float = 0.0

    # Gray-failure fault axes (ISSUE 19) — the slow-but-alive pathologies
    # fail-stop fuzzing cannot draw. ALL dynamic (they ride in Knobs, so no
    # new compiled programs) and ALL neutral by default: at the defaults
    # every draw below rides already-free low bits of existing threefry
    # words (step.py _DrawBlock harvesting), so a neutral run's trajectory
    # — and every golden guard — is bit-identical to the fail-stop-only
    # simulator. README "Fault model" has the full table.
    #
    # Limping nodes: an alive node enters a limp with p_limp per tick; its
    # per-send delivery delay is multiplied by a factor drawn uniformly in
    # [2, limp_mult_max] at onset (redrawn per episode), healing with
    # p_limp_heal per tick; a restart always clears the limp (fresh
    # process). limp_mult_max=1 disables the axis entirely.
    p_limp: float = 0.0
    limp_mult_max: int = 1
    p_limp_heal: float = 0.0

    # Per-node election clock skew: node i's election-timeout window is
    # [eto_min + i*eto_skew, eto_max + i*eto_skew] — a persistent per-node
    # clock offset (low ids time out first and win elections structurally;
    # contested elections need the fast node cut off or dead). 0 = off.
    eto_skew: int = 0

    # Fsync stalls: an alive node's BACKGROUND fsync cadence stalls for a
    # duration drawn uniformly in [1, fsync_stall_ticks] with p_fsync_stall
    # per tick (a device-level write spike — the durable watermark lags,
    # widening the ack_before_fsync volatile window). Distinct from
    # p_lose_unsynced (which loses the suffix at crash): a stall DELAYS
    # durability without losing anything by itself. The correct algorithm's
    # explicit persist-before-reply syncs are NOT stalled (they model
    # blocking fsync calls that eventually complete within the tick), so
    # the oracle stays provably safe under any stall schedule.
    p_fsync_stall: float = 0.0
    fsync_stall_ticks: int = 0

    # Rolling restart waves: a DETERMINISTIC staggered kill/restart
    # schedule (not a Bernoulli draw — game-day ops, not random faults).
    # Wave w starts at tick w * rolling_period and takes node (w mod
    # n_nodes) down for exactly rolling_down ticks, bypassing the max_dead
    # budget; the node restarts (persisted state intact) when its window
    # ends. rolling_period=0 disables; rolling_down < rolling_period is
    # enforced so a node is never scheduled down forever.
    rolling_period: int = 0
    rolling_down: int = 0

    # Deliberate-bug injection for oracle validation (None = correct algorithm).
    # E.g. majority_override=2 on a 5-node cluster lets two leaders win a term,
    # which the election-safety oracle must flag.
    majority_override: int | None = None

    # Planted-bug library (mutation testing for the oracles): "" = correct
    # algorithm; otherwise one of the classic Raft implementation bugs, each
    # of which a specific oracle must catch (tests/test_tpusim_bugs.py) and
    # each of which the C++ backend mirrors via MADTPU_BUG for differential
    # replay (cpp/raftcore/raft.cpp quorum()/bug() knobs):
    #   "commit_any_term"  - leader counts replicas for OLD-term entries too
    #                        (drops the §5.4.2/Figure-8 current-term rule)
    #   "grant_any_vote"   - voter skips the §5.4.1 up-to-date log check
    #   "forget_voted_for" - votedFor is not persisted across a crash
    #   "no_truncate"      - follower appends past its end but never
    #                        overwrites/truncates a conflicting suffix
    #   "ack_before_fsync" - RequestVote/AppendEntries handlers reply from
    #                        VOLATILE state (skip the persist-before-reply
    #                        fsync); a crash storm with p_lose_unsynced > 0
    #                        then un-commits acked entries / re-frees votes
    # Static (trace-time) on purpose: the correct program carries zero
    # bug-branch cost, and a bug selects its own compiled program.
    bug: str = ""

    @property
    def majority(self) -> int:
        if self.majority_override is not None:
            return self.majority_override
        return self.n_nodes // 2 + 1

    def replace(self, **kw) -> "SimConfig":
        return dataclasses.replace(self, **kw)

    def knobs(self) -> "Knobs":
        """The dynamic knobs as traced-able scalars (see module docstring)."""
        return Knobs(
            loss_prob=jnp.float32(self.loss_prob),
            p_crash=jnp.float32(self.p_crash),
            p_restart=jnp.float32(self.p_restart),
            p_repartition=jnp.float32(self.p_repartition),
            p_heal=jnp.float32(self.p_heal),
            p_leader_part=jnp.float32(self.p_leader_part),
            p_asym_cut=jnp.float32(self.p_asym_cut),
            p_client_cmd=jnp.float32(self.p_client_cmd),
            fsync_every=jnp.int32(self.fsync_every),
            p_lose_unsynced=jnp.float32(self.p_lose_unsynced),
            eto_min=jnp.int32(self.election_timeout_min),
            eto_max=jnp.int32(self.election_timeout_max),
            delay_min=jnp.int32(self.delay_min),
            delay_max=jnp.int32(self.delay_max),
            heartbeat_ticks=jnp.int32(self.heartbeat_ticks),
            compact_every=jnp.int32(self.compact_every),
            flow_cap=jnp.int32(self.flow_cap),
            max_dead=jnp.int32(self.max_dead),
            majority=jnp.int32(self.majority),
            compact_at_commit=jnp.bool_(self.compact_at_commit),
            p_limp=jnp.float32(self.p_limp),
            limp_mult_max=jnp.int32(self.limp_mult_max),
            p_limp_heal=jnp.float32(self.p_limp_heal),
            eto_skew=jnp.int32(self.eto_skew),
            p_fsync_stall=jnp.float32(self.p_fsync_stall),
            fsync_stall_ticks=jnp.int32(self.fsync_stall_ticks),
            rolling_period=jnp.int32(self.rolling_period),
            rolling_down=jnp.int32(self.rolling_down),
        )

    def static_key(self) -> "SimConfig":
        """Canonical config carrying only the fields that shape the compiled
        program (everything else rides in ``Knobs``). Two configs with equal
        static_key share one XLA program. Dynamic fields are pinned to fixed
        safe values (they never reach the program; compact_every=1 keeps the
        flow/compaction margin check satisfiable at any log_cap)."""
        return SimConfig(
            n_nodes=self.n_nodes, log_cap=self.log_cap, ae_max=self.ae_max,
            max_lane_ticks=self.max_lane_ticks, compact_every=1, bug=self.bug,
            metrics=self.metrics, fuse_packed_step=self.fuse_packed_step,
        )


class Knobs(NamedTuple):
    """Dynamic simulation knobs, traced through jit (one leaf per field).

    Uniform scalars normally (the fast layout); ``engine.make_sweep_fn``
    broadcasts them to a leading ``[clusters]`` axis so heterogeneous
    per-cluster fault schedules (parameter sweeps) run in one program.
    """

    loss_prob: jax.Array
    p_crash: jax.Array
    p_restart: jax.Array
    p_repartition: jax.Array
    p_heal: jax.Array
    p_leader_part: jax.Array
    p_asym_cut: jax.Array
    p_client_cmd: jax.Array
    fsync_every: jax.Array
    p_lose_unsynced: jax.Array
    eto_min: jax.Array
    eto_max: jax.Array
    delay_min: jax.Array
    delay_max: jax.Array
    heartbeat_ticks: jax.Array
    compact_every: jax.Array
    flow_cap: jax.Array
    max_dead: jax.Array
    majority: jax.Array
    compact_at_commit: jax.Array
    # gray-failure axes (ISSUE 19; all neutral at the SimConfig defaults)
    p_limp: jax.Array
    limp_mult_max: jax.Array
    p_limp_heal: jax.Array
    eto_skew: jax.Array
    p_fsync_stall: jax.Array
    fsync_stall_ticks: jax.Array
    rolling_period: jax.Array
    rolling_down: jax.Array

    def broadcast(self, n_clusters: int) -> "Knobs":
        """Per-cluster copies (leading axis) for vmap'ing over clusters."""
        return Knobs(*(jnp.broadcast_to(x, (n_clusters,)) for x in self))


# ---------------------------------------------------------------------------
# Packed state layout bounds (ISSUE 9; the schema itself lives in state.py).
#
# The packed ClusterState narrows every cold field to the smallest dtype its
# CONFIGURED range admits. The ranges all derive from SimConfig — this
# function is the one place the derivation lives, so the schema, the engine's
# layout choice, and the width-pinning tests cannot disagree about what fits:
#
#   tick   <= max_lane_ticks              (T; the declared per-lane ceiling)
#   term   <= T                           (cluster-wide max term bumps at most
#                                          once per tick — only an election
#                                          timeout increments it)
#   index  <= 2 * T + 1                   (log_len grows at most 2/tick:
#                                          leader no-op + injection on a win
#                                          tick; next_idx <= log_len + 1)
#   cmd    <= n_nodes * (T + 1)           (cmd_val = next_cmd * n + me + 1,
#                                          next_cmd <= T; NOOP_CMD is encoded
#                                          as the dtype's reserved max)
#
# Mailbox delivery STAMPS are stored relative to the cluster tick (every
# live slot holds a future tick; the per-send delay is < 256 by the
# _net_draws packed-draw contract), so they fit one byte regardless of T —
# provided delay_max <= 253, which state.packed_layout_reason checks along
# with the other dynamic-knob ceilings (timer/heartbeat fit u16).
# ---------------------------------------------------------------------------


class PackedBounds(NamedTuple):
    """Largest value each packed field class must represent (inclusive)."""

    tick: int    # tick, next_cmd, and every term-valued field
    term: int
    index: int   # log_len/base/commit/next_idx/match/prev/... (absolute)
    cmd: int     # log_val/shadow_val payloads (excluding the NOOP sentinel)
    rel_stamp: int  # mailbox stamp minus cluster tick (0 = empty slot)
    event: int   # any ev_counts liveness counter (ISSUE 10): every counted
    #              event fires at most once per NODE per tick (pick_one
    #              delivers one message per destination per type; elections/
    #              term bumps/crashes/restarts/commit advances are per-node
    #              facts), so n_nodes * max_lane_ticks bounds every row entry


def packed_bounds(cfg: "SimConfig") -> PackedBounds:
    # The dtype derivations downstream of these bounds are statically
    # pinned: tests/test_width_pin.py re-derives the minimal containers
    # independently, and the lint packed_width pass (tpusim/lint.py)
    # checks every hot-loop carry against them (ISSUE 15).
    t = cfg.max_lane_ticks
    return PackedBounds(
        tick=t,
        term=t,
        index=2 * t + 1,
        cmd=cfg.n_nodes * (t + 1),
        rel_stamp=254,  # u8 with 0 reserved for "empty" => delay_max <= 253
        event=cfg.n_nodes * t,
    )


# ---------------------------------------------------------------------------
# On-device metrics plane (ISSUE 10; fold helpers live in metrics.py, the
# instrumentation in step.py/kv.py/shardkv.py). These constants shape the
# metric arrays, so they live here with the other static shape knobs.
#
# Latency histogram: fixed log-spaced (power-of-two) buckets over
# submit->ack ticks — bucket 0 covers [0, 1], bucket k >= 1 covers
# [2^k, 2^(k+1) - 1], and the last bucket is open-ended. 16 buckets span
# latencies past 32k ticks, far beyond any configured horizon, and the
# fixed layout is what lets histograms MERGE by plain addition across
# lanes, shards, and report files (the DrJAX-style MapReduce fold:
# millions of lane-ticks of latency come back as one small row per lane).
#
# Event counters: one i32 row per lane, indexed by METRIC_EVENTS order.
# Every entry is a cumulative per-lane count of a liveness event; the
# delivery counters use the trace module's exact derivation (one delivery
# per destination per mailbox type per tick), so their sum equals
# msg_count — a cross-check the tests pin.
# ---------------------------------------------------------------------------

HIST_BUCKETS = 16

# Tail-latency attribution phases (ISSUE 12): every submit->ack latency
# decomposes into consecutive phase durations whose sum equals the
# end-to-end latency EXACTLY (test-pinned), each phase folding into its own
# fixed log-spaced histogram. The taxonomy follows the optimization
# catalogue of arXiv:1905.10786 / 2004.05074 — each production-Raft
# optimization moves exactly one of these phases (PreVote -> leader_wait,
# pipelined AppendEntries -> replicate, lease reads -> apply), so ROADMAP
# item 1's knob matrix gets a per-phase readout:
#   leader_wait  submit -> first accepted append (election windows and
#                NotLeader retry hunts; 0 for raft-injected commands,
#                which are born at a leader)
#   replicate    first append -> committed (majority replication; for a
#                clerk op, includes stale-leader false starts and
#                re-submissions after an overwrite)
#   apply        commit -> applied observation (Get ops waiting on the
#                apply machine / walker; 0 for mutations)
#   ack          applied -> ack delivery at the clerk. In the lockstep
#                tick model the ack is same-tick, so this leg is 0 today;
#                it is schema-present so a reply-delay model folds in
#                without a report-format change.
# The shardkv deployment adds:
#   migration    pre-append ticks the clerk spent marked WrongGroup (the
#                believed owner's leader answered but the shard was not
#                OWNED there — a migration stall / stale-config hunt);
#                counted out of the leader_wait window, so the sum stays
#                exact.
# Phase rows are keyed BY NAME in every JSON surface, so layers with
# different phase sets merge correctly in `stats`.
LATENCY_PHASES = ("leader_wait", "replicate", "apply", "ack")
SHARDKV_PHASES = LATENCY_PHASES + ("migration",)

# phase_names dispatches on AXIS LENGTH (the decoders — pool rows, report
# JSON, trace tracks — see only the array), which is sound only while the
# two taxonomies differ in length. Growing LATENCY_PHASES therefore also
# means teaching the decoders the layer explicitly; this assert makes that
# day a loud import error instead of silently labeling a new base phase
# as "migration".
assert len(LATENCY_PHASES) != len(SHARDKV_PHASES), (
    "phase taxonomies must differ in length for phase_names dispatch; "
    "pass the layer's phase tuple explicitly through the decoders instead"
)


def phase_names(n_phases: int) -> tuple:
    """Phase-name tuple for a phase-axis length (reports/stats decode;
    see the dispatch-contract assert above)."""
    if n_phases == len(SHARDKV_PHASES):
        return SHARDKV_PHASES
    return LATENCY_PHASES[:n_phases]


METRIC_EVENTS = (
    "elections_won",     # candidate reached majority and became leader
    "term_bumps",        # a node's term increased this tick (any cause)
    "crashes",           # node kills (incl. suffix-loss crashes)
    "restarts",          # node recoveries
    "rv_req_delivered",  # deliveries by RPC type (sum == msg_count)
    "rv_rsp_delivered",
    "ae_req_delivered",
    "ae_rsp_delivered",
    "snap_delivered",
    "commit_advances",   # nodes whose commit index advanced this tick
)


def metrics_dims(cfg: "SimConfig") -> tuple:
    """(hist_buckets, n_events, stamp_cap, n_phases, reg) — the metric
    arrays' shapes for one config. ALL ZERO with metrics off: the
    metrics-off ClusterState carries zero-size leaves (no bytes, no HBM, no
    packed-layout growth), which is what keeps the metrics-off programs'
    reports — and the ci.sh bytes_per_lane bound — untouched. stamp_cap
    sizes the per-entry submit-stamp rings (log_tick / shadow_sub), which
    mirror log_cap; n_phases the per-phase histogram axis (ISSUE 12); reg
    the worst-op register slots (scalar-like fields must be zero-SIZE when
    off, so they are [reg] arrays, never true scalars)."""
    if not cfg.metrics:
        return 0, 0, 0, 0, 0
    return HIST_BUCKETS, len(METRIC_EVENTS), cfg.log_cap, \
        len(LATENCY_PHASES), 1


# Violation bitmask values (oracle reductions; raft oracles live in step.py,
# service-layer oracles extend these in kv.py / shardkv.py with bits 8..256).
VIOLATION_DUAL_LEADER = 1      # two live leaders share a term (election safety)
VIOLATION_LOG_MATCHING = 2     # same (index, term) but diverging entries/prefix
VIOLATION_COMMIT_SHADOW = 4    # a committed entry changed or was lost (durability)
VIOLATION_PREFIX_DIVERGE = 512  # equal snapshot boundaries, different compacted
#                                 prefix hashes (durability beyond the window)

# The ONE name table for every oracle bit across all layers — the shared
# decoder every JSON report routes through (fuzz/replay/bridge/explain), so
# no user ever again has to decode a raw bitmask by reading this file. The
# service-layer bits are duplicated here by value on purpose: config.py is
# imported by every layer, so it cannot import them back, and
# tests/test_trace.py cross-checks each layer's VIOLATION_* constant against
# this table so the duplication cannot silently drift.
VIOLATION_NAMES = {
    1: "DUAL_LEADER",          # config.py (election safety)
    2: "LOG_MATCHING",         # config.py
    4: "COMMIT_SHADOW",        # config.py (commit durability)
    8: "EXACTLY_ONCE",         # kv.py
    16: "KV_DIVERGE",          # kv.py
    32: "STALE_READ",          # kv.py
    64: "SHARD_DIVERGE",       # shardkv.py
    128: "SHARD_OWNERSHIP",    # shardkv.py
    256: "SHARD_STORAGE",      # shardkv.py
    512: "PREFIX_DIVERGE",     # config.py (durability beyond the window)
    1024: "SHARD_STALE_READ",  # shardkv.py
    2048: "CTRL_DIVERGE",      # ctrler.py
    4096: "CTRL_BALANCE",      # ctrler.py
    8192: "CTRL_MINIMAL",      # ctrler.py
    16384: "CTRL_QUERY",       # ctrler.py
    32768: "SHARD_CTRL_STALE",  # shardkv.py (live-ctrler mode)
}


def violation_names(mask: int) -> list:
    """Decode a violation bitmask into oracle names, lowest bit first.
    Unknown set bits decode as ``BIT<k>`` rather than vanishing — a report
    must never under-read a violation it cannot name."""
    mask = int(mask)
    names = [name for bit, name in sorted(VIOLATION_NAMES.items())
             if mask & bit]
    known = 0
    for bit in VIOLATION_NAMES:
        known |= bit
    rest = mask & ~known
    k = 0
    while rest:
        if rest & 1:
            names.append(f"BIT{k}")
        rest >>= 1
        k += 1
    return names

# Role encoding.
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2


# ---------------------------------------------------------------------------
# Pod-scale pool id scheme (ROADMAP item 1; programs live in engine.py).
#
# The sharded pool (`run_pool(devices=N)`, CLI `pool --devices`) partitions
# the global-id space PER LANE: lane l's generation-g cluster owns global id
# g * n_lanes + l (generation 0 = the initial batch, ids 0..n_lanes-1).
# Lanes shard contiguously over devices, so shard s owns lanes
# [s * n_lanes/n_shards, (s+1) * n_lanes/n_shards) and draws exactly the ids
# congruent to those lanes mod n_lanes — refill bookkeeping is a per-lane
# generation bump with no cross-shard synchronization, and the id set a
# budgeted run draws is independent of the device count (the replay-contract
# invariance engine._lane_reseed documents and tests/test_pool.py enforces).
# These decoders are the shared vocabulary for reports, tests, and debugging
# (e.g. "which shard harvested cluster 113?").
# ---------------------------------------------------------------------------


def pool_lane(cluster_id: int, n_lanes: int) -> int:
    """Lane slot that ran ``cluster_id`` under the lane-partitioned scheme."""
    return int(cluster_id) % n_lanes


def pool_generation(cluster_id: int, n_lanes: int) -> int:
    """Refill generation of ``cluster_id`` (0 = initial batch) under the
    lane-partitioned scheme. (The single-device monotone scheme assigns ids
    in batch-wide retirement order, so ``id // n_lanes`` is only a dense
    cohort index there — not any lane's generation.)"""
    return int(cluster_id) // n_lanes


def pool_lanes_per_shard(n_lanes: int, n_shards: int) -> int:
    """THE shard-layout rule (one copy): lanes split into ``n_shards``
    contiguous equal slices, so shard ``s`` owns lanes ``[s * lps,
    (s+1) * lps)`` with ``lps = n_lanes // n_shards``. Every consumer —
    ``pool_shard`` here, ``coverage.lane_shards``, and the engine's mesh
    validation — routes through this, so the layout cannot drift between
    report decoding and actual device placement."""
    if n_lanes % n_shards:
        raise ValueError(
            f"lanes ({n_lanes}) must divide evenly over shards ({n_shards})"
        )
    return n_lanes // n_shards


def pool_shard(cluster_id: int, n_lanes: int, n_shards: int) -> int:
    """Device shard that ran (and harvested) ``cluster_id`` in an
    ``n_shards``-device pool."""
    return pool_lane(cluster_id, n_lanes) // pool_lanes_per_shard(
        n_lanes, n_shards
    )


def storm_profiles() -> dict:
    """THE registry of named simulation scenarios — every `--profile` the
    CLI accepts (fuzz/pool/coverage/trace verbs and `--list-profiles`),
    every per-profile bench gate (profile_gates below), and every scenario
    the tests exercise resolve through this one table. The README
    "Fault model" table documents each fault axis; the "Game-day
    profiles" section mirrors the floors/ceilings from profile_gates.

    Two families share the registry:

    **Planted-bug storms** (storm / fig8 / revote / durability) — the
    tuned fail-stop fault mixes each planted raft bug needs to manifest,
    with the fuzz scale each was validated at (shared with
    tests/test_tpusim_bugs.py). Each bug has a characteristic window
    (empirically tuned, see the bug tests' module docstring):
    commit_any_term needs a long old-term catch-up phase (ae_max=1 slow
    replication + wide delays); the forget_voted_for double-vote must land
    inside ONE RequestVote flight (7 nodes, short timeouts,
    crash-while-voting). At CLI defaults the buggy branch often never
    executes and the run is bit-identical to the correct program — a user
    would wrongly conclude the oracles are inert.

    **Game-day gray-failure profiles** (ISSUE 19: limp / skew_storm /
    fsync_stall / rolling_wave / hot_key_openloop / gray_storm) — the
    slow-but-alive pathologies: limping nodes, per-node clock skew, fsync
    stalls, deterministic rolling restart waves, and (via the kv workload
    overrides in profile_gates) open-loop Zipf clerk traffic. Each carries
    a documented clean-algorithm liveness floor and p99 ceiling in
    profile_gates — bench enforces them as the per-profile gate table.

    name -> (SimConfig, n_clusters, n_ticks, bugs_demonstrated)
    """
    storm = SimConfig(
        n_nodes=5, p_client_cmd=0.3, p_crash=0.05, p_restart=0.3,
        max_dead=2, p_repartition=0.03, p_heal=0.05, loss_prob=0.1,
    )
    fig8 = storm.replace(
        ae_max=1, delay_max=5, p_repartition=0.03, loss_prob=0.1,
        p_client_cmd=0.4,
    )
    revote = storm.replace(
        n_nodes=7, max_dead=3, p_crash=0.15, p_restart=0.6, delay_max=6,
        election_timeout_min=10, election_timeout_max=20, p_client_cmd=0.1,
    )
    # The durability storm exercises the storage axis: every crash drops the
    # un-fsynced suffix (p_lose_unsynced=1.0) and background fsync is slow
    # (fsync_every=8 >> the 1-3 tick message delays), so an ack_before_fsync
    # reply is near-certainly volatile when its node crashes. Crashes are
    # frequent enough (p_crash=0.1, max_dead=2) that a freshly-acked entry's
    # holder dies inside the fsync window, yet restarts fast (p_restart=0.4)
    # so commits keep flowing and a later leader can re-mint the lost index.
    durability = storm.replace(
        p_crash=0.1, p_restart=0.4, max_dead=2,
        fsync_every=8, p_lose_unsynced=1.0,
    )
    # --- game-day gray-failure profiles (ISSUE 19) ---
    # Limping nodes on a mild crash storm: one node at a time goes 2-8x
    # slow on every send (episodes ~20 ticks at p_limp_heal=0.05). The
    # cluster must stay live — a limping LEADER is the interesting case:
    # its heartbeats still arrive, so no election fires, but replication
    # crawls. delay_max * limp_mult_max = 24 <= 253 keeps the packed
    # layout exact.
    limp = storm.replace(
        p_crash=0.02, p_limp=0.05, limp_mult_max=8, p_limp_heal=0.05,
    )
    # Clock skew on an election-heavy storm: node i's timeout window is
    # shifted by i*4 ticks over a deliberately narrow [10, 16] base, so
    # node 0 structurally wins elections — until crashes/partitions take
    # it out and the skewed tail must converge.
    skew_storm = storm.replace(
        election_timeout_min=10, election_timeout_max=16, eto_skew=4,
        p_crash=0.08, p_restart=0.4, loss_prob=0.15,
    )
    # Fsync stalls on the durability storm: the background watermark
    # cadence (already slow at fsync_every=8) additionally stalls for up
    # to 24 ticks, so a crash under p_lose_unsynced=1.0 can roll a node
    # back much further — the widest ack_before_fsync window any profile
    # offers, and still provably safe for the correct algorithm.
    fsync_stall = durability.replace(
        p_fsync_stall=0.05, fsync_stall_ticks=24,
    )
    # Deterministic rolling restart waves, no Bernoulli faults at all:
    # every 48 ticks the next node (round-robin) is down for exactly 12
    # ticks — the game-day deploy drill. Liveness must hold through every
    # wave (12 < eto window sums, quorum never lost).
    rolling_wave = storm.replace(
        p_crash=0.0, max_dead=0, p_repartition=0.0, p_heal=0.0,
        loss_prob=0.02, rolling_period=48, rolling_down=12,
    )
    # Open-loop Zipf substrate: a mild fail-stop mix the kv/shardkv
    # workload legs run on — the open-loop arrival rate and Zipf skew
    # themselves are WORKLOAD knobs (KvConfig/ShardKvConfig), carried per
    # profile in profile_gates()["hot_key_openloop"]["workload"].
    hot_key_openloop = storm.replace(
        p_crash=0.02, max_dead=1, p_repartition=0.01, loss_prob=0.05,
    )
    # The composite game day: limping nodes + clock skew + fsync stalls
    # + lossy durability + crashes, all at once.
    gray_storm = storm.replace(
        p_crash=0.08, p_restart=0.4, fsync_every=8, p_lose_unsynced=1.0,
        p_limp=0.03, limp_mult_max=6, p_limp_heal=0.05, eto_skew=2,
        p_fsync_stall=0.03, fsync_stall_ticks=16,
    )
    return {
        "storm": (storm, 256, 600, ("grant_any_vote", "no_truncate")),
        "fig8": (fig8, 1024, 1000, ("commit_any_term",)),
        "revote": (revote, 2048, 1000, ("forget_voted_for",)),
        "durability": (durability, 256, 600, ("ack_before_fsync",)),
        "limp": (limp, 256, 600, ()),
        "skew_storm": (skew_storm, 256, 600, ()),
        "fsync_stall": (fsync_stall, 256, 600, ("ack_before_fsync",)),
        "rolling_wave": (rolling_wave, 256, 600, ()),
        "hot_key_openloop": (hot_key_openloop, 256, 600, ()),
        "gray_storm": (gray_storm, 256, 600, ("ack_before_fsync",)),
    }


# Static capacity of the open-loop pending-arrival stamp ring (ISSUE 19;
# kv.py/shardkv.py clerk open-loop mode). A clerk's pending queue is
# bounded by the open_queue_cap KNOB, which the service layers validate
# against this static ceiling — the ring shape is compiled, the cap is not.
OPEN_QUEUE_SLOTS = 8


def zipf_map(draw: "jax.Array", n_vals: int, a: "jax.Array") -> "jax.Array":
    """Map a uniform integer draw in [0, n_vals) onto a Zipf-like hot-key
    distribution with exponent knob ``a`` (traced f32): the midpoint
    u = (draw + 0.5) / n_vals is raised to the a-th power and rescaled, so
    mass concentrates on low ids as ``a`` grows. a == 1.0 is EXACTLY the
    identity (the neutral knob: the underlying randint draw passes through
    untouched, bit-for-bit) — enforced with an explicit where() because a
    traced pow is not guaranteed exact at 1.0. Shared by the kv key draw
    and the shardkv shard draw so the skew shape cannot drift between
    layers."""
    u = (draw.astype(jnp.float32) + jnp.float32(0.5)) / jnp.float32(n_vals)
    skewed = jnp.clip(
        jnp.floor(jnp.float32(n_vals) * (u ** a)).astype(jnp.int32),
        0, n_vals - 1,
    )
    return jnp.where(a == jnp.float32(1.0), draw, skewed)


def profile_gates() -> dict:
    """Per-profile game-day gate table (ISSUE 19) — the ONE source of
    truth for every liveness floor and p99 ceiling: bench.py's gate table
    (BENCH artifact `profile_gates` rows), ci.sh's gray smoke, the CLI
    `--list-profiles` output, and the README "Game-day profiles" table all
    read this dict. Every storm_profiles() name has an entry.

    Floors/ceilings are for the CORRECT algorithm at the profile's
    `bench_scale` (n_clusters, n_ticks) with metrics on, measured from the
    PR-10 latency histograms: `liveness_floor` = minimum acked client ops
    per lane (histogram mass / lanes), `p99_ceiling` = maximum p99
    submit->ack ticks. Values were measured on the CPU backend (seeds 0,
    7, 12345; round 19, per-entry comments below) with ~2x margin on the
    floor and the ceiling one log-spaced histogram bucket above the worst
    measured p99, so backend/seed jitter and bucket granularity cannot
    flake the gate; a breach means a real distribution shift, not noise.

    `workload` carries kv-layer knob overrides (open-loop rate / Zipf
    skew) for the profiles whose scenario is about traffic shape.
    `bridge` records whether the C++ differential-replay backend can
    express the profile's fault axes ("mirrored") or refuses gray-active
    runs ("unsupported") — see README.

    name -> {"liveness_floor": float, "p99_ceiling": int,
             "bench_scale": (n_clusters, n_ticks),
             "workload": dict, "bridge": str}
    """
    def gate(floor, ceil, scale=(64, 300), workload=None, bridge="mirrored"):
        return {
            "liveness_floor": floor, "p99_ceiling": ceil,
            "bench_scale": scale, "workload": workload or {},
            "bridge": bridge,
        }

    return {
        # fail-stop storms: the C++ bridge mirrors every knob
        "storm": gate(9.0, 511),        # measured 18.6-20.7 ops/lane, p99 255
        "fig8": gate(0.9, 1023),        # measured 1.9-2.3, p99 255-511
        "revote": gate(0.05, 511),      # measured 0.11-0.17, p99 63-255
        "durability": gate(2.5, 511),   # measured 5.4-6.3, p99 255
        # gray profiles: bridge declares the gray axes unsupported
        "limp": gate(6.0, 511, bridge="unsupported"),
        #                                 measured 12.5-16.7, p99 255
        "skew_storm": gate(4.0, 511, bridge="unsupported"),
        #                                 measured 8.4-10.1, p99 255
        "fsync_stall": gate(2.5, 511, bridge="unsupported"),
        #                                 measured 5.4-6.3, p99 255 (clean
        #                                 leg tracks durability: handler
        #                                 persist-before-reply keeps the
        #                                 watermark live, so stalls only
        #                                 widen the BUGGY window)
        "rolling_wave": gate(32.0, 127, bridge="unsupported"),
        #                                 measured 65.8-69.1, p99 63
        "hot_key_openloop": gate(
            16.0, 1023,
            workload={"open_rate": 0.25, "zipf_a": 3.0, "open_queue_cap": 8},
            bridge="unsupported",
        ),                              # measured 32.6-34.1, p99 511
        "gray_storm": gate(2.0, 511, bridge="unsupported"),
        #                                 measured 4.2-5.5, p99 255
    }

# ---------------------------------------------------------------------------
# Coverage-guided schedule search (ROADMAP item 3; subsystem lives in
# coverage.py, corpus scheduler in engine.run_pool). The knobs are STATIC on
# purpose: bitmap size and quantization levels shape the compiled coverage
# programs (array sizes / fold constants), exactly like SimConfig's shape
# knobs — and the coverage programs are SEPARATE cached programs, so enabling
# coverage never touches the plain fuzz/pool HLO (golden-guard property).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CoverageConfig:
    """Static knobs of the on-device coverage subsystem (coverage.py).

    The abstract state of one cluster at one tick is the per-node tuple
    (role, alive, term-rank, commit-delta) quantized to a tiny alphabet
    (state.abstract_node_tuple); its u32 code indexes a device-resident
    power-of-two seen-set bitmap. When the whole code space fits the bitmap
    (the ground-truth configs) the mapping is the identity — one bit is one
    abstract state, which is what the offline enumerator A/B measures.
    """

    bitmap_bits: int = 1 << 16   # seen-set size (power of two, bool bitmap)
    term_rank_levels: int = 3    # term-rank quantization (#nodes strictly
    #                              behind, clipped) — who is ahead, not by
    #                              how much
    commit_delta_levels: int = 3  # commit - min(commit), clipped — who lags
    #                               the commit frontier
    guided: bool = True          # biased refill on; False = measurement-only
    #                              (count coverage, refill exactly like the
    #                              plain pool — the random A/B baseline)
    # Refill-mutation shape (coverage.refill_knobs): a productive retiring
    # lane's float storm knobs are jittered multiplicatively within
    # [1/mut_span, mut_span]; an unproductive lane redraws each knob
    # uniformly in [fresh_lo, fresh_hi] x its base value (clipped to [0,1]).
    # A knob the base profile disabled (0.0) stays 0 under both rules.
    mut_span: float = 2.0
    fresh_lo: float = 0.25
    fresh_hi: float = 2.5

    def __post_init__(self):
        if self.bitmap_bits <= 0 or self.bitmap_bits & (self.bitmap_bits - 1):
            raise ValueError(
                f"bitmap_bits must be a power of two, got {self.bitmap_bits}"
            )
        if self.term_rank_levels < 2 or self.commit_delta_levels < 2:
            raise ValueError(
                "term_rank_levels and commit_delta_levels must be >= 2 "
                f"(got {self.term_rank_levels}, {self.commit_delta_levels})"
            )
        if self.mut_span <= 1.0:
            raise ValueError(f"mut_span must be > 1, got {self.mut_span}")
        if not 0.0 <= self.fresh_lo <= self.fresh_hi:
            raise ValueError(
                f"fresh span empty: [{self.fresh_lo}, {self.fresh_hi}]"
            )

    def replace(self, **kw) -> "CoverageConfig":
        return dataclasses.replace(self, **kw)

    def fingerprint_key(self) -> "CoverageConfig":
        """Canonical config carrying only the fields the FINGERPRINT path
        reads (bitmap size + quantization levels) — the SimConfig.static_key
        idiom. The coverage chunk program is cached on this, so flipping the
        refill policy (guided/mut_span/fresh_*, harvest-only knobs) between
        the A/B legs shares one compiled chunk executable instead of
        re-tracing a bit-identical program."""
        return CoverageConfig(
            bitmap_bits=self.bitmap_bits,
            term_rank_levels=self.term_rank_levels,
            commit_delta_levels=self.commit_delta_levels,
        )


def coverage_ground_truth() -> tuple:
    """The 3-node / short-horizon / small-alphabet validation config
    (ROADMAP item 3, in the style of the LNT/mCRL2 exhaustive Raft models,
    arXiv:2004.13284 / 2403.18916): the abstract-state space is small enough
    for coverage.enumerate_abstract_codes to enumerate offline, and the
    bitmap is sized so the code->bit mapping is the IDENTITY — measured
    coverage is an exact reached-state count, not a hash estimate.

    Returns (SimConfig, CoverageConfig, horizon_ticks) — shared by
    tests/test_coverage.py and bench.py's random-vs-guided A/B row.

    The base fault knobs are deliberately MILD (untuned defaults, not a
    hand-tuned storm): that is the regime guided search exists for — the
    uniform-random pool keeps refilling at the base point and saturates its
    neighborhood, while the guided pool's wide fresh prior (fresh_hi x base)
    plus mutation around productive lanes climbs to the fault intensities
    that actually diversify the abstract states. Measured at this profile:
    guided reaches 1.18-1.34x the states of random at equal tick budget
    across seeds (PERF.md round 7). Against a hand-tuned storm base the
    same machinery measured ~0.9x — guidance cannot beat an oracle that
    already sits on the sweet spot, and the A/B is honest about which
    question it answers.
    """
    cfg = SimConfig(
        n_nodes=3, log_cap=16, ae_max=2, compact_every=4,
        p_client_cmd=0.2, loss_prob=0.02, p_crash=0.01, p_restart=0.3,
        max_dead=1, p_repartition=0.01, p_heal=0.05,
    )
    # per-node alphabet 3*2*2*2 = 24; 24^3 = 13824 codes <= 2^14 bits
    ccfg = CoverageConfig(
        bitmap_bits=1 << 14, term_rank_levels=2, commit_delta_levels=2,
        fresh_lo=0.0, fresh_hi=8.0,
    )
    return cfg, ccfg, 64


# Log value of the no-op entry a freshly elected leader appends (step.py win
# block): guarantees the new term has a committable entry even while flow
# control gates service proposals. Far above any packed service op or
# injected command value; service apply machines skip it (kv.py valid guard;
# shardkv.py's 3-bit kind decodes it as the unused kind 7).
NOOP_CMD = 1 << 30
