"""Flight recorder: per-tick replay traces for one cluster (the ISSUE-2
observability tentpole).

The MADSIM_TEST_SEED replay contract reproduces any violating cluster from
``(seed, cluster_id)`` — but ``engine.replay_cluster`` only returns the
FINAL state, so a caught bug still had to be debugged blind. This module
re-runs the same ``step_cluster`` inside a ``jax.lax.scan`` that emits a
per-tick :class:`TickRecord` pytree, then host-decodes the stacked
``[n_ticks, ...]`` arrays into structured events (leader elected, term
bump, crash with suffix loss, partition change, snapshot install, commit
advance, violation onset) — the counterexample-trace artifact the
formal-verification line of related work (Raft in LNT / mCRL2) shows is
what makes a checker usable.

Deliberately a SEPARATE compiled program: the batched fuzz hot path is
untouched (``engine._fuzz_program`` carries no trace outputs, and a fuzz
report for a fixed seed stays bit-identical to pre-flight-recorder runs).
The scan applies the identical ``step_cluster`` to the identical PRNG
stream, so the traced final state is bit-identical to
``engine.replay_cluster`` — asserted by tests/test_trace.py.

Per-type delivery counts are derived EXACTLY without instrumenting the
step: a mailbox slot due this tick (``stamp == t`` in the pre-tick state)
is delivered iff its destination is alive and the link is up in the
post-fault adjacency (both carried unchanged into the post-tick state),
and ``step.pick_one`` delivers exactly one such source per destination —
so ``sum_dst any_src(due & alive & adj)`` is the delivered count per type.
The sum over types equals the tick's ``msg_count`` delta (cross-checked in
tests).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from madraft_tpu.tpusim.config import (
    LEADER,
    SimConfig,
    violation_names,
)
from madraft_tpu.tpusim.state import (
    ClusterState,
    I32,
    init_cluster,
    pack_state,
    packed_layout_reason,
    unpack_state,
)
from madraft_tpu.tpusim.step import step_cluster

ROLE_NAMES = ("follower", "candidate", "leader")
_DEAD = 3  # pseudo-role for Perfetto spans


class TickRecord(NamedTuple):
    """One tick's post-state snapshot plus that tick's deliveries.

    Leaves are per-tick; ``replay_cluster_traced`` stacks them to a leading
    ``[n_ticks]`` axis (numpy, host-side).
    """

    # --- per-node post-tick state [n] ---
    role: jax.Array
    term: jax.Array
    commit: jax.Array
    log_len: jax.Array
    base: jax.Array            # snapshot boundary
    durable_len: jax.Array     # fsync watermark
    alive: jax.Array           # bool
    adj_mask: jax.Array        # i32 row bitmask: bit s of row d = link s->d up
    # --- exact per-type delivery counts this tick (i32 scalars) ---
    rv_req_delivered: jax.Array
    rv_rsp_delivered: jax.Array
    ae_req_delivered: jax.Array
    ae_rsp_delivered: jax.Array
    snap_delivered: jax.Array
    # --- install-snapshot outcomes [n] ---
    snap_installed_src: jax.Array   # -1 = none this tick
    snap_installed_len: jax.Array
    # --- cluster-wide scalars ---
    shadow_len: jax.Array      # committed entries ever (durability shadow)
    msg_count: jax.Array       # cumulative delivered messages
    violations: jax.Array      # sticky oracle bitmask
    # --- metrics plane (ISSUE 10; zero-size with cfg.metrics off) ---
    shadow_sub: jax.Array      # [CAP] THIS tick's shadow-record submit
    #                            stamps (0 = lane not recorded / no stamp):
    #                            t - stamp over nonzero lanes is the exact
    #                            latency set the device folded this tick —
    #                            what the host cross-check recomputes
    lat_hist: jax.Array        # [HIST_BUCKETS] cumulative device histogram
    ev_counts: jax.Array       # [len(METRIC_EVENTS)] cumulative counters
    # --- attribution plane (ISSUE 12; zero-size with metrics off) ---
    phase_ticks: jax.Array     # [n_phases] cumulative per-phase tick
    #                            totals — per-tick deltas become the
    #                            Perfetto latency_phases counter track
    worst_lat: jax.Array       # [1] the worst-op register (cumulative
    worst_phases: jax.Array    # [n_phases] argmax; the LAST tick's values
    worst_key: jax.Array       # [1] describe the whole trace) — the
    worst_client: jax.Array    # [1] synthesized worst-op span reads these
    worst_sub: jax.Array       # [1]


def _pack_rows(mat: jax.Array) -> jax.Array:
    """[n, n] bool -> [n] i32 row bitmasks (bit j of row i = mat[i, j])."""
    n = mat.shape[-1]
    w = jnp.left_shift(jnp.asarray(1, I32), jnp.arange(n, dtype=I32))
    return jnp.sum(jnp.where(mat, w[None, :], 0), axis=-1).astype(I32)


def _deliveries(prev: ClusterState, nxt: ClusterState):
    """Exact per-type delivered counts for the tick prev -> nxt (see module
    docstring for why this is exact without touching step_cluster)."""
    t = nxt.tick
    alive, adj = nxt.alive, nxt.adj

    def cnt(mail_t, extra_ok=None):
        ok = (mail_t == t) & alive[:, None] & adj
        if extra_ok is not None:
            ok = ok & extra_ok
        return jnp.sum(jnp.any(ok, axis=1), dtype=I32)

    return (
        cnt(prev.rv_req_t),
        cnt(prev.rv_rsp_t),
        cnt(prev.ae_req_t),
        cnt(prev.ae_rsp_t),
        # install-snapshot delivery additionally needs a live SENDER
        # (read-at-delivery payload; step.py sn pick_one extra_ok)
        cnt(prev.sn_req_t, extra_ok=alive[None, :]),
    )


def _record(prev: ClusterState, nxt: ClusterState) -> TickRecord:
    rv_req, rv_rsp, ae_req, ae_rsp, sn = _deliveries(prev, nxt)
    return TickRecord(
        role=nxt.role, term=nxt.term, commit=nxt.commit,
        log_len=nxt.log_len, base=nxt.base, durable_len=nxt.durable_len,
        alive=nxt.alive, adj_mask=_pack_rows(nxt.adj),
        rv_req_delivered=rv_req, rv_rsp_delivered=rv_rsp,
        ae_req_delivered=ae_req, ae_rsp_delivered=ae_rsp, snap_delivered=sn,
        snap_installed_src=nxt.snap_installed_src,
        snap_installed_len=nxt.snap_installed_len,
        shadow_len=nxt.shadow_len, msg_count=nxt.msg_count,
        violations=nxt.violations,
        shadow_sub=nxt.shadow_sub, lat_hist=nxt.lat_hist,
        ev_counts=nxt.ev_counts,
        phase_ticks=nxt.phase_ticks,
        worst_lat=nxt.worst_lat, worst_phases=nxt.worst_phases,
        worst_key=nxt.worst_key, worst_client=nxt.worst_client,
        worst_sub=nxt.worst_sub,
    )


@functools.lru_cache(maxsize=None)
def _traced_program(static_cfg: SimConfig, n_ticks: int,
                    packed: bool = False):
    """One compiled traced-replay program per (static shape, tick count).
    Registered (packed and wide) in tpusim/lint.py's ProgramRegistry in
    the raft.replay draw-parity group: tracing must add zero draws, so
    the traced program's static draw-site count must equal the untraced
    replayer's — checked statically on every lint run (ISSUE 15).
    The scan length must be static (it shapes the stacked outputs), so
    n_ticks joins the cache key — fine for single-cluster replay. With
    ``packed`` the scan CARRY is the packed schema the pool/chunk programs
    use (ISSUE 9: trace shares the one state layout) and each tick widens
    on use; the TickRecord is computed from the wide views, so the trace —
    like the final state — is bit-identical across layouts."""

    def run(cluster_id, kn, seed):
        ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)
        state0 = init_cluster(static_cfg, ckey, kn)
        if packed:
            state0 = pack_state(static_cfg, state0)

        def body(carry, _):
            prev = unpack_state(static_cfg, carry) if packed else carry
            nxt = step_cluster(static_cfg, prev, ckey, kn)
            return (
                pack_state(static_cfg, nxt) if packed else nxt,
                _record(prev, nxt),
            )

        final, rec = jax.lax.scan(body, state0, None, length=n_ticks)
        if packed:
            final = unpack_state(static_cfg, final)
        return final, rec

    return jax.jit(run)


def replay_cluster_traced(
    cfg: SimConfig, seed: int, cluster_id: int, n_ticks: int, knobs=None
):
    """Re-run ONE cluster with the flight recorder on.

    Returns ``(final_state, trace)``: the final :class:`ClusterState`
    (bit-identical to ``engine.replay_cluster`` — same step, same PRNG
    stream) and a :class:`TickRecord` of host numpy arrays with a leading
    ``[n_ticks]`` axis.

    ``knobs``: optional dynamic-knob override (``engine.resolve_knobs``) —
    a coverage-pool row's mutated knob row must be applied here too, or the
    explain timeline would silently decode a DIFFERENT execution (base-knob
    Bernoulli thresholds) than the one that violated.
    """
    from madraft_tpu.tpusim.engine import resolve_knobs

    kn = resolve_knobs(cfg, knobs)
    # same layout rule as replay_cluster/run_pool: packed when exact
    packed = packed_layout_reason(cfg, kn, int(n_ticks)) is None
    prog = _traced_program(cfg.static_key(), int(n_ticks), packed)
    final, rec = jax.block_until_ready(
        prog(jnp.asarray(cluster_id, I32), kn,
             jnp.asarray(seed, jnp.uint32))
    )
    return final, jax.tree.map(np.asarray, rec)


# --------------------------------------------------------------- host decode
def alive_masks(rec: TickRecord) -> np.ndarray:
    """[T] int alive bitmask per tick (bit i = node i alive) — the
    schedule-invariant signal the bridge compares across backends."""
    n = rec.alive.shape[1]
    return (rec.alive.astype(np.int64) << np.arange(n)).sum(axis=1)


def decode_events(rec: TickRecord) -> list:
    """Stacked per-tick arrays -> structured event timeline.

    Every event is a dict with at least ``tick`` (1-based, matching
    ``first_violation_tick``) and ``event``; ticks with nothing notable
    emit nothing, so a 600-tick trace decodes to a readable timeline.
    """
    T, n = rec.role.shape
    events = []
    # tick-0 baseline = init_cluster: all followers, term 0, alive, fully
    # connected, empty logs, no commits, no violations
    prev_role = np.zeros(n, np.int64)
    prev_term = np.zeros(n, np.int64)
    prev_alive = np.ones(n, bool)
    prev_adj = np.full(n, (1 << n) - 1, np.int64)
    prev_len = np.zeros(n, np.int64)
    prev_shadow = 0
    prev_viol = 0
    for ti in range(T):
        t = ti + 1
        role = rec.role[ti]
        term = rec.term[ti]
        alive = rec.alive[ti]
        adj = rec.adj_mask[ti]
        llen = rec.log_len[ti]
        for i in range(n):
            if prev_alive[i] and not alive[i]:
                lost = int(prev_len[i] - llen[i])
                ev = {"tick": t, "event": "crash", "node": i}
                if lost > 0:  # un-fsynced suffix dropped (durability axis)
                    ev["lost_suffix"] = lost
                events.append(ev)
            elif alive[i] and not prev_alive[i]:
                events.append({"tick": t, "event": "restart", "node": i,
                               "term": int(term[i])})
        if (adj != prev_adj).any():
            events.append({
                "tick": t, "event": "partition_change",
                "adj_rows": [int(r) for r in adj],
            })
        for i in range(n):
            if term[i] > prev_term[i] and alive[i]:
                events.append({
                    "tick": t, "event": "term_bump", "node": i,
                    "term": int(term[i]),
                    "role": ROLE_NAMES[int(role[i])],
                })
            if role[i] == LEADER and prev_role[i] != LEADER:
                events.append({
                    "tick": t, "event": "leader_elected", "node": i,
                    "term": int(term[i]),
                })
            elif prev_role[i] == LEADER and role[i] != LEADER and alive[i]:
                events.append({
                    "tick": t, "event": "step_down", "node": i,
                    "term": int(term[i]),
                })
        for i in range(n):
            src = int(rec.snap_installed_src[ti][i])
            if src >= 0:
                events.append({
                    "tick": t, "event": "snapshot_install", "node": i,
                    "from": src,
                    "boundary": int(rec.snap_installed_len[ti][i]),
                })
        shadow = int(rec.shadow_len[ti])
        if shadow > prev_shadow:
            ev = {
                "tick": t, "event": "commit_advance",
                "committed": shadow, "delta": shadow - prev_shadow,
            }
            if rec.shadow_sub.shape[-1]:
                # metrics trace: the commit IS the ack — attach the
                # latencies of the client entries recorded this tick,
                # host-decoded from the per-tick submit stamps (no-ops and
                # unstamped service entries carry 0 and are skipped)
                subs = rec.shadow_sub[ti]
                ev["latencies"] = sorted(
                    int(t - s) for s in subs[subs > 0]
                )
            events.append(ev)
        viol = int(rec.violations[ti])
        new_bits = viol & ~prev_viol
        if new_bits:
            events.append({
                "tick": t, "event": "violation",
                "first": prev_viol == 0,
                "new_bits": new_bits,
                "names": violation_names(new_bits),
            })
        prev_role, prev_term, prev_alive = role, term, alive
        prev_adj, prev_len = adj, llen
        prev_shadow, prev_viol = shadow, viol
    return events


def events_in_window(
    events: list, center: Optional[int], window: int
) -> list:
    """Events within ``window`` ticks of ``center`` — violation events are
    always kept (they are the reason the user is here). ``window <= 0`` or
    no center (no violation found) returns the full timeline."""
    if center is None or center < 0 or window <= 0:
        return events
    return [
        e for e in events
        if abs(e["tick"] - center) <= window or e["event"] == "violation"
    ]


# ------------------------------------------------------------ Perfetto export
def chrome_trace(
    rec: TickRecord,
    ms_per_tick: int,
    events: Optional[list] = None,
    label: str = "cluster",
) -> dict:
    """Chrome/Perfetto trace-event JSON for one traced replay: one track
    (tid) per node with role spans (follower/candidate/leader/dead),
    instant events for the decoded timeline, and counter tracks for commit
    progress and per-tick deliveries. Load in ui.perfetto.dev or
    chrome://tracing."""
    if events is None:
        events = decode_events(rec)
    T, n = rec.role.shape
    us = float(ms_per_tick) * 1000.0  # ts unit is microseconds
    out = [{"name": "process_name", "ph": "M", "pid": 0,
            "args": {"name": label}}]
    for i in range(n):
        out.append({"name": "thread_name", "ph": "M", "pid": 0, "tid": i,
                    "args": {"name": f"node {i}"}})
    # role spans (complete "X" events over contiguous (role|dead) runs)
    states = np.where(rec.alive, rec.role, _DEAD)  # [T, n]
    for i in range(n):
        start = 0
        for ti in range(1, T + 1):
            if ti < T and states[ti, i] == states[start, i] \
                    and (states[start, i] != LEADER
                         or rec.term[ti, i] == rec.term[start, i]):
                continue
            sid = int(states[start, i])
            name = "dead" if sid == _DEAD else ROLE_NAMES[sid]
            if sid == LEADER:
                name = f"leader t{int(rec.term[start, i])}"
            out.append({
                "name": name, "ph": "X", "pid": 0, "tid": i,
                "ts": (start + 1) * us, "dur": (ti - start) * us,
                "args": {"term": int(rec.term[start, i]),
                         "commit": int(rec.commit[start, i])},
            })
            start = ti
    # instant events from the decoded timeline
    for e in events:
        ts = e["tick"] * us
        args = {k: v for k, v in e.items() if k not in ("tick", "event")}
        if "node" in e:
            out.append({"name": e["event"], "ph": "i", "s": "t", "pid": 0,
                        "tid": e["node"], "ts": ts, "args": args})
        elif e["event"] in ("partition_change", "violation"):
            out.append({"name": e["event"], "ph": "i", "s": "p", "pid": 0,
                        "tid": 0, "ts": ts, "args": args})
    # counters: commit progress and message deliveries per tick
    prev_shadow = -1
    for ti in range(T):
        ts = (ti + 1) * us
        shadow = int(rec.shadow_len[ti])
        if shadow != prev_shadow:
            out.append({"name": "committed", "ph": "C", "pid": 0, "ts": ts,
                        "args": {"committed": shadow}})
            prev_shadow = shadow
        out.append({
            "name": "deliveries", "ph": "C", "pid": 0, "ts": ts,
            "args": {
                "rv_req": int(rec.rv_req_delivered[ti]),
                "rv_rsp": int(rec.rv_rsp_delivered[ti]),
                "ae_req": int(rec.ae_req_delivered[ti]),
                "ae_rsp": int(rec.ae_rsp_delivered[ti]),
                "snap": int(rec.snap_delivered[ti]),
            },
        })
    # metrics trace (ISSUE 10): per-tick liveness-event counter tracks from
    # the cumulative ev_counts rows (deltas — the spike view that makes a
    # latency-tail bucket's CAUSE visible in the same timeline), plus a
    # commit-latency track (max latency folded per tick) so a tail op shows
    # as a spike at its ack tick.
    if rec.ev_counts.shape[-1]:
        from madraft_tpu.tpusim.config import METRIC_EVENTS

        ev = np.asarray(rec.ev_counts, np.int64)
        deltas = np.diff(np.concatenate([np.zeros((1, ev.shape[1]),
                                                  np.int64), ev], axis=0),
                         axis=0)
        idx = {name: k for k, name in enumerate(METRIC_EVENTS)}
        for ti in range(T):
            ts = (ti + 1) * us
            out.append({
                "name": "liveness", "ph": "C", "pid": 0, "ts": ts,
                "args": {
                    "elections_won": int(deltas[ti, idx["elections_won"]]),
                    "term_bumps": int(deltas[ti, idx["term_bumps"]]),
                    "crashes": int(deltas[ti, idx["crashes"]]),
                    "restarts": int(deltas[ti, idx["restarts"]]),
                    "commit_advances": int(
                        deltas[ti, idx["commit_advances"]]
                    ),
                },
            })
            subs = rec.shadow_sub[ti]
            lat = (ti + 1) - subs[subs > 0]
            out.append({
                "name": "commit_latency_ticks", "ph": "C", "pid": 0,
                "ts": ts,
                "args": {"max": int(lat.max()) if lat.size else 0},
            })
        # attribution plane (ISSUE 12): per-phase counter tracks — the
        # per-tick DELTA of each phase's exact tick total, so a latency
        # spike's phase shows as a spike in exactly one track
        from madraft_tpu.tpusim.config import phase_names

        pt = np.asarray(rec.phase_ticks, np.int64)
        names = phase_names(pt.shape[1])
        pdeltas = np.diff(
            np.concatenate([np.zeros((1, pt.shape[1]), np.int64), pt],
                           axis=0),
            axis=0,
        )
        for ti in range(T):
            out.append({
                "name": "latency_phases", "ph": "C", "pid": 0,
                "ts": (ti + 1) * us,
                "args": {name: int(pdeltas[ti, k])
                         for k, name in enumerate(names)},
            })
        # synthesized span for the traced worst op: the final tick's
        # register names the argmax-latency op — render it as a complete
        # span from its submit tick, phase vector in the args, on its own
        # track so the tail op is visible against the node timelines
        w_sub = int(rec.worst_sub[-1][0])
        if w_sub > 0:
            w_lat = int(rec.worst_lat[-1][0])
            out.append({"name": "thread_name", "ph": "M", "pid": 0,
                        "tid": n, "args": {"name": "worst op"}})
            out.append({
                "name": f"worst op ({w_lat} ticks)", "ph": "X", "pid": 0,
                "tid": n, "ts": w_sub * us, "dur": max(w_lat, 1) * us,
                "args": {
                    "latency_ticks": w_lat,
                    "submit_tick": w_sub,
                    "key": int(rec.worst_key[-1][0]),
                    "client": int(rec.worst_client[-1][0]),
                    "phases": {
                        name: int(rec.worst_phases[-1][k])
                        for k, name in enumerate(names)
                    },
                },
            })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def chrome_pool_timeline(rows: list, label: str = "pool",
                         manifest: Optional[dict] = None) -> dict:
    """Chrome/Perfetto trace-event JSON for a pool heartbeat stream (ISSUE
    17): the HOST timeline of a run — per-generation dispatch-gap /
    device-execution spans on a "device loop" track and the consumer
    thread's consume+emit span on a "host consume" track, plus counter
    tracks for window violations/s, coverage growth, window p99 and
    device_wait. This renders the PR-7 overlap claim over TIME: the host
    span of generation k sits under the device span of generation k+1
    exactly when the pipeline is doing its job, instead of being three
    summed scalars in the summary.

    ``rows`` are telemetry.read_heartbeat rows; ts is the row's wall_s (the
    fetch end of that generation) in microseconds. The final reconciliation
    row carries run-total timers, not per-generation deltas, so it
    contributes counters only, never spans. ``manifest`` (if given) rides
    the process metadata so the trace is self-describing."""
    out = [{"name": "process_name", "ph": "M", "pid": 0,
            "args": {"name": label,
                     **({"manifest": manifest} if manifest else {})}},
           {"name": "thread_name", "ph": "M", "pid": 0, "tid": 0,
            "args": {"name": "device loop"}},
           {"name": "thread_name", "ph": "M", "pid": 0, "tid": 1,
            "args": {"name": "host consume"}}]
    for row in rows:
        det = row.get("det", {})
        t = row.get("t", {})
        wall = t.get("wall_s")
        if wall is None:
            continue
        ts = wall * 1e6
        args = {"gen": row.get("gen"), "lane_ticks": row.get("lane_ticks"),
                "retired_w": det.get("retired_w"),
                "violating_w": det.get("violating_w")}
        if not row.get("final"):
            wait = t.get("device_wait_s")
            gap = t.get("dispatch_gap_s")
            if wait is not None:
                out.append({"name": f"chunk+harvest g{row.get('gen')}",
                            "ph": "X", "pid": 0, "tid": 0,
                            "ts": (wall - wait) * 1e6, "dur": wait * 1e6,
                            "args": args})
                if gap:
                    out.append({"name": "dispatch gap", "ph": "X",
                                "pid": 0, "tid": 0,
                                "ts": (wall - wait - gap) * 1e6,
                                "dur": gap * 1e6, "args": {}})
            host = t.get("host_overlap_s")
            if host is not None:
                # the consumer's work for generation g runs from the fetch
                # onward, under generation g+1's device execution
                out.append({"name": f"consume+emit g{row.get('gen')}",
                            "ph": "X", "pid": 0, "tid": 1, "ts": ts,
                            "dur": max(host, 1e-6) * 1e6, "args": args})
        if t.get("viol_per_s_w") is not None:
            out.append({"name": "violations_per_s", "ph": "C", "pid": 0,
                        "ts": ts,
                        "args": {"window": t["viol_per_s_w"]}})
        if det.get("new_fps") is not None:
            out.append({"name": "coverage_fingerprints", "ph": "C",
                        "pid": 0, "ts": ts,
                        "args": {"seen": det["new_fps"]}})
        lat = det.get("latency")
        if isinstance(lat, dict) and lat.get("p99_w") is not None:
            out.append({"name": "latency_p99_ticks", "ph": "C", "pid": 0,
                        "ts": ts, "args": {"p99_w": lat["p99_w"]}})
        if not row.get("final") and t.get("device_wait_s") is not None:
            out.append({"name": "device_wait_s", "ph": "C", "pid": 0,
                        "ts": ts, "args": {"wait": t["device_wait_s"]}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}
