"""Batch drivers: vmap over clusters, scan over ticks, pjit over chips.

The fuzzer is embarrassingly data-parallel over the cluster axis (SURVEY.md §5:
"batch parallelism over simulated clusters" is this project's scaling axis) — the
mesh sharding simply splits clusters across chips; XLA inserts no collectives on the
hot path, only for the final violation reduction.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from madraft_tpu.tpusim import coverage as _cov
from madraft_tpu.tpusim import metrics as _metrics
from madraft_tpu.tpusim import telemetry as _telemetry
from madraft_tpu.tpusim.config import (
    CoverageConfig,
    Knobs,
    SimConfig,
    pool_lanes_per_shard,
    violation_names,
)
from madraft_tpu.tpusim.config import phase_names as _phase_names
from madraft_tpu.tpusim.state import (
    ClusterState,
    abstract_bytes,
    init_cluster,
    pack_state,
    packed_layout_reason,
    tree_bytes,
    unpack_state,
)
from madraft_tpu.tpusim.step import step_cluster, step_cluster_packed

CLUSTER_AXIS = "clusters"
# Every cached program factory below (_fuzz_program, _pool_init_program,
# _chunk_program, the harvest/coverage/replay variants) is enumerated in
# tpusim/lint.py's ProgramRegistry and statically linted — lane isolation,
# PRNG discipline, packed widths, zero-when-off (ISSUE 15). A NEW cached
# program must be registered there; tests/test_lint.py pins the families.

# One device execution = one chunk of the host-looped chunked dispatch
# (PERF.md round 3: 256-tick compiled scans keep a single execution under the
# tunnel's per-call deadline; dispatch overhead ~3% vs 64-tick chunks).
# Promoted here from bench.py so bench/CLI/pool share ONE implementation.
CHUNK_TICKS = 256

# Small sweeps dispatch as uniform-knob programs instead of one
# per-cluster-knob program (the measured 2.4x layout cliff — see
# _fuzz_program); above this many distinct knob cells the per-cell batches
# get too small to fill the chip and the per-cluster layout wins back.
SWEEP_UNIFORM_MAX_CELLS = 8


class FuzzReport(NamedTuple):
    """Host-side summary of one fuzz run (per-cluster arrays, length n_clusters)."""

    violations: np.ndarray            # i32 bitmask per cluster (0 = clean)
    first_violation_tick: np.ndarray  # -1 = none
    first_leader_tick: np.ndarray     # -1 = never elected (liveness signal)
    committed: np.ndarray             # entries ever committed (shadow length)
    msg_count: np.ndarray             # delivered messages
    snap_installs: np.ndarray         # install-snapshot deliveries (2D metric)
    # metrics plane (ISSUE 10): None with cfg.metrics off, else per-cluster
    # [n, HIST_BUCKETS] / [n, len(METRIC_EVENTS)] rows (rows merge by sum)
    lat_hist: Optional[np.ndarray] = None
    ev_counts: Optional[np.ndarray] = None
    # attribution plane (ISSUE 12): per-cluster per-phase histograms/tick
    # totals and the worst-op registers; None with cfg.metrics off
    phase_hist: Optional[np.ndarray] = None    # [n, n_phases, HB]
    phase_ticks: Optional[np.ndarray] = None   # [n, n_phases]
    lat_ticks: Optional[np.ndarray] = None     # [n, 1]
    worst_lat: Optional[np.ndarray] = None     # [n, 1]
    worst_phases: Optional[np.ndarray] = None  # [n, n_phases]
    worst_key: Optional[np.ndarray] = None     # [n, 1]
    worst_client: Optional[np.ndarray] = None  # [n, 1]
    worst_sub: Optional[np.ndarray] = None     # [n, 1]

    @property
    def n_violating(self) -> int:
        return int((self.violations != 0).sum())

    def violating_clusters(self) -> np.ndarray:
        return np.nonzero(self.violations != 0)[0]


def _cluster_keys(seed, n_clusters: int, id0=None) -> jax.Array:
    """Per-cluster PRNG keys: fold_in(PRNGKey(seed), global_cluster_id).

    ``id0`` (optional traced offset) shifts the id range to [id0, id0 + n) —
    what the pool's refill and the uniform sweep dispatch need so the
    (seed, cluster_id) replay contract holds for GLOBAL ids. ``None`` (the
    historic spelling, ids 0..n-1) keeps the traced program of every
    existing fuzz caller byte-identical, preserving the warm XLA cache."""
    base = jax.random.PRNGKey(seed)
    ids = jnp.arange(n_clusters)
    if id0 is not None:
        ids = ids + id0
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(ids)


@functools.lru_cache(maxsize=None)
def _fuzz_program(
    static_cfg: SimConfig,
    n_clusters: int,
    mesh: Optional[Mesh],
    per_cluster_knobs: bool = False,
):
    """One compiled program per (static shape, batch, mesh, knob layout).

    Everything else — probabilities, timeouts, quorum override, tick count —
    is a runtime argument: the dynamic knobs ride in as a `Knobs` pytree and
    the tick count as a `fori_loop` bound. Two configs differing only in
    dynamic knobs (or tick counts) share this program, which is what keeps a
    cold test-suite run compile-light.

    ``per_cluster_knobs`` picks the knob layout. UNIFORM (scalars, vmap
    in_axes=None) is the default and the fast path: runtime scalar knobs
    measured WITHIN NOISE of compile-time-baked constants (19.6 vs 20.9
    M steps/s at the 4096-cluster flagship). Per-cluster knob ARRAYS — one
    value per cluster, what make_sweep_fn needs to sweep a fault grid in one
    program — measured a 2.4x cliff (8.1 M): vmapping the knob axis pushes a
    per-cluster scalar into every elementwise op, defeating broadcast
    vectorization. So sweeps alone pay it; plain fuzzing never does.
    """
    constraint = None
    if mesh is not None:
        constraint = NamedSharding(mesh, P(mesh.axis_names[0]))
    kn_ax = 0 if per_cluster_knobs else None

    def run(seed, kn, n_ticks) -> ClusterState:
        keys = _cluster_keys(seed, n_clusters)
        states = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, kn_ax)
        )(keys, kn)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys2 = jax.lax.with_sharding_constraint(keys, constraint)
            if per_cluster_knobs:
                kn = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, constraint), kn
                )
        else:
            keys2 = keys

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_cluster, static_cfg),
                in_axes=(0, 0, kn_ax),
            )(carry, keys2, kn)

        return jax.lax.fori_loop(0, n_ticks, body, states)

    return jax.jit(run)


class FuzzProgram:
    """Callable ``fn(seed) -> final state`` (what make_*_fuzz_fn always
    returned) that can additionally split compile time from execute time —
    the run-telemetry every CLI fuzz/sweep report carries so throughput is
    observable per invocation, not only via bench.py.

    ``compile_timed(seed)`` AOT-compiles the underlying jitted program
    (``jit(...).lower().compile()``) and returns the wall seconds it took;
    subsequent calls dispatch straight to the compiled executable, so a
    later timed call measures pure execution. Never calling it keeps the
    historic behavior exactly (plain jit dispatch, compile on first call).
    The args the program sees are identical either way, so reports stay
    bit-identical — the AOT path changes WHEN compilation happens, not what
    is compiled.
    """

    def __init__(self, prog, make_args):
        self._prog = prog
        self._make_args = make_args
        self._compiled = None
        self._aot_failed = False
        self.compile_s = None

    def compile_timed(self, seed) -> Optional[float]:
        """Compile for ``seed``'s arg shapes, once; returns wall seconds
        (cached result on repeat calls, None if AOT lowering failed and the
        plain jit path will be used — the failure is memoized too, so a
        repeat call never re-pays a failing lower+compile)."""
        if self._compiled is None and not self._aot_failed:
            t0 = time.perf_counter()
            try:
                self._compiled = self._prog.lower(
                    *self._make_args(seed)
                ).compile()
                self.compile_s = time.perf_counter() - t0
            except Exception:  # fall back to plain jit dispatch
                self._aot_failed = True
        return self.compile_s

    def __call__(self, seed):
        args = self._make_args(seed)
        if self._compiled is not None:
            return self._compiled(*args)
        return self._prog(*args)


def run_telemetry(fn, rep_fn, seed, n_steps: int,
                  n_lanes: Optional[int] = None) -> tuple:
    """Shared CLI-report telemetry runner: AOT-compile ``fn`` (timed), run
    it (timed), and return ``(report, telemetry_dict)``. ``rep_fn`` maps the
    final device state to the host report and is included in execute time —
    it contains the device->host sync that makes the measurement honest
    (bench.py methodology). ``n_lanes`` (when given) adds the state-
    footprint telemetry (ISSUE 9): total bytes of the final state's LIVE
    device buffers and the per-lane share, plus which layout the run
    carried (``fn.state_layout`` when the runner packs its carry; the
    single-program fuzz/sweep paths stay wide)."""
    import jax as _jax

    # duck-typed: FuzzProgram and the sweep's uniform dispatch both expose
    # the AOT compile/execute split
    compile_s = fn.compile_timed(seed) if hasattr(fn, "compile_timed") else None
    t0 = time.perf_counter()
    final = _jax.block_until_ready(fn(seed))
    rep = rep_fn(final)
    execute_s = time.perf_counter() - t0
    dev = _jax.devices()[0]
    tele = {
        "execute_s": round(execute_s, 4),
        "steps_per_sec": round(n_steps / execute_s, 1),
        "device": str(dev),
        "backend": dev.platform,
    }
    if n_lanes:
        # a packing runner's RESIDENT carry bytes win over the final state
        # it returns (make_chunked_fuzz_fn always widens the final, so
        # tree_bytes(final) would report the wide footprint under a packed
        # layout label); single-program fuzz/sweep runners expose neither
        # attribute and their final state IS the resident state
        sb = getattr(fn, "state_hbm_bytes", None) or tree_bytes(final)
        tele["state_layout"] = getattr(fn, "state_layout", "wide")
        tele["state_hbm_bytes"] = sb
        tele["bytes_per_lane"] = round(sb / n_lanes, 1)
        # exact-or-wide fallback: when a runner chose the wide layout
        # because a bound failed, say WHICH bound (ISSUE 11) — a silent
        # "wide" reads as a regression, not a gate
        reason = getattr(fn, "state_layout_reason", None)
        if reason:
            tele["state_layout_reason"] = reason
    if compile_s is not None:
        tele["compile_s"] = round(compile_s, 4)
    else:
        # no AOT split available: the timed window paid compile too — say
        # so rather than silently understating steps_per_sec
        tele["execute_includes_compile"] = True
    return rep, tele


def make_fuzz_fn(
    cfg: SimConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
):
    """Build fn(seed) -> final batched ClusterState.

    With a mesh, the cluster axis of every state leaf is sharded over the mesh's
    first axis (pure data parallelism; per-step work stays chip-local).
    """
    prog = _fuzz_program(cfg.static_key(), n_clusters, mesh)
    kn = cfg.knobs()  # uniform runtime scalars — the fast knob layout
    ticks = jnp.asarray(n_ticks, jnp.int32)
    # coerce exactly like fuzz()/replay_cluster(): with x64 enabled a
    # negative or >= 2^32 Python-int seed would otherwise promote to int64
    # and silently break the (seed, cluster_id) replay contract
    return FuzzProgram(
        prog, lambda seed: (jnp.asarray(seed, jnp.uint32), kn, ticks)
    )


# --------------------------------------------------------------------------
# Chunked dispatch + the continuous fuzzing pool (retire-and-refill).
#
# bench.py's hand-rolled donated chunked dispatch is promoted here: a
# compiled chunk program advances the whole batch T ticks with a DONATED
# state carry (the double-buffer is reused, so peak HBM matches the
# fixed-horizon program), and the pool interleaves chunks with a compiled
# harvest+refill step that retires finished slots ON DEVICE — only the small
# per-slot report arrays ever reach the host. Retired lanes are re-seeded
# under fresh GLOBAL cluster ids from a monotone counter, so every pool hit
# reproduces through replay_cluster(seed, global_cluster_id) exactly like a
# fuzz hit — across arbitrarily many refill generations.
# --------------------------------------------------------------------------


class PoolHarvest(NamedTuple):
    """Per-slot report arrays fetched at each harvest (all length n_lanes;
    values are PRE-refill — the retiring cluster's final numbers)."""

    retired: jax.Array             # bool: violated or horizon-reached
    ids: jax.Array                 # i32 global cluster id of the slot
    violations: jax.Array          # i32 sticky bitmask
    first_violation_tick: jax.Array
    first_leader_tick: jax.Array
    committed: jax.Array           # shadow_len
    msg_count: jax.Array
    snap_installs: jax.Array
    ticks_run: jax.Array           # the cluster's age (= state.tick)
    # metrics plane (ISSUE 10): [n, HIST_BUCKETS] / [n, len(METRIC_EVENTS)]
    # per-lane rows (ZERO-SIZE trailing axis with metrics off — the
    # metrics-off harvest fetch is unchanged); summaries merge them by sum
    lat_hist: jax.Array
    ev_counts: jax.Array
    # attribution plane (ISSUE 12; zero-size trailing axes when off):
    # per-phase rows merge by sum, the worst-op registers by max
    phase_hist: jax.Array      # [n, n_phases, HB]
    phase_ticks: jax.Array     # [n, n_phases]
    lat_ticks: jax.Array       # [n, 1]
    worst_lat: jax.Array       # [n, 1]
    worst_phases: jax.Array    # [n, n_phases]
    worst_key: jax.Array       # [n, 1]
    worst_client: jax.Array    # [n, 1]
    worst_sub: jax.Array       # [n, 1]


def _constraint(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def _retired_row(h, lane: int, wall: float, viol_total: int) -> dict:
    """One retired-cluster report dict (the streaming JSONL schema) — ONE
    builder for the plain and coverage pools, so the schema cannot drift
    between them (the coverage pool appends its extra columns)."""
    mask = int(h.violations[lane])
    row = {
        "cluster_id": int(h.ids[lane]),
        "ticks_run": int(h.ticks_run[lane]),
        "violations": mask,
        "violation_names": violation_names(mask),
        "first_violation_tick": int(h.first_violation_tick[lane]),
        "first_leader_tick": int(h.first_leader_tick[lane]),
        "committed": int(h.committed[lane]),
        "msg_count": int(h.msg_count[lane]),
        "snap_installs": int(h.snap_installs[lane]),
        "wall_s": round(wall, 3),
        "violations_per_s": (
            round(viol_total / wall, 3) if wall > 0 else None
        ),
    }
    if h.lat_hist.shape[-1]:
        # metrics columns (ISSUE 10): the retiring lane's whole-lifetime
        # histogram and counter rows ride every JSONL report, so any single
        # retired cluster's tail is inspectable (and `stats` re-merges them)
        row["latency_hist"] = [int(x) for x in h.lat_hist[lane]]
        row["events"] = _metrics.event_summary(h.ev_counts[lane])
        # attribution columns (ISSUE 12): phase rows keyed by name (the
        # merge key `stats` uses) + the lane's worst op
        names = _phase_names(h.phase_hist.shape[-2])
        row["latency_phases"] = {
            name: [int(x) for x in h.phase_hist[lane, p]]
            for p, name in enumerate(names)
        }
        row["worst_op"] = _metrics.worst_op_dict(
            h.worst_lat[lane], h.worst_phases[lane], h.worst_key[lane],
            h.worst_client[lane], h.worst_sub[lane],
        )
    return row


def _pool_summary(n_clusters: int, horizon: int, chunk_ticks: int,
                  lane_ticks: int, acct: "_PoolAccount", wall: float,
                  tele: dict, id_fields: dict) -> dict:
    """The pool summary dict — ONE builder for every pool path (monotone or
    lane-partitioned ids, plain or coverage; the coverage pools add their
    ``coverage`` sub-dict on top). ``tele`` carries the pipeline telemetry
    (compile_s / dispatch_gap_s / host_overlap_s) and ``id_fields`` the
    id-scheme-specific bookkeeping (next_cluster_id, or the lane scheme's
    id_scheme / devices / id_watermark)."""
    dispatched = lane_ticks * n_clusters
    extra = {}
    if acct.hist_total is not None:
        # merged across lanes (and, in a sharded pool, across shards) by
        # plain addition — the summary's client-experience digest; the
        # attribution plane (ISSUE 12) adds the phase breakdown (device-
        # count invariant, like the e2e histogram) and the run's worst op
        extra["latency"] = _metrics.latency_summary(acct.hist_total)
        extra["latency"]["phases"] = _metrics.phases_summary(
            acct.phase_total, acct.phase_ticks_total
        )
        extra["latency"]["ticks_total"] = acct.lat_ticks_total
        extra["events"] = _metrics.event_summary(acct.ev_total)
        extra["worst_op"] = acct.worst
    return {
        "lanes": n_clusters,
        "horizon": horizon,
        "chunk_ticks": chunk_ticks,
        "lane_ticks": lane_ticks,
        "ticks_dispatched": dispatched,
        "retired": acct.retired_total,
        "retired_violating": acct.viol_total,
        "violating_clusters": acct.viol_clusters[:16],
        "violating_clusters_total": len(acct.viol_clusters),
        "violation_names": violation_names(acct.union),
        "effective_cluster_steps": int(acct.effective),
        "wall_s": round(wall, 3),
        "steps_per_sec": round(dispatched / wall, 1) if wall > 0 else None,
        "effective_steps_per_sec": (
            round(acct.effective / wall, 1) if wall > 0 else None
        ),
        "violations_per_s": (
            round(acct.viol_total / wall, 3) if wall > 0 else None
        ),
        **tele,
        **id_fields,
        **extra,
    }


def _hb_context(cfg: SimConfig, seed: int, n_clusters: int, horizon: int,
                chunk_ticks: int, devices: Optional[int],
                budget_ticks: Optional[int],
                budget_seconds: Optional[float],
                coverage: Optional[CoverageConfig] = None,
                profile: str = "") -> dict:
    """The manifest's config echo (ISSUE 17): enough for a watcher to know
    WHAT is running (and for budget_frac/ETA) without the launching shell.
    ``static_key`` is the compiled-program identity — two manifests with
    the same static_key run the same executables."""
    ctx = {
        "kind": "pool",
        "seed": int(seed),
        "lanes": int(n_clusters),
        "horizon": int(horizon),
        "chunk_ticks": int(chunk_ticks),
        "devices": devices,
        "budget_ticks": budget_ticks,
        "budget_seconds": budget_seconds,
        "static_key": repr(cfg.static_key()),
        "config": dataclasses.asdict(cfg),
    }
    if coverage is not None:
        ctx["coverage"] = dataclasses.asdict(coverage)
    if profile:
        # ISSUE 19: the active game-day scenario name (schema-compatible
        # additive field — absent on unnamed-knob runs; MIGRATION.md)
        ctx["profile"] = profile
    return ctx


class _PoolAccount:
    """Host-side harvest accounting shared by every pool path: retirement
    counters, the effective-steps convention (post-violation ticks inside
    the retirement chunk are waste, not coverage), JSONL row emission —
    and, when the harvest carries coverage columns (``new_fps``), the
    discovery curve and refill-kind tallies. ``consume`` is called only
    from _pipeline's single consumer thread (in harvest order) while the
    NEXT chunk executes on device — the overlap — and ``finish`` only
    after that thread joins, so no locking is needed."""

    def __init__(self, on_retired, guided: bool = False, heartbeat=None):
        self.on_retired = on_retired
        self.guided = guided
        # live-telemetry plane (ISSUE 17): a telemetry.HeartbeatWriter (or
        # None). Driven from consume() — i.e. from the consumer thread,
        # off the critical path — on already-fetched numpy arrays only.
        self.heartbeat = heartbeat
        self.retired_total = 0
        self.viol_total = 0
        self.effective = 0
        self.union = 0
        self.viol_clusters: list = []
        self.last = None
        # coverage extras (stay zero on plain harvests)
        self.seen_prev = 0
        self.new_fp_per_gen: list = []
        self.refills_mutated = 0
        self.refills_fresh = 0
        self.lane_new_fps_total = 0
        # metrics extras (ISSUE 10; stay None on metrics-off harvests):
        # retired lanes' histogram/counter rows merged by SUM — the pool-
        # summary analogue of the sharded seen-set OR-reduce
        self.hist_total: Optional[np.ndarray] = None
        self.ev_total: Optional[np.ndarray] = None
        # attribution extras (ISSUE 12): phase rows merge by sum, the
        # worst op by the deterministic max rule (metrics.merge_worst)
        self.phase_total: Optional[np.ndarray] = None
        self.phase_ticks_total: Optional[np.ndarray] = None
        self.lat_ticks_total = 0
        self.worst: Optional[dict] = None

    def consume(self, h, wall: float, children_ran: bool,
                timing: Optional[dict] = None) -> None:
        """Account one fetched harvest. ``children_ran`` is True iff a
        following chunk was dispatched, i.e. this harvest's refilled
        children actually ran a tick — the refills_* summary fields claim
        to record how lanes were actually spent. ``timing`` is _pipeline's
        per-generation delta dict (lane_ticks / device_wait_s /
        dispatch_gap_s) for the heartbeat row's timing column group."""
        t0c = time.perf_counter() if self.heartbeat is not None else 0.0
        self.last = h
        cov = hasattr(h, "new_fps")
        if cov:
            seen_now = int(h.seen_bits)
            self.new_fp_per_gen.append(seen_now - self.seen_prev)
            self.seen_prev = seen_now
        if h.lat_hist.shape[-1] and self.hist_total is None:
            self.hist_total = np.zeros(h.lat_hist.shape[-1], np.int64)
            self.ev_total = np.zeros(h.ev_counts.shape[-1], np.int64)
            self.phase_total = np.zeros(h.phase_hist.shape[-2:], np.int64)
            self.phase_ticks_total = np.zeros(h.phase_ticks.shape[-1],
                                              np.int64)
        if self.hist_total is not None and h.retired.any():
            self.hist_total += h.lat_hist[h.retired].sum(axis=0)
            self.ev_total += h.ev_counts[h.retired].sum(axis=0)
            self.phase_total += h.phase_hist[h.retired].sum(axis=0)
            self.phase_ticks_total += h.phase_ticks[h.retired].sum(axis=0)
            self.lat_ticks_total += int(h.lat_ticks[h.retired].sum())
            self.worst = _metrics.merge_worst_registers(
                h.worst_lat[h.retired], h.worst_phases[h.retired],
                h.worst_key[h.retired], h.worst_client[h.retired],
                h.worst_sub[h.retired], ids=h.ids[h.retired],
                into=self.worst,
            )
        for lane in np.nonzero(h.retired)[0]:
            mask = int(h.violations[lane])
            fvt = int(h.first_violation_tick[lane])
            ticks_run = int(h.ticks_run[lane])
            self.retired_total += 1
            # pre-violation ticks only: post-violation ticks inside the
            # retirement chunk are waste, not coverage
            self.effective += fvt if mask else ticks_run
            if cov:
                self.lane_new_fps_total += int(h.new_fps[lane])
            if mask:
                self.viol_total += 1
                self.union |= mask
                self.viol_clusters.append(int(h.ids[lane]))
            if self.on_retired is not None:
                row = _retired_row(h, lane, wall, self.viol_total)
                if cov:
                    row["new_fingerprints"] = int(h.new_fps[lane])
                    row["refill"] = _cov.REFILL_NAMES[
                        int(h.refill_kind[lane])
                    ]
                    row["knobs"] = {
                        name: float(getattr(h.knobs, name)[lane])
                        for name in _cov.MUTABLE_KNOBS
                    }
                self.on_retired(row)
        if cov and children_ran and self.guided:
            productive = h.retired & (h.new_fps > 0)
            self.refills_mutated += int(productive.sum())
            self.refills_fresh += int((h.retired & ~productive).sum())
        if self.heartbeat is not None:
            # this generation's own consume wall IS its host_overlap share
            # (the work _pipeline hides under the next chunk's execution)
            t = dict(timing or {})
            t["host_overlap_s"] = time.perf_counter() - t0c
            self.heartbeat.generation(self, wall, t)

    def finish(self) -> None:
        """In-flight lanes at shutdown are clean (violated => retired):
        their ticks so far are honest pre-violation coverage."""
        h = self.last
        self.effective += int(h.ticks_run[~h.retired].sum())
        if hasattr(h, "new_fps"):
            self.lane_new_fps_total += int(h.new_fps[~h.retired].sum())
        if self.hist_total is not None:
            # in-flight lanes' ops are real observations (same convention
            # as their effective ticks above); retired rows were already
            # merged at their harvest, so nothing double-counts
            self.hist_total += h.lat_hist[~h.retired].sum(axis=0)
            self.ev_total += h.ev_counts[~h.retired].sum(axis=0)
            self.phase_total += h.phase_hist[~h.retired].sum(axis=0)
            self.phase_ticks_total += h.phase_ticks[~h.retired].sum(axis=0)
            self.lat_ticks_total += int(h.lat_ticks[~h.retired].sum())
            self.worst = _metrics.merge_worst_registers(
                h.worst_lat[~h.retired], h.worst_phases[~h.retired],
                h.worst_key[~h.retired], h.worst_client[~h.retired],
                h.worst_sub[~h.retired], ids=h.ids[~h.retired],
                into=self.worst,
            )


def _pipeline(launch_chunk, launch_harvest, acct: _PoolAccount,
              chunk_ticks: int, budget_ticks: Optional[int],
              budget_seconds: Optional[float]) -> tuple:
    """The pipelined chunk→harvest loop shared by every pool path.

    ``launch_chunk()`` / ``launch_harvest()`` dispatch one compiled chunk
    and one harvest+refill over the carry; the main loop runs them in the
    strict PR-3 device order (chunk k, harvest k, fetch k — so the program
    sequence, and with it every report, is bit-identical to the serialized
    loop), but hands each FETCHED harvest to a dedicated consumer thread:
    JSONL emission, refill bookkeeping, and coverage accounting for chunk
    k then run WHILE chunk k+1 executes on device, instead of sitting on
    the critical path between chunks.

    Why a thread and not dispatch-ahead: measured on the CPU backend,
    whether a donating jit dispatch returns asynchronously or runs the
    whole execution inline inside the dispatch is BISTABLE — it depends on
    the execution history, and both regimes are self-sustaining — so a
    loop that relies on launching chunk k+1 before touching harvest k
    silently degrades to full serialization in one of the two stable
    regimes. The consumer thread overlaps in every regime and on every
    backend: the main thread only performs device calls (which release
    the GIL), the worker only consumes already-fetched numpy arrays and
    never calls into JAX. One worker + a FIFO queue keeps consumption in
    harvest order, so rows stream and accumulate exactly as before; the
    bounded queue back-pressures a host-bound run instead of buffering
    unboundedly.

    Telemetry:
    - ``device_wait_s``    main-thread wall inside device dispatch+fetch:
                           the device-bound share of the run.
    - ``dispatch_gap_s``   everything else on the main thread plus the
                           end-of-run drain (waiting for the worker to
                           finish outstanding host work): the wall that
                           separates consecutive device dispatches.
                           Healthy = milliseconds; it grows toward the
                           host work only when emission out-runs a whole
                           chunk's device wall.
    - ``host_overlap_s``   harvest-processing wall that ran while the
                           device loop was still dispatching — work the
                           serialized loop paid on the critical path, now
                           hidden under device execution.

    Returns ``(lane_ticks, wall, dispatch_gap_s, device_wait_s,
    host_overlap_s)``.
    """
    import queue as queue_mod
    import threading

    t0 = time.perf_counter()
    q: queue_mod.Queue = queue_mod.Queue(maxsize=8)
    host_work = [0.0]
    exc: list = []

    def consumer():
        while True:
            item = q.get()
            if item is None:
                return
            h, wall_at_fetch, children_ran, timing = item
            t1 = time.perf_counter()
            try:
                acct.consume(h, wall_at_fetch, children_ran, timing)
            except BaseException as e:  # surface on the main thread
                exc.append(e)
                return
            finally:
                host_work[0] += time.perf_counter() - t1

    worker = threading.Thread(target=consumer, name="pool-harvest-consumer")
    worker.start()
    lane_ticks = 0
    device_s = 0.0
    t_loop = t0
    t_prev = t0  # previous fetch end: per-generation dispatch-gap origin
    try:
        while True:
            t1 = time.perf_counter()
            launch_chunk()
            h_dev = launch_harvest()
            # the ONLY device->host fetch of the loop: small per-slot arrays
            h = jax.tree.map(np.asarray, h_dev)
            t2 = time.perf_counter()
            device_s += t2 - t1
            lane_ticks += chunk_ticks
            wall = t2 - t0
            stop = (
                (budget_ticks is not None and lane_ticks >= budget_ticks)
                or (budget_seconds is not None and wall >= budget_seconds)
            )
            # per-generation timing deltas for the heartbeat row (ISSUE
            # 17) — the same quantities the run-total telemetry below
            # sums, sliced at the generation boundary
            timing = {
                "lane_ticks": lane_ticks,
                "device_wait_s": t2 - t1,
                "dispatch_gap_s": max(0.0, t1 - t_prev),
            }
            t_prev = t2
            while not exc:  # a dead worker must not deadlock the put
                try:
                    q.put((h, wall, not stop, timing), timeout=1.0)
                    break
                except queue_mod.Full:
                    continue
            if stop or exc:
                break
    finally:
        while worker.is_alive():  # a full queue must not deadlock shutdown
            try:
                q.put(None, timeout=1.0)
                break
            except queue_mod.Full:
                continue
        t_loop = time.perf_counter()
        worker.join()
        if exc:
            raise exc[0]
    t_end = time.perf_counter()
    drain = t_end - t_loop
    gap = max(0.0, (t_loop - t0) - device_s) + drain
    overlap = max(0.0, host_work[0] - drain)
    return lane_ticks, t_end - t0, gap, device_s, overlap


def default_chunk_ticks(horizon: int) -> int:
    """The pool's default chunk size: the horizon split into equal chunks
    no larger than CHUNK_TICKS, so lanes retire AT the horizon rather than
    a chunk-rounding overshoot past it (256-tick chunks against a 600-tick
    horizon would retire every clean lane at 768 ticks — 28% of the budget
    spent on ticks the fixed-horizon comparison never pays). The single
    source of the rule for run_pool and bench.py's A/B."""
    k = -(-horizon // CHUNK_TICKS)
    return -(-horizon // k)


def _fresh_batch(static_cfg: SimConfig, keys, kn, kn_axis, packed: bool):
    """init_cluster over a key batch, in the requested layout — the ONE
    spelling of "make fresh lanes" shared by the init and every harvest
    program, so the packed schema cannot drift between birth sites."""
    states = jax.vmap(
        functools.partial(init_cluster, static_cfg), in_axes=(0, kn_axis)
    )(keys, kn)
    if packed:
        states = jax.vmap(functools.partial(pack_state, static_cfg))(states)
    return states


@functools.lru_cache(maxsize=None)
def _pool_init_program(static_cfg: SimConfig, n_clusters: int,
                       mesh: Optional[Mesh], packed: bool = False):
    """(seed, kn, id0) -> (states, keys, ids): a fresh batch covering global
    cluster ids [id0, id0 + n). Identical init math to _fuzz_program, split
    out so the chunk loop can carry states across compiled calls. With
    ``packed`` the returned states are the PackedClusterState carry (ISSUE
    9) — the chunk/harvest programs must be built with the same flag."""
    constraint = _constraint(mesh)

    def run(seed, kn, id0):
        ids = jnp.arange(n_clusters, dtype=jnp.int32) + id0
        keys = _cluster_keys(seed, n_clusters, id0)
        states = _fresh_batch(static_cfg, keys, kn, None, packed)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys = jax.lax.with_sharding_constraint(keys, constraint)
            ids = jax.lax.with_sharding_constraint(ids, constraint)
        return states, keys, ids

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _chunk_program(static_cfg: SimConfig, n_clusters: int,
                   packed: bool = False):
    """T ticks of the batched step with a DONATED state carry — one
    implementation for bench/CLI/pool. The tick count is a runtime
    fori_loop bound, so one compiled program serves every chunk length
    (full chunks, the remainder chunk, and any pool chunk size). With
    ``packed`` the carry is the narrow-dtype PackedClusterState and each
    tick widens-on-use (step_cluster_packed) — the HBM-resident share of
    the loop is the packed footprint, the arithmetic is unchanged i32."""
    step_fn = step_cluster_packed if packed else step_cluster

    def run(states, keys, kn, n_ticks):
        def body(_, carry):
            return jax.vmap(
                functools.partial(step_fn, static_cfg),
                in_axes=(0, 0, None),
            )(carry, keys, kn)

        return jax.lax.fori_loop(0, n_ticks, body, states)

    return jax.jit(run, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _unpack_batch_program(static_cfg: SimConfig, n_clusters: int):
    """Packed carry -> wide batched ClusterState (donated input): the one
    widening at the END of a packed chunked-fuzz run, so callers keep
    receiving the historic wide final state."""
    return jax.jit(
        lambda p: jax.vmap(functools.partial(unpack_state, static_cfg))(p),
        donate_argnums=(0,),
    )


def _retire_and_reseed(states, ids, next_id, seed, horizon):
    """The (seed, global_cluster_id) replay contract, ONE implementation for
    the plain and coverage harvest programs: the retirement rule, monotone
    left-to-right global-id assignment over retired lanes (deterministic, so
    a pool run is exactly reproducible from its arguments), and the one-rule
    key derivation — key = fold_in(PRNGKey(seed), global_id) for EVERY lane:
    equal to the old key on kept lanes, the fresh key on refilled ones."""
    retired = (states.violations != 0) | (states.tick >= horizon)
    rank = jnp.cumsum(retired.astype(jnp.int32)) - 1
    new_ids = jnp.where(retired, next_id + rank, ids)
    base = jax.random.PRNGKey(seed)
    new_keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(new_ids)
    n_ret = retired.astype(jnp.int32).sum()
    return retired, new_ids, new_keys, n_ret


def _scatter_fresh(retired, fresh, states):
    """Scatter freshly init_cluster-ed states into the retired lanes."""

    def sel(f, s):
        m = retired.reshape(retired.shape + (1,) * (f.ndim - 1))
        return jnp.where(m, f, s)

    return jax.tree.map(sel, fresh, states)


def _wide_view(static_cfg: SimConfig, states, packed: bool):
    """The wide view of a (possibly packed) batched carry — what the
    retirement rule and the report snapshot read. XLA dead-code-eliminates
    the unpacking of fields a program never touches."""
    if not packed:
        return states
    return jax.vmap(functools.partial(unpack_state, static_cfg))(states)


@functools.lru_cache(maxsize=None)
def _harvest_program(static_cfg: SimConfig, n_clusters: int,
                     packed: bool = False):
    """Harvest + refill, one compiled call (states donated): snapshot the
    small per-slot report arrays, then scatter freshly init_cluster-ed
    states into retired lanes under new global ids next_id, next_id+1, ...
    (see _retire_and_reseed). Single-device by construction — the monotone
    id rank is a batch-wide cumsum; the sharded pool uses
    _lane_harvest_program instead. With ``packed`` the carried states are
    PackedClusterState rows: retire/snapshot read the widened view and the
    refill scatters freshly PACKED lanes, so the carry never widens."""

    def run(states, keys, ids, next_id, seed, kn, horizon):
        wide = _wide_view(static_cfg, states, packed)
        retired, new_ids, new_keys, n_ret = _retire_and_reseed(
            wide, ids, next_id, seed, horizon
        )
        harvest = _pool_snapshot(wide, retired, ids)
        fresh = _fresh_batch(static_cfg, new_keys, kn, None, packed)
        states_out = _scatter_fresh(retired, fresh, states)
        return states_out, new_keys, new_ids, next_id + n_ret, harvest

    return jax.jit(run, donate_argnums=(0,))


# --------------------------------------------------------------------------
# Pod-scale sharding (ROADMAP item 1): the LANE-PARTITIONED global-id scheme.
#
# The monotone scheme above ranks retired lanes with a batch-wide cumsum —
# a cross-lane (on a mesh: cross-SHARD) scan at every harvest. The sharded
# pool partitions the id space per lane instead: lane l's generation-g
# cluster owns global id  g * n_lanes + l  (generation 0 is the initial
# batch, ids 0..n-1 — identical coverage of the id space to the monotone
# scheme's first generation). Refill bookkeeping is then a per-lane
# generation bump: purely elementwise, so a mesh-sharded harvest runs with
# ZERO cross-shard communication on the hot path — shard s (a contiguous
# lane slice) draws exactly the ids congruent to its lanes mod n_lanes.
#
# The payoff is a theorem the tests enforce: a cluster's whole lifetime is
# a pure function of (seed, global_id, chunk cadence, horizon) — every lane
# advances in lockstep by chunk_ticks, so a cluster born at any harvest
# boundary sees the same chunk schedule — and the id SET a budgeted run
# draws is a pure function of the budget (lane l always draws l, n+l,
# 2n+l, ...). Hence the multiset of retired-cluster reports over a fixed
# tick budget is IDENTICAL at any device count, and every report replays
# through replay_cluster(seed, global_id) exactly like a fuzz hit.
# config.pool_lane/pool_generation/pool_shard decode the scheme.
# --------------------------------------------------------------------------


def _lane_reseed(states, ids, gens, seed, horizon, n_clusters: int):
    """The lane-partitioned analogue of _retire_and_reseed: same retirement
    rule, per-lane generation counters instead of a batch-wide cumsum, and
    the same one-rule key derivation — key = fold_in(PRNGKey(seed),
    global_id) for EVERY lane."""
    retired = (states.violations != 0) | (states.tick >= horizon)
    gens_new = gens + retired.astype(jnp.int32)
    lane = jnp.arange(n_clusters, dtype=jnp.int32)
    new_ids = jnp.where(retired, gens_new * n_clusters + lane, ids)
    base = jax.random.PRNGKey(seed)
    new_keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(new_ids)
    return retired, new_ids, new_keys, gens_new


def _pool_snapshot(states, retired, ids) -> PoolHarvest:
    """The per-slot report arrays every harvest fetches (PRE-refill) — one
    builder for the monotone and lane-partitioned harvest programs."""
    return PoolHarvest(
        retired=retired,
        ids=ids,
        violations=states.violations,
        first_violation_tick=states.first_violation_tick,
        first_leader_tick=states.first_leader_tick,
        committed=states.shadow_len,
        msg_count=states.msg_count,
        snap_installs=states.snap_install_count,
        ticks_run=states.tick,
        lat_hist=states.lat_hist,
        ev_counts=states.ev_counts,
        phase_hist=states.phase_hist,
        phase_ticks=states.phase_ticks,
        lat_ticks=states.lat_ticks,
        worst_lat=states.worst_lat,
        worst_phases=states.worst_phases,
        worst_key=states.worst_key,
        worst_client=states.worst_client,
        worst_sub=states.worst_sub,
    )


@functools.lru_cache(maxsize=None)
def _lane_harvest_program(static_cfg: SimConfig, n_clusters: int,
                          mesh: Optional[Mesh], packed: bool = False):
    """Harvest + refill under the lane-partitioned id scheme (states
    donated): same report snapshot and scatter as _harvest_program, but the
    refill bookkeeping is the per-lane generation bump of _lane_reseed —
    no cross-shard collective reaches the compiled program. A SEPARATE
    cached program: the monotone pool's HLO (and golden guard) is
    untouched. ``packed`` as in _harvest_program."""
    constraint = _constraint(mesh)

    def run(states, keys, ids, gens, seed, kn, horizon):
        wide = _wide_view(static_cfg, states, packed)
        retired, new_ids, new_keys, gens_new = _lane_reseed(
            wide, ids, gens, seed, horizon, n_clusters
        )
        harvest = _pool_snapshot(wide, retired, ids)
        fresh = _fresh_batch(static_cfg, new_keys, kn, None, packed)
        if constraint is not None:
            fresh = jax.lax.with_sharding_constraint(
                fresh, jax.tree.map(lambda _: constraint, fresh)
            )
            new_keys = jax.lax.with_sharding_constraint(new_keys, constraint)
            new_ids = jax.lax.with_sharding_constraint(new_ids, constraint)
            gens_new = jax.lax.with_sharding_constraint(gens_new, constraint)
        states_out = _scatter_fresh(retired, fresh, states)
        return states_out, new_keys, new_ids, gens_new, harvest

    return jax.jit(run, donate_argnums=(0,))


def _pool_mesh(n_clusters: int, devices: int) -> Optional[Mesh]:
    """Validate a pool ``devices`` request and build its mesh over the
    first ``devices`` attached devices (None for 1 — same lane-partitioned
    program, no sharding constraints; the reports are identical either
    way, which is the device-count-invariance contract)."""
    avail = len(jax.devices())
    if devices < 1:
        raise ValueError(f"pool devices must be >= 1, got {devices}")
    if devices > avail:
        raise ValueError(
            f"pool devices={devices} exceeds the {avail} attached "
            f"device(s)"
        )
    pool_lanes_per_shard(n_clusters, devices)  # the one layout rule
    if devices == 1:
        return None
    return Mesh(np.array(jax.devices()[:devices]), (CLUSTER_AXIS,))


def _shard_put(tree, mesh: Optional[Mesh]):
    """Place every leaf of ``tree`` sharded over the mesh's first axis
    (leading-axis split); identity without a mesh."""
    if mesh is None:
        return tree
    return jax.device_put(
        tree, NamedSharding(mesh, P(mesh.axis_names[0]))
    )


def _summary_fields(compile_s: float, gap: float, wait: float,
                    overlap: float, devices: Optional[int], book,
                    n_clusters: int, layout: str = "wide",
                    state_bytes: int = 0) -> tuple:
    """The pipeline-telemetry and id-scheme summary fields shared by the
    plain and coverage pool bodies — one copy, so the two summaries cannot
    drift. ``book`` is the final id bookkeeping carry: per-lane generation
    counters under the lane scheme, the monotone next-id scalar otherwise.
    The three pipeline timers are defined at ``_pipeline``; ``layout`` /
    ``state_bytes`` are the resident-lane-state footprint (ISSUE 9):
    measured from the LIVE carry buffers at init, never estimated from the
    schema."""
    tele = {
        "compile_s": round(compile_s, 4),
        "dispatch_gap_s": round(gap, 4),
        "device_wait_s": round(wait, 4),
        "host_overlap_s": round(overlap, 4),
        "state_layout": layout,
        "state_hbm_bytes": state_bytes,
        "bytes_per_lane": round(state_bytes / n_clusters, 1),
    }
    if devices is not None:
        # id-space watermark: every id ever drawn is < (max generation + 1)
        # * lanes; the scheme itself is documented at _lane_reseed
        watermark = (int(np.asarray(book).max()) + 1) * n_clusters
        id_fields = {"id_scheme": "lane", "devices": devices,
                     "id_watermark": watermark}
    else:
        id_fields = {"next_cluster_id": int(book)}
    return tele, id_fields


def choose_layout_from_reason(reason: Optional[str],
                              pack_states: Optional[bool]) -> tuple:
    """The layout DECISION rule on a precomputed exactness reason: auto-pack
    when the packed schema is exact (reason None), fall back to wide
    otherwise — and refuse a FORCED pack that would be inexact, because a
    silently-wrapping narrow dtype corrupts trajectories instead of failing
    a bound. The raft paths feed it state.packed_layout_reason via
    _choose_layout; the service layers (ISSUE 11) feed it their own
    kv/ctrler/shardkv layout reasons. Returns (packed, layout_string)."""
    if pack_states is None:
        packed = reason is None
    elif pack_states and reason is not None:
        raise ValueError(f"pack_states=True but the packed layout is not "
                         f"exact for this run: {reason}")
    else:
        packed = bool(pack_states)
    return packed, ("packed" if packed else "wide")


def _choose_layout(cfg: SimConfig, kn, ticks_needed: int,
                   pack_states: Optional[bool]) -> tuple:
    """The ONE layout-choice rule for every packed-capable raft program
    (chunked fuzz, pool, coverage pool; trace/replay apply the same rule
    through state.packed_layout_reason directly)."""
    return choose_layout_from_reason(
        packed_layout_reason(cfg, kn, ticks_needed), pack_states
    )


def attach_layout_telemetry(fn, n_lanes: int, packed: bool, layout: str,
                            reason: Optional[str], packed_shapes):
    """Attach the resident-carry telemetry attrs run_telemetry reads
    (state_layout / state_hbm_bytes / bytes_per_lane — the
    make_chunked_fuzz_fn attr contract — plus the wide-fallback reason).
    ONE copy for the three service runners (ISSUE 11). ``packed_shapes``
    is a thunk building one lane's packed carry, evaluated via
    jax.eval_shape — the true buffer sizes the program holds, with no
    device allocation; a wide run's final state IS its resident carry, so
    telemetry falls back to measuring that directly."""
    fn.state_layout = layout
    if reason is not None:
        fn.state_layout_reason = reason
    if packed:
        fn.state_hbm_bytes = n_lanes * abstract_bytes(
            jax.eval_shape(packed_shapes)
        )
        fn.bytes_per_lane = round(fn.state_hbm_bytes / n_lanes, 1)
    return fn


def make_chunked_fuzz_fn(
    cfg: SimConfig,
    n_clusters: int,
    n_ticks: int,
    chunk_ticks: int = CHUNK_TICKS,
    mesh: Optional[Mesh] = None,
    pack_states: Optional[bool] = None,
):
    """fn(seed) -> final batched ClusterState via a host loop over donated
    compiled chunks (bench.py methodology: a single device execution stays
    well under the tunnel's per-call deadline; donate_argnums reuses the
    state double-buffer). Bit-identical to make_fuzz_fn's single program —
    the chunk body is the same vmapped step under the same keys.

    ``pack_states``: None (default) carries the loop state in the PACKED
    schema whenever it is exact for this run (state.packed_layout_reason —
    the run fits cfg.max_lane_ticks and the knob ceilings); True forces it
    (ValueError when inexact); False forces the historic wide carry. The
    final state returned is ALWAYS wide. After the first call the returned
    fn carries ``state_layout`` / ``state_hbm_bytes`` / ``bytes_per_lane``
    attributes measured from the live resident carry buffers."""
    static = cfg.static_key()
    kn = cfg.knobs()
    packed, run_layout = _choose_layout(cfg, kn, n_ticks, pack_states)
    init = _pool_init_program(static, n_clusters, mesh, packed)
    chunk = _chunk_program(static, n_clusters, packed)
    unpack = _unpack_batch_program(static, n_clusters) if packed else None
    sizes = [chunk_ticks] * (n_ticks // chunk_ticks)
    if n_ticks % chunk_ticks or not sizes:
        sizes.append(n_ticks % chunk_ticks or n_ticks)

    def run(seed):
        states, keys, _ = init(
            jnp.asarray(seed, jnp.uint32), kn, jnp.asarray(0, jnp.int32)
        )
        run.state_hbm_bytes = tree_bytes(states)  # live resident buffers
        run.bytes_per_lane = round(run.state_hbm_bytes / n_clusters, 1)
        for s in sizes:
            states = chunk(states, keys, kn, jnp.asarray(s, jnp.int32))
        return unpack(states) if packed else states

    run.state_layout = run_layout
    return run


def run_pool(
    cfg: SimConfig,
    seed: int,
    n_clusters: int,
    horizon: int,
    *,
    chunk_ticks: int = 0,
    budget_ticks: Optional[int] = None,
    budget_seconds: Optional[float] = None,
    devices: Optional[int] = None,
    on_retired=None,
    coverage: Optional[CoverageConfig] = None,
    pack_states: Optional[bool] = None,
    heartbeat=None,
    profile: str = "",
) -> dict:
    """Continuous fuzzing pool: chunk -> harvest -> refill until the budget
    is spent. ``n_clusters`` lanes stay resident on device; a lane retires
    when its cluster violated or reached ``horizon`` ticks (detected at
    chunk boundaries, so a lane's age is always a multiple of
    ``chunk_ticks``), and is refilled with a fresh cluster under a new
    global id. ``on_retired`` (if given) is called with one report dict per
    retired cluster, in retirement order — the streaming JSONL source. It
    runs on the pool's harvest-consumer thread (``_pipeline``): harvest
    processing and emission overlap the next chunk's device execution
    instead of serializing with it, so the callback must not call back
    into JAX; the summary's ``dispatch_gap_s`` / ``device_wait_s`` /
    ``host_overlap_s`` report the measured pipeline, and ``compile_s``
    the (untimed-window) program warm-up.

    Budgets: ``budget_ticks`` stops once every lane has dispatched that many
    ticks (rounded up to whole chunks); ``budget_seconds`` stops at the
    first harvest past the wall-clock budget; neither given = one horizon.
    Returns a summary dict (counts, effective pre-violation steps, rates).

    ``devices`` (int >= 1) is the pod-scale path (ROADMAP item 1): lanes
    shard contiguously over the first ``devices`` attached devices and
    global ids follow the LANE-PARTITIONED scheme (lane l's generation-g
    cluster owns id ``g * n_clusters + l`` — see the scheme comment above
    _lane_reseed), so refill bookkeeping is per-shard with no cross-shard
    synchronization, and the multiset of retired reports over a fixed tick
    budget is bit-identical at ANY device count (test-enforced).
    ``devices=1`` runs the same scheme unsharded. ``None`` (the default)
    is the historic single-device monotone-id pool — byte-identical
    programs and reports (golden guard).

    ``coverage`` (a ``config.CoverageConfig``) turns the pool into the
    coverage-guided corpus scheduler (ROADMAP item 3): every tick each
    lane's abstract-state fingerprint (coverage.py) updates a
    device-resident seen-set, and the refill step is BIASED — see
    ``_run_pool_coverage``. With ``devices`` the seen-set is PER-SHARD
    (one bitmap row per shard, OR-reduced at harvest/summary time), so
    coverage composes with the mesh.

    ``pack_states``: the packed-carry choice (ISSUE 9; see
    make_chunked_fuzz_fn). None auto-packs whenever the schema is exact for
    ``horizon + chunk_ticks`` per-lane ticks (a lane can overshoot the
    horizon by at most one chunk before the harvest retires it); the
    summary's ``state_layout`` / ``state_hbm_bytes`` / ``bytes_per_lane``
    report the layout and the measured live-buffer footprint of the
    resident lane state. Reports are bit-identical across layouts (the
    widen-on-use round trip is exact on the packed path — golden-guard
    property, tests/test_state_layout.py).

    ``heartbeat`` (ISSUE 17): a path (or a ``telemetry.HeartbeatWriter``)
    for the live-telemetry plane — one JSONL row per harvest generation
    plus an atomically-replaced ``<path>.manifest.json`` a watcher can
    attach to. Rows are emitted from the SAME consumer thread that runs
    ``on_retired`` (never from device code — zero new compiled programs;
    the lint registry pin and golden guards enforce that statically); the
    row's ``det`` column group is device-count invariant and layout-blind
    like the summary it reconciles with, the ``t`` group is wall-clock.
    """
    if horizon < 1:
        raise ValueError(f"pool horizon must be >= 1 tick, got {horizon}")
    if chunk_ticks <= 0:
        chunk_ticks = default_chunk_ticks(horizon)
    if budget_ticks is None and budget_seconds is None:
        budget_ticks = horizon
    mesh = None if devices is None else _pool_mesh(n_clusters, devices)
    if coverage is not None:
        return _run_pool_coverage(
            cfg, seed, n_clusters, horizon, coverage,
            chunk_ticks=chunk_ticks, budget_ticks=budget_ticks,
            budget_seconds=budget_seconds, mesh=mesh, devices=devices,
            on_retired=on_retired, pack_states=pack_states,
            heartbeat=heartbeat, profile=profile,
        )
    hb = _telemetry.as_writer(heartbeat)
    if hb is not None:
        hb.open(_hb_context(cfg, seed, n_clusters, horizon, chunk_ticks,
                            devices, budget_ticks, budget_seconds,
                            profile=profile))
    static = cfg.static_key()
    kn = cfg.knobs()
    packed, layout = _choose_layout(cfg, kn, horizon + chunk_ticks,
                                    pack_states)
    lane_ids = devices is not None
    init = _pool_init_program(static, n_clusters, mesh, packed)
    chunk = _chunk_program(static, n_clusters, packed)
    harv = (_lane_harvest_program(static, n_clusters, mesh, packed)
            if lane_ids else _harvest_program(static, n_clusters, packed))
    seed_u = jnp.asarray(seed, jnp.uint32)
    hz = jnp.asarray(horizon, jnp.int32)
    ct = jnp.asarray(chunk_ticks, jnp.int32)

    def book0():
        # the id-scheme bookkeeping carried through the harvest: per-lane
        # generation counters (lane scheme) or the monotone next-id scalar
        if lane_ids:
            return _shard_put(jnp.zeros((n_clusters,), jnp.int32), mesh)
        return jnp.asarray(n_clusters, jnp.int32)

    def steps(c, ticks):
        """The _pipeline launch pair bound to a carry list."""

        def launch_chunk():
            c[0] = chunk(c[0], c[1], kn, ticks)

        def launch_harvest():
            out = harv(c[0], c[1], c[2], c[3], seed_u, kn, hz)
            c[:] = out[:4]
            return out[4]

        return launch_chunk, launch_harvest

    # Warm all three programs OUTSIDE the timed window (a 1-tick chunk
    # compiles the same executable — the tick count is a runtime bound), so
    # a cold run's steps_per_sec/violations_per_s never silently include
    # compile time (run_telemetry's measurement-honesty convention). Warm
    # cost: n_clusters ticks + one harvest — noise against any real budget.
    try:
        t_warm = time.perf_counter()
        ws, wk, wi = init(seed_u, kn, jnp.asarray(0, jnp.int32))
        wc, wh = steps([ws, wk, wi, book0()], jnp.asarray(1, jnp.int32))
        wc()
        jax.block_until_ready(wh().retired)
        compile_s = time.perf_counter() - t_warm
        states, keys, ids = init(seed_u, kn, jnp.asarray(0, jnp.int32))
        state_bytes = tree_bytes(states)  # live resident carry buffers
        carry = [states, keys, ids, book0()]
        launch_chunk, launch_harvest = steps(carry, ct)
        acct = _PoolAccount(on_retired, heartbeat=hb)
        lane_ticks, wall, gap, wait, overlap = _pipeline(
            launch_chunk, launch_harvest, acct, chunk_ticks, budget_ticks,
            budget_seconds,
        )
        acct.finish()
        tele, id_fields = _summary_fields(
            compile_s, gap, wait, overlap, devices, carry[3], n_clusters,
            layout, state_bytes,
        )
        summary = _pool_summary(n_clusters, horizon, chunk_ticks,
                                lane_ticks, acct, wall, tele, id_fields)
    except BaseException:
        # in-process failure: terminal manifest status "failed" (an
        # abrupt SIGKILL instead leaves "running" + a dead pid, which
        # telemetry.manifest_status reads back as "crashed")
        if hb is not None:
            hb.close("failed")
        raise
    if hb is not None:
        hb.final_row(acct, lane_ticks, wall, tele)
        hb.close("done")
    return summary


# --------------------------------------------------------------------------
# Coverage-guided pool (ROADMAP item 3; the abstraction lives in coverage.py).
#
# The pool's retire-and-refill loop IS the corpus scheduler — no new driver.
# Coverage adds three device-resident pieces: a per-tick abstract-state
# fingerprint folded inside the chunk program, a power-of-two seen-set
# bitmap, and per-lane new-fingerprint counters that the harvest consumes to
# BIAS the refill (productive lanes spawn knob-mutated children, the rest
# draw fresh knob rows from the prior). Lanes therefore run HETEROGENEOUS
# knobs, which needs the per-cluster knob layout (the measured 2.4x sweep
# cliff — the price of guided search, paid only in coverage mode). All
# coverage programs are SEPARATE cached programs: with coverage off, no
# existing program's HLO — or warm persistent-cache entry — changes.
# --------------------------------------------------------------------------


class CovHarvest(NamedTuple):
    """PoolHarvest plus the coverage columns (all PRE-refill)."""

    retired: jax.Array
    ids: jax.Array
    violations: jax.Array
    first_violation_tick: jax.Array
    first_leader_tick: jax.Array
    committed: jax.Array
    msg_count: jax.Array
    snap_installs: jax.Array
    ticks_run: jax.Array
    lat_hist: jax.Array     # metrics rows (PoolHarvest; zero-size when off)
    ev_counts: jax.Array
    phase_hist: jax.Array   # attribution rows (PoolHarvest; ISSUE 12)
    phase_ticks: jax.Array
    lat_ticks: jax.Array
    worst_lat: jax.Array
    worst_phases: jax.Array
    worst_key: jax.Array
    worst_client: jax.Array
    worst_sub: jax.Array
    new_fps: jax.Array      # i32 [n]: new fingerprints this lane discovered
    #                         since ITS refill (its whole lifetime)
    refill_kind: jax.Array  # i32 [n]: how this lane's knobs were produced
    #                         (coverage.REFILL_SEED / _FRESH / _MUTATE)
    seen_bits: jax.Array    # i32 scalar: seen-set popcount after this chunk
    knobs: Knobs            # per-lane knob rows (for JSONL + replay)


@functools.lru_cache(maxsize=None)
def _cov_chunk_program(static_cfg: SimConfig, n_clusters: int,
                       ccfg: CoverageConfig, packed: bool = False):
    """The coverage chunk: T ticks of the batched step under PER-LANE knob
    rows, with every tick's post-step abstract-state fingerprint recorded in
    the seen-set and credited to its lane's new-fingerprint counter. Two
    lanes landing the same new bit in one tick both get credit
    (deterministic; the alternative needs a per-tick segment reduction for
    a tie nobody acts on). The state, bitmap, and counters are donated —
    the pool's double-buffer discipline. With ``packed`` the carry is the
    narrow schema and the fingerprint is folded FROM THE PACKED WORDS
    (coverage.abstract_code_packed — role/alive read straight out of their
    bitfield words; identical codes, test-pinned)."""
    step_fn = step_cluster_packed if packed else step_cluster
    code_fn = _cov.abstract_code_packed if packed else _cov.abstract_code

    def run(states, keys, kn_lanes, bitmap, new_fps, n_ticks):
        def body(_, carry):
            st, bm, nf = carry
            st = jax.vmap(
                functools.partial(step_fn, static_cfg),
                in_axes=(0, 0, 0),
            )(st, keys, kn_lanes)
            code = jax.vmap(functools.partial(code_fn, ccfg))(st)
            idx = _cov.bitmap_index(ccfg, static_cfg.n_nodes, code)
            # a violated lane's post-violation states are waste, not
            # coverage (the effective_cluster_steps convention): until the
            # harvest retires it, it must neither set seen-set bits nor
            # earn the productivity credit that biases refill
            ok = st.violations == 0
            nf = nf + (ok & ~bm[idx]).astype(jnp.int32)
            bm = bm.at[idx].max(ok)
            return st, bm, nf

        return jax.lax.fori_loop(
            0, n_ticks, body, (states, bitmap, new_fps)
        )

    return jax.jit(run, donate_argnums=(0, 3, 4))


@functools.lru_cache(maxsize=None)
def _cov_harvest_program(static_cfg: SimConfig, n_clusters: int,
                         ccfg: CoverageConfig, packed: bool = False):
    """Harvest + BIASED refill, one compiled call (states donated): same
    retirement rule and monotone global-id scheme as _harvest_program, plus
    the corpus-scheduler policy — a retiring lane that discovered new
    fingerprints respawns with its knob row mutated
    (coverage.refill_knobs), an unproductive one with a fresh prior draw.
    With ``ccfg.guided`` False the refill keeps every lane at the base knob
    row (measurement-only mode: trajectories identical to the plain pool —
    the random A/B baseline and the first-generation golden guard).
    ``packed`` as in _harvest_program."""

    def run(states, keys, ids, kn_lanes, kinds, new_fps, bitmap,
            next_id, seed, base_kn, horizon):
        wide = _wide_view(static_cfg, states, packed)
        retired, new_ids, new_keys, n_ret = _retire_and_reseed(
            wide, ids, next_id, seed, horizon
        )
        harvest = CovHarvest(
            **_pool_snapshot(wide, retired, ids)._asdict(),
            new_fps=new_fps,
            refill_kind=kinds,
            seen_bits=jnp.sum(bitmap, dtype=jnp.int32),
            knobs=kn_lanes,
        )
        if ccfg.guided:
            productive = retired & (new_fps > 0)
            kn_new, drawn = _cov.refill_knobs(
                ccfg, kn_lanes, base_kn, retired, productive, new_ids, seed
            )
            kinds_new = jnp.where(retired, drawn, kinds)
        else:
            kn_new, kinds_new = kn_lanes, kinds  # base rows forever
        fresh = _fresh_batch(static_cfg, new_keys, kn_new, 0, packed)
        states_out = _scatter_fresh(retired, fresh, states)
        new_fps_out = jnp.where(retired, 0, new_fps)
        return (states_out, new_keys, new_ids, kn_new, kinds_new,
                new_fps_out, next_id + n_ret, harvest)

    return jax.jit(run, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _cov_chunk_sharded_program(static_cfg: SimConfig, n_clusters: int,
                               ccfg: CoverageConfig, n_shards: int,
                               packed: bool = False):
    """_cov_chunk_program with a PER-SHARD seen-set (ROADMAP 3a): the
    bitmap is ``[n_shards, bitmap_bits]`` — one row per shard, sharded over
    the mesh axis with the lanes — and each lane reads/writes ONLY its own
    shard's row (lane -> shard is the contiguous-slice map
    ``coverage.lane_shards``), so the per-tick seen-set update stays
    shard-local: no cross-shard traffic joins the hot loop. Novelty (the
    refill-bias credit) is therefore per-shard novelty — two shards may
    each credit the same code once; the harvest OR-reduces the rows so the
    summary's ``seen_fingerprints`` still counts the exact union. A
    SEPARATE cached program: the single-device coverage pool's HLO is
    untouched."""
    shard_ix = _cov.lane_shards(n_clusters, n_shards)
    step_fn = step_cluster_packed if packed else step_cluster
    code_fn = _cov.abstract_code_packed if packed else _cov.abstract_code

    def run(states, keys, kn_lanes, bitmap, new_fps, n_ticks):
        def body(_, carry):
            st, bm, nf = carry
            st = jax.vmap(
                functools.partial(step_fn, static_cfg),
                in_axes=(0, 0, 0),
            )(st, keys, kn_lanes)
            code = jax.vmap(functools.partial(code_fn, ccfg))(st)
            idx = _cov.bitmap_index(ccfg, static_cfg.n_nodes, code)
            ok = st.violations == 0
            nf = nf + (ok & ~bm[shard_ix, idx]).astype(jnp.int32)
            bm = bm.at[shard_ix, idx].max(ok)
            return st, bm, nf

        return jax.lax.fori_loop(
            0, n_ticks, body, (states, bitmap, new_fps)
        )

    return jax.jit(run, donate_argnums=(0, 3, 4))


@functools.lru_cache(maxsize=None)
def _cov_harvest_sharded_program(static_cfg: SimConfig, n_clusters: int,
                                 ccfg: CoverageConfig,
                                 mesh: Optional[Mesh],
                                 packed: bool = False):
    """_cov_harvest_program under the lane-partitioned id scheme: per-lane
    generation bookkeeping (_lane_reseed — no cross-shard scan), the same
    biased-refill policy (knob draws are a pure function of (seed, new
    global id), so mutated lanes replay identically at any device count),
    and ``seen_bits`` = popcount of the OR over the shard bitmaps — the
    one cross-shard reduction, paid at harvest time on the small bitmap,
    never on the per-tick path."""
    constraint = _constraint(mesh)

    def run(states, keys, ids, gens, kn_lanes, kinds, new_fps, bitmap,
            seed, base_kn, horizon):
        wide = _wide_view(static_cfg, states, packed)
        retired, new_ids, new_keys, gens_new = _lane_reseed(
            wide, ids, gens, seed, horizon, n_clusters
        )
        harvest = CovHarvest(
            **_pool_snapshot(wide, retired, ids)._asdict(),
            new_fps=new_fps,
            refill_kind=kinds,
            seen_bits=jnp.sum(jnp.any(bitmap, axis=0), dtype=jnp.int32),
            knobs=kn_lanes,
        )
        if ccfg.guided:
            productive = retired & (new_fps > 0)
            kn_new, drawn = _cov.refill_knobs(
                ccfg, kn_lanes, base_kn, retired, productive, new_ids, seed
            )
            kinds_new = jnp.where(retired, drawn, kinds)
        else:
            kn_new, kinds_new = kn_lanes, kinds  # base rows forever
        fresh = _fresh_batch(static_cfg, new_keys, kn_new, 0, packed)
        if constraint is not None:
            fresh = jax.lax.with_sharding_constraint(
                fresh, jax.tree.map(lambda _: constraint, fresh)
            )
            new_keys = jax.lax.with_sharding_constraint(new_keys, constraint)
            new_ids = jax.lax.with_sharding_constraint(new_ids, constraint)
            gens_new = jax.lax.with_sharding_constraint(gens_new, constraint)
        states_out = _scatter_fresh(retired, fresh, states)
        new_fps_out = jnp.where(retired, 0, new_fps)
        return (states_out, new_keys, new_ids, gens_new, kn_new, kinds_new,
                new_fps_out, harvest)

    return jax.jit(run, donate_argnums=(0,))


def _run_pool_coverage(
    cfg: SimConfig,
    seed: int,
    n_clusters: int,
    horizon: int,
    ccfg: CoverageConfig,
    *,
    chunk_ticks: int,
    budget_ticks: Optional[int],
    budget_seconds: Optional[float],
    mesh: Optional[Mesh],
    devices: Optional[int],
    on_retired,
    pack_states: Optional[bool] = None,
    heartbeat=None,
    profile: str = "",
) -> dict:
    """run_pool's coverage-guided body (see run_pool for the contract).

    Extra report surface: each retired-cluster row carries
    ``new_fingerprints`` (the lane's lifetime discovery count), ``refill``
    ("seed" | "fresh" | "mutate" — how its knob row was produced) and
    ``knobs`` (the mutable storm-knob values it ran, full f32 precision —
    feed them to ``replay_cluster(..., knobs=row["knobs"])`` for bit-exact
    replay); the summary gains a ``coverage`` dict with the seen-set totals
    and the per-generation discovery curve.

    With ``devices`` the seen-set is PER-SHARD (ROADMAP 3a; see
    _cov_chunk_sharded_program) and ids follow the lane-partitioned
    scheme. Per-shard novelty makes the GUIDED refill topology-dependent
    (a lane's bias credit is novelty against its own shard's bitmap), so
    coverage runs are exactly reproducible per device count but — unlike
    the plain sharded pool — not invariant across counts; every retired
    row still replays bit-exactly from its recorded knob row.
    """
    sharded = devices is not None
    hb = _telemetry.as_writer(heartbeat)
    if hb is not None:
        hb.open(_hb_context(cfg, seed, n_clusters, horizon, chunk_ticks,
                            devices, budget_ticks, budget_seconds,
                            coverage=ccfg, profile=profile))
    static = cfg.static_key()
    base_kn = cfg.knobs()
    packed, layout = _choose_layout(cfg, base_kn, horizon + chunk_ticks,
                                    pack_states)
    init = _pool_init_program(static, n_clusters, mesh, packed)
    # the chunk only reads the fingerprint fields — cache it on those, so
    # the A/B's guided/random legs share one compiled chunk executable
    if sharded:
        chunk = _cov_chunk_sharded_program(
            static, n_clusters, ccfg.fingerprint_key(), devices, packed
        )
        harv = _cov_harvest_sharded_program(static, n_clusters, ccfg, mesh,
                                            packed)
    else:
        chunk = _cov_chunk_program(static, n_clusters, ccfg.fingerprint_key(),
                                   packed)
        harv = _cov_harvest_program(static, n_clusters, ccfg, packed)
    seed_u = jnp.asarray(seed, jnp.uint32)
    hz = jnp.asarray(horizon, jnp.int32)
    ct = jnp.asarray(chunk_ticks, jnp.int32)

    def fresh_carry():
        states, keys, ids = init(seed_u, base_kn, jnp.asarray(0, jnp.int32))
        kn_lanes = _shard_put(base_kn.broadcast(n_clusters), mesh)
        kinds = _shard_put(
            jnp.full((n_clusters,), _cov.REFILL_SEED, jnp.int32), mesh
        )
        new_fps = _shard_put(jnp.zeros((n_clusters,), jnp.int32), mesh)
        if sharded:
            # one seen-set row per shard, sharded over the mesh axis with
            # the lanes (a [1, bits] row for devices=1)
            bitmap = _shard_put(
                jnp.zeros((devices, ccfg.bitmap_bits), jnp.bool_), mesh
            )
            book = _shard_put(jnp.zeros((n_clusters,), jnp.int32), mesh)
        else:
            bitmap = jnp.zeros((ccfg.bitmap_bits,), jnp.bool_)
            book = jnp.asarray(n_clusters, jnp.int32)  # monotone next_id
        return [states, keys, ids, book, kn_lanes, kinds, new_fps, bitmap]

    def steps(c, ticks):
        """The _pipeline launch pair bound to a carry list (shared by the
        warm block and the timed loop)."""

        def launch_chunk():
            st, bm, nf = chunk(c[0], c[1], c[4], c[7], c[6], ticks)
            c[0], c[7], c[6] = st, bm, nf

        def launch_harvest():
            if sharded:
                (c[0], c[1], c[2], c[3], c[4], c[5], c[6], h_dev) = harv(
                    c[0], c[1], c[2], c[3], c[4], c[5], c[6], c[7],
                    seed_u, base_kn, hz
                )
            else:
                (c[0], c[1], c[2], c[4], c[5], c[6], c[3], h_dev) = harv(
                    c[0], c[1], c[2], c[4], c[5], c[6], c[7], c[3],
                    seed_u, base_kn, hz
                )
            return h_dev

        return launch_chunk, launch_harvest

    # warm all programs outside the timed window (run_pool convention; the
    # tick count is a runtime bound so 1 tick compiles the real executables)
    try:
        t_warm = time.perf_counter()
        wc, wh = steps(fresh_carry(), jnp.asarray(1, jnp.int32))
        wc()
        jax.block_until_ready(wh().retired)
        compile_s = time.perf_counter() - t_warm
        carry = fresh_carry()
        state_bytes = tree_bytes(carry[0])  # live resident carry buffers
        launch_chunk, launch_harvest = steps(carry, ct)
        acct = _PoolAccount(on_retired, guided=ccfg.guided, heartbeat=hb)
        lane_ticks, wall, gap, wait, overlap = _pipeline(
            launch_chunk, launch_harvest, acct, chunk_ticks, budget_ticks,
            budget_seconds,
        )
        acct.finish()
        tele, id_fields = _summary_fields(
            compile_s, gap, wait, overlap, devices, carry[3], n_clusters,
            layout, state_bytes,
        )
        summary = _pool_summary(n_clusters, horizon, chunk_ticks,
                                lane_ticks, acct, wall, tele, id_fields)
    except BaseException:
        if hb is not None:
            hb.close("failed")  # SIGKILL instead reads back as "crashed"
        raise
    if hb is not None:
        hb.final_row(acct, lane_ticks, wall, tele)
        hb.close("done")
    summary["coverage"] = {
        "bitmap_bits": ccfg.bitmap_bits,
        "identity": _cov.identity_mapped(cfg.n_nodes, ccfg),
        "guided": ccfg.guided,
        # with shards > 1 this is the popcount of the OR over the per-shard
        # bitmaps — the exact union in identity mode
        "seen_fingerprints": acct.seen_prev,
        "new_fingerprints_per_s": (
            round(acct.seen_prev / wall, 2) if wall > 0 else None
        ),
        "lane_new_fps_total": acct.lane_new_fps_total,
        "generations": len(acct.new_fp_per_gen),
        # truncated like violating_clusters[:16]; "generations" carries the
        # full count so a consumer can detect the cut
        "new_fp_per_gen": acct.new_fp_per_gen[:64],
        "refills_mutated": acct.refills_mutated,
        "refills_fresh": acct.refills_fresh,
        **({"shards": devices} if sharded else {}),
    }
    return summary


def _validate_knobs(knobs) -> None:
    """Eagerly reject knob values that would silently misbehave inside the
    compiled program (mod-by-zero spans, out-of-range probabilities)."""
    k = jax.tree.map(np.asarray, knobs)
    validate_probs(
        k, ("loss_prob", "p_crash", "p_restart", "p_repartition", "p_heal",
            "p_leader_part", "p_asym_cut", "p_client_cmd",
            "p_lose_unsynced"), "raft",
    )
    if (k.fsync_every < 1).any():
        raise ValueError(
            f"fsync_every must be >= 1 tick (1 = sync every tick, the "
            f"perfect-persistence model): {k.fsync_every}"
        )
    if (k.eto_max < k.eto_min).any() or (k.eto_min < 1).any():
        raise ValueError(f"election timeout span empty: [{k.eto_min}, {k.eto_max}]")
    if (k.delay_max < k.delay_min).any() or (k.delay_min < 1).any():
        raise ValueError(f"delay span empty: [{k.delay_min}, {k.delay_max}]")
    if (k.delay_max - k.delay_min >= 256).any():
        raise ValueError(
            "delay span wider than 256 ticks exceeds the packed draw width "
            "(step.py _net_draws)"
        )
    if (k.majority < 1).any() or (k.heartbeat_ticks < 1).any():
        raise ValueError("majority and heartbeat_ticks must be >= 1")
    if (k.flow_cap < 1).any() or (k.compact_every < 1).any():
        raise ValueError("flow_cap and compact_every must be >= 1")
    # gray-failure knobs (ISSUE 19)
    validate_probs(k, ("p_limp", "p_limp_heal", "p_fsync_stall"), "raft")
    if (k.limp_mult_max < 1).any():
        raise ValueError(
            f"limp_mult_max must be >= 1 (1 = limping off): {k.limp_mult_max}"
        )
    if (k.eto_skew < 0).any() or (k.fsync_stall_ticks < 0).any():
        raise ValueError("eto_skew and fsync_stall_ticks must be >= 0")
    if (k.rolling_period < 0).any() or (k.rolling_down < 0).any():
        raise ValueError("rolling_period and rolling_down must be >= 0")
    if ((k.rolling_period > 0) & (k.rolling_down >= k.rolling_period)).any():
        raise ValueError(
            "rolling_down must stay < rolling_period (a wave's node must "
            "come back up before the next wave starts)"
        )


def validate_probs(k, names, layer: str) -> None:
    """Shared [0,1] range check for probability knobs (k = numpy-mapped
    knob pytree) — one copy of the rule for every layer's validator."""
    for name in names:
        v = getattr(k, name)
        if (v < 0).any() or (v > 1).any():
            raise ValueError(f"{layer} knob {name} outside [0, 1]: {v}")


def validate_bool_bugs(k, names, layer: str) -> None:
    """Shared bool-dtype check for planted-bug knob axes: an int 0/1 matrix
    would otherwise fail deep inside the compiled loop with an opaque
    carry-dtype error."""
    for name in names:
        if getattr(k, name).dtype != np.bool_:
            raise ValueError(
                f"{layer} bug knob {name} must be boolean (got "
                f"{getattr(k, name).dtype}); an int 0/1 matrix would fail "
                "deep inside the compiled loop with a carry-dtype error"
            )


def validate_service_raft_knobs(knobs) -> None:
    """Service-layer sweeps: the RAFT knob values that reach the program
    (the static cfg's dynamic fields are pinned and never read) must leave
    command injection and the compaction boundary to the service layer."""
    k = jax.tree.map(np.asarray, knobs)
    if (k.p_client_cmd != 0).any():
        raise ValueError(
            "service-layer sweeps need p_client_cmd == 0 in the raft knobs "
            "(the service layer owns command injection)"
        )
    if k.compact_at_commit.any():
        raise ValueError(
            "service-layer sweeps need compact_at_commit=False in the raft "
            "knobs (the compaction boundary must follow the apply cursor)"
        )


@functools.lru_cache(maxsize=None)
def _uniform_cell_program(static_cfg: SimConfig, n_clusters: int):
    """_fuzz_program's uniform-knob (fast) layout plus a runtime GLOBAL-ID
    offset: one sweep cell covers global cluster ids [id0, id0 + n), so the
    (seed, cluster_id) replay contract matches the per-cluster-knob layout
    it replaces. A separate cached program (rather than an extra arg on
    _fuzz_program) so every existing fuzz program's HLO — and its warm
    persistent-cache entry — stays byte-identical."""

    def run(seed, kn, n_ticks, id0):
        keys = _cluster_keys(seed, n_clusters, id0)
        states = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, None)
        )(keys, kn)

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_cluster, static_cfg),
                in_axes=(0, 0, None),
            )(carry, keys, kn)

        return jax.lax.fori_loop(0, n_ticks, body, states)

    return jax.jit(run)


def _knob_runs(kb, n_clusters: int) -> list:
    """Contiguous runs of identical per-cluster knob rows, as
    [(start, length), ...]. For the tiled grids every sweep builder emits,
    runs == distinct knob points; a non-contiguous layout simply yields
    more runs and falls back to the per-cluster program."""
    cols = np.stack(
        [np.asarray(x, dtype=np.float64) for x in kb], axis=1
    )  # i32/bool knob values are exact in f64
    change = np.any(cols[1:] != cols[:-1], axis=1)
    starts = np.concatenate([[0], np.nonzero(change)[0] + 1])
    lengths = np.diff(np.concatenate([starts, [n_clusters]]))
    return list(zip(starts.tolist(), lengths.tolist()))


class _UniformSweepDispatch:
    """K uniform-knob dispatches over contiguous global-id ranges — the
    fast knob layout (shared from the program cache across cells of equal
    batch) instead of one per-cluster-knob program with its measured 2.4x
    cliff. Returns the cell finals concatenated back into one batched
    ClusterState, so every make_sweep_fn caller is unchanged; reports are
    bit-identical to the per-cluster layout (same knob values reach the
    same (seed, cluster_id) streams — tests/test_pool.py asserts it)."""

    dispatch = "uniform"

    def __init__(self, static_cfg, kb, runs, n_ticks):
        ticks = jnp.asarray(n_ticks, jnp.int32)
        self._parts = []
        self._compiled = {}
        self._aot_failed = False
        self.compile_s = None
        for start, length in runs:
            prog = _uniform_cell_program(static_cfg, length)
            kn = jax.tree.map(lambda x, s=start: x[s], kb)  # 0-d, same dtype

            def make_args(seed, kn=kn, start=start):
                return (jnp.asarray(seed, jnp.uint32), kn, ticks,
                        jnp.asarray(start, jnp.int32))

            self._parts.append((length, prog, make_args))

    def compile_timed(self, seed) -> Optional[float]:
        """AOT-compile each distinct cell batch size once (cells share the
        compiled executable — only shapes are baked, knob values ride in as
        arguments); returns total wall seconds like FuzzProgram."""
        if self.compile_s is None and not self._aot_failed:
            t0 = time.perf_counter()
            try:
                for length, prog, make_args in self._parts:
                    if length not in self._compiled:
                        self._compiled[length] = prog.lower(
                            *make_args(seed)
                        ).compile()
                self.compile_s = time.perf_counter() - t0
            except Exception:  # fall back to plain jit dispatch
                self._aot_failed = True
                self._compiled = {}
        return self.compile_s

    def __call__(self, seed):
        finals = []
        for length, prog, make_args in self._parts:
            args = make_args(seed)
            compiled = self._compiled.get(length)
            finals.append(compiled(*args) if compiled else prog(*args))
        return jax.tree.map(lambda *xs: jnp.concatenate(xs), *finals)


def make_sweep_fn(
    cfg: SimConfig,
    knobs,  # config.Knobs with leading [n_clusters] axes (heterogeneous)
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    uniform_max_cells: int = SWEEP_UNIFORM_MAX_CELLS,
):
    """Like make_fuzz_fn, but each cluster runs its own dynamic knobs — a
    fault-parameter sweep (e.g. loss x crash-rate grid) in ONE compiled
    program, something the reference's compile-time test matrix cannot do.

    Small grids (<= ``uniform_max_cells`` contiguous knob cells, no mesh)
    dispatch as one uniform-knob program per cell instead — the fast layout,
    sidestepping the per-cluster-knob 2.4x cliff. The returned callable's
    ``dispatch`` attribute says which path was taken; pass
    ``uniform_max_cells=0`` to force the per-cluster layout."""
    _validate_knobs(knobs)
    kb = knobs.broadcast(n_clusters)
    if mesh is None and uniform_max_cells:
        runs = _knob_runs(kb, n_clusters)
        if len(runs) <= uniform_max_cells:
            return _UniformSweepDispatch(cfg.static_key(), kb, runs, n_ticks)
    prog = _fuzz_program(cfg.static_key(), n_clusters, mesh, per_cluster_knobs=True)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    fn = FuzzProgram(
        prog, lambda seed: (jnp.asarray(seed, jnp.uint32), kb, ticks)
    )
    fn.dispatch = "per_cluster"
    return fn


def report(final: ClusterState) -> FuzzReport:
    has_metrics = final.lat_hist.size > 0

    def m(x):
        return np.asarray(x) if has_metrics else None

    return FuzzReport(
        violations=np.asarray(final.violations),
        first_violation_tick=np.asarray(final.first_violation_tick),
        first_leader_tick=np.asarray(final.first_leader_tick),
        committed=np.asarray(final.shadow_len),
        msg_count=np.asarray(final.msg_count),
        snap_installs=np.asarray(final.snap_install_count),
        lat_hist=m(final.lat_hist),
        ev_counts=m(final.ev_counts),
        phase_hist=m(final.phase_hist),
        phase_ticks=m(final.phase_ticks),
        lat_ticks=m(final.lat_ticks),
        worst_lat=m(final.worst_lat),
        worst_phases=m(final.worst_phases),
        worst_key=m(final.worst_key),
        worst_client=m(final.worst_client),
        worst_sub=m(final.worst_sub),
    )


def fuzz(
    cfg: SimConfig,
    seed: int,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
) -> FuzzReport:
    """Run n_clusters independent (seed x fault-schedule) simulations for n_ticks.

    Every cluster derives its PRNG stream from fold_in(PRNGKey(seed), cluster_id),
    so any violating cluster is exactly reproducible from (seed, cluster_id) — the
    MADSIM_TEST_SEED replay contract (/root/reference/README.md:42-55).
    """
    fn = make_fuzz_fn(cfg, n_clusters, n_ticks, mesh=mesh)
    final = jax.block_until_ready(fn(jnp.asarray(seed, jnp.uint32)))
    return report(final)


@functools.lru_cache(maxsize=None)
def _replay_program(static_cfg: SimConfig, packed: bool = False):
    """Single-cluster replay. With ``packed`` the fori carry is the packed
    schema (the SAME one the pool/chunk programs carry — the replay path
    shares the layout, ISSUE 9) and the returned final state is widened, so
    callers always see the historic wide ClusterState — bit-identical
    either way (exact round trip)."""
    step_fn = step_cluster_packed if packed else step_cluster

    def run(cluster_id, kn, n_ticks, seed):
        ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)
        state = init_cluster(static_cfg, ckey, kn)
        if packed:
            state = pack_state(static_cfg, state)

        def body(_, carry):
            return step_fn(static_cfg, carry, ckey, kn)

        final = jax.lax.fori_loop(0, n_ticks, body, state)
        return unpack_state(static_cfg, final) if packed else final

    return jax.jit(run)


def resolve_knobs(cfg: SimConfig, knobs) -> Knobs:
    """``cfg.knobs()`` with an optional override merged on top and validated.

    ``knobs``: ``None``, a full ``config.Knobs``, or a mapping of field
    name -> value (e.g. a coverage-pool JSONL row's ``"knobs"`` object).
    Overrides pass ``_validate_knobs`` like every other entry point — a
    hand-edited row with an out-of-range probability is rejected eagerly,
    not silently run. Shared by ``replay_cluster`` and
    ``trace.replay_cluster_traced`` so the replay and explain surfaces
    apply a mutated lane's knob row identically."""
    kn = cfg.knobs()
    if knobs is None:
        return kn
    if isinstance(knobs, Knobs):
        kn = knobs
    else:
        if not isinstance(knobs, dict):
            # --knobs-json '0.5' or a bare string must fail with a named
            # rejection, not an opaque TypeError / nonsense field list
            raise ValueError(
                "knobs override must be a JSON object / mapping of field "
                f"-> value, got {type(knobs).__name__}"
            )
        unknown = set(knobs) - set(kn._fields)
        if unknown:
            raise ValueError(f"unknown knob fields: {sorted(unknown)}")
        coerced = {}
        for k, v in knobs.items():
            dt = getattr(kn, k).dtype
            if jnp.issubdtype(dt, jnp.integer) and v != int(v):
                # bit-exact replay must not silently truncate a
                # hand-edited row (the _validate_knobs eager convention)
                raise ValueError(
                    f"knob {k!r} is integer-valued; {v!r} would be "
                    f"silently truncated"
                )
            coerced[k] = jnp.asarray(v, dt)
        kn = kn._replace(**coerced)
    _validate_knobs(kn)
    return kn


def replay_cluster(
    cfg: SimConfig, seed: int, cluster_id: int, n_ticks: int, knobs=None
) -> ClusterState:
    """Re-run a single cluster (e.g. a violating one) for inspection/replay.

    ``knobs``: optional dynamic-knob override (see ``resolve_knobs``). This
    is how a coverage-pool hit with a mutated knob row replays bit-exactly
    (the pool's JSONL row carries the row under ``"knobs"``); it reuses the
    SAME compiled replay program either way, because knobs were always
    runtime scalars — exactly like replaying a sweep cell needs the cell's
    knob values, the (seed, cluster_id) PRNG-stream contract itself is
    knob-independent. The carry uses the packed schema whenever it is
    exact for this run (state.packed_layout_reason) — same layout rule as
    the pool that produced the hit; the result is bit-identical in either
    layout."""
    kn = resolve_knobs(cfg, knobs)
    packed = packed_layout_reason(cfg, kn, n_ticks) is None
    prog = _replay_program(cfg.static_key(), packed)
    return jax.block_until_ready(
        prog(jnp.asarray(cluster_id, jnp.int32), kn,
             jnp.asarray(n_ticks, jnp.int32), jnp.asarray(seed, jnp.uint32))
    )
