"""Batch drivers: vmap over clusters, scan over ticks, pjit over chips.

The fuzzer is embarrassingly data-parallel over the cluster axis (SURVEY.md §5:
"batch parallelism over simulated clusters" is this project's scaling axis) — the
mesh sharding simply splits clusters across chips; XLA inserts no collectives on the
hot path, only for the final violation reduction.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from madraft_tpu.tpusim import coverage as _cov
from madraft_tpu.tpusim.config import (
    CoverageConfig,
    Knobs,
    SimConfig,
    violation_names,
)
from madraft_tpu.tpusim.state import ClusterState, init_cluster
from madraft_tpu.tpusim.step import step_cluster

CLUSTER_AXIS = "clusters"

# One device execution = one chunk of the host-looped chunked dispatch
# (PERF.md round 3: 256-tick compiled scans keep a single execution under the
# tunnel's per-call deadline; dispatch overhead ~3% vs 64-tick chunks).
# Promoted here from bench.py so bench/CLI/pool share ONE implementation.
CHUNK_TICKS = 256

# Small sweeps dispatch as uniform-knob programs instead of one
# per-cluster-knob program (the measured 2.4x layout cliff — see
# _fuzz_program); above this many distinct knob cells the per-cell batches
# get too small to fill the chip and the per-cluster layout wins back.
SWEEP_UNIFORM_MAX_CELLS = 8


class FuzzReport(NamedTuple):
    """Host-side summary of one fuzz run (per-cluster arrays, length n_clusters)."""

    violations: np.ndarray            # i32 bitmask per cluster (0 = clean)
    first_violation_tick: np.ndarray  # -1 = none
    first_leader_tick: np.ndarray     # -1 = never elected (liveness signal)
    committed: np.ndarray             # entries ever committed (shadow length)
    msg_count: np.ndarray             # delivered messages
    snap_installs: np.ndarray         # install-snapshot deliveries (2D metric)

    @property
    def n_violating(self) -> int:
        return int((self.violations != 0).sum())

    def violating_clusters(self) -> np.ndarray:
        return np.nonzero(self.violations != 0)[0]


def _cluster_keys(seed, n_clusters: int, id0=None) -> jax.Array:
    """Per-cluster PRNG keys: fold_in(PRNGKey(seed), global_cluster_id).

    ``id0`` (optional traced offset) shifts the id range to [id0, id0 + n) —
    what the pool's refill and the uniform sweep dispatch need so the
    (seed, cluster_id) replay contract holds for GLOBAL ids. ``None`` (the
    historic spelling, ids 0..n-1) keeps the traced program of every
    existing fuzz caller byte-identical, preserving the warm XLA cache."""
    base = jax.random.PRNGKey(seed)
    ids = jnp.arange(n_clusters)
    if id0 is not None:
        ids = ids + id0
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(ids)


@functools.lru_cache(maxsize=None)
def _fuzz_program(
    static_cfg: SimConfig,
    n_clusters: int,
    mesh: Optional[Mesh],
    per_cluster_knobs: bool = False,
):
    """One compiled program per (static shape, batch, mesh, knob layout).

    Everything else — probabilities, timeouts, quorum override, tick count —
    is a runtime argument: the dynamic knobs ride in as a `Knobs` pytree and
    the tick count as a `fori_loop` bound. Two configs differing only in
    dynamic knobs (or tick counts) share this program, which is what keeps a
    cold test-suite run compile-light.

    ``per_cluster_knobs`` picks the knob layout. UNIFORM (scalars, vmap
    in_axes=None) is the default and the fast path: runtime scalar knobs
    measured WITHIN NOISE of compile-time-baked constants (19.6 vs 20.9
    M steps/s at the 4096-cluster flagship). Per-cluster knob ARRAYS — one
    value per cluster, what make_sweep_fn needs to sweep a fault grid in one
    program — measured a 2.4x cliff (8.1 M): vmapping the knob axis pushes a
    per-cluster scalar into every elementwise op, defeating broadcast
    vectorization. So sweeps alone pay it; plain fuzzing never does.
    """
    constraint = None
    if mesh is not None:
        constraint = NamedSharding(mesh, P(mesh.axis_names[0]))
    kn_ax = 0 if per_cluster_knobs else None

    def run(seed, kn, n_ticks) -> ClusterState:
        keys = _cluster_keys(seed, n_clusters)
        states = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, kn_ax)
        )(keys, kn)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys2 = jax.lax.with_sharding_constraint(keys, constraint)
            if per_cluster_knobs:
                kn = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, constraint), kn
                )
        else:
            keys2 = keys

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_cluster, static_cfg),
                in_axes=(0, 0, kn_ax),
            )(carry, keys2, kn)

        return jax.lax.fori_loop(0, n_ticks, body, states)

    return jax.jit(run)


class FuzzProgram:
    """Callable ``fn(seed) -> final state`` (what make_*_fuzz_fn always
    returned) that can additionally split compile time from execute time —
    the run-telemetry every CLI fuzz/sweep report carries so throughput is
    observable per invocation, not only via bench.py.

    ``compile_timed(seed)`` AOT-compiles the underlying jitted program
    (``jit(...).lower().compile()``) and returns the wall seconds it took;
    subsequent calls dispatch straight to the compiled executable, so a
    later timed call measures pure execution. Never calling it keeps the
    historic behavior exactly (plain jit dispatch, compile on first call).
    The args the program sees are identical either way, so reports stay
    bit-identical — the AOT path changes WHEN compilation happens, not what
    is compiled.
    """

    def __init__(self, prog, make_args):
        self._prog = prog
        self._make_args = make_args
        self._compiled = None
        self._aot_failed = False
        self.compile_s = None

    def compile_timed(self, seed) -> Optional[float]:
        """Compile for ``seed``'s arg shapes, once; returns wall seconds
        (cached result on repeat calls, None if AOT lowering failed and the
        plain jit path will be used — the failure is memoized too, so a
        repeat call never re-pays a failing lower+compile)."""
        if self._compiled is None and not self._aot_failed:
            t0 = time.perf_counter()
            try:
                self._compiled = self._prog.lower(
                    *self._make_args(seed)
                ).compile()
                self.compile_s = time.perf_counter() - t0
            except Exception:  # fall back to plain jit dispatch
                self._aot_failed = True
        return self.compile_s

    def __call__(self, seed):
        args = self._make_args(seed)
        if self._compiled is not None:
            return self._compiled(*args)
        return self._prog(*args)


def run_telemetry(fn, rep_fn, seed, n_steps: int) -> tuple:
    """Shared CLI-report telemetry runner: AOT-compile ``fn`` (timed), run
    it (timed), and return ``(report, telemetry_dict)``. ``rep_fn`` maps the
    final device state to the host report and is included in execute time —
    it contains the device->host sync that makes the measurement honest
    (bench.py methodology)."""
    import jax as _jax

    # duck-typed: FuzzProgram and the sweep's uniform dispatch both expose
    # the AOT compile/execute split
    compile_s = fn.compile_timed(seed) if hasattr(fn, "compile_timed") else None
    t0 = time.perf_counter()
    rep = rep_fn(_jax.block_until_ready(fn(seed)))
    execute_s = time.perf_counter() - t0
    dev = _jax.devices()[0]
    tele = {
        "execute_s": round(execute_s, 4),
        "steps_per_sec": round(n_steps / execute_s, 1),
        "device": str(dev),
        "backend": dev.platform,
    }
    if compile_s is not None:
        tele["compile_s"] = round(compile_s, 4)
    else:
        # no AOT split available: the timed window paid compile too — say
        # so rather than silently understating steps_per_sec
        tele["execute_includes_compile"] = True
    return rep, tele


def make_fuzz_fn(
    cfg: SimConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
):
    """Build fn(seed) -> final batched ClusterState.

    With a mesh, the cluster axis of every state leaf is sharded over the mesh's
    first axis (pure data parallelism; per-step work stays chip-local).
    """
    prog = _fuzz_program(cfg.static_key(), n_clusters, mesh)
    kn = cfg.knobs()  # uniform runtime scalars — the fast knob layout
    ticks = jnp.asarray(n_ticks, jnp.int32)
    # coerce exactly like fuzz()/replay_cluster(): with x64 enabled a
    # negative or >= 2^32 Python-int seed would otherwise promote to int64
    # and silently break the (seed, cluster_id) replay contract
    return FuzzProgram(
        prog, lambda seed: (jnp.asarray(seed, jnp.uint32), kn, ticks)
    )


# --------------------------------------------------------------------------
# Chunked dispatch + the continuous fuzzing pool (retire-and-refill).
#
# bench.py's hand-rolled donated chunked dispatch is promoted here: a
# compiled chunk program advances the whole batch T ticks with a DONATED
# state carry (the double-buffer is reused, so peak HBM matches the
# fixed-horizon program), and the pool interleaves chunks with a compiled
# harvest+refill step that retires finished slots ON DEVICE — only the small
# per-slot report arrays ever reach the host. Retired lanes are re-seeded
# under fresh GLOBAL cluster ids from a monotone counter, so every pool hit
# reproduces through replay_cluster(seed, global_cluster_id) exactly like a
# fuzz hit — across arbitrarily many refill generations.
# --------------------------------------------------------------------------


class PoolHarvest(NamedTuple):
    """Per-slot report arrays fetched at each harvest (all length n_lanes;
    values are PRE-refill — the retiring cluster's final numbers)."""

    retired: jax.Array             # bool: violated or horizon-reached
    ids: jax.Array                 # i32 global cluster id of the slot
    violations: jax.Array          # i32 sticky bitmask
    first_violation_tick: jax.Array
    first_leader_tick: jax.Array
    committed: jax.Array           # shadow_len
    msg_count: jax.Array
    snap_installs: jax.Array
    ticks_run: jax.Array           # the cluster's age (= state.tick)


def _constraint(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def _retired_row(h, lane: int, wall: float, viol_total: int) -> dict:
    """One retired-cluster report dict (the streaming JSONL schema) — ONE
    builder for the plain and coverage pools, so the schema cannot drift
    between them (the coverage pool appends its extra columns)."""
    mask = int(h.violations[lane])
    return {
        "cluster_id": int(h.ids[lane]),
        "ticks_run": int(h.ticks_run[lane]),
        "violations": mask,
        "violation_names": violation_names(mask),
        "first_violation_tick": int(h.first_violation_tick[lane]),
        "first_leader_tick": int(h.first_leader_tick[lane]),
        "committed": int(h.committed[lane]),
        "msg_count": int(h.msg_count[lane]),
        "snap_installs": int(h.snap_installs[lane]),
        "wall_s": round(wall, 3),
        "violations_per_s": (
            round(viol_total / wall, 3) if wall > 0 else None
        ),
    }


def _pool_summary(n_clusters: int, horizon: int, chunk_ticks: int,
                  lane_ticks: int, retired_total: int, viol_total: int,
                  viol_clusters: list, union: int, effective: int,
                  wall: float, next_id) -> dict:
    """The pool summary dict — shared by the plain and coverage pools (the
    coverage pool adds its ``coverage`` sub-dict on top)."""
    dispatched = lane_ticks * n_clusters
    return {
        "lanes": n_clusters,
        "horizon": horizon,
        "chunk_ticks": chunk_ticks,
        "lane_ticks": lane_ticks,
        "ticks_dispatched": dispatched,
        "retired": retired_total,
        "retired_violating": viol_total,
        "violating_clusters": viol_clusters[:16],
        "violating_clusters_total": len(viol_clusters),
        "violation_names": violation_names(union),
        "effective_cluster_steps": int(effective),
        "wall_s": round(wall, 3),
        "steps_per_sec": round(dispatched / wall, 1) if wall > 0 else None,
        "effective_steps_per_sec": (
            round(effective / wall, 1) if wall > 0 else None
        ),
        "violations_per_s": round(viol_total / wall, 3) if wall > 0 else None,
        "next_cluster_id": int(next_id),
    }


def default_chunk_ticks(horizon: int) -> int:
    """The pool's default chunk size: the horizon split into equal chunks
    no larger than CHUNK_TICKS, so lanes retire AT the horizon rather than
    a chunk-rounding overshoot past it (256-tick chunks against a 600-tick
    horizon would retire every clean lane at 768 ticks — 28% of the budget
    spent on ticks the fixed-horizon comparison never pays). The single
    source of the rule for run_pool and bench.py's A/B."""
    k = -(-horizon // CHUNK_TICKS)
    return -(-horizon // k)


@functools.lru_cache(maxsize=None)
def _pool_init_program(static_cfg: SimConfig, n_clusters: int,
                       mesh: Optional[Mesh]):
    """(seed, kn, id0) -> (states, keys, ids): a fresh batch covering global
    cluster ids [id0, id0 + n). Identical init math to _fuzz_program, split
    out so the chunk loop can carry states across compiled calls."""
    constraint = _constraint(mesh)

    def run(seed, kn, id0):
        ids = jnp.arange(n_clusters, dtype=jnp.int32) + id0
        keys = _cluster_keys(seed, n_clusters, id0)
        states = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, None)
        )(keys, kn)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys = jax.lax.with_sharding_constraint(keys, constraint)
            ids = jax.lax.with_sharding_constraint(ids, constraint)
        return states, keys, ids

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _chunk_program(static_cfg: SimConfig, n_clusters: int):
    """T ticks of the batched step with a DONATED state carry — one
    implementation for bench/CLI/pool. The tick count is a runtime
    fori_loop bound, so one compiled program serves every chunk length
    (full chunks, the remainder chunk, and any pool chunk size)."""

    def run(states, keys, kn, n_ticks):
        def body(_, carry):
            return jax.vmap(
                functools.partial(step_cluster, static_cfg),
                in_axes=(0, 0, None),
            )(carry, keys, kn)

        return jax.lax.fori_loop(0, n_ticks, body, states)

    return jax.jit(run, donate_argnums=(0,))


def _retire_and_reseed(states, ids, next_id, seed, horizon):
    """The (seed, global_cluster_id) replay contract, ONE implementation for
    the plain and coverage harvest programs: the retirement rule, monotone
    left-to-right global-id assignment over retired lanes (deterministic, so
    a pool run is exactly reproducible from its arguments), and the one-rule
    key derivation — key = fold_in(PRNGKey(seed), global_id) for EVERY lane:
    equal to the old key on kept lanes, the fresh key on refilled ones."""
    retired = (states.violations != 0) | (states.tick >= horizon)
    rank = jnp.cumsum(retired.astype(jnp.int32)) - 1
    new_ids = jnp.where(retired, next_id + rank, ids)
    base = jax.random.PRNGKey(seed)
    new_keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(new_ids)
    n_ret = retired.astype(jnp.int32).sum()
    return retired, new_ids, new_keys, n_ret


def _scatter_fresh(retired, fresh, states):
    """Scatter freshly init_cluster-ed states into the retired lanes."""

    def sel(f, s):
        m = retired.reshape(retired.shape + (1,) * (f.ndim - 1))
        return jnp.where(m, f, s)

    return jax.tree.map(sel, fresh, states)


@functools.lru_cache(maxsize=None)
def _harvest_program(static_cfg: SimConfig, n_clusters: int,
                     mesh: Optional[Mesh]):
    """Harvest + refill, one compiled call (states donated): snapshot the
    small per-slot report arrays, then scatter freshly init_cluster-ed
    states into retired lanes under new global ids next_id, next_id+1, ...
    (see _retire_and_reseed)."""
    constraint = _constraint(mesh)

    def run(states, keys, ids, next_id, seed, kn, horizon):
        retired, new_ids, new_keys, n_ret = _retire_and_reseed(
            states, ids, next_id, seed, horizon
        )
        harvest = PoolHarvest(
            retired=retired,
            ids=ids,
            violations=states.violations,
            first_violation_tick=states.first_violation_tick,
            first_leader_tick=states.first_leader_tick,
            committed=states.shadow_len,
            msg_count=states.msg_count,
            snap_installs=states.snap_install_count,
            ticks_run=states.tick,
        )
        fresh = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, None)
        )(new_keys, kn)
        if constraint is not None:
            fresh = jax.lax.with_sharding_constraint(
                fresh, jax.tree.map(lambda _: constraint, fresh)
            )
            new_keys = jax.lax.with_sharding_constraint(new_keys, constraint)
        states_out = _scatter_fresh(retired, fresh, states)
        return states_out, new_keys, new_ids, next_id + n_ret, harvest

    return jax.jit(run, donate_argnums=(0,))


def make_chunked_fuzz_fn(
    cfg: SimConfig,
    n_clusters: int,
    n_ticks: int,
    chunk_ticks: int = CHUNK_TICKS,
    mesh: Optional[Mesh] = None,
):
    """fn(seed) -> final batched ClusterState via a host loop over donated
    compiled chunks (bench.py methodology: a single device execution stays
    well under the tunnel's per-call deadline; donate_argnums reuses the
    state double-buffer). Bit-identical to make_fuzz_fn's single program —
    the chunk body is the same vmapped step under the same keys."""
    static = cfg.static_key()
    init = _pool_init_program(static, n_clusters, mesh)
    chunk = _chunk_program(static, n_clusters)
    kn = cfg.knobs()
    sizes = [chunk_ticks] * (n_ticks // chunk_ticks)
    if n_ticks % chunk_ticks or not sizes:
        sizes.append(n_ticks % chunk_ticks or n_ticks)

    def run(seed):
        states, keys, _ = init(
            jnp.asarray(seed, jnp.uint32), kn, jnp.asarray(0, jnp.int32)
        )
        for s in sizes:
            states = chunk(states, keys, kn, jnp.asarray(s, jnp.int32))
        return states

    return run


def run_pool(
    cfg: SimConfig,
    seed: int,
    n_clusters: int,
    horizon: int,
    *,
    chunk_ticks: int = 0,
    budget_ticks: Optional[int] = None,
    budget_seconds: Optional[float] = None,
    mesh: Optional[Mesh] = None,
    on_retired=None,
    coverage: Optional[CoverageConfig] = None,
) -> dict:
    """Continuous fuzzing pool: chunk -> harvest -> refill until the budget
    is spent. ``n_clusters`` lanes stay resident on device; a lane retires
    when its cluster violated or reached ``horizon`` ticks (detected at
    chunk boundaries, so a lane's age is always a multiple of
    ``chunk_ticks``), and is refilled with a fresh cluster under the next
    global id. ``on_retired`` (if given) is called with one report dict per
    retired cluster, in retirement order — the streaming JSONL source.

    Budgets: ``budget_ticks`` stops once every lane has dispatched that many
    ticks (rounded up to whole chunks); ``budget_seconds`` stops at the
    first harvest past the wall-clock budget; neither given = one horizon.
    Returns a summary dict (counts, effective pre-violation steps, rates).

    ``coverage`` (a ``config.CoverageConfig``) turns the pool into the
    coverage-guided corpus scheduler (ROADMAP item 3): every tick each
    lane's abstract-state fingerprint (coverage.py) updates a
    device-resident seen-set, and the refill step is BIASED — see
    ``_run_pool_coverage``. ``None`` (the default) is the historic pool,
    byte-identical programs and reports.
    """
    if horizon < 1:
        raise ValueError(f"pool horizon must be >= 1 tick, got {horizon}")
    if chunk_ticks <= 0:
        chunk_ticks = default_chunk_ticks(horizon)
    if budget_ticks is None and budget_seconds is None:
        budget_ticks = horizon
    if coverage is not None:
        return _run_pool_coverage(
            cfg, seed, n_clusters, horizon, coverage,
            chunk_ticks=chunk_ticks, budget_ticks=budget_ticks,
            budget_seconds=budget_seconds, mesh=mesh, on_retired=on_retired,
        )
    static = cfg.static_key()
    kn = cfg.knobs()
    init = _pool_init_program(static, n_clusters, mesh)
    chunk = _chunk_program(static, n_clusters)
    harv = _harvest_program(static, n_clusters, mesh)
    seed_u = jnp.asarray(seed, jnp.uint32)
    next_id = jnp.asarray(n_clusters, jnp.int32)
    hz = jnp.asarray(horizon, jnp.int32)
    ct = jnp.asarray(chunk_ticks, jnp.int32)
    # Warm all three programs OUTSIDE the timed window (a 1-tick chunk
    # compiles the same executable — the tick count is a runtime bound), so
    # a cold run's steps_per_sec/violations_per_s never silently include
    # compile time (run_telemetry's measurement-honesty convention). Warm
    # cost: n_clusters ticks + one harvest — noise against any real budget.
    ws, wk, wi = init(seed_u, kn, jnp.asarray(0, jnp.int32))
    ws = chunk(ws, wk, kn, jnp.asarray(1, jnp.int32))
    jax.block_until_ready(
        harv(ws, wk, wi, next_id, seed_u, kn, hz)[4].retired
    )
    states, keys, ids = init(seed_u, kn, jnp.asarray(0, jnp.int32))
    t0 = time.perf_counter()
    lane_ticks = 0
    retired_total = 0
    viol_total = 0
    effective = 0
    union = 0
    viol_clusters: list = []
    wall = 0.0
    h = None
    while True:
        states = chunk(states, keys, kn, ct)
        lane_ticks += chunk_ticks
        states, keys, ids, next_id, h_dev = harv(
            states, keys, ids, next_id, seed_u, kn, hz
        )
        # the ONLY device->host fetch of the loop: small per-slot arrays
        h = jax.tree.map(np.asarray, h_dev)
        wall = time.perf_counter() - t0
        for lane in np.nonzero(h.retired)[0]:
            mask = int(h.violations[lane])
            fvt = int(h.first_violation_tick[lane])
            ticks_run = int(h.ticks_run[lane])
            retired_total += 1
            # pre-violation ticks only: post-violation ticks inside the
            # retirement chunk are waste, not coverage
            effective += fvt if mask else ticks_run
            if mask:
                viol_total += 1
                union |= mask
                viol_clusters.append(int(h.ids[lane]))
            if on_retired is not None:
                on_retired(_retired_row(h, lane, wall, viol_total))
        if budget_ticks is not None and lane_ticks >= budget_ticks:
            break
        if budget_seconds is not None and wall >= budget_seconds:
            break
    # in-flight lanes at shutdown are clean (violated => retired): their
    # ticks so far are honest pre-violation coverage
    effective += int(h.ticks_run[~h.retired].sum())
    return _pool_summary(n_clusters, horizon, chunk_ticks, lane_ticks,
                         retired_total, viol_total, viol_clusters, union,
                         effective, wall, next_id)


# --------------------------------------------------------------------------
# Coverage-guided pool (ROADMAP item 3; the abstraction lives in coverage.py).
#
# The pool's retire-and-refill loop IS the corpus scheduler — no new driver.
# Coverage adds three device-resident pieces: a per-tick abstract-state
# fingerprint folded inside the chunk program, a power-of-two seen-set
# bitmap, and per-lane new-fingerprint counters that the harvest consumes to
# BIAS the refill (productive lanes spawn knob-mutated children, the rest
# draw fresh knob rows from the prior). Lanes therefore run HETEROGENEOUS
# knobs, which needs the per-cluster knob layout (the measured 2.4x sweep
# cliff — the price of guided search, paid only in coverage mode). All
# coverage programs are SEPARATE cached programs: with coverage off, no
# existing program's HLO — or warm persistent-cache entry — changes.
# --------------------------------------------------------------------------


class CovHarvest(NamedTuple):
    """PoolHarvest plus the coverage columns (all PRE-refill)."""

    retired: jax.Array
    ids: jax.Array
    violations: jax.Array
    first_violation_tick: jax.Array
    first_leader_tick: jax.Array
    committed: jax.Array
    msg_count: jax.Array
    snap_installs: jax.Array
    ticks_run: jax.Array
    new_fps: jax.Array      # i32 [n]: new fingerprints this lane discovered
    #                         since ITS refill (its whole lifetime)
    refill_kind: jax.Array  # i32 [n]: how this lane's knobs were produced
    #                         (coverage.REFILL_SEED / _FRESH / _MUTATE)
    seen_bits: jax.Array    # i32 scalar: seen-set popcount after this chunk
    knobs: Knobs            # per-lane knob rows (for JSONL + replay)


@functools.lru_cache(maxsize=None)
def _cov_chunk_program(static_cfg: SimConfig, n_clusters: int,
                       ccfg: CoverageConfig):
    """The coverage chunk: T ticks of the batched step under PER-LANE knob
    rows, with every tick's post-step abstract-state fingerprint recorded in
    the seen-set and credited to its lane's new-fingerprint counter. Two
    lanes landing the same new bit in one tick both get credit
    (deterministic; the alternative needs a per-tick segment reduction for
    a tie nobody acts on). The state, bitmap, and counters are donated —
    the pool's double-buffer discipline."""

    def run(states, keys, kn_lanes, bitmap, new_fps, n_ticks):
        def body(_, carry):
            st, bm, nf = carry
            st = jax.vmap(
                functools.partial(step_cluster, static_cfg),
                in_axes=(0, 0, 0),
            )(st, keys, kn_lanes)
            code = jax.vmap(functools.partial(_cov.abstract_code, ccfg))(st)
            idx = _cov.bitmap_index(ccfg, static_cfg.n_nodes, code)
            # a violated lane's post-violation states are waste, not
            # coverage (the effective_cluster_steps convention): until the
            # harvest retires it, it must neither set seen-set bits nor
            # earn the productivity credit that biases refill
            ok = st.violations == 0
            nf = nf + (ok & ~bm[idx]).astype(jnp.int32)
            bm = bm.at[idx].max(ok)
            return st, bm, nf

        return jax.lax.fori_loop(
            0, n_ticks, body, (states, bitmap, new_fps)
        )

    return jax.jit(run, donate_argnums=(0, 3, 4))


@functools.lru_cache(maxsize=None)
def _cov_harvest_program(static_cfg: SimConfig, n_clusters: int,
                         ccfg: CoverageConfig):
    """Harvest + BIASED refill, one compiled call (states donated): same
    retirement rule and monotone global-id scheme as _harvest_program, plus
    the corpus-scheduler policy — a retiring lane that discovered new
    fingerprints respawns with its knob row mutated
    (coverage.refill_knobs), an unproductive one with a fresh prior draw.
    With ``ccfg.guided`` False the refill keeps every lane at the base knob
    row (measurement-only mode: trajectories identical to the plain pool —
    the random A/B baseline and the first-generation golden guard)."""

    def run(states, keys, ids, kn_lanes, kinds, new_fps, bitmap,
            next_id, seed, base_kn, horizon):
        retired, new_ids, new_keys, n_ret = _retire_and_reseed(
            states, ids, next_id, seed, horizon
        )
        harvest = CovHarvest(
            retired=retired,
            ids=ids,
            violations=states.violations,
            first_violation_tick=states.first_violation_tick,
            first_leader_tick=states.first_leader_tick,
            committed=states.shadow_len,
            msg_count=states.msg_count,
            snap_installs=states.snap_install_count,
            ticks_run=states.tick,
            new_fps=new_fps,
            refill_kind=kinds,
            seen_bits=jnp.sum(bitmap, dtype=jnp.int32),
            knobs=kn_lanes,
        )
        if ccfg.guided:
            productive = retired & (new_fps > 0)
            kn_new, drawn = _cov.refill_knobs(
                ccfg, kn_lanes, base_kn, retired, productive, new_ids, seed
            )
            kinds_new = jnp.where(retired, drawn, kinds)
        else:
            kn_new, kinds_new = kn_lanes, kinds  # base rows forever
        fresh = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, 0)
        )(new_keys, kn_new)
        states_out = _scatter_fresh(retired, fresh, states)
        new_fps_out = jnp.where(retired, 0, new_fps)
        return (states_out, new_keys, new_ids, kn_new, kinds_new,
                new_fps_out, next_id + n_ret, harvest)

    return jax.jit(run, donate_argnums=(0,))


def _run_pool_coverage(
    cfg: SimConfig,
    seed: int,
    n_clusters: int,
    horizon: int,
    ccfg: CoverageConfig,
    *,
    chunk_ticks: int,
    budget_ticks: Optional[int],
    budget_seconds: Optional[float],
    mesh: Optional[Mesh],
    on_retired,
) -> dict:
    """run_pool's coverage-guided body (see run_pool for the contract).

    Extra report surface: each retired-cluster row carries
    ``new_fingerprints`` (the lane's lifetime discovery count), ``refill``
    ("seed" | "fresh" | "mutate" — how its knob row was produced) and
    ``knobs`` (the mutable storm-knob values it ran, full f32 precision —
    feed them to ``replay_cluster(..., knobs=row["knobs"])`` for bit-exact
    replay); the summary gains a ``coverage`` dict with the seen-set totals
    and the per-generation discovery curve.
    """
    if mesh is not None:
        raise ValueError(
            "the coverage pool is single-device for now (the seen-set "
            "bitmap is one shared array; ROADMAP item 1 owns the sharded "
            "pool) — drop mesh= or coverage="
        )
    static = cfg.static_key()
    base_kn = cfg.knobs()
    init = _pool_init_program(static, n_clusters, None)
    # the chunk only reads the fingerprint fields — cache it on those, so
    # the A/B's guided/random legs share one compiled chunk executable
    chunk = _cov_chunk_program(static, n_clusters, ccfg.fingerprint_key())
    harv = _cov_harvest_program(static, n_clusters, ccfg)
    seed_u = jnp.asarray(seed, jnp.uint32)
    hz = jnp.asarray(horizon, jnp.int32)
    ct = jnp.asarray(chunk_ticks, jnp.int32)

    def fresh_carry():
        states, keys, ids = init(seed_u, base_kn, jnp.asarray(0, jnp.int32))
        kn_lanes = base_kn.broadcast(n_clusters)
        kinds = jnp.full((n_clusters,), _cov.REFILL_SEED, jnp.int32)
        new_fps = jnp.zeros((n_clusters,), jnp.int32)
        bitmap = jnp.zeros((ccfg.bitmap_bits,), jnp.bool_)
        return states, keys, ids, kn_lanes, kinds, new_fps, bitmap

    # warm all programs outside the timed window (run_pool convention; the
    # tick count is a runtime bound so 1 tick compiles the real executables)
    ws, wk, wi, wkn, wkd, wnf, wbm = fresh_carry()
    ws, wbm, wnf = chunk(ws, wk, wkn, wbm, wnf, jnp.asarray(1, jnp.int32))
    next_id = jnp.asarray(n_clusters, jnp.int32)
    jax.block_until_ready(
        harv(ws, wk, wi, wkn, wkd, wnf, wbm, next_id, seed_u, base_kn,
             hz)[7].retired
    )
    states, keys, ids, kn_lanes, kinds, new_fps, bitmap = fresh_carry()
    next_id = jnp.asarray(n_clusters, jnp.int32)
    t0 = time.perf_counter()
    lane_ticks = 0
    retired_total = 0
    viol_total = 0
    effective = 0
    union = 0
    viol_clusters: list = []
    wall = 0.0
    h = None
    seen_prev = 0
    new_fp_per_gen: list = []
    refills_mutated = 0
    refills_fresh = 0
    lane_new_fps_total = 0
    while True:
        states, bitmap, new_fps = chunk(
            states, keys, kn_lanes, bitmap, new_fps, ct
        )
        lane_ticks += chunk_ticks
        (states, keys, ids, kn_lanes, kinds, new_fps, next_id,
         h_dev) = harv(states, keys, ids, kn_lanes, kinds, new_fps,
                       bitmap, next_id, seed_u, base_kn, hz)
        h = jax.tree.map(np.asarray, h_dev)
        wall = time.perf_counter() - t0
        seen_now = int(h.seen_bits)
        new_fp_per_gen.append(seen_now - seen_prev)
        seen_prev = seen_now
        for lane in np.nonzero(h.retired)[0]:
            mask = int(h.violations[lane])
            fvt = int(h.first_violation_tick[lane])
            ticks_run = int(h.ticks_run[lane])
            retired_total += 1
            effective += fvt if mask else ticks_run
            lane_new_fps_total += int(h.new_fps[lane])
            if mask:
                viol_total += 1
                union |= mask
                viol_clusters.append(int(h.ids[lane]))
            if on_retired is not None:
                row = _retired_row(h, lane, wall, viol_total)
                row["new_fingerprints"] = int(h.new_fps[lane])
                row["refill"] = _cov.REFILL_NAMES[int(h.refill_kind[lane])]
                row["knobs"] = {
                    name: float(getattr(h.knobs, name)[lane])
                    for name in _cov.MUTABLE_KNOBS
                }
                on_retired(row)
        if budget_ticks is not None and lane_ticks >= budget_ticks:
            break
        if budget_seconds is not None and wall >= budget_seconds:
            break
        if ccfg.guided:
            # counted only when the loop CONTINUES: the final harvest's
            # refilled children never run a tick, and the summary's
            # refills_* claim to record how lanes were actually spent
            productive = h.retired & (h.new_fps > 0)
            refills_mutated += int(productive.sum())
            refills_fresh += int((h.retired & ~productive).sum())
    effective += int(h.ticks_run[~h.retired].sum())
    lane_new_fps_total += int(h.new_fps[~h.retired].sum())
    summary = _pool_summary(n_clusters, horizon, chunk_ticks, lane_ticks,
                            retired_total, viol_total, viol_clusters, union,
                            effective, wall, next_id)
    summary["coverage"] = {
        "bitmap_bits": ccfg.bitmap_bits,
        "identity": _cov.identity_mapped(cfg.n_nodes, ccfg),
        "guided": ccfg.guided,
        "seen_fingerprints": seen_prev,
        "new_fingerprints_per_s": (
            round(seen_prev / wall, 2) if wall > 0 else None
        ),
        "lane_new_fps_total": lane_new_fps_total,
        "generations": len(new_fp_per_gen),
        # truncated like violating_clusters[:16]; "generations" carries the
        # full count so a consumer can detect the cut
        "new_fp_per_gen": new_fp_per_gen[:64],
        "refills_mutated": refills_mutated,
        "refills_fresh": refills_fresh,
    }
    return summary


def _validate_knobs(knobs) -> None:
    """Eagerly reject knob values that would silently misbehave inside the
    compiled program (mod-by-zero spans, out-of-range probabilities)."""
    k = jax.tree.map(np.asarray, knobs)
    validate_probs(
        k, ("loss_prob", "p_crash", "p_restart", "p_repartition", "p_heal",
            "p_leader_part", "p_asym_cut", "p_client_cmd",
            "p_lose_unsynced"), "raft",
    )
    if (k.fsync_every < 1).any():
        raise ValueError(
            f"fsync_every must be >= 1 tick (1 = sync every tick, the "
            f"perfect-persistence model): {k.fsync_every}"
        )
    if (k.eto_max < k.eto_min).any() or (k.eto_min < 1).any():
        raise ValueError(f"election timeout span empty: [{k.eto_min}, {k.eto_max}]")
    if (k.delay_max < k.delay_min).any() or (k.delay_min < 1).any():
        raise ValueError(f"delay span empty: [{k.delay_min}, {k.delay_max}]")
    if (k.delay_max - k.delay_min >= 256).any():
        raise ValueError(
            "delay span wider than 256 ticks exceeds the packed draw width "
            "(step.py _net_draws)"
        )
    if (k.majority < 1).any() or (k.heartbeat_ticks < 1).any():
        raise ValueError("majority and heartbeat_ticks must be >= 1")
    if (k.flow_cap < 1).any() or (k.compact_every < 1).any():
        raise ValueError("flow_cap and compact_every must be >= 1")


def validate_probs(k, names, layer: str) -> None:
    """Shared [0,1] range check for probability knobs (k = numpy-mapped
    knob pytree) — one copy of the rule for every layer's validator."""
    for name in names:
        v = getattr(k, name)
        if (v < 0).any() or (v > 1).any():
            raise ValueError(f"{layer} knob {name} outside [0, 1]: {v}")


def validate_bool_bugs(k, names, layer: str) -> None:
    """Shared bool-dtype check for planted-bug knob axes: an int 0/1 matrix
    would otherwise fail deep inside the compiled loop with an opaque
    carry-dtype error."""
    for name in names:
        if getattr(k, name).dtype != np.bool_:
            raise ValueError(
                f"{layer} bug knob {name} must be boolean (got "
                f"{getattr(k, name).dtype}); an int 0/1 matrix would fail "
                "deep inside the compiled loop with a carry-dtype error"
            )


def validate_service_raft_knobs(knobs) -> None:
    """Service-layer sweeps: the RAFT knob values that reach the program
    (the static cfg's dynamic fields are pinned and never read) must leave
    command injection and the compaction boundary to the service layer."""
    k = jax.tree.map(np.asarray, knobs)
    if (k.p_client_cmd != 0).any():
        raise ValueError(
            "service-layer sweeps need p_client_cmd == 0 in the raft knobs "
            "(the service layer owns command injection)"
        )
    if k.compact_at_commit.any():
        raise ValueError(
            "service-layer sweeps need compact_at_commit=False in the raft "
            "knobs (the compaction boundary must follow the apply cursor)"
        )


@functools.lru_cache(maxsize=None)
def _uniform_cell_program(static_cfg: SimConfig, n_clusters: int):
    """_fuzz_program's uniform-knob (fast) layout plus a runtime GLOBAL-ID
    offset: one sweep cell covers global cluster ids [id0, id0 + n), so the
    (seed, cluster_id) replay contract matches the per-cluster-knob layout
    it replaces. A separate cached program (rather than an extra arg on
    _fuzz_program) so every existing fuzz program's HLO — and its warm
    persistent-cache entry — stays byte-identical."""

    def run(seed, kn, n_ticks, id0):
        keys = _cluster_keys(seed, n_clusters, id0)
        states = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, None)
        )(keys, kn)

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_cluster, static_cfg),
                in_axes=(0, 0, None),
            )(carry, keys, kn)

        return jax.lax.fori_loop(0, n_ticks, body, states)

    return jax.jit(run)


def _knob_runs(kb, n_clusters: int) -> list:
    """Contiguous runs of identical per-cluster knob rows, as
    [(start, length), ...]. For the tiled grids every sweep builder emits,
    runs == distinct knob points; a non-contiguous layout simply yields
    more runs and falls back to the per-cluster program."""
    cols = np.stack(
        [np.asarray(x, dtype=np.float64) for x in kb], axis=1
    )  # i32/bool knob values are exact in f64
    change = np.any(cols[1:] != cols[:-1], axis=1)
    starts = np.concatenate([[0], np.nonzero(change)[0] + 1])
    lengths = np.diff(np.concatenate([starts, [n_clusters]]))
    return list(zip(starts.tolist(), lengths.tolist()))


class _UniformSweepDispatch:
    """K uniform-knob dispatches over contiguous global-id ranges — the
    fast knob layout (shared from the program cache across cells of equal
    batch) instead of one per-cluster-knob program with its measured 2.4x
    cliff. Returns the cell finals concatenated back into one batched
    ClusterState, so every make_sweep_fn caller is unchanged; reports are
    bit-identical to the per-cluster layout (same knob values reach the
    same (seed, cluster_id) streams — tests/test_pool.py asserts it)."""

    dispatch = "uniform"

    def __init__(self, static_cfg, kb, runs, n_ticks):
        ticks = jnp.asarray(n_ticks, jnp.int32)
        self._parts = []
        self._compiled = {}
        self._aot_failed = False
        self.compile_s = None
        for start, length in runs:
            prog = _uniform_cell_program(static_cfg, length)
            kn = jax.tree.map(lambda x, s=start: x[s], kb)  # 0-d, same dtype

            def make_args(seed, kn=kn, start=start):
                return (jnp.asarray(seed, jnp.uint32), kn, ticks,
                        jnp.asarray(start, jnp.int32))

            self._parts.append((length, prog, make_args))

    def compile_timed(self, seed) -> Optional[float]:
        """AOT-compile each distinct cell batch size once (cells share the
        compiled executable — only shapes are baked, knob values ride in as
        arguments); returns total wall seconds like FuzzProgram."""
        if self.compile_s is None and not self._aot_failed:
            t0 = time.perf_counter()
            try:
                for length, prog, make_args in self._parts:
                    if length not in self._compiled:
                        self._compiled[length] = prog.lower(
                            *make_args(seed)
                        ).compile()
                self.compile_s = time.perf_counter() - t0
            except Exception:  # fall back to plain jit dispatch
                self._aot_failed = True
                self._compiled = {}
        return self.compile_s

    def __call__(self, seed):
        finals = []
        for length, prog, make_args in self._parts:
            args = make_args(seed)
            compiled = self._compiled.get(length)
            finals.append(compiled(*args) if compiled else prog(*args))
        return jax.tree.map(lambda *xs: jnp.concatenate(xs), *finals)


def make_sweep_fn(
    cfg: SimConfig,
    knobs,  # config.Knobs with leading [n_clusters] axes (heterogeneous)
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
    uniform_max_cells: int = SWEEP_UNIFORM_MAX_CELLS,
):
    """Like make_fuzz_fn, but each cluster runs its own dynamic knobs — a
    fault-parameter sweep (e.g. loss x crash-rate grid) in ONE compiled
    program, something the reference's compile-time test matrix cannot do.

    Small grids (<= ``uniform_max_cells`` contiguous knob cells, no mesh)
    dispatch as one uniform-knob program per cell instead — the fast layout,
    sidestepping the per-cluster-knob 2.4x cliff. The returned callable's
    ``dispatch`` attribute says which path was taken; pass
    ``uniform_max_cells=0`` to force the per-cluster layout."""
    _validate_knobs(knobs)
    kb = knobs.broadcast(n_clusters)
    if mesh is None and uniform_max_cells:
        runs = _knob_runs(kb, n_clusters)
        if len(runs) <= uniform_max_cells:
            return _UniformSweepDispatch(cfg.static_key(), kb, runs, n_ticks)
    prog = _fuzz_program(cfg.static_key(), n_clusters, mesh, per_cluster_knobs=True)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    fn = FuzzProgram(
        prog, lambda seed: (jnp.asarray(seed, jnp.uint32), kb, ticks)
    )
    fn.dispatch = "per_cluster"
    return fn


def report(final: ClusterState) -> FuzzReport:
    return FuzzReport(
        violations=np.asarray(final.violations),
        first_violation_tick=np.asarray(final.first_violation_tick),
        first_leader_tick=np.asarray(final.first_leader_tick),
        committed=np.asarray(final.shadow_len),
        msg_count=np.asarray(final.msg_count),
        snap_installs=np.asarray(final.snap_install_count),
    )


def fuzz(
    cfg: SimConfig,
    seed: int,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
) -> FuzzReport:
    """Run n_clusters independent (seed x fault-schedule) simulations for n_ticks.

    Every cluster derives its PRNG stream from fold_in(PRNGKey(seed), cluster_id),
    so any violating cluster is exactly reproducible from (seed, cluster_id) — the
    MADSIM_TEST_SEED replay contract (/root/reference/README.md:42-55).
    """
    fn = make_fuzz_fn(cfg, n_clusters, n_ticks, mesh=mesh)
    final = jax.block_until_ready(fn(jnp.asarray(seed, jnp.uint32)))
    return report(final)


@functools.lru_cache(maxsize=None)
def _replay_program(static_cfg: SimConfig):
    def run(cluster_id, kn, n_ticks, seed):
        ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)
        state = init_cluster(static_cfg, ckey, kn)

        def body(_, carry):
            return step_cluster(static_cfg, carry, ckey, kn)

        return jax.lax.fori_loop(0, n_ticks, body, state)

    return jax.jit(run)


def resolve_knobs(cfg: SimConfig, knobs) -> Knobs:
    """``cfg.knobs()`` with an optional override merged on top and validated.

    ``knobs``: ``None``, a full ``config.Knobs``, or a mapping of field
    name -> value (e.g. a coverage-pool JSONL row's ``"knobs"`` object).
    Overrides pass ``_validate_knobs`` like every other entry point — a
    hand-edited row with an out-of-range probability is rejected eagerly,
    not silently run. Shared by ``replay_cluster`` and
    ``trace.replay_cluster_traced`` so the replay and explain surfaces
    apply a mutated lane's knob row identically."""
    kn = cfg.knobs()
    if knobs is None:
        return kn
    if isinstance(knobs, Knobs):
        kn = knobs
    else:
        if not isinstance(knobs, dict):
            # --knobs-json '0.5' or a bare string must fail with a named
            # rejection, not an opaque TypeError / nonsense field list
            raise ValueError(
                "knobs override must be a JSON object / mapping of field "
                f"-> value, got {type(knobs).__name__}"
            )
        unknown = set(knobs) - set(kn._fields)
        if unknown:
            raise ValueError(f"unknown knob fields: {sorted(unknown)}")
        coerced = {}
        for k, v in knobs.items():
            dt = getattr(kn, k).dtype
            if jnp.issubdtype(dt, jnp.integer) and v != int(v):
                # bit-exact replay must not silently truncate a
                # hand-edited row (the _validate_knobs eager convention)
                raise ValueError(
                    f"knob {k!r} is integer-valued; {v!r} would be "
                    f"silently truncated"
                )
            coerced[k] = jnp.asarray(v, dt)
        kn = kn._replace(**coerced)
    _validate_knobs(kn)
    return kn


def replay_cluster(
    cfg: SimConfig, seed: int, cluster_id: int, n_ticks: int, knobs=None
) -> ClusterState:
    """Re-run a single cluster (e.g. a violating one) for inspection/replay.

    ``knobs``: optional dynamic-knob override (see ``resolve_knobs``). This
    is how a coverage-pool hit with a mutated knob row replays bit-exactly
    (the pool's JSONL row carries the row under ``"knobs"``); it reuses the
    SAME compiled replay program either way, because knobs were always
    runtime scalars — exactly like replaying a sweep cell needs the cell's
    knob values, the (seed, cluster_id) PRNG-stream contract itself is
    knob-independent."""
    prog = _replay_program(cfg.static_key())
    kn = resolve_knobs(cfg, knobs)
    return jax.block_until_ready(
        prog(jnp.asarray(cluster_id, jnp.int32), kn,
             jnp.asarray(n_ticks, jnp.int32), jnp.asarray(seed, jnp.uint32))
    )
