"""Batch drivers: vmap over clusters, scan over ticks, pjit over chips.

The fuzzer is embarrassingly data-parallel over the cluster axis (SURVEY.md §5:
"batch parallelism over simulated clusters" is this project's scaling axis) — the
mesh sharding simply splits clusters across chips; XLA inserts no collectives on the
hot path, only for the final violation reduction.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from madraft_tpu.tpusim.config import SimConfig
from madraft_tpu.tpusim.state import ClusterState, init_cluster
from madraft_tpu.tpusim.step import step_cluster

CLUSTER_AXIS = "clusters"


class FuzzReport(NamedTuple):
    """Host-side summary of one fuzz run (per-cluster arrays, length n_clusters)."""

    violations: np.ndarray            # i32 bitmask per cluster (0 = clean)
    first_violation_tick: np.ndarray  # -1 = none
    first_leader_tick: np.ndarray     # -1 = never elected (liveness signal)
    committed: np.ndarray             # entries ever committed (shadow length)
    msg_count: np.ndarray             # delivered messages
    snap_installs: np.ndarray         # install-snapshot deliveries (2D metric)

    @property
    def n_violating(self) -> int:
        return int((self.violations != 0).sum())

    def violating_clusters(self) -> np.ndarray:
        return np.nonzero(self.violations != 0)[0]


def _cluster_keys(seed, n_clusters: int) -> jax.Array:
    base = jax.random.PRNGKey(seed)
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(n_clusters))


@functools.lru_cache(maxsize=None)
def _fuzz_program(
    static_cfg: SimConfig,
    n_clusters: int,
    mesh: Optional[Mesh],
    per_cluster_knobs: bool = False,
):
    """One compiled program per (static shape, batch, mesh, knob layout).

    Everything else — probabilities, timeouts, quorum override, tick count —
    is a runtime argument: the dynamic knobs ride in as a `Knobs` pytree and
    the tick count as a `fori_loop` bound. Two configs differing only in
    dynamic knobs (or tick counts) share this program, which is what keeps a
    cold test-suite run compile-light.

    ``per_cluster_knobs`` picks the knob layout. UNIFORM (scalars, vmap
    in_axes=None) is the default and the fast path: runtime scalar knobs
    measured WITHIN NOISE of compile-time-baked constants (19.6 vs 20.9
    M steps/s at the 4096-cluster flagship). Per-cluster knob ARRAYS — one
    value per cluster, what make_sweep_fn needs to sweep a fault grid in one
    program — measured a 2.4x cliff (8.1 M): vmapping the knob axis pushes a
    per-cluster scalar into every elementwise op, defeating broadcast
    vectorization. So sweeps alone pay it; plain fuzzing never does.
    """
    constraint = None
    if mesh is not None:
        constraint = NamedSharding(mesh, P(mesh.axis_names[0]))
    kn_ax = 0 if per_cluster_knobs else None

    def run(seed, kn, n_ticks) -> ClusterState:
        keys = _cluster_keys(seed, n_clusters)
        states = jax.vmap(
            functools.partial(init_cluster, static_cfg), in_axes=(0, kn_ax)
        )(keys, kn)
        if constraint is not None:
            states = jax.lax.with_sharding_constraint(
                states, jax.tree.map(lambda _: constraint, states)
            )
            keys2 = jax.lax.with_sharding_constraint(keys, constraint)
            if per_cluster_knobs:
                kn = jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, constraint), kn
                )
        else:
            keys2 = keys

        def body(_, carry):
            return jax.vmap(
                functools.partial(step_cluster, static_cfg),
                in_axes=(0, 0, kn_ax),
            )(carry, keys2, kn)

        return jax.lax.fori_loop(0, n_ticks, body, states)

    return jax.jit(run)


class FuzzProgram:
    """Callable ``fn(seed) -> final state`` (what make_*_fuzz_fn always
    returned) that can additionally split compile time from execute time —
    the run-telemetry every CLI fuzz/sweep report carries so throughput is
    observable per invocation, not only via bench.py.

    ``compile_timed(seed)`` AOT-compiles the underlying jitted program
    (``jit(...).lower().compile()``) and returns the wall seconds it took;
    subsequent calls dispatch straight to the compiled executable, so a
    later timed call measures pure execution. Never calling it keeps the
    historic behavior exactly (plain jit dispatch, compile on first call).
    The args the program sees are identical either way, so reports stay
    bit-identical — the AOT path changes WHEN compilation happens, not what
    is compiled.
    """

    def __init__(self, prog, make_args):
        self._prog = prog
        self._make_args = make_args
        self._compiled = None
        self._aot_failed = False
        self.compile_s = None

    def compile_timed(self, seed) -> Optional[float]:
        """Compile for ``seed``'s arg shapes, once; returns wall seconds
        (cached result on repeat calls, None if AOT lowering failed and the
        plain jit path will be used — the failure is memoized too, so a
        repeat call never re-pays a failing lower+compile)."""
        if self._compiled is None and not self._aot_failed:
            t0 = time.perf_counter()
            try:
                self._compiled = self._prog.lower(
                    *self._make_args(seed)
                ).compile()
                self.compile_s = time.perf_counter() - t0
            except Exception:  # fall back to plain jit dispatch
                self._aot_failed = True
        return self.compile_s

    def __call__(self, seed):
        args = self._make_args(seed)
        if self._compiled is not None:
            return self._compiled(*args)
        return self._prog(*args)


def run_telemetry(fn, rep_fn, seed, n_steps: int) -> tuple:
    """Shared CLI-report telemetry runner: AOT-compile ``fn`` (timed), run
    it (timed), and return ``(report, telemetry_dict)``. ``rep_fn`` maps the
    final device state to the host report and is included in execute time —
    it contains the device->host sync that makes the measurement honest
    (bench.py methodology)."""
    import jax as _jax

    compile_s = fn.compile_timed(seed) if isinstance(fn, FuzzProgram) else None
    t0 = time.perf_counter()
    rep = rep_fn(_jax.block_until_ready(fn(seed)))
    execute_s = time.perf_counter() - t0
    dev = _jax.devices()[0]
    tele = {
        "execute_s": round(execute_s, 4),
        "steps_per_sec": round(n_steps / execute_s, 1),
        "device": str(dev),
        "backend": dev.platform,
    }
    if compile_s is not None:
        tele["compile_s"] = round(compile_s, 4)
    else:
        # no AOT split available: the timed window paid compile too — say
        # so rather than silently understating steps_per_sec
        tele["execute_includes_compile"] = True
    return rep, tele


def make_fuzz_fn(
    cfg: SimConfig,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
):
    """Build fn(seed) -> final batched ClusterState.

    With a mesh, the cluster axis of every state leaf is sharded over the mesh's
    first axis (pure data parallelism; per-step work stays chip-local).
    """
    prog = _fuzz_program(cfg.static_key(), n_clusters, mesh)
    kn = cfg.knobs()  # uniform runtime scalars — the fast knob layout
    ticks = jnp.asarray(n_ticks, jnp.int32)
    # coerce exactly like fuzz()/replay_cluster(): with x64 enabled a
    # negative or >= 2^32 Python-int seed would otherwise promote to int64
    # and silently break the (seed, cluster_id) replay contract
    return FuzzProgram(
        prog, lambda seed: (jnp.asarray(seed, jnp.uint32), kn, ticks)
    )


def _validate_knobs(knobs) -> None:
    """Eagerly reject knob values that would silently misbehave inside the
    compiled program (mod-by-zero spans, out-of-range probabilities)."""
    k = jax.tree.map(np.asarray, knobs)
    validate_probs(
        k, ("loss_prob", "p_crash", "p_restart", "p_repartition", "p_heal",
            "p_leader_part", "p_asym_cut", "p_client_cmd",
            "p_lose_unsynced"), "raft",
    )
    if (k.fsync_every < 1).any():
        raise ValueError(
            f"fsync_every must be >= 1 tick (1 = sync every tick, the "
            f"perfect-persistence model): {k.fsync_every}"
        )
    if (k.eto_max < k.eto_min).any() or (k.eto_min < 1).any():
        raise ValueError(f"election timeout span empty: [{k.eto_min}, {k.eto_max}]")
    if (k.delay_max < k.delay_min).any() or (k.delay_min < 1).any():
        raise ValueError(f"delay span empty: [{k.delay_min}, {k.delay_max}]")
    if (k.delay_max - k.delay_min >= 256).any():
        raise ValueError(
            "delay span wider than 256 ticks exceeds the packed draw width "
            "(step.py _net_draws)"
        )
    if (k.majority < 1).any() or (k.heartbeat_ticks < 1).any():
        raise ValueError("majority and heartbeat_ticks must be >= 1")
    if (k.flow_cap < 1).any() or (k.compact_every < 1).any():
        raise ValueError("flow_cap and compact_every must be >= 1")


def validate_probs(k, names, layer: str) -> None:
    """Shared [0,1] range check for probability knobs (k = numpy-mapped
    knob pytree) — one copy of the rule for every layer's validator."""
    for name in names:
        v = getattr(k, name)
        if (v < 0).any() or (v > 1).any():
            raise ValueError(f"{layer} knob {name} outside [0, 1]: {v}")


def validate_bool_bugs(k, names, layer: str) -> None:
    """Shared bool-dtype check for planted-bug knob axes: an int 0/1 matrix
    would otherwise fail deep inside the compiled loop with an opaque
    carry-dtype error."""
    for name in names:
        if getattr(k, name).dtype != np.bool_:
            raise ValueError(
                f"{layer} bug knob {name} must be boolean (got "
                f"{getattr(k, name).dtype}); an int 0/1 matrix would fail "
                "deep inside the compiled loop with a carry-dtype error"
            )


def validate_service_raft_knobs(knobs) -> None:
    """Service-layer sweeps: the RAFT knob values that reach the program
    (the static cfg's dynamic fields are pinned and never read) must leave
    command injection and the compaction boundary to the service layer."""
    k = jax.tree.map(np.asarray, knobs)
    if (k.p_client_cmd != 0).any():
        raise ValueError(
            "service-layer sweeps need p_client_cmd == 0 in the raft knobs "
            "(the service layer owns command injection)"
        )
    if k.compact_at_commit.any():
        raise ValueError(
            "service-layer sweeps need compact_at_commit=False in the raft "
            "knobs (the compaction boundary must follow the apply cursor)"
        )


def make_sweep_fn(
    cfg: SimConfig,
    knobs,  # config.Knobs with leading [n_clusters] axes (heterogeneous)
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
):
    """Like make_fuzz_fn, but each cluster runs its own dynamic knobs — a
    fault-parameter sweep (e.g. loss x crash-rate grid) in ONE compiled
    program, something the reference's compile-time test matrix cannot do."""
    _validate_knobs(knobs)
    prog = _fuzz_program(cfg.static_key(), n_clusters, mesh, per_cluster_knobs=True)
    kn = knobs.broadcast(n_clusters)
    ticks = jnp.asarray(n_ticks, jnp.int32)
    return FuzzProgram(
        prog, lambda seed: (jnp.asarray(seed, jnp.uint32), kn, ticks)
    )


def report(final: ClusterState) -> FuzzReport:
    return FuzzReport(
        violations=np.asarray(final.violations),
        first_violation_tick=np.asarray(final.first_violation_tick),
        first_leader_tick=np.asarray(final.first_leader_tick),
        committed=np.asarray(final.shadow_len),
        msg_count=np.asarray(final.msg_count),
        snap_installs=np.asarray(final.snap_install_count),
    )


def fuzz(
    cfg: SimConfig,
    seed: int,
    n_clusters: int,
    n_ticks: int,
    mesh: Optional[Mesh] = None,
) -> FuzzReport:
    """Run n_clusters independent (seed x fault-schedule) simulations for n_ticks.

    Every cluster derives its PRNG stream from fold_in(PRNGKey(seed), cluster_id),
    so any violating cluster is exactly reproducible from (seed, cluster_id) — the
    MADSIM_TEST_SEED replay contract (/root/reference/README.md:42-55).
    """
    fn = make_fuzz_fn(cfg, n_clusters, n_ticks, mesh=mesh)
    final = jax.block_until_ready(fn(jnp.asarray(seed, jnp.uint32)))
    return report(final)


@functools.lru_cache(maxsize=None)
def _replay_program(static_cfg: SimConfig):
    def run(cluster_id, kn, n_ticks, seed):
        ckey = jax.random.fold_in(jax.random.PRNGKey(seed), cluster_id)
        state = init_cluster(static_cfg, ckey, kn)

        def body(_, carry):
            return step_cluster(static_cfg, carry, ckey, kn)

        return jax.lax.fori_loop(0, n_ticks, body, state)

    return jax.jit(run)


def replay_cluster(
    cfg: SimConfig, seed: int, cluster_id: int, n_ticks: int
) -> ClusterState:
    """Re-run a single cluster (e.g. a violating one) for inspection/replay."""
    prog = _replay_program(cfg.static_key())
    return jax.block_until_ready(
        prog(jnp.asarray(cluster_id, jnp.int32), cfg.knobs(),
             jnp.asarray(n_ticks, jnp.int32), jnp.asarray(seed, jnp.uint32))
    )
